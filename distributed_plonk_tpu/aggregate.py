"""Batch-KZG proof aggregation: N proofs in, ONE 2-pair pairing check out.

The service's verification story before this module: every served proof
costs its own pairing check — fine for a client verifying one result,
hopeless for anyone consuming the fleet's output at rate (the
"millions of verifications" amortization ROADMAP direction 4 names).
This module is the batching layer on top of verifier.opening_terms:

  build()              N completed jobs' (spec, public input, proof
                       bytes) -> one canonical, content-addressed
                       aggregate artifact (a JSON blob; `agg_id` is the
                       SHA-256 of the canonical member encoding, so the
                       same batch always produces the same artifact)
  derive_challenges()  the aggregation transcript: a FRESH Merlin
                       transcript (label b"DptAggregate") absorbs every
                       member's canonical bytes — job id, spec wire
                       dict, public inputs (fr_to_bytes), the raw
                       944-byte proof — and only then draws, per member,
                       the opening-fold challenge u_j and the
                       linear-combination weight r_j. Flipping ANY bit
                       of any member shifts EVERY (u_j, r_j).
  verify()             artifact -> bool, by folding all members into
                       verifier.verify_aggregate's single 2-pair
                       pairing check.

Soundness sketch: each member's verification equation is a pairing
identity  e(lhs_j, g2) e(-rhs_j, tau_g2) == 1.  verify() checks the
r_j-weighted fold of those identities. The r_j are derived Fiat-Shamir
style AFTER every member's bytes are committed to the transcript, so a
prover cannot choose proof bytes as a function of the weights; if any
single member's identity fails, the fold is a nonzero element hit by a
random linear combination — it cancels with probability ~1/r (|Fr| ~
2^255). The u_j (which fold each member's two openings, at zeta and
omega*zeta) come from the same transcript for the same reason. Cost
model: verification is two size-O(30N) G1 MSMs + ONE pairing_check with
2 pairs, vs N pairing checks (2N pairs) sequentially — the pairings,
not the MSMs, dominate, so verify time is ~flat in N.

All members must share the SRS tail (g2, tau_g2): this repo's service
derives every bucket's keys from the fixed TEST_TAU, so that holds by
construction; verify_aggregate still REJECTS (not asserts) on mismatch.
"""

import hashlib
import json

from .constants import R_MOD
from . import proof_io, verifier
from .transcript import MerlinTranscript, fr_from_le_bytes_mod_order, fr_to_bytes

SCHEMA = 1
TRANSCRIPT_LABEL = b"DptAggregate"


def _canonical_json(obj):
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def _norm_member(m):
    proof = m["proof"] if isinstance(m["proof"], str) else bytes(m["proof"]).hex()
    return {
        "job_id": str(m["job_id"]),
        "spec": m["spec"],
        "pub": [x if isinstance(x, str) else format(int(x) % R_MOD, "x")
                for x in m["pub"]],
        "proof": proof,
    }


def member_id(members):
    """Content address of a member list: the artifact id is a digest of
    the canonical encoding, so the same batch of jobs aggregates to the
    same `aggregate:<id>` artifact on every run (and across restarts)."""
    blob = _canonical_json([_norm_member(m) for m in members])
    return "agg-" + hashlib.sha256(blob).hexdigest()[:16]


def build(members):
    """[{job_id, spec (wire dict), pub ([int]|[hex]), proof (bytes|hex)}]
    -> the canonical aggregate artifact dict."""
    if not members:
        raise ValueError("aggregate needs at least one member")
    norm = [_norm_member(m) for m in members]
    return {"schema": SCHEMA, "agg_id": member_id(members), "members": norm}


def to_bytes(agg):
    return _canonical_json(agg)


def from_bytes(blob):
    """Parse + structurally validate an untrusted artifact. Raises
    ValueError on anything malformed (verification happens in verify())."""
    try:
        agg = json.loads(bytes(blob).decode())
    except (UnicodeDecodeError, ValueError):
        raise ValueError("aggregate artifact is not valid JSON")
    if not isinstance(agg, dict) or agg.get("schema") != SCHEMA:
        raise ValueError("aggregate artifact has unknown schema")
    members = agg.get("members")
    if not isinstance(members, list) or not members:
        raise ValueError("aggregate artifact has no members")
    for m in members:
        if not isinstance(m, dict) or not isinstance(m.get("spec"), dict) \
                or not isinstance(m.get("pub"), list) \
                or not isinstance(m.get("proof"), str):
            raise ValueError("malformed aggregate member")
    return agg


def derive_challenges(members):
    """Normalized member list -> [(u_j, r_j)] from the aggregation
    transcript. Absorb-everything-then-draw ordering is the binding: no
    challenge exists until every member's bytes are committed."""
    t = MerlinTranscript(TRANSCRIPT_LABEL)
    t.append_message(b"n_members", len(members).to_bytes(4, "little"))
    for m in members:
        t.append_message(b"job_id", m["job_id"].encode())
        t.append_message(b"spec", _canonical_json(m["spec"]))
        t.append_message(b"pub", b"".join(
            fr_to_bytes(int(x, 16)) for x in m["pub"]))
        t.append_message(b"proof", bytes.fromhex(m["proof"]))
    out = []
    for _ in members:
        u = fr_from_le_bytes_mod_order(t.challenge_bytes(b"u", 64))
        r = fr_from_le_bytes_mod_order(t.challenge_bytes(b"r", 64))
        out.append((u, r))
    return out


def _vk_for_spec(spec_wire, cache):
    # lazy import: aggregate is a core-layer module; only vk resolution
    # needs the service's spec/bucket machinery
    from .service import jobs
    spec = jobs.JobSpec.from_wire(spec_wire)
    key = jobs.shape_key(spec)
    if key not in cache:
        cache[key] = jobs.build_bucket_keys(spec)[2]
    return cache[key]


def verify(agg, vk_cache=None):
    """Aggregate artifact -> bool: ONE 2-pair pairing check for all N
    members, accepting iff every constituent proof verifies.

    vk_cache (optional dict) carries shape_key -> vk across calls: vks
    are rebuilt deterministically from each member's spec (the service's
    fixed-test-tau contract, service/jobs.py), which costs a preprocess
    per distinct shape — cache it when verifying a stream.
    """
    vk_cache = vk_cache if vk_cache is not None else {}
    try:
        agg = from_bytes(to_bytes(agg)) if isinstance(agg, dict) else from_bytes(agg)
    except ValueError:
        return False
    if agg.get("agg_id") != member_id(agg["members"]):
        return False  # content address doesn't match the content
    try:
        fold_members = []
        challenges = derive_challenges(agg["members"])
        for m, (u, r) in zip(agg["members"], challenges):
            vk = _vk_for_spec(m["spec"], vk_cache)
            pub = [int(x, 16) for x in m["pub"]]
            proof = proof_io.deserialize_proof(bytes.fromhex(m["proof"]))
            fold_members.append((vk, pub, proof, u, r))
    except ValueError:
        return False
    return verifier.verify_aggregate(fold_members)
