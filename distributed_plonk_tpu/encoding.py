"""BLS12-381 point encoding, zcash/IETF wire format.

Interop surface with EXTERNAL golden vectors: the big-endian
"zcash-style" encoding standardized by the IETF BLS-signature and
hash-to-curve drafts (draft-irtf-cfrg-pairing-friendly-curves, appendix
C) and used by zcash, eth2, blst, py_ecc, ... Its generator encodings
are published constants, so tests/test_encoding.py anchors this repo's
curve constants and sign conventions to an external specification — the
byte-compat evidence class the transcript's merlin KAT provides for
Fiat-Shamir (the arkworks little-endian layout used on the transcript
itself, transcript.py:173-216, has no published vectors and no Rust
toolchain exists in this environment to record any; this module is the
independently-checkable complement).

Format (compressed): 48 bytes (G1) / 96 bytes (G2), big-endian x
(G2: c1 then c0), three flag bits in the MOST significant byte:
  bit 7 (0x80): compressed form
  bit 6 (0x40): point at infinity (remaining bytes zero)
  bit 5 (0x20): y is the lexicographically larger of the two roots
                (only when compressed and not infinity)
Uncompressed: 96 / 192 bytes, x then y, flags bit7=bit5=0.
"""

from .constants import Q_MOD, R_MOD
from . import curve as C

_HALF = (Q_MOD - 1) // 2


def _g1_in_subgroup(p):
    """True iff affine p lies in the r-order subgroup (r·p = O).

    BLS12-381's G1 cofactor is ≈2^125, so on-curve points outside the
    prime-order subgroup exist and the zcash/IETF format requires
    rejecting them (draft-irtf-cfrg-pairing-friendly-curves, appendix C).
    reduce=False: reducing r mod r would turn the check into 0·p.
    Host-oracle scale (255 Jacobian steps)."""
    return C.g1_mul(p, R_MOD, reduce=False) is None


def _g2_in_subgroup(p):
    """True iff affine G2 p satisfies r·p = O (cofactor ≈2^378 — almost
    every on-curve point is OUTSIDE the subgroup)."""
    return C.g2_mul(p, R_MOD, reduce=False) is None


def _fq_sign(y):
    """True iff y is the lexicographically larger root (y > (q-1)/2)."""
    return y > _HALF


def _fq2_sign(y):
    """Lexicographic order on Fq2 per the spec: compare c1 first."""
    y0, y1 = y
    if y1 != 0:
        return y1 > _HALF
    return y0 > _HALF


def g1_to_zcash(p, compressed=True):
    """Affine G1 (or None = infinity) -> 48/96 zcash-format bytes."""
    if p is None:
        out = bytearray(48 if compressed else 96)
        out[0] = (0x80 if compressed else 0) | 0x40
        return bytes(out)
    x, y = p
    if compressed:
        out = bytearray(x.to_bytes(48, "big"))
        out[0] |= 0x80 | (0x20 if _fq_sign(y) else 0)
        return bytes(out)
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def g1_from_zcash(b):
    """48/96 zcash-format bytes -> affine G1 or None. Validates flags,
    field range, curve membership and the r-order subgroup (r·p = O),
    per the zcash/IETF validation rules."""
    b = bytes(b)
    if len(b) not in (48, 96):
        raise ValueError("G1 encoding must be 48 or 96 bytes")
    comp = bool(b[0] & 0x80)
    inf = bool(b[0] & 0x40)
    sign = bool(b[0] & 0x20)
    if comp != (len(b) == 48):
        raise ValueError("compression flag does not match length")
    if inf:
        if sign or any(b[1:]) or (b[0] & 0x1F):
            raise ValueError("malformed infinity encoding")
        return None
    if comp:
        x = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:], "big")
        if x >= Q_MOD:
            raise ValueError("x out of range")
        y2 = (pow(x, 3, Q_MOD) + 4) % Q_MOD  # E: y^2 = x^3 + 4
        y = pow(y2, (Q_MOD + 1) // 4, Q_MOD)  # q ≡ 3 (mod 4)
        if y * y % Q_MOD != y2:
            raise ValueError("x is not on the curve")
        if _fq_sign(y) != sign:
            y = (Q_MOD - y) % Q_MOD
        if not _g1_in_subgroup((x, y)):
            raise ValueError("point not in the r-order subgroup")
        return (x, y)
    if sign or (b[0] & 0x20):
        raise ValueError("sign flag set on uncompressed encoding")
    x = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:48], "big")
    y = int.from_bytes(b[48:], "big")
    if x >= Q_MOD or y >= Q_MOD:
        raise ValueError("coordinate out of range")
    if not C.g1_is_on_curve((x, y)):
        raise ValueError("point not on curve")
    if not _g1_in_subgroup((x, y)):
        raise ValueError("point not in the r-order subgroup")
    return (x, y)


def g2_to_zcash(p, compressed=True):
    """Affine G2 (or None) -> 96/192 zcash-format bytes (x = c1 || c0)."""
    if p is None:
        out = bytearray(96 if compressed else 192)
        out[0] = (0x80 if compressed else 0) | 0x40
        return bytes(out)
    (x0, x1), (y0, y1) = p
    if compressed:
        out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
        out[0] |= 0x80 | (0x20 if _fq2_sign((y0, y1)) else 0)
        return bytes(out)
    return (x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
            + y1.to_bytes(48, "big") + y0.to_bytes(48, "big"))


def g2_from_zcash(b):
    """96/192 zcash-format bytes -> affine G2 or None. Same validation
    surface as g1_from_zcash, including the r-order subgroup check."""
    b = bytes(b)
    if len(b) not in (96, 192):
        raise ValueError("G2 encoding must be 96 or 192 bytes")
    comp = bool(b[0] & 0x80)
    inf = bool(b[0] & 0x40)
    sign = bool(b[0] & 0x20)
    if comp != (len(b) == 96):
        raise ValueError("compression flag does not match length")
    if inf:
        if sign or any(b[1:]) or (b[0] & 0x1F):
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:48], "big")
    x0 = int.from_bytes(b[48:96], "big")
    if x0 >= Q_MOD or x1 >= Q_MOD:
        raise ValueError("x out of range")
    if comp:
        y = _fq2_sqrt(_fq2_add(_fq2_mul_xx_x((x0, x1)), (4, 4)))  # b' = 4+4i
        if y is None:
            raise ValueError("x is not on the curve")
        if _fq2_sign(y) != sign:
            y = ((Q_MOD - y[0]) % Q_MOD, (Q_MOD - y[1]) % Q_MOD)
        p = ((x0, x1), y)
        if not _g2_in_subgroup(p):
            raise ValueError("point not in the r-order subgroup")
        return p
    if sign:
        raise ValueError("sign flag set on uncompressed encoding")
    y1 = int.from_bytes(b[96:144], "big")
    y0 = int.from_bytes(b[144:], "big")
    if y0 >= Q_MOD or y1 >= Q_MOD:
        raise ValueError("y out of range")
    p = ((x0, x1), (y0, y1))
    if not C.g2_is_on_curve(p):
        raise ValueError("point not on curve")
    if not _g2_in_subgroup(p):
        raise ValueError("point not in the r-order subgroup")
    return p


# --- minimal Fq2 helpers (host oracle scale only) ----------------------------

def _fq2_add(a, b):
    return ((a[0] + b[0]) % Q_MOD, (a[1] + b[1]) % Q_MOD)


def _fq2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % Q_MOD, (a0 * b1 + a1 * b0) % Q_MOD)


def _fq2_mul_xx_x(x):
    return _fq2_mul(_fq2_mul(x, x), x)


def _fq2_sqrt(a):
    """Square root in Fq2 (q ≡ 3 mod 4): candidate a^((q^2+7)/16)-free
    shortcut via the norm map — compute with the standard complex method:
    sqrt(a0 + a1*i) from Fq square roots of the norm."""
    a0, a1 = a
    if a1 == 0:
        # a0 might be a QR in Fq, else sqrt is i * sqrt(-a0)
        r = pow(a0, (Q_MOD + 1) // 4, Q_MOD)
        if r * r % Q_MOD == a0:
            return (r, 0)
        na = (Q_MOD - a0) % Q_MOD
        r = pow(na, (Q_MOD + 1) // 4, Q_MOD)
        if r * r % Q_MOD == na:
            return (0, r)
        return None
    # norm = a0^2 + a1^2 (since i^2 = -1); need alpha with alpha^2 = norm
    norm = (a0 * a0 + a1 * a1) % Q_MOD
    alpha = pow(norm, (Q_MOD + 1) // 4, Q_MOD)
    if alpha * alpha % Q_MOD != norm:
        return None
    inv2 = pow(2, Q_MOD - 2, Q_MOD)
    for al in (alpha, (Q_MOD - alpha) % Q_MOD):
        delta = (a0 + al) * inv2 % Q_MOD
        x0 = pow(delta, (Q_MOD + 1) // 4, Q_MOD)
        if x0 * x0 % Q_MOD != delta or x0 == 0:
            continue
        x1 = a1 * pow(2 * x0 % Q_MOD, Q_MOD - 2, Q_MOD) % Q_MOD
        cand = (x0, x1)
        if _fq2_mul(cand, cand) == a:
            return cand
    return None
