"""Prover checkpoint/resume: round-boundary snapshots of an in-flight prove.

The reference has NO checkpointing — a dispatcher crash loses the whole
prove (its per-round `Instant` prints are the only trace a round ever ran,
/root/reference/src/dispatcher.rs:625-942). This module closes that gap
(SURVEY.md §5): `prove(..., checkpoint=ProverCheckpoint(path))` persists,
after each of rounds 1-4, everything the remaining rounds need — the
inter-round polynomial handles, the Fiat-Shamir transcript sponge state,
the blinder RNG state, and the commitments/evaluations already produced.
A new process pointed at the same file resumes at the first unfinished
round and produces a proof BYTE-IDENTICAL to an uninterrupted run (test:
tests/test_checkpoint.py).

Design notes:
- One self-contained .npz file, written atomically (tmp + os.replace);
  each round overwrites the last, so at most one snapshot exists.
- Poly handles cross through the backend's `dump_h`/`load_h` (host numpy
  (16, L) uint32 Montgomery limb arrays on every backend), so the same
  checkpoint file is backend-portable: a prove started on the chip can
  resume on the host oracle and vice versa — both produce the same bytes.
- A workload fingerprint (hash of the verifying key and public input)
  binds the snapshot to its circuit+keys; resuming against anything else
  raises instead of silently producing an invalid proof.
- The transcript snapshot is the raw 200-byte STROBE/Keccak sponge state
  plus its three position counters (transcript.py `Strobe128`); the RNG
  snapshot is `random.Random.getstate()` — both restored exactly, so the
  challenge schedule and blinds continue bit-for-bit.
"""

import hashlib
import io
import json
import logging
import os
import zipfile

import numpy as np

from .transcript import g1_to_bytes_compressed, fr_to_bytes

log = logging.getLogger("dpt.checkpoint")


def workload_fingerprint(vk, pub_input):
    """Hash binding a checkpoint to its circuit + proving keys."""
    h = hashlib.sha256()
    h.update(vk.domain_size.to_bytes(8, "little"))
    h.update(vk.num_inputs.to_bytes(8, "little"))
    for ki in vk.k:
        h.update(fr_to_bytes(ki))
    for comm in list(vk.selector_comms) + list(vk.sigma_comms):
        h.update(g1_to_bytes_compressed(comm))
    for x in pub_input:
        h.update(fr_to_bytes(x))
    return h.hexdigest()


def dump_handle(backend, h):
    """Poly handle -> canonical (16, L) uint32 limb array (host numpy).
    Backends may provide a fast `dump_h`; the fallback goes through the
    universal lower() int-list protocol."""
    fn = getattr(backend, "dump_h", None)
    if fn is not None:
        return fn(h)
    from .backend.limbs import ints_to_limbs
    from .constants import FR_LIMBS
    return ints_to_limbs(backend.lower(h), FR_LIMBS)


def load_handle(backend, arr):
    fn = getattr(backend, "load_h", None)
    if fn is not None:
        return fn(arr)
    from .backend.limbs import limbs_to_ints
    return backend.lift(limbs_to_ints(arr))


def _point_enc(p):
    """Affine point (x, y) host ints or None (identity) -> JSON value."""
    return None if p is None else [hex(p[0]), hex(p[1])]


def _point_dec(v):
    return None if v is None else (int(v[0], 16), int(v[1], 16))


def _transcript_state(transcript):
    s = transcript.t.strobe
    return {"state": bytes(s.state).hex(), "pos": s.pos,
            "pos_begin": s.pos_begin, "cur_flags": s.cur_flags}


def _restore_transcript(transcript, snap):
    s = transcript.t.strobe
    s.state = bytearray(bytes.fromhex(snap["state"]))
    s.pos = snap["pos"]
    s.pos_begin = snap["pos_begin"]
    s.cur_flags = snap["cur_flags"]


# -- snapshot <-> bytes codec (shared by the file and store backends) --------

def encode_snapshot(round_no, fingerprint, rng, transcript, arrays, meta):
    """One self-contained npz blob for a completed round.

    arrays: {name: host numpy array} (poly handle dumps);
    meta: JSON-able dict (commitments, evaluations) for this round.
    """
    rng_state = rng.getstate()
    manifest = {
        "round": round_no,
        "fingerprint": fingerprint,
        "transcript": _transcript_state(transcript),
        # Mersenne-Twister state: (version, 625 ints, gauss_next)
        "rng": [rng_state[0], list(rng_state[1]), rng_state[2]],
        "meta": meta,
    }
    buf = io.BytesIO()
    np.savez(buf, __manifest__=np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8), **arrays)
    return buf.getvalue()


def decode_snapshot(blob, fingerprint, origin="<blob>"):
    """Blob -> {round, arrays, meta, rng_state, transcript} state dict.

    Raises ValueError on a fingerprint mismatch (wrong circuit/keys: the
    caller must NOT silently rebuild over someone else's snapshot).
    Returns None on structural damage (truncated/bit-flipped npz, missing
    manifest) — a corrupt snapshot is a missing snapshot, never a crash:
    the prove restarts from round 1 and, with a seeded RNG, still emits
    byte-identical proof bytes.
    """
    try:
        with np.load(io.BytesIO(blob)) as z:
            manifest = json.loads(bytes(z["__manifest__"]).decode())
            arrays = {k: z[k] for k in z.files if k != "__manifest__"}
        rng_state = (manifest["rng"][0], tuple(manifest["rng"][1]),
                     manifest["rng"][2])
        state = {
            "round": manifest["round"],
            "arrays": arrays,
            "meta": manifest["meta"],
            "rng_state": rng_state,
            "transcript": manifest["transcript"],
        }
        fp = manifest["fingerprint"]
    except (zipfile.BadZipFile, OSError, KeyError, json.JSONDecodeError,
            IndexError, TypeError, ValueError) as e:
        # ValueError here is np.load/json structural damage; the
        # fingerprint-mismatch ValueError is raised BELOW, outside this try
        log.warning("checkpoint %s undecodable (%s); treating as absent",
                    origin, e)
        return None
    if fp != fingerprint:
        raise ValueError(
            "checkpoint %s was written for a different circuit/keys "
            "(fingerprint %s != %s)" % (origin, fp, fingerprint))
    return state


def _flip_middle_byte(path):
    """Chaos plane (runtime/faults.py corrupt_ckpt): XOR one byte at the
    midpoint of `path`, under whatever integrity layer guards it. True
    iff there were bytes to flip."""
    try:
        with open(path, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if not size:
                return False
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        return True
    except OSError:
        return False


# DPT_CKPT_FSYNC=1: fsync the snapshot tmp file before the atomic rename.
# Default off (the historical contract — atomic-rename-only survives a
# process crash, which is what the kill/drain guards need); on makes the
# latch durable against power loss. Under the round pipeline the fsync is
# pure host-finalize work that overlaps other members' device launches.
_CKPT_FSYNC = os.environ.get("DPT_CKPT_FSYNC", "0") != "0"


class ProverCheckpoint:
    """Round-boundary checkpoint store backed by one .npz file.

    prove() drives it; user code only chooses the path:

        ck = ProverCheckpoint("run.ckpt.npz")
        proof = prove(rng, ckt, pk, backend, checkpoint=ck)

    If the process dies mid-prove, rerunning the same line resumes from
    the last completed round. `clear()` removes the file (prove() calls
    it on success so a finished run leaves nothing behind).
    """

    def __init__(self, path):
        self.path = path

    # -- write ---------------------------------------------------------------

    def save(self, round_no, fingerprint, rng, transcript, arrays, meta):
        """Persist a completed round atomically (tmp write + rename;
        optionally fsync'd — _CKPT_FSYNC)."""
        blob = encode_snapshot(round_no, fingerprint, rng, transcript,
                               arrays, meta)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            if _CKPT_FSYNC:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- read ----------------------------------------------------------------

    def load(self, fingerprint):
        """Return {round, arrays, meta, rng_state, transcript_snap} for the
        stored snapshot, or None if no (readable) checkpoint exists — a
        damaged file is deleted so the rerun restarts cleanly. Raises
        ValueError on a fingerprint mismatch (wrong circuit/keys)."""
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        state = decode_snapshot(blob, fingerprint, origin=self.path)
        if state is None:
            self.clear()
        return state

    def restore_into(self, state, rng, transcript):
        """Rewind rng + transcript to the snapshot point."""
        rng.setstate(state["rng_state"])
        _restore_transcript(transcript, state["transcript"])

    def has_snapshot(self):
        """Cheap existence probe (no decode, no metrics side effects):
        the batched prover uses it to route members that must RESUME to
        the sequential path, whose resume contract is the pinned one."""
        return os.path.exists(self.path)

    def clear(self):
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass

    def chaos_corrupt(self):
        """Fault injection: flip one byte mid-file. Returns True if there
        was a snapshot to corrupt. The next load() must detect the
        damage and restart the prove."""
        return _flip_middle_byte(self.path)


class StoreCheckpoint(ProverCheckpoint):
    """Round-boundary checkpoints as content-addressed store artifacts.

    Same wire format as the file backend (`encode_snapshot` npz bytes),
    persisted via `store.ArtifactStore` under `ckpt:<name>` — so prover
    checkpoints share the store's single durability surface: SHA-256
    integrity on every read (a bit-flipped snapshot is a detected miss,
    not a resumed-garbage prove), the one LRU byte budget, and the
    STORE_FETCH wire tag. A replacement worker on a FRESH host fetches
    the blob from the dispatcher/a peer (store/remote.py) and resumes the
    prove mid-flight instead of restarting it — cross-host resume is a
    network copy (tests/test_runtime_faults.py pins byte-identity).
    """

    def __init__(self, store, name):
        super().__init__(path=None)
        self.store = store
        self.key = name if name.startswith("ckpt:") else f"ckpt:{name}"

    def save(self, round_no, fingerprint, rng, transcript, arrays, meta):
        blob = encode_snapshot(round_no, fingerprint, rng, transcript,
                               arrays, meta)
        self.store.put(self.key, blob,
                       meta={"kind": "prover_ckpt", "round": round_no,
                             "fingerprint": fingerprint})

    def load(self, fingerprint):
        blob = self.store.get(self.key)  # integrity-verified; corrupt=None
        if blob is None:
            return None
        state = decode_snapshot(blob, fingerprint, origin=self.key)
        if state is None:  # parse damage below the SHA's radar (stale fmt)
            self.clear()
        return state

    def has_snapshot(self):
        return self.store.get_entry(self.key) is not None

    def clear(self):
        self.store.delete(self.key)

    def chaos_corrupt(self):
        """Flip a byte in the backing object file (the store's SHA-256
        must catch it on the next get). Returns True if a snapshot
        existed. Reaches into the store's object layout deliberately —
        corruption is injected UNDER the integrity layer being tested."""
        e = self.store.meta(self.key)
        if e is None:
            return False
        digest = None
        with self.store._lock:  # analysis: ok(chaos hook corrupts beneath the API on purpose)
            ent = self.store._manifest["entries"].get(self.key)
            if ent is not None:
                digest = ent["digest"]
        if digest is None:
            return False
        return _flip_middle_byte(self.store._obj_path(digest))
