"""Pure-Python reference BLS12-381 curve arithmetic + pairing (CPU oracle).

Replaces the role of `ark-ec`/`ark-bls12-381` in the reference
(/root/reference/Cargo.toml:31-37, used at src/worker.rs:122 for MSM and in
jf-plonk's verifier). The TPU G1 kernels are tested bit-identical against
these ops; the pairing is only used host-side by the verifier.

Point formats:
  G1 affine:   (x, y) ints, or None for the point at infinity.
  G1 jacobian: (X, Y, Z) with x = X/Z^2, y = Y/Z^3; Z == 0 -> infinity.
  G2 affine:   ((x0,x1), (y0,y1)) Fq2 pairs, or None.
"""

from .constants import (
    Q_MOD,
    R_MOD,
    G1_GEN_X,
    G1_GEN_Y,
    G2_GEN_X,
    G2_GEN_Y,
)
from . import fields as F
from .fields import (
    fq_inv,
    fq2_add,
    fq2_sub,
    fq2_mul,
    fq2_sq,
    fq2_inv,
    fq2_neg,
    fq12_mul,
    fq12_sq,
    fq12_inv,
    fq12_pow,
    FQ12_ONE,
)

G1_GEN = (G1_GEN_X, G1_GEN_Y)
G2_GEN = (G2_GEN_X, G2_GEN_Y)

INF = None


# --- G1 affine / jacobian ----------------------------------------------------

def g1_is_on_curve(p):
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x % Q_MOD * x + 4)) % Q_MOD == 0


def g1_neg(p):
    if p is None:
        return None
    return (p[0], (-p[1]) % Q_MOD)


def g1_add_affine(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % Q_MOD == 0:
            return None
        lam = 3 * x1 * x1 % Q_MOD * fq_inv(2 * y1 % Q_MOD) % Q_MOD
    else:
        lam = (y2 - y1) * fq_inv((x2 - x1) % Q_MOD) % Q_MOD
    x3 = (lam * lam - x1 - x2) % Q_MOD
    y3 = (lam * (x1 - x3) - y1) % Q_MOD
    return (x3, y3)


def g1_to_jac(p):
    if p is None:
        return (1, 1, 0)
    return (p[0], p[1], 1)


def g1_from_jac(j):
    X, Y, Z = j
    if Z == 0:
        return None
    zinv = fq_inv(Z)
    z2 = zinv * zinv % Q_MOD
    return (X * z2 % Q_MOD, Y * z2 % Q_MOD * zinv % Q_MOD)


def g1_jac_double(j):
    X1, Y1, Z1 = j
    if Z1 == 0:
        return j
    return _g1_jac_double_nonzero(X1, Y1, Z1)


def _g1_jac_double_nonzero(X1, Y1, Z1):
    # dbl-2009-l (a = 0)
    A = X1 * X1 % Q_MOD
    B = Y1 * Y1 % Q_MOD
    C = B * B % Q_MOD
    D = 2 * ((X1 + B) * (X1 + B) - A - C) % Q_MOD
    E = 3 * A % Q_MOD
    Fv = E * E % Q_MOD
    X3 = (Fv - 2 * D) % Q_MOD
    Y3 = (E * (D - X3) - 8 * C) % Q_MOD
    Z3 = 2 * Y1 * Z1 % Q_MOD
    return (X3, Y3, Z3)


def g1_jac_add(j1, j2):
    X1, Y1, Z1 = j1
    X2, Y2, Z2 = j2
    if Z1 == 0:
        return j2
    if Z2 == 0:
        return j1
    Z1Z1 = Z1 * Z1 % Q_MOD
    Z2Z2 = Z2 * Z2 % Q_MOD
    U1 = X1 * Z2Z2 % Q_MOD
    U2 = X2 * Z1Z1 % Q_MOD
    S1 = Y1 * Z2 % Q_MOD * Z2Z2 % Q_MOD
    S2 = Y2 * Z1 % Q_MOD * Z1Z1 % Q_MOD
    if U1 == U2:
        if S1 != S2:
            return (1, 1, 0)
        return _g1_jac_double_nonzero(X1, Y1, Z1)
    H = (U2 - U1) % Q_MOD
    I = 4 * H * H % Q_MOD
    J = H * I % Q_MOD
    rr = 2 * (S2 - S1) % Q_MOD
    V = U1 * I % Q_MOD
    X3 = (rr * rr - J - 2 * V) % Q_MOD
    Y3 = (rr * (V - X3) - 2 * S1 * J) % Q_MOD
    Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) % Q_MOD * H % Q_MOD
    return (X3, Y3, Z3)


def g1_mul(p, k, reduce=True):
    """Scalar multiplication (double-and-add, jacobian).

    reduce=False keeps k unreduced mod r — needed by subgroup checks
    (r·p = O?), where reducing would turn the check into 0·p."""
    if reduce:
        k %= R_MOD
    acc = (1, 1, 0)
    base = g1_to_jac(p)
    while k > 0:
        if k & 1:
            acc = g1_jac_add(acc, base)
        base = g1_jac_double(base)
        k >>= 1
    return g1_from_jac(acc)


def g1_msm(points, scalars):
    """Reference variable-base MSM (Pippenger, window=8).

    Oracle for the device MSM (reference behavior: src/worker.rs:159-185).
    Accepts affine points (None = infinity, as produced by the reference's
    zero-padding of the SRS at src/dispatcher2.rs:208).
    """
    assert len(points) == len(scalars)
    scalars = [s % R_MOD for s in scalars]
    c = 8
    num_windows = (R_MOD.bit_length() + c - 1) // c
    window_sums = []
    for w in range(num_windows):
        buckets = [(1, 1, 0)] * ((1 << c) - 1)
        shift = w * c
        for p, s in zip(points, scalars):
            if p is None:
                continue
            digit = (s >> shift) & ((1 << c) - 1)
            if digit != 0:
                buckets[digit - 1] = g1_jac_add(buckets[digit - 1], g1_to_jac(p))
        acc = (1, 1, 0)
        running = (1, 1, 0)
        for b in reversed(buckets):
            running = g1_jac_add(running, b)
            acc = g1_jac_add(acc, running)
        window_sums.append(acc)
    total = (1, 1, 0)
    for ws in reversed(window_sums):
        for _ in range(c):
            total = g1_jac_double(total)
        total = g1_jac_add(total, ws)
    return g1_from_jac(total)


# --- G2 affine ---------------------------------------------------------------

def g2_is_on_curve(p):
    if p is None:
        return True
    x, y = p
    rhs = fq2_add(fq2_mul(fq2_sq(x), x), (4, 4))
    return fq2_sub(fq2_sq(y), rhs) == (0, 0)


def g2_neg(p):
    if p is None:
        return None
    return (p[0], fq2_neg(p[1]))


def g2_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if fq2_add(y1, y2) == (0, 0):
            return None
        lam = fq2_mul(fq2_mul((3, 0), fq2_sq(x1)), fq2_inv(fq2_mul((2, 0), y1)))
    else:
        lam = fq2_mul(fq2_sub(y2, y1), fq2_inv(fq2_sub(x2, x1)))
    x3 = fq2_sub(fq2_sub(fq2_sq(lam), x1), x2)
    y3 = fq2_sub(fq2_mul(lam, fq2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_mul(p, k, reduce=True):
    if reduce:
        k %= R_MOD
    acc = None
    base = p
    while k > 0:
        if k & 1:
            acc = g2_add(acc, base)
        base = g2_add(base, base)
        k >>= 1
    return acc


# --- Pairing (Tate, with denominators eliminated by the final exponentiation)

def _fq12_from_fq(a):
    return (((a, 0), (0, 0), (0, 0)), ((0, 0), (0, 0), (0, 0)))


def _fq12_scalar_fq(a, k):
    """Multiply a generic Fq12 element by k in Fq."""
    c0, c1 = a
    return (
        tuple((x[0] * k % Q_MOD, x[1] * k % Q_MOD) for x in c0),
        tuple((x[0] * k % Q_MOD, x[1] * k % Q_MOD) for x in c1),
    )


def _fq12_sub(a, b):
    return (F.fq6_sub(a[0], b[0]), F.fq6_sub(a[1], b[1]))


_W = (F.FQ6_ZERO, F.FQ6_ONE)  # w, with w^2 = v, w^6 = xi = u + 1
_W2_INV = fq12_inv(fq12_sq(_W))
_W3_INV = fq12_inv(fq12_mul(fq12_sq(_W), _W))


def _untwist(q):
    """Map a G2 point on the twist E'/Fq2 into E(Fq12).

    BLS12-381 uses the M-twist y^2 = x^3 + 4(u+1); psi(x, y) =
    (x * w^-2, y * w^-3) lands on y^2 = x^3 + 4 since w^6 = u + 1.
    """
    x, y = q
    return (fq12_mul(_embed_fq2(x), _W2_INV), fq12_mul(_embed_fq2(y), _W3_INV))


def _embed_fq2(a):
    return ((a, F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


FINAL_EXP = (Q_MOD ** 12 - 1) // R_MOD


def miller_loop(p, q_untwisted):
    """f_{r,P}(Q) with vertical lines dropped (killed by the final exp).

    P is a G1 affine point (coords in Fq); Q is an untwisted G2 point with
    coordinates in Fq12. Line arithmetic stays in Fq; only the evaluation
    accumulator lives in Fq12.
    """
    xq, yq = q_untwisted
    f = FQ12_ONE
    tx, ty = p  # T = P, affine in Fq

    def line_eval(lam, x0, y0):
        # l(Q) = (y_Q - y0) - lam * (x_Q - x0)
        t1 = _fq12_sub(yq, _fq12_from_fq(y0))
        t2 = _fq12_scalar_fq(_fq12_sub(xq, _fq12_from_fq(x0)), lam)
        return _fq12_sub(t1, t2)

    bits = bin(R_MOD)[3:]  # skip leading 1
    T_inf = False
    for b in bits:
        if not T_inf:
            # doubling step
            if ty == 0:
                T_inf = True
            else:
                lam = 3 * tx * tx % Q_MOD * fq_inv(2 * ty % Q_MOD) % Q_MOD
                f = fq12_mul(fq12_sq(f), line_eval(lam, tx, ty))
                nx = (lam * lam - 2 * tx) % Q_MOD
                ny = (lam * (tx - nx) - ty) % Q_MOD
                tx, ty = nx, ny
        else:
            f = fq12_sq(f)
        if b == "1" and not T_inf:
            # addition step T += P
            px, py = p
            if tx == px:
                if (ty + py) % Q_MOD == 0:
                    # vertical line, dropped; T becomes infinity
                    T_inf = True
                else:
                    lam = 3 * tx * tx % Q_MOD * fq_inv(2 * ty % Q_MOD) % Q_MOD
                    f = fq12_mul(f, line_eval(lam, tx, ty))
                    nx = (lam * lam - 2 * tx) % Q_MOD
                    ny = (lam * (tx - nx) - ty) % Q_MOD
                    tx, ty = nx, ny
            else:
                lam = (py - ty) * fq_inv((px - tx) % Q_MOD) % Q_MOD
                f = fq12_mul(f, line_eval(lam, tx, ty))
                nx = (lam * lam - tx - px) % Q_MOD
                ny = (lam * (tx - nx) - ty) % Q_MOD
                tx, ty = nx, ny
    return f


# pairing-cost accounting: aggregation's whole value proposition is
# "N proofs, one 2-pair check", so tests pin the claim against these
# counters instead of trusting the docstring (reset_pairing_counters()
# then assert checks == 1 and pairs == 2 after verify_aggregate).
PAIRING_COUNTERS = {"checks": 0, "pairs": 0}


def reset_pairing_counters():
    PAIRING_COUNTERS["checks"] = 0
    PAIRING_COUNTERS["pairs"] = 0


def pairing_check(pairs):
    """Return True iff prod e(P_i, Q_i) == 1.

    Multi-pairing: one Miller loop per pair, a single shared final
    exponentiation. This is all the verifier needs (KZG check at
    jf-plonk's verify, reference src/dispatcher2.rs:1290-1293).
    """
    PAIRING_COUNTERS["checks"] += 1
    f = FQ12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        PAIRING_COUNTERS["pairs"] += 1
        f = fq12_mul(f, miller_loop(p, _untwist(q)))
    return fq12_pow(f, FINAL_EXP) == FQ12_ONE


def pairing(p, q):
    """Full pairing value (slow; used only in tests for bilinearity)."""
    if p is None or q is None:
        return FQ12_ONE
    return fq12_pow(miller_loop(p, _untwist(q)), FINAL_EXP)
