"""Job model: wire specs, circuit builders, and bucket (shape) keys.

A job spec names a WORKLOAD FAMILY + parameters + a witness seed, not a
circuit: the circuit is rebuilt deterministically from the spec on every
prove attempt (so a checkpoint-resumed retry sees the identical circuit),
and — crucially for the scheduler — two specs with the same parameters but
different seeds produce circuits with IDENTICAL structure (gates, wiring,
selectors): only witness values and the public input differ. That is what
makes a bucket's SRS + proving key shareable across every job in it
(verified empirically by tests/test_service.py: proofs made with the
bucket pk verify under the bucket vk for arbitrary seeds).

Families:
  toy      {"kind": "toy", "gates": G, "seed": S}
           add/mul/lc chain, G gates -> domain next_pow2(G + ~4). The
           small-domain family load tests and tier-1 use.
  merkle   {"kind": "merkle", "height": H, "num_proofs": P,
            "num_leaves": L?, "seed": S}
           the paper's Merkle-membership workload (workload.py); structure
           depends only on (H, P, L) because leaf indices are k % L.
  range    {"kind": "range", "bits": B, "count": C?, "seed": S}
  preimage {"kind": "preimage", "count": C?, "seed": S}
  rollup   {"kind": "rollup", "height": H, "updates": M?,
            "num_accounts": A?, "seed": S}
           the circuit zoo (circuits/ package, ISSUE 17): validation and
           construction are delegated to circuits.REGISTRY, and every zoo
           builder honors the same structure-from-params contract.

The SRS uses the repo's fixed test tau, so clients can rebuild the
matching vk locally with build_bucket_keys() and verify results without a
vk serializer. This is a test-setup service, not a production ceremony.
"""

import itertools
import os
import random
import threading
import time

from ..circuit import PlonkCircuit
from ..constants import R_MOD
from ..trace import new_trace_id
from .. import circuits

# same deterministic toxic-waste tau as tests/conftest.py's fixture SRS:
# server and clients derive identical keys from a spec alone
TEST_TAU = 0xDEADBEEF

_SPEC_KINDS = ("toy", "merkle") + circuits.KINDS

# SLO serving classes (ISSUE 16): flat ttl_s shedding grows into three
# classes with per-class queue priority (flagship pops first), per-class
# default deadlines (DPT_TTL_<CLASS>_S), and shed-lowest-class-first under
# pressure (queue.steal_lowest / the autoscaler). A spec without a class
# is `standard`, and an all-standard stream sorts, sheds, and proves
# exactly like the pre-class tree — that back-compat is the contract
# tests/test_autoscale.py pins.
SLO_CLASSES = ("flagship", "standard", "batch")
SLO_RANK = {"batch": 0, "standard": 1, "flagship": 2}
DEFAULT_SLO = "standard"


def class_default_ttl(slo):
    """Per-class default TTL seconds (`DPT_TTL_FLAGSHIP_S` /
    `DPT_TTL_STANDARD_S` / `DPT_TTL_BATCH_S`), read at call time so an
    operator (or test) can set one without rebuilding the service. The
    explicit per-job `ttl_s` always overrides. Unset or non-positive
    means no default deadline — exactly the pre-class behavior, so
    classless deployments keep bit-parity."""
    raw = os.environ.get("DPT_TTL_%s_S" % slo.upper())
    if not raw:
        return None
    try:
        ttl = float(raw)
    except ValueError:
        return None
    return ttl if ttl > 0 else None


class JobSpec:
    """Validated job description (the SUBMIT payload).

    Beyond the shape/witness fields, a spec may carry two durability
    knobs (both excluded from the shape key — they change nothing about
    the circuit):
      job_key  client-supplied idempotency key: two SUBMITs with the same
               job_key are ONE job, across retries, reconnects, and
               service restarts (the journal persists the mapping) — the
               duplicate is answered from the existing job or its
               finished-proof artifact, never re-proved.
      ttl_s    deadline budget in seconds from submission: a job that has
               not STARTED proving within its TTL is load-shed with a
               journaled, queryable SHED verdict instead of burning a
               worker on an answer nobody is waiting for.
      slo      serving class, one of SLO_CLASSES (default "standard"):
               decides queue precedence (flagship > standard > batch,
               ahead of the numeric priority), the default deadline
               (class_default_ttl, overridden by ttl_s), and who sheds
               first under pressure (lowest class). Excluded from the
               shape key — a class changes scheduling, never the circuit
               or the proof bytes.
    """

    def __init__(self, kind, params, seed, priority=0, job_key=None,
                 ttl_s=None, slo=DEFAULT_SLO):
        self.kind = kind
        self.params = params  # shape-determining, seed excluded
        self.seed = seed
        self.priority = priority
        self.job_key = job_key
        self.ttl_s = ttl_s
        self.slo = slo

    @classmethod
    def from_wire(cls, obj):
        """Parse + validate an untrusted JSON dict. Raises ValueError with
        a client-presentable reason."""
        if not isinstance(obj, dict):
            raise ValueError("spec must be a JSON object")
        kind = obj.get("kind")
        if kind not in _SPEC_KINDS:
            raise ValueError(f"unknown kind {kind!r} (want one of {_SPEC_KINDS})")
        seed = obj.get("seed", 0)
        priority = obj.get("priority", 0)
        if not isinstance(seed, int) or not isinstance(priority, int):
            raise ValueError("seed and priority must be integers")
        job_key = obj.get("job_key")
        if job_key is not None and not (isinstance(job_key, str)
                                        and 0 < len(job_key) <= 128):
            raise ValueError("job_key must be a 1..128 char string")
        ttl_s = obj.get("ttl_s")
        if ttl_s is not None:
            if not isinstance(ttl_s, (int, float)) or not ttl_s > 0:
                raise ValueError("ttl_s must be a positive number")
            ttl_s = float(ttl_s)
        slo = obj.get("slo", DEFAULT_SLO)
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"slo must be one of {SLO_CLASSES} (got {slo!r})")
        if kind == "toy":
            gates = obj.get("gates")
            if not isinstance(gates, int) or not 1 <= gates <= 1 << 16:
                raise ValueError("toy spec needs 1 <= gates <= 65536")
            params = {"gates": gates}
        elif kind in circuits.REGISTRY:
            params = circuits.validate_params(kind, obj)
        else:
            height = obj.get("height")
            num_proofs = obj.get("num_proofs", 1)
            if not isinstance(height, int) or not 1 <= height <= 64:
                raise ValueError("merkle spec needs 1 <= height <= 64")
            if not isinstance(num_proofs, int) or not 1 <= num_proofs <= 1 << 12:
                raise ValueError("merkle spec needs 1 <= num_proofs <= 4096")
            num_leaves = obj.get("num_leaves")
            if num_leaves is None:
                num_leaves = max(num_proofs, 3)
            if not isinstance(num_leaves, int) or num_leaves < 1:
                raise ValueError("num_leaves must be a positive integer")
            params = {"height": height, "num_proofs": num_proofs,
                      "num_leaves": num_leaves}
        return cls(kind, params, seed, priority, job_key=job_key,
                   ttl_s=ttl_s, slo=slo)

    def to_wire(self):
        out = {"kind": self.kind, "seed": self.seed,
               "priority": self.priority}
        if self.job_key is not None:
            out["job_key"] = self.job_key
        if self.ttl_s is not None:
            out["ttl_s"] = self.ttl_s
        # omitted when standard: a classless client round-trips to the
        # byte-identical wire dict it sent (pre-class servers also parse)
        if self.slo != DEFAULT_SLO:
            out["slo"] = self.slo
        out.update(self.params)
        return out


def shape_key(spec):
    """Bucket key: everything that determines circuit STRUCTURE (and so
    the domain size, SRS, proving key, and compiled stages)."""
    return (spec.kind,) + tuple(sorted(spec.params.items()))


def _toy_circuit(gates, seed):
    rng = random.Random(seed)
    ckt = PlonkCircuit()
    x = ckt.create_public_variable(rng.randrange(1, R_MOD))
    y = ckt.create_public_variable(rng.randrange(1, R_MOD))
    acc = ckt.add(x, y)
    for i in range(gates):
        if i % 3 == 0:
            acc = ckt.mul(acc, x)
        elif i % 3 == 1:
            acc = ckt.add(acc, y)
        else:
            acc = ckt.lc([acc, x, y, acc], [1, 2, 3, 4])
    return ckt


def build_circuit(spec):
    """Spec -> finalized, satisfied circuit (deterministic in the spec)."""
    if spec.kind == "toy":
        ckt = _toy_circuit(spec.params["gates"], spec.seed)
        ok, bad = ckt.check_satisfiability()
        assert ok, f"toy circuit unsatisfied at gate {bad}"
        return ckt.finalize()
    if spec.kind in circuits.REGISTRY:
        return circuits.build(spec.kind, spec.params, spec.seed)
    from ..workload import generate_circuit
    ckt, _tree = generate_circuit(
        rng=random.Random(spec.seed), height=spec.params["height"],
        num_proofs=spec.params["num_proofs"],
        num_leaves=spec.params["num_leaves"])
    return ckt


def build_bucket_keys(spec, backend=None):
    """(srs, pk, vk) for a spec's SHAPE — seed-independent, so the server's
    scheduler and a verifying client derive identical keys. Uses the
    canonical seed-0 circuit purely as the structure donor."""
    from .. import kzg
    canonical = JobSpec(spec.kind, dict(spec.params), seed=0)
    ckt = build_circuit(canonical)
    srs = kzg.universal_setup(ckt.n + 3, tau=TEST_TAU)
    pk, vk = kzg.preprocess(srs, ckt, backend=backend)
    return srs, pk, vk


# --- job lifecycle -----------------------------------------------------------

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
SHED = "shed"        # deadline/TTL load shedding: a journaled, queryable
                     # verdict (STATUS reports it like done/failed)
TERMINAL = (DONE, FAILED, SHED)

_job_seq = itertools.count(1)
# per-process run token in every job id: ids (and so checkpoint file
# names under a persistent --ckpt-dir) can never collide with a previous
# crashed run's, whose counter also started at 1
_RUN_TOKEN = "%04x" % random.SystemRandom().randrange(1 << 16)


class Job:
    """One submitted proof job. Mutated by exactly one owner at a time
    (server accept thread -> scheduler -> pool worker); `status()` builds
    the externally visible JSON snapshot."""

    def __init__(self, spec, job_id=None):
        # job_id: journal recovery reuses the ORIGINAL id so the job's
        # checkpoint artifact (ckpt:<id>) and finished-proof artifact
        # (proof:<id>) still address its state from the previous process
        self.id = job_id or "job-%s-%06d" % (_RUN_TOKEN, next(_job_seq))
        self.spec = spec
        self.shape_key = shape_key(spec)
        self.priority = spec.priority
        self.job_key = spec.job_key
        self.slo = getattr(spec, "slo", DEFAULT_SLO)
        self.slo_rank = SLO_RANK.get(self.slo, SLO_RANK[DEFAULT_SLO])
        # wall clock, not monotonic: the deadline must survive a service
        # restart (the journal carries it; a recovered job whose TTL
        # expired during the outage is shed, not resumed). Explicit
        # ttl_s wins; otherwise the job's SLO class supplies the default
        ttl = spec.ttl_s if spec.ttl_s is not None \
            else class_default_ttl(self.slo)
        self.deadline_ts = time.time() + ttl if ttl is not None else None
        # every job IS one trace: the id is stamped here (or adopted from
        # the client's trace_ctx by the frontend), handed to the prover
        # tracer, and addresses the merged-timeline artifact trace:<id>
        self.trace_id = new_trace_id()
        self.trace_parent = None    # client-side parent span, if adopted
        self.trace_dump = None      # merged timeline (set at finish_ok)
        self.state = QUEUED
        self.submitted_at = time.monotonic()
        self.submitted_wall = time.time()   # anchors the queue-wait span
        self.scheduled_at = None
        self.started_at = None
        self.finished_at = None
        self.retries = 0
        self.attempts = []     # [{worker, outcome}]
        self.worker = None
        self.batch_id = None
        self.batch_size = None
        # placement verdict (service/placement.py): "batch" (data-parallel
        # cross-job prove), "mesh" (sharded submesh prove), or "pool"
        # (per-job worker dispatch — also the base scheduler's only mode)
        self.placement = None
        self.error = None
        self.proof_bytes = None
        self.public_input = None
        self.round_totals = {}
        self.done_event = threading.Event()

    @property
    def wait_s(self):
        """submit -> first prove start (queue + key-build wait)."""
        if self.started_at is None:
            return time.monotonic() - self.submitted_at
        return self.started_at - self.submitted_at

    @property
    def run_s(self):
        if self.started_at is None:
            return None
        end = self.finished_at or time.monotonic()
        return end - self.started_at

    def finish_ok(self, proof_bytes, public_input, round_totals):
        self.proof_bytes = proof_bytes
        self.public_input = public_input
        self.round_totals = round_totals
        self.state = DONE
        self.finished_at = time.monotonic()
        self.done_event.set()

    def finish_err(self, reason):
        self.error = reason
        self.state = FAILED
        self.finished_at = time.monotonic()
        self.done_event.set()

    def finish_shed(self, reason):
        """Terminal load-shed verdict (deadline/TTL): clients polling
        STATUS see state=shed + the reason, same shape as a failure."""
        self.error = reason
        self.state = SHED
        self.finished_at = time.monotonic()
        self.done_event.set()

    def expired(self, now=None):
        """True once the job's TTL deadline has passed (never for jobs
        without one). Checked before key build and before each prove
        attempt — not during one (a started prove is worth finishing:
        its result is cacheable under the job_key)."""
        if self.deadline_ts is None:
            return False
        return (now if now is not None else time.time()) > self.deadline_ts

    def status(self):
        return {
            "job_id": self.id,
            "state": self.state,
            "trace_id": self.trace_id,
            "trace_spans": (len(self.trace_dump.get("events") or [])
                            if self.trace_dump else None),
            "spec": self.spec.to_wire(),
            "shape_key": [str(p) for p in self.shape_key],
            "priority": self.priority,
            "slo": self.slo,
            "job_key": self.job_key,
            "deadline_ts": self.deadline_ts,
            "retries": self.retries,
            "attempts": list(self.attempts),
            "worker": self.worker,
            "batch_id": self.batch_id,
            "batch_size": self.batch_size,
            "placement": self.placement,
            "wait_s": round(self.wait_s, 6),
            "run_s": None if self.run_s is None else round(self.run_s, 6),
            "rounds": {k: round(v, 6) for k, v in self.round_totals.items()},
            "error": self.error,
        }
