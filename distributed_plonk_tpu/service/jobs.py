"""Job model: wire specs, circuit builders, and bucket (shape) keys.

A job spec names a WORKLOAD FAMILY + parameters + a witness seed, not a
circuit: the circuit is rebuilt deterministically from the spec on every
prove attempt (so a checkpoint-resumed retry sees the identical circuit),
and — crucially for the scheduler — two specs with the same parameters but
different seeds produce circuits with IDENTICAL structure (gates, wiring,
selectors): only witness values and the public input differ. That is what
makes a bucket's SRS + proving key shareable across every job in it
(verified empirically by tests/test_service.py: proofs made with the
bucket pk verify under the bucket vk for arbitrary seeds).

Families:
  toy    {"kind": "toy", "gates": G, "seed": S}
         add/mul/lc chain, G gates -> domain next_pow2(G + ~4). The
         small-domain family load tests and tier-1 use.
  merkle {"kind": "merkle", "height": H, "num_proofs": P,
          "num_leaves": L?, "seed": S}
         the paper's Merkle-membership workload (workload.py); structure
         depends only on (H, P, L) because leaf indices are k % L.

The SRS uses the repo's fixed test tau, so clients can rebuild the
matching vk locally with build_bucket_keys() and verify results without a
vk serializer. This is a test-setup service, not a production ceremony.
"""

import itertools
import random
import threading
import time

from ..circuit import PlonkCircuit
from ..constants import R_MOD

# same deterministic toxic-waste tau as tests/conftest.py's fixture SRS:
# server and clients derive identical keys from a spec alone
TEST_TAU = 0xDEADBEEF

_SPEC_KINDS = ("toy", "merkle")


class JobSpec:
    """Validated job description (the SUBMIT payload)."""

    def __init__(self, kind, params, seed, priority=0):
        self.kind = kind
        self.params = params  # shape-determining, seed excluded
        self.seed = seed
        self.priority = priority

    @classmethod
    def from_wire(cls, obj):
        """Parse + validate an untrusted JSON dict. Raises ValueError with
        a client-presentable reason."""
        if not isinstance(obj, dict):
            raise ValueError("spec must be a JSON object")
        kind = obj.get("kind")
        if kind not in _SPEC_KINDS:
            raise ValueError(f"unknown kind {kind!r} (want one of {_SPEC_KINDS})")
        seed = obj.get("seed", 0)
        priority = obj.get("priority", 0)
        if not isinstance(seed, int) or not isinstance(priority, int):
            raise ValueError("seed and priority must be integers")
        if kind == "toy":
            gates = obj.get("gates")
            if not isinstance(gates, int) or not 1 <= gates <= 1 << 16:
                raise ValueError("toy spec needs 1 <= gates <= 65536")
            params = {"gates": gates}
        else:
            height = obj.get("height")
            num_proofs = obj.get("num_proofs", 1)
            if not isinstance(height, int) or not 1 <= height <= 64:
                raise ValueError("merkle spec needs 1 <= height <= 64")
            if not isinstance(num_proofs, int) or not 1 <= num_proofs <= 1 << 12:
                raise ValueError("merkle spec needs 1 <= num_proofs <= 4096")
            num_leaves = obj.get("num_leaves")
            if num_leaves is None:
                num_leaves = max(num_proofs, 3)
            if not isinstance(num_leaves, int) or num_leaves < 1:
                raise ValueError("num_leaves must be a positive integer")
            params = {"height": height, "num_proofs": num_proofs,
                      "num_leaves": num_leaves}
        return cls(kind, params, seed, priority)

    def to_wire(self):
        out = {"kind": self.kind, "seed": self.seed,
               "priority": self.priority}
        out.update(self.params)
        return out


def shape_key(spec):
    """Bucket key: everything that determines circuit STRUCTURE (and so
    the domain size, SRS, proving key, and compiled stages)."""
    return (spec.kind,) + tuple(sorted(spec.params.items()))


def _toy_circuit(gates, seed):
    rng = random.Random(seed)
    ckt = PlonkCircuit()
    x = ckt.create_public_variable(rng.randrange(1, R_MOD))
    y = ckt.create_public_variable(rng.randrange(1, R_MOD))
    acc = ckt.add(x, y)
    for i in range(gates):
        if i % 3 == 0:
            acc = ckt.mul(acc, x)
        elif i % 3 == 1:
            acc = ckt.add(acc, y)
        else:
            acc = ckt.lc([acc, x, y, acc], [1, 2, 3, 4])
    return ckt


def build_circuit(spec):
    """Spec -> finalized, satisfied circuit (deterministic in the spec)."""
    if spec.kind == "toy":
        ckt = _toy_circuit(spec.params["gates"], spec.seed)
        ok, bad = ckt.check_satisfiability()
        assert ok, f"toy circuit unsatisfied at gate {bad}"
        return ckt.finalize()
    from ..workload import generate_circuit
    ckt, _tree = generate_circuit(
        rng=random.Random(spec.seed), height=spec.params["height"],
        num_proofs=spec.params["num_proofs"],
        num_leaves=spec.params["num_leaves"])
    return ckt


def build_bucket_keys(spec, backend=None):
    """(srs, pk, vk) for a spec's SHAPE — seed-independent, so the server's
    scheduler and a verifying client derive identical keys. Uses the
    canonical seed-0 circuit purely as the structure donor."""
    from .. import kzg
    canonical = JobSpec(spec.kind, dict(spec.params), seed=0)
    ckt = build_circuit(canonical)
    srs = kzg.universal_setup(ckt.n + 3, tau=TEST_TAU)
    pk, vk = kzg.preprocess(srs, ckt, backend=backend)
    return srs, pk, vk


# --- job lifecycle -----------------------------------------------------------

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"

_job_seq = itertools.count(1)
# per-process run token in every job id: ids (and so checkpoint file
# names under a persistent --ckpt-dir) can never collide with a previous
# crashed run's, whose counter also started at 1
_RUN_TOKEN = "%04x" % random.SystemRandom().randrange(1 << 16)


class Job:
    """One submitted proof job. Mutated by exactly one owner at a time
    (server accept thread -> scheduler -> pool worker); `status()` builds
    the externally visible JSON snapshot."""

    def __init__(self, spec):
        self.id = "job-%s-%06d" % (_RUN_TOKEN, next(_job_seq))
        self.spec = spec
        self.shape_key = shape_key(spec)
        self.priority = spec.priority
        self.state = QUEUED
        self.submitted_at = time.monotonic()
        self.scheduled_at = None
        self.started_at = None
        self.finished_at = None
        self.retries = 0
        self.attempts = []     # [{worker, outcome}]
        self.worker = None
        self.batch_id = None
        self.batch_size = None
        self.error = None
        self.proof_bytes = None
        self.public_input = None
        self.round_totals = {}
        self.done_event = threading.Event()

    @property
    def wait_s(self):
        """submit -> first prove start (queue + key-build wait)."""
        if self.started_at is None:
            return time.monotonic() - self.submitted_at
        return self.started_at - self.submitted_at

    @property
    def run_s(self):
        if self.started_at is None:
            return None
        end = self.finished_at or time.monotonic()
        return end - self.started_at

    def finish_ok(self, proof_bytes, public_input, round_totals):
        self.proof_bytes = proof_bytes
        self.public_input = public_input
        self.round_totals = round_totals
        self.state = DONE
        self.finished_at = time.monotonic()
        self.done_event.set()

    def finish_err(self, reason):
        self.error = reason
        self.state = FAILED
        self.finished_at = time.monotonic()
        self.done_event.set()

    def status(self):
        return {
            "job_id": self.id,
            "state": self.state,
            "spec": self.spec.to_wire(),
            "shape_key": [str(p) for p in self.shape_key],
            "priority": self.priority,
            "retries": self.retries,
            "attempts": list(self.attempts),
            "worker": self.worker,
            "batch_id": self.batch_id,
            "batch_size": self.batch_size,
            "wait_s": round(self.wait_s, 6),
            "run_s": None if self.run_s is None else round(self.run_s, 6),
            "rounds": {k: round(v, 6) for k, v in self.round_totals.items()},
            "error": self.error,
        }
