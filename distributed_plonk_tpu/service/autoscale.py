"""Closed-loop autoscaler: the observability plane drives the fleet.

PRs 11-15 built every sensor (queue depth, per-class mix, the
slo_roundtrip/<class> p95, fleet MFU gauges, per-worker up/suspect state
from the liveness tracker) and every actuator (WorkerSupervisor.add_slot
+ warm membership JOIN, graceful retire_slot drain-then-LEAVE,
SubmeshLeaser.set_capacity) — this module closes the loop (ROADMAP
"Next directions" #3, ISSUE 16). An `Autoscaler` ticks every
DPT_AUTOSCALE_TICK_S seconds:

  sensors   queue depth + depth-by-SLO-class, busy pool workers, the
            standard-class roundtrip p95 from the metrics registry,
            mean kernel MFU, fleet width/usable/suspects from the
            dispatcher's liveness tracker, supervised worker count.
  control   hysteresis streaks + cooldown windows + min/max bounds:
            scale UP (supervisor.add_slot — warm rejoin makes this
            seconds) after `up_ticks` consecutive breach ticks (queue
            depth per worker over DPT_AS_UP_QUEUE, or standard p95 over
            DPT_SLO_STANDARD_S) and an elapsed DPT_AS_UP_COOLDOWN_S;
            scale DOWN (supervisor.retire_slot — drain, membership
            LEAVE, then SIGTERM: never a mid-prove kill) after
            `down_ticks` consecutive idle ticks and an elapsed
            DPT_AS_DOWN_COOLDOWN_S; resize the submesh lease capacity
            between batch-dominated and flagship traffic; and under
            queue pressure shed lowest-class-first through
            queue.steal_lowest + pool.shed.
  obs       every decision is one structured log event (subsystem
            `autoscale`) + autoscale_* counters/gauges; /autoscale on
            the ObsServer returns `state()` (targets, streaks,
            cooldowns, last decisions); scripts/console.py renders it.

Modes (DPT_AUTOSCALE): "0" (default) — OFF, `attach` returns None
without constructing anything, bit-parity with the pre-autoscaler tree;
"dry" — the loop runs, decisions are computed, logged, and counted, but
ZERO actuator calls happen (every decision records applied=False);
"1" — actuating.

Knobs (env, read at construction; constructor args override):
    DPT_AUTOSCALE           0 | dry | 1 (0)
    DPT_AUTOSCALE_TICK_S    control-loop period, seconds (2)
    DPT_AS_MIN_WORKERS      scale-down floor (1)
    DPT_AS_MAX_WORKERS      scale-up ceiling (8)
    DPT_AS_UP_QUEUE         queued jobs per worker that count as a
                            breach (2)
    DPT_AS_UP_TICKS         consecutive breach ticks before an up (2)
    DPT_AS_DOWN_TICKS       consecutive idle ticks before a down (5)
    DPT_AS_UP_COOLDOWN_S    min seconds between ups (10)
    DPT_AS_DOWN_COOLDOWN_S  min seconds between downs (30)
    DPT_SLO_STANDARD_S      standard-class p95 target, seconds; unset
                            disables the latency breach signal
    DPT_AS_SHED_WATERMARK   queue-fullness fraction that arms the
                            pressure shed (0.9)

The controller is deliberately dependency-injected: `sensors` (a
callable returning the sensor dict) and `actuators` (worker_count /
add_worker / retire_worker / lease_capacity / shed_lowest) default to
the live service + supervisor but are plain fakes in
tests/test_autoscale.py and bench.py's canary — `tick()` is directly
callable, so the control law is tested without threads, sockets, or
clocks (inject `clock`).
"""

import os
import threading
import time
from collections import deque

from ..obs import log as olog
from .jobs import SLO_CLASSES, SLO_RANK

MODES = ("0", "dry", "1")


def mode_from_env():
    """DPT_AUTOSCALE -> "0" | "dry" | "1" (unknown values read as off:
    a typo must fail safe, not fail actuating)."""
    raw = os.environ.get("DPT_AUTOSCALE", "0").strip().lower()
    if raw in ("1", "on", "true", "actuate"):
        return "1"
    if raw in ("dry", "recommend"):
        return "dry"
    return "0"


def _env_f(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class _NullMetrics:
    def inc(self, name, n=1):
        pass

    def gauge(self, name, value):
        pass


class ServiceActuators:
    """The live actuator surface over a ProofService (+ optional
    WorkerSupervisor). Worker scaling without a supervisor is a no-op
    returning None — the controller records the decision as not applied
    instead of crashing a supervisor-less deployment."""

    def __init__(self, service, supervisor=None):
        self.service = service
        self.supervisor = supervisor

    def worker_count(self):
        if self.supervisor is not None:
            return self.supervisor.active_count()
        return None  # unsupervised pool: worker scaling is unavailable

    def add_worker(self):
        if self.supervisor is None:
            return None
        return self.supervisor.add_slot()

    def retire_worker(self):
        """Retire the highest-index active slot. The drain can take up
        to DPT_SUP_RETIRE_TIMEOUT_S, so it runs on a daemon thread — the
        control loop must keep ticking while a worker drains. Returns
        the retiring slot index (the retire is INITIATED, not complete)
        or None."""
        sup = self.supervisor
        if sup is None:
            return None
        with sup._lock:
            victims = [j for j, s in enumerate(sup.slots)
                       if not s.failed and not s.retired]
        if not victims:
            return None
        j = victims[-1]
        threading.Thread(target=sup.retire_slot, args=(j,),
                         name=f"autoscale-retire-{j}", daemon=True).start()
        return j

    def lease_capacity(self, frac):
        """Resize the submesh leaser to `frac` of the device pool.
        Returns the applied capacity, or None when no leaser exists yet
        (small-jobs-only service: nothing to resize)."""
        sched = self.service.scheduler
        leaser = getattr(sched, "_leaser_if_ready", lambda: None)()
        if leaser is None:
            return None
        k = max(1, round(frac * leaser.total()))
        return leaser.set_capacity(k)

    def shed_lowest(self, below_rank):
        """Evict the worst queued job of class rank < below_rank with a
        journaled SHED verdict. Returns the victim's class or None."""
        victim = self.service.queue.steal_lowest(below_rank)
        if victim is None:
            return None
        self.service.pool.shed(victim, "autoscale pressure shed")
        return victim.slo


class Autoscaler:
    def __init__(self, service=None, supervisor=None, metrics=None,
                 mode=None, tick_s=None, sensors=None, actuators=None,
                 min_workers=None, max_workers=None,
                 up_queue_per_worker=None, up_ticks=None, down_ticks=None,
                 up_cooldown_s=None, down_cooldown_s=None,
                 slo_p95_standard_s=None, shed_watermark=None,
                 clock=time.monotonic):
        self.service = service
        self.metrics = metrics if metrics is not None else \
            (service.metrics if service is not None else _NullMetrics())
        self.mode = mode_from_env() if mode is None else str(mode)
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}")
        self.tick_s = tick_s if tick_s is not None \
            else _env_f("DPT_AUTOSCALE_TICK_S", "2")
        self.min_workers = min_workers if min_workers is not None \
            else int(_env_f("DPT_AS_MIN_WORKERS", "1"))
        self.max_workers = max_workers if max_workers is not None \
            else int(_env_f("DPT_AS_MAX_WORKERS", "8"))
        self.up_queue_per_worker = up_queue_per_worker \
            if up_queue_per_worker is not None \
            else _env_f("DPT_AS_UP_QUEUE", "2")
        self.up_ticks = up_ticks if up_ticks is not None \
            else int(_env_f("DPT_AS_UP_TICKS", "2"))
        self.down_ticks = down_ticks if down_ticks is not None \
            else int(_env_f("DPT_AS_DOWN_TICKS", "5"))
        self.up_cooldown_s = up_cooldown_s if up_cooldown_s is not None \
            else _env_f("DPT_AS_UP_COOLDOWN_S", "10")
        self.down_cooldown_s = down_cooldown_s \
            if down_cooldown_s is not None \
            else _env_f("DPT_AS_DOWN_COOLDOWN_S", "30")
        raw_slo = os.environ.get("DPT_SLO_STANDARD_S")
        self.slo_p95_standard_s = slo_p95_standard_s \
            if slo_p95_standard_s is not None \
            else (float(raw_slo) if raw_slo else None)
        self.shed_watermark = shed_watermark if shed_watermark is not None \
            else _env_f("DPT_AS_SHED_WATERMARK", "0.9")
        self.clock = clock
        self.sensors = sensors or self.read_sensors
        self.actuators = actuators or ServiceActuators(service, supervisor)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._ticks = 0
        self._up_streak = 0
        self._down_streak = 0
        self._up_cool_until = 0.0
        self._down_cool_until = 0.0
        self._lease_frac = 1.0
        self._last_sensors = None
        self._decisions = deque(maxlen=32)

    @property
    def actuating(self):
        return self.mode == "1"

    # -- sensors --------------------------------------------------------------

    def read_sensors(self):
        """The default sensor sweep over the live service. Every field
        degrades to None/empty rather than raising — a half-wired
        service (no fleet, no supervisor) still autoscales on what it
        can see."""
        out = {"queue_depth": 0, "queue_by_class": {}, "max_depth": None,
               "busy_workers": 0, "p95_standard_s": None, "mfu_pct": None,
               "fleet": None}
        svc = self.service
        if svc is None:
            return out
        out["queue_depth"] = svc.queue.depth()
        out["queue_by_class"] = svc.queue.depth_by_class()
        out["max_depth"] = svc.queue.max_depth
        out["busy_workers"] = len(svc.pool.busy())
        snap = svc.metrics.snapshot()
        h = snap["histograms"].get("slo_roundtrip/standard")
        if h and h.get("count"):
            out["p95_standard_s"] = h.get("p95_s")
        mfu = [v for k, v in snap["gauges"].items()
               if k.startswith("mfu_") and isinstance(v, (int, float))]
        if mfu:
            out["mfu_pct"] = round(sum(mfu) / len(mfu), 3)
        d = svc.fleet_dispatcher
        if d is not None:
            try:
                ts = d.tracker.snapshot()
                out["fleet"] = {
                    "epoch": d.epoch, "width": len(ts),
                    "usable": sum(1 for s in ts if not s["open"]),
                    "suspects": sum(1 for s in ts if s["suspect"]),
                }
            except Exception:
                pass
        return out

    # -- the control law ------------------------------------------------------

    def tick(self):
        """One control cycle: read sensors, decide, (maybe) actuate,
        record. Directly callable — the unit tests and the bench canary
        drive the law without the thread. Returns this tick's decision
        list (possibly empty)."""
        now = self.clock()
        try:
            sensors = self.sensors()
        except Exception:
            self.metrics.inc("autoscale_sensor_errors")
            return []
        with self._lock:
            self._ticks += 1
            self._last_sensors = sensors
        self.metrics.inc("autoscale_ticks")
        decisions = []
        workers = self.actuators.worker_count()
        depth = sensors.get("queue_depth") or 0
        busy = sensors.get("busy_workers") or 0
        p95 = sensors.get("p95_standard_s")

        # breach / idle hysteresis streaks (mutually exclusive per tick)
        breach = False
        reasons = []
        if workers is not None and workers > 0 \
                and depth / workers >= self.up_queue_per_worker:
            breach = True
            reasons.append(f"queue/worker={depth / workers:.2f}"
                           f">={self.up_queue_per_worker:g}")
        if self.slo_p95_standard_s is not None and p95 is not None \
                and p95 > self.slo_p95_standard_s:
            breach = True
            reasons.append(f"p95={p95:.3f}s>{self.slo_p95_standard_s:g}s")
        idle = depth == 0 and busy == 0
        with self._lock:
            self._up_streak = self._up_streak + 1 if breach else 0
            self._down_streak = self._down_streak + 1 if idle else 0
            up_streak, down_streak = self._up_streak, self._down_streak

        # scale up: streak + bounds + cooldown
        if breach and up_streak >= self.up_ticks and workers is not None:
            if workers >= self.max_workers:
                pass  # at the ceiling: the streak stays armed, no event
            elif now < self._up_cool_until:
                pass  # cooling down from the last up
            else:
                applied, detail = self._actuate(
                    lambda: self.actuators.add_worker())
                decisions.append(self._decision(
                    "scale_up", "; ".join(reasons), applied,
                    {"workers": workers, "target": workers + 1,
                     "slot": detail}))
                with self._lock:
                    self._up_streak = 0
                    self._up_cool_until = now + self.up_cooldown_s

        # scale down: idle streak + floor + cooldown. Only when nothing
        # is queued or proving — retire never races in-flight work (the
        # retire itself also drains before LEAVE, belt and braces).
        if idle and down_streak >= self.down_ticks and workers is not None:
            if workers <= self.min_workers or now < self._down_cool_until:
                pass
            else:
                applied, detail = self._actuate(
                    lambda: self.actuators.retire_worker())
                decisions.append(self._decision(
                    "scale_down", f"idle x{down_streak}", applied,
                    {"workers": workers, "target": workers - 1,
                     "slot": detail}))
                with self._lock:
                    self._down_streak = 0
                    self._down_cool_until = now + self.down_cooldown_s

        # lease capacity: batch-dominated queues give half the device
        # pool back to interactive classes; any queued flagship (or an
        # empty queue) restores full capacity
        by_class = sensors.get("queue_by_class") or {}
        flagship_q = by_class.get("flagship", 0)
        batch_q = by_class.get("batch", 0)
        want_frac = 0.5 if (depth > 0 and flagship_q == 0
                            and batch_q >= depth / 2) else 1.0
        if want_frac != self._lease_frac:
            applied, detail = self._actuate(
                lambda: self.actuators.lease_capacity(want_frac))
            decisions.append(self._decision(
                "lease_resize",
                f"batch={batch_q} flagship={flagship_q} depth={depth}",
                applied, {"frac": want_frac, "capacity": detail}))
            self._lease_frac = want_frac

        # pressure shed: the queue is nearly full — evict the worst
        # sub-flagship job now instead of letting admission bounce the
        # next flagship SUBMIT
        max_depth = sensors.get("max_depth")
        if max_depth and depth >= self.shed_watermark * max_depth:
            applied, detail = self._actuate(
                lambda: self.actuators.shed_lowest(SLO_RANK["flagship"]))
            if not self.actuating or detail is not None:
                decisions.append(self._decision(
                    "shed", f"depth={depth}/{max_depth}", applied,
                    {"victim_class": detail}))

        for d in decisions:
            self._record(d)
        self._publish_gauges(sensors, workers)
        return decisions

    def _actuate(self, fn):
        """Run one actuator call in mode "1"; in "dry" record only.
        Returns (applied, detail) — applied is False in dry mode and
        when the actuator declined (returned None)."""
        if not self.actuating:
            return False, None
        try:
            detail = fn()
        except Exception as e:  # an actuator failing must not kill the loop
            self.metrics.inc("autoscale_actuator_errors")
            return False, f"error: {e!r}"
        return detail is not None, detail

    def _decision(self, action, reason, applied, detail):
        return {"ts": round(time.time(), 3), "action": action,
                "reason": reason, "mode": self.mode,
                "applied": bool(applied), "detail": detail}

    def _record(self, d):
        with self._lock:
            self._decisions.append(d)
        self.metrics.inc("autoscale_decisions")
        if d["applied"]:
            self.metrics.inc({"scale_up": "autoscale_scale_ups",
                              "scale_down": "autoscale_scale_downs",
                              "lease_resize": "autoscale_lease_resizes",
                              "shed": "autoscale_sheds"}[d["action"]])
        olog.emit("autoscale", d["action"],
                  level="info" if d["applied"] else "debug",
                  mode=d["mode"], applied=d["applied"],
                  reason=d["reason"], **{
                      k: v for k, v in (d["detail"] or {}).items()
                      if isinstance(v, (int, float, str, bool,
                                        type(None)))})

    def _publish_gauges(self, sensors, workers):
        if workers is not None:
            self.metrics.gauge("autoscale_workers", workers)
            self.metrics.gauge("autoscale_target_workers",
                               max(self.min_workers,
                                   min(self.max_workers, workers)))
        by_class = sensors.get("queue_by_class") or {}
        for cls in SLO_CLASSES:
            self.metrics.gauge(f"autoscale_queue_{cls}",
                               by_class.get(cls, 0))

    # -- introspection --------------------------------------------------------

    def state(self):
        """The /autoscale endpoint payload: mode, bounds/targets, live
        worker count, per-class queue depth, hysteresis streaks,
        cooldown remainders, and the recent decision ring."""
        now = self.clock()
        with self._lock:
            decisions = list(self._decisions)
            sensors = self._last_sensors or {}
            ticks = self._ticks
            up_streak, down_streak = self._up_streak, self._down_streak
            up_rem = max(0.0, self._up_cool_until - now)
            down_rem = max(0.0, self._down_cool_until - now)
        return {
            "mode": self.mode,
            "tick_s": self.tick_s,
            "ticks": ticks,
            "bounds": {"min_workers": self.min_workers,
                       "max_workers": self.max_workers},
            "targets": {"up_queue_per_worker": self.up_queue_per_worker,
                        "slo_p95_standard_s": self.slo_p95_standard_s,
                        "up_ticks": self.up_ticks,
                        "down_ticks": self.down_ticks,
                        "up_cooldown_s": self.up_cooldown_s,
                        "down_cooldown_s": self.down_cooldown_s},
            "workers": self.actuators.worker_count(),
            "queue": {"depth": sensors.get("queue_depth"),
                      "by_class": sensors.get("queue_by_class") or {}},
            "p95_standard_s": sensors.get("p95_standard_s"),
            "fleet": sensors.get("fleet"),
            "lease_frac": self._lease_frac,
            "streaks": {"up": up_streak, "down": down_streak},
            "cooldowns": {"up_remaining_s": round(up_rem, 3),
                          "down_remaining_s": round(down_rem, 3)},
            "last_decisions": decisions,
        }

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        olog.emit("autoscale", "start", mode=self.mode,
                  tick_s=self.tick_s, min_workers=self.min_workers,
                  max_workers=self.max_workers)
        return self

    def _loop(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # the control loop must outlive any tick
                self.metrics.inc("autoscale_sensor_errors")

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def attach(service, supervisor=None, mode=None, start=True, **kw):
    """Build + start an Autoscaler for `service` per DPT_AUTOSCALE.
    Mode "0" returns None WITHOUT constructing anything — off-mode
    bit-parity: no thread, no metrics, no log events; the tree is the
    pre-autoscaler tree. "dry" and "1" attach (service.autoscaler) and,
    with start=True, begin ticking."""
    m = mode_from_env() if mode is None else str(mode)
    if m == "0":
        return None
    asc = Autoscaler(service=service, supervisor=supervisor, mode=m, **kw)
    if service is not None:
        service.autoscaler = asc
    return asc.start() if start else asc
