"""Client for the proof service wire plane (scripts/loadgen.py, tests).

One framed TCP connection, strict request/reply, thread-safe (a lock
serializes frames, so concurrent submitters may share one client or open
one each). Raises ServiceError with the server's JSON reason on ERR."""

import threading
import time

from ..runtime import native, protocol


class ServiceError(Exception):
    def __init__(self, info):
        super().__init__(info.get("reason", "service error"))
        self.info = info


class ServiceClient:
    def __init__(self, host, port, timeout_ms=None):
        self.conn = native.connect(host, port)
        if timeout_ms is not None:
            self.conn.set_timeout(timeout_ms)
        self._lock = threading.Lock()

    def close(self):
        self.conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(self, tag, payload=b""):
        with self._lock:
            self.conn.send(tag, payload)
            rtag, rpayload = self.conn.recv()
        if rtag != protocol.OK:
            raise ServiceError(protocol.decode_json(rpayload))
        return rpayload

    def ping(self):
        self._call(protocol.PING)

    def submit(self, spec, trace_ctx=None):
        """spec: JSON-able job dict -> SUBMIT reply dict ({job_id, ...,
        trace_id}). trace_ctx (a trace.Tracer.context() dict) makes the
        server ADOPT the client's trace id instead of stamping a fresh
        one, so the job's merged timeline links back to the caller's
        span — one trace from the client through the last worker
        kernel."""
        if trace_ctx:
            spec = dict(spec, trace_ctx=trace_ctx)
        return protocol.decode_json(
            self._call(protocol.SUBMIT, protocol.encode_json(spec)))

    def status(self, job_id):
        return protocol.decode_json(
            self._call(protocol.STATUS,
                       protocol.encode_json({"job_id": job_id})))

    def result(self, job_id):
        """-> (header dict, proof bytes). Raises ServiceError (reason
        not_ready / failure) until the job is DONE."""
        return protocol.decode_result(
            self._call(protocol.RESULT,
                       protocol.encode_json({"job_id": job_id})))

    def warmup(self, spec, aot=False):
        """Pre-warm one shape bucket on the server (keys through the store
        tiers; aot=True also precompiles prover stages). Returns the
        server's summary dict ({source: memory|disk|built, ...})."""
        req = dict(spec)
        if aot:
            req["aot"] = True
        return protocol.decode_json(
            self._call(protocol.WARMUP, protocol.encode_json(req)))

    def metrics(self):
        return protocol.decode_json(self._call(protocol.METRICS))

    def store_fetch(self, key):
        """-> (header dict {key, digest, meta}, blob bytes) for one
        artifact-store entry on the server. Raises ServiceError on a
        miss. store.remote.fetch_into is the digest-verifying consumer;
        this raw accessor is for tooling/tests."""
        return protocol.decode_result(
            self._call(protocol.STORE_FETCH,
                       protocol.encode_json({"key": key})))

    def trace(self, job_id):
        """The job's merged distributed timeline (the trace:<job_id>
        store artifact) as a dict. Raises ServiceError when the server
        is storeless or the trace is gone; `serve.py --obs-port`'s
        /trace/<job_id> serves the same bytes over HTTP."""
        import json
        _hdr, blob = self.store_fetch(f"trace:{job_id}")
        return json.loads(blob.decode())

    def aggregate(self, job_ids):
        """Fold N DONE jobs into one batch-KZG aggregate on the server.
        Returns the AGGREGATE reply dict ({agg_id, members, kinds,
        digest, build_s}); raises ServiceError when any member is
        unknown or not DONE (the fold is all-or-nothing)."""
        return protocol.decode_json(
            self._call(protocol.AGGREGATE,
                       protocol.encode_json({"job_ids": list(job_ids)})))

    def fetch_aggregate(self, agg_id):
        """The built aggregate's canonical JSON artifact as a dict —
        exactly what aggregate.verify() consumes (one 2-pair pairing
        check for the whole batch). Raises ServiceError on a miss."""
        from .. import aggregate as AGG
        _hdr, blob = protocol.decode_result(
            self._call(protocol.AGG_FETCH,
                       protocol.encode_json({"agg_id": agg_id})))
        return AGG.from_bytes(blob)

    def kill_worker(self, worker=None, job_id=None, at_round=None):
        req = {}
        if worker is not None:
            req["worker"] = worker
        if job_id is not None:
            req["job_id"] = job_id
        if at_round is not None:
            req["at_round"] = at_round
        return protocol.decode_json(
            self._call(protocol.KILL_WORKER,
                       protocol.encode_json(req)))["worker"]

    def shutdown_server(self):
        self._call(protocol.SHUTDOWN)

    def wait(self, job_id, timeout_s=120, poll_s=0.05):
        """Poll STATUS until the job reaches a terminal state (done,
        failed, or a shed TTL verdict); returns the final status dict.
        Raises TimeoutError."""
        deadline = time.monotonic() + timeout_s
        while True:
            st = self.status(job_id)
            if st["state"] in ("done", "failed", "shed"):
                return st
            if time.monotonic() > deadline:
                raise TimeoutError(f"{job_id} still {st['state']}")
            time.sleep(poll_s)
