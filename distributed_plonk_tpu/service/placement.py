"""Placement-aware scheduling: one resource pool from 1 chip to a pod.

The three scale layers used to be siloed (ROADMAP direction 1): the pool
forked single-device prover workers, `parallel/` sharded ONE prove over a
mesh, and nothing composed them. This module is the composition point —
the shape-bucket scheduler's popped batches flow through a placement
decision instead of straight onto the pool:

  classify(domain_size)
      "batch"  small jobs (domain <= DPT_PLACE_SMALL_MAX, default 2^14):
               N same-shape jobs prove TOGETHER, data-parallel — one
               worker runs prover.prove_many, whose round-1/3/5 commit
               MSMs and round-4 evaluations launch as single batched
               kernels across jobs (the O(1)-trace fused MSM was built
               for exactly this). Per-job transcripts/blinding stay
               independent: proof bytes are identical to N sequential
               proves (the hard contract, pinned by
               tests/test_placement.py).
      "mesh"   large jobs (domain >= DPT_PLACE_LARGE_MIN, default 2^18):
               the prove SHARDS over a leased submesh via
               parallel.MeshBackend — latency scales in shards while the
               rest of the pool keeps serving.
      "pool"   everything between: per-job worker dispatch. Under
               DPT_PIPELINE (default on) the pool layer additionally
               ROUND-PIPELINES whatever lands on it: a worker that pops
               a dispatch unit coalesces queue neighbors (plain singles
               and batch groups, never leased-submesh units) up to
               DPT_PIPELINE_DEPTH jobs and proves them staggered through
               prover.prove_pipelined — so "batch" and "pool" traffic
               alike fill the round pipeline, with the same byte-identity
               contract (pool.py _run_pipeline, tests/test_pipeline.py).

  SubmeshLeaser
      partitions one device enumeration into disjoint leased submeshes.
      A big sharded prove leases k contiguous devices and releases them
      on completion; small batches take a 1-device lease OPPORTUNISTICALLY
      (non-blocking — on a fully-leased host they fall back to the shared
      default device, today's behavior, rather than queueing behind the
      big prove). That is what lets concurrent small batches and one big
      sharded prove coexist on one host.

Knobs:
  DPT_BATCH_PROVE=0        force the sequential per-job path everywhere
                           (byte-identity parity escape hatch)
  DPT_PLACE_SMALL_MAX      data-parallel ceiling (domain size, 2^14)
  DPT_PLACE_LARGE_MIN      sharded-prove floor (domain size, 2^18)
  DPT_MESH_LEASE           devices per big-job submesh (0 = auto: the
                           largest power of two <= half the pool, so one
                           flagship prove can never starve the rest)

Placement decisions land as counters (placement_batch/mesh/pool,
batch_jobs_per_launch, submesh_leases) and as span attrs on each job's
trace timeline (the pool stamps placement/batch size on the prove span).
"""

import os
import threading
import time

from .scheduler import Scheduler

# resolved per call (module attrs, monkeypatchable) like msm_jax's
# _BUCKET_UPDATE — tests and bench A/Bs flip them without re-importing
BATCH_PROVE = os.environ.get("DPT_BATCH_PROVE", "1") != "0"
SMALL_MAX = int(os.environ.get("DPT_PLACE_SMALL_MAX", str(1 << 14)))
LARGE_MIN = int(os.environ.get("DPT_PLACE_LARGE_MIN", str(1 << 18)))
MESH_LEASE = int(os.environ.get("DPT_MESH_LEASE", "0"))


def classify(domain_size):
    """Placement class for one shape bucket's evaluation-domain size."""
    if domain_size >= LARGE_MIN:
        return "mesh"
    if domain_size <= SMALL_MAX:
        return "batch"
    return "pool"


class SubmeshLease:
    """A granted, disjoint slice of the device pool. Release exactly
    once (the leaser tolerates double release defensively)."""

    __slots__ = ("devices", "_released")

    def __init__(self, devices):
        self.devices = tuple(devices)
        self._released = False

    def __len__(self):
        return len(self.devices)


class SubmeshLeaser:
    """Partition one device enumeration into disjoint leased runs.

    Devices are any hashable tokens (real jax Device objects in
    production, plain ints in tests — the leaser never touches device
    APIs). Contiguity: leases are CONTIGUOUS runs of the original
    enumeration order, because a sharded submesh wants ICI neighbors;
    the free list keeps original order so releases restore contiguity.
    """

    def __init__(self, devices):
        self._all = list(devices)
        self._index = {id(d): i for i, d in enumerate(self._all)}
        self._free = list(self._all)
        # capacity resize (the autoscaler's batch-vs-flagship actuator):
        # devices past the capacity are held in _reserved instead of the
        # free list. Shrinking NEVER revokes a granted lease — it only
        # withholds free devices; a release past capacity parks the
        # devices in _reserved until capacity grows again.
        self._capacity = len(self._all)
        self._reserved = []
        self._cond = threading.Condition()

    def total(self):
        return len(self._all)

    def capacity(self):
        with self._cond:
            return self._capacity

    def free_count(self):
        with self._cond:
            return len(self._free)

    def set_capacity(self, n):
        """Resize the leasable pool to n devices (clamped to [1, total]).
        Grow returns reserved devices to the free list immediately;
        shrink withholds FREE devices only (highest enumeration index
        first, preserving low-index contiguity for submesh runs) —
        granted leases are never revoked, the book just stops re-issuing
        their devices as they release. Returns the applied capacity."""
        with self._cond:
            n = max(1, min(int(n), len(self._all)))
            self._capacity = n
            self._rebalance_locked()
            self._cond.notify_all()
            return self._capacity

    def _rebalance_locked(self):
        """Move devices between _free and _reserved to honor _capacity.
        Outstanding (leased) devices count against capacity, so the
        invariant is: len(free) + len(reserved) + leased == total, with
        free allowed up to capacity - leased."""
        leased = len(self._all) - len(self._free) - len(self._reserved)
        allowed_free = max(0, self._capacity - leased)
        if len(self._free) > allowed_free:
            # withhold highest-index devices first: contiguous low-index
            # runs (what _grab_locked prefers) survive the shrink
            self._free.sort(key=lambda d: self._index[id(d)])
            while len(self._free) > allowed_free:
                self._reserved.append(self._free.pop())
        elif len(self._free) < allowed_free and self._reserved:
            self._reserved.sort(key=lambda d: self._index[id(d)])
            while len(self._free) < allowed_free and self._reserved:
                self._free.append(self._reserved.pop(0))

    def _grab_locked(self, k):
        """Best contiguous run of k free devices (by original index);
        falls back to any k free devices when fragmentation leaves no
        contiguous run (correctness never depends on contiguity)."""
        order = sorted(self._free, key=lambda d: self._index[id(d)])
        for s in range(len(order) - k + 1):
            run = order[s:s + k]
            idx = [self._index[id(d)] for d in run]
            if idx[-1] - idx[0] == k - 1:
                break
        else:
            run = order[:k]
        for d in run:
            self._free.remove(d)
        return SubmeshLease(run)

    def lease(self, k, timeout_s=None):
        """Lease k devices. timeout_s=None blocks until available;
        timeout_s=0 is the opportunistic probe (None when the pool
        cannot satisfy it right now). k is clamped to the pool size."""
        deadline = None
        with self._cond:
            k = max(1, min(k, self._capacity))
            while len(self._free) < k:
                if timeout_s is not None and timeout_s <= 0:
                    return None
                if timeout_s is not None:
                    if deadline is None:
                        deadline = time.monotonic() + timeout_s
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        if len(self._free) < k:
                            return None
                else:
                    self._cond.wait()
            return self._grab_locked(k)

    def release(self, lease):
        if lease is None:
            return
        with self._cond:
            if lease._released:
                return
            lease._released = True
            self._free.extend(lease.devices)
            # a release after a shrink may overfill the free list;
            # rebalance parks the excess in _reserved
            self._rebalance_locked()
            self._cond.notify_all()


def _default_devices():
    """The process's device enumeration, lazily (the service frontend
    must not import jax unless a placement actually needs devices)."""
    import jax
    return list(jax.devices())


def _default_mesh_backend_factory(devices):
    """Leased devices -> a MeshBackend sharding over exactly them."""
    from ..parallel.mesh import make_submesh
    from ..parallel.mesh_backend import MeshBackend
    return MeshBackend(make_submesh(devices))


class PlacementScheduler(Scheduler):
    """The placement layer: Scheduler whose `_place` routes each popped
    shape batch by size class instead of per-job pool dispatch.

    devices / mesh_backend_factory are injection points (tests lease
    fake device tokens and prove "mesh" jobs on a stub backend); by
    default devices enumerate lazily from jax.devices() on the first
    placement that needs a lease, and mesh backends shard over
    parallel.make_submesh of the leased devices. Mesh backends are
    cached per leased device tuple, so a repeat lease of the same slice
    reuses its compiled stages."""

    def __init__(self, queue, pool, metrics, buckets=None, max_batch=8,
                 devices=None, mesh_backend_factory=None):
        super().__init__(queue, pool, metrics, buckets=buckets,
                         max_batch=max_batch)
        self._devices = devices
        self._mesh_backend_factory = (mesh_backend_factory
                                      or _default_mesh_backend_factory)
        self._leaser = None
        self._leaser_lock = threading.Lock()
        self._mesh_backends = {}

    # -- resources -----------------------------------------------------------

    def leaser(self):
        with self._leaser_lock:
            if self._leaser is None:
                devs = self._devices
                if devs is None:
                    devs = _default_devices()
                self._leaser = SubmeshLeaser(devs)
            return self._leaser

    def _leaser_if_ready(self):
        """The leaser WITHOUT triggering device enumeration: batch
        placements only participate in lease bookkeeping once devices
        are known (injected, or a mesh placement enumerated them) — a
        small-jobs-only service never pays the jax import for a lease
        that would be pure bookkeeping."""
        with self._leaser_lock:
            if self._leaser is None and self._devices is not None:
                self._leaser = SubmeshLeaser(self._devices)
            return self._leaser

    def _mesh_lease_size(self):
        total = self.leaser().total()
        if MESH_LEASE > 0:
            return min(MESH_LEASE, total)
        if total <= 1:
            return 1
        # auto: largest power of two <= half the pool — one flagship
        # prove shards wide but can never starve the small-job classes
        return 1 << max(0, (total // 2).bit_length() - 1)

    # bound the per-device-subset backend cache: the leaser's
    # fragmentation fallback can mint many distinct subsets over a long
    # run, and each MeshBackend pins compiled executables + device key
    # contexts — an uncapped map is an HBM/host leak (same rationale as
    # JaxBackend._CACHE_CAP)
    _MESH_BACKEND_CAP = 4

    def _mesh_backend(self, lease):
        leaser = self.leaser()
        key = tuple(sorted(leaser._index[id(d)] for d in lease.devices))
        backend = self._mesh_backends.get(key)
        if backend is None:
            if len(self._mesh_backends) >= self._MESH_BACKEND_CAP:
                self._mesh_backends.pop(next(iter(self._mesh_backends)))
            backend = self._mesh_backends[key] = \
                self._mesh_backend_factory(list(lease.devices))
        return backend

    def _release_fn(self, leaser):
        """Release callback that keeps the submesh_devices_free gauge
        honest on BOTH edges (a grant-only gauge reads the low-water
        mark forever on an idle host)."""
        def release(lease):
            leaser.release(lease)
            self.metrics.gauge("submesh_devices_free", leaser.free_count())
        return release

    # -- the placement decision ----------------------------------------------

    def _place(self, batch, res):
        placement = classify(res.domain_size)
        if placement == "batch" and (not BATCH_PROVE or len(batch) < 2):
            placement = "pool"  # nothing to batch / parity knob forced
        self.metrics.inc(f"placement_{placement}")

        if placement == "mesh":
            # one sharded prove per job, each on its own leased submesh.
            # The lease blocks like pool dispatch does (backpressure):
            # devices free up when an earlier sharded prove finishes.
            leaser = self.leaser()
            for job in batch:
                lease = leaser.lease(self._mesh_lease_size())
                self.metrics.inc("submesh_leases")
                self.metrics.gauge("submesh_devices_free",
                                   leaser.free_count())
                job.placement = "mesh"
                try:
                    self.pool.dispatch_group(
                        [job], res, backend=self._mesh_backend(lease),
                        lease=lease, release=self._release_fn(leaser))
                except Exception as e:  # mesh-backend build/dispatch
                    leaser.release(lease)
                    self.metrics.inc("dispatch_errors")
                    job.finish_err(f"mesh dispatch failed: {e!r}")
            return

        if placement == "batch":
            # data-parallel cross-job prove on one worker. The device
            # lease is opportunistic: hold a chip when one is free (so
            # the leaser's book shows batches and big proves dividing
            # the host), but never queue small jobs behind a flagship
            # prove — a fully-leased host falls back to the shared
            # default device, which is exactly the pre-placement
            # behavior. A leaser only exists once devices are known
            # (injected or mesh-enumerated): lease bookkeeping never
            # costs a small-jobs-only service the device-API import.
            leaser = self._leaser_if_ready()
            lease = leaser.lease(1, timeout_s=0) if leaser else None
            if lease is not None:
                self.metrics.inc("submesh_leases")
                self.metrics.gauge("submesh_devices_free",
                                   leaser.free_count())
            for job in batch:
                job.placement = "batch"
            try:
                self.pool.dispatch_group(
                    batch, res, lease=lease,
                    release=self._release_fn(leaser) if leaser else None)
            except Exception as e:  # stamped jobs are OURS to terminate:
                # the scheduler's outer handler skips stamped jobs, so an
                # orphaned batch would hang queued forever
                if leaser is not None:
                    leaser.release(lease)
                self.metrics.inc("dispatch_errors")
                for job in batch:
                    job.finish_err(f"batch dispatch failed: {e!r}")
            return

        for job in batch:
            job.placement = "pool"
            self.pool.dispatch(job, res)
