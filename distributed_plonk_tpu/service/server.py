"""TCP frontend: SUBMIT/STATUS/RESULT/METRICS/WARMUP on the runtime wire plane.

Reuses runtime/native.py's framed transport and runtime/protocol.py's tag
space (the same plane the kernel workers speak), one thread per
connection like runtime/worker.py — so a deployment speaks ONE protocol
whether a frame carries an MSM or a proof job. Control payloads are JSON;
the RESULT reply carries the 944-byte proof_io layout after a JSON header.

`ProofService` is also directly embeddable (tests/test_service.py,
bench.py drive it in-process through `submit_local`/the client): the TCP
listener is just one more producer into the queue.

Durability (PR 7): with `journal_dir`, every job transition is journaled
write-ahead (service/journal.py) — a crashed/restarted frontend replays
the journal, resumes in-flight jobs from their store checkpoints, serves
finished jobs from content-addressed proof artifacts, dedups resubmitted
job_keys, sheds expired TTLs with a queryable verdict, and drains
gracefully on SIGTERM (scripts/serve.py). `crash()` is the in-process
SIGKILL analog the restart tests and bench canary use.
"""

import os
import threading
import time

from ..obs import log as olog
from ..runtime import native, protocol
from ..store import ArtifactStore, aot_warmup, remote
from . import jobs as J
from . import journal as JN
from .jobs import Job, JobSpec
from .metrics import Metrics
from .placement import PlacementScheduler
from .pool import WorkerPool
from .queue import JobQueue, Rejected
from .scheduler import BucketCache


class ProofService:
    def __init__(self, host="127.0.0.1", port=0, prover_workers=2,
                 queue_depth=64, max_batch=8, max_retries=2,
                 job_timeout_s=None, ckpt_dir=None, chaos=False,
                 backend_factory=None, verify_on_complete=False,
                 finished_retention=4096, allow_remote_shutdown=False,
                 store_dir=None, store_byte_budget=None, bucket_cap=64,
                 store_peers=None, faults=None, journal_dir=None,
                 devices=None, mesh_backend_factory=None,
                 self_verify=None, verify_remote=False):
        self.host = host
        self.port = port
        self.chaos = chaos
        self.allow_remote_shutdown = allow_remote_shutdown
        self.metrics = Metrics()
        self.queue = JobQueue(max_depth=queue_depth)
        self.store = None
        if store_dir is not None:
            # NOTE: the service does not repoint the JAX compile cache —
            # an embedded ProofService (tests, bench) must not hijack its
            # host process's cache config. Daemon entry points that OWN
            # their process call store.set_jax_cache_env themselves
            # (scripts/serve.py) so compiled stages warm-start alongside
            # the keys they serve.
            self.store = ArtifactStore(store_dir,
                                       byte_budget=store_byte_budget,
                                       metrics=self.metrics.scoped("store"))
        # faults: runtime.faults.FaultInjector (chaos mode only) — the
        # pool runs its checkpoint-plane rules at round boundaries and
        # the journal its journal-plane rules after each append. An
        # injector built without a metrics registry adopts ours, so its
        # faults_injected_*/faults_ckpt_corrupted counters show up in the
        # same METRICS snapshot as the recovery counters they provoke.
        self.faults = faults if chaos else None
        if self.faults is not None and self.faults.metrics is None:
            self.faults.metrics = self.metrics
        # journal: the crash-safety spine (service/journal.py). Replays
        # on open; `start()` then recovers every journaled job — queued
        # and in-flight ones resume from their checkpoints, finished ones
        # serve from their proof artifacts. Without a journal_dir the
        # service keeps the PR-1 in-memory-only behavior.
        self.journal = None
        if journal_dir is not None:
            self.journal = JN.JobJournal(journal_dir, metrics=self.metrics,
                                         retain_terminal=finished_retention,
                                         chaos=self.faults)
        self.pool = WorkerPool(
            self.metrics, prover_workers=prover_workers,
            max_retries=max_retries, job_timeout_s=job_timeout_s,
            ckpt_dir=ckpt_dir, backend_factory=backend_factory,
            verify_on_complete=verify_on_complete, store=self.store,
            faults=self.faults, journal=self.journal,
            requeue=self.queue, self_verify=self_verify,
            verify_remote=verify_remote)
        # store_peers: [(host, port)] of peers speaking STORE_FETCH — a
        # bucket miss tries a network copy from a warm peer before paying
        # for a full key build (elastic scale-out: a fresh host serves
        # warm after one fetch)
        self.buckets = BucketCache(self.metrics, store=self.store,
                                   max_entries=bucket_cap,
                                   peers=store_peers)
        # placement-aware scheduling (service/placement.py): small shape
        # buckets prove data-parallel (cross-job batched kernel launches,
        # byte-identical to sequential), large ones shard over a leased
        # submesh, mid sizes keep the per-job pool path. devices /
        # mesh_backend_factory are test injection points; production
        # enumerates jax.devices() lazily on the first lease.
        self.scheduler = PlacementScheduler(
            self.queue, self.pool, self.metrics, buckets=self.buckets,
            max_batch=max_batch, devices=devices,
            mesh_backend_factory=mesh_backend_factory)
        # kernel-calibration pickup report (store/calibration.py), filled
        # by start(): {"source": off|none|store|fresh, ...}. Without a
        # store (or DPT_AUTOTUNE=off) no plan is loaded and every kernel
        # path keeps the built-in defaults.
        self.autotune = {"source": "off"}
        # fleet observability (obs/fleet.py): attach_fleet() arms it —
        # the scraper aggregates every roster member's METRICS_FETCH
        # snapshot into dpt_fleet_* series on /metrics and feeds the
        # /fleet endpoint; profile captures land under profile:<id>
        self.fleet = None
        self.fleet_dispatcher = None
        # closed-loop autoscaler (service/autoscale.py): attach_autoscaler
        # arms it per DPT_AUTOSCALE; None is the off-mode bit-parity state
        self.autoscaler = None
        self._profiles = {}  # storeless fallback: id -> (meta, blob)
        # built aggregate artifacts (ISSUE 17): storeless fallback table
        # agg_id -> JSON blob bytes, restored from the journal's AGG
        # records at recovery; store-backed services serve from
        # aggregate:<agg_id> instead. Bounded like the journal's memory
        # of terminal jobs — refolding N DONE jobs is always possible.
        self._aggregates = {}
        self._aggregates_cap = max(64, finished_retention // 4)
        # shape_key -> vk cache for aggregate self-verification (usually
        # satisfied straight from the bucket cache, see aggregate_jobs)
        self._agg_vk_cache = {}
        # structured logs (obs/log.py) publish their counters into this
        # registry (per-process buffer; last-constructed service wins,
        # which is the daemon case that matters)
        olog.set_metrics(self.metrics)
        self._warm_backend = None
        self._warm_backend_lock = threading.Lock()
        self.jobs = {}
        self._job_keys = {}   # idempotency: job_key -> job_id (journaled)
        self.finished_retention = finished_retention
        self._jobs_lock = threading.Lock()
        # serializes the whole admission sequence (dedup check -> journal
        # SUBMIT -> queue insert), so a concurrent duplicate can never
        # dedup onto a job that is still mid-admission (and might yet be
        # rejected and rolled back, or not yet journaled — its positive
        # ack must imply the write-ahead record exists). Distinct from
        # _jobs_lock so STATUS lookups never wait behind an fsync.
        self._submit_lock = threading.Lock()
        self._listener = None
        self._stopped = threading.Event()

    def attach_membership(self, registry):
        """Auto-discover the fleet's store-serving members as bucket-
        cache peers (runtime/membership.py): every current store member
        is registered now, and every future JOIN that advertises a store
        is registered as it lands — a scaled-out service never needs a
        hand-maintained --store-peers list (ROADMAP direction-2
        remainder)."""
        def _on_change(ev):
            if ev.get("event") == "join" and ev.get("store"):
                self.buckets.add_peer(ev["host"], ev["port"])
            elif ev.get("event") == "leave" and "host" in ev:
                # a decommissioned member must stop costing a peer-fetch
                # timeout on every later cold miss
                self.buckets.remove_peer(ev["host"], ev["port"])
        registry.subscribe(_on_change)
        for host, port in registry.store_peers():
            self.buckets.add_peer(host, port)
        return self

    def attach_fleet(self, dispatcher, interval_s=None, start=True):
        """Arm the fleet observability plane (obs/fleet.py) for a
        service whose backend proves on a worker fleet: an interval
        scraper pulls every roster member's METRICS_FETCH snapshot,
        folds fleet aggregates into this registry, and keeps the latest
        per-worker snapshots for ObsServer's /metrics (labelled
        dpt_fleet_* series) and /fleet endpoints; profile_fleet_worker
        becomes available. Membership-driven by construction: the
        scraper walks the dispatcher's CURRENT worker list each cycle,
        so joins/leaves show up at the next scrape."""
        from ..obs.fleet import FleetScraper
        self.fleet_dispatcher = dispatcher
        self.fleet = FleetScraper(dispatcher, self.metrics,
                                  interval_s=interval_s)
        if start:
            self.fleet.start()
        return self

    def attach_autoscaler(self, supervisor=None, mode=None, **kw):
        """Arm the closed-loop autoscaler (service/autoscale.py) per
        DPT_AUTOSCALE: "0" (the default) attaches NOTHING and returns
        None — bit-parity with the pre-autoscaler tree; "dry" runs the
        control loop and logs/counts decisions without one actuator
        call; "1" actuates (supervisor add_slot / retire_slot, submesh
        lease resize, pressure sheds). Pass the WorkerSupervisor that
        owns the fleet's worker processes to enable worker scaling;
        without one the controller still resizes leases and sheds."""
        from . import autoscale as AS
        return AS.attach(self, supervisor=supervisor, mode=mode, **kw)

    def profile_fleet_worker(self, worker=0, duration_ms=None,
                             kind="auto"):
        """On-demand device/host profile of one fleet worker (PROFILE
        wire tag): the capture lands as a content-addressed
        profile:<id> artifact (store-backed when the service has one,
        else a small in-memory table) served at /profile/<id>. Returns
        {"profile_id", "format", "bytes", ...}. Raises RuntimeError
        without an attached fleet."""
        if self.fleet_dispatcher is None:
            raise RuntimeError("no fleet attached (attach_fleet)")
        from ..obs import profiling
        meta, blob = self.fleet_dispatcher.profile_worker(
            worker, duration_ms=duration_ms, kind=kind)
        if not blob:
            self.metrics.inc("profile_errors")
            return dict(meta, profile_id=None)
        pid = profiling.profile_id(blob)
        meta = dict(meta, profile_id=pid)
        if self.store is not None:
            from ..store import keycache as KC
            KC.store_profile(self.store, pid, blob, meta)
        else:
            self._profiles[pid] = (meta, blob)
            while len(self._profiles) > 8:  # bounded fallback table
                self._profiles.pop(next(iter(self._profiles)))
        self.metrics.inc("profiles_stored")
        olog.emit("obs", "profile_stored", worker=worker,
                  profile_id=pid, format=meta.get("format"))
        return meta

    def load_profile(self, profile_id):
        """(meta, blob) for one stored capture, or None."""
        if self.store is not None:
            from ..store import keycache as KC
            hit = KC.load_profile(self.store, profile_id)
            if hit is not None:
                return hit
        return self._profiles.get(profile_id)

    # -- batch-KZG proof aggregation (aggregate.py, ISSUE 17) ------------------

    def aggregate_jobs(self, job_ids):
        """Fold N DONE jobs' proofs into one batch-KZG aggregate artifact
        (the AGGREGATE wire tag's local implementation).

        All-or-nothing by design: any unknown or non-DONE member raises
        (LookupError / ValueError with the offending job id) — a partial
        aggregate would silently weaken the client's "everything in this
        batch verified" claim. The built artifact is self-verified (ONE
        2-pair pairing check, vks served from the bucket cache the
        members were just proved with), journaled as an AGG record, and
        persisted as aggregate:<agg_id> (store) or in the in-memory
        fallback table. Returns the AGGREGATE reply dict.
        """
        from .. import aggregate as AGG
        if not isinstance(job_ids, list) or not job_ids \
                or not all(isinstance(j, str) for j in job_ids):
            raise ValueError("job_ids must be a non-empty list of ids")
        members, kinds = [], []
        for jid in job_ids:
            job = self.get_job(jid)
            if job is None:
                raise LookupError(f"unknown job {jid!r}")
            if job.state != J.DONE or job.proof_bytes is None:
                raise ValueError(
                    f"job {jid} not aggregatable (state={job.state})")
            members.append({"job_id": job.id, "spec": job.spec.to_wire(),
                            "pub": job.public_input,
                            "proof": job.proof_bytes})
            kinds.append(job.spec.kind)
        t0 = time.monotonic()
        agg = AGG.build(members)
        blob = AGG.to_bytes(agg)
        agg_id = agg["agg_id"]
        # self-verify before anything durable: the pool already verified
        # every member, so this pins the FOLD itself (and the vk cache is
        # warm — the bucket cache just proved these shapes)
        for jid in job_ids:
            job = self.get_job(jid)
            key = job.shape_key
            if key not in self._agg_vk_cache:
                self._agg_vk_cache[key] = self.buckets.get(job.spec).vk
        t_v = time.monotonic()
        if not AGG.verify(agg, self._agg_vk_cache):
            self.metrics.inc("aggregate_verify_failures")
            raise ValueError("aggregate self-verification failed")
        self.metrics.observe("aggregate_verify_s", time.monotonic() - t_v)
        rec = {"members": list(job_ids), "ts": time.time()}
        digest = None
        if self.store is not None:
            from ..store import keycache as KC
            digest = KC.store_aggregate(self.store, agg_id, blob,
                                        job_ids, kinds=kinds)
            rec["store_key"] = KC.aggregate_store_key(agg_id)
            rec["digest"] = digest
        else:
            rec["agg_hex"] = blob.hex()
        self._stash_aggregate(agg_id, blob)
        # journal writers serialize on _submit_lock (same discipline as
        # the SUBMIT write-ahead append)
        if self.journal is not None:
            with self._submit_lock:
                self.journal.append(JN.AGG, agg_id, **rec)
        build_s = time.monotonic() - t0
        self.metrics.inc("aggregates_built")
        self.metrics.inc("aggregate_members", len(members))
        olog.emit("aggregate", "built", agg_id=agg_id,
                  members=len(members), kinds=sorted(set(kinds)),
                  build_s=round(build_s, 6))
        return {"agg_id": agg_id, "members": list(job_ids),
                "kinds": sorted(set(kinds)), "digest": digest,
                "build_s": round(build_s, 6)}

    def _stash_aggregate(self, agg_id, blob):
        self._aggregates[agg_id] = blob
        while len(self._aggregates) > self._aggregates_cap:
            self._aggregates.pop(next(iter(self._aggregates)))

    def load_aggregate_blob(self, agg_id):
        """Canonical JSON blob of one built aggregate, or None."""
        if self.store is not None:
            from ..store import keycache as KC
            hit = KC.load_aggregate(self.store, agg_id)
            if hit is not None:
                return hit[0]
        return self._aggregates.get(agg_id)

    # -- local (in-process) API ----------------------------------------------

    def submit_local(self, spec_obj):
        """Validate + admit one job; returns the Job. Raises ValueError
        (bad spec) or Rejected (admission control)."""
        return self.submit_ex(spec_obj)[0]

    def submit_ex(self, spec_obj):
        """(job, deduped): like submit_local, but reports whether the
        spec's job_key matched an existing job (idempotent submission —
        the duplicate gets the ORIGINAL job, which may already be done
        and served from its finished-proof artifact, even across a
        service restart)."""
        spec = JobSpec.from_wire(spec_obj)
        job = Job(spec)
        # distributed tracing: adopt the client's trace context when the
        # SUBMIT payload carries one (trace_ctx rides beside the spec
        # fields; it changes nothing about the circuit), else the fresh
        # id Job() stamped stands — either way every job has exactly one
        # trace id from admission to the last worker kernel
        ctx = spec_obj.get("trace_ctx") if isinstance(spec_obj, dict) \
            else None
        if isinstance(ctx, dict):
            tid = ctx.get("trace_id")
            if isinstance(tid, str) and tid:
                job.trace_id = tid
            parent = ctx.get("parent_id")
            if isinstance(parent, str) and parent:
                job.trace_parent = parent
        with self._submit_lock:
            with self._jobs_lock:
                if spec.job_key is not None:
                    existing = self.jobs.get(
                        self._job_keys.get(spec.job_key))
                    if existing is not None:
                        self.metrics.inc("dedup_hits")
                        return existing, True
                    self._job_keys[spec.job_key] = job.id
                self._register_locked(job)
            self.metrics.inc("jobs_submitted")
            # write-ahead: journal the admission BEFORE the in-memory
            # queue sees it — a crash on the next line recovers the job;
            # the reverse order would ack a job a restart has never
            # heard of
            if self.journal is not None:
                self.journal.append(JN.SUBMIT, job.id, spec=spec.to_wire(),
                                    key=spec.job_key,
                                    deadline=job.deadline_ts,
                                    trace=job.trace_id,
                                    trace_parent=job.trace_parent,
                                    ts=time.time())
            try:
                self.queue.submit(job)
            except Rejected as e:
                # shed-lowest-class-first admission: a FULL queue refusing
                # a higher-SLO-class job first tries to evict the worst
                # queued job of a strictly lower class (journaled SHED)
                # and admit the newcomer in its place. An all-standard
                # stream can never preempt (no lower rank exists), so the
                # classless path keeps the historical plain rejection.
                if e.reason == "queue_full":
                    victim = self.queue.steal_lowest(job.slo_rank)
                    if victim is not None:
                        self.metrics.inc("slo_preempt_sheds")
                        self.pool.shed(
                            victim,
                            f"preempted by {job.slo}-class admission")
                        # force: we hold _submit_lock, and the victim's
                        # slot was freed this instant — bouncing on a
                        # racing scheduler pop would lose the preemption
                        self.queue.submit(job, force=True)
                        self.metrics.inc("jobs_accepted")
                        self.metrics.gauge("queue_depth",
                                           self.queue.depth())
                        return job, False
                self.metrics.inc("jobs_rejected")
                if self.journal is not None:
                    # terminal verdict so replay never resurrects a job
                    # the client was told was refused
                    self.journal.append(JN.SHED, job.id,
                                        reason=JN.REJECTED_PREFIX + e.reason)
                with self._jobs_lock:
                    self.jobs.pop(job.id, None)
                    if spec.job_key is not None \
                            and self._job_keys.get(spec.job_key) == job.id:
                        del self._job_keys[spec.job_key]
                raise
        self.metrics.inc("jobs_accepted")
        self.metrics.gauge("queue_depth", self.queue.depth())
        return job, False

    def _register_locked(self, job):
        """Insert into the job table (caller holds _jobs_lock) and bound
        it: evict the oldest FINISHED jobs (dict preserves insertion
        order) once past the retention cap — live jobs are never evicted,
        and admission control already bounds how many can be live."""
        self.jobs[job.id] = job
        excess = len(self.jobs) - self.finished_retention
        if excess > 0:
            # oldest-first (dict insertion order), stop as soon as the
            # excess is covered — finished jobs cluster at the front,
            # so this stays O(excess + live prefix), not O(table)
            evict = []
            for jid, j in self.jobs.items():
                if len(evict) >= excess:
                    break
                if j.state in J.TERMINAL:
                    evict.append(jid)
            for jid in evict:
                j = self.jobs.pop(jid)
                if j.job_key is not None \
                        and self._job_keys.get(j.job_key) == jid:
                    del self._job_keys[j.job_key]
            if evict:
                self.metrics.inc("jobs_evicted", len(evict))

    def get_job(self, job_id):
        with self._jobs_lock:
            return self.jobs.get(job_id)

    def warmup_local(self, spec_obj, aot=False):
        """Pre-resolve one shape bucket through the cache tiers (memory ->
        store -> build; a build lands in the store) and, with aot=True,
        precompile its prover stages on a pool-equivalent backend. Returns
        the summary the WARMUP tag replies with. Raises ValueError on a
        bad spec."""
        spec = JobSpec.from_wire(spec_obj)
        self.metrics.inc("warmups")
        t0 = time.monotonic()
        res, source = self.buckets.get_with_source(spec)
        out = {
            "shape_key": [str(p) for p in res.shape_key],
            "source": source,
            "domain_size": res.domain_size,
            "build_s": round(res.build_s, 6),
            "warm_s": round(time.monotonic() - t0, 6),
        }
        if aot:
            # same factory the pool workers use, so what we compile is
            # what they run; one shared instance — stage compiles are
            # cached process-wide (NTT plans) / on disk (persistent cache)
            with self._warm_backend_lock:
                if self._warm_backend is None:
                    self._warm_backend = self.pool.backend_factory()
                backend = self._warm_backend
            out["aot"] = aot_warmup(backend, res.domain_size, ck=res.pk.ck)
        return out

    # -- restart recovery -----------------------------------------------------

    def _recover(self):
        """Rebuild queue + job table from the replayed journal (runs in
        start(), before the scheduler/listener). Non-terminal jobs are
        re-enqueued under their ORIGINAL ids — their `ckpt:<id>` round
        snapshots still match, so the prove resumes at the last journaled
        round boundary with zero recompute. DONE jobs are restored from
        their finished-proof artifacts (no re-prove; a lost artifact
        degrades to a re-prove of the same deterministic bytes). SHED and
        FAILED verdicts stay queryable."""
        if self.journal is None:
            return
        recovered = finished = aggregates = 0
        for jid, st in list(self.journal.state.items()):
            if st.get("phase") == "aggregate":
                # AGG records carry no job spec: restore the artifact's
                # serving path (store or fallback table) and move on
                if self._restore_aggregate(jid, st):
                    aggregates += 1
                continue
            try:
                spec = JobSpec.from_wire(st.get("spec"))
            except (ValueError, TypeError):
                # unparseable SUBMIT payload (foreign/ancient journal):
                # skip the record, never refuse to start
                continue
            job = Job(spec, job_id=jid)
            # the deadline is the ORIGINAL submission's, not re-derived
            # from recovery time — a restart must not extend any TTL
            job.deadline_ts = st.get("deadline")
            # ...and so is the trace identity: the SUBMIT reply already
            # told the client this id; re-stamping would orphan the
            # client's spans from the recovered job's timeline
            if st.get("trace"):
                job.trace_id = st["trace"]
                job.trace_parent = st.get("trace_parent")
            phase = st["phase"]
            if phase == "done" and self._restore_done(job, st):
                finished += 1
            elif phase == "shed":
                job.finish_shed(st.get("reason") or "shed")
            elif phase == "failed":
                job.finish_err(st.get("reason") or "failed")
            elif job.expired():
                # deadline lapsed during the outage: verdict, not work.
                # (JobJournal serializes internally; _recover runs before
                # the scheduler/listener threads exist, so the submit
                # lock is not needed here)
                self.journal.append(JN.SHED, job.id,  # analysis: ok(journal has its own lock; single-threaded recovery)
                                    reason="ttl expired during restart")
                self.metrics.inc("jobs_shed")
                job.finish_shed("ttl expired during restart")
            else:
                # queued or mid-prove at crash time (a DONE job whose
                # artifact was lost also lands here): back in the queue,
                # bypassing the depth cap — the PREVIOUS process already
                # admitted it
                self.queue.submit(job, force=True)
                recovered += 1
            # rejected submissions keep their queryable verdict but do
            # NOT reclaim the job_key: the live path frees the key on
            # rejection so a retry is a fresh admission attempt, and a
            # restart must not change that (review finding)
            rejected = (phase == "shed" and (st.get("reason") or "")
                        .startswith(JN.REJECTED_PREFIX))
            with self._jobs_lock:
                if job.job_key is not None and not rejected:
                    self._job_keys[job.job_key] = job.id
                self._register_locked(job)
        if recovered:
            self.metrics.inc("jobs_recovered", recovered)
        if finished:
            self.metrics.inc("jobs_recovered_finished", finished)
        if aggregates:
            self.metrics.inc("aggregates_recovered", aggregates)
        self.metrics.gauge("queue_depth", self.queue.depth())
        # replay + recovery is the natural compaction point: the rewritten
        # log starts this process's epoch at its minimal size
        self.journal.compact()

    def _restore_aggregate(self, agg_id, st):
        """Re-arm serving one journaled aggregate after a restart: the
        inline blob goes back into the fallback table; a store-backed
        record just needs the artifact to still be present. False means
        the artifact is gone (evicted/corrupt) — clients refold from the
        member proofs, nothing crashes."""
        rec = st.get("done") or {}
        if rec.get("agg_hex"):
            try:
                self._stash_aggregate(agg_id, bytes.fromhex(rec["agg_hex"]))
            except ValueError:
                self.metrics.inc("aggregate_artifacts_lost")
                return False
            return True
        if self.store is not None and rec.get("store_key"):
            from ..store import keycache as KC
            hit = KC.load_aggregate(self.store, agg_id)
            if hit is not None:
                self._stash_aggregate(agg_id, hit[0])
                return True
        self.metrics.inc("aggregate_artifacts_lost")
        return False

    def _restore_done(self, job, st):
        """Restore a finished job from its DONE record: proof bytes come
        from the store artifact (or the record's inline fallback). False
        means the artifact is gone (evicted/corrupt) — caller re-proves."""
        rec = st.get("done") or {}
        proof_bytes = pub = None
        if rec.get("proof_hex"):
            proof_bytes = bytes.fromhex(rec["proof_hex"])
            pub = [int(x, 16) for x in rec.get("pub") or []]
        elif self.store is not None and rec.get("store_key"):
            from ..store import keycache as KC
            hit = KC.load_proof(self.store, job.id)
            if hit is not None:
                proof_bytes, pub, _meta = hit
                if not pub:
                    pub = [int(x, 16) for x in rec.get("pub") or []]
        if proof_bytes is None:
            self.metrics.inc("proof_artifacts_lost")
            return False
        job.retries = int(rec.get("retries") or 0)
        job.finish_ok(proof_bytes, pub, {})
        return True

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        """Start scheduler + listener threads; returns self. With port=0
        an ephemeral port is chosen and published as `self.port`.

        Kernel-calibration pickup runs FIRST (store/calibration.py,
        DPT_AUTOTUNE=load|run|off): a calibrated store's plan is adopted
        before any job can trace a kernel, so a second service start
        reaches its first proof with zero measurement runs and zero
        kernel compiles at the calibrated shapes (the plan pins the
        dispatch, the store-synced persistent compile cache holds the
        winners' executables)."""
        if self.store is not None:
            from ..store import calibration
            try:
                self.autotune = calibration.load_or_run(
                    self.store, metrics=self.metrics)
            except Exception as e:  # noqa: BLE001 - calibration is an
                # accelerator: a broken plan/measure pass must never
                # stop the service from serving with defaults
                self.autotune = {"source": "error", "error": repr(e)}
        self._recover()
        self.scheduler.start()
        self._listener = native.Listener(self.host, self.port)
        if self.port == 0:
            import socket
            s = socket.socket(fileno=os.dup(self._listener.fd))
            try:
                self.port = s.getsockname()[1]
            finally:
                s.close()
        threading.Thread(target=self._accept_loop, name="proof-accept",
                         daemon=True).start()
        return self

    def _accept_loop(self):
        while True:
            conn = self._listener.accept()
            if conn.fd < 0:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def serve_forever(self, poll_s=0.5):
        # bounded waits so the MAIN thread regularly re-enters the
        # interpreter: POSIX signal handlers (scripts/serve.py's
        # SIGTERM graceful drain) only run between bytecodes, and an
        # unbounded Event.wait can starve them on some platforms
        while not self._stopped.wait(poll_s):
            pass

    def shutdown(self):
        self.scheduler.stop()
        self.pool.shutdown()
        if self.autoscaler is not None:
            self.autoscaler.close()
        if self.fleet is not None:
            self.fleet.close()
        if self._listener is not None:
            self._listener.close()
        if self.journal is not None:
            self.journal.close()
        self._stopped.set()

    def drain(self, timeout_s=30.0):
        """Graceful drain (the SIGTERM path, scripts/serve.py): stop
        admission immediately, let in-flight jobs finish until the
        deadline, then force the stragglers to stop at their next round
        boundary (snapshot durable, journal consistent), flush + close
        the journal, and release serve_forever. Returns True iff nothing
        needed the forced stop. Queued-but-unstarted jobs stay journaled
        and resume on the next start — a drain defers work, it never
        loses it."""
        self.metrics.inc("drain_started")
        deadline = time.monotonic() + timeout_s
        self.queue.close()       # admission now rejects with "draining"
        self.scheduler.stop()
        clean = self.pool.drain(deadline)
        self.metrics.inc("drain_clean" if clean else "drain_forced")
        olog.emit("service", "drain", clean=bool(clean))
        if self.autoscaler is not None:
            self.autoscaler.close()
        if self.fleet is not None:
            self.fleet.close()
        if self._listener is not None:
            self._listener.close()
        if self.journal is not None:
            self.journal.close()
        self._stopped.set()
        return clean

    def crash(self):
        """In-process analog of SIGKILL (tests, bench restart canary):
        seal the journal (nothing more reaches disk — exactly what a
        dead process writes), stop admission, and abandon the worker
        threads at their next round boundary WITHOUT any of shutdown's
        bookkeeping (no checkpoint clears, no terminal records, no journal
        flush). What the journal + store hold at this instant is what a
        restarted service gets."""
        if self.journal is not None:
            self.journal.seal()
        self.queue.close()
        self.scheduler.crash()
        self.pool.crash()
        if self.autoscaler is not None:
            self.autoscaler.close()
        if self.fleet is not None:
            self.fleet.close()
        if self._listener is not None:
            self._listener.close()
        self._stopped.set()

    # -- wire handling --------------------------------------------------------

    def _serve_conn(self, conn):
        try:
            while True:
                try:
                    tag, payload = conn.recv()
                except ConnectionError:
                    return
                try:
                    cont = self._dispatch(conn, tag, payload)
                except Exception as e:
                    try:
                        conn.send(protocol.ERR,
                                  protocol.encode_json({"reason": repr(e)}))
                    except ConnectionError:
                        return
                    continue
                if cont is False:
                    self.shutdown()
                    return
        finally:
            conn.close()

    def _dispatch(self, conn, tag, payload):
        if tag == protocol.PING:
            conn.send(protocol.OK)
        elif tag == protocol.SUBMIT:
            try:
                job, deduped = self.submit_ex(protocol.decode_json(payload))
            except ValueError as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": f"bad_spec: {e}"}))
                return None
            except Rejected as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": e.reason,
                     "queue_depth": self.queue.depth(),
                     "max_depth": self.queue.max_depth}))
                return None
            conn.send(protocol.OK, protocol.encode_json(
                {"job_id": job.id,
                 "shape_key": [str(p) for p in job.shape_key],
                 # idempotency: a duplicate job_key lands on the ORIGINAL
                 # job (possibly already done — across restarts too);
                 # "state" lets the client skip straight to RESULT
                 "dedup": deduped,
                 "state": job.state,
                 "trace_id": job.trace_id,
                 "queue_depth": self.queue.depth()}))
        elif tag == protocol.STATUS:
            job = self._lookup(conn, payload)
            if job is not None:
                conn.send(protocol.OK, protocol.encode_json(job.status()))
        elif tag == protocol.RESULT:
            job = self._lookup(conn, payload)
            if job is None:
                return None
            if job.proof_bytes is None:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "not_ready", "state": job.state,
                     "error": job.error}))
                return None
            header = {"job_id": job.id,
                      "public_input": [hex(x) for x in job.public_input],
                      "spec": job.spec.to_wire(),
                      "trace_id": job.trace_id,
                      "retries": job.retries}
            conn.send(protocol.OK,
                      protocol.encode_result(header, job.proof_bytes))
        elif tag == protocol.WARMUP:
            req = protocol.decode_json(payload)
            aot = bool(req.pop("aot", False))
            try:
                out = self.warmup_local(req, aot=aot)
            except ValueError as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": f"bad_spec: {e}"}))
                return None
            conn.send(protocol.OK, protocol.encode_json(out))
        elif tag == protocol.AGGREGATE:
            req = protocol.decode_json(payload)
            try:
                out = self.aggregate_jobs(req.get("job_ids"))
            except (ValueError, LookupError) as e:
                conn.send(protocol.ERR,
                          protocol.encode_json({"reason": str(e)}))
                return None
            conn.send(protocol.OK, protocol.encode_json(out))
        elif tag == protocol.AGG_FETCH:
            agg_id = protocol.decode_json(payload).get("agg_id")
            blob = self.load_aggregate_blob(agg_id) \
                if isinstance(agg_id, str) else None
            if blob is None:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": f"no aggregate {agg_id!r}"}))
                return None
            conn.send(protocol.OK, protocol.encode_result(
                {"agg_id": agg_id, "bytes": len(blob)}, blob))
        elif tag == protocol.STORE_FETCH:
            # serve one artifact blob to a peer/replacement host: bucket
            # keys, prover checkpoints, anything under the store —
            # cross-host warm start and resume become a digest-verified
            # network copy (store/remote.py holds both wire sides)
            remote.serve_fetch(
                self.store, payload, conn, metrics=self.metrics,
                no_store_reason="no store on this server (serve --store-dir)")
        elif tag == protocol.STORE_LIST:
            # enumerate what STORE_FETCH can serve (manifest keys +
            # jaxcache:<rel> compile-cache pseudo-keys): a joining
            # worker's warm rejoin asks this first
            remote.serve_list(
                self.store, payload, conn, metrics=self.metrics,
                no_store_reason="no store on this server (serve --store-dir)")
        elif tag == protocol.METRICS:
            snap = self.metrics.snapshot()
            snap["gauges"]["queue_depth"] = self.queue.depth()
            snap["gauges"]["queue_high_water"] = self.queue.high_water
            conn.send(protocol.OK, protocol.encode_json(snap))
        elif tag == protocol.KILL_WORKER:
            if not self.chaos:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "fault injection disabled (serve --chaos)"}))
                return None
            req = protocol.decode_json(payload)
            try:
                victim = self.pool.kill_worker(
                    worker=req.get("worker"), job_id=req.get("job_id"),
                    at_round=req.get("at_round"))
            except LookupError as e:
                conn.send(protocol.ERR,
                          protocol.encode_json({"reason": str(e)}))
                return None
            conn.send(protocol.OK, protocol.encode_json({"worker": victim}))
        elif tag == protocol.SHUTDOWN:
            # a multi-client daemon must not die to any one client's frame;
            # opt in (self-hosted loadgen, tests) or stop it from the host
            if not self.allow_remote_shutdown:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "remote shutdown disabled "
                               "(serve --allow-remote-shutdown)"}))
                return None
            conn.send(protocol.OK)
            return False
        else:
            conn.send(protocol.ERR,
                      protocol.encode_json({"reason": "unknown tag"}))
        return None

    def _lookup(self, conn, payload):
        job_id = protocol.decode_json(payload).get("job_id")
        job = self.get_job(job_id)
        if job is None:
            conn.send(protocol.ERR, protocol.encode_json(
                {"reason": f"unknown job {job_id!r}"}))
        return job

    # -- observability plane (serve.py --obs-port) -----------------------------

    def merge_fleet_trace(self, job_id):
        """Splice the attached fleet's distributed timeline into one
        job's trace artifact: the service-side merged dump (pool spans +
        service log events) plus Dispatcher.collect_trace() (dispatcher
        and worker spans, dispatcher/membership/supervisor/worker log
        events, offset-corrected) become ONE trace:<job_id> artifact —
        the "one artifact per incident" surface. Worker span buffers are
        fetch-and-forget and dispatcher-tracer-scoped, so call this
        right after the job of interest finishes (the normal use: an
        incident-bearing prove). Returns the merged dump (or None
        without an attached fleet)."""
        if self.fleet_dispatcher is None:
            return None
        from ..trace import merge_traces
        job = self.get_job(job_id)
        base = job.trace_dump if job is not None else None
        fleet = self.fleet_dispatcher.collect_trace()
        dumps = [d for d in (base, fleet) if d]
        if not dumps:
            return None
        merged = merge_traces(dumps)
        merged["logs"] = sorted(
            ((base or {}).get("logs") or [])
            + ((fleet or {}).get("logs") or []),
            key=lambda e: e.get("ts", 0))
        if job is not None and job.trace_id:
            merged["trace_id"] = job.trace_id
            # the fleet-side events were recorded under the DISPATCHER
            # tracer's id (one dispatcher serves many jobs); splicing
            # them into this job's artifact IS the attribution, so they
            # take the job's trace id — grep one id, get the incident
            merged["logs"] = [dict(e, trace_id=job.trace_id)
                              for e in merged["logs"]]
            job.trace_dump = merged
        if self.store is not None:
            from ..store import keycache as KC
            try:
                KC.store_trace(self.store, job_id, merged)
            except Exception:  # best-effort, like _store_trace
                self.metrics.inc("store_write_errors")
        return merged

    def load_trace_merged(self, job_id):
        """The merged timeline for one job: the store artifact
        (trace:<job_id>) when present, else the finished Job's in-memory
        copy. None when the job is unknown or its trace is gone."""
        if self.store is not None:
            from ..store import keycache as KC
            merged = KC.load_trace(self.store, job_id)
            if merged is not None:
                return merged
        job = self.get_job(job_id)
        return job.trace_dump if job is not None else None


class ObsServer:
    """Pull-based observability endpoint over stdlib HTTP (one thread per
    request; read-only except the explicit /profile/capture trigger):

        /metrics         Prometheus text exposition (Metrics.to_prometheus:
                         counters, gauges incl. per-stage MFU, per-round
                         latency summaries) — with an attached fleet
                         (ProofService.attach_fleet), PLUS the labelled
                         per-worker dpt_fleet_* series of the latest scrape
        /healthz         JSON readiness: queue depth, busy workers,
                         draining — and, fleet-attached, the membership
                         epoch, fleet width, suspects, and open breakers,
                         so load balancers and the console read ONE truth
        /fleet           JSON snapshot: roster with per-member breaker/
                         suspect state and each member's full metrics
                         snapshot (the scripts/console.py data source)
        /autoscale       the closed-loop controller's state (mode,
                         bounds/targets, streaks, cooldowns, per-class
                         queue depth, last decisions); 404 while
                         DPT_AUTOSCALE=0 / unattached
        /logs            this process's structured-log ring (obs/log.py);
                         ?trace_id=&since_seq=&limit= filter/tail
        /trace/<job_id>  the job's merged timeline as Chrome trace-event
                         JSON (load in chrome://tracing / Perfetto);
                         ?raw=1 returns the lossless merged dump instead
        /profile/<id>    one stored on-demand capture (profile:<id>
                         artifact — xplane tar.gz or pystacks JSON)
        /profile/capture?worker=N&ms=M  arm a capture on fleet worker N
                         and store it; answers {"profile_id": ...}

    Deliberately a separate listener from the proof-service wire plane:
    scrapers and dashboards must not compete with SUBMIT/RESULT frames,
    and plain HTTP means curl/Prometheus need no custom codec."""

    def __init__(self, service, host="127.0.0.1", port=0):
        import http.server
        svc = service

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: metrics are the log
                pass

            def do_GET(self):
                try:
                    code, ctype, body = _obs_route(svc, self.path)
                except Exception as e:  # pragma: no cover - defensive
                    code, ctype = 500, "application/json"
                    body = protocol.encode_json({"error": repr(e)})
                svc.metrics.inc("obs_http_requests")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="obs-http", daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def _query_params(query):
    import urllib.parse
    return {k: v[-1] for k, v in
            urllib.parse.parse_qs(query, keep_blank_values=True).items()}


def _obs_route(svc, path):
    """(status, content_type, body bytes) for one observability GET."""
    from ..trace import to_chrome_trace
    path, _, query = path.partition("?")
    if path == "/metrics":
        text = svc.metrics.to_prometheus(extra_gauges={
            "queue_depth": svc.queue.depth(),
            "queue_high_water": svc.queue.high_water,
        })
        if svc.fleet is not None:
            # the labelled per-worker series of the latest fleet scrape
            # ride the same exposition: one scrape target for the whole
            # deployment
            text += svc.fleet.render()
        return 200, "text/plain; version=0.0.4; charset=utf-8", \
            text.encode()
    if path == "/healthz":
        # per-circuit-kind job counts (the console's workload-mix pane):
        # what the zoo's heterogeneous traffic actually looks like inside
        # the service, by kind -> {state: count}
        by_kind = {}
        with svc._jobs_lock:
            for j in svc.jobs.values():
                per = by_kind.setdefault(j.spec.kind, {})
                per[j.state] = per.get(j.state, 0) + 1
        body = {
            "ok": True,
            "uptime_s": round(time.monotonic() - svc.metrics.started_at, 3),
            "queue_depth": svc.queue.depth(),
            "busy_workers": len(svc.pool.busy()),
            "draining": svc.queue.closed(),
            "jobs_by_kind": by_kind,
            "aggregates": len(svc._aggregates),
            # fleet summary (None without an attached fleet): the same
            # readiness truth the console and /fleet read — a LB can
            # route on width/suspects without scraping the full snapshot
            "fleet": None,
        }
        if svc.fleet_dispatcher is not None:
            d = svc.fleet_dispatcher
            snap = d.tracker.snapshot()
            body["fleet"] = {
                "epoch": d.epoch,
                "width": len(snap),
                "usable": sum(1 for s in snap if not s["open"]),
                "suspects": sum(1 for s in snap if s["suspect"]),
                "breakers_open": sum(1 for s in snap if s["open"]),
            }
        return 200, "application/json", protocol.encode_json(body)
    if path == "/fleet":
        if svc.fleet is None:
            return 404, "application/json", protocol.encode_json(
                {"error": "no fleet attached "
                          "(ProofService.attach_fleet)"})
        out = svc.fleet.fleet_json(extra={
            "queue_depth": svc.queue.depth(),
            "draining": svc.queue.closed(),
        })
        return 200, "application/json", protocol.encode_json(out)
    if path == "/autoscale":
        asc = getattr(svc, "autoscaler", None)
        if asc is None:
            return 404, "application/json", protocol.encode_json(
                {"error": "autoscaler off (DPT_AUTOSCALE=dry|1 and "
                          "ProofService.attach_autoscaler)"})
        return 200, "application/json", protocol.encode_json(asc.state())
    if path == "/logs":
        q = _query_params(query)
        out = olog.fetch(trace_id=q.get("trace_id") or None,
                         since_seq=int(q.get("since_seq") or 0),
                         limit=int(q["limit"]) if q.get("limit") else None)
        return 200, "application/json", protocol.encode_json(out)
    if path == "/profile/capture":
        q = _query_params(query)
        try:
            meta = svc.profile_fleet_worker(
                worker=int(q.get("worker") or 0),
                duration_ms=int(q["ms"]) if q.get("ms") else None,
                kind=q.get("kind") or "auto")
        except (RuntimeError, ValueError, ConnectionError, OSError) as e:
            return 400, "application/json", protocol.encode_json(
                {"error": repr(e)})
        return 200, "application/json", protocol.encode_json(meta)
    if path.startswith("/profile/"):
        pid = path[len("/profile/"):]
        hit = svc.load_profile(pid)
        if hit is None:
            return 404, "application/json", protocol.encode_json(
                {"error": f"no profile {pid!r}"})
        meta, blob = hit
        ctype = "application/gzip" \
            if meta.get("format") == "xplane-targz" else "application/json"
        return 200, ctype, blob
    if path.startswith("/trace/"):
        job_id = path[len("/trace/"):]
        merged = svc.load_trace_merged(job_id)
        if merged is None:
            return 404, "application/json", protocol.encode_json(
                {"error": f"no trace for job {job_id!r}"})
        if "raw=1" in query:
            return 200, "application/json", protocol.encode_json(merged)
        return 200, "application/json", \
            protocol.encode_json(to_chrome_trace(merged))
    return 404, "application/json", protocol.encode_json(
        {"error": f"unknown path {path!r}",
         "endpoints": ["/metrics", "/healthz", "/fleet", "/autoscale",
                       "/logs", "/trace/<job_id>", "/profile/<id>",
                       "/profile/capture"]})
