"""TCP frontend: SUBMIT/STATUS/RESULT/METRICS/WARMUP on the runtime wire plane.

Reuses runtime/native.py's framed transport and runtime/protocol.py's tag
space (the same plane the kernel workers speak), one thread per
connection like runtime/worker.py — so a deployment speaks ONE protocol
whether a frame carries an MSM or a proof job. Control payloads are JSON;
the RESULT reply carries the 944-byte proof_io layout after a JSON header.

`ProofService` is also directly embeddable (tests/test_service.py,
bench.py drive it in-process through `submit_local`/the client): the TCP
listener is just one more producer into the queue.
"""

import os
import threading
import time

from ..runtime import native, protocol
from ..store import ArtifactStore, aot_warmup, remote
from .jobs import Job, JobSpec
from .metrics import Metrics
from .pool import WorkerPool
from .queue import JobQueue, Rejected
from .scheduler import BucketCache, Scheduler


class ProofService:
    def __init__(self, host="127.0.0.1", port=0, prover_workers=2,
                 queue_depth=64, max_batch=8, max_retries=2,
                 job_timeout_s=None, ckpt_dir=None, chaos=False,
                 backend_factory=None, verify_on_complete=False,
                 finished_retention=4096, allow_remote_shutdown=False,
                 store_dir=None, store_byte_budget=None, bucket_cap=64,
                 store_peers=None, faults=None):
        self.host = host
        self.port = port
        self.chaos = chaos
        self.allow_remote_shutdown = allow_remote_shutdown
        self.metrics = Metrics()
        self.queue = JobQueue(max_depth=queue_depth)
        self.store = None
        if store_dir is not None:
            # NOTE: the service does not repoint the JAX compile cache —
            # an embedded ProofService (tests, bench) must not hijack its
            # host process's cache config. Daemon entry points that OWN
            # their process call store.set_jax_cache_env themselves
            # (scripts/serve.py) so compiled stages warm-start alongside
            # the keys they serve.
            self.store = ArtifactStore(store_dir,
                                       byte_budget=store_byte_budget,
                                       metrics=self.metrics.scoped("store"))
        # faults: runtime.faults.FaultInjector (chaos mode only) — the
        # pool runs its checkpoint-plane rules at round boundaries. An
        # injector built without a metrics registry adopts ours, so its
        # faults_injected_*/faults_ckpt_corrupted counters show up in the
        # same METRICS snapshot as the recovery counters they provoke.
        self.faults = faults if chaos else None
        if self.faults is not None and self.faults.metrics is None:
            self.faults.metrics = self.metrics
        self.pool = WorkerPool(
            self.metrics, prover_workers=prover_workers,
            max_retries=max_retries, job_timeout_s=job_timeout_s,
            ckpt_dir=ckpt_dir, backend_factory=backend_factory,
            verify_on_complete=verify_on_complete, store=self.store,
            faults=self.faults)
        # store_peers: [(host, port)] of peers speaking STORE_FETCH — a
        # bucket miss tries a network copy from a warm peer before paying
        # for a full key build (elastic scale-out: a fresh host serves
        # warm after one fetch)
        self.buckets = BucketCache(self.metrics, store=self.store,
                                   max_entries=bucket_cap,
                                   peers=store_peers)
        self.scheduler = Scheduler(self.queue, self.pool, self.metrics,
                                   buckets=self.buckets, max_batch=max_batch)
        self._warm_backend = None
        self._warm_backend_lock = threading.Lock()
        self.jobs = {}
        self.finished_retention = finished_retention
        self._jobs_lock = threading.Lock()
        self._listener = None
        self._stopped = threading.Event()

    # -- local (in-process) API ----------------------------------------------

    def submit_local(self, spec_obj):
        """Validate + admit one job; returns the Job. Raises ValueError
        (bad spec) or Rejected (admission control)."""
        spec = JobSpec.from_wire(spec_obj)
        job = Job(spec)
        self.metrics.inc("jobs_submitted")
        try:
            self.queue.submit(job)
        except Rejected:
            self.metrics.inc("jobs_rejected")
            raise
        self.metrics.inc("jobs_accepted")
        self.metrics.gauge("queue_depth", self.queue.depth())
        with self._jobs_lock:
            self.jobs[job.id] = job
            # bound the job table in a long-running daemon: evict the
            # oldest FINISHED jobs (dict preserves insertion order) once
            # past the retention cap — live jobs are never evicted, and
            # admission control already bounds how many can be live
            excess = len(self.jobs) - self.finished_retention
            if excess > 0:
                # oldest-first (dict insertion order), stop as soon as the
                # excess is covered — finished jobs cluster at the front,
                # so this stays O(excess + live prefix), not O(table)
                evict = []
                for jid, j in self.jobs.items():
                    if len(evict) >= excess:
                        break
                    if j.state in ("done", "failed"):
                        evict.append(jid)
                for jid in evict:
                    del self.jobs[jid]
                if evict:
                    self.metrics.inc("jobs_evicted", len(evict))
        return job

    def get_job(self, job_id):
        with self._jobs_lock:
            return self.jobs.get(job_id)

    def warmup_local(self, spec_obj, aot=False):
        """Pre-resolve one shape bucket through the cache tiers (memory ->
        store -> build; a build lands in the store) and, with aot=True,
        precompile its prover stages on a pool-equivalent backend. Returns
        the summary the WARMUP tag replies with. Raises ValueError on a
        bad spec."""
        spec = JobSpec.from_wire(spec_obj)
        self.metrics.inc("warmups")
        t0 = time.monotonic()
        res, source = self.buckets.get_with_source(spec)
        out = {
            "shape_key": [str(p) for p in res.shape_key],
            "source": source,
            "domain_size": res.domain_size,
            "build_s": round(res.build_s, 6),
            "warm_s": round(time.monotonic() - t0, 6),
        }
        if aot:
            # same factory the pool workers use, so what we compile is
            # what they run; one shared instance — stage compiles are
            # cached process-wide (NTT plans) / on disk (persistent cache)
            with self._warm_backend_lock:
                if self._warm_backend is None:
                    self._warm_backend = self.pool.backend_factory()
                backend = self._warm_backend
            out["aot"] = aot_warmup(backend, res.domain_size, ck=res.pk.ck)
        return out

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        """Start scheduler + listener threads; returns self. With port=0
        an ephemeral port is chosen and published as `self.port`."""
        self.scheduler.start()
        self._listener = native.Listener(self.host, self.port)
        if self.port == 0:
            import socket
            s = socket.socket(fileno=os.dup(self._listener.fd))
            try:
                self.port = s.getsockname()[1]
            finally:
                s.close()
        threading.Thread(target=self._accept_loop, name="proof-accept",
                         daemon=True).start()
        return self

    def _accept_loop(self):
        while True:
            conn = self._listener.accept()
            if conn.fd < 0:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def serve_forever(self):
        self._stopped.wait()

    def shutdown(self):
        self.scheduler.stop()
        self.pool.shutdown()
        if self._listener is not None:
            self._listener.close()
        self._stopped.set()

    # -- wire handling --------------------------------------------------------

    def _serve_conn(self, conn):
        try:
            while True:
                try:
                    tag, payload = conn.recv()
                except ConnectionError:
                    return
                try:
                    cont = self._dispatch(conn, tag, payload)
                except Exception as e:
                    try:
                        conn.send(protocol.ERR,
                                  protocol.encode_json({"reason": repr(e)}))
                    except ConnectionError:
                        return
                    continue
                if cont is False:
                    self.shutdown()
                    return
        finally:
            conn.close()

    def _dispatch(self, conn, tag, payload):
        if tag == protocol.PING:
            conn.send(protocol.OK)
        elif tag == protocol.SUBMIT:
            try:
                job = self.submit_local(protocol.decode_json(payload))
            except ValueError as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": f"bad_spec: {e}"}))
                return None
            except Rejected as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": e.reason,
                     "queue_depth": self.queue.depth(),
                     "max_depth": self.queue.max_depth}))
                return None
            conn.send(protocol.OK, protocol.encode_json(
                {"job_id": job.id,
                 "shape_key": [str(p) for p in job.shape_key],
                 "queue_depth": self.queue.depth()}))
        elif tag == protocol.STATUS:
            job = self._lookup(conn, payload)
            if job is not None:
                conn.send(protocol.OK, protocol.encode_json(job.status()))
        elif tag == protocol.RESULT:
            job = self._lookup(conn, payload)
            if job is None:
                return None
            if job.proof_bytes is None:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "not_ready", "state": job.state,
                     "error": job.error}))
                return None
            header = {"job_id": job.id,
                      "public_input": [hex(x) for x in job.public_input],
                      "spec": job.spec.to_wire(),
                      "retries": job.retries}
            conn.send(protocol.OK,
                      protocol.encode_result(header, job.proof_bytes))
        elif tag == protocol.WARMUP:
            req = protocol.decode_json(payload)
            aot = bool(req.pop("aot", False))
            try:
                out = self.warmup_local(req, aot=aot)
            except ValueError as e:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": f"bad_spec: {e}"}))
                return None
            conn.send(protocol.OK, protocol.encode_json(out))
        elif tag == protocol.STORE_FETCH:
            # serve one artifact blob to a peer/replacement host: bucket
            # keys, prover checkpoints, anything under the store —
            # cross-host warm start and resume become a digest-verified
            # network copy (store/remote.py holds both wire sides)
            remote.serve_fetch(
                self.store, payload, conn, metrics=self.metrics,
                no_store_reason="no store on this server (serve --store-dir)")
        elif tag == protocol.METRICS:
            snap = self.metrics.snapshot()
            snap["gauges"]["queue_depth"] = self.queue.depth()
            snap["gauges"]["queue_high_water"] = self.queue.high_water
            conn.send(protocol.OK, protocol.encode_json(snap))
        elif tag == protocol.KILL_WORKER:
            if not self.chaos:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "fault injection disabled (serve --chaos)"}))
                return None
            req = protocol.decode_json(payload)
            try:
                victim = self.pool.kill_worker(
                    worker=req.get("worker"), job_id=req.get("job_id"),
                    at_round=req.get("at_round"))
            except LookupError as e:
                conn.send(protocol.ERR,
                          protocol.encode_json({"reason": str(e)}))
                return None
            conn.send(protocol.OK, protocol.encode_json({"worker": victim}))
        elif tag == protocol.SHUTDOWN:
            # a multi-client daemon must not die to any one client's frame;
            # opt in (self-hosted loadgen, tests) or stop it from the host
            if not self.allow_remote_shutdown:
                conn.send(protocol.ERR, protocol.encode_json(
                    {"reason": "remote shutdown disabled "
                               "(serve --allow-remote-shutdown)"}))
                return None
            conn.send(protocol.OK)
            return False
        else:
            conn.send(protocol.ERR,
                      protocol.encode_json({"reason": "unknown tag"}))
        return None

    def _lookup(self, conn, payload):
        job_id = protocol.decode_json(payload).get("job_id")
        job = self.get_job(job_id)
        if job is None:
            conn.send(protocol.ERR, protocol.encode_json(
                {"reason": f"unknown job {job_id!r}"}))
        return job
