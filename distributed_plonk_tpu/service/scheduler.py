"""Batching scheduler: shape buckets with shared keys, pool dispatch.

Jobs whose specs have the same shape key (jobs.shape_key) are structurally
identical circuits — same domain, same selectors, same wiring — so they
can share one SRS + proving/verifying key. The scheduler exploits that two
ways:

1. BucketCache resolves (srs, pk, vk) ONCE per shape, on first demand,
   through three tiers — bounded in-memory LRU, on-disk artifact store
   (persists across restarts), full build — and every later job in the
   bucket skips key setup entirely (at small domains key setup costs more
   than the prove itself — the cache is the difference between O(jobs)
   and O(shapes) setups, and the disk tier makes that hold across
   process lifetimes).
2. JobQueue.pop_batch hands the scheduler the best job plus every queued
   compatible job, and the whole batch is dispatched against one
   resources object — so a burst of same-shape traffic touches the cache
   lock once and lands on the pool back-to-back (maximum key/stage reuse
   in the workers).

The scheduler is one thread: admission (queue) and execution (pool) are
concurrent around it, and pool dispatch blocking is the backpressure that
keeps scheduling from racing ahead of proving capacity.
"""

import itertools
import os
import threading
import time
from collections import OrderedDict

from . import jobs as J
from ..store import keycache as KC

_batch_seq = itertools.count(1)


class BucketResources:
    """Everything a worker needs to prove any job of one shape."""

    def __init__(self, shape_key, srs, pk, vk, domain_size, build_s):
        self.shape_key = shape_key
        self.srs = srs
        self.pk = pk
        self.vk = vk
        self.domain_size = domain_size
        self.build_s = build_s


class _KeyLatch:
    """One shape's in-flight load/build: later callers of the same shape
    wait on `done` instead of re-running the setup; callers of OTHER
    shapes never see it at all (the cache lock is held only for map
    bookkeeping, never across the load/fetch/build work)."""

    def __init__(self):
        self.done = threading.Event()
        self.res = None
        self.source = None
        self.error = None


class BucketCache:
    """Three-tier shape-bucket key cache: memory -> disk -> build.

    Tier 1 is a BOUNDED in-memory LRU (`max_entries`; the PR-1 version
    grew without limit — at 2^18-domain shapes one resident bucket is
    hundreds of MB of SRS+pk, so a long-lived daemon serving many shapes
    needs the cap). Tier 2 is the on-disk ArtifactStore (`store`), where
    keys persist across process restarts and are shared with warmup jobs;
    integrity failures there self-heal (the corrupt entry is deleted and
    the build tier repopulates it). Tier 3 is `jobs.build_bucket_keys`.

    Concurrency: the load/peer-fetch/build tiers run OUTSIDE the cache
    lock behind a per-key latch. Concurrent first-touch of one shape
    still does exactly one setup (waiters block on that shape's latch),
    but a cold miss against an unreachable peer no longer stalls other
    shapes' lookups for DPT_PEER_FETCH_TIMEOUT_MS per peer — the
    PR 6 ROADMAP remainder this closes (regression-tested by
    tests/test_service.py's timing-bound latch tests).

    Metrics: bucket_hits (memory), bucket_disk_hits, bucket_misses
    (full build), bucket_latch_waits (blocked on another caller's
    in-flight setup of the same shape), bucket_mem_evictions, plus the
    store's own store_* counters/gauges.
    """

    def __init__(self, metrics, backend=None, store=None, max_entries=None,
                 peers=None):
        self.metrics = metrics
        self.backend = backend
        self.store = store
        # peers: [(host, port)] speaking STORE_FETCH — tier 2.5, between
        # local disk and full build: a fresh host pulls a warm peer's key
        # blob (digest-verified network copy) instead of re-running
        # trusted setup + preprocess (ROADMAP: store-backed distributed
        # serving; cold start for a scaled-out replica = one fetch)
        self.peers = list(peers or [])
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._buckets = OrderedDict()
        self._latches = {}

    def add_peer(self, host, port):
        """Register one STORE_FETCH peer at runtime (idempotent) — the
        membership plane's auto-discovery path: a worker that JOINs the
        fleet advertising a store becomes a key-fetch tier immediately
        (ProofService.attach_membership wires this up)."""
        pair = (host, int(port))
        with self._lock:
            if pair in self.peers:
                return False
            self.peers.append(pair)
        self.metrics.inc("bucket_peers_added")
        return True

    def remove_peer(self, host, port):
        """Drop one STORE_FETCH peer (a member LEAVEd the fleet): every
        later cold miss would otherwise burn PEER_TIMEOUT_MS dialing the
        decommissioned address before falling through to a build."""
        pair = (host, int(port))
        with self._lock:
            if pair not in self.peers:
                return False
            self.peers.remove(pair)
        self.metrics.inc("bucket_peers_removed")
        return True

    def get(self, spec):
        """Resources for the spec's shape, loading/building on first use."""
        return self.get_with_source(spec)[0]

    def get_with_source(self, spec):
        """(resources, tier) where tier is memory|disk|built — the WARMUP
        handler reports it so operators can see what a warmup did."""
        key = J.shape_key(spec)
        with self._lock:
            res = self._buckets.get(key)
            if res is not None:
                self._buckets.move_to_end(key)
                self.metrics.inc("bucket_hits")
                return res, "memory"
            latch = self._latches.get(key)
            owner = latch is None
            if owner:
                latch = self._latches[key] = _KeyLatch()
        if not owner:
            # same shape already loading on another thread: wait on ITS
            # latch (off-lock — other shapes proceed), then share the
            # outcome. A builder failure propagates: the latch is gone,
            # so a later retry re-attempts the build fresh.
            self.metrics.inc("bucket_latch_waits")
            latch.done.wait()
            if latch.error is not None:
                raise latch.error
            return latch.res, latch.source
        try:
            res, source = self._load_or_build(spec, key)
        except BaseException as e:
            with self._lock:
                self._latches.pop(key, None)
            latch.error = e
            latch.done.set()
            raise
        with self._lock:
            self._buckets[key] = res
            self._latches.pop(key, None)
            if self.max_entries is not None \
                    and len(self._buckets) > self.max_entries:
                self._buckets.popitem(last=False)  # LRU out
                self.metrics.inc("bucket_mem_evictions")
            self.metrics.gauge("buckets_resident", len(self._buckets))
        latch.res, latch.source = res, source
        latch.done.set()
        return res, source

    def _load_or_build(self, spec, key):
        if self.store is not None:
            t0 = time.monotonic()
            hit = KC.load_bucket(self.store, key)
            if hit is None and self.peers:
                hit = self._fetch_from_peers(key)
            if hit is not None:
                srs, pk, vk, meta = hit
                self.metrics.inc("bucket_disk_hits")
                self.metrics.observe("bucket_disk_load",
                                     time.monotonic() - t0)
                return BucketResources(key, srs, pk, vk, vk.domain_size,
                                       meta.get("build_s") or 0.0), "disk"
        self.metrics.inc("bucket_misses")
        t0 = time.monotonic()
        srs, pk, vk = J.build_bucket_keys(spec, backend=self.backend)
        build_s = time.monotonic() - t0
        self.metrics.observe("bucket_build", build_s)
        res = BucketResources(key, srs, pk, vk, vk.domain_size, build_s)
        if self.store is not None:
            # persistence is best-effort: a full disk or unwritable store
            # must degrade to cold starts, never fail the build's jobs
            try:
                KC.store_bucket(self.store, key, srs, pk, vk,
                                build_s=build_s)
            except Exception:  # pragma: no cover - environmental
                self.metrics.inc("store_write_errors")
        return res, "built"

    # per-peer dial+transfer budget for the fetch tier. Peer fetch runs
    # off-lock behind the shape's own latch (so an unreachable peer only
    # delays THAT shape's first-touch callers), but the budget still
    # bounds how long a cold miss can hang on one dead peer before the
    # build tier takes over — keep it far below fetch_into's 30 s default.
    PEER_TIMEOUT_MS = int(os.environ.get("DPT_PEER_FETCH_TIMEOUT_MS", "5000"))

    def _fetch_from_peers(self, key):
        """Try each peer's STORE_FETCH for this bucket's key blob; a hit
        lands in the local store (so the fetch pays once) and parses
        through the normal disk-tier loader. Any per-peer failure falls
        through — the build tier is always below us."""
        from ..store import remote as RS
        store_key = KC.bucket_store_key(key)
        with self._lock:
            peers = list(self.peers)
        for host, port in peers:
            blob = RS.fetch_into(self.store, host, port, store_key,
                                 timeout_ms=self.PEER_TIMEOUT_MS)
            if blob is None:
                continue
            hit = KC.load_bucket(self.store, key)
            if hit is not None:
                self.metrics.inc("bucket_peer_hits")
                return hit
        return None


class Scheduler:
    def __init__(self, queue, pool, metrics, buckets=None, max_batch=8):
        self.queue = queue
        self.pool = pool
        self.metrics = metrics
        self.buckets = buckets or BucketCache(metrics)
        self.max_batch = max_batch
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="proof-scheduler", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.queue.close()
        self._thread.join(timeout=10)

    def crash(self):
        """Crash simulation: stop scheduling without the join/close
        bookkeeping (the 'process' is gone, not exiting)."""
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            batch = self.queue.pop_batch(self.max_batch, timeout=0.25)
            self.metrics.gauge("queue_depth", self.queue.depth())
            if not batch:
                continue
            # TTL load shedding happens HERE, before the (possibly
            # expensive) key build: a job whose deadline lapsed in the
            # queue gets a journaled SHED verdict, not a worker
            live = []
            for job in batch:
                if job.expired():
                    self.pool.shed(job, "ttl expired in queue")
                else:
                    live.append(job)
            batch = live
            if not batch:
                continue
            # the scheduler is ONE thread: an unguarded exception here
            # (key build OOM on an extreme-but-valid spec, backend error)
            # would kill scheduling forever while SUBMIT keeps accepting —
            # fail the batch loudly and keep serving instead
            try:
                res = self.buckets.get(batch[0].spec)
            except Exception as e:
                self.metrics.inc("bucket_build_errors")
                for job in batch:
                    job.finish_err(f"bucket key build failed: {e!r}")
                continue
            batch_id = "batch-%05d" % next(_batch_seq)
            self.metrics.inc("batches_dispatched")
            self.metrics.observe("batch_size", len(batch))
            for job in batch:
                job.scheduled_at = time.monotonic()
                job.batch_id = batch_id
                job.batch_size = len(batch)
            try:
                self._place(batch, res)
            except Exception as e:  # pragma: no cover - defensive
                # a job whose placement was never stamped was never
                # handed to execution: fail it loudly instead of letting
                # it hang queued forever (stamped jobs are owned by
                # their dispatch unit — never double-finished here)
                self.metrics.inc("dispatch_errors")
                for job in batch:
                    if job.placement is None:
                        job.finish_err(f"dispatch failed: {e!r}")

    def _place(self, batch, res):
        """Hand one popped shape batch to execution. The base scheduler
        dispatches every job individually onto the pool (the pre-
        placement behavior); PlacementScheduler (service/placement.py)
        overrides this with the classify/lease/batch logic. The
        contract: `job.placement` is stamped exactly when the job is
        handed to an execution unit."""
        for job in batch:
            job.placement = "pool"  # stamped before dispatch: the worker
            # thread may read it for the trace attrs the moment it pops
            try:
                self.pool.dispatch(job, res)
            except Exception as e:  # pragma: no cover - defensive
                self.metrics.inc("dispatch_errors")
                job.finish_err(f"dispatch failed: {e!r}")
