"""Batching scheduler: shape buckets with shared keys, pool dispatch.

Jobs whose specs have the same shape key (jobs.shape_key) are structurally
identical circuits — same domain, same selectors, same wiring — so they
can share one SRS + proving/verifying key. The scheduler exploits that two
ways:

1. BucketCache resolves (srs, pk, vk) ONCE per shape, on first demand,
   through three tiers — bounded in-memory LRU, on-disk artifact store
   (persists across restarts), full build — and every later job in the
   bucket skips key setup entirely (at small domains key setup costs more
   than the prove itself — the cache is the difference between O(jobs)
   and O(shapes) setups, and the disk tier makes that hold across
   process lifetimes).
2. JobQueue.pop_batch hands the scheduler the best job plus every queued
   compatible job, and the whole batch is dispatched against one
   resources object — so a burst of same-shape traffic touches the cache
   lock once and lands on the pool back-to-back (maximum key/stage reuse
   in the workers).

The scheduler is one thread: admission (queue) and execution (pool) are
concurrent around it, and pool dispatch blocking is the backpressure that
keeps scheduling from racing ahead of proving capacity.
"""

import itertools
import os
import threading
import time
from collections import OrderedDict

from . import jobs as J
from ..store import keycache as KC

_batch_seq = itertools.count(1)


class BucketResources:
    """Everything a worker needs to prove any job of one shape."""

    def __init__(self, shape_key, srs, pk, vk, domain_size, build_s):
        self.shape_key = shape_key
        self.srs = srs
        self.pk = pk
        self.vk = vk
        self.domain_size = domain_size
        self.build_s = build_s


class BucketCache:
    """Three-tier shape-bucket key cache: memory -> disk -> build.

    Tier 1 is a BOUNDED in-memory LRU (`max_entries`; the PR-1 version
    grew without limit — at 2^18-domain shapes one resident bucket is
    hundreds of MB of SRS+pk, so a long-lived daemon serving many shapes
    needs the cap). Tier 2 is the on-disk ArtifactStore (`store`), where
    keys persist across process restarts and are shared with warmup jobs;
    integrity failures there self-heal (the corrupt entry is deleted and
    the build tier repopulates it). Tier 3 is `jobs.build_bucket_keys`.

    Metrics: bucket_hits (memory), bucket_disk_hits, bucket_misses
    (full build), bucket_mem_evictions, plus the store's own store_*
    counters/gauges.
    """

    def __init__(self, metrics, backend=None, store=None, max_entries=None,
                 peers=None):
        self.metrics = metrics
        self.backend = backend
        self.store = store
        # peers: [(host, port)] speaking STORE_FETCH — tier 2.5, between
        # local disk and full build: a fresh host pulls a warm peer's key
        # blob (digest-verified network copy) instead of re-running
        # trusted setup + preprocess (ROADMAP: store-backed distributed
        # serving; cold start for a scaled-out replica = one fetch)
        self.peers = list(peers or [])
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._buckets = OrderedDict()

    def get(self, spec):
        """Resources for the spec's shape, loading/building on first use."""
        return self.get_with_source(spec)[0]

    def get_with_source(self, spec):
        """(resources, tier) where tier is memory|disk|built — the WARMUP
        handler reports it so operators can see what a warmup did."""
        key = J.shape_key(spec)
        with self._lock:
            res = self._buckets.get(key)
            if res is not None:
                self._buckets.move_to_end(key)
                self.metrics.inc("bucket_hits")
                return res, "memory"
            # load/build inside the lock: concurrent first-touch of one
            # shape must not duplicate a key setup (the expensive part)
            res, source = self._load_or_build(spec, key)
            self._buckets[key] = res
            if self.max_entries is not None \
                    and len(self._buckets) > self.max_entries:
                self._buckets.popitem(last=False)  # LRU out
                self.metrics.inc("bucket_mem_evictions")
            self.metrics.gauge("buckets_resident", len(self._buckets))
            return res, source

    def _load_or_build(self, spec, key):
        if self.store is not None:
            t0 = time.monotonic()
            hit = KC.load_bucket(self.store, key)
            if hit is None and self.peers:
                hit = self._fetch_from_peers(key)
            if hit is not None:
                srs, pk, vk, meta = hit
                self.metrics.inc("bucket_disk_hits")
                self.metrics.observe("bucket_disk_load",
                                     time.monotonic() - t0)
                return BucketResources(key, srs, pk, vk, vk.domain_size,
                                       meta.get("build_s") or 0.0), "disk"
        self.metrics.inc("bucket_misses")
        t0 = time.monotonic()
        srs, pk, vk = J.build_bucket_keys(spec, backend=self.backend)
        build_s = time.monotonic() - t0
        self.metrics.observe("bucket_build", build_s)
        res = BucketResources(key, srs, pk, vk, vk.domain_size, build_s)
        if self.store is not None:
            # persistence is best-effort: a full disk or unwritable store
            # must degrade to cold starts, never fail the build's jobs
            try:
                KC.store_bucket(self.store, key, srs, pk, vk,
                                build_s=build_s)
            except Exception:  # pragma: no cover - environmental
                self.metrics.inc("store_write_errors")
        return res, "built"

    # per-peer dial+transfer budget for the fetch tier. Peer fetch runs
    # under the cache lock (build dedup), so an unreachable peer stalls
    # OTHER shapes' lookups for this long per peer per cold miss — keep
    # it far below fetch_into's 30 s default. (Moving the fetch/build
    # outside the lock behind a per-key latch is the structural fix,
    # tracked in ROADMAP direction 2.)
    PEER_TIMEOUT_MS = int(os.environ.get("DPT_PEER_FETCH_TIMEOUT_MS", "5000"))

    def _fetch_from_peers(self, key):
        """Try each peer's STORE_FETCH for this bucket's key blob; a hit
        lands in the local store (so the fetch pays once) and parses
        through the normal disk-tier loader. Any per-peer failure falls
        through — the build tier is always below us."""
        from ..store import remote as RS
        store_key = KC.bucket_store_key(key)
        for host, port in self.peers:
            blob = RS.fetch_into(self.store, host, port, store_key,
                                 timeout_ms=self.PEER_TIMEOUT_MS)
            if blob is None:
                continue
            hit = KC.load_bucket(self.store, key)
            if hit is not None:
                self.metrics.inc("bucket_peer_hits")
                return hit
        return None


class Scheduler:
    def __init__(self, queue, pool, metrics, buckets=None, max_batch=8):
        self.queue = queue
        self.pool = pool
        self.metrics = metrics
        self.buckets = buckets or BucketCache(metrics)
        self.max_batch = max_batch
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="proof-scheduler", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.queue.close()
        self._thread.join(timeout=10)

    def _loop(self):
        while not self._stop.is_set():
            batch = self.queue.pop_batch(self.max_batch, timeout=0.25)
            self.metrics.gauge("queue_depth", self.queue.depth())
            if not batch:
                continue
            # the scheduler is ONE thread: an unguarded exception here
            # (key build OOM on an extreme-but-valid spec, backend error)
            # would kill scheduling forever while SUBMIT keeps accepting —
            # fail the batch loudly and keep serving instead
            try:
                res = self.buckets.get(batch[0].spec)
            except Exception as e:
                self.metrics.inc("bucket_build_errors")
                for job in batch:
                    job.finish_err(f"bucket key build failed: {e!r}")
                continue
            batch_id = "batch-%05d" % next(_batch_seq)
            self.metrics.inc("batches_dispatched")
            self.metrics.observe("batch_size", len(batch))
            for job in batch:
                job.scheduled_at = time.monotonic()
                job.batch_id = batch_id
                job.batch_size = len(batch)
                try:
                    self.pool.dispatch(job, res)
                except Exception as e:  # pragma: no cover - defensive
                    self.metrics.inc("dispatch_errors")
                    job.finish_err(f"dispatch failed: {e!r}")
