"""Crash-safe write-ahead job journal for the proof frontend.

PR 6 made WORKER death routine; this module does the same for the service
process itself — the reference's weak spot reincarnated (its sequential
dispatcher unwrap-panics and loses everything in flight,
/root/reference/src/dispatcher.rs). The service's queue and job table are
in-memory; every state transition that matters is therefore journaled
here FIRST, so a frontend crash or deploy restart loses nothing:

    SUBMIT  job admitted (spec, idempotency key, deadline) — written
            before the job enters the in-memory queue (write-ahead)
    START   a prover attempt began (worker name)
    ROUND   round N's checkpoint snapshot is durable (store/ckpt-file) —
            appended AFTER the snapshot write, so a journaled ROUND N is a
            promise that resume-from-round-N state exists
    DONE    proof finished; the record carries the finished-proof store
            artifact's key+digest (or the raw bytes inline when the
            service has no store), the public input, and retry count
    SHED    deadline/TTL load shedding verdict (queryable by clients)
    FAILED  terminal failure (reason)

A restarted service replays the journal (`JobJournal(dir)` replays on
open), re-enqueues every non-terminal job under its ORIGINAL id — so its
`ckpt:<job_id>` checkpoint artifact still matches and the prove resumes at
the last round boundary with zero recompute — and serves DONE jobs from
their finished-proof artifacts without re-proving.

Durability model:
- One append-only file `journal.log`; each record is one line
  `crc32(json) json\n`, flushed + fsync'd before append() returns
  (DPT_JOURNAL_FSYNC=0 trades durability for speed in tests).
- Torn/corrupt tail (power cut mid-append, bit rot): replay keeps the
  longest valid prefix, TRUNCATES the file there, counts
  journal_torn_records, and continues — never crashes, never trusts a
  damaged suffix (append-only means damage can only be a suffix).
- Store-backed compaction: every DPT_JOURNAL_COMPACT_EVERY appends (and
  once after each replay) the log is rewritten from live state — one
  SUBMIT(+ROUND/terminal) line per job, oldest terminal jobs beyond
  `retain_terminal` dropped. Payloads never bloat the log: proofs and
  checkpoints live in the artifact store; the journal only carries keys
  and digests.

Metrics (duck-typed inc): journal_appends, journal_replays,
journal_torn_records, journal_compactions.
"""

import json
import logging
import os
import threading
import zlib

from ..runtime.health import NullMetrics

log = logging.getLogger("dpt.journal")

# record types
SUBMIT = "SUBMIT"
START = "START"
ROUND = "ROUND"
DONE = "DONE"
SHED = "SHED"
FAILED = "FAILED"
AGG = "AGG"      # aggregate artifact built (ISSUE 17): id is the
                 # aggregate's content-addressed agg_id (NOT a job id);
                 # the record carries the member job ids and the
                 # artifact's store key+digest (or the JSON blob inline
                 # hex when the service has no store) — recovery re-serves
                 # the aggregate exactly like a DONE job's proof

# replayed-state phases that mean "no further records will follow"
# ("aggregate" rides along so compaction's retain_terminal bounds the
# journal's memory of old aggregates the same way it bounds old jobs)
TERMINAL_PHASES = ("done", "shed", "failed", "aggregate")

# SHED-record reason prefix for admission-control rejections: the client
# was told 'no' synchronously, so recovery keeps the verdict queryable
# by id but must NOT bind the job_key to it (a live retry of the key is
# a fresh admission attempt, matching the non-restart path)
REJECTED_PREFIX = "rejected: "

_FSYNC = os.environ.get("DPT_JOURNAL_FSYNC", "1") != "0"
_COMPACT_EVERY = int(os.environ.get("DPT_JOURNAL_COMPACT_EVERY", "512"))


def record_label(rtype, rec):
    """Chaos-rule label for one record: ROUND records carry their round
    number (kill:at=journal:tag=ROUND2 dies after round 2's append),
    everything else is the bare type."""
    if rtype == ROUND:
        return f"{ROUND}{rec.get('round')}"
    return rtype


class JobJournal:
    """Append-only journal + the replayed job-state map it implies.

    `state` maps job_id -> {spec, key, deadline, submitted, phase, round,
    worker, done, reason} in SUBMIT order; `phase` is the lowercase last
    record type. The service reads `state` once at recovery and appends
    transitions forever after; the journal itself is the only component
    that parses the file.
    """

    def __init__(self, journal_dir, metrics=None, fsync=None,
                 compact_every=None, retain_terminal=4096, chaos=None):
        self.dir = journal_dir
        self.path = os.path.join(journal_dir, "journal.log")
        self.metrics = metrics or NullMetrics()
        self.fsync = _FSYNC if fsync is None else fsync
        self.compact_every = compact_every or _COMPACT_EVERY
        self.retain_terminal = retain_terminal
        # chaos: runtime.faults.FaultInjector (or None). Its journal-plane
        # rules run after each record is DURABLE — "kill the service right
        # after journal occurrence X" is the restart-recovery test plane.
        self.chaos = chaos
        self._lock = threading.Lock()
        self._sealed = False
        self._since_compact = 0
        os.makedirs(journal_dir, exist_ok=True)
        self.state = {}
        self._replay()
        self._f = open(self.path, "ab")

    # -- replay ---------------------------------------------------------------

    def _replay(self):
        """Load the valid record prefix into `state`; truncate any torn or
        corrupt tail in place (append-only file: damage is always a
        suffix; the prefix before it is still the true history)."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        good_end = 0
        replayed = 0
        torn = False
        for line in raw.split(b"\n")[:-1]:
            rec = self._parse(line)
            if rec is None:
                torn = True
                break
            self._apply(rec)
            replayed += 1
            good_end += len(line) + 1
        if good_end < len(raw):
            # tail beyond the last valid record: torn final append, bit
            # rot, or a missing trailing newline — drop it and continue
            torn = True
        if torn:
            log.warning("journal %s: dropping %d damaged tail bytes "
                        "(%d valid records kept)", self.path,
                        len(raw) - good_end, replayed)
            self.metrics.inc("journal_torn_records")
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        if replayed:
            self.metrics.inc("journal_replays", replayed)

    @staticmethod
    def _parse(line):
        """One journal line -> record dict, or None if damaged."""
        head, sep, body = line.partition(b" ")
        if not sep or len(head) != 8:
            return None
        try:
            want = int(head, 16)
        except ValueError:
            return None
        if zlib.crc32(body) != want:
            return None
        try:
            rec = json.loads(body.decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            return None
        return rec if isinstance(rec, dict) and "t" in rec else None

    def _apply(self, rec):
        """Fold one record into the state map."""
        rtype, jid = rec.get("t"), rec.get("id")
        if jid is None:
            return
        if rtype == AGG:
            # aggregates are their own single-record state entries: no
            # SUBMIT precedes them, and no later record ever follows
            self.state[jid] = {
                "spec": None, "key": None, "deadline": None,
                "submitted": rec.get("ts"), "trace": None,
                "trace_parent": None, "phase": "aggregate", "round": 0,
                "worker": None, "reason": None,
                "done": {k: rec.get(k) for k in
                         ("members", "store_key", "digest", "agg_hex")},
            }
            return
        st = self.state.get(jid)
        if st is None:
            if rtype != SUBMIT:
                # record for a job whose SUBMIT was compacted away or lost
                # to a torn tail: tolerate (recovery treats unknown-spec
                # jobs as unrecoverable, never crashes)
                return
            self.state[jid] = {
                "spec": rec.get("spec"), "key": rec.get("key"),
                "deadline": rec.get("deadline"),
                "submitted": rec.get("ts"),
                # trace identity survives a restart: the client was told
                # this id at SUBMIT, so the recovered job (and its
                # trace:<job_id> artifact) must keep answering to it
                "trace": rec.get("trace"),
                "trace_parent": rec.get("trace_parent"),
                "phase": "submit", "round": 0, "worker": None,
                "done": None, "reason": None,
            }
            return
        if rtype == START:
            st["phase"] = "start"
            st["worker"] = rec.get("worker")
        elif rtype == ROUND:
            st["phase"] = "round"
            st["round"] = max(st["round"], int(rec.get("round") or 0))
        elif rtype == DONE:
            st["phase"] = "done"
            st["done"] = {k: rec.get(k) for k in
                          ("store_key", "digest", "proof_hex", "pub",
                           "retries")}
        elif rtype in (SHED, FAILED):
            st["phase"] = rtype.lower()
            st["reason"] = rec.get("reason")

    # -- append ---------------------------------------------------------------

    def append(self, rtype, job_id, **fields):
        """Durably journal one transition; returns False when sealed
        (crashed service — the in-process analog of a dead process writes
        nothing). The chaos hook runs AFTER the fsync, outside the lock:
        a journal-plane kill models a crash at exactly this occurrence,
        with this record on disk and nothing after it."""
        rec = dict(fields)
        rec["t"] = rtype
        rec["id"] = job_id
        with self._lock:
            if self._sealed:
                return False
            self._apply(rec)
            body = json.dumps(rec, separators=(",", ":")).encode()
            self._f.write(b"%08x " % zlib.crc32(body) + body + b"\n")
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self.metrics.inc("journal_appends")
            self._since_compact += 1
            if self._since_compact >= self.compact_every:
                self._compact_locked()
        if self.chaos is not None:
            self.chaos.on_journal(rtype, record_label(rtype, rec),
                                  job_id=job_id)
        return True

    # -- compaction -----------------------------------------------------------

    def compact(self):
        """Rewrite the log from live state (one line per surviving job),
        dropping the oldest terminal jobs beyond `retain_terminal` — their
        proof artifacts stay in the store; only the journal's memory of
        them is bounded. Atomic (tmp + fsync + rename)."""
        with self._lock:
            if not self._sealed:
                self._compact_locked()

    def _compact_locked(self):
        terminal = [j for j, st in self.state.items()
                    if st["phase"] in TERMINAL_PHASES]
        for jid in terminal[:max(0, len(terminal) - self.retain_terminal)]:
            del self.state[jid]
        tmp = self.path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            for jid, st in self.state.items():
                for rec in self._state_records(jid, st):
                    body = json.dumps(rec, separators=(",", ":")).encode()
                    f.write(b"%08x " % zlib.crc32(body) + body + b"\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._since_compact = 0
        self.metrics.inc("journal_compactions")

    @staticmethod
    def _state_records(jid, st):
        """Minimal record sequence that replays back to `st`."""
        if st["phase"] == "aggregate":
            rec = {"t": AGG, "id": jid, "ts": st["submitted"]}
            rec.update({k: v for k, v in (st["done"] or {}).items()
                        if v is not None})
            yield rec
            return
        sub = {"t": SUBMIT, "id": jid, "spec": st["spec"],
               "key": st["key"], "deadline": st["deadline"],
               "ts": st["submitted"]}
        for k in ("trace", "trace_parent"):
            if st.get(k) is not None:
                sub[k] = st[k]
        yield sub
        if st["round"]:
            yield {"t": ROUND, "id": jid, "round": st["round"]}
        if st["phase"] == "done":
            rec = {"t": DONE, "id": jid}
            rec.update({k: v for k, v in (st["done"] or {}).items()
                        if v is not None})
            yield rec
        elif st["phase"] in ("shed", "failed"):
            yield {"t": st["phase"].upper(), "id": jid,
                   "reason": st["reason"]}

    # -- lifecycle ------------------------------------------------------------

    def seal(self):
        """Crash simulation (ProofService.crash / tests): stop writing as
        a SIGKILL'd process would — whatever is on disk now is exactly
        what a restarted service will see."""
        with self._lock:
            self._sealed = True
            try:
                self._f.close()
            except OSError:  # pragma: no cover - already closed
                pass

    def close(self):
        """Clean shutdown: flush + fsync + close (drain's last step)."""
        with self._lock:
            if self._sealed:
                return
            self._sealed = True
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
