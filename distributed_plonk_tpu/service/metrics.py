"""Service observability: counters, gauges, latency histograms, exposition.

The structured upgrade of the worker plane's raw `{tag: count}` STATS
counters (runtime/worker.py) for the serving layer: one `Metrics` registry
aggregates queue depth, wait/run latencies, per-prover-round times (fed
from trace.Tracer totals), retries/kills, and throughput, snapshots to one
JSON-able dict for the METRICS wire tag, and renders the Prometheus text
exposition (`to_prometheus`) that serve.py --obs-port serves at /metrics.

Histograms keep a bounded reservoir (uniform sampling past the cap, so
long runs stay O(1) memory) and report count/sum/min/mean/percentiles
computed from the reservoir at snapshot time; `samples` says how many
reservoir values back the percentile estimates (past the cap they are
estimates over a uniform sample, not exact order statistics).

METRIC GLOSSARY — every counter/histogram name the code records must be
documented here; analysis/lint.py's OBS01 lint enforces it (a `_*`
suffix documents a name family). Scoped registries (Metrics.scoped)
publish under their prefix: the artifact store's entries appear as
store_<name>.

Job lifecycle (service/server.py, service/pool.py, service/queue.py):
    jobs_submitted / jobs_accepted / jobs_rejected   admission outcomes
    jobs_completed / jobs_failed / jobs_timeout      terminal outcomes
    job_retries / job_attempt_errors                 retry-loop activity
    jobs_evicted                                     finished jobs aged out
                                                     of the job table
    workers_spawned / workers_killed / kill_requests  pool slot lifecycle
                                                     + fault injection
    warmups                                          WARMUP requests served
    job_wait / job_run (histograms)                  submit->start and
                                                     start->done seconds
    prove_round/* (histograms)                       per-round prover
                                                     latency (trace totals)
    queue_depth / queue_high_water (gauges)          admission backlog

Scheduler + shape buckets (service/scheduler.py):
    batches_dispatched / batch_size                  shape-batch activity
    dispatch_errors                                  pool handoff failures

Placement + cross-job batched proving (service/placement.py, pool.py):
    placement_*                                      decisions per popped
                                                     shape batch: _batch
                                                     (data-parallel cross-
                                                     job prove), _mesh
                                                     (sharded submesh
                                                     prove), _pool (per-job
                                                     dispatch)
    batch_proves                                     batched prove_many
                                                     attempts launched
    batch_jobs                                       jobs proved inside
                                                     batched attempts
    batch_jobs_per_launch (histogram)                achieved jobs per
                                                     batched attempt
    batch_member_kills                               batch members killed
                                                     mid-prove (resumed
                                                     alone; the others
                                                     finished unaffected)
    submesh_leases                                   device leases granted
                                                     (big sharded proves +
                                                     opportunistic batch
                                                     leases)
    submesh_devices_free (gauge)                     unleased devices
    bucket_hits / bucket_misses / bucket_disk_hits   key-cache tiers
    bucket_peer_hits                                 keys fetched from a
                                                     warm STORE_FETCH peer
    bucket_latch_waits                               callers that waited on
                                                     another thread's
                                                     in-flight key setup
    bucket_mem_evictions / buckets_resident (gauge)  memory-tier LRU
    bucket_build / bucket_disk_load (histograms)     tier latencies
    bucket_build_errors                              key builds that failed
    store_write_errors                               best-effort artifact
                                                     writes that failed

Round-pipelined proving (prover.PipelinedProver via pool._run_pipeline):
    pipelined_proves                         pipelined attempts launched
                                             (one per coalesced window)
    pipelined_jobs                           jobs proved inside pipelined
                                             attempts
    pipeline_depth (gauge)                   members in flight at the last
                                             observed stage boundary
    pipeline_depth_achieved (histogram)      in-flight depth sampled at
                                             every stage finalize (the
                                             fill the pipeline actually
                                             achieved vs DPT_PIPELINE_DEPTH)
    pipeline_stage_wait_s (histogram)        driver wait for a member's
                                             oldest ready stage (also per
                                             round: pipeline_stage_wait_s/
                                             round<N>)
    pipeline_device_idle_s/round<N> (gauge)  host-finalize span not covered
                                             by the device force — the
                                             serial host work the pipeline
                                             overlaps with other members'
                                             launches

Artifact store, scoped `store_*` (store/artifacts.py, store/remote.py):
    store_hits / store_misses / store_evictions      blob cache activity
    store_corrupt                                    integrity failures on
                                                     read (entry deleted,
                                                     rebuilt on demand)
    store_entries / store_bytes (gauges)             resident inventory
    store_put_bytes                                  bytes written
    store_jax_cache_bytes / store_jax_cache_evictions  compile-cache GC
    store_fetch_served / store_fetch_misses          STORE_FETCH server side
    store_fetch_bytes                                blob bytes served

Failure-observability vocabulary (one registry can be handed to the
runtime Dispatcher AND the service pool, so a whole deployment's fault
story reads off one snapshot):
    fleet_reconnects / fleet_backoff_waits   reconnect loop activity
    fleet_backoff (histogram)                seconds slept in backoff
    fleet_breaker_opens / fleet_readmissions  circuit-breaker transitions
    fleet_range_adoptions                    MSM ranges moved off a dead
                                             worker (runtime dispatcher)
    fleet_fft_replans / fleet_fft_degraded   sharded-FFT recovery events
    checkpoint_saves / checkpoint_resumes    prover round snapshots and
                                             resumed (not restarted)
                                             attempts (service pool)
    faults_injected_* / faults_ckpt_corrupted  chaos-injection activity
                                             (runtime/faults.py)

Membership & supervision vocabulary (runtime/membership.py,
runtime/supervisor.py — the self-healing fleet):
    fleet_size (gauge)                       current member count (slots,
                                             incl. breaker-open ones)
    membership_epoch (gauge)                 roster version; bumps on
                                             every join/rejoin/leave
    membership_joins / membership_rejoins    new members admitted vs
                                             known addresses re-admitted
                                             in place (supervisor
                                             respawns land here)
    membership_leaves                        members declared permanently
                                             gone (flap cap, operator)
    roster_pushes                            epoch tables pushed to live
                                             workers after a change
    warm_rejoins                             JOIN phase=ready reports
                                             carrying warm-sync stats
    warm_rejoin_s (histogram)                seconds a joiner spent
                                             pulling bucket/compile-cache
                                             artifacts from roster peers
    worker_respawns                          supervisor restarts of dead
                                             or wedged worker processes
    worker_flap_capped                       slots given up on (flap_cap
                                             respawns inside the window)
    supervisor_probe_misses                  liveness probes a supervised
                                             worker failed to answer
    supervised_workers (gauge)               slots under supervision
    bucket_peers_added / bucket_peers_removed  store-serving members
                                             auto-registered as key-fetch
                                             peers / dropped on LEAVE
                                             (attach_membership)
    store_list_served                        STORE_LIST enumerations
                                             answered (warm-rejoin scans)

Durability vocabulary (service/journal.py + the restart-recovery path):
    journal_appends / journal_replays        records written / replayed
                                             at open
    journal_torn_records / journal_compactions  damaged-tail truncations
                                             and log rewrites
    jobs_recovered / jobs_recovered_finished  re-enqueued in-flight jobs
                                             and artifact-served DONE
                                             jobs after a restart
    jobs_shed                                TTL/deadline load-shed
                                             verdicts (journaled)
    dedup_hits                               duplicate job_key SUBMITs
                                             answered from the original
    drain_started / drain_clean / drain_forced  graceful-drain outcomes
    jobs_drain_parked                        in-flight jobs checkpointed
                                             + parked by a forced drain
    proof_artifacts_lost                     DONE records whose proof
                                             artifact was evicted (job
                                             re-proved, same bytes)

Result-integrity vocabulary (runtime/integrity.py, runtime/dispatcher.py,
runtime/health.py, service/pool.py — the SDC defense):
    integrity_checks                         algebraic phase checks run
                                             (FFT/NTT Schwartz-Zippel,
                                             MSM group-law sanity, eval
                                             dup sampling decisions)
    integrity_failures                       checks that caught a WRONG
                                             (well-formed) answer
    integrity_msm_dups                       MSM ranges duplicate-
                                             executed on a second worker
                                             (rate DPT_INTEGRITY_MSM_DUP)
    integrity_eval_dups                      evaluation chunks duplicate-
                                             executed (same rate knob)
    workers_quarantined                      workers marked SUSPECT by an
                                             attributed integrity failure
                                             (sticky breaker; LEAVEd when
                                             membership is armed)
    integrity_challenges                     known-answer challenge
                                             proves run against (re-)
                                             joining quarantined
                                             addresses
    integrity_challenges_failed              challenges the worker
                                             answered WRONG (it stays
                                             quarantined)
    self_verify_checks                       verify-before-serve pairing
                                             checks run (DPT_SELF_VERIFY)
    self_verify_failures                     finished proofs that failed
                                             the pairing verifier
    self_verify_s (histogram)                verify-before-serve latency
    proofs_blocked                           proofs withheld from the
                                             journal/client by a failed
                                             self-verify (job re-proved)

Kernel-autotune vocabulary (backend/autotune.py, store/calibration.py —
the measured kernel-dispatch plan, ISSUE 14):
    autotune_runs                            calibration measure passes
                                             started (mode=run on a
                                             plan-less store)
    autotune_cells                           (kind, domain-size) cells
                                             decided by a pass
    autotune_measure_runs                    candidate configurations
                                             measured (incl. the parity
                                             reference per cell)
    autotune_candidate_errors                candidates that failed to
                                             build/trace/run (skipped)
    autotune_parity_rejects                  fast-but-WRONG candidates
                                             rejected by the bit-identity
                                             gate (never adopted)
    autotune_run_s (histogram)               wall-clock per measure pass
    autotune_plan_stores / autotune_plan_loads  plan artifacts persisted
                                             to / adopted from the store
    autotune_plan_source (gauge)             off|none|store|fresh — where
                                             this process's plan came from
    autotune_plan_cells (gauge)              cells in the active plan
    autotune_plan_revision (gauge)           process-wide plan revision
                                             (bumps on every reload; memo
                                             caches key on it)

Tracing vocabulary (trace.py, service/pool.py, server.py --obs-port):
    trace_spans_recorded                     spans folded into finished
                                             jobs' merged timelines
    traces_stored                            trace:<job_id> artifacts
                                             written to the store
    obs_http_requests                        /metrics /healthz /trace
                                             requests served
    kernel_*_gflops / mfu_*_pct (gauges)     live per-stage throughput
                                             and model-flops MFU from
                                             kernel span attrs (peak set
                                             by DPT_PEAK_TFLOPS)

Fleet observability vocabulary (obs/log.py, obs/fleet.py,
runtime/worker.py METRICS_FETCH/LOG_FETCH/PROFILE — the one-pane plane,
ISSUE 15):
    served_*                                 worker-side request counters
                                             per wire tag (served_msm,
                                             served_fft2, ...): the
                                             structured twin of the raw
                                             STATS dict, scrapeable over
                                             METRICS_FETCH
    worker_*_s (histograms)                  worker-side kernel latency
                                             per stage (worker_msm_s,
                                             worker_ntt_s, worker_fft1_s,
                                             worker_fft2_s)
    serve_errors                             worker request frames that
                                             drew an ERR reply (malformed
                                             payload / backend failure)
    log_events                               structured log events
                                             recorded into the ring
    log_dropped                              ring-capacity overwrites:
                                             every oldest-event eviction
                                             once the ring is full (a
                                             fetch may or may not have
                                             read it first — high values
                                             mean raise DPT_LOG_CAP or
                                             tail more often)
    fleet_scrapes                            METRICS_FETCH scrape cycles
                                             completed by the aggregator
    fleet_scrape_errors                      scrape cycles that failed
                                             whole (fan-out error)
    fleet_width / fleet_reachable (gauges)   roster size vs members that
                                             answered the last scrape
    fleet_suspects / fleet_breakers_open (gauges)  quarantined members /
                                             open breakers at last scrape
    fleet_served_total / fleet_serve_errors_total (gauges)  fleet-summed
                                             request counters from the
                                             last scrape
    profiles_captured                        PROFILE captures served by
                                             this worker
    profiles_stored                          profile:<id> artifacts
                                             persisted by the service
    profile_errors                           captures that failed or came
                                             back empty/unsupported

Autoscaling & SLO-class vocabulary (service/autoscale.py,
service/queue.py, service/pool.py, runtime/supervisor.py — the
closed-loop controller, ISSUE 16):
    autoscale_*                              controller activity:
                                             autoscale_ticks (control-
                                             loop cycles), autoscale_
                                             decisions (recorded
                                             verdicts), autoscale_scale_
                                             ups / autoscale_scale_downs
                                             (worker-count moves
                                             APPLIED), autoscale_lease_
                                             resizes (submesh capacity
                                             moves), autoscale_sheds
                                             (pressure evictions),
                                             autoscale_sensor_errors;
                                             gauges autoscale_workers /
                                             autoscale_target_workers /
                                             autoscale_queue_<class>
                                             (per-class queued depth at
                                             last tick)
    slo_*                                    per-class serving outcomes:
                                             slo_roundtrip/<class>
                                             (histogram: submit -> done
                                             seconds per SLO class; the
                                             standard-class p95_s is the
                                             controller's latency
                                             sensor), slo_sheds_<class>
                                             (terminal SHED verdicts per
                                             class), slo_preempt_sheds
                                             (lower-class jobs evicted
                                             by a full queue admitting a
                                             higher class)
    worker_retires                           supervised workers retired
                                             gracefully by scale-down:
                                             drain -> membership LEAVE
                                             -> SIGTERM (SIGKILL only
                                             past DPT_SUP_RETIRE_
                                             TIMEOUT_S); a retire is
                                             never a flap and never
                                             respawns

Circuit zoo + proof aggregation vocabulary (circuits/, aggregate.py,
service/server.py AGGREGATE path — ISSUE 17):
    circuit_kind_*                           jobs served to DONE per
                                             circuit kind (circuit_kind_
                                             toy, circuit_kind_range,
                                             ...): the zoo mix as the
                                             server actually proved it
    aggregates_built                         batch-KZG aggregates built
                                             (self-verified + journaled)
    aggregate_members                        constituent proofs folded
                                             into built aggregates
                                             (members per build summed)
    aggregate_verify_s (histogram)           server-side fold-then-one-
                                             pairing-check latency per
                                             built aggregate
    aggregate_verify_failures                aggregate builds REJECTED by
                                             the server's own verify gate
                                             (nothing journaled/served)
    aggregates_recovered                     aggregate artifacts restored
                                             from the journal after a
                                             restart
    aggregate_artifacts_lost                 journaled aggregates whose
                                             artifact bytes were gone at
                                             recovery (store eviction)
"""

import math
import os
import random
import re
import threading
import time

_RESERVOIR = 2048

# MFU denominator: the chip's peak f32 FMA rate in TFLOP/s (bench.py's
# f32_fma_tflops_measured is the number to use). The default 1.0 makes
# mfu_*_pct read as GFLOP/s / 10 until an operator calibrates it — a
# consistent relative signal either way.
PEAK_TFLOPS = float(os.environ.get("DPT_PEAK_TFLOPS", "1.0"))


class Histogram:
    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._samples = []
        self._rng = random.Random(0xC0FFEE)

    def record(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self._samples) < _RESERVOIR:
            self._samples.append(v)
        else:
            i = self._rng.randrange(self.count)
            if i < _RESERVOIR:
                self._samples[i] = v

    def snapshot(self):
        if not self.count:
            return {"count": 0}
        s = sorted(self._samples)

        def pct(p):
            # nearest-rank percentile over the reservoir: ceil(p*k)-1,
            # clamped for tiny counts (the old int(p*k) indexed the MAX
            # for any p >= 1-1/k — e.g. a 2-sample p50 returned the max)
            return s[max(0, min(len(s) - 1, math.ceil(p * len(s)) - 1))]

        return {
            "count": self.count,
            # percentiles below are computed over `samples` retained
            # reservoir values, not all `count` observations — estimates,
            # not exact order statistics, once samples < count
            "samples": len(s),
            "sum_s": round(self.sum, 6),
            "min_s": round(self.min, 6),
            "mean_s": round(self.sum / self.count, 6),
            "p50_s": round(pct(0.50), 6),
            "p90_s": round(pct(0.90), 6),
            "p95_s": round(pct(0.95), 6),
            "p99_s": round(pct(0.99), 6),
            "max_s": round(self.max, 6),
        }


def _prom_name(name):
    """Metric name -> Prometheus-legal name under the dpt_ namespace."""
    return "dpt_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._hists = {}
        self.started_at = time.monotonic()

    def inc(self, name, by=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def gauge(self, name, value):
        with self._lock:
            self._gauges[name] = value

    def observe(self, name, seconds):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.record(seconds)

    def scoped(self, prefix):
        """A view of this registry that prefixes every metric name with
        `prefix_` — how subsystems with their own metric vocabulary (the
        artifact store's hits/misses/bytes/evictions) publish into the
        one service registry without hardcoding its namespace."""
        return _Scoped(self, prefix)

    def observe_rounds(self, totals):
        """Fold a prove's trace.Tracer.totals() into per-round histograms
        (keys like round1..round5, checkpoint_save)."""
        for span, dur in totals.items():
            self.observe(f"prove_round/{span}", dur)

    def observe_kernels(self, events, peak_tflops=None):
        """Fold kernel spans carrying `flops` attrs (trace.Tracer events
        of a finished prove — see prover.py / trace.ntt_flops) into live
        per-stage gauges: kernel_<stage>_gflops (model-flops throughput)
        and mfu_<stage>_pct (against DPT_PEAK_TFLOPS). The serving-path
        counterpart of bench.py's one-shot MFU numbers."""
        peak = (peak_tflops if peak_tflops is not None else PEAK_TFLOPS) \
            * 1e12
        for ev in events:
            flops = ev.get("flops")
            dur = ev.get("dur_s")
            if not flops or not dur:
                continue
            stage = re.sub(r"[^a-zA-Z0-9_]", "_",
                           ev["span"].rsplit("/", 1)[-1])
            self.gauge(f"kernel_{stage}_gflops",
                       round(flops / dur / 1e9, 3))
            if peak > 0:
                self.gauge(f"mfu_{stage}_pct",
                           round(100.0 * flops / (dur * peak), 4))

    def snapshot(self):
        with self._lock:
            done = self._counters.get("jobs_completed", 0)
            uptime = time.monotonic() - self.started_at
            return {
                "uptime_s": round(uptime, 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                # analysis: ok(Histogram.snapshot is a lockless data object)
                "histograms": {k: h.snapshot()
                               for k, h in sorted(self._hists.items())},
                "throughput_jobs_per_s": round(done / uptime, 6) if uptime else 0.0,
            }

    def to_prometheus(self, extra_gauges=None):
        """Prometheus text exposition (format version 0.0.4) of the
        current snapshot: counters as `dpt_<name>_total`, gauges as
        `dpt_<name>`, histograms as summaries (`{quantile=...}` series
        from the reservoir percentiles, plus _sum/_count and a _samples
        gauge for the reservoir size). `extra_gauges` lets the caller
        splice in point-in-time values (queue depth) the registry does
        not own."""
        snap = self.snapshot()
        gauges = dict(snap["gauges"])
        if extra_gauges:
            gauges.update(extra_gauges)
        gauges["uptime_s"] = snap["uptime_s"]
        gauges["throughput_jobs_per_s"] = snap["throughput_jobs_per_s"]
        lines = []
        for name, v in sorted(snap["counters"].items()):
            n = _prom_name(name) + "_total"
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {v}")
        for name, v in sorted(gauges.items()):
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue  # non-numeric gauge (labels) — JSON snapshot only
            n = _prom_name(name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {v}")
        for name, h in sorted(snap["histograms"].items()):
            if not h.get("count"):
                continue
            n = _prom_name(name) + "_seconds"
            lines.append(f"# TYPE {n} summary")
            for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"),
                           ("0.95", "p95_s"), ("0.99", "p99_s")):
                lines.append(f'{n}{{quantile="{q}"}} {h[key]}')
            lines.append(f"{n}_sum {h['sum_s']}")
            lines.append(f"{n}_count {h['count']}")
            lines.append(f"# TYPE {n}_samples gauge")
            lines.append(f"{n}_samples {h['samples']}")
        return "\n".join(lines) + "\n"


class _Scoped:
    """Name-prefixing adapter over a Metrics registry (see Metrics.scoped)."""

    def __init__(self, base, prefix):
        self._base = base
        self._prefix = prefix

    def inc(self, name, by=1):
        self._base.inc(f"{self._prefix}_{name}", by)

    def gauge(self, name, value):
        self._base.gauge(f"{self._prefix}_{name}", value)

    def observe(self, name, seconds):
        self._base.observe(f"{self._prefix}_{name}", seconds)
