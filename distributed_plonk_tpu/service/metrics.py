"""Service observability: counters, gauges, and latency histograms.

The structured upgrade of the worker plane's raw `{tag: count}` STATS
counters (runtime/worker.py) for the serving layer: one `Metrics` registry
aggregates queue depth, wait/run latencies, per-prover-round times (fed
from trace.Tracer totals), retries/kills, and throughput, and snapshots to
one JSON-able dict for the METRICS wire tag.

Histograms keep a bounded reservoir (uniform sampling past the cap, so
long runs stay O(1) memory) and report count/sum/min/mean/percentiles
computed from the reservoir at snapshot time.

Failure-observability vocabulary (one registry can be handed to the
runtime Dispatcher AND the service pool, so a whole deployment's fault
story reads off one snapshot):
    fleet_reconnects / fleet_backoff_waits   reconnect loop activity
    fleet_backoff (histogram)                seconds slept in backoff
    fleet_breaker_opens / fleet_readmissions circuit-breaker transitions
    fleet_range_adoptions                    MSM ranges moved off a dead
                                             worker (runtime dispatcher)
    fleet_fft_replans / fleet_fft_degraded   sharded-FFT recovery events
    checkpoint_saves / checkpoint_resumes    prover round snapshots and
                                             resumed (not restarted)
                                             attempts (service pool)
    faults_injected_* / faults_ckpt_corrupted  chaos-injection activity
                                             (runtime/faults.py)

Durability vocabulary (service/journal.py + the restart-recovery path):
    journal_appends / journal_replays        records written / replayed
                                             at open
    journal_torn_records / journal_compactions  damaged-tail truncations
                                             and log rewrites
    jobs_recovered / jobs_recovered_finished re-enqueued in-flight jobs
                                             and artifact-served DONE
                                             jobs after a restart
    jobs_shed                                TTL/deadline load-shed
                                             verdicts (journaled)
    dedup_hits                               duplicate job_key SUBMITs
                                             answered from the original
    drain_started / drain_clean / drain_forced  graceful-drain outcomes
    jobs_drain_parked                        in-flight jobs checkpointed
                                             + parked by a forced drain
    proof_artifacts_lost                     DONE records whose proof
                                             artifact was evicted (job
                                             re-proved, same bytes)
"""

import random
import threading
import time

_RESERVOIR = 2048


class Histogram:
    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._samples = []
        self._rng = random.Random(0xC0FFEE)

    def record(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self._samples) < _RESERVOIR:
            self._samples.append(v)
        else:
            i = self._rng.randrange(self.count)
            if i < _RESERVOIR:
                self._samples[i] = v

    def snapshot(self):
        if not self.count:
            return {"count": 0}
        s = sorted(self._samples)

        def pct(p):
            return s[min(len(s) - 1, int(p * len(s)))]

        return {
            "count": self.count,
            "sum_s": round(self.sum, 6),
            "min_s": round(self.min, 6),
            "mean_s": round(self.sum / self.count, 6),
            "p50_s": round(pct(0.50), 6),
            "p90_s": round(pct(0.90), 6),
            "p99_s": round(pct(0.99), 6),
            "max_s": round(self.max, 6),
        }


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._hists = {}
        self.started_at = time.monotonic()

    def inc(self, name, by=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def gauge(self, name, value):
        with self._lock:
            self._gauges[name] = value

    def observe(self, name, seconds):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.record(seconds)

    def scoped(self, prefix):
        """A view of this registry that prefixes every metric name with
        `prefix_` — how subsystems with their own metric vocabulary (the
        artifact store's hits/misses/bytes/evictions) publish into the
        one service registry without hardcoding its namespace."""
        return _Scoped(self, prefix)

    def observe_rounds(self, totals):
        """Fold a prove's trace.Tracer.totals() into per-round histograms
        (keys like round1..round5, checkpoint_save)."""
        for span, dur in totals.items():
            self.observe(f"prove_round/{span}", dur)

    def snapshot(self):
        with self._lock:
            done = self._counters.get("jobs_completed", 0)
            uptime = time.monotonic() - self.started_at
            return {
                "uptime_s": round(uptime, 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in sorted(self._hists.items())},
                "throughput_jobs_per_s": round(done / uptime, 6) if uptime else 0.0,
            }


class _Scoped:
    """Name-prefixing adapter over a Metrics registry (see Metrics.scoped)."""

    def __init__(self, base, prefix):
        self._base = base
        self._prefix = prefix

    def inc(self, name, by=1):
        self._base.inc(f"{self._prefix}_{name}", by)

    def gauge(self, name, value):
        self._base.gauge(f"{self._prefix}_{name}", value)

    def observe(self, name, seconds):
        self._base.observe(f"{self._prefix}_{name}", seconds)
