"""Prover worker pool: per-job timeout, bounded retry, checkpoint resume.

Each worker owns a backend instance and proves one DISPATCH UNIT at a
time: a single job, or — from the placement layer — a GROUP
(`dispatch_group`): N same-shape jobs proved together through
`prover.prove_many` (cross-job batched kernel launches, byte-identical
to sequential), or one job on an override backend (a leased-submesh
MeshBackend). When round pipelining is on (`DPT_PIPELINE`, the
default), a worker that dequeues a plain unit also coalesces queue
neighbors up to `DPT_PIPELINE_DEPTH` jobs and proves them through
`prover.prove_pipelined`: members advance through the five round stages
staggered, so one member's device launches overlap the others' host
transcript/checkpoint work — still byte-identical per job. Every
attempt runs with a `checkpoint.ProverCheckpoint`
under the job's id, so when a worker dies mid-prove the retry does NOT
restart at round 1: it resumes at the last completed round with the
identical transcript/RNG state and produces the same bytes the
uninterrupted run would have (tests/test_checkpoint.py pins that
contract; this module is its consumer). In a group, failure is
member-scoped: a killed batch member retries ALONE (resuming from its
snapshot) while the survivors finish in the original batch.

Failure semantics:
- worker kill (fault injection / crash analog): the worker thread dies and
  is REPLACED (new generation of the same slot); its in-flight job is
  requeued with retries+1 and resumes from its snapshot.
- generic prove error: bounded retry (`max_retries`), also resuming.
- per-job timeout: checked cooperatively at round boundaries (the
  checkpoint-save hook), because a Python thread cannot be preempted
  mid-kernel; a timed-out job fails and its snapshot is removed.

Fault injection (`kill_worker`) arms a flag the victim observes at its
next round boundary — after the round's snapshot is persisted, modeling a
crash between "state made durable" and "next round started".
"""

import os
import random
import tempfile
import threading
import time
import queue as _stdlib_queue

from ..checkpoint import ProverCheckpoint, StoreCheckpoint
from ..obs import log as olog
from .. import prover as _prover
from ..prover import prove, prove_many, prove_pipelined
from ..proof_io import serialize_proof
from ..trace import Tracer
from . import jobs as J
from . import journal as JN


class WorkerKilled(Exception):
    pass


class JobTimeout(Exception):
    pass


class ProofRejected(Exception):
    """Verify-before-serve failed: the finished proof does not pairing-
    verify (silent data corruption somewhere between witness and
    serialization). The proof is BLOCKED — it never reaches a journal
    DONE record or a client; the checkpoint is cleared so the retry
    re-proves from scratch (resuming would replay the corrupt state)."""


class WorkerDrained(Exception):
    """Graceful drain hit its deadline: the worker stops at the next
    round boundary (snapshot already durable) and the job stays
    journaled as in-flight — the restarted service resumes it."""


def _default_backend():
    from ..backend.python_backend import PythonBackend
    return PythonBackend()


class _GuardHooks:
    """Round-boundary control points the pool mixes into a checkpoint
    backend: kill flags and deadlines fire AFTER the round's snapshot is
    durable (so the subsequent retry has the maximum state to resume
    from), the fault injector's checkpoint plane (slow-prover delay,
    snapshot corruption) runs at the same boundary, the job journal's
    ROUND record is appended (snapshot first, THEN the journal's promise
    that it exists), and resumes/saves land in the metrics registry."""

    def _arm_guard(self, worker, metrics=None, faults=None, journal=None,
                   job_id=None):
        self.worker = worker
        self._metrics = metrics
        self._faults = faults
        self._journal = journal
        self._job_id = job_id
        return self

    def load(self, fingerprint):
        self.worker.check(round_no=0, job_id=self._job_id)
        state = super().load(fingerprint)
        if state is not None and self._metrics is not None:
            # a non-None load means this attempt RESUMES mid-prove
            # (cross-host or same-host) instead of restarting at round 1
            self._metrics.inc("checkpoint_resumes")
        return state

    def save(self, round_no, *args, **kwargs):
        super().save(round_no, *args, **kwargs)
        if self._metrics is not None:
            self._metrics.inc("checkpoint_saves")
        if self._journal is not None:
            # write-ahead contract: the snapshot IS durable at this point,
            # so a crash at (or any time after) this journal append finds
            # resume-from-round-N state in the store/ckpt file
            self._journal.append(JN.ROUND, self._job_id, round=round_no)
        if self._faults is not None:
            self._faults.on_round(round_no, checkpoint=self)
        # job_id rides along so a job-targeted kill in a BATCHED prove
        # fires on exactly its member's boundary (the other members'
        # guards pass through unharmed)
        self.worker.check(round_no=round_no, job_id=self._job_id)


class _GuardedCheckpoint(_GuardHooks, ProverCheckpoint):
    def __init__(self, path, worker, metrics=None, faults=None,
                 journal=None, job_id=None):
        super().__init__(path)
        self._arm_guard(worker, metrics, faults, journal, job_id)


class _GuardedStoreCheckpoint(_GuardHooks, StoreCheckpoint):
    """Store-backed variant: snapshots are content-addressed artifacts
    (SHA-verified, budget-shared, STORE_FETCHable by a replacement host)."""

    def __init__(self, store, name, worker, metrics=None, faults=None,
                 journal=None, job_id=None):
        super().__init__(store, name)
        self._arm_guard(worker, metrics, faults, journal, job_id)


class _Worker:
    """One pool slot's current thread. A killed slot respawns as a new
    generation (`w2g1` -> `w2g2`) — the slot is permanent, threads are not."""

    def __init__(self, index, generation, drain_stop=None):
        self.index = index
        self.generation = generation
        self.name = f"w{index}g{generation}"
        # None | {"at_round": int|None, "job_id": str|None}: a job_id-
        # scoped arm (set when the kill targeted a specific job inside a
        # BATCHED prove) fires only on that member's round boundaries
        self.kill_arm = None
        self.deadline = None
        self.busy_jobs = []        # jobs this slot is proving right now
        self.thread = None
        # pool-wide forced-drain flag: set once the drain deadline passes,
        # observed here at round boundaries (the snapshot just became
        # durable — the cheapest possible point to stop)
        self.drain_stop = drain_stop

    def check(self, round_no=None, job_id=None):
        arm = self.kill_arm
        if arm is not None and (arm["at_round"] is None
                                or arm["at_round"] == round_no) \
                and (arm.get("job_id") is None
                     or arm["job_id"] == job_id):
            self.kill_arm = None
            raise WorkerKilled(self.name)
        if self.drain_stop is not None and self.drain_stop.is_set():
            raise WorkerDrained(self.name)
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise JobTimeout(f"deadline exceeded on {self.name}")


_STOP = object()


class _Group:
    """One placement unit on the dispatch queue (see
    WorkerPool.dispatch_group): jobs + shared resources, an optional
    backend override (leased-submesh MeshBackend), and the lease-release
    callback that must run when the attempt ends."""

    __slots__ = ("jobs", "res", "backend", "lease", "release")

    def __init__(self, jobs, res, backend, lease, release):
        self.jobs = jobs
        self.res = res
        self.backend = backend
        self.lease = lease
        self.release = release


class WorkerPool:
    def __init__(self, metrics, prover_workers=2, max_retries=2,
                 job_timeout_s=None, ckpt_dir=None, backend_factory=None,
                 verify_on_complete=False, store=None, faults=None,
                 journal=None, requeue=None, self_verify=None,
                 verify_remote=False):
        self.metrics = metrics
        self.max_retries = max_retries
        self.job_timeout_s = job_timeout_s
        # verify-before-serve (DPT_SELF_VERIFY): "1" verifies EVERY
        # finished proof with the host pairing verifier before the
        # journal DONE record / client-visible done; "0" never; "auto"
        # (default) verifies work that ran on a non-local compute plane
        # — mesh-placed sharded proves, or any prove when the pool's
        # backend is a remote fleet (verify_remote=True) — which is
        # where silent data corruption lives. A failing proof is never
        # served: it is BLOCKED (proofs_blocked), the checkpoint
        # dropped, and the job re-proved; with a fleet backend the
        # integrity plane has meanwhile quarantined the suspect workers,
        # so the re-prove runs on the survivors.
        self.self_verify = (os.environ.get("DPT_SELF_VERIFY", "auto")
                            if self_verify is None else str(self_verify))
        self.verify_remote = bool(verify_remote)
        # requeue: the admission JobQueue (set by ProofService) — a
        # retried MESH-placed job goes back through the scheduler for
        # RE-PLACEMENT (fresh lease + sharded backend) instead of
        # retrying on this worker's shared single-device backend, which
        # is exactly the memory/latency ceiling mesh placement avoids
        self._requeue = requeue
        # checkpoint surface: with a store, snapshots are content-addressed
        # store artifacts (one durability surface + one eviction policy,
        # and a replacement host can STORE_FETCH them); the ckpt-dir file
        # path remains the storeless fallback
        self.store = store
        self.faults = faults
        # journal: service job journal (service/journal.py) — the pool
        # appends START/ROUND/DONE/SHED/FAILED; None runs journal-free
        self.journal = journal
        self.ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="dpt-service-ck-")
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.backend_factory = backend_factory or _default_backend
        self.verify_on_complete = verify_on_complete
        # small buffer past the worker count: keeps workers fed while the
        # scheduler builds the next bucket, without hoarding the queue's
        # jobs where priorities can no longer reorder them
        self._dispatch_q = _stdlib_queue.Queue(maxsize=2 * prover_workers)
        self._lock = threading.Lock()
        self._workers = []
        self._stopping = False
        self._drain_stop = threading.Event()
        for i in range(prover_workers):
            self._workers.append(self._spawn(i, 1))

    # -- lifecycle ------------------------------------------------------------

    def _spawn(self, index, generation):
        w = _Worker(index, generation, drain_stop=self._drain_stop)
        w.thread = threading.Thread(target=self._loop, args=(w,),
                                    name=f"pool-{w.name}", daemon=True)
        w.thread.start()
        self.metrics.inc("workers_spawned")
        return w

    def _respawn(self, dead):
        with self._lock:
            if self._stopping:
                return
            replacement = self._spawn(dead.index, dead.generation + 1)
            self._workers[dead.index] = replacement

    def shutdown(self):
        # _stopping is the respawn gate _respawn checks under the lock:
        # setting it inside the same lock closes the window where a
        # concurrently dying worker respawns after shutdown decided to
        # stop (LOCK02 finding of the lock-discipline lint)
        with self._lock:
            self._stopping = True
            workers = list(self._workers)
        for _ in workers:
            self._dispatch_q.put(_STOP)
        for w in workers:
            w.thread.join(timeout=10)

    def crash(self):
        """Crash simulation (ProofService.crash): workers stop at their
        next round boundary through the DRAIN path — which parks the job
        with no retry bookkeeping, no terminal journal records, and
        crucially no checkpoint clears (a real dead process can't delete
        the snapshots its successor resumes from)."""
        with self._lock:
            self._stopping = True
        self._drain_stop.set()

    def busy(self):
        """Names of workers currently holding at least one job."""
        with self._lock:
            pool = list(self._workers)
        return [w.name for w in pool if w.busy_jobs]

    def drain(self, deadline):
        """Graceful drain: let in-flight proves finish until `deadline`
        (monotonic), then force the stragglers to stop at their next
        round boundary — the snapshot is durable and the journal still
        shows them in-flight, so a restart resumes with zero recompute.
        Returns True iff everything finished without the forced stop."""
        clean = True
        while self.busy() and time.monotonic() < deadline:
            time.sleep(0.02)
        if self.busy():
            clean = False
            self._drain_stop.set()
            # round boundaries are the check points; wait for the busy
            # set to clear, bounded (a worker inside one long round can
            # exceed this — threads are daemons, the journal is already
            # consistent either way)
            stop_wait = time.monotonic() + 10
            while self.busy() and time.monotonic() < stop_wait:
                time.sleep(0.02)
        self.shutdown()
        return clean

    def workers(self):
        with self._lock:
            return list(self._workers)

    # -- dispatch -------------------------------------------------------------

    def dispatch(self, job, resources):
        """Hand a scheduled job to the pool (blocks for backpressure)."""
        self._dispatch_q.put((job, resources))

    def dispatch_group(self, jobs, resources, backend=None, lease=None,
                       release=None):
        """Hand one PLACEMENT UNIT to the pool (blocks for backpressure):
        N same-shape jobs proved together by one worker through
        prover.prove_many (the data-parallel small-job class), or a
        single job with a `backend` override (a sharded MeshBackend over
        a leased submesh). `release(lease)` runs when the group's attempt
        ends — success, member failure, or drain — so submesh devices
        always return to the leaser."""
        self._dispatch_q.put(_Group(list(jobs), resources, backend,
                                    lease, release))

    def kill_worker(self, worker=None, job_id=None, at_round=None):
        """Fault injection: arm a kill on a specific worker, on whichever
        worker is proving `job_id`, or on any busy (else any) worker.
        Returns the victim's name; raises LookupError if no match.

        A job-targeted kill is scoped to that JOB: on a worker running a
        batched prove only the targeted member dies (it resumes alone
        from its snapshot; the other members finish unaffected) — on a
        single-job worker the semantics are the historical thread kill."""
        with self._lock:
            pool = list(self._workers)
        victim = None
        arm_job = None
        if worker is not None:
            victim = next((w for w in pool if w.name == worker), None)
        elif job_id is not None:
            victim = next((w for w in pool
                           if any(j.id == job_id for j in w.busy_jobs)),
                          None)
            arm_job = job_id
        else:
            victim = next((w for w in pool if w.busy_jobs),
                          pool[0] if pool else None)
        if victim is None:
            raise LookupError("no such worker/job to kill")
        victim.kill_arm = {"at_round": at_round, "job_id": arm_job}
        self.metrics.inc("kill_requests")
        return victim.name

    # -- execution ------------------------------------------------------------

    def _ckpt_path(self, job):
        return os.path.join(self.ckpt_dir, f"{job.id}.ckpt.npz")

    def _make_guard(self, job, worker):
        if self.store is not None:
            return _GuardedStoreCheckpoint(self.store, job.id, worker,
                                           metrics=self.metrics,
                                           faults=self.faults,
                                           journal=self.journal,
                                           job_id=job.id)
        return _GuardedCheckpoint(self._ckpt_path(job), worker,
                                  metrics=self.metrics, faults=self.faults,
                                  journal=self.journal, job_id=job.id)

    def _clear_ckpt(self, job):
        if self.store is not None:
            StoreCheckpoint(self.store, job.id).clear()
            return
        try:
            os.remove(self._ckpt_path(job))
        except OSError:
            pass

    def shed(self, job, reason):
        """Terminal TTL/deadline verdict: journaled (clients can query it
        across a restart), counted, never proved. Shared by the scheduler
        (expired before key build) and the pool loop (expired in the
        dispatch buffer)."""
        self.metrics.inc("jobs_shed")
        self.metrics.inc("slo_sheds_%s" % getattr(job, "slo", "standard"))
        olog.emit("service", "shed", level="warn", job_id=job.id,
                  trace_id=job.trace_id, reason=reason,
                  slo=getattr(job, "slo", "standard"))
        if self.journal is not None:
            self.journal.append(JN.SHED, job.id, reason=reason)
        self._clear_ckpt(job)
        job.finish_shed(reason)

    def _loop(self, worker):
        backend = self.backend_factory()
        while True:
            item = self._dispatch_q.get()
            if item is _STOP:
                return
            if not self._run_item(worker, backend, item):
                return

    def _put_back(self, item):
        """Return an item to the dispatch queue without ever blocking a
        worker thread on its own queue (same hazard as _retry_or_fail:
        workers are the consumers)."""
        try:
            self._dispatch_q.put_nowait(item)
        except _stdlib_queue.Full:
            threading.Thread(target=self._dispatch_q.put, args=(item,),
                             daemon=True).start()

    def _coalesce(self, budget):
        """Opportunistically pop up to `budget` more JOBS' worth of
        pipeline-eligible units (plain tuples and pool-backend groups)
        off the dispatch queue, so mixed small/mid-shape traffic fills
        the round pipeline instead of proving one job at depth 1 while
        its queue neighbors wait. _STOP and override-backend (leased
        submesh) groups are put back and end the scan — their routing is
        per-unit."""
        units = []
        taken = 0
        while taken < budget:
            try:
                item = self._dispatch_q.get_nowait()
            except _stdlib_queue.Empty:
                break
            if item is _STOP or (isinstance(item, _Group)
                                 and item.backend is not None):
                self._put_back(item)
                break
            if isinstance(item, _Group):
                units.append(item)
                taken += len(item.jobs)
            else:
                units.append(_Group([item[0]], item[1], None, None, None))
                taken += 1
        return units

    def _run_item(self, worker, backend, item):
        """Route one dequeued dispatch unit. Returns False when this
        worker thread must exit (killed slot or drain)."""
        if isinstance(item, _Group) and item.backend is not None:
            # leased-submesh sharded prove: the historical non-pipelined
            # paths on the override backend — the lease is per-unit, so
            # these units never coalesce with queue neighbors
            try:
                if len(item.jobs) == 1:
                    return self._run_one(worker, item.backend,
                                         item.jobs[0], item.res)
                return self._run_group(worker, item.backend, item.jobs,
                                       item.res)
            finally:
                if item.release is not None:
                    item.release(item.lease)
        units = ([item] if isinstance(item, _Group)
                 else [_Group([item[0]], item[1], None, None, None)])
        if _prover.PIPELINE:
            units.extend(self._coalesce(
                _prover.PIPELINE_DEPTH - len(units[0].jobs)))
        try:
            if _prover.PIPELINE and sum(len(u.jobs) for u in units) > 1:
                return self._run_pipeline(worker, backend, units)
            unit = units[0]
            if len(unit.jobs) == 1:
                return self._run_one(worker, backend, unit.jobs[0],
                                     unit.res)
            return self._run_group(worker, backend, unit.jobs, unit.res)
        finally:
            for u in units:
                if u.release is not None:
                    u.release(u.lease)

    def _run_one(self, worker, backend, job, res):
        """One single-job attempt on this worker thread. Returns False
        when the thread must exit (killed slot — already respawned — or
        drain)."""
        if job.expired():
            self.shed(job, "ttl expired before prove start")
            return True
        worker.busy_jobs = [job]
        if job.started_at is None:
            job.started_at = time.monotonic()
            self.metrics.observe("job_wait", job.wait_s)
        job.worker = worker.name
        job.state = J.RUNNING
        if self.journal is not None:
            self.journal.append(JN.START, job.id, worker=worker.name)
        try:
            self._run_attempt(worker, backend, job, res)
            job.attempts.append({"worker": worker.name, "outcome": "ok"})
            self.metrics.inc("jobs_completed")
            self.metrics.observe("job_run", job.run_s)
        except WorkerDrained:
            # deadline-forced drain: the round snapshot is durable and
            # the job's journal entry still reads in-flight — park it
            # (no requeue, no terminal record); the restarted service
            # resumes it from the checkpoint
            job.attempts.append({"worker": worker.name,
                                 "outcome": "drained"})
            job.state = J.QUEUED
            job.worker = None
            worker.busy_jobs = []
            self.metrics.inc("jobs_drain_parked")
            return False  # draining: this thread is done
        except WorkerKilled:
            job.attempts.append({"worker": worker.name,
                                 "outcome": "killed"})
            self.metrics.inc("workers_killed")
            worker.busy_jobs = []
            # replacement first: with a 1-worker pool the requeue below
            # can block on a full dispatch queue until someone consumes
            self._respawn(worker)
            self._retry_or_fail(job, res, "worker killed mid-prove")
            return False  # this thread is the "dead process"
        except JobTimeout:
            job.attempts.append({"worker": worker.name,
                                 "outcome": "timeout"})
            self.metrics.inc("jobs_timeout")
            self._fail(job, f"timeout after {self.job_timeout_s}s")
        except Exception as e:  # prove/verify error: bounded retry
            job.attempts.append({"worker": worker.name,
                                 "outcome": f"error: {e!r}"})
            self.metrics.inc("job_attempt_errors")
            self._retry_or_fail(job, res, f"prove failed: {e!r}")
        finally:
            worker.busy_jobs = []
            # a kill that armed too late to fire on its target (e.g.
            # during round 5, past the last boundary check) must not
            # leak onto the worker's next, unrelated job
            worker.kill_arm = None
        return True

    def _run_group(self, worker, backend, jobs, res):
        """One data-parallel batch attempt: N same-shape jobs proved
        together through prover.prove_many on this worker's backend,
        cross-job kernel launches batched, proof bytes byte-identical to
        N sequential attempts. Member failures are isolated: a killed /
        timed-out / erroring member is retried or failed ALONE (its
        snapshot is durable; the retry resumes it through the sequential
        path) while the surviving members complete in this very call.
        Returns False when the pool is draining (thread exits)."""
        live = []
        for job in jobs:
            if job.expired():
                self.shed(job, "ttl expired before prove start")
            else:
                live.append(job)
        if not live:
            return True
        worker.busy_jobs = list(live)
        for job in live:
            if job.started_at is None:
                job.started_at = time.monotonic()
                self.metrics.observe("job_wait", job.wait_s)
            job.worker = worker.name
            job.state = J.RUNNING
            if self.journal is not None:
                self.journal.append(JN.START, job.id, worker=worker.name)
        self.metrics.inc("batch_proves")
        self.metrics.inc("batch_jobs", len(live))
        self.metrics.observe("batch_jobs_per_launch", len(live))
        tracers = [self._job_tracer(worker, job) for job in live]
        ckts = [J.build_circuit(job.spec) for job in live]
        guards = [self._make_guard(job, worker) for job in live]
        rngs = [random.Random(job.spec.seed) for job in live]
        if self.job_timeout_s is not None:
            worker.deadline = (min(j.started_at for j in live)
                               + self.job_timeout_s)
        try:
            proofs, errors = prove_many(rngs, ckts, res.pk, backend,
                                        tracers=tracers, checkpoints=guards,
                                        abort_on=(WorkerDrained,))
        except WorkerDrained:
            # drain aborts the whole batch: every member parks in-flight
            # (snapshots durable, journal unchanged) — the restarted
            # service resumes or re-proves deterministically
            for job in live:
                job.attempts.append({"worker": worker.name,
                                     "outcome": "drained"})
                job.state = J.QUEUED
                job.worker = None
                self.metrics.inc("jobs_drain_parked")
            worker.busy_jobs = []
            return False
        except Exception as e:  # batch-wide infrastructure failure
            for job in live:
                job.attempts.append({"worker": worker.name,
                                     "outcome": f"error: {e!r}"})
                self.metrics.inc("job_attempt_errors")
                self._retry_or_fail(job, res, f"batch prove failed: {e!r}")
            worker.busy_jobs = []
            worker.kill_arm = None
            return True
        finally:
            worker.deadline = None
        for job, tracer, ckt, proof, err in zip(live, tracers, ckts,
                                                proofs, errors):
            if proof is not None:
                try:
                    self._finish_proved(job, res, ckt, proof, tracer,
                                        backend=backend)
                    job.attempts.append({"worker": worker.name,
                                         "outcome": "ok"})
                    self.metrics.inc("jobs_completed")
                    self.metrics.observe("job_run", job.run_s)
                except Exception as e:  # verify/journal failure
                    job.attempts.append({"worker": worker.name,
                                         "outcome": f"error: {e!r}"})
                    self.metrics.inc("job_attempt_errors")
                    self._retry_or_fail(job, res, f"prove failed: {e!r}")
            elif isinstance(err, WorkerKilled):
                # job-scoped kill: only this member died; it resumes
                # ALONE from its snapshot via the single-job retry path
                job.attempts.append({"worker": worker.name,
                                     "outcome": "killed"})
                self.metrics.inc("batch_member_kills")
                self._retry_or_fail(job, res,
                                    "batch member killed mid-prove")
            elif isinstance(err, JobTimeout):
                job.attempts.append({"worker": worker.name,
                                     "outcome": "timeout"})
                self.metrics.inc("jobs_timeout")
                self._fail(job, f"timeout after {self.job_timeout_s}s")
            else:
                job.attempts.append({"worker": worker.name,
                                     "outcome": f"error: {err!r}"})
                self.metrics.inc("job_attempt_errors")
                self._retry_or_fail(job, res, f"prove failed: {err!r}")
        worker.busy_jobs = []
        worker.kill_arm = None
        return True

    def _pipeline_observer(self):
        """Stage-level pipeline telemetry -> metrics: the live fill
        gauge, the achieved-depth histogram, per-round stage-wait
        histograms, and the device-idle estimate (host-finalize span not
        covered by the device force — the overlap the pipeline buys)."""
        m = self.metrics

        def observe(ev):
            r = ev["round"]
            m.gauge("pipeline_depth", ev["depth"])
            m.observe("pipeline_depth_achieved", ev["depth"])
            m.observe("pipeline_stage_wait_s", ev["stage_wait_s"])
            m.observe("pipeline_stage_wait_s/round%d" % r,
                      ev["stage_wait_s"])
            m.gauge("pipeline_device_idle_s/round%d" % r,
                    ev["device_idle_s"])
        return observe

    def _run_pipeline(self, worker, backend, units):
        """One round-pipelined attempt: the units' jobs advance through
        the five round stages with their device launches overlapping
        each other's host finalize work (prover.prove_pipelined), proof
        bytes byte-identical to sequential attempts. Failure isolation
        matches _run_group: a killed/timed-out/erroring member is
        retried or failed ALONE (its round snapshot is durable; the
        retry resumes it via the sequential path) while the surviving
        members complete in this very call. Returns False when the pool
        is draining (thread exits)."""
        live, reses = [], []
        for u in units:
            for job in u.jobs:
                if job.expired():
                    self.shed(job, "ttl expired before prove start")
                else:
                    live.append(job)
                    reses.append(u.res)
        if not live:
            return True
        worker.busy_jobs = list(live)
        for job in live:
            if job.started_at is None:
                job.started_at = time.monotonic()
                self.metrics.observe("job_wait", job.wait_s)
            job.worker = worker.name
            job.state = J.RUNNING
            if self.journal is not None:
                self.journal.append(JN.START, job.id, worker=worker.name)
        # batch_* counters keep their meaning (scheduler-formed shape
        # batches), independent of queue-coalesced singles riding along
        for u in units:
            n = sum(1 for j in u.jobs if j in live)
            if n > 1:
                self.metrics.inc("batch_proves")
                self.metrics.inc("batch_jobs", n)
                self.metrics.observe("batch_jobs_per_launch", n)
        self.metrics.inc("pipelined_proves")
        self.metrics.inc("pipelined_jobs", len(live))
        tracers = [self._job_tracer(worker, job) for job in live]
        ckts = [J.build_circuit(job.spec) for job in live]
        guards = [self._make_guard(job, worker) for job in live]
        rngs = [random.Random(job.spec.seed) for job in live]
        pks = [res.pk for res in reses]
        if self.job_timeout_s is not None:
            worker.deadline = (min(j.started_at for j in live)
                               + self.job_timeout_s)
        try:
            proofs, errors = prove_pipelined(
                rngs, ckts, pks, backend, tracers=tracers,
                checkpoints=guards, abort_on=(WorkerDrained,),
                observer=self._pipeline_observer())
        except WorkerDrained:
            # drain aborts the pipeline: every member parks at its own
            # stage latch (snapshots durable, journal unchanged) — the
            # restarted service resumes or re-proves deterministically
            for job in live:
                job.attempts.append({"worker": worker.name,
                                     "outcome": "drained"})
                job.state = J.QUEUED
                job.worker = None
                self.metrics.inc("jobs_drain_parked")
            worker.busy_jobs = []
            return False
        except Exception as e:  # pipeline-wide infrastructure failure
            for job, res in zip(live, reses):
                job.attempts.append({"worker": worker.name,
                                     "outcome": f"error: {e!r}"})
                self.metrics.inc("job_attempt_errors")
                self._retry_or_fail(job, res,
                                    f"pipelined prove failed: {e!r}")
            worker.busy_jobs = []
            worker.kill_arm = None
            return True
        finally:
            worker.deadline = None
        for job, res, tracer, ckt, proof, err in zip(live, reses, tracers,
                                                     ckts, proofs, errors):
            if proof is not None:
                try:
                    self._finish_proved(job, res, ckt, proof, tracer,
                                        backend=backend)
                    job.attempts.append({"worker": worker.name,
                                         "outcome": "ok"})
                    self.metrics.inc("jobs_completed")
                    self.metrics.observe("job_run", job.run_s)
                except Exception as e:  # verify/journal failure
                    job.attempts.append({"worker": worker.name,
                                         "outcome": f"error: {e!r}"})
                    self.metrics.inc("job_attempt_errors")
                    self._retry_or_fail(job, res, f"prove failed: {e!r}")
            elif isinstance(err, WorkerKilled):
                # job-scoped kill: only this member died; it resumes
                # ALONE from its snapshot via the single-job retry path
                job.attempts.append({"worker": worker.name,
                                     "outcome": "killed"})
                self.metrics.inc("batch_member_kills")
                self._retry_or_fail(job, res,
                                    "pipeline member killed mid-prove")
            elif isinstance(err, JobTimeout):
                job.attempts.append({"worker": worker.name,
                                     "outcome": "timeout"})
                self.metrics.inc("jobs_timeout")
                self._fail(job, f"timeout after {self.job_timeout_s}s")
            else:
                job.attempts.append({"worker": worker.name,
                                     "outcome": f"error: {err!r}"})
                self.metrics.inc("job_attempt_errors")
                self._retry_or_fail(job, res, f"prove failed: {err!r}")
        worker.busy_jobs = []
        worker.kill_arm = None
        return True

    def _retry_or_fail(self, job, res, reason):
        job.retries += 1
        if job.retries > self.max_retries:
            self._fail(job, f"{reason} (retries exhausted)")
            return
        self.metrics.inc("job_retries")
        olog.emit("service", "retry", level="warn", job_id=job.id,
                  trace_id=job.trace_id, retries=job.retries,
                  reason=reason[:200])
        job.state = J.QUEUED
        if job.placement == "mesh" and self._requeue is not None:
            # back through the scheduler: the retry must be RE-PLACED on
            # a fresh submesh lease (the snapshot still resumes it — the
            # checkpoint is keyed by job id, not by backend)
            job.worker = None
            job.placement = None
            try:
                self._requeue.submit(job, force=True)
                return
            except Exception:  # queue closed (drain/shutdown): fall back
                pass           # to the in-pool retry below
        # snapshot stays in place: the retry resumes, not restarts.
        # NEVER block a worker thread on the requeue: workers are the
        # dispatch queue's consumers, so a blocking put from one with the
        # queue full can deadlock the whole pool — hand a full queue off
        # to a detached putter instead
        try:
            self._dispatch_q.put_nowait((job, res))
        except _stdlib_queue.Full:
            threading.Thread(target=self._dispatch_q.put, args=((job, res),),
                             daemon=True).start()

    def _fail(self, job, reason):
        self.metrics.inc("jobs_failed")
        olog.emit("service", "job_failed", level="error", job_id=job.id,
                  trace_id=job.trace_id, reason=reason[:200])
        self._clear_ckpt(job)
        if self.journal is not None:
            self.journal.append(JN.FAILED, job.id, reason=reason)
        job.finish_err(reason)

    def _job_tracer(self, worker, job):
        """The prover traces under the JOB's id (stamped/adopted at
        SUBMIT), parented to the client's span when one was propagated —
        every retry attempt re-records from scratch, so the stored
        timeline is the attempt that produced the proof plus the queue
        wait that preceded it. The queued span carries the PLACEMENT
        decision as attrs (placement class + shape-batch size), so the
        trace timeline shows how the scheduler routed the job."""
        tracer = Tracer(trace_id=job.trace_id,
                        parent_id=job.trace_parent,
                        proc=f"pool/{worker.name}")
        tracer.add_event("service/queued", ts=job.submitted_wall,
                         dur_s=job.wait_s, job_id=job.id,
                         placement=job.placement,
                         batch_size=job.batch_size)
        return tracer

    def _finish_proved(self, job, res, ckt, proof, tracer, backend=None):
        """Post-prove completion shared by the single and batched paths:
        verify-before-serve, round/kernel metrics, finished-proof
        durability, trace artifact, client-visible done. ORDER IS THE
        CONTRACT: the self-verify gate runs on the serialized bytes
        BEFORE the journal DONE append, so a corrupted proof can never
        be journaled as done, served from an artifact after a restart,
        or handed to a client."""
        totals = tracer.totals(depth=1)
        self.metrics.observe_rounds(totals)
        # kernel spans carry flops attrs (prover.py): fold them into
        # live per-stage MFU/throughput gauges — the serving-path
        # replacement for bench-only MFU numbers
        self.metrics.observe_kernels(tracer.events)
        proof_bytes = serialize_proof(proof)
        pub = ckt.public_input()
        if self.faults is not None and self.faults.on_proof(job.id):
            # at=proof chaos plane: SDC between prove and serve — flip
            # one byte so only the verify gate below can catch it
            mid = len(proof_bytes) // 2
            proof_bytes = (proof_bytes[:mid]
                           + bytes([proof_bytes[mid] ^ 0xFF])
                           + proof_bytes[mid + 1:])
        if self._should_self_verify(job, backend):
            self._self_verify(job, res, pub, proof_bytes, tracer)
        self._journal_done(job, proof_bytes, pub)
        self._store_trace(job, tracer)
        job.finish_ok(proof_bytes, pub, totals)
        # per-kind served counter: the circuit-zoo mix as the server saw
        # it (aggregation eligibility and console's by-kind pane both
        # read job state; this is the cheap cumulative view)
        self.metrics.inc("circuit_kind_%s" % job.spec.kind)
        # per-SLO-class roundtrip (submit -> served): the standard-class
        # p95_s of this histogram is the autoscaler's latency sensor
        self.metrics.observe(
            "slo_roundtrip/%s" % getattr(job, "slo", "standard"),
            time.monotonic() - job.submitted_at)

    def _should_self_verify(self, job, backend=None):
        if self.verify_on_complete:
            return True
        mode = self.self_verify
        if mode in ("0", "off"):
            return False
        if mode in ("1", "on", "always"):
            return True
        # auto: only the non-local compute planes pay the pairing check —
        # mesh placements, an operator-declared remote pool, or a prove
        # that actually ran on a fleet backend (RemoteBackend.name):
        # fleet-placed work is where SDC lives, and the flag must not
        # depend on every call site remembering to set verify_remote
        return (self.verify_remote or job.placement == "mesh"
                or getattr(backend, "name", "") == "remote")

    def _self_verify(self, job, res, pub, proof_bytes, tracer):
        """The end-to-end truth oracle, moved into the serving path: the
        host pairing verifier runs on the SERIALIZED bytes (what would
        be journaled/served), its verdict and latency land in metrics +
        the job's trace timeline, and a failure blocks the proof."""
        from ..proof_io import deserialize_proof
        from ..verifier import verify
        w0, p0 = time.time(), time.perf_counter()
        try:
            ok = verify(res.vk, pub, deserialize_proof(proof_bytes),
                        rng=random.Random(1))
        except Exception:  # undecodable bytes are equally blocked
            ok = False
        dur = time.perf_counter() - p0
        self.metrics.inc("self_verify_checks")
        self.metrics.observe("self_verify_s", dur)
        tracer.add_event("service/self_verify", ts=w0, dur_s=dur,
                         job_id=job.id, ok=ok)
        if ok:
            return
        self.metrics.inc("self_verify_failures")
        self.metrics.inc("proofs_blocked")
        olog.emit("service", "self_verify_blocked", level="error",
                  job_id=job.id, trace_id=job.trace_id)
        # never resume the corrupt state: the retry re-proves fresh
        # (deterministic bytes — a transient SDC yields a good proof,
        # a persistent one exhausts retries into a FAILED verdict,
        # which is still never a wrong answer served)
        self._clear_ckpt(job)
        raise ProofRejected(
            f"proof for job {job.id} failed verify-before-serve")

    def _run_attempt(self, worker, backend, job, res):
        if self.job_timeout_s is not None:
            worker.deadline = job.started_at + self.job_timeout_s
        try:
            tracer = self._job_tracer(worker, job)
            ckt = J.build_circuit(job.spec)
            guard = self._make_guard(job, worker)
            try:
                proof = prove(random.Random(job.spec.seed), ckt, res.pk,
                              backend, tracer=tracer, checkpoint=guard)
            except ValueError as e:
                if "different circuit" in str(e):
                    # a stale snapshot from some earlier run squats on our
                    # path: drop it so the retry restarts fresh instead of
                    # failing identically until retries are exhausted
                    guard.clear()
                raise
            self._finish_proved(job, res, ckt, proof, tracer,
                                backend=backend)
        finally:
            worker.deadline = None

    def _store_trace(self, job, tracer):
        """Merge + persist the job's timeline: always retained on the Job
        (STATUS reports trace_spans; /trace serves it), and — with a
        store — written as the content-addressed `trace:<job_id>`
        artifact (STORE_FETCHable, like the proof it explains).
        Observability is best-effort: failure to persist never fails a
        finished prove."""
        from ..trace import merge_traces
        merged = merge_traces([tracer.dump()])
        # trace-correlated structured log events (obs/log.py) ride the
        # stored timeline too: every shed/retry/self-verify verdict for
        # this trace id, queryable next to the spans it explains (the
        # chrome export renders them as instant events)
        merged["logs"] = olog.fetch(trace_id=job.trace_id)["events"]
        job.trace_dump = merged
        self.metrics.inc("trace_spans_recorded", len(merged["events"]))
        if self.store is None:
            return
        from ..store import keycache as KC
        try:
            KC.store_trace(self.store, job.id, merged)
            self.metrics.inc("traces_stored")
        except Exception:  # pragma: no cover - environmental (disk)
            self.metrics.inc("store_write_errors")

    def _journal_done(self, job, proof_bytes, pub):
        """Finished-proof durability, BEFORE the client-visible state
        flips to done: the proof becomes a content-addressed store
        artifact (STORE_FETCHable cross-host; a restart serves it
        instead of re-proving) and the journal DONE record carries its
        digest — or, storeless, the raw bytes inline (944B per proof:
        small enough that the journal stays the single durable surface).
        A crash anywhere before the DONE append re-proves from the
        round-4 snapshot and lands on the identical bytes."""
        if self.journal is None:
            return
        fields = {"pub": [hex(x) for x in pub], "retries": job.retries}
        if self.store is not None:
            from ..store import keycache as KC
            try:
                fields["digest"] = KC.store_proof(
                    self.store, job.id, proof_bytes, pub,
                    spec_wire=job.spec.to_wire(), retries=job.retries)
                fields["store_key"] = KC.proof_store_key(job.id)
            except Exception:  # pragma: no cover - environmental (disk)
                self.metrics.inc("store_write_errors")
                fields["proof_hex"] = proof_bytes.hex()
        else:
            fields["proof_hex"] = proof_bytes.hex()
        self.journal.append(JN.DONE, job.id, **fields)
