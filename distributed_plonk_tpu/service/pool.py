"""Prover worker pool: per-job timeout, bounded retry, checkpoint resume.

Each worker owns a backend instance and proves one job at a time. Every
attempt runs with a `checkpoint.ProverCheckpoint` under the job's id, so
when a worker dies mid-prove the retry does NOT restart at round 1: it
resumes at the last completed round with the identical transcript/RNG
state and produces the same bytes the uninterrupted run would have
(tests/test_checkpoint.py pins that contract; this module is its consumer).

Failure semantics:
- worker kill (fault injection / crash analog): the worker thread dies and
  is REPLACED (new generation of the same slot); its in-flight job is
  requeued with retries+1 and resumes from its snapshot.
- generic prove error: bounded retry (`max_retries`), also resuming.
- per-job timeout: checked cooperatively at round boundaries (the
  checkpoint-save hook), because a Python thread cannot be preempted
  mid-kernel; a timed-out job fails and its snapshot is removed.

Fault injection (`kill_worker`) arms a flag the victim observes at its
next round boundary — after the round's snapshot is persisted, modeling a
crash between "state made durable" and "next round started".
"""

import os
import random
import tempfile
import threading
import time
import queue as _stdlib_queue

from ..checkpoint import ProverCheckpoint, StoreCheckpoint
from ..prover import prove
from ..proof_io import serialize_proof
from ..trace import Tracer
from . import jobs as J
from . import journal as JN


class WorkerKilled(Exception):
    pass


class JobTimeout(Exception):
    pass


class WorkerDrained(Exception):
    """Graceful drain hit its deadline: the worker stops at the next
    round boundary (snapshot already durable) and the job stays
    journaled as in-flight — the restarted service resumes it."""


def _default_backend():
    from ..backend.python_backend import PythonBackend
    return PythonBackend()


class _GuardHooks:
    """Round-boundary control points the pool mixes into a checkpoint
    backend: kill flags and deadlines fire AFTER the round's snapshot is
    durable (so the subsequent retry has the maximum state to resume
    from), the fault injector's checkpoint plane (slow-prover delay,
    snapshot corruption) runs at the same boundary, the job journal's
    ROUND record is appended (snapshot first, THEN the journal's promise
    that it exists), and resumes/saves land in the metrics registry."""

    def _arm_guard(self, worker, metrics=None, faults=None, journal=None,
                   job_id=None):
        self.worker = worker
        self._metrics = metrics
        self._faults = faults
        self._journal = journal
        self._job_id = job_id
        return self

    def load(self, fingerprint):
        self.worker.check(round_no=0)
        state = super().load(fingerprint)
        if state is not None and self._metrics is not None:
            # a non-None load means this attempt RESUMES mid-prove
            # (cross-host or same-host) instead of restarting at round 1
            self._metrics.inc("checkpoint_resumes")
        return state

    def save(self, round_no, *args, **kwargs):
        super().save(round_no, *args, **kwargs)
        if self._metrics is not None:
            self._metrics.inc("checkpoint_saves")
        if self._journal is not None:
            # write-ahead contract: the snapshot IS durable at this point,
            # so a crash at (or any time after) this journal append finds
            # resume-from-round-N state in the store/ckpt file
            self._journal.append(JN.ROUND, self._job_id, round=round_no)
        if self._faults is not None:
            self._faults.on_round(round_no, checkpoint=self)
        self.worker.check(round_no=round_no)


class _GuardedCheckpoint(_GuardHooks, ProverCheckpoint):
    def __init__(self, path, worker, metrics=None, faults=None,
                 journal=None, job_id=None):
        super().__init__(path)
        self._arm_guard(worker, metrics, faults, journal, job_id)


class _GuardedStoreCheckpoint(_GuardHooks, StoreCheckpoint):
    """Store-backed variant: snapshots are content-addressed artifacts
    (SHA-verified, budget-shared, STORE_FETCHable by a replacement host)."""

    def __init__(self, store, name, worker, metrics=None, faults=None,
                 journal=None, job_id=None):
        super().__init__(store, name)
        self._arm_guard(worker, metrics, faults, journal, job_id)


class _Worker:
    """One pool slot's current thread. A killed slot respawns as a new
    generation (`w2g1` -> `w2g2`) — the slot is permanent, threads are not."""

    def __init__(self, index, generation, drain_stop=None):
        self.index = index
        self.generation = generation
        self.name = f"w{index}g{generation}"
        self.kill_arm = None       # None | {"at_round": int|None}
        self.deadline = None
        self.busy_job = None
        self.thread = None
        # pool-wide forced-drain flag: set once the drain deadline passes,
        # observed here at round boundaries (the snapshot just became
        # durable — the cheapest possible point to stop)
        self.drain_stop = drain_stop

    def check(self, round_no=None):
        arm = self.kill_arm
        if arm is not None and (arm["at_round"] is None
                                or arm["at_round"] == round_no):
            self.kill_arm = None
            raise WorkerKilled(self.name)
        if self.drain_stop is not None and self.drain_stop.is_set():
            raise WorkerDrained(self.name)
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise JobTimeout(f"deadline exceeded on {self.name}")


_STOP = object()


class WorkerPool:
    def __init__(self, metrics, prover_workers=2, max_retries=2,
                 job_timeout_s=None, ckpt_dir=None, backend_factory=None,
                 verify_on_complete=False, store=None, faults=None,
                 journal=None):
        self.metrics = metrics
        self.max_retries = max_retries
        self.job_timeout_s = job_timeout_s
        # checkpoint surface: with a store, snapshots are content-addressed
        # store artifacts (one durability surface + one eviction policy,
        # and a replacement host can STORE_FETCH them); the ckpt-dir file
        # path remains the storeless fallback
        self.store = store
        self.faults = faults
        # journal: service job journal (service/journal.py) — the pool
        # appends START/ROUND/DONE/SHED/FAILED; None runs journal-free
        self.journal = journal
        self.ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="dpt-service-ck-")
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.backend_factory = backend_factory or _default_backend
        self.verify_on_complete = verify_on_complete
        # small buffer past the worker count: keeps workers fed while the
        # scheduler builds the next bucket, without hoarding the queue's
        # jobs where priorities can no longer reorder them
        self._dispatch_q = _stdlib_queue.Queue(maxsize=2 * prover_workers)
        self._lock = threading.Lock()
        self._workers = []
        self._stopping = False
        self._drain_stop = threading.Event()
        for i in range(prover_workers):
            self._workers.append(self._spawn(i, 1))

    # -- lifecycle ------------------------------------------------------------

    def _spawn(self, index, generation):
        w = _Worker(index, generation, drain_stop=self._drain_stop)
        w.thread = threading.Thread(target=self._loop, args=(w,),
                                    name=f"pool-{w.name}", daemon=True)
        w.thread.start()
        self.metrics.inc("workers_spawned")
        return w

    def _respawn(self, dead):
        with self._lock:
            if self._stopping:
                return
            replacement = self._spawn(dead.index, dead.generation + 1)
            self._workers[dead.index] = replacement

    def shutdown(self):
        # _stopping is the respawn gate _respawn checks under the lock:
        # setting it inside the same lock closes the window where a
        # concurrently dying worker respawns after shutdown decided to
        # stop (LOCK02 finding of the lock-discipline lint)
        with self._lock:
            self._stopping = True
            workers = list(self._workers)
        for _ in workers:
            self._dispatch_q.put(_STOP)
        for w in workers:
            w.thread.join(timeout=10)

    def crash(self):
        """Crash simulation (ProofService.crash): workers stop at their
        next round boundary through the DRAIN path — which parks the job
        with no retry bookkeeping, no terminal journal records, and
        crucially no checkpoint clears (a real dead process can't delete
        the snapshots its successor resumes from)."""
        with self._lock:
            self._stopping = True
        self._drain_stop.set()

    def busy(self):
        """Names of workers currently holding a job."""
        with self._lock:
            pool = list(self._workers)
        return [w.name for w in pool if w.busy_job is not None]

    def drain(self, deadline):
        """Graceful drain: let in-flight proves finish until `deadline`
        (monotonic), then force the stragglers to stop at their next
        round boundary — the snapshot is durable and the journal still
        shows them in-flight, so a restart resumes with zero recompute.
        Returns True iff everything finished without the forced stop."""
        clean = True
        while self.busy() and time.monotonic() < deadline:
            time.sleep(0.02)
        if self.busy():
            clean = False
            self._drain_stop.set()
            # round boundaries are the check points; wait for the busy
            # set to clear, bounded (a worker inside one long round can
            # exceed this — threads are daemons, the journal is already
            # consistent either way)
            stop_wait = time.monotonic() + 10
            while self.busy() and time.monotonic() < stop_wait:
                time.sleep(0.02)
        self.shutdown()
        return clean

    def workers(self):
        with self._lock:
            return list(self._workers)

    # -- dispatch -------------------------------------------------------------

    def dispatch(self, job, resources):
        """Hand a scheduled job to the pool (blocks for backpressure)."""
        self._dispatch_q.put((job, resources))

    def kill_worker(self, worker=None, job_id=None, at_round=None):
        """Fault injection: arm a kill on a specific worker, on whichever
        worker is proving `job_id`, or on any busy (else any) worker.
        Returns the victim's name; raises LookupError if no match."""
        with self._lock:
            pool = list(self._workers)
        victim = None
        if worker is not None:
            victim = next((w for w in pool if w.name == worker), None)
        elif job_id is not None:
            victim = next((w for w in pool
                           if w.busy_job is not None
                           and w.busy_job.id == job_id), None)
        else:
            victim = next((w for w in pool if w.busy_job is not None),
                          pool[0] if pool else None)
        if victim is None:
            raise LookupError("no such worker/job to kill")
        victim.kill_arm = {"at_round": at_round}
        self.metrics.inc("kill_requests")
        return victim.name

    # -- execution ------------------------------------------------------------

    def _ckpt_path(self, job):
        return os.path.join(self.ckpt_dir, f"{job.id}.ckpt.npz")

    def _make_guard(self, job, worker):
        if self.store is not None:
            return _GuardedStoreCheckpoint(self.store, job.id, worker,
                                           metrics=self.metrics,
                                           faults=self.faults,
                                           journal=self.journal,
                                           job_id=job.id)
        return _GuardedCheckpoint(self._ckpt_path(job), worker,
                                  metrics=self.metrics, faults=self.faults,
                                  journal=self.journal, job_id=job.id)

    def _clear_ckpt(self, job):
        if self.store is not None:
            StoreCheckpoint(self.store, job.id).clear()
            return
        try:
            os.remove(self._ckpt_path(job))
        except OSError:
            pass

    def shed(self, job, reason):
        """Terminal TTL/deadline verdict: journaled (clients can query it
        across a restart), counted, never proved. Shared by the scheduler
        (expired before key build) and the pool loop (expired in the
        dispatch buffer)."""
        self.metrics.inc("jobs_shed")
        if self.journal is not None:
            self.journal.append(JN.SHED, job.id, reason=reason)
        self._clear_ckpt(job)
        job.finish_shed(reason)

    def _loop(self, worker):
        backend = self.backend_factory()
        while True:
            item = self._dispatch_q.get()
            if item is _STOP:
                return
            job, res = item
            if job.expired():
                self.shed(job, "ttl expired before prove start")
                continue
            worker.busy_job = job
            if job.started_at is None:
                job.started_at = time.monotonic()
                self.metrics.observe("job_wait", job.wait_s)
            job.worker = worker.name
            job.state = J.RUNNING
            if self.journal is not None:
                self.journal.append(JN.START, job.id, worker=worker.name)
            try:
                self._run_attempt(worker, backend, job, res)
                job.attempts.append({"worker": worker.name, "outcome": "ok"})
                self.metrics.inc("jobs_completed")
                self.metrics.observe("job_run", job.run_s)
            except WorkerDrained:
                # deadline-forced drain: the round snapshot is durable and
                # the job's journal entry still reads in-flight — park it
                # (no requeue, no terminal record); the restarted service
                # resumes it from the checkpoint
                job.attempts.append({"worker": worker.name,
                                     "outcome": "drained"})
                job.state = J.QUEUED
                job.worker = None
                worker.busy_job = None
                self.metrics.inc("jobs_drain_parked")
                return  # draining: this thread is done
            except WorkerKilled:
                job.attempts.append({"worker": worker.name,
                                     "outcome": "killed"})
                self.metrics.inc("workers_killed")
                worker.busy_job = None
                # replacement first: with a 1-worker pool the requeue below
                # can block on a full dispatch queue until someone consumes
                self._respawn(worker)
                self._retry_or_fail(job, res, "worker killed mid-prove")
                return  # this thread is the "dead process"
            except JobTimeout:
                job.attempts.append({"worker": worker.name,
                                     "outcome": "timeout"})
                self.metrics.inc("jobs_timeout")
                self._fail(job, f"timeout after {self.job_timeout_s}s")
            except Exception as e:  # prove/verify error: bounded retry
                job.attempts.append({"worker": worker.name,
                                     "outcome": f"error: {e!r}"})
                self.metrics.inc("job_attempt_errors")
                self._retry_or_fail(job, res, f"prove failed: {e!r}")
            finally:
                worker.busy_job = None
                # a kill that armed too late to fire on its target (e.g.
                # during round 5, past the last boundary check) must not
                # leak onto the worker's next, unrelated job
                worker.kill_arm = None

    def _retry_or_fail(self, job, res, reason):
        job.retries += 1
        if job.retries > self.max_retries:
            self._fail(job, f"{reason} (retries exhausted)")
            return
        self.metrics.inc("job_retries")
        job.state = J.QUEUED
        # snapshot stays in place: the retry resumes, not restarts.
        # NEVER block a worker thread on the requeue: workers are the
        # dispatch queue's consumers, so a blocking put from one with the
        # queue full can deadlock the whole pool — hand a full queue off
        # to a detached putter instead
        try:
            self._dispatch_q.put_nowait((job, res))
        except _stdlib_queue.Full:
            threading.Thread(target=self._dispatch_q.put, args=((job, res),),
                             daemon=True).start()

    def _fail(self, job, reason):
        self.metrics.inc("jobs_failed")
        self._clear_ckpt(job)
        if self.journal is not None:
            self.journal.append(JN.FAILED, job.id, reason=reason)
        job.finish_err(reason)

    def _run_attempt(self, worker, backend, job, res):
        if self.job_timeout_s is not None:
            worker.deadline = job.started_at + self.job_timeout_s
        try:
            # the prover traces under the JOB's id (stamped/adopted at
            # SUBMIT), parented to the client's span when one was
            # propagated — every retry attempt re-records from scratch,
            # so the stored timeline is the attempt that produced the
            # proof plus the queue wait that preceded it
            tracer = Tracer(trace_id=job.trace_id,
                            parent_id=job.trace_parent,
                            proc=f"pool/{worker.name}")
            tracer.add_event("service/queued", ts=job.submitted_wall,
                             dur_s=job.wait_s, job_id=job.id)
            ckt = J.build_circuit(job.spec)
            guard = self._make_guard(job, worker)
            try:
                proof = prove(random.Random(job.spec.seed), ckt, res.pk,
                              backend, tracer=tracer, checkpoint=guard)
            except ValueError as e:
                if "different circuit" in str(e):
                    # a stale snapshot from some earlier run squats on our
                    # path: drop it so the retry restarts fresh instead of
                    # failing identically until retries are exhausted
                    guard.clear()
                raise
            if self.verify_on_complete:
                from ..verifier import verify
                assert verify(res.vk, ckt.public_input(), proof,
                              rng=random.Random(1)), \
                    "proof failed server-side verification"
            totals = tracer.totals(depth=1)
            self.metrics.observe_rounds(totals)
            # kernel spans carry flops attrs (prover.py): fold them into
            # live per-stage MFU/throughput gauges — the serving-path
            # replacement for bench-only MFU numbers
            self.metrics.observe_kernels(tracer.events)
            proof_bytes = serialize_proof(proof)
            pub = ckt.public_input()
            self._journal_done(job, proof_bytes, pub)
            self._store_trace(job, tracer)
            job.finish_ok(proof_bytes, pub, totals)
        finally:
            worker.deadline = None

    def _store_trace(self, job, tracer):
        """Merge + persist the job's timeline: always retained on the Job
        (STATUS reports trace_spans; /trace serves it), and — with a
        store — written as the content-addressed `trace:<job_id>`
        artifact (STORE_FETCHable, like the proof it explains).
        Observability is best-effort: failure to persist never fails a
        finished prove."""
        from ..trace import merge_traces
        merged = merge_traces([tracer.dump()])
        job.trace_dump = merged
        self.metrics.inc("trace_spans_recorded", len(merged["events"]))
        if self.store is None:
            return
        from ..store import keycache as KC
        try:
            KC.store_trace(self.store, job.id, merged)
            self.metrics.inc("traces_stored")
        except Exception:  # pragma: no cover - environmental (disk)
            self.metrics.inc("store_write_errors")

    def _journal_done(self, job, proof_bytes, pub):
        """Finished-proof durability, BEFORE the client-visible state
        flips to done: the proof becomes a content-addressed store
        artifact (STORE_FETCHable cross-host; a restart serves it
        instead of re-proving) and the journal DONE record carries its
        digest — or, storeless, the raw bytes inline (944B per proof:
        small enough that the journal stays the single durable surface).
        A crash anywhere before the DONE append re-proves from the
        round-4 snapshot and lands on the identical bytes."""
        if self.journal is None:
            return
        fields = {"pub": [hex(x) for x in pub], "retries": job.retries}
        if self.store is not None:
            from ..store import keycache as KC
            try:
                fields["digest"] = KC.store_proof(
                    self.store, job.id, proof_bytes, pub,
                    spec_wire=job.spec.to_wire(), retries=job.retries)
                fields["store_key"] = KC.proof_store_key(job.id)
            except Exception:  # pragma: no cover - environmental (disk)
                self.metrics.inc("store_write_errors")
                fields["proof_hex"] = proof_bytes.hex()
        else:
            fields["proof_hex"] = proof_bytes.hex()
        self.journal.append(JN.DONE, job.id, **fields)
