"""Priority job queue with admission control and bounded backpressure.

Admission is decided AT SUBMIT TIME, synchronously, so a client always
learns immediately whether its job is queued or why not (`Rejected.reason`)
— the queue never grows past `max_depth` and never silently drops work.
Within the queue, higher `priority` wins; FIFO within a priority class
(stable sequence numbers, no starvation among equals).

`pop_batch` is the scheduler's accessor: it returns the best job AND every
other queued job sharing its shape key (up to `max_batch`), so one bucket's
SRS/proving key build is amortized over the whole compatible batch.
"""

import threading


class Rejected(Exception):
    """Admission control said no. `reason` is client-presentable."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class JobQueue:
    def __init__(self, max_depth=64):
        self.max_depth = max_depth
        self._items = []            # [(sort_key, job)], kept sorted on pop
        self._seq = 0
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        self.high_water = 0

    def depth(self):
        with self._lock:
            return len(self._items)

    def submit(self, job, force=False):
        """Enqueue or raise Rejected (queue_full | draining). force=True
        bypasses the depth cap — journal recovery re-enqueues every job
        the previous process had already admitted; bouncing them against
        this process's depth limit would turn a restart into data loss."""
        with self._lock:
            if self._closed:
                raise Rejected("draining")
            if not force and len(self._items) >= self.max_depth:
                raise Rejected("queue_full")
            self._seq += 1
            # negative priority first => higher priority pops first
            self._items.append(((-job.priority, self._seq), job))
            self.high_water = max(self.high_water, len(self._items))
            self._nonempty.notify()

    def pop_batch(self, max_batch=1, timeout=None):
        """Remove and return up to `max_batch` jobs sharing the shape key
        of the current best (highest-priority, oldest) job. Returns [] on
        timeout or when closed and empty."""
        with self._lock:
            while not self._items:
                if self._closed or not self._nonempty.wait(timeout):
                    return []
            self._items.sort(key=lambda kv: kv[0])
            head_key = self._items[0][1].shape_key
            batch, rest = [], []
            for kv in self._items:
                if len(batch) < max_batch and kv[1].shape_key == head_key:
                    batch.append(kv[1])
                else:
                    rest.append(kv)
            self._items = rest
            return batch

    def closed(self):
        """True once close() ran (draining) — /healthz reports it."""
        with self._lock:
            return self._closed

    def close(self):
        """Stop admitting; wake any blocked pop."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()
