"""Priority job queue with admission control and bounded backpressure.

Admission is decided AT SUBMIT TIME, synchronously, so a client always
learns immediately whether its job is queued or why not (`Rejected.reason`)
— the queue never grows past `max_depth` and never silently drops work.
Ordering is (SLO class, priority, FIFO): flagship pops before standard
before batch (jobs.SLO_RANK), higher numeric `priority` wins within a
class, and stable sequence numbers keep FIFO among equals (no
starvation). Jobs without a class rank as `standard`, so an all-standard
stream — every pre-class caller — sorts exactly as the old
(priority, seq) key did.

`pop_batch` is the scheduler's accessor: it returns the best job AND every
other queued job sharing its shape key (up to `max_batch`), so one bucket's
SRS/proving key build is amortized over the whole compatible batch.

`steal_lowest` is the pressure valve: admission (a full queue refusing a
higher-class job) and the autoscaler both evict the WORST queued job of a
strictly lower class through it — shed-lowest-class-first, per-class TTL
defaults (`DPT_TTL_<CLASS>_S`, resolved by jobs.Job at submit) doing the
slow-path equivalent for jobs nobody pops in time.
"""

import threading


class Rejected(Exception):
    """Admission control said no. `reason` is client-presentable."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class JobQueue:
    def __init__(self, max_depth=64):
        self.max_depth = max_depth
        self._items = []            # [(sort_key, job)], kept sorted on pop
        self._seq = 0
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        self.high_water = 0

    def depth(self):
        with self._lock:
            return len(self._items)

    def depth_by_class(self):
        """{slo_class: queued count} — the autoscaler's class-mix sensor
        and the console's per-class depth row. Classless jobs count as
        standard."""
        with self._lock:
            out = {}
            for _key, job in self._items:
                cls = getattr(job, "slo", "standard")
                out[cls] = out.get(cls, 0) + 1
            return out

    def submit(self, job, force=False):
        """Enqueue or raise Rejected (queue_full | draining). force=True
        bypasses the depth cap — journal recovery re-enqueues every job
        the previous process had already admitted; bouncing them against
        this process's depth limit would turn a restart into data loss."""
        with self._lock:
            if self._closed:
                raise Rejected("draining")
            if not force and len(self._items) >= self.max_depth:
                raise Rejected("queue_full")
            self._seq += 1
            # higher SLO class first, then higher priority, then FIFO;
            # classless jobs rank standard, which keeps an all-standard
            # stream's order identical to the historical (priority, seq)
            self._items.append(((-getattr(job, "slo_rank", 1),
                                 -job.priority, self._seq), job))
            self.high_water = max(self.high_water, len(self._items))
            self._nonempty.notify()

    def pop_batch(self, max_batch=1, timeout=None):
        """Remove and return up to `max_batch` jobs sharing the shape key
        of the current best (highest-priority, oldest) job. Returns [] on
        timeout or when closed and empty."""
        with self._lock:
            while not self._items:
                if self._closed or not self._nonempty.wait(timeout):
                    return []
            self._items.sort(key=lambda kv: kv[0])
            head_key = self._items[0][1].shape_key
            batch, rest = [], []
            for kv in self._items:
                if len(batch) < max_batch and kv[1].shape_key == head_key:
                    batch.append(kv[1])
                else:
                    rest.append(kv)
            self._items = rest
            return batch

    def steal_lowest(self, below_rank):
        """Remove and return the WORST queued job of SLO rank strictly
        below `below_rank` (lowest class, then lowest priority, then
        newest), or None when nothing qualifies. Shed-lowest-class-first:
        the caller owns the returned job's terminal SHED verdict
        (pool.shed journals it) — the queue only picks the victim. With
        `below_rank` <= the lowest queued rank this is a no-op, so a
        classless deployment can never preempt anything."""
        with self._lock:
            worst = None
            for i, (key, job) in enumerate(self._items):
                if getattr(job, "slo_rank", 1) >= below_rank:
                    continue
                # sort keys order best-first, so the largest key is the
                # worst victim candidate
                if worst is None or key > self._items[worst][0]:
                    worst = i
            if worst is None:
                return None
            return self._items.pop(worst)[1]

    def closed(self):
        """True once close() ran (draining) — /healthz reports it."""
        with self._lock:
            return self._closed

    def close(self):
        """Stop admitting; wake any blocked pop."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()
