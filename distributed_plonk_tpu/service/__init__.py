"""Multi-client proving service in front of the prover/backends.

The serving layer the ROADMAP's "heavy traffic" north star needs and the
reference never had (its dispatcher proves exactly one hardcoded workload
per process, /root/reference/src/dispatcher2.rs:1218-1295):

    client --SUBMIT/STATUS/RESULT/METRICS/WARMUP--> server.ProofService
        -> queue.JobQueue          (priority, admission control, backpressure)
        -> placement.PlacementScheduler
                                   (shape buckets: shared SRS/pk per bucket,
                                    BucketCache tiers memory -> disk -> build
                                    over the ../store artifact store; then the
                                    PLACEMENT decision — small jobs prove
                                    data-parallel as one batched launch set,
                                    big jobs shard over a leased submesh,
                                    mid sizes take the per-job pool)
        -> pool.WorkerPool         (per-job timeout, bounded retry,
                                    resume-from-checkpoint on worker death;
                                    batched groups via prover.prove_many)
        -> metrics.Metrics         (counters + latency histograms, JSON)

The wire control plane rides runtime/protocol.py's framed transport (tags
SUBMIT/STATUS/RESULT/METRICS/KILL_WORKER/WARMUP). Entry points:
scripts/serve.py (daemon), scripts/loadgen.py (concurrent submitters +
fault injection), and scripts/warmup.py (shape pre-warming / offline store
provisioning); tests/test_service.py runs the whole loop in-process and
tests/test_store.py pins the warm-start contracts.
"""

from .jobs import Job, JobSpec, build_circuit, build_bucket_keys, shape_key
from .journal import JobJournal
from .queue import JobQueue, Rejected
from .metrics import Metrics
from .placement import PlacementScheduler, SubmeshLeaser
from .pool import WorkerPool, WorkerKilled, JobTimeout, WorkerDrained
from .scheduler import BucketCache, Scheduler
from .server import ProofService
from .client import ServiceClient

__all__ = [
    "Job", "JobSpec", "build_circuit", "build_bucket_keys", "shape_key",
    "JobJournal", "JobQueue", "Rejected", "Metrics", "WorkerPool",
    "WorkerKilled", "JobTimeout", "WorkerDrained", "BucketCache",
    "Scheduler", "PlacementScheduler", "SubmeshLeaser", "ProofService",
    "ServiceClient",
]
