"""BLS12-381 curve and field constants.

All values are standard, publicly specified BLS12-381 parameters (as used by
the reference's `ark-bls12-381` dependency, see /root/reference/Cargo.toml:31).
Derived quantities (Montgomery constants, roots of unity) are computed here
from first principles so nothing is copied from any implementation.

Runtime knob glossary (DPT_* environment variables)
---------------------------------------------------
The single source of truth for every environment knob the package reads,
enforced by analysis.lint ENV01: an undocumented `DPT_*` string literal
anywhere in the package is a lint failure. Format mirrors the OBS01 metric
glossary — indented lines, the knob name separated from its description by
two or more spaces; a trailing `*` documents a whole family.

Kernel dispatch and device tuning (backend/, parallel/):

    DPT_FIELD_MUL             field mont_mul kernel: auto|f32|u32|pallas
    DPT_PALLAS_MIN_LANES      min lanes before the pallas mul engages (2048)
    DPT_PALLAS_LANE_TILE      pallas mul lane-tile width (512)
    DPT_MUL_MXU               pallas mul: use the MXU matmul core (0)
    DPT_MUL_LAZY              pallas mul: lazy-carry accumulation (1)
    DPT_CURVE_ADD             curve add kernel: xla|pallas (xla)
    DPT_NTT_KERNEL            NTT kernel: auto|xla|pallas (auto)
    DPT_NTT_RADIX             force the NTT radix (unset = auto)
    DPT_NTT_BATCH             NTT batch width for *_many paths (8)
    DPT_NTT_PALLAS_VMEM_MB    pallas NTT VMEM budget in MB
    DPT_NTT_PALLAS_ROWS       pallas NTT rows per grid step
    DPT_R3_FUSE               fuse the round-3 quotient pipeline (1)
    DPT_R3_BITREV             consumer-side bit-reversal fusion (1)
    DPT_QUOT_SLICE            round-3 quotient eval slice length (2^20)
    DPT_STREAM_SYNC_EVERY     drain the dispatch queue every N FFTs (4)
    DPT_STREAM_SYNC_MIN_M     min domain before stream draining arms (2^23)
    DPT_RELEASE_TABLES_MIN    free circuit tables at/above this n (2^19)
    DPT_MSM_KERNEL            MSM bucket kernel: auto|xla|pallas (auto)
    DPT_MSM_C                 MSM window bits (7)
    DPT_MSM_BATCH             MSM scalar batch width (8)
    DPT_MSM_JOB_BATCH         MSM jobs folded per device dispatch (16)
    DPT_MSM_GROUP_MAX         max MSM group size (autotune-plan override)
    DPT_MSM_PLANE_MB          bucket-plane HBM budget in MB (1536)
    DPT_MSM_PALLAS_VMEM_MB    pallas MSM VMEM budget in MB
    DPT_MSM_CALL_ADDS         target bucket adds per device call (8e6)
    DPT_MSM_CALL_ADDS_MAX     hard cap on adds per device call
    DPT_MSM_CALL_S            target seconds per MSM device call (20)
    DPT_BUCKET_UPDATE         bucket update strategy: auto|onehot|put
    DPT_PLANE_PACK            packed bucket planes (1)
    DPT_FIXED_BASE_CHUNK      fixed-base table build chunk size
    DPT_MESH_MIN_LOCAL        min per-device rows before mesh sharding (1024)
    DPT_MESH_LEASE            lease mesh backends to the pool (0)
    DPT_AUTOTUNE              calibration plan mode: load|run|off (load)
    DPT_AUTOTUNE_BUDGET_S     autotune sweep wall-clock budget (120)
    DPT_AUTOTUNE_SHAPES       comma list of shapes to calibrate
    DPT_AUTOTUNE_INTERPRET    allow pallas interpret-mode candidates
    DPT_JAX_CACHE_DIR         persistent compile-cache directory
    DPT_JAX_TRACE             jax.profiler span annotations on hot paths

Proof service and autoscaling (service/):

    DPT_PIPELINE              round-pipelined multi-job proving (1)
    DPT_PIPELINE_DEPTH        max in-flight pipelined jobs (4)
    DPT_BATCH_PROVE           shape-batched proving (1)
    DPT_PLACE_SMALL_MAX       small-job placement cutoff, gates (2^14)
    DPT_PLACE_LARGE_MIN       large-job placement cutoff, gates (2^18)
    DPT_SELF_VERIFY           verify-before-serve: auto|0|1 (auto)
    DPT_SLO_STANDARD_S        standard-class SLO seconds
    DPT_TTL_*                 per-SLO-class job TTL seconds (DPT_TTL_<CLASS>_S)
    DPT_JOURNAL_FSYNC         fsync the job journal per append (1)
    DPT_JOURNAL_COMPACT_EVERY journal compaction cadence, appends (512)
    DPT_PEER_FETCH_TIMEOUT_MS peer artifact-fetch timeout (5000)
    DPT_PEAK_TFLOPS           MFU denominator for gflops gauges (1.0)
    DPT_AUTOSCALE             autoscaler arm: 0|dry|1 (0)
    DPT_AUTOSCALE_TICK_S      autoscaler control-loop period (2)
    DPT_AS_MIN_WORKERS        autoscaler floor (1)
    DPT_AS_MAX_WORKERS        autoscaler ceiling (8)
    DPT_AS_UP_QUEUE           queue-per-worker upscale threshold (2)
    DPT_AS_UP_TICKS           consecutive ticks before upscale (2)
    DPT_AS_DOWN_TICKS         consecutive idle ticks before downscale (5)
    DPT_AS_UP_COOLDOWN_S      cooldown after an upscale (10)
    DPT_AS_DOWN_COOLDOWN_S    cooldown after a downscale (30)
    DPT_AS_SHED_WATERMARK     queue fraction where batch-class sheds (0.9)

Fleet runtime, faults, integrity (runtime/):

    DPT_CALL_TIMEOUT_MS       per-RPC timeout (600000)
    DPT_RECONNECT_TRIES       dispatcher reconnect attempts (3)
    DPT_BACKOFF_BASE_MS       reconnect backoff base (50)
    DPT_BACKOFF_MAX_MS        reconnect backoff cap (2000)
    DPT_FFT_QUORUM            min workers for a sharded FFT (2)
    DPT_FFT_TASK_TTL          worker FFT task GC TTL seconds (600)
    DPT_FFT_DONE_TTL          completed-task retention seconds (60)
    DPT_FFT_TASK_CAP          max concurrent worker FFT tasks (64)
    DPT_FLEET_EVAL            distribute round-4 evaluation (1)
    DPT_BREAKER_K             failures to open a worker breaker (3)
    DPT_PROBE_BASE_MS         breaker half-open probe base (200)
    DPT_PROBE_MAX_MS          breaker half-open probe cap (5000)
    DPT_INTEGRITY             result-integrity plane arm (1)
    DPT_INTEGRITY_MSM_DUP     MSM duplicate-execution fraction (0.05)
    DPT_INTEGRITY_NTT_RATE    FFT spot-check sampling rate (1.0)
    DPT_INTEGRITY_SUBGROUP    subgroup-check returned points (1)
    DPT_INTEGRITY_REFEREE_MAX max referee recompute size (2048)
    DPT_JOIN_RETRY_S          membership JOIN retry period (30)
    DPT_JOIN_TIMEOUT_MS       membership JOIN timeout (10000)
    DPT_SUP_PROBE_MS          supervisor liveness probe period (500)
    DPT_SUP_PROBE_TIMEOUT_MS  supervisor probe timeout (3000)
    DPT_SUP_MISS_BUDGET       missed probes before respawn (3)
    DPT_SUP_STARTUP_GRACE_S   no-probe grace after spawn
    DPT_SUP_BACKOFF_BASE_MS   respawn backoff base (250)
    DPT_SUP_BACKOFF_MAX_MS    respawn backoff cap (10000)
    DPT_SUP_FLAP_CAP          respawns inside the window before retire (5)
    DPT_SUP_FLAP_WINDOW_S     flap-counting window (60)
    DPT_SUP_RETIRE_TIMEOUT_S  graceful retire drain timeout (20)
    DPT_WORKER_TRACE_CAP      per-worker retained trace spans (32)
    DPT_FAULTS                chaos fault-injection spec (off unset)

Observability, checkpoints, stores (obs/, store/, top-level):

    DPT_LOG_CAP               structured-log ring capacity (512)
    DPT_LOG_LEVEL             structured-log emit threshold (debug)
    DPT_LOG_DIR               mirror structured logs to JSONL files
    DPT_PROFILE_MS            default on-demand profile window (250)
    DPT_PROFILE_HZ            host stack-sampler frequency (100)
    DPT_FLEET_SCRAPE_S        fleet metrics scrape period (5)
    DPT_CKPT_FSYNC            fsync prover checkpoints (0)
    DPT_STORE_JAX_SWEEP_S     compile-cache upload sweep period (300)
    DPT_WARM_SYNC_PREFIXES    store prefixes pulled on warm rejoin
"""

# BLS parameter (the curve family is parameterised by z; z is negative).
# All moduli below are validated against this parameterisation at import time.
BLS_Z = -0xD201000000010000

# --- Scalar field Fr ---------------------------------------------------------
# r = order of the BLS12-381 G1/G2 subgroups (255 bits); r = z^4 - z^2 + 1
R_MOD = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
assert R_MOD == BLS_Z ** 4 - BLS_Z ** 2 + 1

# Multiplicative generator of Fr* (arkworks' `GENERATOR` for Fr is 7; it is a
# primitive root mod r). Used as the coset shift for coset-FFTs
# (reference: Fr::multiplicative_generator() at src/worker.rs:76).
FR_GENERATOR = 7

# two-adicity: r - 1 = 2^32 * FR_ODD
FR_TWO_ADICITY = 32
FR_ODD = (R_MOD - 1) >> FR_TWO_ADICITY
assert (R_MOD - 1) == FR_ODD << FR_TWO_ADICITY and FR_ODD % 2 == 1

# 2^32-th primitive root of unity in Fr
FR_ROOT_OF_UNITY = pow(FR_GENERATOR, FR_ODD, R_MOD)

# --- Base field Fq -----------------------------------------------------------
# q = characteristic of the base field (381 bits); q = (z-1)^2 * r / 3 + z
Q_MOD = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
assert Q_MOD == (BLS_Z - 1) ** 2 * R_MOD // 3 + BLS_Z

# --- Curve equations ---------------------------------------------------------
# G1: y^2 = x^3 + 4 over Fq
G1_B = 4
# G2: y^2 = x^3 + 4(1+u) over Fq2 = Fq[u]/(u^2+1)
G2_B = (4, 4)

# --- Standard generators -----------------------------------------------------
G1_GEN_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_GEN_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_GEN_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_GEN_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# Absolute value of the BLS parameter (for ate-style Miller loops)
BLS_X = -BLS_Z
BLS_X_IS_NEG = True

# --- Limb layouts for device kernels ----------------------------------------
# TPU integer units have no 64-bit multiply; we use 16-bit limbs held in
# uint32 lanes so a limb product fits in 32 bits with headroom for lazy
# carry accumulation (see backend/limbs.py).
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1
FR_LIMBS = 16  # 256 bits
FQ_LIMBS = 24  # 384 bits

# Montgomery radixes match arkworks' 64-bit-limb layout (R = 2^256 for Fr,
# R = 2^384 for Fq) so Montgomery-form values are bit-compatible.
FR_MONT_R = (1 << 256) % R_MOD
FR_MONT_R2 = (FR_MONT_R * FR_MONT_R) % R_MOD
FR_MONT_INV = (-pow(R_MOD, -1, 1 << 256)) % (1 << 256)  # -r^-1 mod 2^256
FR_MONT_INV16 = FR_MONT_INV & LIMB_MASK  # -r^-1 mod 2^16 (per-limb CIOS)

FQ_MONT_R = (1 << 384) % Q_MOD
FQ_MONT_R2 = (FQ_MONT_R * FQ_MONT_R) % Q_MOD
FQ_MONT_INV = (-pow(Q_MOD, -1, 1 << 384)) % (1 << 384)
FQ_MONT_INV16 = FQ_MONT_INV & LIMB_MASK
