"""BLS12-381 curve and field constants.

All values are standard, publicly specified BLS12-381 parameters (as used by
the reference's `ark-bls12-381` dependency, see /root/reference/Cargo.toml:31).
Derived quantities (Montgomery constants, roots of unity) are computed here
from first principles so nothing is copied from any implementation.
"""

# BLS parameter (the curve family is parameterised by z; z is negative).
# All moduli below are validated against this parameterisation at import time.
BLS_Z = -0xD201000000010000

# --- Scalar field Fr ---------------------------------------------------------
# r = order of the BLS12-381 G1/G2 subgroups (255 bits); r = z^4 - z^2 + 1
R_MOD = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
assert R_MOD == BLS_Z ** 4 - BLS_Z ** 2 + 1

# Multiplicative generator of Fr* (arkworks' `GENERATOR` for Fr is 7; it is a
# primitive root mod r). Used as the coset shift for coset-FFTs
# (reference: Fr::multiplicative_generator() at src/worker.rs:76).
FR_GENERATOR = 7

# two-adicity: r - 1 = 2^32 * FR_ODD
FR_TWO_ADICITY = 32
FR_ODD = (R_MOD - 1) >> FR_TWO_ADICITY
assert (R_MOD - 1) == FR_ODD << FR_TWO_ADICITY and FR_ODD % 2 == 1

# 2^32-th primitive root of unity in Fr
FR_ROOT_OF_UNITY = pow(FR_GENERATOR, FR_ODD, R_MOD)

# --- Base field Fq -----------------------------------------------------------
# q = characteristic of the base field (381 bits); q = (z-1)^2 * r / 3 + z
Q_MOD = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
assert Q_MOD == (BLS_Z - 1) ** 2 * R_MOD // 3 + BLS_Z

# --- Curve equations ---------------------------------------------------------
# G1: y^2 = x^3 + 4 over Fq
G1_B = 4
# G2: y^2 = x^3 + 4(1+u) over Fq2 = Fq[u]/(u^2+1)
G2_B = (4, 4)

# --- Standard generators -----------------------------------------------------
G1_GEN_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_GEN_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_GEN_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_GEN_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# Absolute value of the BLS parameter (for ate-style Miller loops)
BLS_X = -BLS_Z
BLS_X_IS_NEG = True

# --- Limb layouts for device kernels ----------------------------------------
# TPU integer units have no 64-bit multiply; we use 16-bit limbs held in
# uint32 lanes so a limb product fits in 32 bits with headroom for lazy
# carry accumulation (see backend/limbs.py).
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1
FR_LIMBS = 16  # 256 bits
FQ_LIMBS = 24  # 384 bits

# Montgomery radixes match arkworks' 64-bit-limb layout (R = 2^256 for Fr,
# R = 2^384 for Fq) so Montgomery-form values are bit-compatible.
FR_MONT_R = (1 << 256) % R_MOD
FR_MONT_R2 = (FR_MONT_R * FR_MONT_R) % R_MOD
FR_MONT_INV = (-pow(R_MOD, -1, 1 << 256)) % (1 << 256)  # -r^-1 mod 2^256
FR_MONT_INV16 = FR_MONT_INV & LIMB_MASK  # -r^-1 mod 2^16 (per-limb CIOS)

FQ_MONT_R = (1 << 384) % Q_MOD
FQ_MONT_R2 = (FQ_MONT_R * FQ_MONT_R) % Q_MOD
FQ_MONT_INV = (-pow(Q_MOD, -1, 1 << 384)) % (1 << 384)
FQ_MONT_INV16 = FQ_MONT_INV & LIMB_MASK
