"""Pallas fused complete projective add: the whole RCB15 formula in VMEM.

WHY (measured on a v5e, BASELINE.md round 4): after the fused Montgomery
multiplier landed, the MSM bucket scan still ran at ~510k lane-adds/s
against a ~12M lane-muls/s multiplier — the projective add is a ~12-deep
dependent chain of muls/adds/subs, and issuing it as ~24 separate XLA
ops per scan step pays the per-op dispatch + VPU/MXU layout-transition
cost ~24 times and round-trips every intermediate through HBM (~300 B
per lane per op). This kernel runs the ENTIRE complete-add formula
(RCB15 algorithms 7/8 for a=0, b3=12 — the same straight-line sequence
as curve_jax.proj_add / proj_add_mixed) in one Pallas program: the 11/12
full Montgomery products execute as TWO wide banded group-products (the
independent muls concatenate along lanes, exactly like curve_jax's
stacked-lane staging, but inside VMEM), and all modular adds/subs reuse
the same in-register Kogge-Stone sweeps. HBM traffic per lane-add drops
from ~24 round-trips to: read 5 (mixed) or 6 (full) coordinates, write 3.

Bit-identity: every intermediate is fully reduced mod p by the same
paired-sweep rule as field_jax.add/sub/mont_mul, so outputs are
limb-identical to the XLA path (oracle-tested in
tests/test_curve_pallas.py; the MSM consuming it stays byte-identical).

Dispatch: curve_jax.proj_add{,_mixed} route wide TPU shapes here under
the same gate as the fused multiplier (DPT_FIELD_MUL=auto + lane
threshold; DPT_CURVE_ADD=xla opts just the add kernel out). The q_inf /
sign selects of the callers stay in XLA where they fuse for free.

Reference parity: this is the device replacement for the per-bucket
point additions inside ark-ec's VariableBaseMSM as driven by the MSM
workers (/root/reference/src/worker.rs:122,159-185).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .field_pallas import (LIMB_BITS, LIMB_MASK, _carry_sweep_val,
                           _to_bytes_f32, _cols_to_limbs, _const_bytes,
                           int_from_limbs)

# lanes of each coordinate per grid step. The group products run 5-6x
# this wide; 256 keeps the f32 column scratch at 96*6*256*4 = 590 KB for
# Fq and the whole working set low single-digit MB of VMEM.
LANE_TILE = 256


def _col_const(limbs):
    """Python limb ints -> (L, 1) i32 column built from inlined scalars
    (pallas kernels cannot capture array constants)."""
    return jnp.concatenate(
        [jnp.full((1, 1), int(v), jnp.int32) for v in limbs], axis=0)


# --- in-kernel modular primitives (i32 limbs in [0, 2^16), width-generic) ----

def _mod_add(a, b, n_limbs, negp):
    """a + b mod p, mirroring field_jax.add: sweep the raw sum and the
    sum + (2^(16L) - p); the second's carry-out flags sum >= p."""
    s = a + b
    r1, _ = _carry_sweep_val(s, n_limbs)
    r2, c2 = _carry_sweep_val(s + negp, n_limbs)
    return jnp.where((c2 != 0)[None], r2, r1)


def _row0_mask(shape):
    """(rows, w) i32 that is 1 on row 0, else 0 — the concat-free way to
    adjust the head row (a row-concatenate gives the result an offset
    vector layout that Mosaic then cannot lane-concatenate)."""
    import jax.lax as lax
    return (lax.broadcasted_iota(jnp.int32, shape, 0) == 0).astype(jnp.int32)


def _mod_sub(a, b, n_limbs, p_col):
    """a - b mod p, mirroring field_jax.sub: a + ~b + 1 carries iff
    a >= b; otherwise take the + p wrap-around lane."""
    base = a + (b ^ LIMB_MASK)
    base = base + _row0_mask(base.shape)
    r1, c1 = _carry_sweep_val(base, n_limbs)
    r2, _ = _carry_sweep_val(base + p_col, n_limbs)
    return jnp.where((c1 != 0)[None], r1, r2)


def _band_mul_w(t_ref, a_bytes, b_bytes, w):
    """field_pallas._band_mul on the leading `w` lanes of the scratch.

    The zeroing covers the FULL scratch, not just [:, :w]: a partial
    zero is a weak update to the static verifier's per-ref interval cell
    (analysis/bounds.py), so stale bounds from a wider prior product
    would compound across the ~12 products of a fused add and trip the
    f32-exactness check; the extra lanes cost ~1% of the band FMAs."""
    nb = a_bytes.shape[0]
    t_ref[...] = jnp.zeros(t_ref.shape, jnp.float32)
    for i in range(nb):
        t_ref[i:i + nb, :w] += a_bytes[i][None, :] * b_bytes
    return t_ref[:, :w]


def _band_mul_const_w(t_ref, c_bytes, b_bytes, w):
    nb = b_bytes.shape[0]
    t_ref[...] = jnp.zeros(t_ref.shape, jnp.float32)
    for i, c in enumerate(c_bytes):
        if c == 0:
            continue
        t_ref[i:i + nb, :w] += np.float32(c) * b_bytes
    return t_ref[:, :w]


def _mont_mul_val(t_ref, a, b, k):
    """Full Montgomery SOS product on in-register (L, w) i32 values —
    the body of field_pallas._mont_mul_kernel, reusing one (4L, Wmax)
    f32 scratch. k carries the per-field constants."""
    L = k["n_limbs"]
    w = a.shape[1]
    a_by = _to_bytes_f32(a)
    b_by = _to_bytes_f32(b)
    t_cols = _band_mul_w(t_ref, a_by, b_by, w)
    t_limbs = _cols_to_limbs(t_cols)
    t_lo, c_t = _carry_sweep_val(t_limbs[:L], L)
    tlo_by = _to_bytes_f32(t_lo)
    m_cols = _band_mul_const_w(t_ref, k["ninv_bytes"], tlo_by, w)[:2 * L]
    m, _ = _carry_sweep_val(_cols_to_limbs(m_cols), L)
    m_by = _to_bytes_f32(m)
    mp_cols = _band_mul_const_w(t_ref, k["mod_bytes"], m_by, w)
    mp_limbs = _cols_to_limbs(mp_cols)
    _, c_low = _carry_sweep_val(t_lo + mp_limbs[:L], L)
    hi = t_limbs[L:] + mp_limbs[L:]
    hi = hi + _row0_mask(hi.shape) * (c_t + c_low)[None]
    r1, _ = _carry_sweep_val(hi, L)
    r2, c2 = _carry_sweep_val(hi + k["negp"], L)
    return jnp.where((c2 != 0)[None], r2, r1)


def _mm_group(t_ref, pairs, k):
    """Stacked-lane group product: the independent muls concatenate along
    lanes into ONE banded product (the in-VMEM analog of
    curve_jax._mul_lanes — same batching idea, zero HBM round-trips)."""
    T = pairs[0][0].shape[1]
    a = jnp.concatenate([p[0] for p in pairs], axis=1)
    b = jnp.concatenate([p[1] for p in pairs], axis=1)
    r = _mont_mul_val(t_ref, a, b, k)
    return [r[:, i * T:(i + 1) * T] for i in range(len(pairs))]


def _mul12(a, k):
    """12*a = 8a + 4a (the b3 = 3*4 multiply for y^2 = x^3 + 4), via the
    same dbl/add chain as curve_jax._mul12 (fully reduced at each step)."""
    L, negp = k["n_limbs"], k["negp"]
    a2 = _mod_add(a, a, L, negp)
    a4 = _mod_add(a2, a2, L, negp)
    a8 = _mod_add(a4, a4, L, negp)
    return _mod_add(a8, a4, L, negp)


# --- the fused kernels -------------------------------------------------------

def consts_env(kc):
    """Hashable const tuple (from _fq_consts / fq_consts) -> the dict the
    value-level helpers consume, with the modulus columns materialized.
    Exported for kernels that embed these primitives (msm_pallas)."""
    k = dict(kc)
    k["negp"] = _col_const(k.pop("negmod_limbs"))
    k["p_col"] = _col_const(k.pop("mod_limbs"))
    return k


def _rcb15_tail(t_ref, k, t0, t1, t3, t4, ym, t2):
    """Shared tail of RCB15 algorithms 7/8 once (t0, t1, t3, t4, ym) and
    the b3-scaled t2 are in hand; returns (x3, y3, z3) i32 values."""
    L, negp, p_col = k["n_limbs"], k["negp"], k["p_col"]
    t0x3 = _mod_add(_mod_add(t0, t0, L, negp), t0, L, negp)
    z3a = _mod_add(t1, t2, L, negp)
    t1a = _mod_sub(t1, t2, L, p_col)
    y3b = _mul12(ym, k)
    x3a, t2c, y3c, t1b, t0c, z3b = _mm_group(
        t_ref,
        [(t4, y3b), (t3, t1a), (y3b, t0x3),
         (t1a, z3a), (t0x3, t3), (z3a, t4)], k)
    return (_mod_sub(t2c, x3a, L, p_col),
            _mod_add(t1b, y3c, L, negp),
            _mod_add(z3b, t0c, L, negp))


def add_mixed_val(t_ref, k, p, q):
    """Complete projective P + affine Q (RCB15 algorithm 8, a=0) on
    in-VMEM (L, w) i32 VALUES — the exact op sequence of
    curve_jax.proj_add_mixed, width-generic (w is whatever the caller's
    lane count is; t_ref must be at least 6*w lanes wide). The q_inf /
    skip select stays with the caller. Returns (x3, y3, z3) values."""
    L, negp, p_col = k["n_limbs"], k["negp"], k["p_col"]
    x1, y1, z1 = p
    x2, y2 = q
    a1 = _mod_add(x1, y1, L, negp)
    a2 = _mod_add(x2, y2, L, negp)
    t0, t1, m3, t4a, y3a = _mm_group(
        t_ref, [(x1, x2), (y1, y2), (a1, a2), (y2, z1), (x2, z1)], k)
    t3 = _mod_sub(m3, _mod_add(t0, t1, L, negp), L, p_col)
    t4 = _mod_add(t4a, y1, L, negp)
    ym = _mod_add(y3a, x1, L, negp)
    t2 = _mul12(z1, k)
    return _rcb15_tail(t_ref, k, t0, t1, t3, t4, ym, t2)


def add_full_val(t_ref, k, p, q):
    """Complete projective P + Q (RCB15 algorithm 7, a=0) on in-VMEM
    (L, w) i32 values — the exact op sequence of curve_jax.proj_add."""
    L, negp, p_col = k["n_limbs"], k["negp"], k["p_col"]
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0, t1, t2r, m3, m4, m5 = _mm_group(
        t_ref,
        [(x1, x2), (y1, y2), (z1, z2),
         (_mod_add(x1, y1, L, negp), _mod_add(x2, y2, L, negp)),
         (_mod_add(y1, z1, L, negp), _mod_add(y2, z2, L, negp)),
         (_mod_add(x1, z1, L, negp), _mod_add(x2, z2, L, negp))], k)
    t3 = _mod_sub(m3, _mod_add(t0, t1, L, negp), L, p_col)
    t4 = _mod_sub(m4, _mod_add(t1, t2r, L, negp), L, p_col)
    ym = _mod_sub(m5, _mod_add(t0, t2r, L, negp), L, p_col)
    t2 = _mul12(t2r, k)
    return _rcb15_tail(t_ref, k, t0, t1, t3, t4, ym, t2)


def _add_mixed_kernel(x1_ref, y1_ref, z1_ref, x2_ref, y2_ref,
                      ox_ref, oy_ref, oz_ref, t_ref, *, kc):
    """Complete projective P + affine Q (RCB15 algorithm 8, a=0): the
    exact op sequence of curve_jax.proj_add_mixed, in one program."""
    k = consts_env(kc)
    p = tuple(r[...].astype(jnp.int32) for r in (x1_ref, y1_ref, z1_ref))
    q = tuple(r[...].astype(jnp.int32) for r in (x2_ref, y2_ref))
    x3, y3, z3 = add_mixed_val(t_ref, k, p, q)
    ox_ref[...] = x3.astype(jnp.uint32)
    oy_ref[...] = y3.astype(jnp.uint32)
    oz_ref[...] = z3.astype(jnp.uint32)


def _add_full_kernel(x1_ref, y1_ref, z1_ref, x2_ref, y2_ref, z2_ref,
                     ox_ref, oy_ref, oz_ref, t_ref, *, kc):
    """Complete projective P + Q (RCB15 algorithm 7, a=0): the exact op
    sequence of curve_jax.proj_add, in one program."""
    k = consts_env(kc)
    p = tuple(r[...].astype(jnp.int32) for r in (x1_ref, y1_ref, z1_ref))
    q = tuple(r[...].astype(jnp.int32) for r in (x2_ref, y2_ref, z2_ref))
    x3, y3, z3 = add_full_val(t_ref, k, p, q)
    ox_ref[...] = x3.astype(jnp.uint32)
    oy_ref[...] = y3.astype(jnp.uint32)
    oz_ref[...] = z3.astype(jnp.uint32)


def field_consts(spec):
    """Hashable per-field constant tuple for kernels embedding these
    primitives (jit-static; feed through consts_env inside the kernel
    body). Width-generic: Fq for the curve/MSM kernels, Fr for the fused
    NTT stage kernel (ntt_pallas)."""
    L = spec.n_limbs
    return (("n_limbs", L),
            ("ninv_bytes",
             tuple(_const_bytes(int_from_limbs(spec.ninv_limbs), 2 * L))),
            ("mod_bytes",
             tuple(_const_bytes(int_from_limbs(spec.mod_limbs), 2 * L))),
            ("negmod_limbs", tuple(int(v) for v in spec.negmod_limbs)),
            ("mod_limbs", tuple(int(v) for v in spec.mod_limbs)))


def fq_consts():
    """field_consts(Fq) — the constant set of the curve/MSM kernels."""
    from .field_jax import FQ

    return field_consts(FQ)


_fq_consts = fq_consts  # internal spelling kept for the add kernels below


@functools.partial(jax.jit, static_argnums=(0, 1))
def _add_flat(mixed, interpret, *coords):
    """(L, N) coordinate arrays (5 mixed / 6 full), N a LANE_TILE
    multiple -> three (L, N) outputs."""
    from jax.experimental.pallas import tpu as pltpu
    from .field_jax import FQ

    L = FQ.n_limbs
    kern = _add_mixed_kernel if mixed else _add_full_kernel
    kernel = functools.partial(kern, kc=_fq_consts())
    n = coords[0].shape[1]
    grid = n // LANE_TILE
    spec = pl.BlockSpec((L, LANE_TILE), lambda i: (0, i))
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((L, n), jnp.uint32)] * 3,
        grid=(grid,),
        in_specs=[spec] * len(coords),
        out_specs=[spec] * 3,
        scratch_shapes=[pltpu.VMEM((4 * L, 6 * LANE_TILE), jnp.float32)],
        interpret=interpret,
    )(*coords)


def _dispatch(mixed, parts):
    from .field_jax import FQ

    interpret = jax.default_backend() != "tpu"
    L = FQ.n_limbs
    shape = jnp.broadcast_shapes(*[p.shape for p in parts])
    lanes = 1
    for d in shape[1:]:
        lanes *= d
    pad = (-lanes) % LANE_TILE
    flat = []
    for p in parts:
        f = jnp.broadcast_to(p, shape).reshape(L, lanes)
        flat.append(jnp.pad(f, ((0, 0), (0, pad))) if pad else f)
    out = _add_flat(mixed, interpret, *flat)
    if pad:
        out = [o[:, :lanes] for o in out]
    return tuple(o.reshape(shape) for o in out)


def proj_add_mixed(p, q_affine):
    """Fused-kernel counterpart of curve_jax.proj_add_mixed WITHOUT the
    q_inf select (the caller applies it in XLA, where it fuses)."""
    return _dispatch(True, [*p, *q_affine])


def proj_add(p, q):
    """Fused-kernel counterpart of curve_jax.proj_add."""
    return _dispatch(False, [*p, *q])
