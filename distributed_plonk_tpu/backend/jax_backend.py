"""Single-device JAX backend: the full prover dataflow device-resident.

The device analog of one reference worker's compute surface
(/root/reference/src/worker.rs:125-409) — but where the reference only ever
offloaded NTT + MSM and kept every intermediate polynomial on the
dispatcher host, here poly handles are (16, L) Montgomery limb arrays that
STAY on device across all 5 rounds (the round3*/round5* offload the
reference declared and never built, src/hello_world.capnp:26-44): NTTs,
commitments (with on-device digit extraction), the permutation product,
quotient evaluation, blinding, evaluation, linear combination and the
opening divisions all run as jitted kernels. Host transfers during a prove
are the witness upload (once), commitment results, and transcript scalars.

Heavy state (SRS bases as Montgomery limb arrays, NTT plans/twiddles,
per-circuit witness/permutation tables, per-domain quotient tables) is
cached device-resident across calls, like the worker's `State`
(/root/reference/src/worker.rs:42-59).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS
from ..circuit import NUM_WIRE_TYPES
from . import ntt_jax
from . import prover_jax as PJ
from . import field_jax as FJ
from .field_jax import FR
from .msm_jax import MsmContext
from .limbs import ints_to_limbs

# Round-3 pointwise fusion (DPT_R3_FUSE, default on): fold the gate /
# sigma quotient products into the selector/sigma coset-FFT programs as
# epilogues, and the final quotient combine into the coset iNTT as a
# prologue (NttPlan.kernel_fused) — the quotient pipeline loses its
# standalone O(m) passes. 0 restores the separate jitted step programs
# (the value-identical reference path, kept like DPT_NTT_KERNEL=xla).
_R3_FUSE = os.environ.get("DPT_R3_FUSE", "1") != "0"

# Bit-reversal deferral for the FUSED round 3 (DPT_R3_BITREV, default on;
# only meaningful under DPT_R3_FUSE): every forward coset-FFT launch in
# the quotient pipeline emits in constant-geometry (bit-reversed) order
# (NttPlan defer_perm) and the accumulator planes stay bit-reversed all
# the way to the combine — valid because every fold is pointwise, so it
# holds in any order the operands share (the z_next roll and the domain
# tables are re-indexed once, per-plan). The ONE place the order returns
# to natural is the consuming coset-iNTT's input gather (kernel_fused
# input_perm), fused into that program's first stage reads — the
# "consumer-side fusion" follow-on noted in backend/ntt_pallas.py: ~26
# standalone O(m) bit-reversal gathers per round 3 collapse into 1.
# 0 restores per-launch output permutation (bit-identical either way).
_R3_BITREV = os.environ.get("DPT_R3_BITREV", "1") != "0"


class _DevicePending:
    """Dispatched-but-unforced device result (commit_many_async /
    eval_many_async): jax has already enqueued the launches; force() pays
    the device→host transfer. The prover's pipeline driver forces only at
    the owning member's host-finalize."""

    __slots__ = ("force",)

    def __init__(self, force):
        self.force = force


class JaxBackend:
    """Backend over single-device jitted kernels.

    Poly handles: (16, L) uint32 Montgomery limb jnp arrays. The plain
    int-list compute API (fft/msm/...) is kept for the worker daemon and
    fleet dispatcher surface."""

    name = "jax"

    def __init__(self):
        import threading
        self._msm_ctxs = {}
        self._circuit_tabs = {}
        self._pk_polys = {}
        self._domain_tabs = {}
        self._domain_tabs_packed = {}
        # guards check-then-insert on the capped caches: the worker daemon
        # runs kernels outside its state lock, so two connections can hit a
        # backend cache concurrently (an eviction between check and read
        # would KeyError)
        self._cache_lock = threading.Lock()
        # host-boundary transfer counters (asserted on in tests: mid-prove
        # traffic must be scalars only). `drains` counts the round-3
        # queue-bounding fences (1-element fetches) separately from the
        # protocol `lowers`.
        self.lifts = 0
        self.lowers = 0
        self.drains = 0

    # --- plain int-list compute API (worker daemon / dispatcher surface) ----

    def fft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values)

    def ifft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, inverse=True)

    def coset_fft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, coset=True)

    def coset_ifft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, inverse=True, coset=True)

    def _make_msm_ctx(self, bases):
        """MSM context factory hook (the mesh backend overrides this to
        build a mesh-sharded context; the caching in _ctx is shared)."""
        return MsmContext(bases)

    def _ctx(self, bases):
        # keyed by identity; the bases reference is retained so the id can
        # never be recycled by a different object while cached. Capped like
        # the other device caches: an uncapped map keyed by commit keys
        # retains every SRS's Jacobian arrays forever (HBM leak in a
        # long-lived worker process serving many circuits).
        # Double-checked: the EXPENSIVE build (MsmContext runs a batched
        # affine normalization at SRS scale) happens outside the lock so
        # concurrent cache hits never wait on it; a lost race costs one
        # duplicate build, not correctness.
        key = id(bases)
        with self._cache_lock:
            hit = self._msm_ctxs.get(key)
        if hit is None:
            built = self._make_msm_ctx(bases)
            with self._cache_lock:
                if key not in self._msm_ctxs:
                    self._cache_put(self._msm_ctxs, key, (bases, built))
                hit = self._msm_ctxs[key]
        return hit[1]

    def msm(self, bases, scalars):
        """Variable-base MSM; scalars zero-padded to |bases| on device."""
        return self._ctx(bases).msm(scalars)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)

    def commit_many(self, ck, coeff_lists):
        """B commitments over the same key in one batched launch."""
        return self._ctx(ck).msm_many(coeff_lists)

    # --- poly-handle protocol: handles are (16, L) Montgomery arrays --------

    def _lift_arr(self, arr):
        """Host (16, K) limb array -> device array. Placement hook: the
        single-device backend uses the default device; the mesh backend
        overrides this to device_put with a sharded layout."""
        return jnp.asarray(arr)

    def lift(self, values):
        self.lifts += 1
        return self._lift_arr(PJ.lift(values))

    def lift_many(self, value_lists):
        """Upload B equal-length int lists as ONE transfer -> B handles
        (preprocess lifts its 18 selector/sigma columns this way: one
        tunnel round-trip instead of 18)."""
        n = len(value_lists[0])
        assert all(len(v) == n for v in value_lists)
        flat = [x for vs in value_lists for x in vs]
        self.lifts += 1
        h = self._lift_arr(PJ.lift(flat))
        return [h[:, i * n:(i + 1) * n] for i in range(len(value_lists))]

    def lower(self, h):
        self.lowers += 1
        return PJ.lower(h)

    # checkpoint dump/load (checkpoint.py): CANONICAL (16, L) uint32 limb
    # arrays — the same layout limbs.ints_to_limbs produces — so snapshots
    # are portable across backends. The int round-trip is skipped: one
    # device from_mont/to_mont pass instead of 2^20 Python conversions.
    def dump_h(self, h):
        return np.asarray(PJ._from_mont_jit(h)).astype(np.uint32, copy=False)

    def load_h(self, arr):
        return PJ._to_mont_jit(self._lift_arr(np.asarray(arr, np.uint32)))

    def wire_values(self, circuit):
        tabs = self._circuit_tables(circuit)
        return [tabs["wires"][:, i] for i in range(NUM_WIRE_TYPES)]

    _CACHE_CAP = 4  # bound the per-pk/per-circuit device caches

    @staticmethod
    def _cache_put(cache, key, value):
        if len(cache) >= JaxBackend._CACHE_CAP:
            cache.pop(next(iter(cache)))
        cache[key] = value

    def pk_polys(self, pk):
        key = id(pk)
        with self._cache_lock:
            hit = self._pk_polys.get(key)
        if hit is None:
            self.lifts += 1  # O(n) upload: proving-key polys, once per pk
            sel = [self._lift_arr(PJ.lift(s)) for s in pk.selectors]
            sig = [self._lift_arr(PJ.lift(s)) for s in pk.sigmas]
            with self._cache_lock:
                if key not in self._pk_polys:
                    self._cache_put(self._pk_polys, key, (pk, sel, sig))
                hit = self._pk_polys[key]
        return hit[1], hit[2]

    def register_pk_polys(self, pk, sel_h, sig_h):
        """Seed the pk-poly cache with handles preprocess just computed on
        device, so the prover never lowers+re-lifts 18 selector/sigma
        polynomials through the host (kzg.preprocess batched path)."""
        with self._cache_lock:
            self._cache_put(self._pk_polys, id(pk), (pk, list(sel_h), list(sig_h)))

    def warm_stages(self, domain_size, ck=None):
        """AOT warm-start for one shape bucket (store/warmstart.py's hook).

        Pre-lowers/compiles the NTT kernel variants for the bucket's
        evaluation domain AND its quotient domain (the two sizes a prove
        of this shape launches, prover.py:59), at both single-poly and the
        batch widths _kernel_batches would pick — so the executables are
        in the persistent compile cache before the first job lands. With
        `ck`, also builds the commit key's MsmContext and AOT-lowers its
        commitment pipeline (`MsmContext.aot_compile`) at the prover's
        commit-batch widths — the wire batch (NUM_WIRE_TYPES), the
        opening pair, and single commits; an ancient jax with no AOT API
        falls back to the old one-zero-scalar execution pass."""
        from ..poly import Domain
        report = {"ntt": {}}
        quot = Domain((NUM_WIRE_TYPES + 1) * (domain_size + 1) + 1)
        for dom_n in sorted({domain_size, quot.size}):
            chunk = self._ntt_chunk(dom_n)
            report["ntt"][dom_n] = ntt_jax.get_plan(dom_n).aot_compile(
                batch_sizes=(chunk,) if chunk > 1 else ())
        if ck is not None:
            ctx = self._ctx(ck)
            # digit widths = the blinded coefficient-handle widths the
            # prover actually commits: wires/quotient-splits/openings are
            # n+2 wide, the permutation poly n+3 (prover.py rounds 1-5)
            msm_report = ctx.aot_compile(
                batch_sizes=(1, 2, NUM_WIRE_TYPES),
                digit_widths=(domain_size + 2, domain_size + 3))
            if msm_report["failed"]:  # pragma: no cover - no/partial-AOT
                # ANY stage that failed to lower would pay its compile on
                # the first real job: keep the old warm-by-execution
                # guarantee (one zero-scalar MSM bakes the whole pipeline)
                ctx.msm([0])
                msm_report["fallback_exec"] = True
            report["msm"] = msm_report
            report["msm_warmed"] = True
        return report

    def _kernel(self, domain, h, inverse, coset):
        plan = ntt_jax.get_plan(domain.size)
        if h.shape[1] < domain.size:
            h = jnp.pad(h, ((0, 0), (0, domain.size - h.shape[1])))
        assert h.shape[1] == domain.size
        return plan.kernel(inverse=inverse, coset=coset, boundary="mont")(h)

    def ifft_h(self, domain, h):
        return self._kernel(domain, h, True, False)

    # batch NTTs run as single multi-poly launches, chunked by a B*n cap.
    # The XLA f32 mul path materializes its column tensor (~1 KB/elem) so
    # it needs B*n <= 2^21 (~2 GB transient); the fused Pallas multiplier
    # keeps those in VMEM, so the cap rises to 2^23 (working set is then
    # the (16, B, n) stage arrays, ~0.5 GB per copy at the cap) — at the
    # 2^21 quotient domain that turns round 3's 25 per-poly coset-FFT
    # launches into 7, saving ~18 x the ~120 ms per-call dispatch.
    # DPT_NTT_BATCH caps the chunk width.
    _NTT_BATCH = int(os.environ.get("DPT_NTT_BATCH", "8"))

    @staticmethod
    def _pad_to(h, size):
        # padding happens PER BATCH, never up front: materializing all 25
        # round-3 inputs at the quotient-domain width was 6.4 GB of
        # transient at m=2^22 — the dominant term of the measured 2^19
        # OOM (scale_2p19_r05.log attempt 1); inputs stay at their n-scale
        # widths until the launch that consumes them
        return (jnp.pad(h, ((0, 0), (0, size - h.shape[1])))
                if h.shape[1] < size else h)

    def _ntt_chunk(self, domain_size):
        """Batch width of one NTT launch: B*n capped by the mul-path
        transient budget (the ONE copy of the cap heuristic —
        _kernel_batches, the fused round-3 launches, and AOT warmup all
        pick their widths here so they can never desync)."""
        elems_cap = 1 << (23 if FJ._use_pallas((16, 1 << 22)) else 21)
        return max(1, min(self._NTT_BATCH, elems_cap // domain_size))

    def _kernel_batches(self, domain, hs, inverse, coset, defer_perm=False):
        """Yield (16, B, m) NTT result batches covering hs in order, B
        capped by the launch budget (_ntt_chunk). _kernel_many collects,
        quotient_streamed folds each batch into accumulators so no batch
        outlives its consumption. defer_perm: bit-reversed-order output
        (the round-3 deferral, DPT_R3_BITREV)."""
        plan = ntt_jax.get_plan(domain.size)
        chunk = self._ntt_chunk(domain.size)
        if chunk == 1 and not defer_perm:
            fn1 = plan.kernel(inverse=inverse, coset=coset, boundary="mont")
            for h in hs:
                yield fn1(self._pad_to(h, domain.size))[:, None]
            return
        fn = plan.kernel_batch(inverse=inverse, coset=coset,
                               defer_perm=defer_perm)
        for i in range(0, len(hs), max(chunk, 1)):
            yield fn(jnp.stack([self._pad_to(h, domain.size)
                                for h in hs[i:i + max(chunk, 1)]], axis=1))

    def _kernel_many(self, domain, hs, inverse, coset, post=None,
                     defer_perm=False):
        """B NTTs in capped batches; `post` (if given) maps each launch's
        (16, B, m) result before results are split out — e.g. the round-3
        limb packing, applied while at most one batch is unpacked."""
        out = []
        for res in self._kernel_batches(domain, hs, inverse, coset,
                                        defer_perm=defer_perm):
            if post is not None:
                res = post(res)
            out.extend(res[:, j] for j in range(res.shape[1]))
        return out

    def ifft_many(self, domain, hs):
        return self._kernel_many(domain, hs, True, False)

    def coset_fft_many(self, domain, hs):
        return self._kernel_many(domain, hs, False, True)

    # --- streaming round 3 ---------------------------------------------------
    # The single-device memory strategy for the quotient round
    # (/root/reference/src/dispatcher2.rs:382-507): the quotient formula
    # reads each SELECTOR plane once (a gate term) and each SIGMA plane
    # once (an acc2 factor), so both fold into running accumulators right
    # after their coset FFT and are dropped. Only ~10 planes stay
    # resident — 5 wires, z, z_next/acc2, pi→gate — all LIMB-PACKED
    # (8, m), and the final combine runs in lane slices that unpack on
    # the fly. Residency: ~2.5 GB at m=2^23 vs 6.4 GB all-packed and
    # 12.8 GB naive — the measured single-chip budget is ~7-9.5 GB
    # (scale_2p19_r05 attempt logs). The mesh backend opts out
    # (quotient_streamed = None): its memory strategy is sharding, and
    # slicing a GSPMD-sharded lane axis would reshard every chunk.

    _QUOT_SLICE = int(os.environ.get("DPT_QUOT_SLICE", str(1 << 20)))
    # drain the device queue every K streamed launches once the quotient
    # domain is huge: a fully-async warm round 3 enqueues the whole
    # 25-FFT pipeline before anything frees, and the queued buffer
    # lifetimes overlap enough to OOM at m=2^23 (scale_2p20_r05b.log
    # attempts 1-2: cold passes — compile pauses drain the queue — warm
    # RESOURCE_EXHAUSTEDs). A 1-element fetch costs ~0.1 s per drain.
    _STREAM_SYNC_EVERY = int(os.environ.get("DPT_STREAM_SYNC_EVERY", "4"))
    _STREAM_SYNC_MIN_M = int(os.environ.get("DPT_STREAM_SYNC_MIN_M",
                                            str(1 << 23)))

    def coset_fft_many_packed(self, domain, hs, defer_perm=False):
        """coset_fft_many, but each (16, m) result returns limb-packed
        (8, m). Packing rides the launch loop so at most one batch of
        unpacked outputs is ever resident. defer_perm: results stay in
        bit-reversed order (DPT_R3_BITREV pipeline)."""
        return self._kernel_many(domain, hs, False, True, post=PJ.pack_jit,
                                 defer_perm=defer_perm)

    def _domain_tables_packed(self, m, n, group_gen, bitrev=False):
        """Packed quotient-domain tables; bitrev=True re-indexes every
        lane through the bit-reversal permutation so the tables line up
        with the deferred-order accumulator planes (one extra gather at
        cache build, amortized over every prove of the shape)."""
        key = (m, n, bitrev)
        with self._cache_lock:
            hit = self._domain_tabs_packed.get(key)
        if hit is None:
            tabs = PJ.domain_tables_jit(m, n, FR_GENERATOR, group_gen)
            if bitrev:
                perm = jnp.asarray(ntt_jax.get_plan(m).perm)
                tabs = {kk: v[:, perm] for kk, v in tabs.items()}
            hit = {kk: PJ.pack_jit(v) for kk, v in tabs.items()}
            with self._cache_lock:
                self._domain_tabs_packed[key] = hit
        return hit

    def _roll_perm(self, m, ratio):
        """Gather index array carrying the z -> z_next roll INTO the
        bit-reversed plane order: with perm the bit-reversal permutation,
        bitrev(roll(natural, ratio))[i] = bitrev(z)[perm[(perm[i] +
        ratio) % m]] — one precomputed gather replaces the natural-order
        roll (both are pure data movement)."""
        key = ("roll_perm", m, ratio)
        with self._cache_lock:
            hit = self._domain_tabs_packed.get(key)
        if hit is None:
            perm = ntt_jax.get_plan(m).perm.astype(np.int64)
            hit = jnp.asarray(perm[(perm + ratio) % m].astype(np.int32))
            with self._cache_lock:
                self._domain_tabs_packed[key] = hit
        return hit

    # selector index -> (UNJITTED step body, wire-plane operand indices);
    # the round-3 FUSED path (DPT_R3_FUSE) traces these as the epilogue
    # of the selector coset-FFT program itself, so XLA fuses the gate
    # product with the NTT's final stage / output permutation and the
    # (16, B, m) selector planes never round-trip HBM between the FFT
    # and their one consuming multiply. Same circuit.py order as the
    # jitted gate_steps table below.
    _R3_GATE_STEPS = (
        [(PJ.gate_linear_step, (i,)) for i in range(4)]             # Q_LC
        + [(PJ.gate_mul2_step, (0, 1)), (PJ.gate_mul2_step, (2, 3))]  # Q_MUL
        + [(PJ.gate_pow5_step, (i,)) for i in range(4)]             # Q_HASH
        + [(PJ.gate_out_step, (4,)),                                # Q_O
           (PJ.gate_const_step, ()),                                # Q_C
           (PJ.gate_ecc_step, (0, 1, 2, 3, 4))]                     # Q_ECC
    )

    @classmethod
    def _gate_epilogue(cls, start, width):
        steps = cls._R3_GATE_STEPS[start:start + width]

        def epi(res, gate_p, *wires):
            for j, (fn, widx) in enumerate(steps):
                gate_p = fn(gate_p, res[:, j], *[wires[x] for x in widx])
            return gate_p
        return epi

    @staticmethod
    def _sigma_epilogue(start, width):
        def epi(res, acc2_p, beta_c, gamma_c, *wires):
            for j in range(width):
                acc2_p = PJ.sigma_step(acc2_p, res[:, j], wires[start + j],
                                       beta_c, gamma_c)
            return acc2_p
        return epi

    @staticmethod
    def _combine_prologue(m):
        def pro(w0, w1, w2, w3, w4, z_p, gate_p, acc2_p, ep, zh, sh,
                k_arr, beta, gamma, alpha, asdn):
            ev = PJ.quotient_combine_slice(
                [w0, w1, w2, w3, w4], z_p, gate_p, acc2_p, ep, zh, sh,
                k_arr, beta, gamma, alpha, asdn, jnp.uint32(0), chunk=m)
            return ev[:, None, :]
        return pro

    def _r3_accumulate(self, n, m, quot_domain, beta, gamma, sel_h, sigma_h,
                       wire_polys, perm_poly, pi_coeffs, bitrev=False):
        """Shared front half of round 3: base coset FFTs + gate/sigma
        plane folding. Returns (wires_p, z_p, gate_p, acc2_p, throttle).
        Under DPT_R3_FUSE each selector/sigma batch's fold runs as the
        EPILOGUE of its own coset-FFT program (NttPlan.kernel_fused) —
        value-identical to the standalone jitted steps, minus their
        write-plane + read-plane HBM pass per batch.

        bitrev=True (DPT_R3_BITREV, fused path only): every FFT launch
        defers its output bit-reversal, so all returned planes are in
        constant-geometry order — the folds are pointwise, so they are
        value-identical in any shared order; the z_next roll becomes one
        re-indexed gather (_roll_perm). The caller owns getting back to
        natural order (the consuming iNTT's input_perm)."""
        ratio = m // n
        bitrev = bitrev and _R3_FUSE  # only the fused folds speak deferred
        base = self.coset_fft_many_packed(
            quot_domain, list(wire_polys) + [perm_poly, pi_coeffs],
            defer_perm=bitrev)
        wires_p = base[:5]
        z_p = base[5]
        gate_p = base[6]               # gate accumulator starts as pi plane
        # acc2 starts as z_next: a natural-order roll, or — deferred —
        # the same data movement through the re-indexed gather
        acc2_p = (z_p[:, self._roll_perm(m, ratio)] if bitrev
                  else PJ.roll_jit(z_p, ratio))
        del base

        sync_every = (self._STREAM_SYNC_EVERY
                      if m >= self._STREAM_SYNC_MIN_M else 0)
        launches = [0]

        def _throttle(h):
            launches[0] += 1
            if sync_every and launches[0] % sync_every == 0:
                # 1-element fetch: bounds the async queue. Counted in
                # `drains`, NOT `lowers` — the lowers counter audits
                # PROTOCOL transfers (transcript scalars); this is a
                # fence whose payload is 4 bytes
                self.drains += 1
                np.asarray(h[:1, :1])

        _throttle(acc2_p)

        beta_c = jnp.asarray(PJ.lift_scalar(beta))
        gamma_c = jnp.asarray(PJ.lift_scalar(gamma))
        w = wires_p
        if _R3_FUSE:
            plan = ntt_jax.get_plan(quot_domain.size)
            chunk = self._ntt_chunk(quot_domain.size)
            for i in range(0, len(sel_h), chunk):
                hs = [self._pad_to(h, quot_domain.size)
                      for h in sel_h[i:i + chunk]]
                fnk = plan.kernel_fused(
                    False, True, key=("r3gate", i, len(hs)),
                    epilogue=self._gate_epilogue(i, len(hs)),
                    defer_perm=bitrev)
                gate_p = fnk((jnp.stack(hs, axis=1),),
                             (gate_p,) + tuple(w))
                _throttle(gate_p)
            for i in range(0, len(sigma_h), chunk):
                hs = [self._pad_to(h, quot_domain.size)
                      for h in sigma_h[i:i + chunk]]
                fnk = plan.kernel_fused(
                    False, True, key=("r3sigma", i, len(hs)),
                    epilogue=self._sigma_epilogue(i, len(hs)),
                    defer_perm=bitrev)
                acc2_p = fnk((jnp.stack(hs, axis=1),),
                             (acc2_p, beta_c, gamma_c) + tuple(w))
                _throttle(acc2_p)
            return wires_p, z_p, gate_p, acc2_p, _throttle

        # unfused reference path: standalone jitted step programs
        # (13 selectors share 6 compiled programs, circuit.py order)
        gate_steps = (
            [(PJ.gate_linear_step_jit, (w[i],)) for i in range(4)]      # Q_LC
            + [(PJ.gate_mul2_step_jit, (w[0], w[1])),                   # Q_MUL
               (PJ.gate_mul2_step_jit, (w[2], w[3]))]
            + [(PJ.gate_pow5_step_jit, (w[i],)) for i in range(4)]      # Q_HASH
            + [(PJ.gate_out_step_jit, (w[4],)),                         # Q_O
               (PJ.gate_const_step_jit, ()),                            # Q_C
               (PJ.gate_ecc_step_jit, tuple(w))]                        # Q_ECC
        )
        idx = 0
        for res in self._kernel_batches(quot_domain, list(sel_h), False, True):
            for j in range(res.shape[1]):
                fn, operands = gate_steps[idx]
                gate_p = fn(gate_p, res[:, j], *operands)
                idx += 1
            _throttle(gate_p)
        sj = 0
        for res in self._kernel_batches(quot_domain, list(sigma_h), False, True):
            for j in range(res.shape[1]):
                acc2_p = PJ.sigma_step_jit(acc2_p, res[:, j], w[sj],
                                           beta_c, gamma_c)
                sj += 1
            _throttle(acc2_p)
        return wires_p, z_p, gate_p, acc2_p, _throttle

    def quotient_streamed(self, n, m, quot_domain, k, beta, gamma, alpha,
                          alpha_sq_div_n, sel_h, sigma_h, wire_polys,
                          perm_poly, pi_coeffs):
        """Round 3 from coefficient handles: coset FFTs + quotient
        evaluation in one streaming pass (see class comment). Returns
        unpacked (16, m) quotient evals for the coset iFFT (the sliced
        combine; `quotient_poly_streamed` is the fused path that skips
        this materialization entirely)."""
        tabs = self._domain_tables_packed(m, n, quot_domain.group_gen)
        wires_p, z_p, gate_p, acc2_p, _throttle = self._r3_accumulate(
            n, m, quot_domain, beta, gamma, sel_h, sigma_h, wire_polys,
            perm_poly, pi_coeffs)

        chunk = min(self._QUOT_SLICE, m)
        assert m % chunk == 0
        k_arr = jnp.asarray(PJ.lift(list(k))).reshape(FR_LIMBS, len(k), 1)
        scal = [jnp.asarray(PJ.lift_scalar(x))
                for x in (beta, gamma, alpha, alpha_sq_div_n)]
        outs = []
        for j0 in range(0, m, chunk):
            outs.append(PJ.quotient_combine_slice_jit(
                list(wires_p), z_p, gate_p, acc2_p,
                tabs["ep"], tabs["zh_inv"], tabs["shifted_inv"],
                k_arr, *scal, np.uint32(j0), chunk=chunk))
            _throttle(outs[-1])
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)

    def quotient_poly_streamed(self, n, m, quot_domain, k, beta, gamma,
                               alpha, alpha_sq_div_n, sel_h, sigma_h,
                               wire_polys, perm_poly, pi_coeffs):
        """Round 3 all the way to the quotient POLYNOMIAL: the streaming
        accumulation, then — under DPT_R3_FUSE (default on) — the final
        pointwise combine runs as the PROLOGUE of the coset iNTT program
        (NttPlan.kernel_fused), fusing into the first inverse stage's
        reads so the (16, m) quotient-eval array never materializes as a
        standalone pass. With the knob off this is exactly
        quotient_streamed + coset_ifft_h (the sliced reference path)."""
        if not _R3_FUSE:
            evals = self.quotient_streamed(
                n, m, quot_domain, k, beta, gamma, alpha, alpha_sq_div_n,
                sel_h, sigma_h, wire_polys, perm_poly, pi_coeffs)
            return self.coset_ifft_h(quot_domain, evals)
        # DPT_R3_BITREV: the whole accumulation runs in bit-reversed
        # order (no per-launch output gathers) and the combine's result
        # returns to natural order through the consuming iNTT's input
        # gather — the one bit-reversal pass left in round 3
        bitrev = _R3_BITREV
        tabs = self._domain_tables_packed(m, n, quot_domain.group_gen,
                                          bitrev=bitrev)
        wires_p, z_p, gate_p, acc2_p, _throttle = self._r3_accumulate(
            n, m, quot_domain, beta, gamma, sel_h, sigma_h, wire_polys,
            perm_poly, pi_coeffs, bitrev=bitrev)
        k_arr = jnp.asarray(PJ.lift(list(k))).reshape(FR_LIMBS, len(k), 1)
        scal = [jnp.asarray(PJ.lift_scalar(x))
                for x in (beta, gamma, alpha, alpha_sq_div_n)]
        plan = ntt_jax.get_plan(quot_domain.size)
        fnk = plan.kernel_fused(True, True, key=("r3combine",),
                                prologue=self._combine_prologue(m),
                                input_perm=bitrev)
        poly = fnk(tuple(wires_p) + (z_p, gate_p, acc2_p, tabs["ep"],
                                     tabs["zh_inv"], tabs["shifted_inv"],
                                     k_arr) + tuple(scal))[:, 0]
        _throttle(poly)
        return poly

    def coset_fft_h(self, domain, h):
        return self._kernel(domain, h, False, True)

    def coset_ifft_h(self, domain, h):
        return self._kernel(domain, h, True, True)

    def blind(self, h, blinds, n):
        return PJ.blind_jit(h, jnp.asarray(PJ.lift(blinds)), n)

    def commit_h(self, ck, h):
        ctx = self._ctx(ck)
        return ctx.msm_mont_limbs(h)

    def commit_many_h(self, ck, hs):
        return self._ctx(ck).msm_mont_limbs_many(hs)

    # cross-job commit batching (the placement layer's data-parallel
    # path): one launch covers up to DPT_MSM_JOB_BATCH handles — wider
    # than the per-prove DPT_MSM_BATCH because a batch of N small jobs
    # commits 5N same-shape wire polys per round, and the per-launch
    # fixed cost is what batching across jobs exists to amortize. Plane
    # memory scales with the chunk (B*W*buckets), so the default stays
    # modest; small domains are exactly where it is cheap.
    _MSM_JOB_BATCH = int(os.environ.get("DPT_MSM_JOB_BATCH", "16"))

    def commit_batch(self, ck, hs):
        """Multi-proof commit path (prover.prove_many): B commitments —
        typically the SAME round of N different jobs — in launches of up
        to _MSM_JOB_BATCH, with same-width handles sharing ONE stacked
        digit-extraction launch (MsmContext._digits_many_fn). Results are
        bit-identical to commit_many_h per handle (each MSM is
        independent; grouping only changes launch boundaries)."""
        return self._ctx(ck).msm_mont_limbs_many(
            hs, chunk=max(1, self._MSM_JOB_BATCH))

    def commit_many_async(self, ck, hs):
        """Async commit dispatch (prover round pipeline): enqueue the MSM
        launches for `hs` and return an unforced pending whose force()
        performs the host-side decode. Values are bit-identical to
        commit_many_h — only WHEN the host blocks moves, which is what
        lets a pipelined member's host-finalize overlap another member's
        dispatched device work."""
        return self._ctx(ck).msm_mont_limbs_many_async(hs)

    def eval_many_async(self, pairs):
        """Async eval_many_h: the batched evaluation launch is enqueued
        here; the transfer + canonical decode run at pending.force()."""
        from .limbs import limbs_to_ints

        L = max(h.shape[1] for h, _ in pairs)
        polys = jnp.stack([jnp.pad(h, ((0, 0), (0, L - h.shape[1])))
                           for h, _ in pairs])  # (B, 16, L)
        zs = jnp.stack([jnp.asarray(PJ.lift_scalar(p)) for _, p in pairs])
        out = PJ.poly_eval_many_jit(polys, zs)  # (16, B) canonical

        def force():
            self.lowers += 1  # B scalars cross in one transfer
            return limbs_to_ints(np.asarray(out))
        return _DevicePending(force)

    def degree_is(self, h, d):
        if h.shape[1] <= d:
            return False
        top_nonzero = not PJ.tail_is_zero(h, d - 1)
        return PJ.tail_is_zero(h, d) and top_nonzero

    def split(self, h, size, count, total):
        assert count * size >= total
        if h.shape[1] < count * size:
            h = jnp.pad(h, ((0, 0), (0, count * size - h.shape[1])))
        return [h[:, i:i + size] for i in range(0, count * size, size)]

    def eval_h(self, h, point):
        self.lowers += 1  # one scalar crosses the boundary
        zc = jnp.asarray(PJ.lift_scalar(point))
        return PJ.lower(PJ.poly_eval_jit(h, zc))[0]

    def eval_many_h(self, pairs):
        """[(handle, point)] -> evaluations, in ONE device call: round 4's
        10 evaluations would otherwise pay 10 dispatch round-trips for 10
        scalars (the tunnel round-trip is ~0.1s; SURVEY §7 hard part (d))."""
        from .limbs import limbs_to_ints

        L = max(h.shape[1] for h, _ in pairs)
        polys = jnp.stack([jnp.pad(h, ((0, 0), (0, L - h.shape[1])))
                           for h, _ in pairs])  # (B, 16, L)
        zs = jnp.stack([jnp.asarray(PJ.lift_scalar(p)) for _, p in pairs])
        out = PJ.poly_eval_many_jit(polys, zs)  # (16, B) canonical
        self.lowers += 1  # B scalars cross in one transfer
        return limbs_to_ints(np.asarray(out))

    def lin_comb_h(self, polys, coeffs):
        L = max(p.shape[1] for p in polys)
        stacked = jnp.stack(
            [jnp.pad(p, ((0, 0), (0, L - p.shape[1]))) for p in polys], axis=1)
        cf = jnp.asarray(PJ.lift(coeffs)).reshape(16, len(coeffs), 1)
        return PJ.lin_comb_jit(stacked, cf)

    def synth_div_h(self, h, point):
        zc = jnp.asarray(PJ.lift_scalar(point))
        return PJ.synthetic_divide_jit(h, zc)

    def _circuit_tables(self, circuit):
        """Per-circuit device tables: witness wires, identity-permutation
        values, and sigma-mapped identity values — lifted once."""
        key = id(circuit)
        with self._cache_lock:
            hit = self._circuit_tabs.get(key)
        if hit is not None:
            return hit[1]
        tabs = self._build_circuit_tables(circuit)
        with self._cache_lock:
            if key not in self._circuit_tabs:
                self._cache_put(self._circuit_tabs, key, (circuit, tabs))
            return self._circuit_tabs[key][1]

    def _build_circuit_tables(self, circuit):
        self.lifts += 1  # O(n) upload: witness + permutation tables
        n = len(circuit.wire_variables[0])
        w = NUM_WIRE_TYPES
        wire_vals = [circuit.wire_values(i) for i in range(w)]
        flat = [v for vals in wire_vals for v in vals]
        wires = self._lift_tab(PJ.lift(flat), w, n)
        id_flat = [circuit.extended_id_permutation[i][j]
                   for i in range(w) for j in range(n)]
        id_tab = self._lift_tab(PJ.lift(id_flat), w, n)
        sig_flat = []
        for i in range(w):
            for j in range(n):
                pi, pj = circuit.wire_permutation[i][j]
                sig_flat.append(circuit.extended_id_permutation[pi][pj])
        sig_tab = self._lift_tab(PJ.lift(sig_flat), w, n)
        return {"wires": wires, "id": id_tab, "sig": sig_tab, "n": n}

    def _lift_tab(self, arr, w, n):
        """Host (16, w*n) limb array -> (16, w, n) device table (placement
        hook, like _lift_arr)."""
        return jnp.asarray(arr).reshape(FR_LIMBS, w, n)

    # below this n the circuit tables stay cached across proves: the
    # release exists for round-3 HBM headroom at 2^19+, while re-lifting
    # the ~3*(16,5,n) tables through the tunnel costs real wall-clock
    # (measured +8.6s on the 2^18 warm prove, scale_2p18_r05.json r1)
    _RELEASE_TABLES_MIN = int(os.environ.get("DPT_RELEASE_TABLES_MIN",
                                             str(1 << 19)))

    def release_circuit_tables(self, circuit):
        """Free the witness/permutation device tables (≈0.5 GB at n=2^19)
        when the circuit is large enough that round 3 needs the HBM.

        The prover calls this after round 2 — wire_values (round 1) and
        perm_product (round 2) are the only consumers. Above the
        threshold a subsequent prove re-lifts them (one O(n) upload);
        below it they stay cached keyed by circuit IDENTITY (the
        long-standing _circuit_tabs contract: mutating a circuit's
        witness in place and re-proving the same object is not
        supported — build a new circuit)."""
        if len(circuit.wire_variables[0]) < self._RELEASE_TABLES_MIN:
            return
        with self._cache_lock:
            self._circuit_tabs.pop(id(circuit), None)

    def perm_product(self, circuit, beta, gamma, n):
        tabs = self._circuit_tables(circuit)
        assert tabs["n"] == n
        return PJ.perm_product_jit(
            tabs["wires"], tabs["id"], tabs["sig"],
            jnp.asarray(PJ.lift_scalar(beta, 3)),
            jnp.asarray(PJ.lift_scalar(gamma, 3)))

    def _domain_tables(self, m, n, group_gen):
        key = (m, n)
        with self._cache_lock:
            if key not in self._domain_tabs:
                self._domain_tabs[key] = PJ.domain_tables_jit(
                    m, n, FR_GENERATOR, group_gen)
            return self._domain_tabs[key]

    def quotient(self, n, m, quot_domain, k, beta, gamma, alpha, alpha_sq_div_n,
                 selectors_coset, sigmas_coset, wires_coset, z_coset, pi_coset):
        tabs = self._domain_tables(m, n, quot_domain.group_gen)
        sel = jnp.stack(selectors_coset, axis=1)
        sig = jnp.stack(sigmas_coset, axis=1)
        wir = jnp.stack(wires_coset, axis=1)
        k_arr = jnp.asarray(PJ.lift(list(k))).reshape(FR_LIMBS, len(k), 1)
        ratio = m // n
        return PJ.quotient_evals_jit(
            sel, sig, wir, z_coset, pi_coset, tabs, k_arr,
            jnp.asarray(PJ.lift_scalar(beta)),
            jnp.asarray(PJ.lift_scalar(gamma)),
            jnp.asarray(PJ.lift_scalar(alpha)),
            jnp.asarray(PJ.lift_scalar(alpha_sq_div_n)), ratio)
