"""Single-device JAX backend: NTT + MSM on the TPU limb kernels.

The device analog of one reference worker's compute surface
(/root/reference/src/worker.rs:125-409): the prover's round logic stays on
host (like the dispatcher), every FFT and MSM runs on device. Heavy state
(SRS bases as Montgomery limb arrays, NTT plans/twiddles) is cached
device-resident across calls, like the worker's `State`
(/root/reference/src/worker.rs:42-59).
"""

from . import ntt_jax
from .msm_jax import MsmContext


class JaxBackend:
    """Backend over single-device jitted kernels (plain int host boundary)."""

    name = "jax"

    def __init__(self):
        self._msm_ctxs = {}

    def fft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values)

    def ifft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, inverse=True)

    def coset_fft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, coset=True)

    def coset_ifft(self, domain, values):
        return ntt_jax.get_plan(domain.size).run_ints(values, inverse=True, coset=True)

    def _ctx(self, bases):
        # keyed by identity; the bases reference is retained so the id can
        # never be recycled by a different object while cached
        key = id(bases)
        if key not in self._msm_ctxs:
            self._msm_ctxs[key] = (bases, MsmContext(bases))
        return self._msm_ctxs[key][1]

    def msm(self, bases, scalars):
        """Variable-base MSM; scalars zero-padded to |bases| on device."""
        return self._ctx(bases).msm(scalars)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)
