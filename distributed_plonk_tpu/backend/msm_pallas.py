"""Pallas fused MSM bucket accumulation: VMEM-resident bucket planes.

WHY (BENCH_r05 + scripts/scatter_ab.py round 4): after the radix-4 NTT
landed, the variable-base MSM is the prover's dominant kernel by an
order of magnitude (2^20 MSM 49.2 s vs 2^20 NTT 5.6 s), and it runs at
`mfu_msm_pct` 19.4 against a 63.7% multiplier — ~3x headroom that the
scatter A/B already attributed to bucket-plane MEMORY TRAFFIC, not the
RCB15 add: every `lax.scan` step of msm_jax._bucket_scan* issues the
one-hot gather/update as XLA ops, so the full (G, M, B) plane
round-trips HBM once per step (the measured 3.5 ms/step floor at
G=256, M=32, B=128).

THIS kernel fuses the whole per-step pipeline — digit decode, bucket
gather, complete projective mixed add (RCB15 algorithm 8), bucket
update — into one Pallas program whose bucket planes live in VMEM
scratch for the entire point stream:

  grid = (window_tiles, steps), steps innermost. For one tile of Mt
  window lanes, the (rows, B, G*Mt) plane scratch persists across all
  n/G point steps (packed limb pairs by default: 12 rows of u32 — a
  (G=8, B=128) per-window plane is ~150 KB, so ~256 resident lanes fit
  in ~4.7 MB of VMEM); each step streams one (24, G) point tile plus a
  (G*Mt,) op word tile from HBM and performs the gather + add + update
  entirely in registers/VMEM, reusing curve_pallas.add_mixed_val (the
  same straight-line RCB15 sequence, bit-identical to the XLA path)
  and field_pallas' carry sweeps.

HBM traffic model: the XLA scan moves 3 coords x rows x G x M x B x 4 B
of plane per step (n/G steps); this kernel reads each point tile
ceil(M/Mt) times, reads the op words once, and writes the planes ONCE
at the end — per-step HBM traffic drops from the full plane round trip
to 'read points + ops once-ish', leaving the RCB15 multiplier as the
bound (the whole reason the fused multiplier's 3x headroom is
recoverable).

Bit-identity: digits, skip/sign derivation, gather, RCB15 add, and
update replicate the EXACT op sequence of msm_jax._bucket_scan /
_bucket_scan_signed with fully-reduced canonical intermediates, so the
output planes are limb-identical to the XLA path at the same group
width (tests/test_msm_pallas.py), and everything downstream (fold /
finish / proof bytes) is unchanged. Select DPT_MSM_KERNEL=pallas|xla
(auto: pallas on TPU); the XLA scan remains the parity/debug core
exactly like DPT_NTT_RADIX=2.
"""

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..constants import FQ_LIMBS
from . import autotune
from .curve_pallas import add_mixed_val, consts_env, fq_consts, _mod_sub
from .field_jax import pack_limb_pairs, unpack_limb_pairs

# op-word encoding shared by the wrapper (XLA side) and the kernel:
# bits [0, 8) bucket index, bit 8 negate-y, bit 9 skip (zero digit /
# infinity / lane padding)
_NEG_BIT = 8
_SKIP_BIT = 9

# peak VMEM the resident bucket planes may occupy (3 coords x rows x
# B x lanes x 4 B); the lane tile shrinks to fit
_VMEM_MB_DEFAULT = 6
_VMEM_MB = int(os.environ.get("DPT_MSM_PALLAS_VMEM_MB",
                              str(_VMEM_MB_DEFAULT)))


def _vmem_mb():
    """Per-call plane budget: env/patched attr > autotune plan winner
    > default (same precedence as ntt_pallas._vmem_mb)."""
    return int(autotune.attr_or_plan(
        _VMEM_MB, _VMEM_MB_DEFAULT, "DPT_MSM_PALLAS_VMEM_MB",
        "msm", "vmem_mb", None, cast=int))


def plane_lanes_cap(n_buckets, packed):
    """Largest power-of-two G*Mt lane count whose PER-LANE VMEM footprint
    fits the budget (>= 8 so degenerate budgets still run). Charged per
    lane: the three resident bucket-plane scratches plus their
    same-shaped output windows (revisited across the step grid axis, so
    they occupy VMEM alongside the scratch), the f32 multiplier scratch
    (4*L x 6*lanes), and the op-word block; the per-group point tile is
    amortized over Mt lanes and left out."""
    rows = FQ_LIMBS // 2 if packed else FQ_LIMBS
    per_lane = (6 * rows * n_buckets * 4   # planes: scratch + out window
                + 4 * FQ_LIMBS * 6 * 4     # mul scratch t_ref
                + 4)                       # op words
    cap = (_vmem_mb() << 20) // per_lane
    return max(8, 1 << max(3, cap.bit_length() - 1))


def _bucket_kernel(sx_ref, sy_ref, ops_ref, ox_ref, oy_ref, oz_ref,
                   px_ref, py_ref, pz_ref, t_ref, *, kc, n_buckets,
                   signed, packed, steps, mt, one_rows):
    """One (window-tile, step) grid cell: gather + RCB15 mixed add +
    update on the VMEM-resident planes.

    px/py/pz scratch: (rows, B, L) u32 bucket planes, L = G*Mt lanes
    (lane l = g*Mt + ml), persisted across the `steps` grid axis.
    sx/sy: one (24, G) affine Montgomery point tile. ops: (L,) op words.
    ox/oy/oz: (rows, B, L) plane outputs, written on the last step.
    """
    k = consts_env(kc)
    L = k["n_limbs"]
    s = pl.program_id(1)
    plane_shape = px_ref.shape

    @pl.when(s == 0)
    def _init():
        # projective identity (0 : 1 : 0), row-packed like the carries
        zero = jnp.zeros(plane_shape, jnp.uint32)
        one_col = jnp.concatenate(
            [jnp.full((1, 1, 1), int(v), jnp.uint32) for v in one_rows],
            axis=0)
        px_ref[...] = zero
        py_ref[...] = jnp.broadcast_to(one_col, plane_shape)
        pz_ref[...] = zero

    ops = ops_ref[...].reshape(1, ops_ref.shape[-1])      # (1, lanes)
    idx = ops & (n_buckets - 1)
    negb = ((ops >> _NEG_BIT) & 1) != 0
    skipb = ((ops >> _SKIP_BIT) & 1) != 0

    # one-hot bucket gather: at most one hit per lane along the bucket
    # (sublane) axis, so the masked sum IS the per-lane bucket value.
    # The mask is built at FULL rank (iota directly over (1, B, L), the
    # compare against a trailing-1 reshape) — the same structural shape
    # as the XLA onehot path, which analysis/bounds.py recognizes; a
    # reshape AFTER the eq would drop the one-hot tag and the verifier
    # would multiply the sum bound by B
    hit = (lax.broadcasted_iota(jnp.uint32, (1,) + plane_shape[1:], 1)
           == idx[:, None, :])
    cur_p = tuple(
        jnp.sum(jnp.where(hit, r[...], 0), axis=1, dtype=jnp.uint32)
        for r in (px_ref, py_ref, pz_ref))
    if packed:
        cur = tuple(unpack_limb_pairs(c) for c in cur_p)
    else:
        cur = cur_p
    cur = tuple(c.astype(jnp.int32) for c in cur)

    sx = sx_ref[...].reshape(FQ_LIMBS, sx_ref.shape[-1]).astype(jnp.int32)
    sy = sy_ref[...].reshape(FQ_LIMBS, sy_ref.shape[-1]).astype(jnp.int32)
    if signed:
        # negate once per point tile (the XLA scan's FJ.neg), select per
        # lane after the window broadcast
        nsy = _mod_sub(jnp.zeros_like(sy), sy, L, k["p_col"])
        qy = jnp.where(negb, jnp.repeat(nsy, mt, axis=1),
                       jnp.repeat(sy, mt, axis=1))
    else:
        qy = jnp.repeat(sy, mt, axis=1)
    sxb = jnp.repeat(sx, mt, axis=1)

    res = add_mixed_val(t_ref, k, cur, (sxb, qy))
    nv = tuple(jnp.where(skipb, c, r).astype(jnp.uint32)
               for c, r in zip(cur, res))
    if packed:
        nv = tuple(pack_limb_pairs(v) for v in nv)
    for r, v in zip((px_ref, py_ref, pz_ref), nv):
        r[...] = jnp.where(hit, v[:, None, :], r[...])

    @pl.when(s == steps - 1)
    def _flush():
        ox_ref[0] = px_ref[...]
        oy_ref[0] = py_ref[...]
        oz_ref[0] = pz_ref[...]


@functools.partial(jax.jit,
                   static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _bucket_call(interpret, group, n_buckets, signed, packed, mt, wt,
                 sx, sy, ops):
    """(steps, 24, G) points + (Wt, steps, G*Mt) op words -> 3 x
    (Wt, rows, B, G*Mt) u32 planes."""
    from jax.experimental.pallas import tpu as pltpu
    from .field_jax import FQ
    from .limbs import int_to_limbs
    from ..constants import FQ_MONT_R, Q_MOD

    steps = sx.shape[0]
    lanes = group * mt
    rows = FQ_LIMBS // 2 if packed else FQ_LIMBS
    one = int_to_limbs(FQ_MONT_R % Q_MOD, FQ_LIMBS)
    if packed:
        one_rows = tuple(int(one[2 * i]) | (int(one[2 * i + 1]) << 16)
                         for i in range(FQ_LIMBS // 2))
    else:
        one_rows = tuple(int(v) for v in one)
    kernel = functools.partial(
        _bucket_kernel, kc=fq_consts(), n_buckets=n_buckets,
        signed=signed, packed=packed, steps=steps, mt=mt,
        one_rows=one_rows)
    pt_spec = pl.BlockSpec((1, FQ_LIMBS, group), lambda w, s: (s, 0, 0))
    plane_spec = pl.BlockSpec((1, rows, n_buckets, lanes),
                              lambda w, s: (w, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((wt, rows, n_buckets, lanes),
                                        jnp.uint32)] * 3,
        grid=(wt, steps),
        in_specs=[pt_spec, pt_spec,
                  pl.BlockSpec((1, 1, lanes), lambda w, s: (w, s, 0))],
        out_specs=[plane_spec] * 3,
        scratch_shapes=[pltpu.VMEM((rows, n_buckets, lanes), jnp.uint32)
                        for _ in range(3)]
        + [pltpu.VMEM((4 * FQ.n_limbs, 6 * lanes), jnp.float32)],
        interpret=interpret,
    )(sx, sy, ops)


def _scan_pallas(ax, ay, ops, group, n_buckets, signed, packed):
    """Shared wrapper: (24, n) points + (M, n) op words ->
    ((24, G, M, B),)*3 planes, laid out exactly like the XLA scans."""
    from .msm_jax import _scan_layout, _to_scan_m

    M, n = ops.shape
    steps = n // group
    sx, sy = _scan_layout(ax, ay, group)
    sops = _to_scan_m(ops, group)                    # (steps, G, M)

    cap = plane_lanes_cap(n_buckets, packed)
    mt = max(1, min(M, cap // group))
    wt = -(-M // mt)
    pad = wt * mt - M
    if pad:
        sops = jnp.pad(sops, ((0, 0), (0, 0), (0, pad)),
                       constant_values=np.uint32(1 << _SKIP_BIT))
    # (steps, G, Wt, Mt) -> (Wt, steps, G*Mt): lane l = g*Mt + ml
    sops = sops.reshape(steps, group, wt, mt).transpose(2, 0, 1, 3)
    sops = sops.reshape(wt, steps, group * mt)

    interpret = jax.default_backend() != "tpu"
    outs = _bucket_call(interpret, group, n_buckets, signed, packed,
                        mt, wt, sx, sy, sops)
    planes = []
    for o in outs:
        rows = o.shape[1]
        o = o.reshape(wt, rows, n_buckets, group, mt)
        # (w, r, b, g, ml) -> (r, g, w, ml, b) -> (r, g, M, b)
        o = o.transpose(1, 3, 0, 4, 2).reshape(
            rows, group, wt * mt, n_buckets)[:, :, :M]
        if packed:
            o = unpack_limb_pairs(o)
        planes.append(o)
    return tuple(planes)


def bucket_scan(ax, ay, ainf, digits, group, n_buckets, packed=True):
    """Fused-kernel counterpart of msm_jax._bucket_scan (unsigned):
    identical signature and bit-identical ((24, G, M, B),)*3 planes."""
    ops = digits | (ainf[None].astype(jnp.uint32) << _SKIP_BIT)
    return _scan_pallas(ax, ay, ops, group, n_buckets,
                        signed=False, packed=packed)


def bucket_scan_signed(ax, ay, ainf, packed_digits, group,
                       n_buckets=128, packed=True):
    """Fused-kernel counterpart of msm_jax._bucket_scan_signed: the
    sign/skip/index derivation matches the XLA scan step for step."""
    off = packed_digits.astype(jnp.int32) - n_buckets
    neg = off < 0
    mag = jnp.abs(off)
    skip = (mag == 0) | ainf[None]
    idx = jnp.maximum(mag, 1).astype(jnp.uint32) - 1
    ops = (idx
           | (neg.astype(jnp.uint32) << _NEG_BIT)
           | (skip.astype(jnp.uint32) << _SKIP_BIT))
    return _scan_pallas(ax, ay, ops, group, n_buckets,
                        signed=True, packed=packed)
