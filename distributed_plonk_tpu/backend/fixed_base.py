"""Fixed-base batch scalar multiplication on device: the SRS generator.

The reference gets its SRS from jf-plonk's `universal_setup`
(/root/reference/src/dispatcher2.rs:1279), a serial fixed-base walk
[tau^0]G, [tau^1]G, ... on the host. That walk is the scale blocker for
reference-size domains (2^18 powers = 2^18 sequential scalar muls), so here
it becomes one device program: a windowed fixed-base table is precomputed
once on the host (the base is a single public generator — 32 windows x 256
multiples, ~8k cheap host adds), and the batch [s_i]G for all N scalars is
a lax.scan over the 32 windows whose body gathers each scalar's digit row
from the table and performs ONE vectorized Jacobian add across the whole
batch. Like the MSM pipeline (msm_jax.py), the traced program contains a
single jac_add instance, so compile time is O(1) in N.

The result stays on device as Jacobian Montgomery limb arrays and feeds the
MSM directly (MsmContext.from_jacobian) — the commit key never needs to be
normalized to affine on the host for the prover path.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import FQ_MONT_R, Q_MOD, FQ_LIMBS
from .. import curve as C
from . import curve_jax as CJ
from .limbs import ints_to_limbs
from .msm_jax import SCALAR_BITS, digits_of_scalars

WINDOW_BITS = 8
N_WINDOWS = SCALAR_BITS // WINDOW_BITS  # 32
N_BUCKETS = 1 << WINDOW_BITS  # 256


def _host_window_table(base_affine):
    """(N_WINDOWS, N_BUCKETS) table of d * 2^(8w) * base as host Jacobian
    int tuples; table[w][0] is the point at infinity."""
    inf = (1, 1, 0)
    table = []
    b = C.g1_to_jac(base_affine)
    for _ in range(N_WINDOWS):
        row = [inf]
        acc = inf
        for _ in range(N_BUCKETS - 1):
            acc = C.g1_jac_add(acc, b)
            row.append(acc)
        table.append(row)
        for _ in range(WINDOW_BITS):
            b = C.g1_jac_double(b)
    return table


def _table_to_device(table):
    """Host Jacobian int table -> ((24, W, B),)*3 Montgomery limb arrays."""
    flat = [p for row in table for p in row]
    coords = []
    for k in range(3):
        vals = [p[k] * FQ_MONT_R % Q_MOD for p in flat]
        arr = ints_to_limbs(vals, FQ_LIMBS).reshape(FQ_LIMBS, N_WINDOWS, N_BUCKETS)
        coords.append(jnp.asarray(arr))
    return tuple(coords)


def _batch_mul_kernel(tx, ty, tz, digits):
    """digits: (W, N) uint32 -> ((24, N),)*3 Jacobian sum over windows."""
    init = CJ.pt_inf((digits.shape[1],))

    def step(acc, x):
        sx, sy, sz, dg = x  # (24, B) table row + (N,) digit column
        return CJ.jac_add(acc, (sx[:, dg], sy[:, dg], sz[:, dg])), None

    xs = (tx.transpose(1, 0, 2), ty.transpose(1, 0, 2), tz.transpose(1, 0, 2),
          digits)
    acc, _ = lax.scan(step, init, xs)
    return acc


class FixedBaseContext:
    """Device-resident windowed table for one base point; reusable across
    batches (the table for G1 is built once per process)."""

    def __init__(self, base_affine):
        self.table = _table_to_device(_host_window_table(base_affine))
        self._fn = jax.jit(_batch_mul_kernel)

    def batch_mul(self, scalars):
        """[s_i]base for host int scalars -> ((24, N),)*3 device Jacobian."""
        digits = digits_of_scalars(scalars, len(scalars), WINDOW_BITS)
        return self._fn(*self.table, digits)


_G1_CTX = None


def g1_batch_mul(scalars):
    """[s_i]G1 on device, with the G1 table cached process-wide."""
    global _G1_CTX
    if _G1_CTX is None:
        _G1_CTX = FixedBaseContext(C.G1_GEN)
    return _G1_CTX.batch_mul(scalars)
