"""Fixed-base batch scalar multiplication on device: the SRS generator.

The reference gets its SRS from jf-plonk's `universal_setup`
(/root/reference/src/dispatcher2.rs:1279), a serial fixed-base walk
[tau^0]G, [tau^1]G, ... on the host. That walk is the scale blocker for
reference-size domains (2^18 powers = 2^18 sequential scalar muls), so here
it becomes one device program: a windowed fixed-base table is precomputed
once on the host (the base is a single public generator — 32 windows x 256
multiples, ~8k cheap host adds, normalized to AFFINE with one batched
inversion), and the batch [s_i]G for all N scalars is a lax.scan over the
32 windows whose body gathers each scalar's digit row from the table and
performs ONE vectorized COMPLETE projective mixed add (RCB15; no edge
handling, 11 muls in 2 stacked-lane instances) across the whole batch.
Like the MSM pipeline (msm_jax.py), the traced program contains a single
add instance, so compile time is O(1) in N.

The result converts to Jacobian in-kernel (3 muls per point: (XZ, YZ^2,
Z)) and stays on device as Montgomery limb arrays feeding DeviceCommitKey
— the commit key never needs host affine normalization for the prover
path.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import Q_MOD, FQ_LIMBS
from .. import curve as C
from . import curve_jax as CJ
from . import field_jax as FJ
from .field_jax import FQ
from .msm_jax import SCALAR_BITS, digits_of_scalars, points_to_device

WINDOW_BITS = 8
N_WINDOWS = SCALAR_BITS // WINDOW_BITS  # 32
N_BUCKETS = 1 << WINDOW_BITS  # 256


def _host_window_table(base_affine):
    """(N_WINDOWS, N_BUCKETS) table of d * 2^(8w) * base as host AFFINE
    tuples (None at index 0); one batched inversion normalizes the whole
    Jacobian walk."""
    inf = (1, 1, 0)
    table = []
    b = C.g1_to_jac(base_affine)
    for _ in range(N_WINDOWS):
        row = [inf]
        acc = inf
        for _ in range(N_BUCKETS - 1):
            acc = C.g1_jac_add(acc, b)
            row.append(acc)
        table.append(row)
        for _ in range(WINDOW_BITS):
            b = C.g1_jac_double(b)
    # batch-invert all Z coordinates (Montgomery's trick, host ints)
    flat = [p for row in table for p in row]
    zs = [p[2] if p[2] else 1 for p in flat]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % Q_MOD)
    inv_total = pow(prefix[-1], Q_MOD - 2, Q_MOD)
    invs = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        invs[i] = prefix[i] * inv_total % Q_MOD
        inv_total = inv_total * zs[i] % Q_MOD
    out = []
    for p, zi in zip(flat, invs):
        if p[2] == 0:
            out.append(None)
        else:
            zi2 = zi * zi % Q_MOD
            out.append((p[0] * zi2 % Q_MOD, p[1] * zi2 * zi % Q_MOD))
    return [out[w * N_BUCKETS:(w + 1) * N_BUCKETS] for w in range(N_WINDOWS)]


def _table_to_device(table):
    """Host affine table -> ((24, W, B) x, (24, W, B) y, (W, B) inf),
    encoded by the same converter the MSM bases use."""
    flat = [p for row in table for p in row]
    x, y, inf = points_to_device(flat, 0)
    tx = jnp.asarray(x.reshape(FQ_LIMBS, N_WINDOWS, N_BUCKETS))
    ty = jnp.asarray(y.reshape(FQ_LIMBS, N_WINDOWS, N_BUCKETS))
    return tx, ty, jnp.asarray(inf.reshape(N_WINDOWS, N_BUCKETS))


def _batch_mul_kernel(tx, ty, tinf, digits):
    """digits: (W, N) uint32 -> ((24, N),)*3 Jacobian sum over windows
    (accumulated with complete projective mixed adds, converted to
    Jacobian at the end)."""
    init = CJ.proj_inf((digits.shape[1],))

    def step(acc, x):
        sx, sy, si, dg = x  # (24, B) affine table row + (N,) digit column
        return CJ.proj_add_mixed(acc, (sx[:, dg], sy[:, dg]), si[dg]), None

    xs = (tx.transpose(1, 0, 2), ty.transpose(1, 0, 2), tinf, digits)
    (X, Y, Z), _ = lax.scan(step, init, xs)
    # projective (X : Y : Z) == Jacobian (X*Z, Y*Z^2, Z)
    xz = FJ.mont_mul(FQ, X, Z)
    z2 = FJ.mont_mul(FQ, Z, Z)
    yz2 = FJ.mont_mul(FQ, Y, z2)
    return xz, yz2, Z


class FixedBaseContext:
    """Device-resident windowed table for one base point; reusable across
    batches (the table for G1 is built once per process)."""

    # lanes per device call: one mont_mul's f32 byte-column transient is
    # ~18 KB/lane (measured: a 2^18-lane call allocates 24 GB and OOMs a
    # 16 GB v5e), so the batch walk is chunked. 2^15 lanes ≈ 3 GB peak.
    _CHUNK = int(__import__("os").environ.get("DPT_FIXED_BASE_CHUNK",
                                              str(1 << 15)))

    def __init__(self, base_affine):
        self.table = _table_to_device(_host_window_table(base_affine))
        self._fn = jax.jit(_batch_mul_kernel)

    def batch_mul(self, scalars):
        """[s_i]base for host int scalars -> ((24, N),)*3 device Jacobian."""
        n = len(scalars)
        if n <= self._CHUNK:  # common small case: one compile at its own shape
            digits = digits_of_scalars(scalars, n, WINDOW_BITS)
            return self._fn(*self.table, digits)
        # multi-chunk: zero-pad the tail to _CHUNK so exactly ONE kernel
        # shape compiles regardless of n ([0]G rows are sliced off below)
        padded = list(scalars) + [0] * ((-n) % self._CHUNK)
        parts = []
        for i0 in range(0, len(padded), self._CHUNK):
            digits = digits_of_scalars(padded[i0:i0 + self._CHUNK],
                                       self._CHUNK, WINDOW_BITS)
            parts.append(self._fn(*self.table, digits))
        return tuple(jnp.concatenate([p[i] for p in parts], axis=1)[:, :n]
                     for i in range(3))


_G1_CTX = None


def g1_batch_mul(scalars):
    """[s_i]G1 on device, with the G1 table cached process-wide."""
    global _G1_CTX
    if _G1_CTX is None:
        _G1_CTX = FixedBaseContext(C.G1_GEN)
    return _G1_CTX.batch_mul(scalars)
