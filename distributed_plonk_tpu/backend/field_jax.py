"""Vectorized prime-field arithmetic for TPU: 16-bit limbs in uint32 lanes.

This is the device replacement for the reference's `ark-ff` field layer
(/root/reference/Cargo.toml:31-37). TPU integer units have no 64-bit multiply,
so elements are radix-2^16 little-endian limb vectors on the LEADING axis
(shape (L, *batch), see limbs.py): a 16x16-bit limb product fits a uint32
exactly, and column sums of <= 2*L such products stay under 2^23 < 2^32, so
schoolbook products accumulate carry-free before one exact carry sweep.

Multiplication is Montgomery (SOS variant: full product, one low half-product
by -p^-1 mod R, one full product by p, one shift) with R = 2^256 (Fr) /
2^384 (Fq) — the same Montgomery radix arkworks uses, so Montgomery-form
values are bit-compatible with the reference's in-memory representation.

All functions are shape-polymorphic over the batch dims and jit-safe (static
limb counts, no data-dependent control flow).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

# Persistent compilation cache: limb-arithmetic graphs are large (O(log n)
# fused stages, ~1k ops each) and compile time dominates cold-start
# wall-clock. Defer to the standard JAX env knob when the user set it.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    _default_cache = os.environ.get(
        "DPT_JAX_CACHE_DIR",
        os.path.normpath(os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", _default_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - older jax without these options
        pass

from ..constants import (
    LIMB_BITS,
    LIMB_MASK,
    FR_LIMBS,
    FQ_LIMBS,
    R_MOD,
    Q_MOD,
    FR_MONT_R2,
    FR_MONT_INV,
    FQ_MONT_R2,
    FQ_MONT_INV,
)
from .limbs import int_to_limbs


class FieldSpec:
    """Static per-field constants (host numpy; embedded into jit traces)."""

    def __init__(self, name, mod, n_limbs, mont_r2, mont_inv):
        self.name = name
        self.mod = mod
        self.n_limbs = n_limbs
        self.mod_limbs = int_to_limbs(mod, n_limbs)
        self.r2_limbs = int_to_limbs(mont_r2, n_limbs)
        # full-width -p^-1 mod 2^(16L) for the SOS reduction low half-product
        self.ninv_limbs = int_to_limbs(mont_inv, n_limbs)
        self.one_limbs = int_to_limbs(1, n_limbs)


FR = FieldSpec("Fr", R_MOD, FR_LIMBS, FR_MONT_R2, FR_MONT_INV)
FQ = FieldSpec("Fq", Q_MOD, FQ_LIMBS, FQ_MONT_R2, FQ_MONT_INV)


def _bcast_const(limbs, ndim):
    """(L,) host constant -> (L, 1, ..., 1) for broadcasting against batch."""
    return jnp.asarray(limbs).reshape(limbs.shape + (1,) * (ndim - 1))


def _carry_sweep(cols):
    """Exact carry propagation. cols: (K, *batch) uint32 with entries < 2^23.

    Returns (limbs, carry_out): limbs (K, *batch) all < 2^16, carry_out the
    overflow past the top limb (zero whenever the caller's bound guarantees
    the value fits in K limbs).
    """
    k = cols.shape[0]
    outs = []
    carry = jnp.zeros_like(cols[0])
    for i in range(k):
        v = cols[i] + carry
        outs.append(v & LIMB_MASK)
        carry = v >> LIMB_BITS
    return jnp.stack(outs, axis=0), carry


def _mul_columns(a, b, out_limbs):
    """Carry-free column sums of the product, truncated to out_limbs limbs."""
    la = a.shape[0]
    lb = b.shape[0]
    cols = jnp.zeros((out_limbs,) + a.shape[1:], dtype=jnp.uint32)
    for i in range(min(la, out_limbs)):
        width = min(lb, out_limbs - i)
        p = a[i] * b[:width]  # (width, *batch), each product < 2^32
        lo = p & LIMB_MASK
        hi = p >> LIMB_BITS
        cols = cols.at[i:i + width].add(lo)
        hi_width = min(lb, out_limbs - i - 1)
        if hi_width > 0:
            cols = cols.at[i + 1:i + 1 + hi_width].add(hi[:hi_width])
    return cols


def _mul_full(a, b):
    """Exact product: (La, *b) x (Lb, *b) -> (La+Lb, *b) carried limbs."""
    cols = _mul_columns(a, b, a.shape[0] + b.shape[0])
    limbs, carry = _carry_sweep(cols)
    del carry  # exact product fits in La+Lb limbs
    return limbs


def _mul_low(a, b, out_limbs):
    """Product mod 2^(16*out_limbs), carried limbs."""
    cols = _mul_columns(a, b, out_limbs)
    limbs, _ = _carry_sweep(cols)
    return limbs


def _add_limbs(a, b):
    """Limbwise add with carry sweep; final carry returned separately."""
    n = max(a.shape[0], b.shape[0])
    outs = []
    carry = jnp.zeros_like(a[0])
    for i in range(n):
        v = carry
        if i < a.shape[0]:
            v = v + a[i]
        if i < b.shape[0]:
            v = v + b[i]
        outs.append(v & LIMB_MASK)
        carry = v >> LIMB_BITS
    return jnp.stack(outs, axis=0), carry


def _sub_limbs(a, b):
    """a - b mod 2^(16L) with final borrow flag (1 iff a < b)."""
    n = a.shape[0]
    outs = []
    borrow = jnp.zeros_like(a[0])
    for i in range(n):
        bi = b[i] if i < b.shape[0] else jnp.zeros_like(a[i])
        need = bi + borrow  # <= 2^16, fits
        v = (a[i] - need) & LIMB_MASK
        borrow = (a[i] < need).astype(jnp.uint32)
        outs.append(v)
    return jnp.stack(outs, axis=0), borrow


def _cond_sub_mod(spec, t):
    """t - p if t >= p else t  (t < 2p)."""
    p = _bcast_const(spec.mod_limbs, t.ndim)
    d, borrow = _sub_limbs(t, p)
    keep = (borrow == 1)
    return jnp.where(keep[None], t, d)


def add(spec, a, b):
    s, carry = _add_limbs(a, b)
    del carry  # a, b < p  =>  a+b < 2p < 2^(16L)
    return _cond_sub_mod(spec, s)


def sub(spec, a, b):
    d, borrow = _sub_limbs(a, b)
    p = _bcast_const(spec.mod_limbs, a.ndim)
    dp, _ = _add_limbs(d, p)  # wraps mod 2^(16L): restores a-b+p when a < b
    return jnp.where((borrow == 1)[None], dp, d)


def neg(spec, a):
    zero = jnp.zeros_like(a)
    return sub(spec, zero, a)


def mont_mul(spec, a, b):
    """Montgomery product: a*b*R^-1 mod p, inputs/outputs reduced (< p)."""
    l = spec.n_limbs
    t = _mul_full(a, b)  # 2L limbs, < p^2
    ninv = _bcast_const(spec.ninv_limbs, a.ndim)
    m = _mul_low(t[:l], ninv, l)  # m = (t mod R) * (-p^-1) mod R
    p = _bcast_const(spec.mod_limbs, a.ndim)
    mp = _mul_full(m, p)  # 2L limbs, < R*p
    s, carry = _add_limbs(t, mp)  # t + m*p  ==  0 mod R,  < R*p + p^2 < R^2
    del carry
    return _cond_sub_mod(spec, s[l:])  # (t + m*p) / R < 2p


def to_mont(spec, a):
    return mont_mul(spec, a, _bcast_const(spec.r2_limbs, a.ndim) * jnp.ones_like(a[:1]))


def from_mont(spec, a):
    one = _bcast_const(spec.one_limbs, a.ndim) * jnp.ones_like(a[:1])
    return mont_mul(spec, a, one)


def mont_sq(spec, a):
    return mont_mul(spec, a, a)


def is_zero(spec, a):
    return jnp.all(a == 0, axis=0)


def eq(spec, a, b):
    return jnp.all(a == b, axis=0)


def select(cond, a, b):
    """cond: (*batch,) bool; a, b: (L, *batch) -> where(cond, a, b)."""
    return jnp.where(cond[None], a, b)


def double(spec, a):
    return add(spec, a, a)
