"""Vectorized prime-field arithmetic for TPU: 16-bit limbs in uint32 lanes.

This is the device replacement for the reference's `ark-ff` field layer
(/root/reference/Cargo.toml:31-37). TPU integer units have no 64-bit multiply,
so elements are radix-2^16 little-endian limb vectors on the LEADING axis
(shape (L, *batch), see limbs.py): a 16x16-bit limb product fits a uint32
exactly, and column sums of <= 2*L such products stay under 2^23 < 2^32, so
schoolbook products accumulate carry-free before one exact carry sweep.

Multiplication is Montgomery (SOS variant: full product, one low half-product
by -p^-1 mod R, one full product by p, one shift) with R = 2^256 (Fr) /
2^384 (Fq) — the same Montgomery radix arkworks uses, so Montgomery-form
values are bit-compatible with the reference's in-memory representation.

All functions are shape-polymorphic over the batch dims and jit-safe (static
limb counts, no data-dependent control flow).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

# Persistent compilation cache: limb-arithmetic graphs are large (O(log n)
# fused stages, ~1k ops each) and compile time dominates cold-start
# wall-clock. Defer to the standard JAX env knob when the user set it.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    _default_cache = os.environ.get(
        "DPT_JAX_CACHE_DIR",
        os.path.normpath(os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", _default_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - older jax without these options
        pass

from ..constants import (
    LIMB_BITS,
    LIMB_MASK,
    FR_LIMBS,
    FQ_LIMBS,
    R_MOD,
    Q_MOD,
    FR_MONT_R2,
    FR_MONT_INV,
    FQ_MONT_R2,
    FQ_MONT_INV,
)
from .limbs import int_to_limbs


class FieldSpec:
    """Static per-field constants (host numpy; embedded into jit traces)."""

    def __init__(self, name, mod, n_limbs, mont_r2, mont_inv):
        self.name = name
        self.mod = mod
        self.n_limbs = n_limbs
        self.mod_limbs = int_to_limbs(mod, n_limbs)
        self.r2_limbs = int_to_limbs(mont_r2, n_limbs)
        # full-width -p^-1 mod 2^(16L) for the SOS reduction low half-product
        self.ninv_limbs = int_to_limbs(mont_inv, n_limbs)
        self.one_limbs = int_to_limbs(1, n_limbs)
        # 2^(16L) - p: adding it == subtracting p, with the sweep's carry
        # bit flagging whether the subtraction stayed nonnegative
        self.negmod_limbs = int_to_limbs((1 << (LIMB_BITS * n_limbs)) - mod,
                                         n_limbs)


FR = FieldSpec("Fr", R_MOD, FR_LIMBS, FR_MONT_R2, FR_MONT_INV)
FQ = FieldSpec("Fq", Q_MOD, FQ_LIMBS, FQ_MONT_R2, FQ_MONT_INV)


def _bcast_const(limbs, ndim):
    """(L,) host constant -> (L, 1, ..., 1) for broadcasting against batch."""
    return jnp.asarray(limbs).reshape(limbs.shape + (1,) * (ndim - 1))


def _carry_sweep(cols):
    """Exact carry propagation. cols: (K, *batch) uint32 with entries < 2^23.

    Returns (limbs, carry_out): limbs (K, *batch) all < 2^16, carry_out the
    overflow past the top limb (zero whenever the caller's bound guarantees
    the value fits in K limbs).

    Log-depth Kogge-Stone instead of a K-step ripple chain: pre-add each
    column's high bits into the next column (s_i = lo_i + hi_{i-1} < 2^17,
    so the residual inter-limb carry is a single bit), then resolve the
    bit-carry recurrence b_i = G_i | (P_i & b_{i-1}) with an associative
    scan over (generate, propagate) pairs. Traced ops: O(log K), and the
    work is whole-array passes (VPU-friendly) rather than per-limb rows.
    """
    lo = cols & LIMB_MASK
    hi = cols >> LIMB_BITS
    zero_row = jnp.zeros_like(hi[:1])
    s = lo + jnp.concatenate([zero_row, hi[:-1]], axis=0)  # s_i < 2^17

    def shift_down(x, k):  # x[i] -> x[i-k], zeros shifted in at the bottom
        return jnp.concatenate([jnp.zeros_like(x[:k]), x[:-k]], axis=0)

    gen = s > LIMB_MASK
    prop = s == LIMB_MASK
    k = 1
    while k < s.shape[0]:  # hand-rolled KS: cheaper lowering than
        gen = gen | (prop & shift_down(gen, k))  # associative_scan here
        prop = prop & shift_down(prop, k)
        k *= 2
    b_in = shift_down(gen, 1)
    limbs = (s + b_in) & LIMB_MASK
    carry = hi[-1] + gen[-1]
    return limbs, carry


def _skew_colsum(m, shift):
    """Anti-diagonal column sums: out[k] = Σ_i m[i, k - i - shift].

    m: (rows, w, *batch). Each row i is logically shifted right by i+shift,
    then columns are summed — computed with pure pad/reshape/slice/reduce
    (row i of the flattened (rows, W-1) view starts at i·(W-1) = i·W - i,
    i.e. sits i slots earlier, which IS the skew), so the traced program is
    O(1) ops instead of an O(rows) chain of dynamic-update-slices. Entries
    must be < 2^16 so sums of <= rows <= 48 terms stay far below 2^32.
    """
    rows, w = m.shape[0], m.shape[1]
    batch = m.shape[2:]
    pad = [(0, 0)] * m.ndim
    pad[1] = (shift, rows)
    mp = jnp.pad(m, pad)  # (rows, W) with W = w + shift + rows
    W = w + shift + rows
    flat = mp.reshape((rows * W,) + batch)
    skewed = flat[: rows * (W - 1)].reshape((rows, W - 1) + batch)
    return jnp.sum(skewed, axis=0, dtype=jnp.uint32)  # (W-1, *batch)


def _mul_columns(a, b, out_limbs):
    """Carry-free column sums of the product, truncated to out_limbs limbs."""
    la, lb = a.shape[0], b.shape[0]
    p = a[:, None] * b[None, :]  # (la, lb, *batch), each product < 2^32
    lo = _skew_colsum(p & LIMB_MASK, 0)  # cols 0 .. la+lb-2
    hi = _skew_colsum(p >> LIMB_BITS, 1)  # cols 1 .. la+lb-1
    lo = lo[:out_limbs]
    hi = hi[:out_limbs]
    if lo.shape[0] < out_limbs:
        lo = jnp.pad(lo, [(0, out_limbs - lo.shape[0])] + [(0, 0)] * (lo.ndim - 1))
    if hi.shape[0] < out_limbs:
        hi = jnp.pad(hi, [(0, out_limbs - hi.shape[0])] + [(0, 0)] * (hi.ndim - 1))
    return lo + hi


def _pad_rows(a, n):
    if a.shape[0] == n:
        return a
    return jnp.pad(a, [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1))


def _sweep_pair(cols_a, cols_b):
    """Carry-sweep two column vectors in ONE vectorized sweep.

    Stacks them on a lane axis so the log-depth carry machinery is traced
    once; returns ((limbs_a, limbs_b), (carry_a, carry_b)).
    """
    pair = jnp.stack([cols_a, cols_b], axis=1)  # (K, 2, *batch)
    limbs, carry = _carry_sweep(pair)
    return (limbs[:, 0], limbs[:, 1]), (carry[0], carry[1])


def _cond_sub_mod(spec, cols):
    """Value of `cols` reduced once: v - p if v >= p else v  (v < 2p).

    Takes UNCARRIED columns (< 2^23 each) and resolves both candidates with
    a single paired sweep: lane2 adds 2^(16L) - p, whose carry-out flags
    v >= p.
    """
    negp = _bcast_const(spec.negmod_limbs, cols.ndim)
    (t, d), (_, c2) = _sweep_pair(cols, cols + negp)
    return jnp.where((c2 != 0)[None], d, t)


def add(spec, a, b):
    """a + b mod p (inputs < p): one paired sweep."""
    return _cond_sub_mod(spec, a + b)


def sub(spec, a, b):
    """a - b mod p (inputs < p): one paired sweep.

    Lane1 = a + ~b + 1 (= a-b mod 2^(16L); carries iff a >= b);
    lane2 = lane1 + p (the wrapped-around candidate).
    """
    nb = (_pad_rows(b, a.shape[0]) ^ LIMB_MASK)
    base = (a + nb).at[0].add(1)
    p = _bcast_const(spec.mod_limbs, a.ndim)
    (d, dp), (c1, _) = _sweep_pair(base, base + p)
    return jnp.where((c1 != 0)[None], d, dp)


def neg(spec, a):
    zero = jnp.zeros_like(a)
    return sub(spec, zero, a)


def mont_mul(spec, a, b):
    """Montgomery product: a*b*R^-1 mod p, inputs/outputs reduced (< p).

    SOS with column-level accumulation: the three partial products stay as
    uncarried column sums (each < 2^22, so sums of two < 2^23 are still
    exact in u32) and only four short sweeps run: t mod R; m; the low-half
    carry-out of t + m*p (those limbs are identically 0 mod R); and the
    final reduce of the uncarried high half (t + m*p)/R, folded into
    _cond_sub_mod's paired sweep.
    """
    l = spec.n_limbs
    t_cols = _mul_columns(a, b, 2 * l)  # a*b < p^2, uncarried
    t_lo, c_t = _carry_sweep(t_cols[:l])  # exact t mod R + carry into col l
    ninv = _bcast_const(spec.ninv_limbs, a.ndim)
    m, _ = _carry_sweep(_mul_columns(t_lo, ninv, l))  # m = (t mod R)*(-p^-1) mod R
    p = _bcast_const(spec.mod_limbs, a.ndim)
    mp_cols = _mul_columns(m, p, 2 * l)  # m*p < R*p, uncarried
    # low half of t + m*p is == 0 mod R: only its carry-out matters
    _, c_lo = _carry_sweep(mp_cols[:l] + t_lo)
    hi = (mp_cols[l:] + t_cols[l:]).at[0].add(c_t + c_lo)
    return _cond_sub_mod(spec, hi)  # (t + m*p) / R < 2p


def to_mont(spec, a):
    return mont_mul(spec, a, _bcast_const(spec.r2_limbs, a.ndim) * jnp.ones_like(a[:1]))


def from_mont(spec, a):
    one = _bcast_const(spec.one_limbs, a.ndim) * jnp.ones_like(a[:1])
    return mont_mul(spec, a, one)


def mont_sq(spec, a):
    return mont_mul(spec, a, a)


def is_zero(spec, a):
    return jnp.all(a == 0, axis=0)


def eq(spec, a, b):
    return jnp.all(a == b, axis=0)


def select(cond, a, b):
    """cond: (*batch,) bool; a, b: (L, *batch) -> where(cond, a, b)."""
    return jnp.where(cond[None], a, b)


def double(spec, a):
    return add(spec, a, a)
