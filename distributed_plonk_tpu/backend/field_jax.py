"""Vectorized prime-field arithmetic for TPU: 16-bit limbs in uint32 lanes.

This is the device replacement for the reference's `ark-ff` field layer
(/root/reference/Cargo.toml:31-37). TPU integer units have no 64-bit multiply,
so elements are radix-2^16 little-endian limb vectors on the LEADING axis
(shape (L, *batch), see limbs.py): a 16x16-bit limb product fits a uint32
exactly, and column sums of <= 2*L such products stay under 2^23 < 2^32, so
schoolbook products accumulate carry-free before one exact carry sweep.

Multiplication is Montgomery (SOS variant: full product, one low half-product
by -p^-1 mod R, one full product by p, one shift) with R = 2^256 (Fr) /
2^384 (Fq) — the same Montgomery radix arkworks uses, so Montgomery-form
values are bit-compatible with the reference's in-memory representation.

All functions are shape-polymorphic over the batch dims and jit-safe (static
limb counts, no data-dependent control flow).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

# Persistent compilation cache: limb-arithmetic graphs are large (O(log n)
# fused stages, ~1k ops each) and compile time dominates cold-start
# wall-clock. Defer to the standard JAX env knob when the user set it.
# The cache is partitioned per machine fingerprint: XLA:CPU AOT entries
# embed host CPU features, and loading another host's entries fails with
# "machine feature mismatch" warnings (round-2 weakness) — separate
# subdirectories make every host build/read only its own entries.
# machine_fingerprint lives in backend/autotune.py now (the calibration
# artifact key and the compile-cache partition are ONE machine identity);
# re-exported here for the existing import sites.
from .autotune import machine_fingerprint
from . import autotune


def configure_compile_cache(base_dir, min_compile_secs=1.0):
    """Point JAX's persistent compile cache at `base_dir/<machine_fp>`.

    Called at import with the repo-local default; the artifact store calls
    it again (store/warmstart.py) to move the cache under a store root so
    compiled prover stages ride the same warm-start lifecycle as keys.
    Returns the per-machine directory, or None when this jax has no
    persistent-cache config (nothing to wire)."""
    path = os.path.join(base_dir, machine_fingerprint())
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs)
    except Exception:  # pragma: no cover - older jax without these options
        return None
    return path


if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    configure_compile_cache(os.environ.get(
        "DPT_JAX_CACHE_DIR",
        os.path.normpath(os.path.join(
            os.path.dirname(__file__), "..", "..", ".jax_cache"))))

from ..constants import (
    LIMB_BITS,
    LIMB_MASK,
    FR_LIMBS,
    FQ_LIMBS,
    R_MOD,
    Q_MOD,
    FR_MONT_R2,
    FR_MONT_INV,
    FQ_MONT_R2,
    FQ_MONT_INV,
)
from .limbs import int_to_limbs


def _const_bytes(value, n_bytes):
    """Host int -> (n_bytes,) radix-2^8 little-endian digits (numpy)."""
    return np.array([(value >> (8 * i)) & 0xFF for i in range(n_bytes)],
                    dtype=np.float32)


def _toeplitz_bytes(value, in_bytes, out_bytes):
    """Constant banded (Toeplitz) matrix T with T[k, i] = byte_{k-i}(value):
    T @ a8 gives the byte-column sums of value * a for any a presented as
    (in_bytes, *batch) radix-2^8 digits — i.e. multiplication by a constant
    is literally a matmul, which XLA tiles onto the MXU (bf16 x bf16 with
    f32 accumulation; every operand is an integer <= 255, every column sum
    <= 96 * 255^2 < 2^23, so the float path is exact)."""
    bts = _const_bytes(value, in_bytes)  # constant has <= in_bytes bytes here
    T = np.zeros((out_bytes, in_bytes), dtype=np.float32)
    for k in range(out_bytes):
        for i in range(in_bytes):
            j = k - i
            if 0 <= j < in_bytes:
                T[k, i] = bts[j]
    return T


class FieldSpec:
    """Static per-field constants (host numpy; embedded into jit traces)."""

    def __init__(self, name, mod, n_limbs, mont_r2, mont_inv):
        self.name = name
        self.mod = mod
        self.n_limbs = n_limbs
        self.mod_limbs = int_to_limbs(mod, n_limbs)
        self.r2_limbs = int_to_limbs(mont_r2, n_limbs)
        # full-width -p^-1 mod 2^(16L) for the SOS reduction low half-product
        self.ninv_limbs = int_to_limbs(mont_inv, n_limbs)
        self.one_limbs = int_to_limbs(1, n_limbs)
        # 2^(16L) - p: adding it == subtracting p, with the sweep's carry
        # bit flagging whether the subtraction stayed nonnegative
        self.negmod_limbs = int_to_limbs((1 << (LIMB_BITS * n_limbs)) - mod,
                                         n_limbs)
        # MXU operands for the two constant products of Montgomery SOS
        # (t_lo * ninv mod R needs only the low half; m * p needs the full
        # double-width product) — see mont_mul
        nb = 2 * n_limbs
        self.ninv_toeplitz = _toeplitz_bytes(mont_inv % (1 << (8 * nb)), nb, nb)
        self.mod_toeplitz = _toeplitz_bytes(mod, nb, 2 * nb)


FR = FieldSpec("Fr", R_MOD, FR_LIMBS, FR_MONT_R2, FR_MONT_INV)
FQ = FieldSpec("Fq", Q_MOD, FQ_LIMBS, FQ_MONT_R2, FQ_MONT_INV)


# --- checked carry/exactness contracts ---------------------------------------
# Every _carry_sweep caller that DROPS the carry lane relies on one of the
# side conditions below: they are modular-number-theory facts about the
# field constants that per-element interval analysis (analysis/bounds.py)
# cannot derive, because the limb-column representation is redundant (a
# column vector bounds the value only up to ~2^7 x slack). They used to
# live as prose in _carry_sweep's docstring; now they are machine-checked
# inequalities over the ACTUAL moduli/limb counts — `python -m
# distributed_plonk_tpu.analysis` (and tests/test_analysis.py) evaluates
# every contract for both FieldSpecs, so a field/limb-layout change that
# silently breaks a zero-carry assumption fails CI instead of corrupting
# proofs. `R(spec)` below is the Montgomery radix 2^(16*L).
#
# These inequalities are the BOUNDS half of the story (machine arithmetic
# == exact integer semantics). The ALGEBRAIC half — mont_mul really
# computes a*b*R^-1 mod p, add/sub/neg/to_mont/from_mont their mod-p
# claims, _carry_sweep the equation value(limbs) + carry*2^(16K) ==
# value(cols) — is no longer prose either: every registered entry point
# of this module carries a value obligation the exact-evaluation pass
# (analysis/values.py via analysis/registry.py) checks at seeded +
# corner sample points, on BOTH multiplier paths. A dropped carry lane
# in the f32 path that keeps every limb in range is invisible to the
# interval pass by construction and is caught there (the seeded-mutant
# harness analysis/mutants.py proves that stays true).

def _R(spec):
    return 1 << (LIMB_BITS * spec.n_limbs)


CARRY_CONTRACTS = (
    {"name": "cond_sub_fits",
     "claim": "v < 2p fits in L limbs (2p <= R), so _cond_sub_mod/add's "
              "lane-1 sweep and sub's lane-2 wrap both have carry <= 1 "
              "and the assumed-zero carry of the reduced lane is zero",
     "holds": lambda spec: 2 * spec.mod <= _R(spec)},
    {"name": "mont_hi_fits",
     "claim": "for reduced inputs a,b < p the Montgomery high half "
              "(a*b + m*p)/R is < 2p (p^2 + R*p <= 2*p*R, i.e. p <= R), "
              "so mont_mul's final _cond_sub_mod sees a value that fits",
     "holds": lambda spec: spec.mod ** 2 + _R(spec) * spec.mod
              <= 2 * spec.mod * _R(spec)},
    {"name": "u32_colsum",
     "claim": "u32-path product columns stay carry-free: <= 2L split "
              "halves per column, each < 2^16, lo+hi recombined "
              "(4L * (2^16-1) < 2^32)",
     "holds": lambda spec: 4 * spec.n_limbs * (LIMB_MASK + 1) < 1 << 32},
    {"name": "byte_colsum_f32_exact",
     "claim": "f32-path byte-column sums stay exactly representable: "
              "<= 4L byte products per column, each <= 255^2 "
              "(4L * 255^2 <= 2^24, the f32 integer round-trip bound)",
     "holds": lambda spec: 4 * spec.n_limbs * 255 ** 2 <= 1 << 24},
    {"name": "combined_cols_u32",
     "claim": "recombined 16-bit columns (even + 2^8 * odd byte columns) "
              "fit u32 before the sweep (4L * 255^2 * 257 < 2^32)",
     "holds": lambda spec: 4 * spec.n_limbs * 255 ** 2 * 257 < 1 << 32},
    {"name": "sweep_preadd_single_bit",
     "claim": "_carry_sweep's pre-add s_i = lo_i + hi_{i-1} < 2^17, so "
              "the residual inter-limb carry is a single bit and the "
              "Kogge-Stone (generate, propagate) recurrence is exact",
     "holds": lambda spec: 2 * LIMB_MASK < 1 << 17},
)


def _bcast_const(limbs, ndim):
    """(L,) host constant -> (L, 1, ..., 1) for broadcasting against batch."""
    return jnp.asarray(limbs).reshape(limbs.shape + (1,) * (ndim - 1))


def _carry_sweep(cols):
    """Exact carry propagation. cols: (K, *batch) uint32 (ANY u32 entries:
    the f32 path feeds combined even+odd byte columns up to ~2^30 here).

    Returns (limbs, carry_out): limbs (K, *batch) all < 2^16, carry_out the
    overflow past the top limb. CONTRACT: callers that drop the carry
    assert the value fits in K limbs (or intend the mod-2^(16K)
    truncation); each such assumption is a named, machine-checked
    inequality in CARRY_CONTRACTS, evaluated for every FieldSpec by the
    static verifier (analysis/bounds.py::check_contracts) — do not add a
    carry-dropping call site without extending that table. The sweep's
    own value equation — value(limbs) + carry*2^(16K) == value(cols),
    exactly, for ANY u32 columns — is machine-checked too (the
    field/carry_sweep value obligation in analysis/registry.py).

    Log-depth Kogge-Stone instead of a K-step ripple chain: pre-add each
    column's high bits into the next column (s_i = lo_i + hi_{i-1} < 2^17,
    so the residual inter-limb carry is a single bit), then resolve the
    bit-carry recurrence b_i = G_i | (P_i & b_{i-1}) with an associative
    scan over (generate, propagate) pairs. Traced ops: O(log K), and the
    work is whole-array passes (VPU-friendly) rather than per-limb rows.
    """
    lo = cols & LIMB_MASK
    hi = cols >> LIMB_BITS
    zero_row = jnp.zeros_like(hi[:1])
    s = lo + jnp.concatenate([zero_row, hi[:-1]], axis=0)  # s_i < 2^17

    def shift_down(x, k):  # x[i] -> x[i-k], zeros shifted in at the bottom
        return jnp.concatenate([jnp.zeros_like(x[:k]), x[:-k]], axis=0)

    gen = s > LIMB_MASK
    prop = s == LIMB_MASK
    k = 1
    while k < s.shape[0]:  # hand-rolled KS: cheaper lowering than
        gen = gen | (prop & shift_down(gen, k))  # associative_scan here
        prop = prop & shift_down(prop, k)
        k *= 2
    b_in = shift_down(gen, 1)
    limbs = (s + b_in) & LIMB_MASK
    carry = hi[-1] + gen[-1]
    return limbs, carry


def _skew_colsum(m, shift, dtype=jnp.uint32):
    """Anti-diagonal column sums: out[k] = Σ_i m[i, k - i - shift].

    m: (rows, w, *batch). Each row i is logically shifted right by i+shift,
    then columns are summed — computed with pure pad/reshape/slice/reduce
    (row i of the flattened (rows, W-1) view starts at i·(W-1) = i·W - i,
    i.e. sits i slots earlier, which IS the skew), so the traced program is
    O(1) ops instead of an O(rows) chain of dynamic-update-slices. Integer
    entries must be < 2^16 (sums of <= 96 terms stay far below 2^32);
    float entries must keep sums < 2^24 so f32 accumulation stays exact.
    """
    rows, w = m.shape[0], m.shape[1]
    batch = m.shape[2:]
    pad = [(0, 0)] * m.ndim
    pad[1] = (shift, rows)
    mp = jnp.pad(m, pad)  # (rows, W) with W = w + shift + rows
    W = w + shift + rows
    flat = mp.reshape((rows * W,) + batch)
    skewed = flat[: rows * (W - 1)].reshape((rows, W - 1) + batch)
    return jnp.sum(skewed, axis=0, dtype=dtype)  # (W-1, *batch)


# Multiplier path (DPT_FIELD_MUL):
#   auto (default): the Pallas fused kernel on TPU for wide shapes, the
#       XLA f32 byte-product path otherwise. Measured round 4 (v5e): the
#       XLA paths materialize their byte-column transients to HBM
#       (~18 KB/lane/mul — the MSM's measured traffic wall and a 24 GB
#       OOM at 2^18-lane calls); the Pallas kernel keeps them in VMEM and
#       runs 42 ns/mul Fr / 85 ns/mul Fq, ~10-40x the XLA paths.
#   f32: XLA byte-product path only (f32 VPU products + bf16 MXU Toeplitz
#       constant products).
#   u32: the round-2 integer path (u32 multiply is an emulation ~50x
#       below the f32 FMA rate; kept as a reference oracle).
#   pallas: force the Pallas kernel for any wide-enough shape (interpret
#       mode off-TPU — slow, test-only).
MUL_CHOICES = ("pallas", "f32", "u32")
_MUL_MODE = os.environ.get("DPT_FIELD_MUL", "auto")


def _mul_path(n=None):
    """Resolved multiplier mode name: the explicit DPT_FIELD_MUL knob
    (env, or a test-patched _MUL_MODE attr) wins, then the autotune
    plan's winner ("field", "mul") near n lanes, else "auto" (platform
    default). Read per call like msm_jax's dispatch knobs."""
    return autotune.attr_or_plan(_MUL_MODE, "auto", "DPT_FIELD_MUL",
                                 "field", "mul", n)


def _f32_active(n=None):
    """Whether the XLA byte-product/MXU path (vs the u32 reference
    oracle) backs non-Pallas mont_muls under the resolved mode."""
    return _mul_path(n) != "u32"

# below this many lanes the per-call overhead of a pallas kernel exceeds
# the XLA path's cost (scalar/narrow shapes: transcript scalars, finish
# tails) — those stay on the fused-XLA path
_PALLAS_MIN_LANES = int(os.environ.get("DPT_PALLAS_MIN_LANES", "2048"))


import contextlib
import threading

_pallas_off = threading.local()


@contextlib.contextmanager
def pallas_disabled():
    """Disable the Pallas dispatch for mont_muls traced inside this block.

    Used by MeshBackend around its GSPMD-auto-sharded round math: a
    pallas_call has no SPMD partitioning rule, so letting the partitioner
    meet one on a sharded operand outside shard_map would either fail or
    silently all-gather the shards. The explicit shard_map paths (mesh
    NTT/MSM) are per-device local and keep the kernel."""
    prev = getattr(_pallas_off, "v", False)
    _pallas_off.v = True
    try:
        yield
    finally:
        _pallas_off.v = prev


def _use_pallas(shape):
    if getattr(_pallas_off, "v", False):
        return False
    lanes = 1
    for d in shape[1:]:
        lanes *= d
    mode = _mul_path(lanes)
    if mode in ("u32", "f32") or lanes < _PALLAS_MIN_LANES:
        return False
    if mode == "pallas":
        return True
    return jax.default_backend() == "tpu"


def pack_limb_pairs(v):
    """(2K, ...) u32 16-bit limbs -> (K, ...) u32 packed pairs (lo | hi<<16).

    Layout compression for RESIDENT arrays, not an arithmetic form: kernels
    unpack slices on the fly. Used by the MSM bucket-plane scan carries and
    round 3's coset-eval set (whose 25 polynomials at 8n were the measured
    single-chip 2^19 OOM, scale_2p19_r04.log)."""
    return v[0::2] | jnp.left_shift(v[1::2], 16)


def unpack_limb_pairs(p):
    """(K, ...) packed pairs -> (2K, ...) u32 16-bit limbs."""
    lo = p & 0xFFFF
    hi = jnp.right_shift(p, 16)
    return jnp.stack([lo, hi], axis=1).reshape((2 * p.shape[0],) + p.shape[1:])


def _bytes_f32(a):
    """(L, *b) u32 16-bit limbs -> (2L, *b) f32 radix-2^8 digits."""
    lo = (a & 0xFF).astype(jnp.float32)
    hi = ((a >> 8) & 0xFF).astype(jnp.float32)
    s = jnp.stack([lo, hi], axis=1)  # (L, 2, *b)
    return s.reshape((2 * a.shape[0],) + a.shape[1:])


def _combine_byte_cols(col8, out_limbs):
    """(K8, *b) f32 byte-column sums (each < 2^23, exact) -> (out_limbs, *b)
    u32 16-bit-column sums: out[k] = col8[2k] + 2^8 * col8[2k+1] (< 2^31)."""
    c = col8.astype(jnp.uint32)
    c = _pad_rows(c, 2 * out_limbs)[: 2 * out_limbs]
    ev = c[0::2]
    od = c[1::2]
    return ev + (od << 8)


def _mul_columns_f32(a, b, out_limbs):
    """Variable x variable product columns via exact f32 byte products."""
    a8 = _bytes_f32(a)
    b8 = _bytes_f32(b)
    p = a8[:, None] * b8[None, :]  # (2la, 2lb, *batch), exact (<= 255^2)
    col8 = _skew_colsum(p, 0, dtype=jnp.float32)
    return _combine_byte_cols(col8, out_limbs)


def _mul_columns_const(T, a, out_limbs):
    """Constant x variable product columns as ONE matmul: T is a banded
    byte-Toeplitz host matrix (_toeplitz_bytes), a is (L, *batch) 16-bit
    limbs. bf16 operands (integers <= 255: exact), f32 accumulation
    (column sums < 2^23: exact) — this is the MXU path."""
    a8 = _bytes_f32(a).astype(jnp.bfloat16)
    col8 = jax.lax.dot_general(
        jnp.asarray(T, dtype=jnp.bfloat16), a8,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return _combine_byte_cols(col8, out_limbs)


def _mul_columns_u32(a, b, out_limbs):
    """Round-2 u32 fallback path (DPT_FIELD_MUL=u32)."""
    la, lb = a.shape[0], b.shape[0]
    p = a[:, None] * b[None, :]  # (la, lb, *batch), each product < 2^32
    lo = _skew_colsum(p & LIMB_MASK, 0)  # cols 0 .. la+lb-2
    hi = _skew_colsum(p >> LIMB_BITS, 1)  # cols 1 .. la+lb-1
    lo = _pad_rows(lo[:out_limbs], out_limbs)
    hi = _pad_rows(hi[:out_limbs], out_limbs)
    return lo + hi


def _mul_columns(a, b, out_limbs):
    """Carry-free column sums of the product, truncated to out_limbs limbs."""
    if _f32_active():
        return _mul_columns_f32(a, b, out_limbs)
    return _mul_columns_u32(a, b, out_limbs)


def _pad_rows(a, n):
    if a.shape[0] == n:
        return a
    return jnp.pad(a, [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1))


def _sweep_pair(cols_a, cols_b):
    """Carry-sweep two column vectors in ONE vectorized sweep.

    Stacks them on a lane axis so the log-depth carry machinery is traced
    once; returns ((limbs_a, limbs_b), (carry_a, carry_b)).
    """
    pair = jnp.stack([cols_a, cols_b], axis=1)  # (K, 2, *batch)
    limbs, carry = _carry_sweep(pair)
    return (limbs[:, 0], limbs[:, 1]), (carry[0], carry[1])


def _cond_sub_mod(spec, cols):
    """Value of `cols` reduced once: v - p if v >= p else v  (v < 2p).

    Takes UNCARRIED columns (any u32 entries — the sweep's pre-add bound
    is per-limb, not per-column; see _carry_sweep) and resolves both
    candidates with a single paired sweep: lane2 adds 2^(16L) - p, whose
    carry-out flags v >= p.
    """
    negp = _bcast_const(spec.negmod_limbs, cols.ndim)
    (t, d), (_, c2) = _sweep_pair(cols, cols + negp)
    return jnp.where((c2 != 0)[None], d, t)


def add(spec, a, b):
    """a + b mod p (inputs < p): one paired sweep."""
    return _cond_sub_mod(spec, a + b)


def sub(spec, a, b):
    """a - b mod p (inputs < p): one paired sweep.

    Lane1 = a + ~b + 1 (= a-b mod 2^(16L); carries iff a >= b);
    lane2 = lane1 + p (the wrapped-around candidate).
    """
    nb = (_pad_rows(b, a.shape[0]) ^ LIMB_MASK)
    base = (a + nb).at[0].add(1)
    p = _bcast_const(spec.mod_limbs, a.ndim)
    (d, dp), (c1, _) = _sweep_pair(base, base + p)
    return jnp.where((c1 != 0)[None], d, dp)


def neg(spec, a):
    zero = jnp.zeros_like(a)
    return sub(spec, zero, a)


def mont_mul(spec, a, b):
    """Montgomery product: a*b*R^-1 mod p, inputs/outputs reduced (< p).

    SOS with column-level accumulation: the three partial products stay as
    uncarried column sums (each < 2^22, so sums of two < 2^23 are still
    exact in u32) and only four short sweeps run: t mod R; m; the low-half
    carry-out of t + m*p (those limbs are identically 0 mod R); and the
    final reduce of the uncarried high half (t + m*p)/R, folded into
    _cond_sub_mod's paired sweep.

    Wide shapes on TPU dispatch to the Pallas fused kernel
    (field_pallas.py) — same algorithm, intermediates in VMEM.

    The claim in the first line IS the machine-checked contract: the
    field/*_mont_mul_{f32,u32} registry entries exactly evaluate this
    body and assert value(out) == a*b*R^-1 mod p with out < p, at
    corner and random points, for both fields and both column paths.
    """
    if _use_pallas(jnp.broadcast_shapes(a.shape, b.shape)):
        from . import field_pallas as FP
        return FP.mont_mul(spec, a, b)
    l = spec.n_limbs
    t_cols = _mul_columns(a, b, 2 * l)  # a*b < p^2, uncarried
    t_lo, c_t = _carry_sweep(t_cols[:l])  # exact t mod R + carry into col l
    if _f32_active():
        # constant products ride the MXU as banded-Toeplitz matmuls
        m_cols = _mul_columns_const(spec.ninv_toeplitz, t_lo, l)
        m, _ = _carry_sweep(m_cols)  # m = (t mod R)*(-p^-1) mod R
        mp_cols = _mul_columns_const(spec.mod_toeplitz, m, 2 * l)
    else:
        ninv = _bcast_const(spec.ninv_limbs, a.ndim)
        m, _ = _carry_sweep(_mul_columns(t_lo, ninv, l))
        p = _bcast_const(spec.mod_limbs, a.ndim)
        mp_cols = _mul_columns(m, p, 2 * l)  # m*p < R*p, uncarried
    # low half of t + m*p is == 0 mod R: only its carry-out matters
    _, c_lo = _carry_sweep(mp_cols[:l] + t_lo)
    hi = (mp_cols[l:] + t_cols[l:]).at[0].add(c_t + c_lo)
    return _cond_sub_mod(spec, hi)  # (t + m*p) / R < 2p


def to_mont(spec, a):
    return mont_mul(spec, a, _bcast_const(spec.r2_limbs, a.ndim) * jnp.ones_like(a[:1]))


def from_mont(spec, a):
    one = _bcast_const(spec.one_limbs, a.ndim) * jnp.ones_like(a[:1])
    return mont_mul(spec, a, one)


def mont_sq(spec, a):
    return mont_mul(spec, a, a)


def cumprod_mont(spec, v, reverse=False):
    """Inclusive prefix (or suffix) Montgomery products along axis 1 of a
    (L, n) array, as a Hillis-Steele shift-multiply ladder.

    NOT lax.associative_scan: the Blelchoch-style lowering runs ~2*log n
    levels of DIFFERENT widths, which (a) instantiates one fused Pallas
    multiplier per width — the resulting multi-Mosaic program wedged the
    remote TPU compile twice at 2^18 scale (round 4) — and (b) even on
    the XLA mul path produces an HLO whose compile never returned for
    jit(perm_product). Here every level is ONE full-width mont_mul of
    the SAME shape (identity-padded shift), so the whole ladder reuses a
    single kernel instantiation: log n levels, n*log n muls instead of
    ~2n — at 2^18 that is 4.7M extra lane-muls, milliseconds at the
    measured mul rate, for a compile that returns in seconds.
    """
    L, n = v.shape
    mont_one = (1 << (LIMB_BITS * spec.n_limbs)) % spec.mod
    one_col = jnp.asarray(
        int_to_limbs(mont_one, spec.n_limbs)).reshape(L, 1)
    k = 1
    while k < n:
        ones = jnp.broadcast_to(one_col, (L, k))
        if reverse:
            shifted = jnp.concatenate([v[:, k:], ones], axis=1)
        else:
            shifted = jnp.concatenate([ones, v[:, :-k]], axis=1)
        v = mont_mul(spec, v, shifted)
        k *= 2
    return v


def cumsum_mont(spec, v, reverse=False):
    """Inclusive prefix (or suffix) modular sums along axis 1 of (L, n):
    the zero-padded Hillis-Steele ladder — same single-width rationale as
    cumprod_mont (every level one full-width add of the same shape; no
    multi-width associative_scan lowering near the remote compiler)."""
    L, n = v.shape
    k = 1
    while k < n:
        zeros = jnp.zeros((L, k), v.dtype)
        if reverse:
            shifted = jnp.concatenate([v[:, k:], zeros], axis=1)
        else:
            shifted = jnp.concatenate([zeros, v[:, :-k]], axis=1)
        v = add(spec, v, shifted)
        k *= 2
    return v


def is_zero(spec, a):
    return jnp.all(a == 0, axis=0)


def eq(spec, a, b):
    return jnp.all(a == b, axis=0)


def select(cond, a, b):
    """cond: (*batch,) bool; a, b: (L, *batch) -> where(cond, a, b)."""
    return jnp.where(cond[None], a, b)


def double(spec, a):
    return add(spec, a, a)
