"""Host (pure-Python) compute backend: the CPU oracle the device backends
are measured against — the analog of the reference's v1 local prover path
(/root/reference/src/dispatcher.rs:523-960, its "CPU oracle").

Implements the prover's poly-handle protocol with int-list handles; the
formerly-inline host loops (permutation product, quotient evaluations —
the loops the reference keeps on the dispatcher, dispatcher2.rs:330-345,
434-504) live here as the oracle implementations.
"""

from .. import poly as P
from .. import curve as C
from ..constants import R_MOD, FR_GENERATOR
from ..fields import fr_inv, batch_inverse
from ..circuit import GATE_WIDTH, NUM_WIRE_TYPES, Q_LC, Q_MUL, Q_HASH, Q_O, Q_C, Q_ECC


def _pad(coeffs, size):
    assert len(coeffs) <= size
    return list(coeffs) + [0] * (size - len(coeffs))


class PythonBackend:
    """Reference backend. All ops on host, Python ints; handles are lists."""

    name = "python"

    # --- plain int-list compute API (worker daemon / dispatcher surface) ----

    def fft(self, domain, values):
        return P.fft(domain, values)

    def ifft(self, domain, values):
        return P.ifft(domain, values)

    def coset_fft(self, domain, values):
        return P.coset_fft(domain, values)

    def coset_ifft(self, domain, values):
        return P.coset_ifft(domain, values)

    def msm(self, bases, scalars):
        """Variable-base MSM; scalars zero-padded to |bases| by caller."""
        return C.g1_msm(bases[:len(scalars)], scalars)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)

    # --- poly-handle protocol (handles = int lists) --------------------------

    def lift(self, values):
        return list(values)

    def lower(self, h):
        return list(h)

    def wire_values(self, circuit):
        return [circuit.wire_values(i) for i in range(NUM_WIRE_TYPES)]

    def pk_polys(self, pk):
        return pk.selectors, pk.sigmas

    def ifft_h(self, domain, h):
        return self.ifft(domain, h)

    def coset_fft_h(self, domain, h):
        return self.coset_fft(domain, h)

    def coset_ifft_h(self, domain, h):
        return self.coset_ifft(domain, h)

    # batch NTT entry points: sequential here; the fleet backend overrides
    # these with concurrent multi-worker dispatch (the join_all pattern,
    # reference dispatcher2.rs:294-321,382-414)
    def ifft_many(self, domain, handles):
        return [self.ifft_h(domain, h) for h in handles]

    def coset_fft_many(self, domain, handles):
        return [self.coset_fft_h(domain, h) for h in handles]

    def blind(self, h, blinds, n):
        return P.poly_add(P.poly_mul_vanishing(blinds, n), h)

    def commit_h(self, ck, h):
        return self.commit(ck, _pad(h, len(ck)))

    # batch commitment entry points (the reference's join_all commit
    # fan-outs, dispatcher2.rs:316-321,526-533): sequential here; the
    # device backend overrides with one batched multi-poly MSM launch
    def commit_many(self, ck, coeff_lists):
        return [self.commit(ck, s) for s in coeff_lists]

    def commit_many_h(self, ck, hs):
        return [self.commit_h(ck, h) for h in hs]

    def degree_is(self, h, d):
        return P.poly_degree(h) == d

    def split(self, h, size, count, total):
        assert count * size >= total
        padded = _pad(h, max(len(h), count * size))
        return [padded[i:i + size] for i in range(0, count * size, size)]

    def eval_h(self, h, point):
        return P.poly_eval(h, point)

    def eval_many_h(self, pairs):
        return [self.eval_h(h, point) for h, point in pairs]

    def lin_comb_h(self, polys, coeffs):
        out = []
        for h, cf in zip(polys, coeffs):
            out = P.poly_add(out, P.poly_scale(h, cf % R_MOD))
        return out

    def synth_div_h(self, h, point):
        return P.synthetic_divide(h, point)

    def perm_product(self, circuit, beta, gamma, n):
        """z(w^j) running product (reference src/dispatcher2.rs:330-345)."""
        w = NUM_WIRE_TYPES
        product_vec = [1]
        nums = []
        dens = []
        for j in range(n - 1):
            a = 1
            b = 1
            for i in range(w):
                wire_value = circuit.witness[circuit.wire_variables[i][j]]
                t = (wire_value + gamma) % R_MOD
                a = a * ((t + beta * circuit.extended_id_permutation[i][j]) % R_MOD) % R_MOD
                pi, pj = circuit.wire_permutation[i][j]
                b = b * ((t + beta * circuit.extended_id_permutation[pi][pj]) % R_MOD) % R_MOD
            nums.append(a)
            dens.append(b)
        den_invs = batch_inverse(dens, R_MOD)
        for j in range(n - 1):
            product_vec.append(product_vec[j] * nums[j] % R_MOD * den_invs[j] % R_MOD)
        return product_vec

    def quotient(self, n, m, quot_domain, k, beta, gamma, alpha, alpha_sq_div_n,
                 selectors_coset, sigmas_coset, wires_coset, z_coset, pi_coset):
        """Coset evaluations of the quotient polynomial
        (reference src/dispatcher2.rs:434-504)."""
        g = FR_GENERATOR
        wq = quot_domain.group_gen
        eval_points = []
        cur = g
        for _ in range(m):
            eval_points.append(cur)
            cur = cur * wq % R_MOD
        ratio = m // n
        z_h_vals = [(pow(eval_points[i], n, R_MOD) - 1) % R_MOD for i in range(ratio)]
        z_h_inv = batch_inverse(z_h_vals, R_MOD)
        # 1/(eval_point - 1) for the L1 term
        shifted = [(e - 1) % R_MOD for e in eval_points]
        shifted_inv = batch_inverse(shifted, R_MOD)

        q_lc = selectors_coset[Q_LC:Q_LC + GATE_WIDTH]
        q_mul = selectors_coset[Q_MUL:Q_MUL + 2]
        q_hash = selectors_coset[Q_HASH:Q_HASH + GATE_WIDTH]
        q_o = selectors_coset[Q_O]
        q_c = selectors_coset[Q_C]
        q_ecc = selectors_coset[Q_ECC]

        out = []
        for i in range(m):
            a, b, c, d, e = (w[i] for w in wires_coset)
            ab = a * b % R_MOD
            cd = c * d % R_MOD
            gate = (
                q_c[i] + pi_coset[i]
                + q_lc[0][i] * a + q_lc[1][i] * b + q_lc[2][i] * c + q_lc[3][i] * d
                + q_mul[0][i] * ab + q_mul[1][i] * cd
                + q_ecc[i] * ab % R_MOD * cd % R_MOD * e
                + q_hash[0][i] * pow(a, 5, R_MOD)
                + q_hash[1][i] * pow(b, 5, R_MOD)
                + q_hash[2][i] * pow(c, 5, R_MOD)
                + q_hash[3][i] * pow(d, 5, R_MOD)
                - q_o[i] * e
            ) % R_MOD
            acc1 = z_coset[i]
            acc2 = z_coset[(i + ratio) % m]
            ep = eval_points[i]
            for j in range(NUM_WIRE_TYPES):
                t = (wires_coset[j][i] + gamma) % R_MOD
                acc1 = acc1 * ((t + k[j] * ep % R_MOD * beta) % R_MOD) % R_MOD
                acc2 = acc2 * ((t + sigmas_coset[j][i] * beta) % R_MOD) % R_MOD
            perm = alpha * (acc1 - acc2) % R_MOD
            l1_term = alpha_sq_div_n * ((z_coset[i] - 1) % R_MOD) % R_MOD * shifted_inv[i] % R_MOD
            out.append((z_h_inv[i % ratio] * ((gate + perm) % R_MOD) + l1_term) % R_MOD)
        return out
