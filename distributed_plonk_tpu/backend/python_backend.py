"""Host (pure-Python) compute backend: the CPU oracle the device backends
are measured against — the analog of the reference's v1 local prover path
(/root/reference/src/dispatcher.rs:523-960, its "CPU oracle")."""

from .. import poly as P
from .. import curve as C


class PythonBackend:
    """Reference backend. All ops on host, Python ints."""

    name = "python"

    def fft(self, domain, values):
        return P.fft(domain, values)

    def ifft(self, domain, values):
        return P.ifft(domain, values)

    def coset_fft(self, domain, values):
        return P.coset_fft(domain, values)

    def coset_ifft(self, domain, values):
        return P.coset_ifft(domain, values)

    def msm(self, bases, scalars):
        """Variable-base MSM; scalars zero-padded to |bases| by caller."""
        return C.g1_msm(bases[:len(scalars)], scalars)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)
