"""Vectorized BLS12-381 G1 arithmetic on device (Jacobian over limb-Fq).

Device replacement for `ark-ec`'s G1 group ops as used by the reference's
MSM workers (/root/reference/src/worker.rs:122). Points are (X, Y, Z)
tuples of (24, *batch) uint32 Montgomery limb arrays; Z == 0 encodes the
point at infinity (matching the oracle's (1, 1, 0) convention, curve.py).

All control flow is branch-free: the add kernel computes the generic sum,
the doubling, and infinity fallbacks unconditionally and `where`-selects —
the TPU-idiomatic shape for data-dependent curve edge cases.
"""

import os

import numpy as np
import jax.numpy as jnp

from ..constants import FQ_MONT_R, FQ_LIMBS, Q_MOD
from . import field_jax as FJ
from .field_jax import FQ
from .limbs import int_to_limbs, ints_to_limbs, limbs_to_ints

# DPT_CURVE_ADD selects the fused whole-formula Pallas add kernel
# (curve_pallas.py). Default is xla (OFF): measured round 4 on a v5e
# (scripts/add_bench.py, 8192 lanes), the fused kernel ties the staged
# XLA path exactly (131 ms / 32 steps both — the staged path's muls
# already ride the fused Pallas multiplier, and at MSM widths XLA's
# per-op overhead amortizes) while costing ~194 s of Mosaic compile per
# distinct shape. auto/pallas opt back in under the multiplier's gate.
_ADD_MODE = os.environ.get("DPT_CURVE_ADD", "xla")


def _use_fused_add(*shapes):
    if _ADD_MODE == "pallas":        # force, regardless of the mul gate
        return True
    if _ADD_MODE != "auto":          # default "xla": fused add off
        return False
    return FJ._use_pallas(jnp.broadcast_shapes(*shapes))

_MONT_ONE = int_to_limbs(FQ_MONT_R, FQ_LIMBS)  # 1 in Montgomery form
_MONT_R_INV = pow(FQ_MONT_R, Q_MOD - 2, Q_MOD)


def _mont_one_like(x):
    return jnp.broadcast_to(
        jnp.asarray(_MONT_ONE).reshape((FQ_LIMBS,) + (1,) * (x.ndim - 1)), x.shape)


def pt_inf(batch_shape=()):
    """Infinity: (1, 1, 0) in Montgomery form."""
    shape = (FQ_LIMBS,) + tuple(batch_shape)
    one = jnp.broadcast_to(
        jnp.asarray(_MONT_ONE).reshape((FQ_LIMBS,) + (1,) * len(batch_shape)), shape)
    return (one, one, jnp.zeros(shape, dtype=jnp.uint32))


def pt_select(cond, p, q):
    """cond (*batch,) ? p : q, componentwise."""
    return tuple(FJ.select(cond, a, b) for a, b in zip(p, q))


def pt_is_inf(p):
    return FJ.is_zero(FQ, p[2])


def pt_neg(p):
    return (p[0], FJ.neg(FQ, p[1]), p[2])


def from_affine(x, y, inf_mask):
    """(24, *b) coords in Montgomery form + bool inf mask -> Jacobian."""
    one = _mont_one_like(x)
    z = jnp.where(inf_mask[None], jnp.zeros_like(x), one)
    return (x, y, z)


def _dbl(spec, a):
    return FJ.add(spec, a, a)


def _mul_lanes(pairs):
    """Batch k independent Fq products into ONE mont_mul on a stacked lane
    axis: the traced program contains one multiplier instance instead of k
    (k-fold smaller XLA graphs — compile time was the round-1 multichip-gate
    killer), and the device sees one wide op instead of k narrow ones."""
    a = jnp.stack([x for x, _ in pairs], axis=1)
    b = jnp.stack([y for _, y in pairs], axis=1)
    r = FJ.mont_mul(FQ, a, b)
    return [r[:, i] for i in range(len(pairs))]


def _sub_lanes(pairs):
    a = jnp.stack([x for x, _ in pairs], axis=1)
    b = jnp.stack([y for _, y in pairs], axis=1)
    r = FJ.sub(FQ, a, b)
    return [r[:, i] for i in range(len(pairs))]


def jac_double(p):
    """dbl-2009-l (a=0), identical formula to the oracle
    (curve.py _g1_jac_double_nonzero); Z1=0 propagates to Z3=0.
    Independent products run as stacked lanes (4 multiplier instances)."""
    x1, y1, z1 = p
    a, b = _mul_lanes([(x1, x1), (y1, y1)])
    xb = FJ.add(FQ, x1, b)
    c, t = _mul_lanes([(b, b), (xb, xb)])
    d = _dbl(FQ, FJ.sub(FQ, FJ.sub(FQ, t, a), c))
    e = FJ.add(FQ, _dbl(FQ, a), a)
    f, yz = _mul_lanes([(e, e), (y1, z1)])
    x3 = FJ.sub(FQ, f, _dbl(FQ, d))
    c8 = _dbl(FQ, _dbl(FQ, _dbl(FQ, c)))
    (g,) = _mul_lanes([(e, FJ.sub(FQ, d, x3))])
    y3 = FJ.sub(FQ, g, c8)
    z3 = _dbl(FQ, yz)
    return (x3, y3, z3)


def jac_add(p, q):
    """add-2007-bl with branch-free edge handling (P==Q -> double,
    P==-Q -> infinity, either infinite -> other operand).
    Independent products run as stacked lanes (6 multiplier instances for
    the generic sum; plus 4 in the doubling fallback)."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    zz = FJ.add(FQ, z1, z2)
    z1z1, z2z2, zz2 = _mul_lanes([(z1, z1), (z2, z2), (zz, zz)])
    u1, u2, s1a, s2a = _mul_lanes(
        [(x1, z2z2), (x2, z1z1), (y1, z2), (y2, z1)])
    s1, s2 = _mul_lanes([(s1a, z2z2), (s2a, z1z1)])
    h, r0 = _sub_lanes([(u2, u1), (s2, s1)])
    h2 = _dbl(FQ, h)
    rr = _dbl(FQ, r0)
    (i,) = _mul_lanes([(h2, h2)])
    j, v, rr2 = _mul_lanes([(h, i), (u1, i), (rr, rr)])
    xa, za = _sub_lanes([(rr2, j), (zz2, z1z1)])
    x3, zb = _sub_lanes([(xa, _dbl(FQ, v)), (za, z2z2)])
    p1, p2, z3 = _mul_lanes([(rr, FJ.sub(FQ, v, x3)), (s1, j), (zb, h)])
    y3 = FJ.sub(FQ, p1, _dbl(FQ, p2))
    res = (x3, y3, z3)

    p_inf = FJ.is_zero(FQ, z1)
    q_inf = FJ.is_zero(FQ, z2)
    both_fin = ~p_inf & ~q_inf
    h_zero = FJ.eq(FQ, u1, u2) & both_fin
    s_eq = FJ.eq(FQ, s1, s2)

    res = pt_select(h_zero & s_eq, jac_double(p), res)
    res = pt_select(h_zero & ~s_eq, pt_inf(z1.shape[1:]), res)
    res = pt_select(q_inf, p, res)
    res = pt_select(p_inf, q, res)
    return res


# --- complete projective kernels (Renes-Costello-Batina 2015, a=0) -----------
# The bucket pipeline's hot ops: COMPLETE homogeneous-projective addition for
# j-invariant-0 curves (y^2 = x^3 + 4, so b3 = 12). Complete means NO edge
# handling at all — identity (0 : 1 : 0), P == Q, and P == -Q all flow
# through the same straight-line formula (valid on the prime-order subgroup)
# — which on a vector machine beats Jacobian adds twice over: fewer
# multiplies AND none of the branch-free select/fallback machinery.
# Each add stages its multiplies into just TWO stacked-lane mont_mul
# instances (6 independent products each), so compiled programs are small.

def _mul12(a):
    """12*a = 8a + 4a via three doublings and one add (b3 multiply)."""
    a4 = _dbl(FQ, _dbl(FQ, a))
    return FJ.add(FQ, _dbl(FQ, a4), a4)


def proj_inf(batch_shape=()):
    """Identity in homogeneous projective coordinates: (0 : 1 : 0)."""
    shape = (FQ_LIMBS,) + tuple(batch_shape)
    one = jnp.broadcast_to(
        jnp.asarray(_MONT_ONE).reshape((FQ_LIMBS,) + (1,) * len(batch_shape)),
        shape)
    zero = jnp.zeros(shape, dtype=jnp.uint32)
    return (zero, one, zero)


def proj_add(p, q):
    """Complete projective P + Q (RCB15 algorithm 7, a=0): 12 full muls in
    2 stacked-lane instances + 2 cheap b3 multiplies. No special cases.

    Wide shapes on TPU run the whole formula as ONE fused Pallas program
    (curve_pallas.py) — same op sequence, intermediates in VMEM."""
    if _use_fused_add(*[c.shape for c in (*p, *q)]):
        from . import curve_pallas as CP
        return CP.proj_add(p, q)
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0, t1, t2, m3, m4, m5 = _mul_lanes([
        (x1, x2), (y1, y2), (z1, z2),
        (FJ.add(FQ, x1, y1), FJ.add(FQ, x2, y2)),
        (FJ.add(FQ, y1, z1), FJ.add(FQ, y2, z2)),
        (FJ.add(FQ, x1, z1), FJ.add(FQ, x2, z2)),
    ])
    t3 = FJ.sub(FQ, m3, FJ.add(FQ, t0, t1))
    t4 = FJ.sub(FQ, m4, FJ.add(FQ, t1, t2))
    ym = FJ.sub(FQ, m5, FJ.add(FQ, t0, t2))
    t0x3 = FJ.add(FQ, _dbl(FQ, t0), t0)   # 3*t0
    t2b = _mul12(t2)                      # b3*t2
    z3a = FJ.add(FQ, t1, t2b)
    t1a = FJ.sub(FQ, t1, t2b)
    y3b = _mul12(ym)                      # b3*ym
    x3a, t2c, y3c, t1b, t0c, z3b = _mul_lanes([
        (t4, y3b), (t3, t1a), (y3b, t0x3),
        (t1a, z3a), (t0x3, t3), (z3a, t4),
    ])
    return (FJ.sub(FQ, t2c, x3a),
            FJ.add(FQ, t1b, y3c),
            FJ.add(FQ, z3b, t0c))


def proj_add_mixed(p, q_affine, q_inf):
    """Complete projective P + affine Q (RCB15 algorithm 8, a=0): 11 full
    muls in 2 stacked-lane instances. Complete in P; the only mask is for
    Q flagged infinite (padding / zero digit), which returns P.

    Wide shapes on TPU run the whole formula as ONE fused Pallas program
    (curve_pallas.py; the q_inf select stays here in XLA, where it fuses)."""
    if _use_fused_add(*[c.shape for c in (*p, *q_affine)]):
        from . import curve_pallas as CP
        res = CP.proj_add_mixed(p, q_affine)
        return pt_select(q_inf, p, res)
    x1, y1, z1 = p
    x2, y2 = q_affine
    t0, t1, m3, t4a, y3a = _mul_lanes([
        (x1, x2), (y1, y2),
        (FJ.add(FQ, x1, y1), FJ.add(FQ, x2, y2)),
        (y2, z1), (x2, z1),
    ])
    t3 = FJ.sub(FQ, m3, FJ.add(FQ, t0, t1))
    t4 = FJ.add(FQ, t4a, y1)
    ym = FJ.add(FQ, y3a, x1)
    t0x3 = FJ.add(FQ, _dbl(FQ, t0), t0)   # 3*t0
    t2 = _mul12(z1)                       # b3*Z1
    z3a = FJ.add(FQ, t1, t2)
    t1a = FJ.sub(FQ, t1, t2)
    y3b = _mul12(ym)                      # b3*ym
    x3a, t2c, y3c, t1b, t0c, z3b = _mul_lanes([
        (t4, y3b), (t3, t1a), (y3b, t0x3),
        (t1a, z3a), (t0x3, t3), (z3a, t4),
    ])
    res = (FJ.sub(FQ, t2c, x3a),
           FJ.add(FQ, t1b, y3c),
           FJ.add(FQ, z3b, t0c))
    return pt_select(q_inf, p, res)


def batch_to_affine(p):
    """Jacobian (24, n) Montgomery -> (x_affine, y_affine, inf_mask), all on
    device: Montgomery batch inversion of the Z column via two log-depth
    prefix/suffix product scans and ONE field inverse, which crosses to the
    host as a single element (pow(z, q-2) there costs nothing). Used to
    normalize a device-built SRS (fixed_base output has arbitrary Z) into
    the affine form the mixed-add bucket scan consumes."""
    import jax

    px, py, pz = p
    inf = FJ.is_zero(FQ, pz)
    one = _mont_one_like(pz)
    z = FJ.select(inf, one, pz)

    def mm(a, b):
        return FJ.mont_mul(FQ, a, b)

    @jax.jit
    def prefix_suffix(z):
        # single-width Hillis-Steele ladders, NOT associative_scan: the
        # multi-width lowering wedged the remote TPU compile at SRS scale
        # (round 4) — rationale at field_jax.cumprod_mont
        pre = FJ.cumprod_mont(FQ, z)
        suf = FJ.cumprod_mont(FQ, z, reverse=True)
        return pre, suf

    pre, suf = prefix_suffix(z)
    total = np.asarray(pre[:, -1])  # ONE element to host
    total_int = 0
    for k, limb in enumerate(total):
        total_int |= int(limb) << (16 * k)
    # total is Montgomery form of T: T*R. Its modular inverse in Montgomery
    # form is (T^-1)*R = R^2 / (T*R) -> compute R^3 * (T*R)^-1 mod q... the
    # clean route: inv_mont = (R^2 * modinv(total_int)) % q with
    # modinv(T*R) = T^-1 * R^-1, so R^2 * that = T^-1 * R. QED.
    inv_int = (FQ_MONT_R * FQ_MONT_R % Q_MOD) * pow(total_int, Q_MOD - 2, Q_MOD) % Q_MOD
    tinv = jnp.asarray(int_to_limbs(inv_int, FQ_LIMBS)).reshape(FQ_LIMBS, 1)

    @jax.jit
    def normalize(px, py, pz, pre, suf, tinv, inf):
        n = pz.shape[1]
        one_col = jnp.broadcast_to(
            jnp.asarray(_MONT_ONE).reshape(FQ_LIMBS, 1), (FQ_LIMBS, 1))
        pre_im1 = jnp.concatenate([one_col, pre[:, :-1]], axis=1)
        suf_ip1 = jnp.concatenate([suf[:, 1:], one_col], axis=1)
        # z_i^-1 (Montgomery) = pre_{i-1} * suf_{i+1} * (T^-1 R)
        zinv = mm(mm(pre_im1, suf_ip1), jnp.broadcast_to(tinv, pz.shape))
        zinv2 = mm(zinv, zinv)
        zinv3 = mm(zinv2, zinv)
        ax = mm(px, zinv2)
        ay = mm(py, zinv3)
        zero = jnp.zeros_like(ax)
        return (FJ.select(inf, zero, ax), FJ.select(inf, zero, ay))

    ax, ay = normalize(px, py, pz, pre, suf, tinv, inf)
    return ax, ay, inf


# --- host boundary helpers (tests / debugging; oracle-grade, not hot) --------

def affine_to_device(points):
    """list[(x, y) | None] -> Jacobian tuple of (24, n) Montgomery arrays."""
    xs = [(p[0] * FQ_MONT_R % Q_MOD) if p else 0 for p in points]
    ys = [(p[1] * FQ_MONT_R % Q_MOD) if p else 0 for p in points]
    inf = np.array([p is None for p in points])
    return from_affine(jnp.asarray(ints_to_limbs(xs, FQ_LIMBS)),
                       jnp.asarray(ints_to_limbs(ys, FQ_LIMBS)),
                       jnp.asarray(inf))


def device_to_affine(p):
    """Jacobian tuple of (24, n) Montgomery arrays -> list[(x, y) | None]."""
    from .. import curve as C

    cols = [limbs_to_ints(np.asarray(c)) for c in p]
    out = []
    for X, Y, Z in zip(*cols):
        jac = tuple(v * _MONT_R_INV % Q_MOD for v in (X, Y, Z))
        out.append(C.g1_from_jac(jac))
    return out
