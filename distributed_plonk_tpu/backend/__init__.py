"""Device backends (JAX limb kernels) + the pure-Python host oracle.

Kept import-free: python_backend must work without jax. The JAX persistent
compilation cache is configured in field_jax.py, the root of every device
module's import chain.
"""
