"""Host-side conversion between Python ints and device limb arrays.

Device representation: radix-2^16 little-endian limbs held in uint32 lanes,
limbs on the LEADING axis -> shape (L, *batch). Leading-axis layout keeps the
batch dimension on the TPU vector lanes (last-dim tiling is (8, 128)), so
elementwise field ops vectorize over the polynomial/point batch with no lane
padding waste.

This is the analog of the reference's host<->wire boundary
(/root/reference/src/utils.rs:27-43), but with an explicit, documented layout
instead of an unsafe transmute.
"""

import numpy as np

from ..constants import LIMB_BITS, LIMB_MASK, FR_LIMBS, FQ_LIMBS

assert LIMB_BITS == 16


def int_to_limbs(x, n_limbs):
    """One Python int -> (n_limbs,) uint32 array of 16-bit limbs."""
    return np.array([(x >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n_limbs)],
                    dtype=np.uint32)


def ints_to_limbs(xs, n_limbs):
    """List of ints -> (n_limbs, len(xs)) uint32 array (leading-axis limbs)."""
    nbytes = n_limbs * 2
    buf = b"".join(int(x).to_bytes(nbytes, "little") for x in xs)
    arr = np.frombuffer(buf, dtype="<u2").reshape(len(xs), n_limbs)
    return np.ascontiguousarray(arr.T).astype(np.uint32)


def limbs_to_int(limbs):
    """(n_limbs,) array -> Python int."""
    x = 0
    for i, limb in enumerate(np.asarray(limbs, dtype=np.uint64)):
        x |= int(limb) << (LIMB_BITS * i)
    return x


def limbs_to_ints(limbs):
    """(n_limbs, n) array -> list of n Python ints."""
    arr = np.asarray(limbs)
    assert arr.ndim == 2
    # a silent >2^16 limb here would mask a missing carry sweep in a kernel
    assert (arr <= LIMB_MASK).all(), "unreduced limb at oracle boundary"
    a16 = arr.T.astype("<u2")  # (n, n_limbs)
    raw = a16.tobytes()
    nbytes = arr.shape[0] * 2
    return [int.from_bytes(raw[i * nbytes:(i + 1) * nbytes], "little")
            for i in range(arr.shape[1])]


def fr_to_limbs(xs):
    return ints_to_limbs(xs, FR_LIMBS)


def fq_to_limbs(xs):
    return ints_to_limbs(xs, FQ_LIMBS)
