"""Kernel autotuner + the KernelConfig resolution layer (ROADMAP dir. 4).

The two superlinear PLONK kernels (MSM, NTT) and the field multiplier
each grew several dispatchable variants (PRs 3/5/8): radix-2/4 XLA vs
fused Pallas stage cores, onehot/put bucket updates, f32/u32/MXU
multiplier paths, VMEM budgets, window width c, chunk budgets — all
selected by ~25 hand-set `DPT_*` env knobs tuned for one box. This
module replaces hand tuning with FFTW/ATLAS-style empirical
calibration: measure the concrete candidate space at the prover's real
launch shapes ONCE per machine, persist the winning configuration (a
`KernelPlan`), and load it forever after (store/calibration.py keys the
plan artifact by `machine_fingerprint()` so it warm-syncs to joining
workers like any other store artifact).

Two halves:

KernelConfig resolution layer (import-light — no jax/numpy at module
scope, so the host-oracle service can load a plan without touching
XLA). Precedence at every per-call `resolve()` site in
ntt_jax/ntt_pallas/msm_jax/msm_pallas/field_jax/field_pallas:

    explicit DPT_* env knob (or a test-patched module attr)
      > active KernelPlan cell          (nearest calibrated shape)
        > the built-in platform default (exact pre-autotune behavior)

so an operator's explicit knob is an OVERRIDE, not the primary
interface, and with no plan active every kernel path is bit- and
counter-identical to the pre-autotune tree. `set_active_plan` bumps a
process-wide revision that `cache_key()` folds into every kernel memo
key (NttPlan._fns, MsmContext chunk/calibration caches, the mesh/fleet
kernel caches) — a mid-process plan reload can therefore never serve a
compiled variant traced under the previous plan.

Autotuner: per (kind, domain_size) cell, enumerates candidates FROM THE
DISPATCH RESOLVERS THEMSELVES (each candidate is applied as a temporary
plan and read back through `_active_radix`/`_kernel_mode`/… — a
candidate the resolvers coerce elsewhere, e.g. one pinned by an env
knob or an unsupported platform, dedups onto what would actually run,
so the space cannot drift from what the kernels accept), measures each
at the real launch shape, and gates every winner on BIT-IDENTITY to the
parity core's output (radix-2 XLA NTT / XLA put bucket scan / u32
multiplier) — a fast-but-wrong candidate is rejected, never adopted.
MSM cells additionally record the measured adds/s rate, which
`MsmContext._chunk_lanes` reads back: chunk shapes are then identical
from the first call, so the AOT pass covers them and the PR 3/5
"post-calibration chunk shapes recompile at serve time" remainder
closes structurally.
"""

import contextlib
import hashlib
import json
import os
import platform
import threading
import time

PLAN_VERSION = 1


def machine_fingerprint():
    """Stable 12-hex id of what XLA:CPU AOT entries actually depend on:
    the architecture + CPU feature flags of this host. Shared by the
    persistent-compile-cache partitioning (field_jax re-exports it) and
    the calibration-plan artifact key — one identity for everything a
    machine compiles or measures."""
    cpu = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    cpu = line
                    break
    except OSError:
        pass
    return hashlib.sha256(
        f"{platform.machine()}|{cpu}".encode()).hexdigest()[:12]


class KernelPlan:
    """A calibrated kernel configuration for one machine fingerprint.

    cells: {(kind, domain_size): {"params": {...}, ...}} with kind in
    ("ntt", "msm", "field"); params hold the winning knob values under
    the names the resolvers look up ("kernel", "radix", "vmem_mb",
    "rows", "bucket_update", "c", "group_max", "adds_per_s", "mul",
    "lane_tile"). JSON serialization is canonical (sorted keys), so a
    plan round-trips through the content-addressed store byte-for-byte.
    """

    def __init__(self, fingerprint, cells=None, meta=None):
        self.fingerprint = fingerprint
        self.cells = {}
        for key, cell in (cells or {}).items():
            if not isinstance(key, tuple):
                kind, _, size = key.partition(":")
                key = (kind, int(size))
            cell = dict(cell)
            if "params" not in cell:
                cell = {"params": cell}
            self.cells[(key[0], int(key[1]))] = cell
        self.meta = dict(meta or {})

    def cell(self, kind, n):
        return self.cells.get((kind, int(n)))

    def lookup(self, kind, param, n=None):
        """Winning value of `param` for `kind` at the calibrated cell
        nearest to domain size `n` (log2 distance, ties to the larger
        cell); n=None picks the largest calibrated cell — serving at
        scale favors the big-shape winner. None when uncalibrated."""
        sizes = [s for (k, s), c in self.cells.items()
                 if k == kind and param in c.get("params", {})]
        if not sizes:
            return None
        if n is None:
            size = max(sizes)
        else:
            nb = max(int(n), 1).bit_length()
            size = min(sizes,
                       key=lambda s: (abs(max(s, 1).bit_length() - nb), -s))
        return self.cells[(kind, size)]["params"][param]

    def to_json_bytes(self):
        cells = {f"{k}:{s}": c for (k, s), c in self.cells.items()}
        return json.dumps(
            {"version": PLAN_VERSION, "fingerprint": self.fingerprint,
             "meta": self.meta, "cells": cells},
            sort_keys=True, indent=1).encode()

    @classmethod
    def from_json_bytes(cls, blob):
        """Parse a stored plan; None for a foreign/future version (the
        caller recalibrates rather than misparsing)."""
        try:
            d = json.loads(blob.decode())
        except (ValueError, UnicodeDecodeError):
            return None
        if d.get("version") != PLAN_VERSION:
            return None
        return cls(d.get("fingerprint", ""), d.get("cells", {}),
                   d.get("meta", {}))


# --- active-plan registry (the per-process KernelConfig source) --------------

_plan_lock = threading.Lock()
_active_plan = None
_plan_revision = 0


def active_plan():
    return _active_plan


def plan_revision():
    """Monotonic counter bumped by every set_active_plan — folded into
    kernel memo keys via cache_key so plan reloads invalidate them."""
    return _plan_revision


def set_active_plan(plan):
    """Install `plan` (a KernelPlan, or None = knob-free defaults) as
    the process-wide KernelConfig source. Returns the new revision."""
    global _active_plan, _plan_revision
    with _plan_lock:
        _active_plan = plan
        _plan_revision += 1
        return _plan_revision


def cache_key(*parts):
    """THE shared kernel-memo cache-key helper: the resolved-mode parts
    plus the current plan revision. Every memo that caches a compiled
    variant keyed on resolved knobs (NttPlan._fns / _pallas_tabs,
    MsmContext._chunk_fns / _chunk_calls / _finish_fns / the adds-per-s
    calibration key, the mesh and fleet kernel caches) builds its key
    here, so a mid-process plan reload misses every stale entry instead
    of serving an executable traced under the previous plan (env knobs
    never change mid-process; plans do)."""
    return tuple(parts) + (_plan_revision,)


def plan_param(kind, param, n=None):
    """Active plan's winner for (kind, param) near domain size n, or
    None (no plan / uncalibrated). Lock-free read: CPython attribute
    loads are atomic and a racing reload just resolves one call on the
    outgoing plan, whose memo entries its revision bump already
    retired."""
    p = _active_plan
    if p is None:
        return None
    return p.lookup(kind, param, n)


def env_or_plan(env_name, kind, param, default, n=None, cast=None):
    """Per-call knob resolution for env-read knobs: explicit env wins,
    then the active plan, then the built-in default."""
    v = os.environ.get(env_name)
    if v is not None:
        return cast(v) if cast is not None else v
    p = plan_param(kind, param, n)
    if p is None:
        return default
    if cast is not None:
        try:
            return cast(p)
        except (TypeError, ValueError):
            # a malformed plan value must never break dispatch — fall
            # back to the built-in default (the plan is machine state,
            # not operator input; only explicit knobs may raise)
            return default
    return p


def attr_or_plan(attr_value, default_value, env_name, kind, param, n=None,
                 cast=None):
    """Per-call knob resolution for module-attr knobs (the env-latched,
    test/registry-patchable kind): the attr wins whenever it was pinned
    — the env var is set, or the attr was patched away from its
    built-in default — otherwise the active plan's winner, else the
    attr (which still holds the default)."""
    if attr_value != default_value or env_name in os.environ:
        return attr_value
    p = plan_param(kind, param, n)
    if p is None:
        return attr_value
    if cast is not None:
        try:
            return cast(p)
        except (TypeError, ValueError):
            # malformed plan value: keep the default (see env_or_plan)
            return attr_value
    return p


@contextlib.contextmanager
def plan_override(cells, fingerprint="override"):
    """Temporarily install a plan built from `cells` ({(kind, n):
    params}) — the Autotuner's candidate-application mechanism; env-
    pinned knobs still win (candidates are deduped against what the
    resolvers actually report). Restores the previous plan (and bumps
    the revision again) on exit."""
    prev = _active_plan
    set_active_plan(KernelPlan(fingerprint, dict(cells)))
    try:
        yield
    finally:
        set_active_plan(prev)


class _NullMetrics:
    def inc(self, name, by=1):
        pass

    def gauge(self, name, value):
        pass

    def observe(self, name, seconds):
        pass


# --- the autotuner -----------------------------------------------------------

class Autotuner:
    """Empirical per-cell calibration (see module docstring).

    shapes: evaluation-domain sizes (powers of two) to calibrate at —
    the REAL launch widths: the NTT cell measures the Montgomery-
    boundary kernel at (16, n); the MSM cell builds an (n + 3)-wide
    base context (the prover's blinded-handle widths are n+2/n+3) and
    commits an (n + 2)-wide Montgomery coefficient handle; the field
    cell measures a jitted mont_mul at (16, n) lanes.

    budget_s bounds the WHOLE run: once spent, remaining candidates and
    cells are skipped (cells already decided keep their winners; a cell
    whose parity reference never ran is simply absent — uncalibrated
    cells resolve to the built-in defaults, so a truncated run is
    always safe, just less tuned).
    """

    PARITY = {"ntt": {"kernel": "xla", "radix": 2},
              "msm": {"kernel": "xla", "bucket_update": "put"},
              "field": {"mul": "u32"}}

    def __init__(self, shapes, budget_s=None, metrics=None,
                 kinds=("ntt", "msm", "field"), seed=0xD7):
        self.shapes = sorted({int(s) for s in shapes})
        if budget_s is None:
            budget_s = float(os.environ.get("DPT_AUTOTUNE_BUDGET_S", "120"))
        self.budget_s = float(budget_s)
        self.metrics = metrics if metrics is not None else _NullMetrics()
        self.kinds = tuple(kinds)
        self.seed = seed
        self._deadline = None
        self._data = {}

    # -- public entry ---------------------------------------------------------

    def run(self, aot=False):
        """Measure every cell within budget; returns the KernelPlan.
        aot=True additionally pre-lowers/compiles the winners' kernel
        variants (NttPlan.aot_compile / MsmContext.aot_compile) with
        the fresh plan ACTIVE, so the executables that land in the
        persistent compile cache are exactly the ones the plan will
        dispatch at serve time."""
        t0 = time.monotonic()
        self._deadline = t0 + self.budget_s
        self.metrics.inc("autotune_runs")
        plan = KernelPlan(machine_fingerprint())
        for n in self.shapes:
            for kind in self.kinds:
                cell = self._tune_cell(kind, n)
                if cell is not None:
                    plan.cells[(kind, n)] = cell
                    self.metrics.inc("autotune_cells")
        plan.meta = {
            "created": round(time.time(), 3),
            "budget_s": self.budget_s,
            "run_s": round(time.monotonic() - t0, 3),
            "shapes": self.shapes,
            "platform": self._backend_platform(),
        }
        if aot:
            prev = active_plan()
            set_active_plan(plan)
            try:
                plan.meta["aot"] = self._aot_winners(plan)
            finally:
                set_active_plan(prev)
        self.metrics.observe("autotune_run_s", time.monotonic() - t0)
        return plan

    # -- cell machinery -------------------------------------------------------

    def _out_of_budget(self):
        return self._deadline is not None \
            and time.monotonic() > self._deadline

    def _tune_cell(self, kind, n):
        """Measure one (kind, n) cell: parity core first (fixes the
        bit-identity reference), then the deduped candidate grid.
        Returns the cell record, or None (budget ran out before the
        reference, or nothing measured)."""
        if self._out_of_budget():
            return None
        candidates = [dict(self.PARITY[kind])] + self._candidates(kind, n)
        seen = set()
        rejected = set()
        measured = []  # (seconds, sig_tuple, resolved_params, aux)
        ref = None
        parity_s = None
        rejects = errors = 0
        for cand in candidates:
            if ref is not None and self._out_of_budget():
                break
            resolved = self._resolved(kind, n, cand)
            sig = tuple(sorted(resolved.items()))
            if sig in seen:
                continue
            seen.add(sig)
            try:
                with plan_override({(kind, n): cand}):
                    out, dt, aux = self._run_candidate(kind, n, cand)
            except Exception:  # noqa: BLE001 - a candidate that cannot
                # build/trace/run is skipped, never fatal to the
                # calibration pass (e.g. an interpret-mode kernel a
                # platform refuses)
                errors += 1
                self.metrics.inc("autotune_candidate_errors")
                if ref is None:
                    # the PARITY CORE itself failed: without a
                    # bit-identity reference no winner can be gated, and
                    # letting the next successful candidate become the
                    # reference would gate correct candidates against a
                    # possibly-wrong kernel — abandon the cell (defaults
                    # stay in force)
                    return None
                continue
            self.metrics.inc("autotune_measure_runs")
            if ref is None:
                # the first successful measurement is the parity core by
                # construction (candidates[0]); its output is the
                # reference every winner must match bit for bit
                ref = out
                parity_s = dt
            elif out != ref:
                rejects += 1
                rejected.add(sig)
                self.metrics.inc("autotune_parity_rejects")
                continue
            measured.append((dt, sig, resolved, aux))
        if not measured:
            return None
        measured.sort(key=lambda m: m[0])
        best_s, _sig, params, aux = measured[0]
        params = dict(params)
        params.update(aux or {})
        cell = {"params": params,
                "best_s": round(best_s, 6),
                "parity_s": round(parity_s, 6),
                "candidates": len(measured),
                "parity_rejects": rejects,
                "errors": errors}
        # default_s: what the knob-free defaults would have run (the
        # resolved empty-candidate config) — the per-cell record of what
        # the plan is worth on this machine
        default_sig = tuple(sorted(self._resolved(kind, n, {}).items()))
        for dt, sig, _p, _a in measured:
            if sig == default_sig:
                cell["default_s"] = round(dt, 6)
                if best_s > 0:
                    cell["speedup_vs_default"] = round(dt / best_s, 3)
                break
        if "default_s" not in cell and default_sig not in rejected:
            # the knob-free default config was never measured (budget
            # truncation or a candidate error cut the grid short): an
            # undecided cell must NOT persist — its "winner" could be
            # just the slow parity reference, and a persisted plan would
            # then make every future start SLOWER than running with no
            # plan at all. (If the default was measured and REJECTED as
            # wrong, any bit-correct winner beats it — keep the cell.)
            return None
        return cell

    def _run_candidate(self, kind, n, cand):
        """Measure ONE candidate (already applied as the active plan by
        the caller): returns (output_bytes, seconds_per_call, aux_params).
        The single monkeypatch seam the parity-gate tests use."""
        if kind == "ntt":
            return self._run_ntt(n)
        if kind == "msm":
            return self._run_msm(n)
        return self._run_field(n)

    def _timed(self, fn, sync):
        """Warm (compile) once, then time `reps` calls; reps shrink to 1
        on slow platforms so calibration respects its budget."""
        t0 = time.perf_counter()
        out = fn()
        sync(out)
        warm_s = time.perf_counter() - t0
        reps = 1 if warm_s > 1.0 else 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        sync(out)
        return out, (time.perf_counter() - t0) / reps

    # -- candidate grids ------------------------------------------------------

    @staticmethod
    def _msm_padded(n):
        """padded_n of the MSM context _run_msm actually measures: n + 3
        bases (the prover's blinded-handle width), padded even."""
        return (n + 3) + ((n + 3) % 2)

    def _backend_platform(self):
        import jax

        return jax.default_backend()

    def _pallas_ok(self):
        """Pallas kernels join the candidate grid only where they can
        actually win: on TPU (interpret mode elsewhere is a test
        vehicle, orders of magnitude off the XLA paths and far too slow
        to measure inside a calibration budget). DPT_AUTOTUNE_INTERPRET=1
        forces them in for harness tests."""
        if os.environ.get("DPT_AUTOTUNE_INTERPRET") == "1":
            return True
        return self._backend_platform() == "tpu"

    def _candidates(self, kind, n):
        if kind == "ntt":
            from . import ntt_jax

            grid = [{"kernel": "xla", "radix": r}
                    for r in ntt_jax.RADIX_CHOICES]
            if self._pallas_ok():
                for vmem in (2, 6, 12):
                    for rows in (16, 64):
                        grid.append({"kernel": "pallas", "radix": 4,
                                     "vmem_mb": vmem, "rows": rows})
            return grid
        if kind == "msm":
            from . import msm_jax

            grid = []
            kernels = ["xla"] + (["pallas"] if self._pallas_ok() else [])
            # the measured context is (n + 3) bases padded even (the
            # prover's blinded-handle width; MsmContext.padded_n) —
            # c_batch applies from 256 padded points up. _resolved uses
            # the same width, so c candidates dedup iff the real context
            # would ignore c.
            wide = self._msm_padded(n) >= 256
            for kern in kernels:
                updates = msm_jax.BUCKET_UPDATE_CHOICES \
                    if kern == "xla" else ("onehot",)
                for up in updates:
                    for c in (msm_jax.C_CHOICES if wide else (None,)):
                        for gmax in (512, 1024):
                            cand = {"kernel": kern, "bucket_update": up,
                                    "group_max": gmax}
                            if c is not None:
                                cand["c"] = c
                            if kern == "pallas":
                                cand["vmem_mb"] = 6
                            grid.append(cand)
            return grid
        from . import field_jax as FJ

        grid = [{"mul": m} for m in ("f32", "u32")]
        if self._pallas_ok():
            for tile in (256, 512, 1024):
                grid.append({"mul": "pallas", "lane_tile": tile})
        del FJ
        return grid

    def _resolved(self, kind, n, cand):
        """Read the candidate BACK through the dispatch resolvers (with
        the candidate applied as the plan): what would actually run.
        Env-pinned dimensions and platform coercions collapse here, so
        duplicate configurations are measured once and the plan records
        reality, not intent."""
        with plan_override({(kind, n): cand}):
            if kind == "ntt":
                from . import ntt_jax, ntt_pallas

                kern = ntt_jax._active_kernel(n=n)
                sig = {"kernel": kern,
                       "radix": ntt_jax._active_radix(n=n)}
                if kern == "pallas":
                    sig["vmem_mb"] = ntt_pallas._vmem_mb(n)
                    sig["rows"] = ntt_pallas._rows_knob(n)
                return sig
            if kind == "msm":
                from . import msm_jax

                kern = msm_jax._kernel_mode(n)
                sig = {"kernel": kern,
                       "group_max": msm_jax._group_max_knob(n)}
                if kern == "xla":
                    sig["bucket_update"] = "onehot" \
                        if msm_jax._use_onehot_update(n) else "put"
                else:
                    from . import msm_pallas

                    sig["vmem_mb"] = msm_pallas._vmem_mb()
                padded = self._msm_padded(n)
                if padded >= 256:
                    sig["c"] = msm_jax._c_batch_knob(padded)
                return sig
            from . import field_jax as FJ

            # mirror mont_mul's REAL dispatch order: the _use_pallas
            # gate (which also coerces a 'pallas' candidate below
            # _PALLAS_MIN_LANES back to the XLA path) first, then the
            # f32/u32 split — so a candidate the dispatch would coerce
            # dedups onto what actually runs instead of being measured
            # as a distinct (identical) configuration
            if FJ._use_pallas((FJ.FR.n_limbs, n)):
                mode = "pallas"
            else:
                mode = "f32" if FJ._f32_active(n) else "u32"
            sig = {"mul": mode}
            if mode == "pallas":
                from . import field_pallas as FP

                sig["lane_tile"] = FP.lane_tile()
            return sig

    # -- per-kind measurement -------------------------------------------------

    def _fr_mont_limbs(self, count, seed_off=0):
        import numpy as np

        from ..constants import FR_LIMBS, FR_MONT_R, R_MOD
        from .limbs import ints_to_limbs

        rng = np.random.default_rng(self.seed + seed_off)
        vals = rng.integers(1, 1 << 62, size=count, dtype=np.int64)
        return ints_to_limbs([int(v) * FR_MONT_R % R_MOD for v in vals],
                             FR_LIMBS)

    def _run_ntt(self, n):
        import numpy as np
        import jax.numpy as jnp

        from . import ntt_jax

        key = ("ntt", n)
        if key not in self._data:
            self._data[key] = jnp.asarray(self._fr_mont_limbs(n))
        v = self._data[key]
        plan = ntt_jax.get_plan(n)
        fn = plan.kernel(boundary="mont")
        out, dt = self._timed(lambda: fn(v),
                              lambda x: np.asarray(x[:, :1]))
        return np.asarray(out).tobytes(), dt, None

    def _run_msm(self, n):
        import numpy as np
        import jax.numpy as jnp

        from ..constants import G1_GEN_X, G1_GEN_Y
        from . import msm_jax

        key = ("msm", n)
        if key not in self._data:
            # real prover widths: an (n + 3)-wide key (the permutation
            # poly's blinded width), an (n + 2)-wide coefficient handle
            self._data[key] = (
                [(G1_GEN_X, G1_GEN_Y)] * (n + 3),
                jnp.asarray(self._fr_mont_limbs(n + 2, seed_off=1)))
        bases, handle = self._data[key]
        ctx = msm_jax.MsmContext(bases)
        pt, dt = self._timed(lambda: ctx.msm_mont_limbs(handle),
                             lambda x: None)
        aux = None
        if dt > 0:
            windows = -(-msm_jax.SCALAR_BITS // ctx.c_batch)
            aux = {"adds_per_s": round(windows * ctx.padded_n / dt, 1)}
        return repr(pt).encode(), dt, aux

    def _run_field(self, n):
        import numpy as np
        import jax

        from . import field_jax as FJ

        key = ("field", n)
        if key not in self._data:
            import jax.numpy as jnp

            self._data[key] = (jnp.asarray(self._fr_mont_limbs(n, 2)),
                               jnp.asarray(self._fr_mont_limbs(n, 3)))
        a, b = self._data[key]
        # a fresh jit wrapper per candidate: the mul-path branch is taken
        # at trace time, and reusing one wrapper would serve candidate
        # A's executable to candidate B at the same shape
        fn = jax.jit(lambda x, y: FJ.mont_mul(FJ.FR, x, y))
        out, dt = self._timed(lambda: fn(a, b),
                              lambda x: np.asarray(x[:, :1]))
        return np.asarray(out).tobytes(), dt, None

    # -- AOT ------------------------------------------------------------------

    def _aot_winners(self, plan):
        """Pre-lower/compile the winners' kernel variants (plan active —
        the caller set it) so the persistent compile cache holds exactly
        what serving will dispatch; executables land under whatever
        cache dir the process configured (the store-owned one for
        scripts/autotune.py and serve startup)."""
        from . import ntt_jax

        report = {}
        for (kind, n), _cell in sorted(plan.cells.items()):
            if self._out_of_budget():
                report["truncated"] = True
                break
            try:
                if kind == "ntt":
                    chunk = max(1, min(8, (1 << 21) // n))
                    report[f"ntt:{n}"] = ntt_jax.get_plan(n).aot_compile(
                        batch_sizes=(chunk,) if chunk > 1 else ())
                elif kind == "msm":
                    bases, _h = self._data.get(("msm", n), (None, None))
                    if bases is not None:
                        from . import msm_jax

                        ctx = msm_jax.MsmContext(bases)
                        report[f"msm:{n}"] = ctx.aot_compile(
                            batch_sizes=(1, 2),
                            digit_widths=(n + 2, n + 3))
            except Exception as e:  # noqa: BLE001 - AOT is an
                # accelerator, never a calibration failure
                report[f"{kind}:{n}"] = {"error": repr(e)}
        return report
