"""Pallas fused multi-stage NTT: radix-16/64 worth of butterflies per
HBM round trip.

WHY (BENCH_r05 + ROADMAP direction 3): after the fused MSM landed, the
NTT is the prover's dominant non-MSM kernel and it is pure
HBM-bandwidth-bound — `mfu_ntt_pct` ~2.15 against a ~64% Fq multiplier,
because every butterfly stage of the constant-geometry core round-trips
the full (16, n) vector through HBM and radix-4 (PR 3) only halved the
stage count. This kernel applies the exact msm_pallas playbook: keep
the working set VMEM-resident across MANY stages, so one HBM round trip
retires R = log2(rows) radix-2 stages (rows = 16..64, i.e. radix-16/64)
instead of two.

THE TILING (why a column tile can run R stages locally): one
constant-geometry radix-2 stage maps v[p], v[p + n/2] -> out[2p],
out[2p+1] — the TOP index bit is consumed and a new BOTTOM bit is
produced. Composing R consecutive stages therefore consumes the top R
bits and emits R bottom bits: with the input viewed as a (2^R, M)
matrix (row r = top bits, column c = low bits, M = n/2^R), the final
outputs out[(c << R) | b] for one column c depend ONLY on the 2^R input
rows of that same column. Columns never mix inside a group — so a
(16, 2^R, T) column tile runs all R stages in VMEM. Better: tracking
the index algebra shows the WITHIN-TILE dataflow is itself constant
geometry on the row axis (butterfly row r with row r + 2^(R-1), write
rows 2r, 2r+1), and the stage-τ twiddle for pair row r depends only on
(r mod 2^τ, c) — so per fused stage the kernel streams a small
(16, 2^τ, T) table of PRECOMPUTED twiddle values and broadcasts it
along the repeat axis. Total twiddle traffic per group is < n lanes
(sum_τ 2^τ · M), comparable to one radix-4 pair's gather volume, while
the DATA makes ceil(log2(n)/R) round trips instead of log2(n)/2.

Traffic model at n = 2^20, rows = 64 (R = 6): radix-4 moves the
(16, n) vector through HBM 10 times (plus twiddle gathers); the fused
kernel moves it ceil(20/6) = 4 times plus one output-permutation pass
— ~2.2x less stage traffic, approaching the 2-pass floor of a
bandwidth-bound transform. The butterfly math itself reuses the
bit-identical in-VMEM Montgomery primitives shared with
curve_pallas/field_pallas (strict SOS multiply, paired Kogge-Stone
carry sweeps), so outputs are limb-identical to the XLA stage cores.

BOUNDARY FUSION (mirrors PR 3's peeled stages): the forward-coset g^j
pre-scale rides the first group as a per-block multiply (group 0's
first stage has trivial twiddles, exactly like _stage4_coset_first);
the iNTT 1/n and inverse-coset g^-i post-scales ride the LAST group,
applied pre-permutation through a bit-reverse-reordered table. The
output bit-reversal itself stays an XLA gather on the kernel result (a
rectangular-block write of a bit-reversed tile is not expressible as a
BlockSpec; the gather is pure data movement and fuses with whatever
consumes the output — e.g. the round-3 pointwise epilogues). Consumer-
side fusion LANDED (DPT_R3_BITREV, jax_backend): the fused round-3
pipeline skips this gather entirely on every producer launch
(NttPlan kernel defer_perm — accumulators stay in constant-geometry
order) and pays ONE input gather at the consuming coset-iNTT instead.

Select with DPT_NTT_KERNEL=auto|pallas|xla (auto: pallas on TPU;
interpret mode elsewhere is test-only, like msm_pallas). The radix-4
XLA core stays the parity/debug reference. Tiles are sized against
DPT_NTT_PALLAS_VMEM_MB; DPT_NTT_PALLAS_ROWS caps the per-group row
count (the analog of msm's group cap).
"""

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from . import autotune
from .curve_pallas import _mod_add, _mod_sub, _row0_mask, field_consts
from .field_pallas import _carry_sweep_val, _cols_to_limbs, _to_bytes_f32

# peak VMEM one grid cell may occupy; the lane tile (and then the fused
# row count) shrink to fit. Per (row, lane) the cell charges: in + out
# blocks (2 x 4 B x 16 limbs), the stage twiddle blocks (sum_τ 2^τ ~ one
# more 16-limb row set), a boundary-scale block, and the (4L, rows, T)
# f32 multiplier scratch (64 rows x 4 B) -> ~512 B.
_VMEM_MB_DEFAULT = 6
_VMEM_MB = int(os.environ.get("DPT_NTT_PALLAS_VMEM_MB",
                              str(_VMEM_MB_DEFAULT)))
_PER_ROW_LANE_BYTES = 512

# group cap: largest fused row count 2^R per HBM round trip (the analog
# of msm_jax's DPT_MSM_GROUP_MAX plane cap); 64 = radix-64
_ROWS_CAP_DEFAULT = 64
_ROWS_CAP = int(os.environ.get("DPT_NTT_PALLAS_ROWS",
                               str(_ROWS_CAP_DEFAULT)))


def _vmem_mb(n=None):
    """Per-call VMEM budget: the env/patched module attr wins, else the
    autotune plan's winner near domain size n, else the default."""
    return int(autotune.attr_or_plan(
        _VMEM_MB, _VMEM_MB_DEFAULT, "DPT_NTT_PALLAS_VMEM_MB",
        "ntt", "vmem_mb", n, cast=int))


def _rows_knob(n=None):
    """Per-call fused-row cap knob (same precedence as _vmem_mb)."""
    return int(autotune.attr_or_plan(
        _ROWS_CAP, _ROWS_CAP_DEFAULT, "DPT_NTT_PALLAS_ROWS",
        "ntt", "rows", n, cast=int))


def fused_rows_cap(n=None):
    """Largest power-of-two fused row count whose working set keeps a
    full 128-lane tile inside the VMEM budget (>= 4 so tiny budgets
    still fuse two stages; capped by the group knob)."""
    cap = (_vmem_mb(n) << 20) // (_PER_ROW_LANE_BYTES * 128)
    cap = 1 << max(2, cap.bit_length() - 1)
    knob = max(4, _rows_knob(n))
    knob = 1 << (knob.bit_length() - 1)
    return min(cap, knob)


def _lane_tile(m_cols, rows, n=None):
    """Columns per grid cell: widest power-of-two tile within budget
    (>= 1; 256 lanes is plenty to feed the VPU)."""
    t = (_vmem_mb(n) << 20) // (_PER_ROW_LANE_BYTES * rows)
    t = 1 << max(0, t.bit_length() - 1)
    return max(1, min(m_cols, t, 256))


def plan_schedule(log_n):
    """Balanced partition of the log2(n) radix-2 stages into
    ceil(log_n / R_max) fused groups: tuple of (s0, R) with s0 the first
    global stage of the group. () for log_n < 2 (no fusion win; the XLA
    core covers those widths — same fallback as radix-4's n <= 2)."""
    if log_n < 2:
        return ()
    r_max = fused_rows_cap(1 << log_n).bit_length() - 1
    n_groups = -(-log_n // r_max)
    base, extra = divmod(log_n, n_groups)
    sizes = [base + 1] * extra + [base] * (n_groups - extra)
    out, s0 = [], 0
    for r in sizes:
        out.append((s0, r))
        s0 += r
    return tuple(out)


def group_tables(log_n, exps, pow_tab, schedule):
    """Host twiddle-VALUE tables for every fused stage, as a FLAT dict
    (flat so mesh shard_map const specs and jit args treat them like any
    other stage-core table): key 'pg{g}s{t}' -> (16, 2^t, M_g) Montgomery
    values, M_g = n >> R_g.

    Stage t of group (s0, R) butterflies pair row r of column c with
    twiddle w^e(s0+t, (c << t) | (r mod 2^t)) — the global pair index is
    (c << t) | h + q*2^(k-R+t) and e(s, p) depends on p mod 2^s only, so
    the repeat coordinate q drops out and the table is (2^t, M) instead
    of (2^(R-1), M). Group 0's stage 0 is the trivial w^0 stage (no
    table, no multiply — the peeled-first-stage identity of PR 3)."""
    n = 1 << log_n
    out = {}
    for g, (s0, r) in enumerate(schedule):
        m_cols = n >> r
        c = np.arange(m_cols, dtype=np.int64)[None, :]
        for t in range(r):
            if s0 + t == 0:
                continue  # trivial stage: every twiddle is w^0 = 1
            h = np.arange(1 << t, dtype=np.int64)[:, None]
            e = exps[s0 + t, (c << t) | h]  # (2^t, M)
            out[f"pg{g}s{t}"] = pow_tab[:, e]
    return out


def schedule_from_consts(log_n, consts):
    """Recover the group schedule from the table keys/shapes, so the
    traced program always agrees with the consts it was handed (the env
    knobs may have moved between consts build and trace)."""
    rows = {}
    for key, v in consts.items():
        if not key.startswith("pg"):
            continue
        g = int(key[2:key.index("s")])
        m_cols = v.shape[-1]
        rows[g] = log_n - (m_cols.bit_length() - 1)
    if not rows:
        return ()
    out, s0 = [], 0
    for g in range(max(rows) + 1):
        if g not in rows:
            raise ValueError(f"pallas NTT consts missing group {g} tables")
        out.append((s0, rows[g]))
        s0 += rows[g]
    if s0 != log_n:
        raise ValueError(
            f"pallas NTT schedule covers {s0} stages, expected {log_n}")
    return tuple(out)


def _col3(limbs):
    """Python limb ints -> (L, 1, 1) i32 column broadcastable against the
    kernel's (L, rows, T) blocks (pallas kernels cannot capture array
    constants; see curve_pallas._col_const)."""
    return jnp.concatenate(
        [jnp.full((1, 1, 1), int(v), jnp.int32) for v in limbs], axis=0)


def fr_consts():
    """Hashable Fr constant tuple (jit-static kernel parameter)."""
    from .field_jax import FR

    return field_consts(FR)


def _env3(kc):
    """Constant tuple -> the dict the block-shaped helpers consume, with
    the modulus columns at rank 3 (curve_pallas.consts_env is the rank-2
    spelling for the lane-flat curve kernels)."""
    k = dict(kc)
    k["negp"] = _col3(k.pop("negmod_limbs"))
    k["p_col"] = _col3(k.pop("mod_limbs"))
    return k


def _band3(t_ref, a_bytes, b_bytes):
    """Banded byte-product accumulation on (2L, rh, T) blocks into the
    (4L, rows, T) f32 VMEM scratch (field_pallas._band_mul one rank up;
    the zeroing covers the FULL scratch so the write is strong for the
    static verifier's ref cells — see curve_pallas._band_mul_w)."""
    nb, rh = a_bytes.shape[0], a_bytes.shape[1]
    t_ref[...] = jnp.zeros(t_ref.shape, jnp.float32)
    for i in range(nb):
        t_ref[i:i + nb, :rh] += a_bytes[i][None] * b_bytes
    return t_ref[:, :rh]


def _band3_const(t_ref, c_bytes, b_bytes):
    """Same accumulation with a compile-time constant multiplicand."""
    nb, rh = b_bytes.shape[0], b_bytes.shape[1]
    t_ref[...] = jnp.zeros(t_ref.shape, jnp.float32)
    for i, c in enumerate(c_bytes):
        if c == 0:
            continue
        t_ref[i:i + nb, :rh] += np.float32(c) * b_bytes
    return t_ref[:, :rh]


def _mont3(t_ref, a, b, k):
    """Full strict Montgomery SOS product on (L, rh, T) i32 blocks —
    curve_pallas._mont_mul_val one rank up (same phase sequence as
    field_jax.mont_mul, so results are fully reduced and limb-identical
    to the XLA stage cores' multiplies)."""
    L = k["n_limbs"]
    a_by = _to_bytes_f32(a)
    b_by = _to_bytes_f32(b)
    t_cols = _band3(t_ref, a_by, b_by)
    t_limbs = _cols_to_limbs(t_cols)
    t_lo, c_t = _carry_sweep_val(t_limbs[:L], L)
    tlo_by = _to_bytes_f32(t_lo)
    m_cols = _band3_const(t_ref, k["ninv_bytes"], tlo_by)[:2 * L]
    m, _ = _carry_sweep_val(_cols_to_limbs(m_cols), L)
    m_by = _to_bytes_f32(m)
    mp_cols = _band3_const(t_ref, k["mod_bytes"], m_by)
    mp_limbs = _cols_to_limbs(mp_cols)
    _, c_low = _carry_sweep_val(t_lo + mp_limbs[:L], L)
    hi = t_limbs[L:] + mp_limbs[L:]
    hi = hi + _row0_mask(hi.shape) * (c_t + c_low)[None]
    r1, _ = _carry_sweep_val(hi, L)
    r2, c2 = _carry_sweep_val(hi + k["negp"], L)
    return jnp.where((c2 != 0)[None], r2, r1)


def _ntt_group_kernel(x_ref, *refs, kc, rows, tile, stage_tabs, has_pre,
                      has_post):
    """One (batch, column-tile) grid cell: R = log2(rows) fused
    constant-geometry stages entirely in VMEM.

    x_ref: (16, 1, rows, T) input block (rows = top index bits). refs:
    [pre block] + one (16, 2^t, T) twiddle block per non-trivial stage +
    [post block], then the (16, 1, T, rows) output block and the
    (4*16, rows, T) f32 multiplier scratch. stage_tabs[t] says whether
    stage t has a table (False only for the trivial global stage 0)."""
    refs = list(refs)
    t_ref = refs.pop()
    o_ref = refs.pop()
    k = _env3(kc)
    L = k["n_limbs"]
    cur = x_ref[...].reshape(L, rows, tile).astype(jnp.int32)
    if has_pre:
        # forward-coset g^j pre-scale fused into the first load (the
        # quarters-of-the-coset-table trick of _stage4_coset_first,
        # generalized to 2^R rows)
        cur = _mont3(t_ref, cur, refs.pop(0)[...].astype(jnp.int32), k)
    half = rows // 2
    for t, has_tab in enumerate(stage_tabs):
        u = cur[:, :half]
        w = cur[:, half:]
        if has_tab:
            tw = refs.pop(0)[...].astype(jnp.int32)  # (L, 2^t, T)
            reps = half >> t
            twb = jnp.broadcast_to(
                tw[:, None], (L, reps, 1 << t, tile)).reshape(L, half, tile)
            w = _mont3(t_ref, w, twb, k)
        hi = _mod_add(u, w, L, k["negp"])
        lo = _mod_sub(u, w, L, k["p_col"])
        # constant-geometry interleave on the row axis: out[2r] = hi_r,
        # out[2r+1] = lo_r (stack + major-axis reshape, the Mosaic-safe
        # interleave of field_pallas._to_bytes_f32)
        cur = jnp.stack([hi, lo], axis=2).reshape(L, rows, tile)
    if has_post:
        # iNTT 1/n / inverse-coset scales, bit-reverse-reordered so they
        # apply pre-permutation (see NttPlan._kernel_consts)
        cur = _mont3(t_ref, cur, refs.pop(0)[...].astype(jnp.int32), k)
    out = cur.swapaxes(1, 2).astype(jnp.uint32)  # (L, T, rows)
    o_ref[...] = out.reshape(o_ref.shape)


def _group_call(v, r, tws, pre, post, interpret):
    """One fused group over the whole (16, B, n) array: grid
    (B, M/T) of independent column tiles; input viewed as
    (16, B, 2^R, M), output written as (16, B, M, 2^R) — which IS the
    flat constant-geometry output vector, reshaped."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L, B, n = v.shape
    rows = 1 << r
    m_cols = n // rows
    tile = _lane_tile(m_cols, rows, n)
    operands = [v.reshape(L, B, rows, m_cols)]
    in_specs = [pl.BlockSpec((L, 1, rows, tile), lambda b, c: (0, b, 0, c))]
    if pre is not None:
        operands.append(jnp.asarray(pre).reshape(L, rows, m_cols))
        in_specs.append(pl.BlockSpec((L, rows, tile), lambda b, c: (0, 0, c)))
    for t, tw in enumerate(tws):
        if tw is None:
            continue
        operands.append(jnp.asarray(tw))
        in_specs.append(
            pl.BlockSpec((L, 1 << t, tile), lambda b, c: (0, 0, c)))
    if post is not None:
        operands.append(jnp.asarray(post))
        in_specs.append(pl.BlockSpec((L, rows, tile), lambda b, c: (0, 0, c)))
    kernel = functools.partial(
        _ntt_group_kernel, kc=fr_consts(), rows=rows, tile=tile,
        stage_tabs=tuple(tw is not None for tw in tws),
        has_pre=pre is not None, has_post=post is not None)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((L, B, m_cols, rows), jnp.uint32),
        grid=(B, m_cols // tile),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((L, 1, tile, rows), lambda b, c: (0, b, c, 0)),
        scratch_shapes=[pltpu.VMEM((4 * L, rows, tile), jnp.float32)],
        interpret=interpret,
    )(*operands)
    return out.reshape(L, B, n)


def run_groups(v, consts):
    """(16, B, n) natural-order Montgomery rows -> ALL butterfly stages,
    fused group-wise; output in the same bit-reversed (constant-geometry)
    order the XLA stage cores produce, so the caller applies
    consts['perm'] exactly as before. 'ppre' (coset pre-scale, flat
    (16, n)) rides the first group; 'ppost' (reordered inverse scales,
    (16, rows, M)) rides the last."""
    n = v.shape[2]
    log_n = n.bit_length() - 1
    schedule = schedule_from_consts(log_n, consts)
    if not schedule:
        raise ValueError("no pallas NTT tables in consts")
    interpret = jax.default_backend() != "tpu"
    last = len(schedule) - 1
    for g, (s0, r) in enumerate(schedule):
        tws = [consts.get(f"pg{g}s{t}") for t in range(r)]
        pre = consts.get("ppre") if g == 0 else None
        post = consts.get("ppost") if g == last else None
        v = _group_call(v, r, tws, pre, post, interpret)
    return v
