"""Device kernels for the prover's formerly-host-side round math.

These move the two serial hot loops the reference keeps on the dispatcher —
the round-2 permutation running product (/root/reference/src/dispatcher2.rs:
330-345) and the round-3 quotient evaluation loop (dispatcher2.rs:434-504) —
plus polynomial evaluation, linear combination, blinding, and the round-5
synthetic divisions (dispatcher2.rs:651-688) onto the device, so that wire/
selector/sigma/z polynomials stay device-resident in Montgomery form across
all 5 rounds and only transcript scalars cross the host boundary mid-prove
(SURVEY.md §7 stage 4; the capability the reference's 12 declared-but-never-
implemented round3*/round5* RPCs were sketching, src/hello_world.capnp:26-44).

Everything here is O(1)-size traced: sequential recurrences become
log-depth ladders — prefix PRODUCTS as the single-width Hillis-Steele
shift-multiply ladder (field_jax.cumprod_mont; NOT associative_scan,
whose multi-width lowering wedged the remote TPU compile at 2^18 —
see that docstring before reintroducing one), suffix SUMS as the
zero-padded add ladder (field_jax.cumsum_mont), and fixed-exponent
power ladders as bit-table scans.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import R_MOD, FR_LIMBS, FR_MONT_R
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_ints, int_to_limbs

_MONT_ONE = int_to_limbs(FR_MONT_R % R_MOD, FR_LIMBS)


def lift(values):
    """Host canonical ints -> (16, n) Montgomery limb array (host numpy;
    becomes device-resident at first jit use)."""
    return ints_to_limbs([v * FR_MONT_R % R_MOD for v in values], FR_LIMBS)


def lift_scalar(x, ndim=2):
    """One int -> (16, 1, ..) Montgomery broadcastable constant."""
    arr = int_to_limbs(x % R_MOD * FR_MONT_R % R_MOD, FR_LIMBS)
    return arr.reshape((FR_LIMBS,) + (1,) * (ndim - 1))


def lower(v):
    """(16, n) Montgomery device array -> host canonical int list."""
    out = _from_mont_jit(v)
    return limbs_to_ints(np.asarray(out))


def _one_like(v):
    return jnp.broadcast_to(
        jnp.asarray(_MONT_ONE).reshape((FR_LIMBS,) + (1,) * (v.ndim - 1)),
        v.shape)


def _mm(a, b):
    return FJ.mont_mul(FR, a, b)


def cumprod(v, reverse=False):
    """Inclusive prefix (or suffix) products along axis 1 of (16, n):
    the single-width Hillis-Steele ladder (see field_jax.cumprod_mont for
    why not associative_scan — the 2^18 remote-compile wedge)."""
    return FJ.cumprod_mont(FR, v, reverse=reverse)


def fr_pow(base, exp):
    """base^exp for a fixed public int exponent; (16, *b) -> (16, *b).

    Square-and-multiply as a scan over the exponent's bits (MSB first):
    O(1) traced ops, ~255 tiny sequential steps."""
    nbits = max(exp.bit_length(), 1)
    bits = np.array([(exp >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                    dtype=np.uint32)

    def step(acc, bit):
        sq = _mm(acc, acc)
        mul = _mm(sq, base)
        return jnp.where(bit != 0, mul, sq), None

    acc, _ = lax.scan(step, _one_like(base), bits)
    return acc


def batch_inverse(v):
    """Elementwise inverse of (16, n) nonzero Montgomery values.

    Montgomery's trick, log-depth: one prefix-product scan, one suffix-
    product scan, ONE field inversion (fixed-exponent ladder), two
    elementwise products:  v_j^-1 = P_{j-1} * S_{j+1} * (P_n)^-1."""
    pre = cumprod(v)
    suf = cumprod(v, reverse=True)
    total_inv = fr_pow(pre[:, -1:], R_MOD - 2)
    one = _one_like(v[:, :1])
    p_shift = jnp.concatenate([one, pre[:, :-1]], axis=1)
    s_shift = jnp.concatenate([suf[:, 1:], one], axis=1)
    return _mm(_mm(p_shift, s_shift), total_inv)


# --- round 2: permutation running product -----------------------------------

def perm_product(wires, id_tab, sig_tab, beta, gamma):
    """z(w^j) running-product evaluations on device.

    wires/id_tab/sig_tab: (16, w, n) Montgomery (witness values, identity
    permutation values k_i*w^j, and sigma-mapped identity values);
    beta/gamma: (16, 1, 1) Montgomery scalars. Returns (16, n) evals:
    [1, prod_{t<j} num_t/den_t ...] — the reference's O(n*w) host loop
    (src/dispatcher2.rs:330-345) as two reduces + a prefix scan."""
    n = wires.shape[2]
    t = FJ.add(FR, wires, jnp.broadcast_to(gamma, wires.shape))
    num_f = FJ.add(FR, t, _mm(jnp.broadcast_to(beta, id_tab.shape), id_tab))
    den_f = FJ.add(FR, t, _mm(jnp.broadcast_to(beta, sig_tab.shape), sig_tab))

    def wire_reduce(f):  # product over the wire axis (w small, unrolled)
        acc = f[:, 0]
        for i in range(1, f.shape[1]):
            acc = _mm(acc, f[:, i])
        return acc

    nums = wire_reduce(num_f)
    dens = wire_reduce(den_f)
    ratio = _mm(nums, batch_inverse(dens))  # (16, n)
    run = cumprod(ratio[:, :n - 1])
    return jnp.concatenate([_one_like(ratio[:, :1]), run], axis=1)


# --- round 3: quotient evaluations ------------------------------------------

def domain_tables(m, n, gen, group_gen):
    """Witness-independent per-(quot-domain) tables, computed on device.

    Returns dict of (16, m) Montgomery arrays: coset eval points
    ep_i = g*w^i, 1/Z_H(ep) tiled, and 1/(ep - 1)."""
    # ep = g * w^i via prefix products of a constant vector
    w_rep = jnp.broadcast_to(lift_scalar(group_gen),
                             (FR_LIMBS, m)).astype(jnp.uint32)
    pw = cumprod(w_rep)  # w^(i+1)
    g_c = lift_scalar(gen)
    ep = jnp.concatenate(
        [jnp.broadcast_to(g_c, (FR_LIMBS, 1)), _mm(pw[:, :m - 1], g_c)], axis=1)
    ratio = m // n
    one = _one_like(ep)
    zh = FJ.sub(FR, fr_pow(ep[:, :ratio], n), one[:, :ratio])
    # host loop indexes z_h_inv[i % ratio]: the (16, ratio) block repeats
    # m/ratio times
    zh_inv = jnp.tile(batch_inverse(zh), (1, m // ratio))
    shifted_inv = batch_inverse(FJ.sub(FR, ep, one))
    return {"ep": ep, "zh_inv": zh_inv, "shifted_inv": shifted_inv}


def _pow5(x):
    x2 = _mm(x, x)
    return _mm(_mm(x2, x2), x)


def quotient_evals_core(selectors, sigmas, wires, z, z_next, pi, ep, zh_inv,
                        shifted_inv, k, beta, gamma, alpha, alpha_sq_div_n):
    """Coset evaluations of the quotient polynomial, fully elementwise on m
    lanes (the reference's serial O(m) loop, src/dispatcher2.rs:434-504).

    selectors: (16, 13, m); sigmas/wires: (16, 5, m); z/z_next/pi: (16, m);
    ep/zh_inv/shifted_inv: (16, m) domain tables; k: (16, 5, 1); challenge
    scalars (16, 1). z_next is z rolled by -m/n (precomputed by the caller
    so m can be SLICED: every other input is pointwise in the lane index).
    Selector order matches circuit.py (Q_LC x4, Q_MUL x2, Q_HASH x4, Q_O,
    Q_C, Q_ECC)."""
    m = z.shape[1]
    a, b, c, d, e = (wires[:, i] for i in range(5))
    ab = _mm(a, b)
    cd = _mm(c, d)
    gate = FJ.add(FR, selectors[:, 11], pi)  # q_c + pi
    for i, operand in ((0, a), (1, b), (2, c), (3, d)):
        gate = FJ.add(FR, gate, _mm(selectors[:, i], operand))
    gate = FJ.add(FR, gate, _mm(selectors[:, 4], ab))
    gate = FJ.add(FR, gate, _mm(selectors[:, 5], cd))
    for i, operand in ((6, a), (7, b), (8, c), (9, d)):
        gate = FJ.add(FR, gate, _mm(selectors[:, i], _pow5(operand)))
    gate = FJ.add(FR, gate, _mm(selectors[:, 12], _mm(_mm(ab, cd), e)))
    gate = FJ.sub(FR, gate, _mm(selectors[:, 10], e))

    acc1 = z
    acc2 = z_next
    beta_b = jnp.broadcast_to(beta, (FR_LIMBS, m))
    for j in range(5):
        t = FJ.add(FR, wires[:, j], jnp.broadcast_to(gamma, (FR_LIMBS, m)))
        acc1 = _mm(acc1, FJ.add(FR, t, _mm(_mm(jnp.broadcast_to(k[:, j], (FR_LIMBS, m)), ep), beta_b)))
        acc2 = _mm(acc2, FJ.add(FR, t, _mm(sigmas[:, j], beta_b)))
    perm = _mm(jnp.broadcast_to(alpha, (FR_LIMBS, m)), FJ.sub(FR, acc1, acc2))

    one = _one_like(z)
    l1 = _mm(_mm(jnp.broadcast_to(alpha_sq_div_n, (FR_LIMBS, m)),
                 FJ.sub(FR, z, one)), shifted_inv)
    out = FJ.add(FR, _mm(zh_inv, FJ.add(FR, gate, perm)), l1)
    return out


def quotient_evals(selectors, sigmas, wires, z, pi, tabs, k, beta, gamma,
                   alpha, alpha_sq_div_n, ratio):
    """One-shot quotient evaluation over the full domain (the unpacked
    path: host-oracle-shaped backends and the mesh backend, whose GSPMD
    sharding replaces slicing as the memory strategy)."""
    z_next = jnp.roll(z, -ratio, axis=1)
    return quotient_evals_core(
        selectors, sigmas, wires, z, z_next, pi, tabs["ep"], tabs["zh_inv"],
        tabs["shifted_inv"], k, beta, gamma, alpha, alpha_sq_div_n)


# --- streaming round 3: consume each selector/sigma plane as it is made ------
# The residency floor of the packed path is still all 25 coset planes at
# once (6.4 GB packed at m=2^23 — past the measured single-chip budget).
# But the quotient formula reads each SELECTOR plane exactly once (one
# gate term) and each SIGMA plane exactly once (one acc2 factor), so both
# can be folded into running accumulators right after their coset FFT and
# dropped. Only 10 planes ever stay resident: 5 wires, z, z_next, pi→gate,
# acc2 — ~2.5 GB packed at m=2^23, unlocking the n=2^20 prove.
# (Reference formula: /root/reference/src/dispatcher2.rs:434-507.)

# Gate accumulation steps, one jitted program per operand STRUCTURE (the
# wire plane(s) a selector multiplies are passed as arguments, so the 13
# selectors reuse 6 compiled programs instead of 13 — each compile is at
# full quotient-domain width and goes through the remote relay, so the
# program count is cold-prove wall-clock). gate_p is the packed (8, m)
# accumulator (initialized to the pi plane); plane is the UNPACKED
# (16, m) selector coset evals straight from the FFT launch. Selector
# order: circuit.py (Q_LC x4, Q_MUL x2, Q_HASH x4, Q_O, Q_C, Q_ECC).

def _gate_add(gate_p, term):
    return FJ.pack_limb_pairs(
        FJ.add(FR, FJ.unpack_limb_pairs(gate_p), term))


def gate_linear_step(gate_p, plane, w_p):
    """gate += sel * w (the four Q_LC selectors)."""
    return _gate_add(gate_p, _mm(plane, FJ.unpack_limb_pairs(w_p)))


def gate_mul2_step(gate_p, plane, wa_p, wb_p):
    """gate += sel * (wa * wb) (the two Q_MUL selectors)."""
    unp = FJ.unpack_limb_pairs
    return _gate_add(gate_p, _mm(plane, _mm(unp(wa_p), unp(wb_p))))


def gate_pow5_step(gate_p, plane, w_p):
    """gate += sel * w^5 (the four Q_HASH selectors)."""
    return _gate_add(gate_p, _mm(plane, _pow5(FJ.unpack_limb_pairs(w_p))))


def gate_out_step(gate_p, plane, w_p):
    """gate -= sel * e (Q_O)."""
    return FJ.pack_limb_pairs(
        FJ.sub(FR, FJ.unpack_limb_pairs(gate_p),
               _mm(plane, FJ.unpack_limb_pairs(w_p))))


def gate_const_step(gate_p, plane):
    """gate += sel (Q_C)."""
    return _gate_add(gate_p, plane)


def gate_ecc_step(gate_p, plane, w0_p, w1_p, w2_p, w3_p, w4_p):
    """gate += sel * a*b*c*d*e (Q_ECC)."""
    unp = FJ.unpack_limb_pairs
    abcd = _mm(_mm(unp(w0_p), unp(w1_p)), _mm(unp(w2_p), unp(w3_p)))
    return _gate_add(gate_p, _mm(plane, _mm(abcd, unp(w4_p))))


def sigma_step(acc2_p, plane, w_p, beta, gamma):
    """acc2 *= (w + gamma + beta * sigma) — ONE program for all 5 sigmas.

    acc2 is INITIALIZED to the rolled z plane (z_next), so after the 5
    sigma steps it equals quotient_evals_core's full acc2 product."""
    unp = FJ.unpack_limb_pairs
    acc2 = unp(acc2_p)
    wj = unp(w_p)
    t = FJ.add(FR, wj, jnp.broadcast_to(gamma, wj.shape))
    f = FJ.add(FR, t, _mm(plane, jnp.broadcast_to(beta, plane.shape)))
    return FJ.pack_limb_pairs(_mm(acc2, f))


def quotient_combine_slice(wires_p, z_p, gate_p, acc2_p, ep_p,
                           zh_inv_p, shifted_inv_p, k, beta, gamma, alpha,
                           alpha_sq_div_n, j0, *, chunk):
    """Final combine on one lane slice: acc1 from the resident wires + ep
    table, then out = zh_inv*(gate + alpha*(acc1 - acc2)) + l1. Inputs
    packed (acc2 already includes the z_next factor); j0 traced so all
    slices share one program."""
    def cut(a):
        return lax.dynamic_slice_in_dim(a, j0, chunk, axis=a.ndim - 1)

    unp = FJ.unpack_limb_pairs
    z = unp(cut(z_p))
    gate = unp(cut(gate_p))
    acc2 = unp(cut(acc2_p))
    ep = unp(cut(ep_p))
    sh = unp(cut(shifted_inv_p))
    zh = unp(cut(zh_inv_p))
    shape = z.shape
    beta_b = jnp.broadcast_to(beta, shape)
    acc1 = z
    for j in range(5):
        wj = unp(cut(wires_p[j]))
        t = FJ.add(FR, wj, jnp.broadcast_to(gamma, shape))
        kj = jnp.broadcast_to(k[:, j], shape)
        acc1 = _mm(acc1, FJ.add(FR, t, _mm(_mm(kj, ep), beta_b)))
    perm = _mm(jnp.broadcast_to(alpha, shape), FJ.sub(FR, acc1, acc2))
    l1 = _mm(_mm(jnp.broadcast_to(alpha_sq_div_n, shape),
                 FJ.sub(FR, z, _one_like(z))), sh)
    return FJ.add(FR, _mm(zh, FJ.add(FR, gate, perm)), l1)


# --- polynomial utility kernels ---------------------------------------------

def poly_eval(poly, zc, chunk=256):
    """p(z) for (16, L) Montgomery coeffs and a (16, 1) Montgomery point.

    Block Horner: `chunk` sequential steps of (L/chunk)-lane fused
    multiply-adds, then a log-depth combine with powers of z^chunk."""
    L = poly.shape[1]
    lanes = -(-L // chunk)
    pad = lanes * chunk - L
    v = jnp.pad(poly, ((0, 0), (0, pad)))
    v = v.reshape(FR_LIMBS, lanes, chunk).transpose(2, 0, 1)  # (chunk,16,lanes)

    def horner(acc, coeff):
        return FJ.add(FR, _mm(acc, jnp.broadcast_to(zc, acc.shape)), coeff), None

    acc, _ = lax.scan(horner, jnp.zeros((FR_LIMBS, lanes), jnp.uint32),
                      v[::-1])
    # combine chunk evals: sum_j acc_j * (z^chunk)^j
    zk = fr_pow(zc, chunk)
    zk_rep = jnp.broadcast_to(zk, (FR_LIMBS, lanes))
    pw = jnp.concatenate([_one_like(acc[:, :1]), cumprod(zk_rep)[:, :lanes - 1]],
                         axis=1)
    terms = _mm(acc, pw)
    # log-tree sum over lanes
    k = lanes
    while k > 1:
        half = (k + 1) // 2
        hi = terms[:, half:k]
        lo = terms[:, :hi.shape[1]]
        summed = FJ.add(FR, lo, hi)
        terms = jnp.concatenate([summed, terms[:, hi.shape[1]:half]], axis=1)
        k = half
    return terms[:, :1]


def poly_eval_many(polys, zs):
    """Batched evaluation: (B, 16, L) polys at (B, 16, 1) points -> (16, B)
    CANONICAL-form limbs. One device program (and one host round-trip) for
    the prover's whole round 4 — per-call dispatch latency dominates
    scalar-result kernels on a tunneled device."""
    evals = jax.vmap(poly_eval)(polys, zs)  # (B, 16, 1)
    return FJ.from_mont(FR, evals[:, :, 0].transpose(1, 0))


def synthetic_divide(poly, zc):
    """Quotient of p(X)/(X - z) (remainder discarded) for a (16, 1)
    Montgomery point, device analog of poly.synthetic_divide:
    q_j = S_{j+1} * z^-(j+1) with S the suffix sums of c_t * z^t — two
    log-depth scans instead of an O(n) recurrence."""
    L = poly.shape[1]
    if L <= 1:
        return poly[:, :0]
    zinv = fr_pow(zc, R_MOD - 2)
    z_rep = jnp.broadcast_to(zc, (FR_LIMBS, L))
    pw = jnp.concatenate([_one_like(poly[:, :1]), cumprod(z_rep)[:, :L - 1]],
                         axis=1)  # z^t
    g = _mm(poly, pw)
    # suffix sums via the single-width add ladder (same remote-compile
    # rationale as cumprod: no multi-width associative_scan lowerings)
    s = FJ.cumsum_mont(FR, g, reverse=True)
    s_next = s[:, 1:]  # S_{j+1}, j = 0..L-2
    ipw = cumprod(jnp.broadcast_to(zinv, (FR_LIMBS, L - 1)))  # z^-(j+1)
    return _mm(s_next, ipw)


def lin_comb(stacked, coeffs):
    """sum_i coeff_i * p_i for (16, k, L) stacked Montgomery polys and
    (16, k, 1) Montgomery coefficients: one scanned multiply-add body."""
    def step(acc, x):
        p, cf = x
        return FJ.add(FR, acc, _mm(p, jnp.broadcast_to(cf, p.shape))), None

    xs = (stacked.transpose(1, 0, 2), coeffs.transpose(1, 0, 2))
    acc, _ = lax.scan(step, jnp.zeros_like(stacked[:, 0]), xs)
    return acc


def add_vanishing_blind(coeffs, b, n):
    """coeffs + blind(X)*(X^n - 1) for a small (16, d1) Montgomery blind:
    out has length n + d1; out[n+i] += b_i, out[i] -= b_i."""
    d1 = b.shape[1]
    ext = jnp.pad(coeffs, ((0, 0), (0, n + d1 - coeffs.shape[1])))
    head = FJ.sub(FR, ext[:, :d1], b)
    tail = FJ.add(FR, ext[:, n:n + d1], b)
    return jnp.concatenate([head, ext[:, d1:n], tail], axis=1)


def _all_zero(t):
    return jnp.all(t == 0)


_all_zero_jit = jax.jit(_all_zero)


def tail_is_zero(poly, degree):
    """True iff all coefficients above `degree` are zero (device reduce)."""
    return bool(_all_zero_jit(poly[:, degree + 1:]))


# --- module-level jitted entry points (stable wrappers => no retracing) ------

_from_mont_jit = jax.jit(partial(FJ.from_mont, FR))
_to_mont_jit = jax.jit(partial(FJ.to_mont, FR))
poly_eval_jit = jax.jit(poly_eval)
poly_eval_many_jit = jax.jit(poly_eval_many)
synthetic_divide_jit = jax.jit(synthetic_divide)
lin_comb_jit = jax.jit(lin_comb)
blind_jit = jax.jit(add_vanishing_blind, static_argnums=2)
quotient_evals_jit = jax.jit(quotient_evals, static_argnums=11)
gate_linear_step_jit = jax.jit(gate_linear_step)
gate_mul2_step_jit = jax.jit(gate_mul2_step)
gate_pow5_step_jit = jax.jit(gate_pow5_step)
gate_out_step_jit = jax.jit(gate_out_step)
gate_const_step_jit = jax.jit(gate_const_step)
gate_ecc_step_jit = jax.jit(gate_ecc_step)
sigma_step_jit = jax.jit(sigma_step)
quotient_combine_slice_jit = jax.jit(quotient_combine_slice,
                                     static_argnames=("chunk",))
domain_tables_jit = jax.jit(domain_tables, static_argnums=(0, 1, 2, 3))
pack_jit = jax.jit(FJ.pack_limb_pairs)
roll_jit = jax.jit(lambda v, r: jnp.roll(v, -r, axis=1), static_argnums=1)
perm_product_jit = jax.jit(perm_product)
