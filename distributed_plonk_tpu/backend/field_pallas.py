"""Pallas fused Montgomery multiplier: the whole SOS product in VMEM.

WHY (measured on a v5e through scripts/msm_ab.py + BASELINE.md round 4):
the XLA-level f32 mont_mul materializes its byte-product column tensor to
HBM (~18 KB per lane per multiply — a 2^18-lane call allocates 24 GB and
OOMs the chip), which makes every projective add ~12 x 18 KB of HBM
traffic. The measured MSM ceiling (~370-620k lane-adds/s regardless of
width) is exactly that traffic bound. This kernel keeps ALL intermediates
(byte rows, product columns, carry sweeps) in VMEM scratch: HBM traffic
per multiply drops to the operands + result (~300 B/lane), a ~60x cut.

HOW: one grid step processes a (n_limbs, LANE_TILE) block of each
operand. The schoolbook byte product is NOT an unrolled i x j loop
(2L x 2L = 2304 FMAs traced) but a BANDED accumulation — for each of the
2L bytes of `a`, one (2L, T)-shaped FMA adds a_i * b_bytes into the
column window [i, i + 2L) of a (4L, T) f32 scratch:

    for i in 0..2L-1:  t[i : i+2L, :] += a_byte[i] * b_bytes

f32 accumulation is exact: products <= 255^2, column sums <= 2L terms
=> < 2^22 < 2^24. The three SOS phases (t = a*b; m = t_lo * (-p^-1) mod R;
m*p) all use the same band loop — the constant products use Python-float
byte constants, costing a scalar*tensor FMA per band row. Carries run as
the same log-depth Kogge-Stone sweep as field_jax._carry_sweep, on VMEM
values. The algorithm is bit-identical to field_jax.mont_mul (same SOS
reduction; oracle-tested in tests/test_field_pallas.py, and statically
proven like the XLA paths: the field/*_mont_mul_pallas_* registry
entries interval-check the kernel jaxpr at the real lane tile AND
exactly evaluate the grid walk against the a*b*R^-1 mod p value
contract — both variants, both fields).

Select with DPT_FIELD_MUL=pallas (TPU; other platforms fall back to the
f32 XLA path automatically, and tests exercise the kernel via
interpret mode).
"""

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1

# lanes per grid step: f32 tiling wants multiples of (8, 128); 512 lanes
# keeps the (4L, T) f32 scratch at 96*512*4 = 196 KB for Fq — far under
# VMEM — while giving the VPU full rows. DPT_PALLAS_LANE_TILE widens the
# tile (fewer sequential grid steps at NTT widths — a 2^22-lane stage mul
# is 8192 steps at 512 — trading VMEM for per-step overhead).
LANE_TILE_DEFAULT = 512
LANE_TILE = int(os.environ.get("DPT_PALLAS_LANE_TILE",
                               str(LANE_TILE_DEFAULT)))


def lane_tile(n=None):
    """Per-call lane tile: the env/patched LANE_TILE attr wins, else the
    autotune plan's winner ("field", "lane_tile") near n lanes, else the
    built-in 512 (same precedence as ntt_pallas._vmem_mb). A plan value
    that is not a positive power of two falls back to the default — the
    tile divides the padded lane count and feeds BlockSpec shapes, so a
    malformed plan (e.g. 0) must never reach the kernel math."""
    from . import autotune

    t = int(autotune.attr_or_plan(
        LANE_TILE, LANE_TILE_DEFAULT, "DPT_PALLAS_LANE_TILE",
        "field", "lane_tile", n, cast=int))
    if t != LANE_TILE and (t < 1 or (t & (t - 1))):
        return LANE_TILE_DEFAULT
    return t


def _const_bytes(value, n_bytes):
    """Python int -> list of n_bytes byte values (little-endian)."""
    return [(value >> (8 * k)) & 0xFF for k in range(n_bytes)]


def _carry_sweep_val(cols, n_limbs):
    """Kogge-Stone carry propagation on an in-register (K, T) i32 value
    (entries any u32; see field_jax._carry_sweep for the bound argument).
    Returns (limbs (K, T) in [0, 2^16), carry_out (T,) i32)."""
    lo = cols & LIMB_MASK
    hi = jnp.right_shift(cols, LIMB_BITS)
    zero_row = jnp.zeros_like(hi[:1])
    s = lo + jnp.concatenate([zero_row, hi[:-1]], axis=0)

    def shift_down(x, k):
        return jnp.concatenate([jnp.zeros_like(x[:k]), x[:-k]], axis=0)

    # carry masks as 0/1 i32, not bool: Mosaic cannot concatenate i1
    # vector registers (shift_down is a concat)
    gen = (s > LIMB_MASK).astype(jnp.int32)
    prop = (s == LIMB_MASK).astype(jnp.int32)
    k = 1
    while k < n_limbs:
        gen = gen | (prop & shift_down(gen, k))
        prop = prop & shift_down(prop, k)
        k *= 2
    b_in = shift_down(gen, 1)
    limbs = (s + b_in) & LIMB_MASK
    # top-row extraction WITHOUT a row slice: x[-1] lowers via
    # dynamic_slice (unimplemented in the Mosaic TC pipeline), and a
    # static x[top] of row 23 gives the result an offset-7 vector layout
    # that poisons any later lane-concatenate (the fused add's group
    # stacking). A masked row reduction yields a clean-layout vector.
    top_mask = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                == s.shape[0] - 1).astype(jnp.int32)
    carry = jnp.sum((hi + gen) * top_mask, axis=0)
    return limbs, carry


def _to_bytes_f32(limbs):
    """(L, *t) i32 16-bit limbs -> (2L, *t) f32 byte rows (little-endian:
    row 2k = limb k low byte, row 2k+1 = high byte). Trailing-dims
    generic: the fused NTT kernel runs it on (L, rows, T) blocks, the 2D
    callers are unchanged."""
    L = limbs.shape[0]
    ev = (limbs & 0xFF).astype(jnp.float32)
    od = jnp.right_shift(limbs, 8).astype(jnp.float32)
    # interleave via stack + reshape on the major axis
    return jnp.stack([ev, od], axis=1).reshape((2 * L,) + limbs.shape[1:])


def _band_mul(t_ref, a_bytes, b_bytes):
    """Banded accumulation: out[k] = sum_{i+j=k} a_i * b_j, computed as
    2L shifted full-width (2L, T) FMAs accumulated IN PLACE into the
    (4L, T) f32 VMEM scratch t_ref (a concat- or .at[]-based functional
    accumulation copies the whole column buffer every iteration — 144
    buffer copies per product — and .at[].add's scatter lowering is
    rejected by pallas anyway). Returns the scratch value."""
    nb, T = a_bytes.shape
    t_ref[...] = jnp.zeros((2 * nb, T), jnp.float32)
    for i in range(nb):
        t_ref[i:i + nb] += a_bytes[i][None, :] * b_bytes
    return t_ref[...]


def _band_mul_const(t_ref, c_bytes, b_bytes):
    """Same in-place band accumulation with a compile-time constant
    multiplicand: out[k] = sum_{i+j=k} c_i * b_j, c_i Python scalars."""
    nb, T = b_bytes.shape
    t_ref[...] = jnp.zeros((2 * nb, T), jnp.float32)
    for i, c in enumerate(c_bytes):
        if c == 0:
            continue
        t_ref[i:i + nb] += np.float32(c) * b_bytes
    return t_ref[...]


def _cols_to_limbs(cols_f32):
    """(2K, *t) f32 byte columns -> (K, *t) i32 combined limb columns
    (ev + od*256, any u32 — fed to the carry sweep). Trailing-dims
    generic like _to_bytes_f32."""
    twoK = cols_f32.shape[0]
    v = cols_f32.reshape((twoK // 2, 2) + cols_f32.shape[1:])
    ev = v[:, 0].astype(jnp.int32)
    od = v[:, 1].astype(jnp.int32)
    return ev + jnp.left_shift(od, 8)


def _local_round(cols):
    """One base-256 local carry round on f32 digit columns (rows, T):
    each column keeps its low byte and pushes floor(col/256) one row up
    (the top row's carry-out is the CALLER's bound obligation). All
    arithmetic exact in f32 for columns < 2^24. Two rounds bring columns
    < 2^24 down to digits < 513; a third round to < 258."""
    hi = jnp.floor(cols * np.float32(1.0 / 256.0))
    dig = cols - hi * np.float32(256.0)
    shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    return dig + shifted


def _pairs_to_u32(cols_f32):
    """(2K, T) f32 digit columns -> (K, T) i32 rows ev + 256*od (entries
    < 2^31 for digit columns < 2^22 — fed to the exact carry sweep)."""
    twoK, T = cols_f32.shape
    v = cols_f32.reshape(twoK // 2, 2, T)
    return v[:, 0].astype(jnp.int32) + jnp.left_shift(
        v[:, 1].astype(jnp.int32), 8)


def _mont_mul_kernel_lazy(a_ref, b_ref, o_ref, t_ref, *, n_limbs,
                          mod_limbs, ninv_bytes, mod_bytes, negmod_limbs):
    """Lazy-carry Montgomery SOS: semi-normalized DIGIT columns flow
    between the three bands; exact Kogge-Stone sweeps only where a VALUE
    must be exact (the low-half carry-out and the final reduce) — 3
    sweeps instead of 5, and no byte re-conversions after the first.

    Soundness sketch (all f32 column values exact, < 2^24):
      - t = a*b band columns < 2L*255^2 < 2^22; two local rounds give
        digits < 513 with NO top-row loss (t < p^2 keeps the top column
        < 2^5). value(t) splits exactly at the R boundary.
      - m-band = ninv_bytes (<=255) x t_digits (<513): column sums
        < 2L*255*513 < 2^23 — exact; truncated at 2L columns the value
        is t*ninv mod R up to multiples of R, which divisibility by R
        tolerates. THREE local rounds bound m's digits < 258, so
        value(m') < 1.012*R and the final quotient stays < 1.52p — one
        conditional subtract reaches the canonical [0, p) result,
        BIT-IDENTICAL to the strict kernel.
      - mp-band = mod_bytes x m_digits (<258): sums < 2^22 — exact.
      - exact sweeps: low-half carry-out of t+m*p (pair-combined rows
        < 2^31), final reduce r1/r2 pair.
    """
    def m_band(t_dig2L):
        m_cols = _band_mul_const(t_ref, ninv_bytes, t_dig2L)[:2 * n_limbs]
        return _local_round(_local_round(_local_round(m_cols)))  # < 258

    def mp_band(m_dig):
        return _band_mul_const(t_ref, mod_bytes, m_dig)  # (4L, T), < 2^22

    _lazy_sos(a_ref, b_ref, o_ref, t_ref, n_limbs=n_limbs,
              negmod_limbs=negmod_limbs, t_rounds=2,
              m_band=m_band, mp_band=mp_band)


def _lazy_sos(a_ref, b_ref, o_ref, t_ref, *, n_limbs, negmod_limbs,
              t_rounds, m_band, mp_band):
    """Shared lazy-carry SOS skeleton: VPU a*b band -> t digit rounds ->
    m_band -> mp_band -> the exact finalize (low-half carry-out sweep +
    conditional subtract). The two kernel variants differ ONLY in how
    the constant bands run (VPU byte bands vs MXU Toeplitz matmuls) and
    in how many local rounds t needs before its band (the MXU band wants
    digits <= 256 for bf16 exactness; the VPU band tolerates < 513)."""
    L = n_limbs
    a = a_ref[...].astype(jnp.int32)
    b = b_ref[...].astype(jnp.int32)
    a_by = _to_bytes_f32(a)
    b_by = _to_bytes_f32(b)

    t_cols = _band_mul(t_ref, a_by, b_by)          # (4L, T) f32, < 2^22
    t_dig = t_cols                                 # exact split at R boundary
    for _ in range(t_rounds):
        t_dig = _local_round(t_dig)

    m_dig = m_band(t_dig[:2 * L])
    mp_cols = mp_band(m_dig)

    lo = _pairs_to_u32(t_dig[:2 * L] + mp_cols[:2 * L])
    _, c_low = _carry_sweep_val(lo, L)             # low half == 0 mod R

    hi = _pairs_to_u32(t_dig[2 * L:] + mp_cols[2 * L:])
    hi = hi + _row0_mask_i32(hi.shape) * c_low[None]
    negp = jnp.concatenate(
        [jnp.full((1, 1), int(v), jnp.int32) for v in negmod_limbs], axis=0)
    r1, _ = _carry_sweep_val(hi, L)
    r2, c2 = _carry_sweep_val(hi + negp, L)
    o_ref[...] = jnp.where((c2 != 0)[None], r2, r1).astype(jnp.uint32)


def _mont_mul_kernel_mxu(a_ref, b_ref, cn_ref, cp_ref, o_ref, t_ref, *,
                         n_limbs, mod_limbs, ninv_bytes, mod_bytes,
                         negmod_limbs):
    """Lazy-carry SOS with the two CONSTANT bands on the MXU.

    The m-band (ninv x t) and mp-band (p x m) are Toeplitz products by
    compile-time constants; as (out, 2L) @ (2L, T) bf16 matmuls with f32
    accumulation they run on the systolic array instead of burning 2/3 of
    the kernel's VPU FMAs (the measured round-5 multiplier ceiling —
    BASELINE.md round-6 roadmap #1a). Only the variable a x b band stays
    on the VPU (per-lane varying operands cannot share MXU weights).

    Exactness: bf16 has 8 significant bits, so integers <= 256 are exact.
    THREE local rounds after each accumulation bound digits <= 256:
      t band cols <= 2L*255^2 < 3.13e6 -> r1 <= 255+12192, r2 <= 303,
      r3 <= 256. The matmul products are <= 255*256 and every f32
      accumulator sum <= 2L*255*256 < 2^23 < 2^24 — exact. value(m') <=
      256*(R-1)/255 < 1.004*R, tighter than the VPU lazy kernel's 1.012*R
      bound, so the same single conditional subtract yields the canonical
      [0, p) result, BIT-IDENTICAL to the strict kernel.
    """
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    def m_band(t_dig2L):
        m_cols = dot(cn_ref[...], t_dig2L.astype(jnp.bfloat16))
        return _local_round(_local_round(_local_round(m_cols)))  # <= 256

    def mp_band(m_dig):
        return dot(cp_ref[...], m_dig.astype(jnp.bfloat16))  # (4L, T) < 2^23

    _lazy_sos(a_ref, b_ref, o_ref, t_ref, n_limbs=n_limbs,
              negmod_limbs=negmod_limbs, t_rounds=3,
              m_band=m_band, mp_band=mp_band)


def _row0_mask_i32(shape):
    """1 on row 0 else 0 (concat-free head-row adjustment — a row concat
    would give the result an offset vector layout; see curve_pallas)."""
    return (jax.lax.broadcasted_iota(jnp.int32, shape, 0) == 0).astype(
        jnp.int32)


def _mont_mul_kernel(a_ref, b_ref, o_ref, t_ref, *, n_limbs, mod_limbs,
                     ninv_bytes, mod_bytes, negmod_limbs):
    """One (n_limbs, LANE_TILE) block: full Montgomery SOS product.

    Mirrors field_jax.mont_mul phase for phase; all intermediates live in
    registers/VMEM (t_ref: one reused (4L, T) f32 column scratch)."""
    L = n_limbs
    a = a_ref[...].astype(jnp.int32)
    b = b_ref[...].astype(jnp.int32)

    a_by = _to_bytes_f32(a)            # (2L, T)
    b_by = _to_bytes_f32(b)

    # t = a * b: 4L byte columns -> 2L limb columns, carry the low half
    t_cols = _band_mul(t_ref, a_by, b_by)
    t_limbs = _cols_to_limbs(t_cols)   # (2L, T) i32
    t_lo, c_t = _carry_sweep_val(t_limbs[:L], L)

    # m = t_lo * (-p^-1) mod R (constant product, low half kept)
    tlo_by = _to_bytes_f32(t_lo)
    m_cols = _band_mul_const(t_ref, ninv_bytes, tlo_by)[:2 * L]
    m, _ = _carry_sweep_val(_cols_to_limbs(m_cols), L)

    # m * p (constant product, full width)
    m_by = _to_bytes_f32(m)
    mp_cols = _band_mul_const(t_ref, mod_bytes, m_by)
    mp_limbs = _cols_to_limbs(mp_cols)  # (2L, T)

    # low half of t + m*p is 0 mod R; only its carry-out survives
    _, c_low = _carry_sweep_val(t_lo + mp_limbs[:L], L)

    # high half: (t + m*p) / R, then one conditional subtract of p
    hi = t_limbs[L:] + mp_limbs[L:]
    hi = jnp.concatenate([hi[:1] + (c_t + c_low)[None], hi[1:]], axis=0)
    # 2^(16L) - p as a (L, 1) column built from inlined scalar constants
    # (pallas kernels cannot capture array constants)
    negp = jnp.concatenate(
        [jnp.full((1, 1), int(v), jnp.int32) for v in negmod_limbs], axis=0)
    r1, c1 = _carry_sweep_val(hi, L)
    r2, c2 = _carry_sweep_val(hi + negp, L)
    take2 = (c2 != 0)[None, :]
    o_ref[...] = jnp.where(take2, r2, r1).astype(jnp.uint32)


# Kernel variant (bit-identical outputs in every case):
#   DPT_MUL_MXU=1 -> lazy-carry with the constant bands as bf16 Toeplitz
#     matmuls on the MXU (opt-in: the chip A/B measured parity with the
#     lazy kernel within relay noise at the default tile — BASELINE.md);
#   DPT_MUL_LAZY=1 -> all-VPU lazy-carry (round-5 default: the chip A/B
#     mul_tile_ab_r05.json measured it ~13-14% over strict at every tile
#     width — Fr 17.6->15.2 ns, Fq 45.7->39.7 ns at tile 512);
#   else the strict kernel.
if os.environ.get("DPT_MUL_MXU", "0") != "0":
    _VARIANT = "mxu"
elif os.environ.get("DPT_MUL_LAZY", "1") != "0":
    _VARIANT = "lazy"
else:
    _VARIANT = "strict"

_KERNELS = {"mxu": _mont_mul_kernel_mxu, "lazy": _mont_mul_kernel_lazy,
            "strict": _mont_mul_kernel}


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _mont_mul_flat(spec_key, interpret, variant, tile, a, b):
    """(L, N) x (L, N) -> (L, N), N a multiple of `tile` (the resolved
    lane tile — a static jit arg, so plan-tuned and knob-tuned tiles
    compile distinct programs instead of sharing one)."""
    from .field_jax import FR, FQ

    spec = FR if spec_key == "fr" else FQ
    L = spec.n_limbs
    kernel = functools.partial(
        _KERNELS[variant], n_limbs=L,
        mod_limbs=tuple(int(x) for x in spec.mod_limbs),
        ninv_bytes=tuple(_const_bytes(int_from_limbs(spec.ninv_limbs), 2 * L)),
        mod_bytes=tuple(_const_bytes(int_from_limbs(spec.mod_limbs), 2 * L)),
        negmod_limbs=tuple(int(x) for x in spec.negmod_limbs),
    )
    from jax.experimental.pallas import tpu as pltpu

    n = a.shape[1]
    grid = n // tile
    scratch = [pltpu.VMEM((4 * L, tile), jnp.float32)]
    in_specs = [pl.BlockSpec((L, tile), lambda i: (0, i)),
                pl.BlockSpec((L, tile), lambda i: (0, i))]
    operands = [a, b]
    if variant == "mxu":
        # broadcast constant Toeplitz operands: same block every grid step
        cn = jnp.asarray(spec.ninv_toeplitz, jnp.bfloat16)
        cp = jnp.asarray(spec.mod_toeplitz, jnp.bfloat16)
        in_specs += [pl.BlockSpec(cn.shape, lambda i: (0, 0)),
                     pl.BlockSpec(cp.shape, lambda i: (0, 0))]
        operands += [cn, cp]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((L, n), jnp.uint32),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((L, tile), lambda i: (0, i)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)


def int_from_limbs(limbs):
    v = 0
    for i, x in enumerate(limbs):
        v |= int(x) << (LIMB_BITS * i)
    return v


def mont_mul(spec, a, b):
    """Drop-in replacement for field_jax.mont_mul (same semantics):
    broadcasts b against a, flattens batch dims to lanes, pads to the
    lane tile, dispatches the fused kernel."""
    interpret = jax.default_backend() != "tpu"
    L = spec.n_limbs
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    lanes = 1
    for d in shape[1:]:
        lanes *= d
    af = a.reshape(L, lanes)
    bf = b.reshape(L, lanes)
    tile = lane_tile(lanes)
    pad = (-lanes) % tile
    if pad:
        af = jnp.pad(af, ((0, 0), (0, pad)))
        bf = jnp.pad(bf, ((0, 0), (0, pad)))
    out = _mont_mul_flat(spec.name.lower(), interpret, _VARIANT, tile,
                         af, bf)
    if pad:
        out = out[:, :lanes]
    return out.reshape(shape)
