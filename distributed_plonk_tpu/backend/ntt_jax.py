"""Single-device radix-2 NTT/iNTT (+ coset variants) over Fr limb arrays.

Device replacement for `ark-poly`'s Radix2EvaluationDomain as the reference
workers use it (/root/reference/src/worker.rs:82-115): forward/inverse NTT
with optional coset pre/post scaling by the Fr multiplicative generator g=7.
Semantics are bit-identical to the host oracle in poly.py.

Design notes (TPU-first):
- One vectorized butterfly per stage: the whole stage is a single reshaped
  (16, blocks, 2, half) Montgomery multiply + add/sub, so the traced op
  count is O(log n), independent of n, and XLA sees large fusible
  elementwise ops that map onto the VPU.
- Twiddles are precomputed incremental tables in Montgomery form (the
  reference recomputes g.pow per element on the hot path,
  src/worker.rs:77-79,91-93 — a known inefficiency we do not copy).
- The iNTT 1/n scale and the inverse-coset g^-i scale are fused into one
  table multiply.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS, FR_MONT_R
from ..fields import fr_inv, fr_root_of_unity
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_ints


def _mont_table(xs):
    """Host ints -> (16, len) Montgomery-form limb table."""
    return ints_to_limbs([x * FR_MONT_R % R_MOD for x in xs], FR_LIMBS)


def _powers(base, count, start=1):
    out = [start % R_MOD]
    for _ in range(count - 1):
        out.append(out[-1] * base % R_MOD)
    return out


def batched_butterflies(v, perm, tables):
    """Radix-2 DIT butterflies on a batch of rows.

    v: (16, B, n) Montgomery limbs; perm: (n,) bit-reversal index;
    tables: per-stage (16, m) Montgomery twiddles. Shared by the
    single-device kernel and the mesh 4-step NTT's row/column stages.
    """
    n = v.shape[2]
    if n == 1:
        return v
    b = v.shape[1]
    v = v[:, :, perm]
    for tw in tables:
        m = tw.shape[1]
        blocks = n // (2 * m)
        v = v.reshape(FR_LIMBS, b, blocks, 2, m)
        u = v[:, :, :, 0, :]
        t = v[:, :, :, 1, :]
        t = FJ.mont_mul(FR, t, tw[:, None, None, :])
        v = jnp.stack([FJ.add(FR, u, t), FJ.sub(FR, u, t)], axis=3)
        v = v.reshape(FR_LIMBS, b, n)
    return v


class NttPlan:
    """Precomputed tables + cached jitted kernels for one domain size."""

    def __init__(self, n):
        assert n >= 1 and n & (n - 1) == 0
        self.n = n
        self.log_n = n.bit_length() - 1
        w = fr_root_of_unity(n)
        w_inv = fr_inv(w) if n > 1 else 1

        idx = np.arange(n, dtype=np.int64)
        rev = np.zeros(n, dtype=np.int64)
        for s in range(self.log_n):
            rev |= ((idx >> s) & 1) << (self.log_n - 1 - s)
        self.perm = rev.astype(np.int32)

        self.tw_fwd = []
        self.tw_inv = []
        m = 1
        while m < n:
            wm = pow(w, n // (2 * m), R_MOD)
            wmi = pow(w_inv, n // (2 * m), R_MOD)
            self.tw_fwd.append(_mont_table(_powers(wm, m)))
            self.tw_inv.append(_mont_table(_powers(wmi, m)))
            m <<= 1

        g = FR_GENERATOR
        n_inv = fr_inv(n % R_MOD)
        self.coset_tab = _mont_table(_powers(g, n))
        # fused iNTT scale: n^-1 * g^-i (coset) / n^-1 (plain)
        self.inv_coset_tab = _mont_table(_powers(fr_inv(g), n, start=n_inv))
        self.n_inv_tab = _mont_table([n_inv])
        self._fns = {}

    def kernel(self, inverse=False, coset=False, boundary="mont"):
        """Jitted (16, n) -> (16, n) kernel.

        boundary="mont": input/output in Montgomery form (device-resident
        pipelines). boundary="plain": canonical-form input/output (host
        round-trips); conversion is fused into the same XLA program.

        The O(n) tables (permutation, twiddles, coset scales) are passed as
        traced arguments, not baked-in constants, so compiled programs and
        persistent-cache entries stay small.
        """
        key = (inverse, coset, boundary)
        if key not in self._fns:
            n = self.n
            plain = boundary == "plain"
            consts = {
                "perm": jnp.asarray(self.perm),
                "tables": tuple(jnp.asarray(t) for t in
                                (self.tw_inv if inverse else self.tw_fwd)),
            }
            if coset and not inverse:
                consts["pre"] = jnp.asarray(self.coset_tab)
            if inverse:
                consts["post"] = jnp.asarray(
                    self.inv_coset_tab if coset else self.n_inv_tab)

            @jax.jit
            def fn(v, consts):
                if plain:
                    v = FJ.to_mont(FR, v)
                if "pre" in consts:
                    v = FJ.mont_mul(FR, v, consts["pre"])
                v = batched_butterflies(
                    v[:, None, :], consts["perm"], consts["tables"])[:, 0, :]
                if "post" in consts:
                    post = consts["post"]
                    if post.shape[1] == 1:  # plain 1/n: broadcast symbolically
                        post = jnp.broadcast_to(post, (FR_LIMBS, n))
                    v = FJ.mont_mul(FR, v, post)
                if plain:
                    v = FJ.from_mont(FR, v)
                return v

            self._fns[key] = (fn, consts)
        fn, consts = self._fns[key]
        return lambda v: fn(v, consts)

    # --- host-boundary convenience (int lists, zero-padded to n) -------------

    def run_ints(self, values, inverse=False, coset=False):
        assert len(values) <= self.n
        padded = list(values) + [0] * (self.n - len(values))
        v = jnp.asarray(ints_to_limbs(padded, FR_LIMBS))
        out = self.kernel(inverse, coset, boundary="plain")(v)
        return limbs_to_ints(np.asarray(out))


_PLANS = {}


def get_plan(n):
    if n not in _PLANS:
        _PLANS[n] = NttPlan(n)
    return _PLANS[n]
