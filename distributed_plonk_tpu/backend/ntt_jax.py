"""Single-device radix-2 NTT/iNTT (+ coset variants) over Fr limb arrays.

Device replacement for `ark-poly`'s Radix2EvaluationDomain as the reference
workers use it (/root/reference/src/worker.rs:82-115): forward/inverse NTT
with optional coset pre/post scaling by the Fr multiplicative generator g=7.
Semantics are bit-identical to the host oracle in poly.py.

Design notes (TPU-first):
- One vectorized butterfly per stage: the whole stage is a single reshaped
  (16, blocks, 2, half) Montgomery multiply + add/sub, so the traced op
  count is O(log n), independent of n, and XLA sees large fusible
  elementwise ops that map onto the VPU.
- Twiddles are precomputed incremental tables in Montgomery form (the
  reference recomputes g.pow per element on the hot path,
  src/worker.rs:77-79,91-93 — a known inefficiency we do not copy).
- The iNTT 1/n scale and the inverse-coset g^-i scale are fused into one
  table multiply.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS, FR_MONT_R
from ..fields import fr_inv, fr_root_of_unity
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_ints


def _mont_table(xs):
    """Host ints -> (16, len) Montgomery-form limb table."""
    return ints_to_limbs([x * FR_MONT_R % R_MOD for x in xs], FR_LIMBS)


def _powers(base, count, start=1):
    out = [start % R_MOD]
    for _ in range(count - 1):
        out.append(out[-1] * base % R_MOD)
    return out


class NttPlan:
    """Precomputed tables + cached jitted kernels for one domain size."""

    def __init__(self, n):
        assert n >= 1 and n & (n - 1) == 0
        self.n = n
        self.log_n = n.bit_length() - 1
        w = fr_root_of_unity(n)
        w_inv = fr_inv(w) if n > 1 else 1

        idx = np.arange(n, dtype=np.int64)
        rev = np.zeros(n, dtype=np.int64)
        for s in range(self.log_n):
            rev |= ((idx >> s) & 1) << (self.log_n - 1 - s)
        self.perm = rev.astype(np.int32)

        self.tw_fwd = []
        self.tw_inv = []
        m = 1
        while m < n:
            wm = pow(w, n // (2 * m), R_MOD)
            wmi = pow(w_inv, n // (2 * m), R_MOD)
            self.tw_fwd.append(_mont_table(_powers(wm, m)))
            self.tw_inv.append(_mont_table(_powers(wmi, m)))
            m <<= 1

        g = FR_GENERATOR
        n_inv = fr_inv(n % R_MOD)
        self.coset_tab = _mont_table(_powers(g, n))
        # fused iNTT scale: n^-1 * g^-i (coset) / n^-1 (plain)
        self.inv_coset_tab = _mont_table(_powers(fr_inv(g), n, start=n_inv))
        self.n_inv_tab = _mont_table([n_inv])
        self._fns = {}

    # --- core (Montgomery-form in/out) ---------------------------------------

    def _core(self, v, inverse, coset):
        n = self.n
        if n == 1:
            return v
        if coset and not inverse:
            v = FJ.mont_mul(FR, v, jnp.asarray(self.coset_tab))
        v = v[:, self.perm]
        tables = self.tw_inv if inverse else self.tw_fwd
        for tw in tables:
            m = tw.shape[1]
            blocks = n // (2 * m)
            v = v.reshape(FR_LIMBS, blocks, 2, m)
            u = v[:, :, 0, :]
            t = v[:, :, 1, :]
            twb = jnp.broadcast_to(jnp.asarray(tw)[:, None, :], t.shape)
            t = FJ.mont_mul(FR, t, twb)
            v = jnp.stack([FJ.add(FR, u, t), FJ.sub(FR, u, t)], axis=2)
            v = v.reshape(FR_LIMBS, n)
        if inverse:
            if coset:
                tab = jnp.asarray(self.inv_coset_tab)
            else:  # symbolic broadcast: only the 16-limb constant is embedded
                tab = jnp.broadcast_to(jnp.asarray(self.n_inv_tab), (FR_LIMBS, n))
            v = FJ.mont_mul(FR, v, tab)
        return v

    def kernel(self, inverse=False, coset=False, boundary="mont"):
        """Jitted (16, n) -> (16, n) kernel.

        boundary="mont": input/output in Montgomery form (device-resident
        pipelines). boundary="plain": canonical-form input/output (host
        round-trips); conversion is fused into the same XLA program.
        """
        key = (inverse, coset, boundary)
        if key not in self._fns:
            if boundary == "mont":
                fn = lambda v: self._core(v, inverse, coset)
            else:
                fn = lambda v: FJ.from_mont(
                    FR, self._core(FJ.to_mont(FR, v), inverse, coset))
            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    # --- host-boundary convenience (int lists, zero-padded to n) -------------

    def run_ints(self, values, inverse=False, coset=False):
        assert len(values) <= self.n
        padded = list(values) + [0] * (self.n - len(values))
        v = jnp.asarray(ints_to_limbs(padded, FR_LIMBS))
        out = self.kernel(inverse, coset, boundary="plain")(v)
        return limbs_to_ints(np.asarray(out))


_PLANS = {}


def get_plan(n):
    if n not in _PLANS:
        _PLANS[n] = NttPlan(n)
    return _PLANS[n]
