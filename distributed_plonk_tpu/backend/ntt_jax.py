"""Single-device radix-4/radix-2 NTT/iNTT (+ coset variants) over Fr limbs.

Device replacement for `ark-poly`'s Radix2EvaluationDomain as the reference
workers use it (/root/reference/src/worker.rs:82-115): forward/inverse NTT
with optional coset pre/post scaling by the Fr multiplicative generator g=7.
Semantics are bit-identical to the host oracle in poly.py.

Design notes (TPU-first):
- Constant-geometry (Pease) dataflow at BOTH radices: every stage is the
  same program — butterfly equally-spaced sub-arrays and interleave the
  outputs — so the middle stages run as ONE `lax.scan` body and the
  traced/compiled program size is O(1) in n (the round-1 version unrolled
  log2(n) distinct reshaped stages and paid tens of seconds of XLA compile
  per domain). Input is natural order; the output is bit-reversed and one
  gather restores natural order.
- DEFAULT core is RADIX-4 with FUSED twiddles (`DPT_NTT_RADIX`, 2|4): one
  radix-4 stage is the exact composition of two radix-2 stages —
    out[4p+2b+c] = x0 + (-1)^b A x2 + (-1)^c B_b (x1 + (-1)^b A x3),
  x_j = v[p + j*n/4], A = w^e(s,p), B_0 = w^(e/2), B_1 = w^(e/2 + n/4)
  (stage-s radix-2 exponent e(s,p) = bitrev_s(p mod 2^s) * 2^(k-1-s); the
  identities e(s, p+n/4) = e(s,p) and e(s+1, 2p+b) = e(s,p)/2 + b*n/4 hold
  for s <= k-2, which every fused pair satisfies). The radix-2 kernel pays
  log2(n) full HBM round trips plus a per-stage (16, n/2) twiddle gather
  and measured ~2% MFU against the field-mul roofline (BENCH_r05); radix-4
  HALVES the stage count (one fixup radix-2 stage when log2(n) is odd) and
  cuts per-two-stage twiddle gather volume from n to 3n/4 lanes at the
  same multiply/add count, because the fused-pair twiddles come from three
  precomputed exponent tables instead of being recombined on the fly.
- Scale fusion: the forward-coset pre-scale g^j folds into the FIRST
  radix-4 stage (the four quarters of the g^j table are exactly the four
  per-input scale tables, and the stage-0 twiddles are trivial: A = B = 1,
  C = w^(n/4)); the iNTT 1/n and inverse-coset g^-i scales ride the LAST
  stage's output pass, fused by XLA with the bit-reversal gather — no
  standalone O(n) table-multiply passes over HBM in any mode.
- The first/last stages are peeled out of the scan so their extra work
  (coset tables, output permutation + post-scale) fuses with the butterfly
  instead of forcing a scan-carry materialization; the peel count is
  constant, so compile size stays O(1) in n.
- Twiddles are looked up per stage from ONE Montgomery power table
  w^0..w^(n-1) via precomputed exponent matrices — the reference
  recomputes g.pow per element on the hot path
  (src/worker.rs:77-79,91-93 — a known inefficiency we do not copy).
- `run_stages`/`NttPlan.core_consts` are the shared stage-core API: the
  mesh 4-step NTT (parallel/ntt_mesh.py) and the fleet stage kernels
  (runtime/jax_stages.py) run the SAME butterflies as the single-device
  kernels, so a radix flip covers every path at once.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS, FR_MONT_R
from ..fields import fr_inv, fr_root_of_unity
from . import autotune
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_ints

# the values the resolvers below accept — the autotuner enumerates its
# candidate grid from these, so the measured space cannot drift from
# what the kernels dispatch on
RADIX_CHOICES = (2, 4)
KERNEL_CHOICES = ("pallas", "xla")


def _active_radix(radix=None, n=None):
    """Resolve the stage radix: explicit argument > DPT_NTT_RADIX (2|4)
    > the active autotune plan's winner near domain size n > 4. Read
    per call — not latched at import — so the radix-2 path stays
    selectable for parity debugging without rebuilding plans (mirrors
    msm_jax's DPT_BUCKET_UPDATE knob)."""
    if radix is None:
        env = os.environ.get("DPT_NTT_RADIX")
        if env is not None:
            radix = int(env)
        else:
            p = autotune.plan_param("ntt", "radix", n)
            try:
                radix = int(p)
            except (TypeError, ValueError):
                radix = 4
            if radix not in RADIX_CHOICES:
                # a malformed plan value falls back to the default —
                # only explicit knobs (arg/env, below) may raise
                radix = 4
    if radix not in RADIX_CHOICES:
        raise ValueError(f"NTT radix must be 2 or 4, got {radix!r}")
    return radix


# Stage-core kernel (DPT_NTT_KERNEL), mirroring DPT_MSM_KERNEL:
#   pallas: the fused multi-stage VMEM-resident kernel (ntt_pallas) —
#     log2(rows) butterfly stages per HBM round trip instead of the
#     radix-4 scan's two; coset pre-scale and inverse post-scales fused
#     into the first/last group.
#   xla: the radix-4/radix-2 lax.scan cores (the parity/debug reference,
#     exactly like DPT_MSM_KERNEL=xla keeps the bucket scan).
#   auto (default): pallas on TPU, xla elsewhere (CPU interpret-mode
#     pallas is test-only).
# field_jax.pallas_disabled() / mesh.pallas_guard override even a forced
# "pallas" — a pallas_call has no GSPMD partitioning rule, so sharded
# operands outside shard_map must never meet one.
_NTT_KERNEL = os.environ.get("DPT_NTT_KERNEL", "auto")


def _use_pallas_kernel(n=None):
    if getattr(FJ._pallas_off, "v", False):
        return False
    mode = _NTT_KERNEL
    if mode == "auto":
        # a plan winner resolves the auto default; an explicit (env or
        # test-patched) DPT_NTT_KERNEL above stays the override
        p = autotune.plan_param("ntt", "kernel", n)
        if p in KERNEL_CHOICES:
            mode = p
    if mode in KERNEL_CHOICES:
        return mode == "pallas"
    if mode != "auto":
        raise ValueError(
            f"DPT_NTT_KERNEL must be auto|pallas|xla, got {_NTT_KERNEL!r}")
    return jax.default_backend() == "tpu"


def _active_kernel(kernel=None, n=None):
    """Resolve the stage-core kernel: explicit argument > DPT_NTT_KERNEL
    > the active autotune plan near domain size n > platform default.
    Read per call like _active_radix; the pallas_disabled guard wins
    even over an explicit 'pallas' (same invariant as msm_jax)."""
    if kernel is not None:
        if kernel not in KERNEL_CHOICES:
            raise ValueError(
                f"NTT kernel must be 'pallas' or 'xla', got {kernel!r}")
        if kernel == "pallas" and getattr(FJ._pallas_off, "v", False):
            return "xla"
        return kernel
    return "pallas" if _use_pallas_kernel(n) else "xla"


def _mont_table(xs):
    """Host ints -> (16, len) Montgomery-form limb table."""
    return ints_to_limbs([x * FR_MONT_R % R_MOD for x in xs], FR_LIMBS)


def _powers(base, count, start=1):
    out = [start % R_MOD]
    for _ in range(count - 1):
        out.append(out[-1] * base % R_MOD)
    return out


def _bitrev_perm(n):
    log_n = n.bit_length() - 1
    idx = np.arange(n, dtype=np.int64)
    rev = np.zeros(n, dtype=np.int64)
    for s in range(log_n):
        rev |= ((idx >> s) & 1) << (log_n - 1 - s)
    return rev.astype(np.int32)


def _stage_exponents(n):
    """(log n, n/2) int32: exponent of w_n for radix-2 stage s, pair p —
    e(s, p) = bitrev_s(p mod 2^s) * 2^(k-1-s)."""
    k = n.bit_length() - 1
    p = np.arange(n // 2, dtype=np.int64)
    exps = np.zeros((max(k, 1), max(n // 2, 1)), dtype=np.int64)
    for s in range(k):
        low = p & ((1 << s) - 1)
        rev = np.zeros_like(low)
        for b in range(s):
            rev |= ((low >> b) & 1) << (s - 1 - b)
        exps[s] = rev << (k - 1 - s)
    return exps[:k, : n // 2].astype(np.int32)


# --- stage bodies (Montgomery, (16, B, n) rows) ------------------------------

def _stage2(v, e, pow_tab):
    """One constant-geometry radix-2 stage: butterfly the two halves and
    interleave. e: (n/2,) int32 twiddle exponents into pow_tab."""
    n = v.shape[2]
    half = n // 2
    u = v[:, :, :half]
    t = v[:, :, half:]
    tw = pow_tab[:, e]  # (16, n/2) gathered stage twiddles
    t = FJ.mont_mul(FR, t, tw[:, None, :])
    hi = FJ.add(FR, u, t)
    lo = FJ.sub(FR, u, t)
    return jnp.stack([hi, lo], axis=3).reshape(v.shape)


def _stage4(v, e, pow_tab):
    """One constant-geometry radix-4 stage (two fused radix-2 stages):
    butterfly the four quarters and interleave by 4. e: (3, n/4) int32
    exponent rows [A, B, C] into pow_tab (see module docstring)."""
    n = v.shape[2]
    q = n // 4
    x0 = v[:, :, :q]
    x1 = v[:, :, q:2 * q]
    x2 = v[:, :, 2 * q:3 * q]
    x3 = v[:, :, 3 * q:]
    A = pow_tab[:, e[0]][:, None, :]
    B = pow_tab[:, e[1]][:, None, :]
    C = pow_tab[:, e[2]][:, None, :]
    t2 = FJ.mont_mul(FR, x2, A)
    t3 = FJ.mont_mul(FR, x3, A)
    y0 = FJ.add(FR, x0, t2)
    y1 = FJ.sub(FR, x0, t2)
    z0 = FJ.add(FR, x1, t3)
    z1 = FJ.sub(FR, x1, t3)
    bz = FJ.mont_mul(FR, z0, B)
    cz = FJ.mont_mul(FR, z1, C)
    o0 = FJ.add(FR, y0, bz)
    o1 = FJ.sub(FR, y0, bz)
    o2 = FJ.add(FR, y1, cz)
    o3 = FJ.sub(FR, y1, cz)
    return jnp.stack([o0, o1, o2, o3], axis=3).reshape(v.shape)


def _stage4_first(v, pow_tab):
    """FIRST radix-4 stage, plain: the stage-0 twiddles are trivial
    (A = B = 1, C = w^(n/4)), so the stage is add/sub plus ONE broadcast
    multiply. Peeled out of the scan to skip 3 of the generic stage's 4
    table multiplies and all 3 twiddle gathers — bit-identical, because
    the skipped multiplies are by the Montgomery ONE."""
    n = v.shape[2]
    q = n // 4
    x0 = v[:, :, :q]
    x1 = v[:, :, q:2 * q]
    x2 = v[:, :, 2 * q:3 * q]
    x3 = v[:, :, 3 * q:]
    y0 = FJ.add(FR, x0, x2)
    y1 = FJ.sub(FR, x0, x2)
    z0 = FJ.add(FR, x1, x3)
    z1 = FJ.sub(FR, x1, x3)
    i4 = pow_tab[:, q][:, None, None]  # w^(n/4)
    cz = FJ.mont_mul(FR, z1, i4)
    o0 = FJ.add(FR, y0, z0)
    o1 = FJ.sub(FR, y0, z0)
    o2 = FJ.add(FR, y1, cz)
    o3 = FJ.sub(FR, y1, cz)
    return jnp.stack([o0, o1, o2, o3], axis=3).reshape(v.shape)


def _stage4_coset_first(v, coset_tab, pow_tab):
    """FIRST radix-4 stage with the forward-coset pre-scale g^j fused in.

    Stage-0 twiddles are trivial (A = B = 1, C = w^(n/4)), so the fused
    stage is four per-quarter table multiplies — the quarters of the g^j
    coset table ARE the fused tables, no new precompute — plus one
    broadcast multiply by w^(n/4): 5 multiplies per output group where
    the unfused path paid 6 (4 stage + 2 pre-scale per two outputs) AND a
    full standalone HBM pass for the pre-scale."""
    n = v.shape[2]
    q = n // 4
    x0 = FJ.mont_mul(FR, v[:, :, :q], coset_tab[:, None, :q])
    x1 = FJ.mont_mul(FR, v[:, :, q:2 * q], coset_tab[:, None, q:2 * q])
    t2 = FJ.mont_mul(FR, v[:, :, 2 * q:3 * q], coset_tab[:, None, 2 * q:3 * q])
    t3 = FJ.mont_mul(FR, v[:, :, 3 * q:], coset_tab[:, None, 3 * q:])
    y0 = FJ.add(FR, x0, t2)
    y1 = FJ.sub(FR, x0, t2)
    z0 = FJ.add(FR, x1, t3)
    z1 = FJ.sub(FR, x1, t3)
    i4 = pow_tab[:, q][:, None, None]  # w^(n/4)
    cz = FJ.mont_mul(FR, z1, i4)
    o0 = FJ.add(FR, y0, z0)
    o1 = FJ.sub(FR, y0, z0)
    o2 = FJ.add(FR, y1, cz)
    o3 = FJ.sub(FR, y1, cz)
    return jnp.stack([o0, o1, o2, o3], axis=3).reshape(v.shape)


def _radix4_core(v, consts, coset_pre=False):
    """All butterfly stages of the radix-4 kernel on (16, B, n) rows in
    natural order; output is in bit-reversed order (no perm, no 1/n).

    Static structure: [fused-coset | trivial-twiddle first stage] ->
    lax.scan over the middle radix-4 stages -> [peeled last radix-4
    stage | radix-2 fixup stage when log2(n) is odd]. The first stage is
    ALWAYS peeled (its twiddles are trivial, or carry the coset tables);
    the last butterfly always runs OUTSIDE the scan so the caller's
    output permutation (+ inverse scales) fuses with it instead of
    re-reading a materialized scan carry."""
    exps4 = consts["exps4"]
    pow_tab = consts["pow"]
    m4 = exps4.shape[0]
    odd = "fix_exps" in consts
    t0 = 0
    if coset_pre:
        v = _stage4_coset_first(v, consts["pre"], pow_tab)
        t0 = 1
    elif m4 >= 1:
        v = _stage4_first(v, pow_tab)
        t0 = 1
    last4 = (not odd) and m4 > t0
    hi = m4 - 1 if last4 else m4
    if hi > t0:
        def stage(carry, e):
            return _stage4(carry, e, pow_tab), None
        v, _ = lax.scan(stage, v, exps4[t0:hi])
    if last4:
        v = _stage4(v, exps4[m4 - 1], pow_tab)
    if odd:
        v = _stage2(v, consts["fix_exps"], pow_tab)
    return v


def _radix2_core(v, exps, pow_tab):
    """All radix-2 butterfly stages on (16, B, n) rows in natural order;
    output in bit-reversed order (no perm, no 1/n)."""
    n = v.shape[2]
    if n == 1:
        return v

    def stage(carry, e):
        return _stage2(carry, e, pow_tab), None

    v, _ = lax.scan(stage, v, exps)
    return v


def batched_butterflies(v, perm, exps, pow_tab):
    """Constant-geometry radix-2 NTT core on a batch of rows.

    v: (16, B, n) Montgomery limbs in NATURAL order; perm: (n,) bit-reversal
    gather applied at the OUTPUT; exps: (log n, n/2) int32 stage exponents;
    pow_tab: (16, n) Montgomery powers of the (inverse) root of unity.
    Returns the (i)NTT in natural order (1/n scaling NOT included).
    Kept as the radix-2 parity/debug core; prefer `run_stages` +
    `NttPlan.core_consts`, which pick the active radix."""
    return _radix2_core(v, exps, pow_tab)[:, :, perm]


def run_stages(v, consts):
    """Shared stage core: (16, B, n) natural-order Montgomery rows ->
    (i)NTT in natural order (1/n scaling NOT included). The kernel and
    radix are carried by the table set (`NttPlan.core_consts`): pallas
    tables hold "pg{g}s{t}" fused-stage twiddle blocks, radix-4 tables
    hold "exps4" (+ "fix_exps" for odd log2(n)), radix-2 tables hold
    "exps". Single-device kernels, the mesh 4-step NTT stages, and the
    fleet panel kernels all run their butterflies through this entry
    point, so one DPT_NTT_KERNEL / DPT_NTT_RADIX flip covers every path.
    The pallas dispatch re-checks the guard at trace time: inside
    pallas_disabled()/pallas_guard the XLA tables (always present) run
    instead — bit-identical either way."""
    if _use_pallas_kernel(v.shape[2]) and any(k.startswith("pg")
                                              for k in consts):
        from . import ntt_pallas
        return ntt_pallas.run_groups(v, consts)[:, :, consts["perm"]]
    if "exps4" in consts:
        return _radix4_core(v, consts)[:, :, consts["perm"]]
    return batched_butterflies(v, consts["perm"], consts["exps"],
                               consts["pow"])


class NttPlan:
    """Precomputed tables + cached jitted kernels for one domain size."""

    def __init__(self, n):
        assert n >= 1 and n & (n - 1) == 0
        self.n = n
        self.log_n = n.bit_length() - 1
        w = fr_root_of_unity(n)
        w_inv = fr_inv(w) if n > 1 else 1

        self.perm = _bitrev_perm(n)
        self.exps = _stage_exponents(n)
        self.pow_fwd = _mont_table(_powers(w, max(n, 1)))
        self.pow_inv = _mont_table(_powers(w_inv, max(n, 1)))

        # radix-4 fused-twiddle exponents, derived from the radix-2 rows:
        # stage t fuses radix-2 stages (2t, 2t+1); row [A, B, C] =
        # [e(2t, p), e(2t, p)/2, e(2t, p)/2 + n/4] for p < n/4 (module
        # docstring identities). Odd log2(n) leaves radix-2 stage k-1 as
        # the fixup row.
        k = self.log_n
        if k >= 2:
            q = n // 4
            eA = self.exps[0:(k // 2) * 2:2, :q].astype(np.int64)
            self.exps4 = np.stack(
                [eA, eA >> 1, (eA >> 1) + q], axis=1).astype(np.int32)
            self.fix_exps = self.exps[k - 1] if k % 2 else None
        else:  # n <= 2: no radix-4 stage exists; kernels fall back to radix-2
            self.exps4 = None
            self.fix_exps = None

        g = FR_GENERATOR
        n_inv = fr_inv(n % R_MOD)
        self.coset_tab = _mont_table(_powers(g, n))
        # fused iNTT scale: n^-1 * g^-i (coset) / n^-1 (plain)
        self.inv_coset_tab = _mont_table(_powers(fr_inv(g), n, start=n_inv))
        self.n_inv_tab = _mont_table([n_inv])
        self._fns = {}
        self._pallas_tabs = {}

    def _effective_radix(self, radix=None):
        """Active radix for this plan: n <= 2 has no radix-4 stage, so the
        radix-2 body covers it (bit-identical either way)."""
        radix = _active_radix(radix, n=self.n)
        return radix if self.exps4 is not None else 2

    def _effective_kernel(self, kernel=None):
        """Active stage-core kernel for this plan: n <= 2 has no fused
        group schedule, so the XLA body covers it (like radix)."""
        if self.log_n < 2:
            return "xla"
        return _active_kernel(kernel, n=self.n)

    def _pallas_consts(self, inverse):
        """Fused-group twiddle VALUE tables (host numpy, cached per
        schedule — the schedule moves with the VMEM/group-cap knobs)."""
        from . import ntt_pallas

        schedule = ntt_pallas.plan_schedule(self.log_n)
        # revision-keyed like _fns: a plan reload may move the schedule
        # knobs, and stale twiddle blocks must not outlive it
        key = autotune.cache_key(inverse, schedule)
        if key not in self._pallas_tabs:
            pow_tab = self.pow_inv if inverse else self.pow_fwd
            self._pallas_tabs[key] = ntt_pallas.group_tables(
                self.log_n, self.exps, pow_tab, schedule)
        return self._pallas_tabs[key]

    def core_consts(self, inverse=False, radix=None, kernel=None):
        """HOST (numpy) table set for `run_stages` at the active radix
        and kernel. Callers (mesh shard_map consts, fleet panel kernels)
        place these on device / build PartitionSpecs per entry; every
        entry is replicated-safe (O(n) tables, no per-shard content).
        Under the pallas kernel the fused-stage twiddle blocks ride
        ALONGSIDE the XLA tables — run_stages falls back to the XLA body
        whenever the guard disables pallas at trace time."""
        pow_tab = self.pow_inv if inverse else self.pow_fwd
        if self._effective_radix(radix) == 4:
            out = {"perm": self.perm, "exps4": self.exps4, "pow": pow_tab}
            if self.fix_exps is not None:
                out["fix_exps"] = self.fix_exps
        else:
            out = {"perm": self.perm, "exps": self.exps, "pow": pow_tab}
        if self._effective_kernel(kernel) == "pallas":
            out.update(self._pallas_consts(inverse))
        return out

    def _pallas_post_tab(self, coset):
        """Inverse scales reordered for pre-permutation application in
        the LAST fused group: s = post[perm] (bit reversal is an
        involution), laid out (16, rows_last, M_last) to match the
        kernel's in-VMEM block orientation."""
        from . import ntt_pallas

        schedule = ntt_pallas.plan_schedule(self.log_n)
        rows = 1 << schedule[-1][1]
        m_cols = self.n // rows
        post = (self.inv_coset_tab if coset
                else np.broadcast_to(self.n_inv_tab, (FR_LIMBS, self.n)))
        s = post[:, self.perm]
        return np.ascontiguousarray(
            s.reshape(FR_LIMBS, m_cols, rows).swapaxes(1, 2))

    def _kernel_consts(self, inverse, coset, radix, kernel="xla"):
        """Traced-argument tables for one compiled kernel variant."""
        consts = {k: jnp.asarray(v)
                  for k, v in self.core_consts(inverse, radix,
                                               kernel=kernel).items()}
        if coset and not inverse:
            consts["pre"] = jnp.asarray(self.coset_tab)
            if kernel == "pallas":
                # the pallas first group consumes the SAME coset table,
                # viewed (16, rows, M) — a reshape, not a new precompute
                consts["ppre"] = consts["pre"]
        if inverse:
            consts["post"] = jnp.asarray(
                self.inv_coset_tab if coset else self.n_inv_tab)
            if kernel == "pallas":
                consts["ppost"] = jnp.asarray(self._pallas_post_tab(coset))
        return consts

    def _apply_batched(self, v, consts, radix, kernel="xla",
                       defer_perm=False):
        """(16, B, n) Montgomery rows -> full (i)(coset)NTT: butterflies +
        output permutation + fused scales, radix/kernel-selected. The
        pallas path runs the fused multi-stage groups (coset pre-scale in
        the first group, inverse scales in the last) and finishes with
        the bit-reversal gather; the radix-4 path peels the first/last
        stages so the coset tables ride the first butterfly and the perm
        gather + inverse scales fuse with the last one; the radix-2 path
        keeps the historical standalone pre/post table multiplies
        (parity/debug reference).

        defer_perm=True (forward launches only) SKIPS the output
        bit-reversal gather: the result stays in constant-geometry
        (bit-reversed) order and the CONSUMER absorbs the permutation —
        the round-3 pipeline keeps every accumulator plane bit-reversed
        and pays one gather at the consuming iNTT's input instead of one
        standalone O(n) pass per FFT launch (DPT_R3_BITREV)."""
        n = self.n
        if kernel == "pallas" and _active_kernel("pallas") == "pallas":
            from . import ntt_pallas
            v = ntt_pallas.run_groups(v, consts)
            return v if defer_perm else v[:, :, consts["perm"]]
        if radix == 4:
            v = _radix4_core(v, consts, coset_pre="pre" in consts)
        else:
            if "pre" in consts:
                v = FJ.mont_mul(FR, v, consts["pre"][:, None, :])
            v = _radix2_core(v, consts["exps"], consts["pow"])
        if not defer_perm:
            v = v[:, :, consts["perm"]]
        if "post" in consts:
            assert not defer_perm, "defer_perm is forward-only (no post)"
            post = consts["post"]
            if post.shape[1] == 1:  # plain 1/n: broadcast symbolically
                post = jnp.broadcast_to(post, (FR_LIMBS, n))
            v = FJ.mont_mul(FR, v, post[:, None, :])
        return v

    def kernel(self, inverse=False, coset=False, boundary="mont", radix=None,
               kernel=None):
        """Jitted (16, n) -> (16, n) kernel.

        boundary="mont": input/output in Montgomery form (device-resident
        pipelines). boundary="plain": canonical-form input/output (host
        round-trips); conversion is fused into the same XLA program.

        The O(n) tables (permutation, exponents, power table, coset scales,
        fused-stage twiddle blocks) are passed as traced arguments, not
        baked-in constants, so compiled programs and persistent-cache
        entries stay small. `kernel` overrides DPT_NTT_KERNEL like `radix`
        overrides DPT_NTT_RADIX; the memo is keyed on the resolved mode
        plus the autotune plan revision (autotune.cache_key), so a
        mid-process plan reload can never serve a stale compiled
        variant.
        """
        radix = self._effective_radix(radix)
        kmode = self._effective_kernel(kernel)
        key = autotune.cache_key(inverse, coset, boundary, radix, kmode)
        if key not in self._fns:
            plain = boundary == "plain"
            consts = self._kernel_consts(inverse, coset, radix, kmode)

            @jax.jit
            def fn(v, consts):
                if plain:
                    v = FJ.to_mont(FR, v)
                v = self._apply_batched(v[:, None, :], consts, radix,
                                        kmode)[:, 0, :]
                if plain:
                    v = FJ.from_mont(FR, v)
                return v

            self._fns[key] = (fn, consts)
        fn, consts = self._fns[key]
        return lambda v: fn(v, consts)

    def kernel_batch(self, inverse=False, coset=False, radix=None,
                     kernel=None, defer_perm=False):
        """Jitted (16, B, n) -> (16, B, n) Montgomery-boundary kernel: B
        polynomials in ONE launch (the prover's round-1/round-3 NTT batches;
        the reference fans these out as concurrent RPCs,
        dispatcher2.rs:294-321,382-414 — on device they are one program).
        Compiled once per (mode, radix, kernel, B). defer_perm=True emits
        the result in bit-reversed order (forward only — the consumer
        absorbs the permutation; see _apply_batched)."""
        radix = self._effective_radix(radix)
        kmode = self._effective_kernel(kernel)
        if defer_perm and inverse:
            raise ValueError("defer_perm is forward-only")
        key = autotune.cache_key(
            inverse, coset, "batch_noperm" if defer_perm else "batch",
            radix, kmode)
        if key not in self._fns:
            consts = self._kernel_consts(inverse, coset, radix, kmode)

            @jax.jit
            def fn(v, consts):
                return self._apply_batched(v, consts, radix, kmode,
                                           defer_perm=defer_perm)

            self._fns[key] = (fn, consts)
        fn, consts = self._fns[key]
        return lambda v: fn(v, consts)

    def kernel_fused(self, inverse=False, coset=False, *, key,
                     prologue=None, epilogue=None, radix=None, kernel=None,
                     input_perm=False, defer_perm=False):
        """Jitted Montgomery-boundary batch kernel with caller-supplied
        pointwise stages fused into the SAME program:

            prologue(*pro_args) -> (16, B, n)  [optional]
            -> (i)(coset)NTT batch
            -> epilogue(result, *epi_args)     [optional]

        This is how round 3 loses its standalone O(n) passes: the gate /
        sigma quotient products run as the epilogue of the selector and
        sigma coset-FFT launches (XLA fuses them with the final stage /
        output permutation, so the (16, B, m) planes never round-trip
        HBM), and the quotient combine runs as the prologue of the coset
        iNTT (fusing into the first inverse stage's reads). `key` must
        uniquely identify the prologue/epilogue semantics — the traced
        closure is memoized under (key, mode) exactly like the plain
        kernels. Returns fn(pro_args, epi_args=()).

        Bit-reversal deferral (DPT_R3_BITREV): defer_perm=True leaves a
        FORWARD launch's output (and so the epilogue's input) in
        constant-geometry order — valid because the epilogues are pure
        pointwise folds, so they hold in any order the operands share.
        input_perm=True gathers the prologue's output through the
        bit-reversal permutation before the butterflies — the one place
        the deferred order returns to natural, fused into the consuming
        iNTT program's first stage reads instead of a standalone pass
        per producer launch."""
        radix = self._effective_radix(radix)
        kmode = self._effective_kernel(kernel)
        ck = autotune.cache_key("fused", key, inverse, coset, radix, kmode,
                                input_perm, defer_perm)
        if ck not in self._fns:
            consts = self._kernel_consts(inverse, coset, radix, kmode)

            @jax.jit
            def fn(pro_args, epi_args, consts):
                v = prologue(*pro_args) if prologue is not None \
                    else pro_args[0]
                if input_perm:
                    v = v[:, :, consts["perm"]]
                v = self._apply_batched(v, consts, radix, kmode,
                                        defer_perm=defer_perm)
                if epilogue is not None:
                    return epilogue(v, *epi_args)
                return v

            # `key` contractually identifies the prologue/epilogue
            # semantics (docstring) — callers rebuild structurally
            # identical closures per key; folding closure ids into the
            # key would retrace every prove for nothing
            self._fns[ck] = (fn, consts)  # analysis: ok(key identifies prologue/epilogue by contract)
        fn, consts = self._fns[ck]
        return lambda pro_args, epi_args=(): fn(tuple(pro_args),
                                                tuple(epi_args), consts)

    def traced_kernel(self, inverse=False, coset=False, boundary="mont",
                      radix=None, batch=False, kernel=None,
                      defer_perm=False):
        """(jitted fn, consts dict) for one kernel variant — the raw
        pair behind `kernel`/`kernel_batch`'s memo. The static verifier
        (analysis/registry.py) traces `fn(v, consts)` through
        jax.make_jaxpr to interval-check the whole stage pipeline
        (including the pallas_call kernel jaxprs under kernel="pallas");
        AOT tooling can reuse it for explicit lower()/compile() too."""
        radix = self._effective_radix(radix)
        kmode = self._effective_kernel(kernel)
        if batch:
            if boundary != "mont":
                raise ValueError(
                    "batch kernels are Montgomery-boundary only")
            self.kernel_batch(inverse, coset, radix=radix, kernel=kmode,
                              defer_perm=defer_perm)
            key = autotune.cache_key(
                inverse, coset, "batch_noperm" if defer_perm else "batch",
                radix, kmode)
        elif defer_perm:
            raise ValueError("defer_perm needs batch=True")
        else:
            self.kernel(inverse, coset, boundary=boundary, radix=radix,
                        kernel=kmode)
            key = autotune.cache_key(inverse, coset, boundary, radix, kmode)
        return self._fns[key]

    def aot_compile(self, batch_sizes=(), boundaries=("mont", "plain"),
                    radix=None, kernel=None):
        """Ahead-of-time lower + compile every (inverse, coset) kernel
        variant for this domain at the ACTIVE radix and kernel mode, plus
        `kernel_batch` at the given batch widths, WITHOUT running anything
        — `jit.lower(shapes).compile()` on ShapeDtypeStructs.

        The executables land in the persistent compilation cache
        (field_jax.configure_compile_cache), which is the point: a warmup
        process can pre-bake a store-owned cache so every later server
        start compiles nothing for this shape. Mode-aware like
        MsmContext.aot_compile: under DPT_NTT_KERNEL=pallas the lowered
        programs ARE the fused multi-stage Mosaic kernels, so
        `warm_stages` / `scripts/warmup.py --aot` pre-bake those too.
        Returns {"compiled": k, "failed": j, "radix": r, "kernel": mode}.
        """
        radix = self._effective_radix(radix)
        kmode = self._effective_kernel(kernel)
        compiled = failed = 0
        v_spec = jax.ShapeDtypeStruct((FR_LIMBS, self.n), jnp.uint32)

        def aot(fn, consts, spec):
            nonlocal compiled, failed
            cspec = {k: jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for k, a in consts.items()}
            try:
                fn.lower(spec, cspec).compile()
                compiled += 1
            except Exception:  # pragma: no cover - older jax without AOT
                failed += 1

        for inverse in (False, True):
            for coset in (False, True):
                for boundary in boundaries:
                    self.kernel(inverse, coset, boundary=boundary,
                                radix=radix, kernel=kmode)
                    fn, consts = self._fns[autotune.cache_key(
                        inverse, coset, boundary, radix, kmode)]
                    aot(fn, consts, v_spec)
                for b in batch_sizes:
                    self.kernel_batch(inverse, coset, radix=radix,
                                      kernel=kmode)
                    fn, consts = self._fns[autotune.cache_key(
                        inverse, coset, "batch", radix, kmode)]
                    aot(fn, consts,
                        jax.ShapeDtypeStruct((FR_LIMBS, b, self.n),
                                             jnp.uint32))
        return {"compiled": compiled, "failed": failed, "radix": radix,
                "kernel": kmode}

    # --- host-boundary convenience (int lists, zero-padded to n) -------------

    def run_ints(self, values, inverse=False, coset=False, radix=None,
                 kernel=None):
        assert len(values) <= self.n
        padded = list(values) + [0] * (self.n - len(values))
        v = jnp.asarray(ints_to_limbs(padded, FR_LIMBS))
        out = self.kernel(inverse, coset, boundary="plain", radix=radix,
                          kernel=kernel)(v)
        return limbs_to_ints(np.asarray(out))


_PLANS = {}


def get_plan(n):
    if n not in _PLANS:
        _PLANS[n] = NttPlan(n)
    return _PLANS[n]
