"""Single-device radix-2 NTT/iNTT (+ coset variants) over Fr limb arrays.

Device replacement for `ark-poly`'s Radix2EvaluationDomain as the reference
workers use it (/root/reference/src/worker.rs:82-115): forward/inverse NTT
with optional coset pre/post scaling by the Fr multiplicative generator g=7.
Semantics are bit-identical to the host oracle in poly.py.

Design notes (TPU-first):
- Constant-geometry (Pease) dataflow: EVERY stage is the same program —
  butterfly the two array halves (i, i+n/2) and interleave the outputs —
  so all log2(n) stages run as ONE `lax.scan` body and the traced/compiled
  program size is O(1) in n (the round-1 version unrolled log2(n) distinct
  reshaped stages and paid tens of seconds of XLA compile per domain).
  Input is natural order; one bit-reversal gather at the output.
  Stage-s twiddle for pair p is w^e with e = bitrev_s(p mod 2^s)·2^(k-1-s),
  verified bit-identical to the oracle's iterative DIT for all modes.
- Twiddles are looked up per stage from ONE Montgomery power table
  w^0..w^(n-1) via a precomputed (log n, n/2) exponent matrix — the
  reference recomputes g.pow per element on the hot path
  (src/worker.rs:77-79,91-93 — a known inefficiency we do not copy).
- The iNTT 1/n scale and the inverse-coset g^-i scale are fused into one
  table multiply.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS, FR_MONT_R
from ..fields import fr_inv, fr_root_of_unity
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_ints


def _mont_table(xs):
    """Host ints -> (16, len) Montgomery-form limb table."""
    return ints_to_limbs([x * FR_MONT_R % R_MOD for x in xs], FR_LIMBS)


def _powers(base, count, start=1):
    out = [start % R_MOD]
    for _ in range(count - 1):
        out.append(out[-1] * base % R_MOD)
    return out


def _bitrev_perm(n):
    log_n = n.bit_length() - 1
    idx = np.arange(n, dtype=np.int64)
    rev = np.zeros(n, dtype=np.int64)
    for s in range(log_n):
        rev |= ((idx >> s) & 1) << (log_n - 1 - s)
    return rev.astype(np.int32)


def _stage_exponents(n):
    """(log n, n/2) int32: exponent of w_n for stage s, pair p —
    e(s, p) = bitrev_s(p mod 2^s) * 2^(k-1-s)."""
    k = n.bit_length() - 1
    p = np.arange(n // 2, dtype=np.int64)
    exps = np.zeros((max(k, 1), max(n // 2, 1)), dtype=np.int64)
    for s in range(k):
        low = p & ((1 << s) - 1)
        rev = np.zeros_like(low)
        for b in range(s):
            rev |= ((low >> b) & 1) << (s - 1 - b)
        exps[s] = rev << (k - 1 - s)
    return exps[:k, : n // 2].astype(np.int32)


def batched_butterflies(v, perm, exps, pow_tab):
    """Constant-geometry radix-2 NTT core on a batch of rows.

    v: (16, B, n) Montgomery limbs in NATURAL order; perm: (n,) bit-reversal
    gather applied at the OUTPUT; exps: (log n, n/2) int32 stage exponents;
    pow_tab: (16, n) Montgomery powers of the (inverse) root of unity.
    Returns the (i)NTT in natural order (1/n scaling NOT included).
    Shared by the single-device kernel and the mesh 4-step NTT stages.
    """
    n = v.shape[2]
    if n == 1:
        return v
    b = v.shape[1]
    half = n // 2

    def stage(carry, e):
        u = carry[:, :, :half]
        t = carry[:, :, half:]
        tw = pow_tab[:, e]  # (16, n/2) gathered stage twiddles
        t = FJ.mont_mul(FR, t, tw[:, None, :])
        hi = FJ.add(FR, u, t)
        lo = FJ.sub(FR, u, t)
        out = jnp.stack([hi, lo], axis=3)  # interleave: out[2p], out[2p+1]
        return out.reshape(FR_LIMBS, b, n), None

    v, _ = lax.scan(stage, v, exps)
    return v[:, :, perm]


class NttPlan:
    """Precomputed tables + cached jitted kernels for one domain size."""

    def __init__(self, n):
        assert n >= 1 and n & (n - 1) == 0
        self.n = n
        self.log_n = n.bit_length() - 1
        w = fr_root_of_unity(n)
        w_inv = fr_inv(w) if n > 1 else 1

        self.perm = _bitrev_perm(n)
        self.exps = _stage_exponents(n)
        self.pow_fwd = _mont_table(_powers(w, max(n, 1)))
        self.pow_inv = _mont_table(_powers(w_inv, max(n, 1)))

        g = FR_GENERATOR
        n_inv = fr_inv(n % R_MOD)
        self.coset_tab = _mont_table(_powers(g, n))
        # fused iNTT scale: n^-1 * g^-i (coset) / n^-1 (plain)
        self.inv_coset_tab = _mont_table(_powers(fr_inv(g), n, start=n_inv))
        self.n_inv_tab = _mont_table([n_inv])
        self._fns = {}

    def kernel(self, inverse=False, coset=False, boundary="mont"):
        """Jitted (16, n) -> (16, n) kernel.

        boundary="mont": input/output in Montgomery form (device-resident
        pipelines). boundary="plain": canonical-form input/output (host
        round-trips); conversion is fused into the same XLA program.

        The O(n) tables (permutation, exponents, power table, coset scales)
        are passed as traced arguments, not baked-in constants, so compiled
        programs and persistent-cache entries stay small.
        """
        key = (inverse, coset, boundary)
        if key not in self._fns:
            n = self.n
            plain = boundary == "plain"
            consts = {
                "perm": jnp.asarray(self.perm),
                "exps": jnp.asarray(self.exps),
                "pow": jnp.asarray(self.pow_inv if inverse else self.pow_fwd),
            }
            if coset and not inverse:
                consts["pre"] = jnp.asarray(self.coset_tab)
            if inverse:
                consts["post"] = jnp.asarray(
                    self.inv_coset_tab if coset else self.n_inv_tab)

            @jax.jit
            def fn(v, consts):
                if plain:
                    v = FJ.to_mont(FR, v)
                if "pre" in consts:
                    v = FJ.mont_mul(FR, v, consts["pre"])
                v = batched_butterflies(
                    v[:, None, :], consts["perm"], consts["exps"],
                    consts["pow"])[:, 0, :]
                if "post" in consts:
                    post = consts["post"]
                    if post.shape[1] == 1:  # plain 1/n: broadcast symbolically
                        post = jnp.broadcast_to(post, (FR_LIMBS, n))
                    v = FJ.mont_mul(FR, v, post)
                if plain:
                    v = FJ.from_mont(FR, v)
                return v

            self._fns[key] = (fn, consts)
        fn, consts = self._fns[key]
        return lambda v: fn(v, consts)

    def kernel_batch(self, inverse=False, coset=False):
        """Jitted (16, B, n) -> (16, B, n) Montgomery-boundary kernel: B
        polynomials in ONE launch (the prover's round-1/round-3 NTT batches;
        the reference fans these out as concurrent RPCs,
        dispatcher2.rs:294-321,382-414 — on device they are one program).
        Compiled once per (mode, B)."""
        key = (inverse, coset, "batch")
        if key not in self._fns:
            n = self.n
            consts = {
                "perm": jnp.asarray(self.perm),
                "exps": jnp.asarray(self.exps),
                "pow": jnp.asarray(self.pow_inv if inverse else self.pow_fwd),
            }
            if coset and not inverse:
                consts["pre"] = jnp.asarray(self.coset_tab)
            if inverse:
                consts["post"] = jnp.asarray(
                    self.inv_coset_tab if coset else self.n_inv_tab)

            @jax.jit
            def fn(v, consts):
                if "pre" in consts:
                    v = FJ.mont_mul(FR, v, consts["pre"][:, None, :])
                v = batched_butterflies(
                    v, consts["perm"], consts["exps"], consts["pow"])
                if "post" in consts:
                    post = consts["post"]
                    if post.shape[1] == 1:  # plain 1/n: broadcast symbolically
                        post = jnp.broadcast_to(post, (FR_LIMBS, n))
                    v = FJ.mont_mul(FR, v, post[:, None, :])
                return v

            self._fns[key] = (fn, consts)
        fn, consts = self._fns[key]
        return lambda v: fn(v, consts)

    def aot_compile(self, batch_sizes=(), boundaries=("mont", "plain")):
        """Ahead-of-time lower + compile every (inverse, coset) kernel
        variant for this domain, plus `kernel_batch` at the given batch
        widths, WITHOUT running anything — `jit.lower(shapes).compile()`
        on ShapeDtypeStructs.

        The executables land in the persistent compilation cache
        (field_jax.configure_compile_cache), which is the point: a warmup
        process can pre-bake a store-owned cache so every later server
        start compiles nothing for this shape. The in-process jit dispatch
        still traces on first real call, but its compile is then a disk
        hit, not an XLA run. Returns {"compiled": k, "failed": j}."""
        compiled = failed = 0
        v_spec = jax.ShapeDtypeStruct((FR_LIMBS, self.n), jnp.uint32)

        def aot(fn, consts, spec):
            nonlocal compiled, failed
            cspec = {k: jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for k, a in consts.items()}
            try:
                fn.lower(spec, cspec).compile()
                compiled += 1
            except Exception:  # pragma: no cover - older jax without AOT
                failed += 1

        for inverse in (False, True):
            for coset in (False, True):
                for boundary in boundaries:
                    self.kernel(inverse, coset, boundary=boundary)
                    fn, consts = self._fns[(inverse, coset, boundary)]
                    aot(fn, consts, v_spec)
                for b in batch_sizes:
                    self.kernel_batch(inverse, coset)
                    fn, consts = self._fns[(inverse, coset, "batch")]
                    aot(fn, consts,
                        jax.ShapeDtypeStruct((FR_LIMBS, b, self.n),
                                             jnp.uint32))
        return {"compiled": compiled, "failed": failed}

    # --- host-boundary convenience (int lists, zero-padded to n) -------------

    def run_ints(self, values, inverse=False, coset=False):
        assert len(values) <= self.n
        padded = list(values) + [0] * (self.n - len(values))
        v = jnp.asarray(ints_to_limbs(padded, FR_LIMBS))
        out = self.kernel(inverse, coset, boundary="plain")(v)
        return limbs_to_ints(np.asarray(out))


_PLANS = {}


def get_plan(n):
    if n not in _PLANS:
        _PLANS[n] = NttPlan(n)
    return _PLANS[n]
