"""Variable-base MSM on device: sort-free Pippenger over the limb G1 kernels.

Device replacement for `ark-ec`'s rayon Pippenger as the reference workers
run it (/root/reference/src/worker.rs:159-185). Scalars are decomposed into
W = 256/c radix-2^c windows — c size-dependent as in standard Pippenger
(8 bits at bench scale, smaller for small MSMs) — and each window's 2^c - 1
buckets are accumulated WITHOUT any sort or data-dependent scatter pattern:

  - points are split into G groups, each group owning a private (G, B)
    bucket array;
  - a lax.scan walks n/G point-batches: gather current buckets at the
    batch's digits (one per group), one G-wide vectorized COMPLETE
    projective mixed add (RCB15, a=0 — no edge cases, 2 stacked-lane
    multiplier instances), scatter back — all writes in a step hit
    distinct rows, so the scan is race-free by construction;
  - group bucket-planes then fold sequentially with a scan whose body is a
    single (24, W, B)-shaped complete projective add — the SAME body the
    mesh version reuses to fold planes across devices, so XLA's
    computation deduplication compiles it once;
  - the remaining O(W * B) tail (running-sum bucket aggregation,
    2^(c*w) window weighting, final window sum) runs as two more
    static-shape scans with no data-dependent indexing at all (see
    `finish`).

Accumulators are homogeneous PROJECTIVE (X : Y : Z), identity (0 : 1 : 0);
results decode as x = X/Z, y = Y/Z (_proj_limbs_to_affine). Large MSMs
(c = 8) use SIGNED digits: B = 128 buckets instead of 256. This keeps the
optimal ~n adds/window of Pippenger while the whole MSM compiles exactly
THREE complete-add bodies regardless of n — XLA compile time (the round-1
multichip-gate killer: >8 min for a 16-point mesh MSM) is O(1) in both n
and the number of reduction phases — and every memory access is regular.
"""

import os
import threading
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import FQ_MONT_R, Q_MOD, R_MOD, FR_LIMBS, FQ_LIMBS
from . import autotune
from . import curve_jax as CJ
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_int

SCALAR_BITS = 256

# accepted knob values — the autotuner enumerates its candidate grid
# from these (and from the C_CHOICES assert below), so the measured
# space cannot drift from what the dispatch accepts
BUCKET_UPDATE_CHOICES = ("onehot", "put")
KERNEL_CHOICES = ("pallas", "xla")
C_CHOICES = (7, 8)


def window_bits(n):
    """Pippenger window size for an n-point MSM, restricted to divisors of
    the 16-bit limb width so digit extraction never crosses a limb.

    Standard size-dependent choice (ark picks ~ln n + 2): small inputs get
    small windows so the O(windows * 2^c) bucket-plane tail does not dwarf
    the O(n * windows) accumulation — this is also what keeps the tiny-shape
    multichip dry-run fast, where 8-bit windows would spend minutes adding
    planes of infinities."""
    if n >= 4096:
        return 8
    if n >= 64:
        return 4
    if n >= 8:
        return 2
    return 1


def _group_size(n):
    """Private-bucket group count for an n-point MSM.

    The accumulation scan does n*W lane-adds no matter what; the plane
    fold does G*W*2^c more. Measured on v5e (2.5us/lane-add end to end),
    total time tracks total lane-adds almost linearly, so G is kept at
    ~n/1024 — fold work <= 25% of scan work — instead of the old fixed 512
    (which at n=9216 made the fold 14x the scan and a 5-poly commit batch
    8x slower than G=8).

    DPT_MSM_GROUP_MAX raises the 512 cap: with the onehot plane update
    (no scatter op) per-ADD plane traffic is G-independent, so wider
    groups only amortize per-step overhead better — bounded by the fold
    work and the plane-budget cap in _group_size_batch."""
    g = _group_max_knob(n)
    if g < 1:
        g = 512
    g = 1 << (g.bit_length() - 1)  # round down to a power of two: the
    # halving search below only terminates on divisors of power-of-two n
    while g > 1 and (n % g != 0 or n // g < 2 or g * 1024 > n):
        g //= 2
    return g


# peak bucket-plane footprint allowed for a batched MSM (all three Jacobian
# coords); beyond this the group width halves, trading scan steps for HBM
_PLANE_BYTES_BUDGET = int(os.environ.get("DPT_MSM_PLANE_MB", "1536")) << 20

# Bucket-plane update strategy for the accumulation scans (DPT_BUCKET_UPDATE):
#   put:    take_along_axis / put_along_axis on the bucket axis.
#   onehot: gather = masked reduction over the bucket axis, update = broadcast
#           compare + where over the whole plane. No scatter op at all — pure
#           streaming reads/writes.
#   auto (default): onehot on TPU, put elsewhere. Measured round 4 on a v5e
#   (scripts/scatter_ab.py, G=256 M=32 B=128): put 15.6 ms/step (524k
#   lane-adds/s) vs onehot 3.5 ms/step (2.32M) — TPU scatter lowering, not
#   the projective add, was the MSM's 4.4x bottleneck. On CPU the scatter is
#   cheap and onehot's full-plane traffic (x buckets) would swamp the mesh
#   tests, hence the platform split.
_BUCKET_UPDATE = os.environ.get("DPT_BUCKET_UPDATE", "auto")


def _group_max_knob(n=None):
    """Per-call group cap: explicit DPT_MSM_GROUP_MAX > autotune plan
    near n points > 512 (the shared env > plan > default resolver)."""
    return autotune.env_or_plan("DPT_MSM_GROUP_MAX", "msm", "group_max",
                                512, n, cast=int)


def _use_onehot_update(n=None):
    mode = autotune.attr_or_plan(_BUCKET_UPDATE, "auto",
                                 "DPT_BUCKET_UPDATE", "msm",
                                 "bucket_update", n)
    if mode in BUCKET_UPDATE_CHOICES:
        return mode == "onehot"
    return jax.default_backend() == "tpu"


# Limb-packed planes (onehot path only): scan carries hold the bucket
# planes as (12, ...) u32 with TWO 16-bit limbs per word, halving the
# dominant per-step streaming traffic (scatter_ab.py round 4: the
# gather+update pass alone is 1.6 ms of the 3.6 ms step at G=256).
# Pack/unpack are cheap shifts on the (24, G, M) gathered slice only.
# DPT_PLANE_PACK=0 opts out.
_PLANE_PACK = os.environ.get("DPT_PLANE_PACK", "1") != "0"


def _use_packed_planes(n=None):
    return _use_onehot_update(n) and _PLANE_PACK


# Bucket-accumulation kernel (DPT_MSM_KERNEL):
#   pallas: the fused msm_pallas kernel — digit decode, bucket gather,
#           RCB15 mixed add, and bucket update in ONE Pallas program
#           whose bucket planes stay VMEM-resident for the whole point
#           stream (no per-step HBM plane round trip).
#   xla:    the lax.scan path below — the parity/debug core, exactly
#           like DPT_NTT_RADIX=2.
#   auto (default): pallas on TPU, xla elsewhere (same platform split as
#   DPT_BUCKET_UPDATE; CPU interpret-mode pallas is test-only).
# Resolved per call (module attr, monkeypatchable) like _BUCKET_UPDATE;
# field_jax.pallas_disabled() / mesh.pallas_guard override even a forced
# "pallas" — a pallas_call has no GSPMD partitioning rule, so sharded
# traces outside shard_map must keep the XLA scan.
_MSM_KERNEL = os.environ.get("DPT_MSM_KERNEL", "auto")


def _use_pallas_kernel(n=None):
    if getattr(FJ._pallas_off, "v", False):
        return False
    mode = autotune.attr_or_plan(_MSM_KERNEL, "auto", "DPT_MSM_KERNEL",
                                 "msm", "kernel", n)
    if mode in KERNEL_CHOICES:
        return mode == "pallas"
    return jax.default_backend() == "tpu"


def _kernel_mode(n=None):
    return "pallas" if _use_pallas_kernel(n) else "xla"


# packed-pair layout shared with field_jax (round 3's packed coset evals
# use the same representation)
_pack_limbs = FJ.pack_limb_pairs
_unpack_limbs = FJ.unpack_limb_pairs


def _plane_init(proj_planes):
    """Scan-carry representation of initial projective planes."""
    if _use_packed_planes():
        return tuple(_pack_limbs(b) for b in proj_planes)
    return tuple(proj_planes)


def _plane_finish(planes):
    """Scan-carry planes -> (24, ...) limb planes for fold/finish."""
    if _use_packed_planes():
        return tuple(_unpack_limbs(b) for b in planes)
    return tuple(planes)


def _plane_gather(planes, dg):
    """Current bucket values at per-lane digits dg (G, M) from the scan's
    plane carry -> ((24, G, M),)*3 limbs, plus the reusable update
    context."""
    if _use_onehot_update():
        hit = dg[None, :, :, None] == lax.broadcasted_iota(
            dg.dtype, (1,) + planes[0].shape[1:], 3)
        cur = tuple(jnp.sum(jnp.where(hit, b, 0), axis=3, dtype=b.dtype)
                    for b in planes)
        if _use_packed_planes():
            cur = tuple(_unpack_limbs(c) for c in cur)
        return cur, hit
    dg4 = dg[None, :, :, None]
    dg4b = jnp.broadcast_to(dg4, (FQ_LIMBS,) + dg4.shape[1:])
    cur = tuple(jnp.take_along_axis(b, dg4b, axis=3)[..., 0] for b in planes)
    return cur, dg4b


def _plane_update(planes, vals, ctx):
    """Write (24, G, M) limb vals back at the gathered positions."""
    if _use_onehot_update():
        if _use_packed_planes():
            vals = tuple(_pack_limbs(v) for v in vals)
        return tuple(jnp.where(ctx, v[..., None], b)
                     for b, v in zip(planes, vals))
    return tuple(jnp.put_along_axis(b, ctx, v[..., None], axis=3,
                                    inplace=False)
                 for b, v in zip(planes, vals))


def _group_size_batch(n, batch, c, signed=False, kernel=None):
    """Group width for a B-poly batched MSM: work-optimal size per
    _group_size, further capped so the plane array (which scales with
    group * B * W * buckets) stays in budget.

    Under the fused Pallas kernel the planes live in VMEM, not HBM, so
    the cap is the VMEM lane budget instead: group shrinks so a window
    tile of >= ~8 lanes still fits (wider window tiles mean fewer
    re-reads of the point stream — see msm_pallas's traffic model);
    per-step overhead no longer rewards huge groups there.

    kernel: explicit resolved mode ('pallas'|'xla') from the caller —
    MsmContext passes its context-width resolution so group sizing,
    the chunk memo key, and the traced branch all agree; None resolves
    at n (direct/mesh callers, whose traces resolve at the same n)."""
    w = -(-SCALAR_BITS // c)  # ceil: c=7 has 37 windows, not 36
    buckets = 1 << (c - 1) if signed else 1 << c
    g = _group_size(n)
    if (kernel == "pallas") if kernel is not None \
            else _use_pallas_kernel(n):
        from . import msm_pallas
        cap = max(8, msm_pallas.plane_lanes_cap(
            buckets, _PLANE_PACK) // 8)
        while g > cap:
            g //= 2
    else:
        per_group = 3 * 4 * FQ_LIMBS * batch * w * buckets
        while g > 1 and g * per_group > _PLANE_BYTES_BUDGET:
            g //= 2
    while g > 1 and n % g != 0:
        g //= 2
    return g


def _scan_layout(ax, ay, group):
    """(24, n) points -> (steps, 24, group) scan inputs."""
    n = ax.shape[1]
    steps = n // group

    def to_scan(a):
        return a.reshape(FQ_LIMBS, group, steps).transpose(2, 0, 1)

    return to_scan(ax), to_scan(ay)


def _to_scan_m(a, group):
    """(M, n) per-lane rows -> (steps, group, M) scan inputs."""
    M, n = a.shape
    return a.reshape(M, group, n // group).transpose(2, 1, 0)


def _bucket_scan(ax, ay, ainf, digits, group, n_buckets, kernel=None):
    """Unsigned COMBINED-LANE bucket accumulation (small-window path).

    All M digit lanes (M = batch x windows) share the point stream: one
    gather + one scatter + ONE wide complete projective mixed add per
    scan step covers every lane — the former per-window vmap issued M
    separate gather/scatter/add op groups per step, which (a) kept each
    mont_mul below the Pallas kernel's profitable width and (b) paid the
    per-op dispatch fixed cost M times (round-4 chip measurement:
    scripts/msm_ab.py).

    ax/ay: (24, n) affine Montgomery; ainf: (n,) bool; digits: (M, n)
    uint32 < n_buckets. Returns ((24, group, M, n_buckets),)*3 PROJECTIVE
    planes with bucket b of (group g, lane m) = sum of g's points whose
    lane-m digit == b (bucket 0 included but ignored downstream).

    DPT_MSM_KERNEL=pallas runs the fused VMEM-resident kernel
    (msm_pallas.bucket_scan) — bit-identical planes at the same group
    width; this scan remains the parity/debug core. `kernel` pins the
    resolved mode from the caller (MsmContext resolves at its context
    width so the trace matches its memo key); None resolves here at the
    local chunk width.
    """
    if (kernel == "pallas") if kernel is not None \
            else _use_pallas_kernel(ax.shape[1]):
        from . import msm_pallas
        return msm_pallas.bucket_scan(ax, ay, ainf, digits, group,
                                      n_buckets, packed=_PLANE_PACK)
    M = digits.shape[0]
    sx_all, sy_all = _scan_layout(ax, ay, group)
    xs = (sx_all, sy_all, _to_scan_m(ainf[None, :] | jnp.zeros_like(digits, bool),
                                     group),
          _to_scan_m(digits, group))

    # varying-zero: under shard_map the scan carry must inherit the inputs'
    # varying-manual-axes tag; adding a data-derived 0 does exactly that
    # (and constant-folds away otherwise)
    vz = ax.ravel()[0] & 0
    init = _plane_init(tuple(
        b + vz for b in CJ.proj_inf((group, M, n_buckets))))

    def step(carry, x):
        planes = carry                # plane carry (packed or limb) x3
        sx, sy, si, dg = x            # sx/sy (24, G); si/dg (G, M)
        cur, ctx = _plane_gather(planes, dg)
        sxb = jnp.broadcast_to(sx[:, :, None], cur[0].shape)
        syb = jnp.broadcast_to(sy[:, :, None], cur[0].shape)
        nv = CJ.proj_add_mixed(cur, (sxb, syb), si)
        return _plane_update(planes, nv, ctx), None

    planes, _ = lax.scan(step, init, xs)
    return _plane_finish(planes)


def _bucket_scan_signed(ax, ay, ainf, packed, group, n_buckets=128,
                        kernel=None):
    """SIGNED-digit COMBINED-LANE bucket accumulation — the signed hot
    path (c=8: 128 bucket columns; c=7: 64): half the buckets of the
    unsigned scan (bucket i holds points whose |digit| == i+1; the sign
    is applied to the point's y on the fly), the accumulator add is
    RCB15's complete formula (11 muls in 2 stacked-lane instances, no
    doubling fallback, no edge selects), and every scan step is ONE wide
    gather/add/scatter across all M lanes (see _bucket_scan for why).

    ax/ay: (24, n) affine Montgomery; ainf: (n,) bool; packed: (M, n)
    uint32 = digit + n_buckets with digit in [-n_buckets, n_buckets-1].
    Returns ((24, group, M, n_buckets),)*3 PROJECTIVE bucket planes.

    DPT_MSM_KERNEL=pallas runs the fused VMEM-resident kernel
    (msm_pallas.bucket_scan_signed) — bit-identical planes at the same
    group width; this scan remains the parity/debug core. `kernel`: see
    _bucket_scan.
    """
    if (kernel == "pallas") if kernel is not None \
            else _use_pallas_kernel(ax.shape[1]):
        from . import msm_pallas
        return msm_pallas.bucket_scan_signed(ax, ay, ainf, packed, group,
                                             n_buckets,
                                             packed=_PLANE_PACK)
    M = packed.shape[0]
    off = packed.astype(jnp.int32) - n_buckets
    neg = off < 0
    mag = jnp.abs(off)
    skip = (mag == 0) | ainf[None, :]
    idx = jnp.maximum(mag, 1).astype(jnp.uint32) - 1  # 0..n_buckets-1

    sx_all, sy_all = _scan_layout(ax, ay, group)
    xs = (sx_all, sy_all, _to_scan_m(skip, group), _to_scan_m(neg, group),
          _to_scan_m(idx, group))

    vz = ax.ravel()[0] & 0  # varying-zero, see _bucket_scan
    init = _plane_init(tuple(
        b + vz for b in CJ.proj_inf((group, M, n_buckets))))

    def step(carry, x):
        planes = carry                # plane carry (packed or limb) x3
        sx, sy, sk, ng, dg = x        # sx/sy (24, G); sk/ng/dg (G, M)
        cur, ctx = _plane_gather(planes, dg)
        nsy = FJ.neg(CJ.FQ, sy)       # negate once per step, select per lane
        qy = jnp.where(ng[None], nsy[:, :, None], sy[:, :, None])
        sxb = jnp.broadcast_to(sx[:, :, None], cur[0].shape)
        nv = CJ.proj_add_mixed(cur, (sxb, qy), sk)
        return _plane_update(planes, nv, ctx), None

    planes, _ = lax.scan(step, init, xs)
    return _plane_finish(planes)


def fold_planes(bx, by, bz):
    """(K, 24, W, B) PROJECTIVE bucket planes -> (24, W, B) bucketwise sum.

    Used for both the group fold and the mesh cross-device fold: the scan
    body is identical in both calls, so XLA compiles it once per program.
    (A log-depth pairwise tree was tried here and reverted: its first
    level is an add over K/2 planes at once, whose mont_mul column
    tensors transiently need ~150x the plane bytes — 33 GB at a batched
    2^10 MSM. The scan touches one plane per step, keeping transients at
    1/K of that; with batched pipelines the per-step lanes are wide enough
    that the sequential depth is not the bottleneck.)"""
    vz = bz.ravel()[0] & 0  # varying-zero, see _bucket_scan
    init = tuple(b + vz for b in CJ.proj_inf(bz.shape[2:]))

    def red(acc, plane):
        return CJ.proj_add(acc, plane), None

    acc, _ = lax.scan(red, init, (bx, by, bz))
    return acc


# --- finish tail -------------------------------------------------------------

def finish(bx, by, bz, signed=False):
    """(24, W, B) folded buckets -> total point ((24,),)*3.

    Three phases, all static-shape scans with NO gather/scatter ops (this
    XLA:CPU build expands scatters into per-index buffer updates, which
    made an indexed-machine variant of this tail pathologically slow):

      1. running-sum bucket aggregation: scan over bucket columns B-1..1
         (+ one infinity flush column), carry (run_w, acc_w) stacked on a
         lane axis so each step is ONE (24, W, 2) complete projective add
         — pipelined:  acc += run ; run += bucket[:, b]  per step.
      2+3. window weighting and final sum in ONE scan of (shift, mask)
         steps on (24, W): `shift=0` steps double the masked windows
         (acc_w ends as 2^(c*w) * A_w), `shift=h` steps add acc[w+h] into
         acc[w] for w < h (pairwise tree); the total lands in lane 0.

    Points are PROJECTIVE with complete adds throughout, so the shift=0
    "doubling" steps and every identity lane need no special handling at
    all. signed=True: planes come from _bucket_scan_signed — B = 2^(c-1)
    columns where column i weighs (i+1), so phase 1 scans ALL columns
    (reversed) instead of dropping column 0.
    """
    wins, buckets = bz.shape[1], bz.shape[2]
    c = -(-SCALAR_BITS // wins)  # ceil: c=7 gives 37 windows (not 256/37=6)
    assert buckets == (1 << (c - 1) if signed else 1 << c), (wins, buckets)
    add = CJ.proj_add
    vz = bz.ravel()[0] & 0  # varying-zero, see _bucket_scan
    inf_w = tuple(x + vz for x in CJ.proj_inf((wins,)))

    # phase 1: bucket columns (weight order), then one infinity flush column
    def col_xs(a):  # (24, W, B) -> (B, 24, W): high-weight column first
        body = a if signed else a[:, :, 1:]
        return body[:, :, ::-1].transpose(2, 0, 1)

    xs = tuple(jnp.concatenate([col_xs(a), i[None, :, :]], axis=0)
               for a, i in zip((bx, by, bz), inf_w))

    def agg(carry, x):
        # carry: ((24, W, 2),)*3 with lane 0 = run, lane 1 = acc
        left = tuple(v for v in carry)
        right = tuple(jnp.stack([xi, v[:, :, 0]], axis=2)
                      for xi, v in zip(x, left))
        out = add(left, right)
        return out, None

    init = tuple(jnp.stack([i, i], axis=2) for i in inf_w)
    acc2, _ = lax.scan(agg, init, xs)
    acc = tuple(v[:, :, 1] for v in acc2)  # (24, W)

    # phase 2+3: doubling ladder + pairwise tree, one (shift, mask) scan
    steps = []
    for k in range(c * (wins - 1)):
        steps.append((0, [k < c * w for w in range(wins)]))
    # pairwise tree over a possibly NON-power-of-two window count (37 at
    # c=7): fold acc[w+h] into acc[w] only where w+h < wins — the roll's
    # wrap-around lanes are masked off
    h = 1 << max(0, (wins - 1).bit_length() - 1)
    while h >= 1:
        steps.append((h, [w < h and w + h < wins for w in range(wins)]))
        h //= 2
    shifts = jnp.asarray(np.array([s for s, _ in steps], dtype=np.int32))
    masks = jnp.asarray(np.array([m for _, m in steps]))

    def weight(carry, step):
        shift, mask = step
        rolled = tuple(jnp.roll(v, -shift, axis=1) for v in carry)
        summed = add(carry, rolled)
        return tuple(jnp.where(mask[None, :], s, v)
                     for s, v in zip(summed, carry)), None

    acc, _ = lax.scan(weight, acc, (shifts, masks))
    return tuple(v[:, 0] for v in acc)


def bucket_planes_batch(ax, ay, ainf, digits, group, kernel=None):
    """B-polynomial bucket accumulation over SHARED bases: affine points
    (24, nc) + inf mask (nc,) + digits (B, W, nc) -> folded planes
    ((24, B*W, 2^c),)*3.

    The prover's per-round commitment batches (5 wires, 5 quotient splits,
    2 openings — the join_all fan-outs of reference dispatcher2.rs:316-321,
    526-533) share every scan step, so fixed per-step latency is paid once
    per round instead of once per polynomial."""
    B, W, n = digits.shape
    buckets = 1 << (SCALAR_BITS // W)
    flat = digits.reshape(B * W, n)
    wb = _bucket_scan(ax, ay, ainf, flat, group, buckets, kernel=kernel)
    planes = tuple(x.transpose(1, 0, 2, 3) for x in wb)  # (G, 24, B*W, buckets)
    return fold_planes(*planes)


def bucket_planes_batch_signed(ax, ay, ainf, packed, group, kernel=None):
    """Signed-digit analog of bucket_planes_batch: affine bases (24, nc) +
    inf mask (nc,) + packed digits (B, W, nc) -> ((24, B*W, 2^(c-1)),)*3.
    The window count W determines c (32 -> c=8, 37 -> c=7)."""
    B, W, n = packed.shape
    c = -(-SCALAR_BITS // W)
    flat = packed.reshape(B * W, n)
    wb = _bucket_scan_signed(ax, ay, ainf, flat, group,
                             n_buckets=1 << (c - 1), kernel=kernel)
    planes = tuple(x.transpose(1, 0, 2, 3) for x in wb)
    return fold_planes(*planes)


def finish_batch(acc_x, acc_y, acc_z, batch, signed=False):
    """((24, B*W, buckets),)*3 folded planes -> ((24, B),)*3 totals."""
    acc_b = tuple(a.reshape(FQ_LIMBS, batch, a.shape[1] // batch, a.shape[2])
                  for a in (acc_x, acc_y, acc_z))
    return jax.vmap(partial(finish, signed=signed),
                    in_axes=(1, 1, 1), out_axes=1)(*acc_b)


def msm_pipeline_batch(ax, ay, ainf, digits, group):
    """One-shot batched MSM (small inputs / tests): bucket accumulation +
    finish in a single program."""
    acc = bucket_planes_batch(ax, ay, ainf, digits, group)
    return finish_batch(*acc, batch=digits.shape[0])


def _canon_padded(v, padded_n):
    """(16, L) Montgomery coefficients -> (16, padded_n) canonical limbs
    (the shared device prologue of every digit-extraction path)."""
    canon = FJ.from_mont(FR, v)
    if canon.shape[1] < padded_n:
        canon = jnp.pad(canon, ((0, 0), (0, padded_n - canon.shape[1])))
    return canon


def digits_from_mont(v, c, padded_n):
    """(16, L) Montgomery Fr coefficients -> (256/c, padded_n) uint32
    digits, entirely on device (no host round-trip before a commitment)."""
    canon = _canon_padded(v, padded_n)
    per_limb = 16 // c
    mask = (1 << c) - 1
    parts = [(canon >> (c * i)) & mask for i in range(per_limb)]
    return jnp.stack(parts, axis=1).reshape(SCALAR_BITS // c, padded_n)


def digits_of_scalars(scalars, padded_n, c):
    """Host int scalars -> (256/c, padded_n) uint32 radix-2^c digits.

    c must divide 16 so every window lives inside one 16-bit limb."""
    assert 16 % c == 0
    scalars = [s % R_MOD for s in scalars]
    scalars += [0] * (padded_n - len(scalars))
    limbs = ints_to_limbs(scalars, FR_LIMBS)  # (16, n)
    per_limb = 16 // c
    mask = (1 << c) - 1
    parts = [(limbs >> (c * i)) & mask for i in range(per_limb)]
    # window order: limb0's sub-digits (low->high), then limb1's, ...
    digits = np.stack(parts, axis=1).astype(np.uint32)
    return digits.reshape(SCALAR_BITS // c, padded_n)


# NOTE on signed-digit safety: recoding carries can only overflow the top
# window if a scalar's top window digit can reach the sign threshold; Fr
# scalars are canonical (< r < 2^255), so at c=8 the top radix-256 digit
# is <= 0x73 and at c=7 the top (bits 252..258) window is <= 7 — the
# final carry is always 0 at BOTH widths. Tiny keys (< 256 points) keep
# the unsigned small-window path for plane-tile reasons, not safety.

def _signed_recode(u, bias, xp):
    """Windowed unsigned digits -> packed signed digits (d + bias, d in
    [-bias, bias-1]): the ONE carry loop shared by the host (xp=numpy)
    and device (xp=jax.numpy) recodes at both window widths (bias 128
    for c=8, 64 for c=7).

    The wrap is a MASK, not `t + bias - (carry << shift)`: with t <
    2*bias + 1 the two are identical ((t + bias) mod 2*bias), but the
    subtraction's uint32 interval dips below zero unless the verifier
    knows carry == (t >= bias) — a correlation interval analysis cannot
    see (analysis/bounds.py flagged it); the masked form is provably
    in-range for any t the digit bound admits."""
    outs = []
    carry = xp.zeros_like(u[0])
    for w in range(u.shape[0]):
        t = u[w] + carry
        carry = (t >= bias).astype(xp.uint32)
        outs.append((t + bias) & (2 * bias - 1))
    return outs, carry


def _signed_recode_np(u, bias=128):
    outs, carry = _signed_recode(u, bias, np)
    assert not np.asarray(carry).any(), "signed recode overflow (>= r?)"
    return np.stack(outs)


def signed_digits_of_scalars(scalars, padded_n):
    """Host int scalars -> (32, padded_n) packed signed radix-256 digits."""
    return _signed_recode_np(digits_of_scalars(scalars, padded_n, 8))


def signed_digits_from_mont(v, padded_n):
    """(16, L) Montgomery Fr coefficients -> (32, padded_n) packed signed
    radix-256 digits, entirely on device (32-step static recode loop)."""
    outs, _ = _signed_recode(digits_from_mont(v, 8, padded_n), 128, jnp)
    return jnp.stack(outs)


# --- c = 7 windows (37 windows x 64 buckets) ---------------------------------
# Halves the bucket-plane bytes/traffic vs c=8 for +16% window-adds
# (roadmap #2). 7 does not divide 16, so each window may straddle a limb
# boundary: window k covers bits [7k, 7k+7), i.e. limb (7k)>>4 shifted by
# (7k)&15, OR'd with the next limb's low bits when the window crosses.
# Signed safety at c=7: scalars are canonical (< r < 2^255), so the top
# window (bits 252..258) is <= 7; recode carries add <= 1 — never >= 64.

W7 = 37  # ceil(256 / 7)


def _digits7_rows(limbs, stack):
    """(16, n) canonical 16-bit limbs -> 37 rows of 7-bit digits (u32)."""
    rows = []
    for k in range(W7):
        bit = 7 * k
        i, off = bit >> 4, bit & 15
        lo = limbs[i] >> off
        if off > 9 and i + 1 < FR_LIMBS:  # window crosses into limb i+1
            lo = lo | (limbs[i + 1] << (16 - off))
        rows.append(lo & 127)
    return stack(rows)


def signed_digits7_of_scalars(scalars, padded_n):
    """Host int scalars -> (37, padded_n) packed signed base-128 digits
    (d + 64, d in [-64, 63])."""
    scalars = [s % R_MOD for s in scalars]
    scalars += [0] * (padded_n - len(scalars))
    u = _digits7_rows(ints_to_limbs(scalars, FR_LIMBS), np.stack)
    return _signed_recode_np(u, bias=64)


def signed_digits7_from_mont(v, padded_n):
    """(16, L) Montgomery Fr coefficients -> (37, padded_n) packed signed
    base-128 digits, entirely on device."""
    canon = _canon_padded(v, padded_n)
    outs, _ = _signed_recode(_digits7_rows(canon, jnp.stack), 64, jnp)
    return jnp.stack(outs)


def points_to_device(bases_affine, pad):
    """list[(x, y) | None] + pad count -> affine Montgomery limb arrays
    ((24, n+pad) x, (24, n+pad) y, (n+pad,) inf mask), as HOST numpy —
    placement is the caller's call (the mesh context device_puts shards;
    building on the default device first would bounce every base through
    whatever chip owns it, round-2 weakness #1)."""
    xs, ys, infs = [], [], []
    for p in bases_affine:
        if p is None:
            xs.append(0)
            ys.append(0)
            infs.append(True)
        else:
            xs.append(p[0] * FQ_MONT_R % Q_MOD)
            ys.append(p[1] * FQ_MONT_R % Q_MOD)
            infs.append(False)
    xs += [0] * pad
    ys += [0] * pad
    infs += [True] * pad
    x = ints_to_limbs(xs, FQ_LIMBS)
    y = ints_to_limbs(ys, FQ_LIMBS)
    inf = np.array(infs)
    return x, y, inf


class DeviceCommitKey:
    """A commit key that lives on device as Jacobian Montgomery limb arrays
    (e.g. straight out of the fixed-base SRS generator) — no host affine
    normalization on the prover path. Identity padding columns (z == 0) are
    part of the key, mirroring the affine path's None-padded ck list."""

    def __init__(self, px, py, pz):
        assert px.shape == py.shape == pz.shape == (FQ_LIMBS, px.shape[1])
        self.point = (px, py, pz)

    def __len__(self):
        return self.point[0].shape[1]


class MsmContext:
    """Device-resident base set (the SRS chunk a worker holds,
    reference src/worker.rs:42-48). Reused across commitments."""

    def __init__(self, bases):
        n = len(bases)
        self.n = n
        pad = n % 2  # groups need >= 2 scan steps
        self.padded_n = n + pad
        self.c = window_bits(self.padded_n)
        # batched pipelines use wide SIGNED windows once the key is big
        # enough: DPT_MSM_C picks 8 (32 windows x 128 buckets, planes
        # exactly fill (8, 128) minor tiles) or 7 (37 x 64 — half the
        # plane traffic per step at +16% window-adds; A/B'd on chip,
        # msm_c7_ab_r05.json); the autotune plan's winner applies when
        # the knob is unset. Tiny keys keep the unsigned small-window
        # scan (a 16-bucket c=4 plane is layout-padded 8x otherwise).
        self.c_batch = _c_batch_knob(self.padded_n) \
            if self.padded_n >= 256 else self.c
        # wide windows run the SIGNED pipeline (half the buckets, sign
        # folded into y); both pipelines take affine bases + inf mask and
        # accumulate with complete projective adds
        self.signed = self.c_batch in (7, 8)
        if isinstance(bases, DeviceCommitKey):
            point = bases.point
            if pad:
                point = tuple(jnp.pad(p, ((0, 0), (0, pad))) for p in point)
            # device-built SRS is Jacobian with arbitrary Z: normalize
            # once with a batched inversion (one scalar host round-trip)
            self.point = CJ.batch_to_affine(point)
        else:
            # place once at context build: leaving host numpy here would
            # re-upload the whole sliced key on every _exec_chunked call
            self.point = tuple(jax.device_put(p)
                               for p in points_to_device(bases, pad))
        self._platform = next(iter(self.point[0].devices())).platform
        if self.c_batch == 7:
            self._digits_batch_fn = jax.jit(
                partial(signed_digits7_from_mont, padded_n=self.padded_n))
        elif self.signed:
            self._digits_batch_fn = jax.jit(
                partial(signed_digits_from_mont, padded_n=self.padded_n))
        else:
            self._digits_batch_fn = jax.jit(
                partial(digits_from_mont, c=self.c_batch,
                        padded_n=self.padded_n))
        # stacked digit extraction (the cross-job commit_batch path): one
        # vmapped launch turns B same-width coefficient handles into the
        # (B, W, padded_n) digit tensor, instead of B separate dispatches.
        # vmap of the same elementwise program — bit-identical digits.
        self._digits_many_fn = jax.jit(jax.vmap(self._digits_batch_fn))
        self._chunk_fns = {}
        self._chunk_calls = {}  # (nc, g) -> times executed (warm detection)
        self._finish_fns = {}
        self._merge_fn = jax.jit(
            lambda a, b: CJ.proj_add(tuple(a), tuple(b)))

    # one device execution is kept under a lane-add budget: the tunneled
    # runtime kills executions in the ~60 s range ("TPU worker process
    # crashed"), observed for single calls at 2^19 points and above on the
    # round-2 integer kernels. The budget is ADAPTIVE: the first chunk is
    # timed (fenced by a tiny transfer) and subsequent chunks resize toward
    # DPT_MSM_CALL_S seconds/call — the f32 kernel rewrite moved the
    # adds/s rate by an order of magnitude, and a static budget would
    # either waste dispatches or trip the kill limit.
    _CALL_ADDS = int(os.environ.get("DPT_MSM_CALL_ADDS", "8000000"))
    _CALL_TARGET_S = float(os.environ.get("DPT_MSM_CALL_S", "20"))
    _CALL_ADDS_MAX = int(os.environ.get("DPT_MSM_CALL_ADDS_MAX",
                                        str(1 << 28)))
    # default 7 (37 windows x 64 buckets): chip A/B at 2^20
    # (msm_c7_ab_r05.json) measured 29.8 s vs 31.4 s for c=8 (~5%), same
    # result point, both host-oracle-checked at 2^12
    _C_BATCH = int(os.environ.get("DPT_MSM_C", "7"))
    assert _C_BATCH in C_CHOICES, \
        f"DPT_MSM_C must be 7 or 8, got {_C_BATCH}"

    def _mode(self):
        """Resolved bucket kernel for this context's width."""
        return _kernel_mode(self.padded_n)

    def _chunk_key(self, nc, group):
        """Chunk-fn/call memo key: resolved mode + the autotune plan
        revision (autotune.cache_key) — the pallas/xla branch is taken
        at TRACE time inside the jit, so neither an env/attr flip
        (bench A/B, tests) nor a mid-process plan reload may reuse the
        other configuration's executable."""
        return autotune.cache_key(nc, group, self._mode())

    def _chunk_fn(self, nc, group):
        key = self._chunk_key(nc, group)
        if key not in self._chunk_fns:
            fn = bucket_planes_batch_signed if self.signed \
                else bucket_planes_batch
            # kernel pinned to the CONTEXT-width resolution (the memo
            # key above): a plan whose nearest cell at the chunk width
            # disagrees must not make the traced branch diverge from
            # the key, the seeded rate, and the AOT-compiled variant
            self._chunk_fns[key] = jax.jit(
                partial(fn, group=group, kernel=self._mode()))
        return self._chunk_fns[key]

    def _finish_fn(self, batch):
        key = autotune.cache_key(batch)
        if key not in self._finish_fns:
            self._finish_fns[key] = jax.jit(
                partial(finish_batch, batch=batch, signed=self.signed))
        return self._finish_fns[key]

    # adds/s measured from the first fenced chunk call; class-level so every
    # context on the process shares the calibration. Keyed by
    # (platform, signed, c_batch): a CPU-mesh context must not size chunks
    # from a TPU rate (or a signed rate from an unsigned shape), and the
    # write is lock-guarded because fleet workers run MSMs from multiple
    # connection threads.
    _measured_adds_per_s = {}
    _calib_lock = threading.Lock()

    def _calib_key(self):
        # the fused kernel's adds/s is far from the XLA scan's: a rate
        # latched under one kernel must not size the other's chunks —
        # and a plan reload retires latched rates with the revision
        return autotune.cache_key(self._platform, self.signed,
                                  self.c_batch, self._mode())

    def _plan_rate(self):
        """The calibration plan's measured adds/s for keys near this
        width — but only when this context actually dispatches the
        kernel the plan measured (an env override to the other kernel
        must not size chunks from the wrong rate). Seeding the rate
        from the plan makes chunk shapes deterministic from the FIRST
        call, so the AOT pass covers them and nothing recompiles at
        serve time (the PR 3/5 chunk-shape remainder)."""
        rate = autotune.plan_param("msm", "adds_per_s", self.padded_n)
        if rate is None:
            return None
        planned = autotune.plan_param("msm", "kernel", self.padded_n)
        if planned is not None and planned != self._mode():
            return None
        return float(rate)

    def _chunk_lanes(self, B, W):
        """Current per-call point budget (1024-aligned)."""
        budget = self._CALL_ADDS
        rate = MsmContext._measured_adds_per_s.get(self._calib_key())
        if rate is None:
            rate = self._plan_rate()
        if rate is not None:
            budget = min(self._CALL_ADDS_MAX, int(rate * self._CALL_TARGET_S))
        return max(1024, (budget // (B * W)) & ~1023)

    def _exec_chunked(self, digits):
        """digits (B, W, padded_n) -> ((24, B),)*3 totals, in as many
        device calls as the per-call budget requires: per-chunk bucket
        accumulation, cheap cross-chunk plane merges, one finish tail."""
        B, W, n = digits.shape
        ax, ay, ainf = self.point
        acc = None
        i0 = 0
        while i0 < n:
            chunk = self._chunk_lanes(B, W)
            nc = min(chunk, n - i0)
            g = _group_size_batch(nc, B, -(-SCALAR_BITS // W),
                                  signed=self.signed, kernel=self._mode())
            fn = self._chunk_fn(nc, g)
            # calibrate once, on a WARM shape only: a first call's
            # wall-clock is dominated by XLA compilation and would wildly
            # under-read the device rate. A plan-provided rate makes the
            # fence unnecessary (and keeps chunk shapes pinned to what
            # the AOT pass compiled).
            warm = self._chunk_calls.get(self._chunk_key(nc, g), 0) > 0
            calibrate = (self._calib_key() not in
                         MsmContext._measured_adds_per_s
                         and self._plan_rate() is None
                         and nc >= 8192 and warm)
            if calibrate:
                if acc is not None:  # drain queued async work first, or
                    np.asarray(acc[0][:1, :1, :1])  # dt covers prior chunks
                t0 = time.perf_counter()
            part = fn(ax[:, i0:i0 + nc], ay[:, i0:i0 + nc], ainf[i0:i0 + nc],
                      digits[:, :, i0:i0 + nc])
            if calibrate:
                np.asarray(part[0][:1, :1, :1])  # fence (tiny transfer)
                # clamp: a sub-latency reading still LATCHES (at an
                # optimistic rate bounded by _CALL_ADDS_MAX) so the fence
                # never re-runs on later chunks
                dt = max(time.perf_counter() - t0, 0.02)
                with MsmContext._calib_lock:
                    MsmContext._measured_adds_per_s.setdefault(
                        self._calib_key(), B * W * nc / dt)
            ck = self._chunk_key(nc, g)
            self._chunk_calls[ck] = self._chunk_calls.get(ck, 0) + 1
            acc = part if acc is None else tuple(self._merge_fn(acc, part))
            i0 += nc
        return self._finish_fn(B)(*acc)

    def aot_compile(self, batch_sizes=(1,), digit_widths=None):
        """Ahead-of-time `lower().compile()` of the commitment pipeline for
        this key at the given batch widths: on-device digit extraction, the
        per-chunk bucket-accumulation scan, the cross-chunk plane merge,
        and the finish tail — no execution (`JaxBackend.warm_stages` used
        to warm this path by RUNNING one zero-scalar MSM, which baked only
        one shape and cost a real bucket-scan pass). Executables land in
        the persistent compilation cache like the NTT AOT path.

        Chunk/finish/merge shapes match a COLD context's first calls (the
        adaptive chunk budget resizes once the adds/s calibration latches,
        so post-calibration chunk shapes still compile at runtime; warmup's
        job is the cold start, where compile time dominates). Digit
        extraction jit-caches per EXACT handle width, so `digit_widths`
        must be the coefficient-handle widths the caller will commit
        (`warm_stages` passes the prover's n+2/n+3 blinded widths);
        default: this key's full padded width.

        Pallas paths are covered too: with DPT_MSM_KERNEL resolving to
        pallas, the chunk lowering IS the fused bucket kernel (Mosaic
        compile, the expensive part of its cold start); and when the
        fused multiplier gate (field_jax._use_pallas) would route the
        XLA scan's group products to field_pallas, those multiplier
        executables are pre-lowered at the scan's 5/6-pair stacked lane
        widths — closing the PR 3 "Pallas mul path has no AOT hook"
        remainder.
        Returns {"compiled", "failed", "shapes", "kernel",
        "mul_path_widths"}."""
        compiled = failed = 0
        shapes = []
        u32 = jnp.uint32

        def aot(fn, *specs):
            nonlocal compiled, failed
            try:
                fn.lower(*specs).compile()
                compiled += 1
            except Exception:  # pragma: no cover - older jax without AOT
                failed += 1

        W = -(-SCALAR_BITS // self.c_batch)
        c = -(-SCALAR_BITS // W)
        buckets = 1 << (c - 1) if self.signed else 1 << c
        if digit_widths is None:
            digit_widths = (self.padded_n,)
        for L in sorted({min(w, self.padded_n) for w in digit_widths}):
            aot(self._digits_batch_fn,
                jax.ShapeDtypeStruct((FR_LIMBS, L), u32))
        mul_widths = set()
        for B in sorted(set(batch_sizes)):
            nc = min(self._chunk_lanes(B, W), self.padded_n)
            g = _group_size_batch(nc, B, c, signed=self.signed,
                                  kernel=self._mode())
            aot(self._chunk_fn(nc, g),
                jax.ShapeDtypeStruct((FQ_LIMBS, nc), u32),
                jax.ShapeDtypeStruct((FQ_LIMBS, nc), u32),
                jax.ShapeDtypeStruct((nc,), jnp.bool_),
                jax.ShapeDtypeStruct((B, W, nc), u32))
            planes = tuple(
                jax.ShapeDtypeStruct((FQ_LIMBS, B * W, buckets), u32)
                for _ in range(3))
            aot(self._finish_fn(B), *planes)
            aot(self._merge_fn, planes, planes)
            shapes.append({"batch": B, "chunk": nc, "group": g,
                           "kernel": self._mode()})
            # the XLA scan's RCB15 add stages its products as 5- and
            # 6-pair stacked-lane mont_muls at g * B * W lanes; collect
            # the padded widths the fused multiplier would compile at
            for pairs in (5, 6):
                lanes = pairs * g * B * W
                if FJ._use_pallas((FQ_LIMBS, lanes)):
                    from . import field_pallas as FP
                    tile = FP.lane_tile(lanes)
                    mul_widths.add((lanes + (-lanes) % tile, tile))
        for Nw, tile in sorted(mul_widths):
            from . import field_pallas as FP
            spec = jax.ShapeDtypeStruct((FQ_LIMBS, Nw), u32)
            aot(FP._mont_mul_flat, "fq",
                jax.default_backend() != "tpu", FP._VARIANT, tile,
                spec, spec)
        return {"compiled": compiled, "failed": failed, "shapes": shapes,
                "kernel": self._mode(),
                "mul_path_widths": sorted(w for w, _ in mul_widths)}

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        assert len(scalars) <= self.n
        return self.msm_many([scalars])[0]

    def msm_mont_limbs(self, h):
        """Commit a (16, L <= padded_n) Montgomery Fr coefficient handle:
        digit extraction happens on device; only the resulting group
        element returns to the host (for the transcript)."""
        return self.msm_mont_limbs_many([h])[0]

    # batched launches are chunked: bucket planes and mont_mul transients
    # scale with B, and a fixed chunk width keeps the set of compiled batch
    # shapes small across prover rounds (8, then the 5/2-size residuals)
    _BATCH_CHUNK = int(os.environ.get("DPT_MSM_BATCH", "8"))

    def _run_batches(self, items, make_digits, chunk=None, stacked=False,
                     defer=False):
        """items -> affine points; digits are materialized per batch chunk
        so peak digit memory is `chunk` (default _BATCH_CHUNK) tensors,
        not len(items).

        stacked=True (items are same-width device handles): each chunk's
        digit extraction runs as ONE vmapped launch over the stacked
        handles (`_digits_many_fn`) instead of one dispatch per handle —
        the cross-job commit_batch path, where a placement batch of N
        jobs commits 5N wire polys per round.

        Double-buffered: batch k's (24, B) device totals convert to host
        only AFTER batch k+1's work is enqueued, so the device never sits
        idle behind the host-side decode fence (the totals are tiny; only
        ONE extra batch's queued work is ever outstanding).

        defer=True: ALL launches are still enqueued here, in order — but
        every host-side projective decode moves into the returned
        _MsmPending's force(). This is the async commit path: the
        pipelined prover dispatches a member's round commits, then runs
        another member's host work before forcing. The one exception is
        the calibration fence below, which must block either way — a
        fence-drained batch rides the pending as already-decoded points."""
        # one entry per batch chunk, in item order; a drain rewrites the
        # entry in place so deferred and eager decodes can interleave
        parts = []  # ["dev", batch_width, device totals] | ["done", points]
        pending = None  # last parts entry still awaiting decode
        batch_chunk = chunk or self._BATCH_CHUNK

        def drain(part):
            if part[0] == "dev":
                part[:] = ["done", _decode_totals(part[1], part[2])]

        for i in range(0, len(items), batch_chunk):
            # until the one-shot adds/s calibration has latched, drain the
            # previous batch BEFORE launching (old behavior): otherwise the
            # calibration fence inside _exec_chunked would time the timed
            # chunk PLUS the whole queued previous batch and latch a
            # permanently under-read rate
            if (pending is not None and self._calib_key()
                    not in MsmContext._measured_adds_per_s):
                drain(pending)
                pending = None
            part_items = items[i:i + batch_chunk]
            if stacked and len({it.shape for it in part_items}) == 1:
                digits = self._digits_many_fn(jnp.stack(part_items))
            else:
                digits = jnp.stack([make_digits(it) for it in part_items])
            totals = self._exec_chunked(digits)
            if pending is not None and not defer:
                drain(pending)
            pending = ["dev", digits.shape[0], totals]
            parts.append(pending)
        if defer:
            return _MsmPending(parts)
        out = []
        for part in parts:
            drain(part)
            out.extend(part[1])
        return out

    def msm_mont_limbs_many(self, hs, chunk=None):
        """Commit B Montgomery coefficient handles in batched launches;
        returns B affine points (host ints). `chunk` widens/narrows the
        per-launch batch (the cross-job commit path passes the job-batch
        width so one placement batch's same-round commits share launches);
        same-width handles in a chunk get ONE stacked digit-extraction
        launch."""
        for h in hs:
            assert h.shape[1] <= self.n, (h.shape, self.n)
        return self._run_batches(hs, self._digits_batch_fn, chunk=chunk,
                                 stacked=True)

    def msm_mont_limbs_many_async(self, hs, chunk=None):
        """Like msm_mont_limbs_many, but returns an unforced _MsmPending:
        the digit-extraction + bucket-accumulation launches are enqueued
        before returning; the host-side projective decode (the part that
        blocks on the device) runs at pending.force()."""
        for h in hs:
            assert h.shape[1] <= self.n, (h.shape, self.n)
        return self._run_batches(hs, self._digits_batch_fn, chunk=chunk,
                                 stacked=True, defer=True)

    def msm_many(self, scalar_lists):
        """B MSMs over host int scalar lists in batched launches."""
        if self.c_batch == 7:
            make = lambda s: jnp.asarray(
                signed_digits7_of_scalars(s, self.padded_n))
        elif self.signed:
            make = lambda s: jnp.asarray(
                signed_digits_of_scalars(s, self.padded_n))
        else:
            make = lambda s: jnp.asarray(
                digits_of_scalars(s, self.padded_n, self.c_batch))
        return self._run_batches(scalar_lists, make)


def _c_batch_knob(n=None):
    """Resolved batch window width: explicit DPT_MSM_C (latched into
    MsmContext._C_BATCH, which its import-time assert already validated
    against C_CHOICES) > autotune plan near an n-point key > 7. A plan
    value outside C_CHOICES falls back to the default — a malformed
    plan must never break dispatch (only explicit knobs may raise)."""
    if "DPT_MSM_C" in os.environ or MsmContext._C_BATCH != 7:
        # env-set, or test/harness-patched away from the built-in
        # default: explicit wins over the plan (attr_or_plan semantics)
        return MsmContext._C_BATCH
    p = autotune.plan_param("msm", "c", n)
    try:
        c = int(p)
    except (TypeError, ValueError):
        return MsmContext._C_BATCH
    return c if c in C_CHOICES else MsmContext._C_BATCH


def _decode_totals(B, totals):
    """One batch chunk's (24, B) device totals -> B affine host points.
    The np.asarray calls are the device sync point."""
    tx, ty, tz = totals
    tx, ty, tz = np.asarray(tx), np.asarray(ty), np.asarray(tz)
    return [_proj_limbs_to_affine(tx[:, j], ty[:, j], tz[:, j])
            for j in range(B)]


class _MsmPending:
    """Deferred MSM results from _run_batches(defer=True): every launch is
    already enqueued; force() walks the batch parts in item order and
    performs the host-side decodes (parts the calibration fence already
    drained pass through). Exactly one consumer forces — the prover
    member's host-finalize."""

    __slots__ = ("_parts",)

    def __init__(self, parts):
        self._parts = parts

    def force(self):
        out = []
        for part in self._parts:
            if part[0] == "dev":
                part[:] = ["done", _decode_totals(part[1], part[2])]
            out.extend(part[1])
        return out


def _proj_limbs_to_affine(tx, ty, tz):
    """Homogeneous projective (X : Y : Z) Montgomery limbs -> affine host
    ints or None. Every pipeline result (signed, unsigned, mesh) is
    projective; decode is x = X/Z, y = Y/Z."""
    def dec(v):
        return limbs_to_int(np.asarray(v)) * CJ._MONT_R_INV % Q_MOD

    z = dec(tz)
    if z == 0:
        return None
    zi = pow(z, Q_MOD - 2, Q_MOD)
    return (dec(tx) * zi % Q_MOD, dec(ty) * zi % Q_MOD)


def msm(bases_affine, scalars):
    """One-shot MSM (context built and discarded)."""
    return MsmContext(bases_affine).msm(scalars)
