"""Variable-base MSM on device: sort-free Pippenger over the limb G1 kernels.

Device replacement for `ark-ec`'s rayon Pippenger as the reference workers
run it (/root/reference/src/worker.rs:159-185). Scalars are decomposed into
32 radix-2^8 windows; each window's 255 buckets are accumulated WITHOUT any
sort or data-dependent scatter pattern:

  - points are split into G groups, each group owning a private (G, 256)
    bucket array;
  - a lax.scan walks n/G point-batches: gather current buckets at the
    batch's digits (one per group), one G-wide vectorized Jacobian add,
    scatter back — all writes in a step hit distinct rows, so the scan is
    race-free by construction;
  - groups then fold sequentially (scan), buckets aggregate with the
    standard running-sum trick (scan over 255 buckets, vectorized across
    all 32 windows), and windows combine by Horner (8 doublings + 1 add
    per window).

This keeps the optimal ~n adds/window of Pippenger while every compiled
program has an O(1)-size trace (limb math is unrolled only inside scan
bodies) and purely regular memory access — the TPU-friendly answer to
Pippenger's scatter problem.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import FQ_MONT_R, Q_MOD, R_MOD, FR_LIMBS, FQ_LIMBS
from . import curve_jax as CJ
from .limbs import ints_to_limbs, limbs_to_int
from .. import curve as C

NUM_WINDOWS = 32  # 256 bits / 8-bit windows
WINDOW_BITS = 8
NUM_BUCKETS = 1 << WINDOW_BITS


def _group_size(n):
    g = 512
    while g > 1 and (n % g != 0 or n // g < 2):
        g //= 2
    return g


def _window_buckets(px, py, pz, digits, group):
    """One window's bucket sums. px/py/pz: (24, n); digits: (n,) uint32.

    Returns bucket points ((24, 256),)*3 with bucket b = sum of points
    whose digit == b (bucket 0 included but ignored downstream).
    """
    n = px.shape[1]
    steps = n // group
    garange = jnp.arange(group)

    def to_scan(a):  # (24, n) -> (steps, 24, group)
        return a.reshape(FQ_LIMBS, group, steps).transpose(2, 0, 1)

    xs = (to_scan(px), to_scan(py), to_scan(pz),
          digits.reshape(group, steps).T)

    # varying-zero: under shard_map the scan carry must inherit the inputs'
    # varying-manual-axes tag; adding a data-derived 0 does exactly that
    # (and constant-folds away otherwise)
    vz = pz.ravel()[0] & 0
    bx, by, bz = (b + vz for b in CJ.pt_inf((group, NUM_BUCKETS)))

    def step(carry, x):
        bx, by, bz = carry
        sx, sy, sz, dg = x
        cur = (bx[:, garange, dg], by[:, garange, dg], bz[:, garange, dg])
        nx, ny, nz = CJ.jac_add(cur, (sx, sy, sz))
        return (bx.at[:, garange, dg].set(nx),
                by.at[:, garange, dg].set(ny),
                bz.at[:, garange, dg].set(nz)), None

    (bx, by, bz), _ = lax.scan(step, (bx, by, bz), xs)

    # fold the per-group private buckets: scan over groups
    def red(acc, grp):
        return CJ.jac_add(acc, grp), None

    acc0 = tuple(b + vz for b in CJ.pt_inf((NUM_BUCKETS,)))
    grps = tuple(b.transpose(1, 0, 2) for b in (bx, by, bz))  # (group, 24, 256)
    acc, _ = lax.scan(red, acc0, grps)
    return acc


@jax.jit
def _finish(bx, by, bz):
    """(24, 32, 256) window buckets -> total point ((24,),)*3.

    Running-sum aggregation (sum_b b*bucket_b, vectorized across windows)
    then Horner window combine (8 doublings + add per window)."""
    # scan b = 255 .. 1
    xs = tuple(b[:, :, 1:][:, :, ::-1].transpose(2, 0, 1) for b in (bx, by, bz))

    def agg(carry, bucket):
        run, acc = carry
        run = CJ.jac_add(run, bucket)
        acc = CJ.jac_add(acc, run)
        return (run, acc), None

    vz = bz.ravel()[0] & 0  # varying-zero, see _window_buckets
    inf_w = tuple(b + vz for b in CJ.pt_inf((NUM_WINDOWS,)))
    (_, wsums), _ = lax.scan(agg, (inf_w, inf_w), xs)

    # Horner over windows from the top: T = 2^8 T + W_w
    ws = tuple(w[:, ::-1].transpose(1, 0) for w in wsums)  # (32, 24)

    def comb(total, w):
        total = lax.fori_loop(0, WINDOW_BITS, lambda i, t: CJ.jac_double(t), total)
        return CJ.jac_add(total, w), None

    total0 = tuple(b + vz for b in CJ.pt_inf(()))
    total, _ = lax.scan(comb, total0, ws)
    return total


class MsmContext:
    """Device-resident base set (the SRS chunk a worker holds,
    reference src/worker.rs:42-48). Reused across commitments."""

    def __init__(self, bases_affine):
        n = len(bases_affine)
        self.n = n
        pad = n % 2  # groups need >= 2 scan steps
        self.padded_n = n + pad
        self.group = _group_size(self.padded_n)
        # one program: all 32 windows' bucket accumulations vmapped together
        self._windows_fn = jax.jit(jax.vmap(
            partial(_window_buckets, group=self.group),
            in_axes=(None, None, None, 0)))
        xs, ys, infs = [], [], []
        for p in bases_affine:
            if p is None:
                xs.append(0)
                ys.append(0)
                infs.append(True)
            else:
                xs.append(p[0] * FQ_MONT_R % Q_MOD)
                ys.append(p[1] * FQ_MONT_R % Q_MOD)
                infs.append(False)
        xs += [0] * pad
        ys += [0] * pad
        infs += [True] * pad
        x = jnp.asarray(ints_to_limbs(xs, FQ_LIMBS))
        y = jnp.asarray(ints_to_limbs(ys, FQ_LIMBS))
        inf = jnp.asarray(np.array(infs))
        self.point = CJ.from_affine(x, y, inf)

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        assert len(scalars) <= self.n
        scalars = [s % R_MOD for s in scalars]
        scalars += [0] * (self.padded_n - len(scalars))
        limbs = jnp.asarray(ints_to_limbs(scalars, FR_LIMBS))  # (16, n)
        digits = jnp.stack([limbs & 0xFF, limbs >> 8], axis=1)
        digits = digits.reshape(NUM_WINDOWS, self.padded_n)

        px, py, pz = self.point
        wb = self._windows_fn(px, py, pz, digits)  # ((32, 24, 256),)*3
        bx, by, bz = (b.transpose(1, 0, 2) for b in wb)
        tx, ty, tz = _finish(bx, by, bz)
        return _jac_limbs_to_affine(tx, ty, tz)


def _jac_limbs_to_affine(tx, ty, tz):
    def dec(v):
        # from Montgomery: value * R^-1 mod q, done on host (single element)
        return limbs_to_int(np.asarray(v)) * CJ._MONT_R_INV % Q_MOD

    return C.g1_from_jac((dec(tx), dec(ty), dec(tz)))


def msm(bases_affine, scalars):
    """One-shot MSM (context built and discarded)."""
    return MsmContext(bases_affine).msm(scalars)
