"""Variable-base MSM on device: sort-free Pippenger over the limb G1 kernels.

Device replacement for `ark-ec`'s rayon Pippenger as the reference workers
run it (/root/reference/src/worker.rs:159-185). Scalars are decomposed into
W = 256/c radix-2^c windows — c size-dependent as in standard Pippenger
(8 bits at bench scale, smaller for small MSMs) — and each window's 2^c - 1
buckets are accumulated WITHOUT any sort or data-dependent scatter pattern:

  - points are split into G groups, each group owning a private (G, 2^c)
    bucket array;
  - a lax.scan walks n/G point-batches: gather current buckets at the
    batch's digits (one per group), one G-wide vectorized Jacobian add,
    scatter back — all writes in a step hit distinct rows, so the scan is
    race-free by construction;
  - group bucket-planes then fold sequentially with a scan whose body is a
    single (24, W, 2^c)-shaped Jacobian add — the SAME body the mesh
    version reuses to fold planes across devices, so XLA's computation
    deduplication compiles it once;
  - the remaining O(W * 2^c) tail (running-sum bucket aggregation,
    2^(c*w) window weighting, final window sum) runs as two more
    static-shape scans with no data-dependent indexing at all (see
    `finish`).

This keeps the optimal ~n adds/window of Pippenger while the whole MSM
compiles exactly THREE large Jacobian-add bodies regardless of n — XLA
compile time (the round-1 multichip-gate killer: >8 min for a 16-point
mesh MSM) is O(1) in both n and the number of reduction phases — and every
memory access is regular.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..constants import FQ_MONT_R, Q_MOD, R_MOD, FR_LIMBS, FQ_LIMBS
from . import curve_jax as CJ
from . import field_jax as FJ
from .field_jax import FR
from .limbs import ints_to_limbs, limbs_to_int
from .. import curve as C

SCALAR_BITS = 256


def window_bits(n):
    """Pippenger window size for an n-point MSM, restricted to divisors of
    the 16-bit limb width so digit extraction never crosses a limb.

    Standard size-dependent choice (ark picks ~ln n + 2): small inputs get
    small windows so the O(windows * 2^c) bucket-plane tail does not dwarf
    the O(n * windows) accumulation — this is also what keeps the tiny-shape
    multichip dry-run fast, where 8-bit windows would spend minutes adding
    planes of infinities."""
    if n >= 4096:
        return 8
    if n >= 64:
        return 4
    if n >= 8:
        return 2
    return 1


def _group_size(n):
    g = 512
    while g > 1 and (n % g != 0 or n // g < 2):
        g //= 2
    return g


def _bucket_scan(px, py, pz, digits, group, n_buckets):
    """One window's private-group bucket accumulation.

    px/py/pz: (24, n); digits: (n,) uint32 < n_buckets. Returns
    ((24, group, n_buckets),)*3 with group-g bucket b = sum of g's points
    whose digit == b (bucket 0 included but ignored downstream).
    """
    n = px.shape[1]
    steps = n // group
    garange = jnp.arange(group)

    def to_scan(a):  # (24, n) -> (steps, 24, group)
        return a.reshape(FQ_LIMBS, group, steps).transpose(2, 0, 1)

    xs = (to_scan(px), to_scan(py), to_scan(pz),
          digits.reshape(group, steps).T)

    # varying-zero: under shard_map the scan carry must inherit the inputs'
    # varying-manual-axes tag; adding a data-derived 0 does exactly that
    # (and constant-folds away otherwise)
    vz = pz.ravel()[0] & 0
    bx, by, bz = (b + vz for b in CJ.pt_inf((group, n_buckets)))

    def step(carry, x):
        bx, by, bz = carry
        sx, sy, sz, dg = x
        cur = (bx[:, garange, dg], by[:, garange, dg], bz[:, garange, dg])
        nx, ny, nz = CJ.jac_add(cur, (sx, sy, sz))
        return (bx.at[:, garange, dg].set(nx),
                by.at[:, garange, dg].set(ny),
                bz.at[:, garange, dg].set(nz)), None

    (bx, by, bz), _ = lax.scan(step, (bx, by, bz), xs)
    return bx, by, bz


def fold_planes(bx, by, bz):
    """(K, 24, W, B) bucket planes -> (24, W, B) bucketwise sum.

    Used for both the group fold and the mesh cross-device fold: the scan
    body is identical in both calls, so XLA compiles it once per program.
    """
    vz = bz.ravel()[0] & 0  # varying-zero, see _bucket_scan
    init = tuple(b + vz for b in CJ.pt_inf(bz.shape[2:]))

    def red(acc, plane):
        return CJ.jac_add(acc, plane), None

    acc, _ = lax.scan(red, init, (bx, by, bz))
    return acc


# --- finish tail -------------------------------------------------------------

def finish(bx, by, bz):
    """(24, W, B) folded buckets -> total point ((24,),)*3.

    Three phases, all static-shape scans with NO gather/scatter ops (this
    XLA:CPU build expands scatters into per-index buffer updates, which
    made an indexed-machine variant of this tail pathologically slow):

      1. running-sum bucket aggregation: scan over bucket columns B-1..1
         (+ one infinity flush column), carry (run_w, acc_w) stacked on a
         lane axis so each step is ONE (24, W, 2) Jacobian add —
         pipelined:  acc += run ; run += bucket[:, b]  per step.
      2+3. window weighting and final sum in ONE scan of (shift, mask)
         steps on (24, W): `shift=0` steps double the masked windows
         (acc_w ends as 2^(c*w) * A_w), `shift=h` steps add acc[w+h] into
         acc[w] for w < h (pairwise tree); the total lands in lane 0.
    """
    wins, buckets = bz.shape[1], bz.shape[2]
    c = SCALAR_BITS // wins
    assert buckets == 1 << c, (wins, buckets)
    vz = bz.ravel()[0] & 0  # varying-zero, see _bucket_scan
    inf_w = tuple(x + vz for x in CJ.pt_inf((wins,)))

    # phase 1: bucket columns b = B-1 .. 1, then one infinity flush column
    def col_xs(a):  # (24, W, B) -> (B, 24, W): columns B-1..1 + inf
        cols = a[:, :, 1:][:, :, ::-1].transpose(2, 0, 1)
        return cols

    xs = tuple(jnp.concatenate([col_xs(a), i[None, :, :]], axis=0)
               for a, i in zip((bx, by, bz), inf_w))

    def agg(carry, x):
        # carry: ((24, W, 2),)*3 with lane 0 = run, lane 1 = acc
        left = tuple(v for v in carry)
        right = tuple(jnp.stack([xi, v[:, :, 0]], axis=2)
                      for xi, v in zip(x, left))
        out = CJ.jac_add(left, right)
        return out, None

    init = tuple(jnp.stack([i, i], axis=2) for i in inf_w)
    acc2, _ = lax.scan(agg, init, xs)
    acc = tuple(v[:, :, 1] for v in acc2)  # (24, W)

    # phase 2+3: doubling ladder + pairwise tree, one (shift, mask) scan
    steps = []
    for k in range(c * (wins - 1)):
        steps.append((0, [k < c * w for w in range(wins)]))
    h = wins // 2
    while h >= 1:
        steps.append((h, [w < h for w in range(wins)]))
        h //= 2
    shifts = jnp.asarray(np.array([s for s, _ in steps], dtype=np.int32))
    masks = jnp.asarray(np.array([m for _, m in steps]))

    def weight(carry, step):
        shift, mask = step
        rolled = tuple(jnp.roll(v, -shift, axis=1) for v in carry)
        summed = CJ.jac_add(carry, rolled)
        return tuple(jnp.where(mask[None, :], s, v)
                     for s, v in zip(summed, carry)), None

    acc, _ = lax.scan(weight, acc, (shifts, masks))
    return tuple(v[:, 0] for v in acc)


def msm_pipeline(px, py, pz, digits, group):
    """Full single-device MSM: points (24, n) + digits (W, n) -> total."""
    buckets = 1 << (SCALAR_BITS // digits.shape[0])
    wb = jax.vmap(partial(_bucket_scan, group=group, n_buckets=buckets),
                  in_axes=(None, None, None, 0))(px, py, pz, digits)
    planes = tuple(x.transpose(2, 1, 0, 3) for x in wb)  # (G, 24, W, B)
    acc = fold_planes(*planes)
    return finish(*acc)


def digits_from_mont(v, c, padded_n):
    """(16, L) Montgomery Fr coefficients -> (256/c, padded_n) uint32
    digits, entirely on device (no host round-trip before a commitment)."""
    canon = FJ.from_mont(FR, v)
    if canon.shape[1] < padded_n:
        canon = jnp.pad(canon, ((0, 0), (0, padded_n - canon.shape[1])))
    per_limb = 16 // c
    mask = (1 << c) - 1
    parts = [(canon >> (c * i)) & mask for i in range(per_limb)]
    return jnp.stack(parts, axis=1).reshape(SCALAR_BITS // c, padded_n)


def digits_of_scalars(scalars, padded_n, c):
    """Host int scalars -> (256/c, padded_n) uint32 radix-2^c digits.

    c must divide 16 so every window lives inside one 16-bit limb."""
    assert 16 % c == 0
    scalars = [s % R_MOD for s in scalars]
    scalars += [0] * (padded_n - len(scalars))
    limbs = ints_to_limbs(scalars, FR_LIMBS)  # (16, n)
    per_limb = 16 // c
    mask = (1 << c) - 1
    parts = [(limbs >> (c * i)) & mask for i in range(per_limb)]
    # window order: limb0's sub-digits (low->high), then limb1's, ...
    digits = np.stack(parts, axis=1).astype(np.uint32)
    return digits.reshape(SCALAR_BITS // c, padded_n)


def points_to_device(bases_affine, pad):
    """list[(x, y) | None] + pad count -> Jacobian (24, n+pad) Montgomery."""
    xs, ys, infs = [], [], []
    for p in bases_affine:
        if p is None:
            xs.append(0)
            ys.append(0)
            infs.append(True)
        else:
            xs.append(p[0] * FQ_MONT_R % Q_MOD)
            ys.append(p[1] * FQ_MONT_R % Q_MOD)
            infs.append(False)
    xs += [0] * pad
    ys += [0] * pad
    infs += [True] * pad
    x = jnp.asarray(ints_to_limbs(xs, FQ_LIMBS))
    y = jnp.asarray(ints_to_limbs(ys, FQ_LIMBS))
    inf = jnp.asarray(np.array(infs))
    return CJ.from_affine(x, y, inf)


class DeviceCommitKey:
    """A commit key that lives on device as Jacobian Montgomery limb arrays
    (e.g. straight out of the fixed-base SRS generator) — no host affine
    normalization on the prover path. Identity padding columns (z == 0) are
    part of the key, mirroring the affine path's None-padded ck list."""

    def __init__(self, px, py, pz):
        assert px.shape == py.shape == pz.shape == (FQ_LIMBS, px.shape[1])
        self.point = (px, py, pz)

    def __len__(self):
        return self.point[0].shape[1]


class MsmContext:
    """Device-resident base set (the SRS chunk a worker holds,
    reference src/worker.rs:42-48). Reused across commitments."""

    def __init__(self, bases):
        n = len(bases)
        self.n = n
        pad = n % 2  # groups need >= 2 scan steps
        self.padded_n = n + pad
        if isinstance(bases, DeviceCommitKey):
            point = bases.point
            if pad:
                point = tuple(jnp.pad(p, ((0, 0), (0, pad))) for p in point)
            self.point = point
        else:
            self.point = points_to_device(bases, pad)
        self.group = _group_size(self.padded_n)
        self.c = window_bits(self.padded_n)
        self._fn = jax.jit(partial(msm_pipeline, group=self.group))
        self._digits_fn = jax.jit(
            partial(digits_from_mont, c=self.c, padded_n=self.padded_n))

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        assert len(scalars) <= self.n
        digits = digits_of_scalars(scalars, self.padded_n, self.c)
        px, py, pz = self.point
        tx, ty, tz = self._fn(px, py, pz, digits)
        return _jac_limbs_to_affine(tx, ty, tz)

    def msm_mont_limbs(self, h):
        """Commit a (16, L <= padded_n) Montgomery Fr coefficient handle:
        digit extraction happens on device; only the resulting group
        element returns to the host (for the transcript)."""
        assert h.shape[1] <= self.n, (h.shape, self.n)
        digits = self._digits_fn(h)
        px, py, pz = self.point
        tx, ty, tz = self._fn(px, py, pz, digits)
        return _jac_limbs_to_affine(tx, ty, tz)


def _jac_limbs_to_affine(tx, ty, tz):
    def dec(v):
        # from Montgomery: value * R^-1 mod q, done on host (single element)
        return limbs_to_int(np.asarray(v)) * CJ._MONT_R_INV % Q_MOD

    return C.g1_from_jac((dec(tx), dec(ty), dec(tz)))


def msm(bases_affine, scalars):
    """One-shot MSM (context built and discarded)."""
    return MsmContext(bases_affine).msm(scalars)
