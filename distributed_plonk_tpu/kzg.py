"""KZG polynomial commitments: setup, preprocess, proving/verifying keys.

Re-provides the jf-plonk surface consumed by the reference:
`universal_setup` / `preprocess` (/root/reference/src/dispatcher2.rs:1279-1280)
and the commit-key layout the dispatcher pads to a multiple of 32
(/root/reference/src/dispatcher2.rs:207-208).
"""

import random

from .constants import R_MOD
from . import curve as C
from . import poly as P
from .circuit import NUM_WIRE_TYPES, NUM_SELECTORS


class UniversalSrs:
    def __init__(self, powers_of_g1, g2, tau_g2):
        self.powers_of_g1 = powers_of_g1  # [G1, tau G1, tau^2 G1, ...]
        self.g2 = g2
        self.tau_g2 = tau_g2


class VerifyingKey:
    def __init__(self, domain_size, num_inputs, selector_comms, sigma_comms,
                 k, g1, g2, tau_g2):
        self.domain_size = domain_size
        self.num_inputs = num_inputs
        self.selector_comms = selector_comms
        self.sigma_comms = sigma_comms
        self.k = k
        self.g1 = g1
        self.g2 = g2
        self.tau_g2 = tau_g2


class ProvingKey:
    """ck: commit key (G1 powers, padded); selectors: 13 coefficient
    vectors; sigmas: 5 coefficient vectors.

    When built by a device backend the host coefficient lists are LAZY:
    the device handles are what the prover consumes (registered via
    backend.register_pk_polys), and materializing 18 host int lists
    (~150 MB of tunnel traffic at the 2^18 workload) only happens if an
    oracle/fleet consumer actually asks for them."""

    def __init__(self, ck, selectors, sigmas, vk, domain, lazy=None):
        self.ck = ck
        self._selectors = selectors
        self._sigmas = sigmas
        self._lazy = lazy  # () -> (selector_lists, sigma_lists)
        self.vk = vk
        self.domain = domain

    def _materialize(self):
        if self._selectors is None:
            self._selectors, self._sigmas = self._lazy()
            self._lazy = None  # release the captured backend/device handles

    @property
    def selectors(self):
        self._materialize()
        return self._selectors

    @property
    def sigmas(self):
        self._materialize()
        return self._sigmas

    @property
    def domain_size(self):
        return self.domain.size


def _tau_powers(max_degree, rng=None, tau=None):
    if tau is None:
        rng = rng or random.Random()
        tau = rng.randrange(1, R_MOD)
    powers = []
    acc = 1
    for _ in range(max_degree + 1):
        powers.append(acc)
        acc = acc * tau % R_MOD
    return tau, powers


def universal_setup(max_degree, rng=None, tau=None):
    """Simulated trusted setup (test SRS; tau is toxic waste).

    Mirrors PlonkKzgSnark::universal_setup (reference src/dispatcher2.rs:1279).
    """
    tau, powers = _tau_powers(max_degree, rng, tau)
    # batch the scalar muls through one Pippenger-style pass per power is
    # overkill here; direct double-and-add per power (host oracle only).
    powers_of_g1 = [C.g1_mul(C.G1_GEN, p) for p in powers]
    tau_g2 = C.g2_mul(C.G2_GEN, tau)
    return UniversalSrs(powers_of_g1, C.G2_GEN, tau_g2)


class DeviceSrs:
    """SRS whose G1 powers live on device as Jacobian Montgomery limb
    arrays ((24, N),)*3 — produced by the fixed-base batch kernel, consumed
    by DeviceCommitKey/MsmContext without ever visiting the host."""

    def __init__(self, jac_powers, count, g2, tau_g2):
        self.jac_powers = jac_powers
        self.count = count
        self.g2 = g2
        self.tau_g2 = tau_g2

    def powers_affine(self):
        """Host affine list (test/oracle boundary only: one inversion per
        point on the host)."""
        from .backend import curve_jax as CJ
        return CJ.device_to_affine(self.jac_powers)


def universal_setup_device(max_degree, rng=None, tau=None):
    """Trusted setup with the [tau^i]G1 walk run as one device batch
    (backend/fixed_base.py) instead of max_degree serial host scalar muls —
    the setup-scale blocker for reference-size domains (2^18 powers,
    reference workload src/dispatcher2.rs:1219-1221)."""
    from .backend.fixed_base import g1_batch_mul

    tau, powers = _tau_powers(max_degree, rng, tau)
    jac = g1_batch_mul(powers)
    tau_g2 = C.g2_mul(C.G2_GEN, tau)
    return DeviceSrs(jac, max_degree + 1, C.G2_GEN, tau_g2)


def commit_host(ck, coeffs):
    """Host-side commitment (oracle); device path uses backend MSM."""
    assert len(coeffs) <= len(ck)
    return C.g1_msm(ck[:len(coeffs)], coeffs)


def pad_commit_key(powers, srs_size):
    """Host G1 powers -> commit key: slice to srs_size, pad to a multiple
    of 32 with the identity, as the dispatcher does (reference
    src/dispatcher2.rs:207-208) so MSM shard sizes divide evenly.

    Shared by `preprocess` and the artifact store's key deserializer
    (store/keycache.py) — both must produce the IDENTICAL layout or a
    disk-loaded proving key would commit differently than a fresh one."""
    assert len(powers) >= srs_size, "SRS too small for this circuit"
    ck = list(powers[:srs_size])
    while len(ck) % 32 != 0:
        ck.append(None)
    return ck


def preprocess(srs, circuit, backend=None):
    """Build (pk, vk) for a finalized circuit.

    Mirrors PlonkKzgSnark::preprocess (reference src/dispatcher2.rs:1280):
    selector/sigma polynomials are iFFTs of their domain evaluations;
    their commitments go into the vk (and the Fiat-Shamir transcript).

    With a backend, the 18 iFFTs and 18 commitments run on its kernels (the
    commit key of a DeviceSrs stays device-resident, never normalized to
    host affine); without one, everything runs on the host oracle.
    """
    n = circuit.n
    domain = circuit.eval_domain
    srs_size = n + 3  # degree n+2 polys (blinded z) must be committable
    if isinstance(srs, DeviceSrs):
        assert backend is not None, "DeviceSrs requires a device backend"
        assert srs.count >= srs_size, "SRS too small for this circuit"
        from .backend.msm_jax import DeviceCommitKey
        import jax.numpy as jnp
        # pad further than the reference's x32 (dispatcher2.rs:207-208):
        # x1024 keeps the MSM bucket-scan group width at its 512 maximum
        # (msm_jax._group_size needs group | n), e.g. at the 2^18+3 SRS of
        # the 50-proof workload; identity padding never changes commitments
        padded = srs_size + (-srs_size) % 1024
        px, py, pz = (p[:, :srs_size] for p in srs.jac_powers)
        if padded > srs_size:
            ext = padded - srs_size
            px, py, pz = (jnp.pad(p, ((0, 0), (0, ext))) for p in (px, py, pz))
        ck = DeviceCommitKey(px, py, pz)
    else:
        ck = pad_commit_key(srs.powers_of_g1, srs_size)

    lazy = None
    if backend is not None:
        # the 18 iFFTs run as batched launches and the 18 commitments as
        # batched MSMs over poly HANDLES (device-resident end to end) —
        # round-2's per-poly int-list path made preprocess 14x the prove
        # (266 s at 2^13, scale_2p13.json) because every selector round-
        # tripped the host; this is the reference's join_all fan-out
        # (src/dispatcher2.rs:294-321) applied to setup
        cols = list(circuit.selectors) + list(circuit.sigma_values())
        assert len(circuit.selectors) == NUM_SELECTORS
        assert len(cols) == NUM_SELECTORS + NUM_WIRE_TYPES
        if hasattr(backend, "lift_many"):
            hs = backend.lift_many(cols)
        else:
            hs = [backend.lift(col) for col in cols]
        chs = backend.ifft_many(domain, hs)
        comms = backend.commit_many_h(ck, chs)
        selector_comms = comms[:NUM_SELECTORS]
        sigma_comms = comms[NUM_SELECTORS:]
        sel_h, sig_h = chs[:NUM_SELECTORS], chs[NUM_SELECTORS:]
        selectors = sigmas = None
        lazy = lambda: ([backend.lower(h) for h in sel_h],
                        [backend.lower(h) for h in sig_h])
    else:
        selectors = [P.ifft(domain, col) for col in circuit.selectors]
        sigmas = [P.ifft(domain, col) for col in circuit.sigma_values()]
        selector_comms = [commit_host(ck, s) for s in selectors]
        sigma_comms = [commit_host(ck, s) for s in sigmas]
        assert len(selectors) == NUM_SELECTORS and len(sigmas) == NUM_WIRE_TYPES

    vk = VerifyingKey(
        domain_size=n,
        num_inputs=circuit.num_inputs,
        selector_comms=selector_comms,
        sigma_comms=sigma_comms,
        k=list(circuit.k),
        g1=C.G1_GEN,
        g2=srs.g2,
        tau_g2=srs.tau_g2,
    )
    pk = ProvingKey(ck, selectors, sigmas, vk, domain, lazy=lazy)
    if backend is not None and hasattr(backend, "register_pk_polys"):
        # seed the backend's device cache so the prover's pk_polys() does
        # not re-lift host coefficient lists it just computed on device
        backend.register_pk_polys(pk, sel_h, sig_h)
    return pk, vk
