"""The 5-round TurboPlonk prover.

Round structure and math mirror the reference's fully-distributed v2 prover
(`Prover::prove`, /root/reference/src/dispatcher2.rs:192-713); ALL
polynomial work — NTTs, MSMs, and the per-round vector math (permutation
product, quotient evaluation, blinding, linear combination, evaluation,
synthetic division) — is delegated to a pluggable backend through an opaque
poly-handle API. On the host oracle backend a handle is an int list; on the
device backend it is a device-resident Montgomery limb array that never
leaves the device between rounds — realizing the fully-offloaded round
structure the reference declared but never implemented (the 12 dead
round3*/round5* RPCs, /root/reference/src/hello_world.capnp:26-44). Only
transcript scalars (commitments, challenges, evaluations) cross the host
boundary mid-prove.

Fiat-Shamir challenge schedule (beta, gamma, alpha, zeta, v) and transcript
bytes match FakeStandardTranscript exactly.
"""

import random
import time

from .checkpoint import (_point_dec, _point_enc, dump_handle, load_handle,
                         workload_fingerprint)
from .constants import R_MOD
from .fields import fr_inv
from .poly import Domain
from .circuit import NUM_WIRE_TYPES, Q_LC, Q_MUL, Q_HASH, Q_O, Q_C, Q_ECC
from .trace import NULL_TRACER, msm_flops, ntt_flops
from .transcript import StandardTranscript


class Proof:
    def __init__(self, wires_poly_comms, prod_perm_poly_comm, split_quot_poly_comms,
                 opening_proof, shifted_opening_proof, wires_evals,
                 wire_sigma_evals, perm_next_eval):
        self.wires_poly_comms = wires_poly_comms
        self.prod_perm_poly_comm = prod_perm_poly_comm
        self.split_quot_poly_comms = split_quot_poly_comms
        self.opening_proof = opening_proof
        self.shifted_opening_proof = shifted_opening_proof
        self.wires_evals = wires_evals
        self.wire_sigma_evals = wire_sigma_evals
        self.perm_next_eval = perm_next_eval


def prove(rng, circuit, pk, backend, tracer=None, checkpoint=None):
    """Produce a TurboPlonk proof for a finalized, satisfied circuit.

    tracer: optional trace.Tracer; records per-round and per-kernel-batch
    wall-clock spans (the reference prints these ad hoc,
    /root/reference/src/dispatcher.rs:625-942).
    checkpoint: optional checkpoint.ProverCheckpoint; after each of rounds
    1-4 the inter-round state is persisted, and a prove interrupted at any
    point resumes from the last completed round, producing byte-identical
    output (the reference has no checkpointing — SURVEY.md §5)."""
    n = pk.domain_size
    domain = pk.domain
    num_wire_types = NUM_WIRE_TYPES
    quot_domain = Domain((num_wire_types + 1) * (n + 1) + 1)
    m = quot_domain.size
    ck = pk.ck
    rng = rng or random.Random()
    tr = tracer or NULL_TRACER

    transcript = StandardTranscript()
    pub_input = circuit.public_input()
    transcript.append_vk_and_pub_input(pk.vk, pub_input)

    sel_h, sigma_h = backend.pk_polys(pk)

    # checkpoint/resume bookkeeping: `start` is the first UNFINISHED round;
    # completed rounds restore their outputs from the snapshot instead of
    # recomputing, and the transcript sponge + blinder RNG rewind to the
    # snapshot point so the challenge schedule continues bit-for-bit
    start = 0
    ck_state = fp = None
    if checkpoint is not None:
        fp = workload_fingerprint(pk.vk, pub_input)
        ck_state = checkpoint.load(fp)
        if ck_state is not None:
            start = ck_state["round"]
            checkpoint.restore_into(ck_state, rng, transcript)

    def _loadh(name):
        return load_handle(backend, ck_state["arrays"][name])

    def _save(round_no, arrays, meta):
        if checkpoint is None:
            return
        with tr.span("checkpoint_save", round=round_no):
            checkpoint.save(
                round_no, fp, rng, transcript,
                {k: dump_handle(backend, h) for k, h in arrays.items()},
                meta)

    def _points(meta_val):
        return [_point_dec(v) for v in meta_val]

    # cumulative checkpoint payload: every snapshot must carry all state
    # the REMAINING rounds read (wire/perm/quotient handles + commitments
    # + challenges), since earlier snapshots are overwritten
    ck_arrays = {}
    ck_meta = {}

    # --- Round 1: wire polynomials -------------------------------------------
    # (reference src/dispatcher2.rs:293-323)
    # kernel spans carry the flops/bytes attribution model (trace.py) so
    # the merged timeline and the live MFU gauges (Metrics.observe_kernels)
    # can say where device time went, not just that it went
    if start < 1:
        with tr.span("round1"):
            with tr.span("ifft_wires", polys=num_wire_types,
                         flops=ntt_flops(n, num_wire_types),
                         data_bytes=num_wire_types * n * 32):
                # one batch call: concurrent across the fleet (join_all,
                # reference dispatcher2.rs:294-306) / one launch on device
                wire_coeffs = backend.ifft_many(domain,
                                                backend.wire_values(circuit))
                wire_polys = [backend.blind(coeffs, _rand(rng, 2), n)
                              for coeffs in wire_coeffs]
            with tr.span("commit_wires", polys=num_wire_types,
                         flops=msm_flops(n + 2, num_wire_types),
                         data_bytes=num_wire_types * (n + 2) * 32):
                wires_poly_comms = backend.commit_many_h(ck, wire_polys)
        transcript.append_commitments(b"witness_poly_comms", wires_poly_comms)
        if checkpoint is not None:
            ck_arrays.update({"wire_poly_%d" % i: h
                              for i, h in enumerate(wire_polys)})
            ck_meta["wires_poly_comms"] = [_point_enc(p)
                                           for p in wires_poly_comms]
            _save(1, ck_arrays, ck_meta)
    else:
        wire_polys = [_loadh("wire_poly_%d" % i)
                      for i in range(num_wire_types)]
        wires_poly_comms = _points(ck_state["meta"]["wires_poly_comms"])
        ck_arrays.update(
            {"wire_poly_%d" % i: h for i, h in enumerate(wire_polys)})
        ck_meta.update(ck_state["meta"])

    # --- Round 2: permutation product ----------------------------------------
    # (reference src/dispatcher2.rs:325-357)
    if start < 2:
        beta = transcript.get_and_append_challenge(b"beta")
        gamma = transcript.get_and_append_challenge(b"gamma")

        with tr.span("round2"):
            with tr.span("perm_product"):
                product_h = backend.perm_product(circuit, beta, gamma, n)
            with tr.span("ifft_perm", flops=ntt_flops(n),
                         data_bytes=n * 32):
                perm_coeffs = backend.ifft_h(domain, product_h)
            permutation_poly = backend.blind(perm_coeffs, _rand(rng, 3), n)
            with tr.span("commit_perm", flops=msm_flops(n + 3),
                         data_bytes=(n + 3) * 32):
                prod_perm_poly_comm = backend.commit_h(ck, permutation_poly)
        transcript.append_commitment(b"perm_poly_comms", prod_perm_poly_comm)
        if checkpoint is not None:
            ck_arrays["permutation_poly"] = permutation_poly
            ck_meta["beta"], ck_meta["gamma"] = hex(beta), hex(gamma)
            ck_meta["prod_perm_poly_comm"] = _point_enc(prod_perm_poly_comm)
            _save(2, ck_arrays, ck_meta)
    else:
        permutation_poly = _loadh("permutation_poly")
        ck_arrays["permutation_poly"] = permutation_poly
        beta = int(ck_meta["beta"], 16)
        gamma = int(ck_meta["gamma"], 16)
        prod_perm_poly_comm = _point_dec(ck_meta["prod_perm_poly_comm"])

    # rounds 3-5 never read the witness/permutation tables; a backend may
    # reclaim that device memory for round 3's quotient-domain working set
    release = getattr(backend, "release_circuit_tables", None)
    if release is not None:
        release(circuit)

    # --- Round 3: quotient polynomial ----------------------------------------
    # (reference src/dispatcher2.rs:360-533)
    # quotient_streamed: single-device backends fold each selector/sigma
    # coset plane into running accumulators as it is produced, so only
    # ~10 limb-packed planes are ever resident (the round-3 working set
    # was the single-chip scale ceiling); the host oracle and the mesh
    # backend (whose memory strategy is sharding) run the one-shot
    # unpacked path. Both compute identical values.
    stream = getattr(backend, "quotient_streamed", None)
    # quotient_poly_streamed: same streaming accumulation, but the final
    # pointwise combine fuses into the coset iNTT program (and the gate/
    # sigma folds into their FFT programs) — round 3 straight to the
    # quotient polynomial with no standalone O(m) passes (DPT_R3_FUSE)
    stream_poly = getattr(backend, "quotient_poly_streamed", None)
    if start >= 3:
        # the round-3 snapshot was taken AFTER the quot-comms transcript
        # absorb, so restoring it must not absorb them again
        alpha = int(ck_meta["alpha"], 16)
        split_quot_polys = [_loadh("split_quot_poly_%d" % i)
                            for i in range(num_wire_types)]
        split_quot_poly_comms = _points(ck_meta["split_quot_poly_comms"])
        ck_arrays.update({"split_quot_poly_%d" % i: h
                          for i, h in enumerate(split_quot_polys)})
    else:
        alpha = transcript.get_and_append_challenge(b"alpha")
        alpha_sq_div_n = alpha * alpha % R_MOD * fr_inv(n % R_MOD) % R_MOD
        with tr.span("round3"):
            pi_coeffs = backend.ifft_h(
                domain, backend.lift(pub_input + [0] * (n - len(pub_input))))
            quot_evals = None
            n_coset_polys = len(sel_h) + 2 * num_wire_types + 2
            if stream_poly is not None:
                with tr.span("quotient_stream_fused", m=m,
                             polys=n_coset_polys,
                             flops=ntt_flops(m, n_coset_polys + 1),
                             data_bytes=n_coset_polys * m * 32):
                    quotient_poly = stream_poly(
                        n, m, quot_domain, pk.vk.k, beta, gamma, alpha,
                        alpha_sq_div_n, sel_h, sigma_h, wire_polys,
                        permutation_poly, pi_coeffs)
            elif stream is not None:
                with tr.span("quotient_stream", m=m, polys=n_coset_polys,
                             flops=ntt_flops(m, n_coset_polys),
                             data_bytes=n_coset_polys * m * 32):
                    quot_evals = stream(
                        n, m, quot_domain, pk.vk.k, beta, gamma, alpha,
                        alpha_sq_div_n, sel_h, sigma_h, wire_polys,
                        permutation_poly, pi_coeffs)
            else:
                with tr.span("coset_ffts", polys=n_coset_polys,
                             flops=ntt_flops(m, n_coset_polys),
                             data_bytes=n_coset_polys * m * 32):
                    # the 24 coset-FFTs go out as one batch (concurrent
                    # across the fleet / one device launch;
                    # dispatcher2.rs:382-423)
                    batch = backend.coset_fft_many(
                        quot_domain,
                        list(sel_h) + list(sigma_h) + wire_polys
                        + [permutation_poly, pi_coeffs])
                    ns, nw = len(sel_h), num_wire_types
                    selectors_coset = batch[:ns]
                    sigmas_coset = batch[ns:ns + nw]
                    wires_coset = batch[ns + nw:ns + 2 * nw]
                    z_coset = batch[ns + 2 * nw]
                    pi_coset = batch[ns + 2 * nw + 1]

                with tr.span("quotient_evals", m=m):
                    quot_evals = backend.quotient(
                        n, m, quot_domain, pk.vk.k, beta, gamma, alpha,
                        alpha_sq_div_n, selectors_coset, sigmas_coset,
                        wires_coset, z_coset, pi_coset,
                    )
                    del batch, selectors_coset, sigmas_coset, wires_coset
                    del z_coset, pi_coset
            if quot_evals is not None:
                with tr.span("coset_ifft_quot", flops=ntt_flops(m),
                             data_bytes=m * 32):
                    quotient_poly = backend.coset_ifft_h(quot_domain,
                                                         quot_evals)

            expected_degree = num_wire_types * (n + 1) + 2
            assert backend.degree_is(quotient_poly, expected_degree), \
                expected_degree
            # split into num_wire_types chunks of n+2 coefficients
            # (reference src/dispatcher2.rs:511-525)
            split_quot_polys = backend.split(
                quotient_poly, n + 2, num_wire_types, expected_degree + 1)
            with tr.span("commit_quot", polys=len(split_quot_polys),
                         flops=msm_flops(n + 2, len(split_quot_polys)),
                         data_bytes=len(split_quot_polys) * (n + 2) * 32):
                split_quot_poly_comms = backend.commit_many_h(
                    ck, split_quot_polys)
        transcript.append_commitments(b"quot_poly_comms",
                                      split_quot_poly_comms)
        if checkpoint is not None:
            ck_arrays.update({"split_quot_poly_%d" % i: h
                              for i, h in enumerate(split_quot_polys)})
            ck_meta["alpha"] = hex(alpha)
            ck_meta["split_quot_poly_comms"] = [
                _point_enc(p) for p in split_quot_poly_comms]
            _save(3, ck_arrays, ck_meta)

    # --- Round 4: evaluations ------------------------------------------------
    # (reference src/dispatcher2.rs:542-561)
    if start >= 4:
        zeta = int(ck_meta["zeta"], 16)
        wires_evals = [int(v, 16) for v in ck_meta["wires_evals"]]
        wire_sigma_evals = [int(v, 16) for v in ck_meta["wire_sigma_evals"]]
        perm_next_eval = int(ck_meta["perm_next_eval"], 16)
    else:
        zeta = transcript.get_and_append_challenge(b"zeta")
        with tr.span("round4"):
            # all 10 evaluations in one backend call (one device round-trip)
            evals = backend.eval_many_h(
                [(w, zeta) for w in wire_polys]
                + [(s, zeta) for s in sigma_h[:num_wire_types - 1]]
                + [(permutation_poly, zeta * domain.group_gen % R_MOD)])
            wires_evals = evals[:num_wire_types]
            wire_sigma_evals = evals[num_wire_types:2 * num_wire_types - 1]
            perm_next_eval = evals[-1]
        transcript.append_proof_evaluations(wires_evals, wire_sigma_evals,
                                            perm_next_eval)
        if checkpoint is not None:
            ck_meta["zeta"] = hex(zeta)
            ck_meta["wires_evals"] = [hex(v) for v in wires_evals]
            ck_meta["wire_sigma_evals"] = [hex(v) for v in wire_sigma_evals]
            ck_meta["perm_next_eval"] = hex(perm_next_eval)
            _save(4, ck_arrays, ck_meta)

    # --- Round 5: linearization + openings -----------------------------------
    # (reference src/dispatcher2.rs:563-692)
    with tr.span("round5"):
        vanish_eval = (pow(zeta, n, R_MOD) - 1) % R_MOD
        with tr.span("lin_poly"):
            lin_poly = _linearization_poly(
                backend, pk, sel_h, sigma_h, n, beta, gamma, alpha, zeta,
                vanish_eval, wires_evals, wire_sigma_evals, perm_next_eval,
                permutation_poly, split_quot_polys,
            )
        v = transcript.get_and_append_challenge(b"v")

        # batched opening at zeta: lin + wires + first 4 sigmas, powers of v
        with tr.span("batch_open", flops=msm_flops(n + 2, 2),
                     data_bytes=2 * (n + 2) * 32):
            polys = [lin_poly] + wire_polys + sigma_h[:num_wire_types - 1]
            coeffs = []
            c = 1
            for _ in polys:
                coeffs.append(c)
                c = c * v % R_MOD
            batch_poly = backend.lin_comb_h(polys, coeffs)
            witness_poly = backend.synth_div_h(batch_poly, zeta)
            shifted_witness_poly = backend.synth_div_h(
                permutation_poly, zeta * domain.group_gen % R_MOD)
            opening_proof, shifted_opening_proof = backend.commit_many_h(
                ck, [witness_poly, shifted_witness_poly])

    # a finished prove must not leave a snapshot behind: a later prove()
    # pointed at the same path would silently resume at round 5 and emit a
    # byte-identical proof with REUSED blinds instead of a fresh one
    if checkpoint is not None:
        checkpoint.clear()

    return Proof(
        wires_poly_comms, prod_perm_poly_comm, split_quot_poly_comms,
        opening_proof, shifted_opening_proof,
        wires_evals, wire_sigma_evals, perm_next_eval,
    )


def _rand(rng, count):
    return [rng.randrange(R_MOD) for _ in range(count)]


class _Member:
    """One job's slice of a batched prove: its own rng, transcript,
    tracer, checkpoint, and round outputs — everything Fiat-Shamir or
    blinding touches stays strictly per member, which is what makes the
    batch byte-identical to N sequential proves."""

    def __init__(self, i, rng, ckt, tracer, checkpoint):
        self.i = i
        self.rng = rng or random.Random()
        self.ckt = ckt
        self.tr = tracer or NULL_TRACER
        self.checkpoint = checkpoint
        self.transcript = StandardTranscript()
        self.pub = ckt.public_input()
        self.fp = None
        self.ck_arrays = {}
        self.ck_meta = {}


def prove_many(rngs, circuits, pk, backend, tracers=None, checkpoints=None,
               abort_on=()):
    """N same-shape TurboPlonk proofs in LOCKSTEP, with the cross-job
    kernel launches batched: the round-1 wire iFFTs/commit MSMs, the
    round-2 permutation commits, the round-3 split-quotient commits, the
    round-4 evaluations, and the round-5 opening commits of ALL members
    each run as one batched backend call (`commit_batch` when the backend
    has it, else `commit_many_h`; `ifft_many`; `eval_many_h`) instead of
    N separate call sequences. This is the data-parallel small-job path
    of the placement scheduler (service/placement.py) — throughput scales
    in jobs per launch while each job's proof bytes stay IDENTICAL to a
    sequential `prove`, because per-job state (transcript sponge,
    blinding rng, challenges) never crosses members and every batched
    kernel computes each member's slice independently (MSM results are
    exact group elements; batch width only moves launch boundaries).

    rngs/circuits/tracers/checkpoints: parallel per-member lists (tracers
    and checkpoints optional). All circuits must share `pk`'s shape.

    Failure isolation: a member whose round-boundary control point raises
    (worker kill, timeout — anything the checkpoint guard fires) is
    dropped from the batch with its exception recorded, and the
    SURVIVORS finish unaffected; the dead member's snapshot is durable,
    so its retry resumes alone through the sequential path. Exception
    types in `abort_on` (e.g. a drain) propagate instead, aborting the
    whole batch. Members that already HAVE a snapshot are routed to the
    sequential prover up front — resume semantics stay the single-job
    contract pinned by tests/test_checkpoint.py.

    Returns (proofs, errors): per-member Proof-or-None and
    exception-or-None lists."""
    N = len(circuits)
    rngs = list(rngs)
    tracers = list(tracers) if tracers is not None else [None] * N
    checkpoints = (list(checkpoints) if checkpoints is not None
                   else [None] * N)
    n = pk.domain_size
    domain = pk.domain
    num_wire_types = NUM_WIRE_TYPES
    quot_domain = Domain((num_wire_types + 1) * (n + 1) + 1)
    m = quot_domain.size
    ck = pk.ck
    sel_h, sigma_h = backend.pk_polys(pk)
    commit_many = (getattr(backend, "commit_batch", None)
                   or backend.commit_many_h)

    proofs = [None] * N
    errors = [None] * N
    live = []
    for i in range(N):
        mb = _Member(i, rngs[i], circuits[i], tracers[i], checkpoints[i])
        if mb.checkpoint is not None and \
                getattr(mb.checkpoint, "has_snapshot", lambda: False)():
            # mid-prove state exists: resume through the sequential
            # prover, whose restore path is the pinned contract
            try:
                proofs[i] = prove(mb.rng, mb.ckt, pk, backend,
                                  tracer=mb.tr, checkpoint=mb.checkpoint)
            except abort_on:
                raise
            except Exception as e:
                errors[i] = e
            continue
        mb.transcript.append_vk_and_pub_input(pk.vk, mb.pub)
        if mb.checkpoint is not None:
            mb.fp = workload_fingerprint(pk.vk, mb.pub)
            # round-0 control point, parity with prove(): loading the
            # (absent) snapshot runs the guard's pre-round check — a
            # kill/drain armed at round 0 fires for batch members too
            try:
                mb.checkpoint.load(mb.fp)
            except abort_on:
                raise
            except Exception as e:
                errors[i] = e
                continue
        live.append(mb)

    def each_live(fn):
        """fn(member) for every live member; a raising member is failed
        and dropped (abort_on propagates — the whole batch stops)."""
        nonlocal live
        kept = []
        for mb in live:
            try:
                fn(mb)
            except abort_on:
                raise
            except Exception as e:  # member-local failure, batch survives
                errors[mb.i] = e
                continue
            kept.append(mb)
        live = kept

    def member_save(mb, round_no):
        if mb.checkpoint is None:
            return
        with mb.tr.span("checkpoint_save", round=round_no):
            mb.checkpoint.save(
                round_no, mb.fp, mb.rng, mb.transcript,
                {k: dump_handle(backend, h)
                 for k, h in mb.ck_arrays.items()},
                mb.ck_meta)

    def mark_round(name, wall0, dur):
        # every member's timeline shows the batch round it rode in (the
        # launches are shared, so the span IS each job's wall time)
        for mb in live:
            mb.tr.add_event(name, ts=wall0, dur_s=dur,
                            batched_jobs=len(live))

    # --- Round 1: wire polynomials (one iFFT + one commit launch set) -------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        all_wires = []
        for mb in live:
            all_wires.extend(backend.wire_values(mb.ckt))
        coeffs = backend.ifft_many(domain, all_wires)
        polys = []
        for j, mb in enumerate(live):
            cs = coeffs[num_wire_types * j:num_wire_types * (j + 1)]
            mb.wire_polys = [backend.blind(c, _rand(mb.rng, 2), n)
                             for c in cs]
            polys.extend(mb.wire_polys)
        comms = commit_many(ck, polys)
        for j, mb in enumerate(live):
            mb.wires_poly_comms = \
                comms[num_wire_types * j:num_wire_types * (j + 1)]

        def r1(mb):
            mb.transcript.append_commitments(b"witness_poly_comms",
                                             mb.wires_poly_comms)
            if mb.checkpoint is not None:
                mb.ck_arrays.update({"wire_poly_%d" % i: h
                                     for i, h in enumerate(mb.wire_polys)})
                mb.ck_meta["wires_poly_comms"] = [
                    _point_enc(p) for p in mb.wires_poly_comms]
            member_save(mb, 1)
        each_live(r1)
        mark_round("round1", w0, time.perf_counter() - p0)

    # --- Round 2: permutation product ---------------------------------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r2a(mb):
            mb.beta = mb.transcript.get_and_append_challenge(b"beta")
            mb.gamma = mb.transcript.get_and_append_challenge(b"gamma")
            mb.product_h = backend.perm_product(mb.ckt, mb.beta, mb.gamma, n)
        each_live(r2a)
    if live:
        prods = backend.ifft_many(domain, [mb.product_h for mb in live])
        for mb, pc in zip(live, prods):
            mb.perm_coeffs = pc

        def r2b(mb):
            mb.permutation_poly = backend.blind(mb.perm_coeffs,
                                                _rand(mb.rng, 3), n)
        each_live(r2b)
    if live:
        comms = commit_many(ck, [mb.permutation_poly for mb in live])
        for mb, c in zip(live, comms):
            mb.prod_perm_poly_comm = c

        def r2c(mb):
            mb.transcript.append_commitment(b"perm_poly_comms",
                                            mb.prod_perm_poly_comm)
            if mb.checkpoint is not None:
                mb.ck_arrays["permutation_poly"] = mb.permutation_poly
                mb.ck_meta["beta"] = hex(mb.beta)
                mb.ck_meta["gamma"] = hex(mb.gamma)
                mb.ck_meta["prod_perm_poly_comm"] = \
                    _point_enc(mb.prod_perm_poly_comm)
            member_save(mb, 2)
        each_live(r2c)
        mark_round("round2", w0, time.perf_counter() - p0)

    release = getattr(backend, "release_circuit_tables", None)
    if release is not None:
        for mb in live:
            release(mb.ckt)

    # --- Round 3: quotient polynomial (per-member pipeline, one commit) -----
    w0, p0 = time.time(), time.perf_counter()
    if live:
        pis = backend.ifft_many(
            domain, [backend.lift(mb.pub + [0] * (n - len(mb.pub)))
                     for mb in live])
        for mb, pi in zip(live, pis):
            mb.pi_coeffs = pi
        stream = getattr(backend, "quotient_streamed", None)
        stream_poly = getattr(backend, "quotient_poly_streamed", None)

        def r3(mb):
            mb.alpha = mb.transcript.get_and_append_challenge(b"alpha")
            asdn = (mb.alpha * mb.alpha % R_MOD
                    * fr_inv(n % R_MOD) % R_MOD)
            if stream_poly is not None:
                quotient_poly = stream_poly(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, sel_h, sigma_h, mb.wire_polys,
                    mb.permutation_poly, mb.pi_coeffs)
            elif stream is not None:
                quot_evals = stream(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, sel_h, sigma_h, mb.wire_polys,
                    mb.permutation_poly, mb.pi_coeffs)
                quotient_poly = backend.coset_ifft_h(quot_domain,
                                                     quot_evals)
            else:
                batch = backend.coset_fft_many(
                    quot_domain,
                    list(sel_h) + list(sigma_h) + mb.wire_polys
                    + [mb.permutation_poly, mb.pi_coeffs])
                ns, nw = len(sel_h), num_wire_types
                quot_evals = backend.quotient(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, batch[:ns], batch[ns:ns + nw],
                    batch[ns + nw:ns + 2 * nw], batch[ns + 2 * nw],
                    batch[ns + 2 * nw + 1])
                quotient_poly = backend.coset_ifft_h(quot_domain,
                                                     quot_evals)
            expected_degree = num_wire_types * (n + 1) + 2
            assert backend.degree_is(quotient_poly, expected_degree), \
                expected_degree
            mb.split_quot_polys = backend.split(
                quotient_poly, n + 2, num_wire_types, expected_degree + 1)
        each_live(r3)
    if live:
        comms = commit_many(ck, [h for mb in live
                                 for h in mb.split_quot_polys])
        for j, mb in enumerate(live):
            mb.split_quot_poly_comms = \
                comms[num_wire_types * j:num_wire_types * (j + 1)]

        def r3b(mb):
            mb.transcript.append_commitments(b"quot_poly_comms",
                                             mb.split_quot_poly_comms)
            if mb.checkpoint is not None:
                mb.ck_arrays.update({
                    "split_quot_poly_%d" % i: h
                    for i, h in enumerate(mb.split_quot_polys)})
                mb.ck_meta["alpha"] = hex(mb.alpha)
                mb.ck_meta["split_quot_poly_comms"] = [
                    _point_enc(p) for p in mb.split_quot_poly_comms]
            member_save(mb, 3)
        each_live(r3b)
        mark_round("round3", w0, time.perf_counter() - p0)

    # --- Round 4: evaluations (one launch across all members) ---------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r4a(mb):
            mb.zeta = mb.transcript.get_and_append_challenge(b"zeta")
        each_live(r4a)
    if live:
        pairs = []
        for mb in live:
            pairs.extend(
                [(w, mb.zeta) for w in mb.wire_polys]
                + [(s, mb.zeta) for s in sigma_h[:num_wire_types - 1]]
                + [(mb.permutation_poly,
                    mb.zeta * domain.group_gen % R_MOD)])
        evals = backend.eval_many_h(pairs)
        per = 2 * num_wire_types  # 5 wires + 4 sigmas + z_next
        for j, mb in enumerate(live):
            ev = evals[per * j:per * (j + 1)]
            mb.wires_evals = ev[:num_wire_types]
            mb.wire_sigma_evals = ev[num_wire_types:2 * num_wire_types - 1]
            mb.perm_next_eval = ev[-1]

        def r4b(mb):
            mb.transcript.append_proof_evaluations(
                mb.wires_evals, mb.wire_sigma_evals, mb.perm_next_eval)
            if mb.checkpoint is not None:
                mb.ck_meta["zeta"] = hex(mb.zeta)
                mb.ck_meta["wires_evals"] = [hex(v) for v in mb.wires_evals]
                mb.ck_meta["wire_sigma_evals"] = [
                    hex(v) for v in mb.wire_sigma_evals]
                mb.ck_meta["perm_next_eval"] = hex(mb.perm_next_eval)
            member_save(mb, 4)
        each_live(r4b)
        mark_round("round4", w0, time.perf_counter() - p0)

    # --- Round 5: linearization + openings (one commit launch) --------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r5a(mb):
            vanish_eval = (pow(mb.zeta, n, R_MOD) - 1) % R_MOD
            lin_poly = _linearization_poly(
                backend, pk, sel_h, sigma_h, n, mb.beta, mb.gamma,
                mb.alpha, mb.zeta, vanish_eval, mb.wires_evals,
                mb.wire_sigma_evals, mb.perm_next_eval,
                mb.permutation_poly, mb.split_quot_polys)
            v = mb.transcript.get_and_append_challenge(b"v")
            polys = ([lin_poly] + mb.wire_polys
                     + sigma_h[:num_wire_types - 1])
            coeffs = []
            c = 1
            for _ in polys:
                coeffs.append(c)
                c = c * v % R_MOD
            batch_poly = backend.lin_comb_h(polys, coeffs)
            mb.witness_poly = backend.synth_div_h(batch_poly, mb.zeta)
            mb.shifted_witness_poly = backend.synth_div_h(
                mb.permutation_poly, mb.zeta * domain.group_gen % R_MOD)
        each_live(r5a)
    if live:
        comms = commit_many(ck, [h for mb in live
                                 for h in (mb.witness_poly,
                                           mb.shifted_witness_poly)])
        for j, mb in enumerate(live):
            mb.opening_proof = comms[2 * j]
            mb.shifted_opening_proof = comms[2 * j + 1]

        def r5b(mb):
            if mb.checkpoint is not None:
                mb.checkpoint.clear()
            proofs[mb.i] = Proof(
                mb.wires_poly_comms, mb.prod_perm_poly_comm,
                mb.split_quot_poly_comms, mb.opening_proof,
                mb.shifted_opening_proof, mb.wires_evals,
                mb.wire_sigma_evals, mb.perm_next_eval)
        each_live(r5b)
        mark_round("round5", w0, time.perf_counter() - p0)

    return proofs, errors


def _linearization_poly(backend, pk, sel_h, sigma_h, n, beta, gamma, alpha,
                        zeta, vanish_eval, wires_evals, wire_sigma_evals,
                        perm_next_eval, permutation_poly, split_quot_polys):
    """lin_poly assembly (reference src/dispatcher2.rs:565-633): all scalar
    coefficients computed on host, one backend linear combination."""
    a, b, c, d, e = wires_evals
    ab = a * b % R_MOD
    cd = c * d % R_MOD

    polys = []
    coeffs = []

    def term(h, cf):
        polys.append(h)
        coeffs.append(cf % R_MOD)

    term(sel_h[Q_LC], a)
    term(sel_h[Q_LC + 1], b)
    term(sel_h[Q_LC + 2], c)
    term(sel_h[Q_LC + 3], d)
    term(sel_h[Q_MUL], ab)
    term(sel_h[Q_MUL + 1], cd)
    term(sel_h[Q_HASH], pow(a, 5, R_MOD))
    term(sel_h[Q_HASH + 1], pow(b, 5, R_MOD))
    term(sel_h[Q_HASH + 2], pow(c, 5, R_MOD))
    term(sel_h[Q_HASH + 3], pow(d, 5, R_MOD))
    term(sel_h[Q_ECC], ab * cd % R_MOD * e % R_MOD)
    term(sel_h[Q_O], -e)
    term(sel_h[Q_C], 1)

    lagrange_1_eval = vanish_eval * fr_inv(
        n % R_MOD * ((zeta - 1) % R_MOD) % R_MOD) % R_MOD
    coeff_z = alpha
    for w_eval, ki in zip(wires_evals, pk.vk.k):
        coeff_z = coeff_z * ((w_eval + beta * ki % R_MOD * zeta + gamma) % R_MOD) % R_MOD
    coeff_z = (coeff_z + alpha * alpha % R_MOD * lagrange_1_eval) % R_MOD
    term(permutation_poly, coeff_z)

    coeff_sigma = alpha * beta % R_MOD * perm_next_eval % R_MOD
    for w_eval, s_eval in zip(wires_evals[:NUM_WIRE_TYPES - 1], wire_sigma_evals):
        coeff_sigma = coeff_sigma * ((w_eval + beta * s_eval + gamma) % R_MOD) % R_MOD
    term(sigma_h[NUM_WIRE_TYPES - 1], -coeff_sigma)

    zeta_np2 = (vanish_eval + 1) * zeta % R_MOD * zeta % R_MOD
    cf = (-vanish_eval) % R_MOD
    for poly in split_quot_polys:
        term(poly, cf)
        cf = cf * zeta_np2 % R_MOD

    return backend.lin_comb_h(polys, coeffs)
