"""The 5-round TurboPlonk prover.

Round structure and math mirror the reference's fully-distributed v2 prover
(`Prover::prove`, /root/reference/src/dispatcher2.rs:192-713); ALL
polynomial work — NTTs, MSMs, and the per-round vector math (permutation
product, quotient evaluation, blinding, linear combination, evaluation,
synthetic division) — is delegated to a pluggable backend through an opaque
poly-handle API. On the host oracle backend a handle is an int list; on the
device backend it is a device-resident Montgomery limb array that never
leaves the device between rounds — realizing the fully-offloaded round
structure the reference declared but never implemented (the 12 dead
round3*/round5* RPCs, /root/reference/src/hello_world.capnp:26-44). Only
transcript scalars (commitments, challenges, evaluations) cross the host
boundary mid-prove.

Fiat-Shamir challenge schedule (beta, gamma, alpha, zeta, v) and transcript
bytes match FakeStandardTranscript exactly.

Each round is factored into an explicit STAGE with a device-launch half
(challenge derivation, host vector math, and the round's commit/eval
dispatch — returns an unforced pending) and a host-finalize half (forces
the pending, absorbs the results into the member's transcript, persists
the round checkpoint). Three drivers share the stages:

  * `prove`          — one job, stages run back-to-back (the reference's
                       sequential round loop).
  * `prove_many`     — N same-shape jobs in LOCKSTEP with cross-job
                       launches batched (PR 11).
  * `prove_pipelined`— N independent jobs in a SOFTWARE PIPELINE over the
                       rounds: up to DPT_PIPELINE_DEPTH members in flight,
                       so job B's round-1 commit MSMs are dispatched while
                       job A's round-2 transcript hashing and checkpoint
                       fsync run on host. The per-round checkpoint
                       boundaries are the stage latches.

All three produce byte-identical proofs for the same (rng, circuit, pk):
everything Fiat-Shamir or blinding touches is per-member state that never
crosses members, and pipelining only moves WHEN a launch happens, never
what it computes.
"""

import os
import random
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from .checkpoint import (_point_dec, _point_enc, dump_handle, load_handle,
                         workload_fingerprint)
from .constants import R_MOD
from .fields import fr_inv
from .poly import Domain
from .circuit import NUM_WIRE_TYPES, Q_LC, Q_MUL, Q_HASH, Q_O, Q_C, Q_ECC
from .trace import NULL_TRACER, msm_flops, ntt_flops
from .transcript import StandardTranscript

# DPT_PIPELINE=0 is the bit-parity escape hatch: prove_pipelined degrades
# to a plain sequential prove loop and the worker pool stops coalescing.
# DPT_PIPELINE_DEPTH bounds in-flight members per pipelined prove. Module
# attributes (not call-time getenv) so tests and operators can flip them
# per-process, same idiom as service/placement.py's knobs.
PIPELINE = os.environ.get("DPT_PIPELINE", "1") != "0"
PIPELINE_DEPTH = max(1, int(os.environ.get("DPT_PIPELINE_DEPTH", "4")))


class Proof:
    def __init__(self, wires_poly_comms, prod_perm_poly_comm, split_quot_poly_comms,
                 opening_proof, shifted_opening_proof, wires_evals,
                 wire_sigma_evals, perm_next_eval):
        self.wires_poly_comms = wires_poly_comms
        self.prod_perm_poly_comm = prod_perm_poly_comm
        self.split_quot_poly_comms = split_quot_poly_comms
        self.opening_proof = opening_proof
        self.shifted_opening_proof = shifted_opening_proof
        self.wires_evals = wires_evals
        self.wire_sigma_evals = wire_sigma_evals
        self.perm_next_eval = perm_next_eval


def _rand(rng, count):
    return [rng.randrange(R_MOD) for _ in range(count)]


# -- pendings: what a stage's launch half hands its finalize half -------------

class _Ready:
    """Already-computed stage result (sync backends, or device work the
    launch half had to block on anyway). force() is free."""

    __slots__ = ("_values",)

    def __init__(self, values):
        self._values = values

    def force(self):
        return self._values


class _KernelPending:
    """A dispatched-but-unforced device result. force() blocks until the
    device delivers, then records a `kernels/<name>` trace event covering
    dispatch→force with the flops/bytes attribution the sync path carries
    on its kernel span — the `kernels/` prefix keeps these events out of
    Tracer.totals(depth=1) round accounting (they overlap other members'
    rounds under the pipeline, so adding them to per-round wall time would
    double-count), while Metrics.observe_kernels still folds them into the
    same per-stage MFU gauges via the last path segment."""

    __slots__ = ("_force", "_tr", "_name", "_attrs", "_w0", "_p0")

    def __init__(self, force, tr, name, **attrs):
        self._force = force
        self._tr = tr
        self._name = name
        self._attrs = attrs
        self._w0 = time.time()
        self._p0 = time.perf_counter()

    def force(self):
        values = self._force()
        self._tr.add_event("kernels/" + self._name, ts=self._w0,
                           dur_s=time.perf_counter() - self._p0,
                           **self._attrs)
        return values


class _ProveCtx:
    """Read-only per-(pk, backend) state shared by the round stages:
    domains, the proving key's selector/sigma handles, and the backend's
    optional capability hooks. One instance serves any number of members
    (sequential, lockstep, or pipelined); nothing here is written after
    construction, so stages running on different threads share it freely."""

    def __init__(self, pk, backend):
        self.pk = pk
        self.backend = backend
        self.n = pk.domain_size
        self.domain = pk.domain
        self.nw = NUM_WIRE_TYPES
        self.quot_domain = Domain((self.nw + 1) * (self.n + 1) + 1)
        self.m = self.quot_domain.size
        self.ck = pk.ck
        self.sel_h, self.sigma_h = backend.pk_polys(pk)
        self.release = getattr(backend, "release_circuit_tables", None)
        # quotient_streamed: single-device backends fold each selector/
        # sigma coset plane into running accumulators as it is produced,
        # so only ~10 limb-packed planes are ever resident (the round-3
        # working set was the single-chip scale ceiling); the host oracle
        # and the mesh backend (whose memory strategy is sharding) run the
        # one-shot unpacked path. Both compute identical values.
        self.stream = getattr(backend, "quotient_streamed", None)
        # quotient_poly_streamed: same streaming accumulation, but the
        # final pointwise combine fuses into the coset iNTT program —
        # round 3 straight to the quotient polynomial with no standalone
        # O(m) passes (DPT_R3_FUSE)
        self.stream_poly = getattr(backend, "quotient_poly_streamed", None)
        self.commit_async = getattr(backend, "commit_many_async", None)
        self.eval_async = getattr(backend, "eval_many_async", None)


class _Member:
    """One job's slice of a batched or pipelined prove: its own rng,
    transcript, tracer, checkpoint, and round outputs — everything
    Fiat-Shamir or blinding touches stays strictly per member, which is
    what makes both drivers byte-identical to N sequential proves."""

    def __init__(self, i, rng, ckt, tracer, checkpoint):
        self.i = i
        self.rng = rng or random.Random()
        self.ckt = ckt
        self.tr = tracer or NULL_TRACER
        self.checkpoint = checkpoint
        self.transcript = StandardTranscript()
        self.pub = ckt.public_input()
        self.fp = None
        self.ck_arrays = {}
        self.ck_meta = {}


def _save_member(cx, mb, round_no):
    """THE round-boundary checkpoint latch — the one shared implementation
    (sequential, lockstep, and pipelined drivers all land here), so the
    snapshot payload can never drift between paths. Every guard control
    point (kill/drain/TTL check, journal ROUND record, fault injection)
    fires inside checkpoint.save's subclass hooks, so pipelined members
    still hit them at their OWN stage boundaries."""
    if mb.checkpoint is None:
        return
    with mb.tr.span("checkpoint_save", round=round_no):
        mb.checkpoint.save(
            round_no, mb.fp, mb.rng, mb.transcript,
            {k: dump_handle(cx.backend, h) for k, h in mb.ck_arrays.items()},
            mb.ck_meta)


def _loadh(cx, ck_state, name):
    return load_handle(cx.backend, ck_state["arrays"][name])


def _points(meta_val):
    return [_point_dec(v) for v in meta_val]


def _dispatch_commit(cx, mb, hs, name, span_attrs):
    """Dispatch the round's commit MSMs over `hs`. Async-capable backends
    enqueue the launches and return an unforced pending (the member's
    host-finalize forces it — that is the pipeline overlap window);
    backends without async dispatch compute inline under the same kernel
    span the sequential prover always recorded, so the host-oracle and
    mesh trace/MFU attribution is unchanged. `span_attrs` carries the
    flops/bytes model: on the kernel span for the sync path, moved onto
    the force-side `kernels/<name>` event for the async path."""
    if cx.commit_async is not None:
        lite = {k: v for k, v in span_attrs.items()
                if k not in ("flops", "data_bytes")}
        with mb.tr.span(name, **lite):
            dev = cx.commit_async(cx.ck, hs)
        attrs = {k: span_attrs[k] for k in ("flops", "data_bytes")
                 if k in span_attrs}
        return _KernelPending(dev.force, mb.tr, name, **attrs)
    with mb.tr.span(name, **span_attrs):
        return _Ready(cx.backend.commit_many_h(cx.ck, hs))


def _dispatch_evals(cx, mb, pairs):
    """Round-4 evaluation dispatch; same contract as _dispatch_commit."""
    if cx.eval_async is not None:
        dev = cx.eval_async(pairs)
        return _KernelPending(dev.force, mb.tr, "eval_many")
    return _Ready(cx.backend.eval_many_h(pairs))


# -- the five round stages ----------------------------------------------------
# Each launch half runs challenges + host math + kernel dispatch and returns
# a pending; each finalize half forces it, absorbs into the transcript, and
# saves the round checkpoint (the stage latch). Each restore half reproduces
# the resume path from a round-`no` snapshot, bit-for-bit the pre-stage
# behavior. The cumulative checkpoint payload rule still holds: every
# snapshot carries all state the REMAINING rounds read (wire/perm/quotient
# handles + commitments + challenges), since earlier snapshots are
# overwritten.

def _launch_r1(cx, mb):
    # --- Round 1: wire polynomials (reference src/dispatcher2.rs:293-323)
    # kernel spans carry the flops/bytes attribution model (trace.py) so
    # the merged timeline and the live MFU gauges (Metrics.observe_kernels)
    # can say where device time went, not just that it went
    be, n, nw = cx.backend, cx.n, cx.nw
    with mb.tr.span("ifft_wires", polys=nw, flops=ntt_flops(n, nw),
                    data_bytes=nw * n * 32):
        # one batch call: concurrent across the fleet (join_all,
        # reference dispatcher2.rs:294-306) / one launch on device
        wire_coeffs = be.ifft_many(cx.domain, be.wire_values(mb.ckt))
        mb.wire_polys = [be.blind(coeffs, _rand(mb.rng, 2), n)
                         for coeffs in wire_coeffs]
    return _dispatch_commit(
        cx, mb, mb.wire_polys, "commit_wires",
        {"polys": nw, "flops": msm_flops(n + 2, nw),
         "data_bytes": nw * (n + 2) * 32})


def _finalize_r1(cx, mb, comms):
    mb.wires_poly_comms = list(comms)
    mb.transcript.append_commitments(b"witness_poly_comms",
                                     mb.wires_poly_comms)
    if mb.checkpoint is not None:
        mb.ck_arrays.update({"wire_poly_%d" % i: h
                             for i, h in enumerate(mb.wire_polys)})
        mb.ck_meta["wires_poly_comms"] = [_point_enc(p)
                                          for p in mb.wires_poly_comms]
    _save_member(cx, mb, 1)


def _restore_r1(cx, mb, ck_state):
    mb.wire_polys = [_loadh(cx, ck_state, "wire_poly_%d" % i)
                     for i in range(cx.nw)]
    mb.wires_poly_comms = _points(ck_state["meta"]["wires_poly_comms"])
    mb.ck_arrays.update({"wire_poly_%d" % i: h
                         for i, h in enumerate(mb.wire_polys)})
    mb.ck_meta.update(ck_state["meta"])


def _launch_r2(cx, mb):
    # --- Round 2: permutation product (reference src/dispatcher2.rs:325-357)
    be, n = cx.backend, cx.n
    mb.beta = mb.transcript.get_and_append_challenge(b"beta")
    mb.gamma = mb.transcript.get_and_append_challenge(b"gamma")
    with mb.tr.span("perm_product"):
        product_h = be.perm_product(mb.ckt, mb.beta, mb.gamma, n)
    with mb.tr.span("ifft_perm", flops=ntt_flops(n), data_bytes=n * 32):
        perm_coeffs = be.ifft_h(cx.domain, product_h)
    mb.permutation_poly = be.blind(perm_coeffs, _rand(mb.rng, 3), n)
    return _dispatch_commit(
        cx, mb, [mb.permutation_poly], "commit_perm",
        {"flops": msm_flops(n + 3), "data_bytes": (n + 3) * 32})


def _finalize_r2(cx, mb, comms):
    mb.prod_perm_poly_comm = comms[0]
    mb.transcript.append_commitment(b"perm_poly_comms",
                                    mb.prod_perm_poly_comm)
    if mb.checkpoint is not None:
        mb.ck_arrays["permutation_poly"] = mb.permutation_poly
        mb.ck_meta["beta"] = hex(mb.beta)
        mb.ck_meta["gamma"] = hex(mb.gamma)
        mb.ck_meta["prod_perm_poly_comm"] = \
            _point_enc(mb.prod_perm_poly_comm)
    _save_member(cx, mb, 2)


def _restore_r2(cx, mb, ck_state):
    mb.permutation_poly = _loadh(cx, ck_state, "permutation_poly")
    mb.ck_arrays["permutation_poly"] = mb.permutation_poly
    mb.beta = int(mb.ck_meta["beta"], 16)
    mb.gamma = int(mb.ck_meta["gamma"], 16)
    mb.prod_perm_poly_comm = _point_dec(mb.ck_meta["prod_perm_poly_comm"])


def _launch_r3(cx, mb):
    # --- Round 3: quotient polynomial (reference src/dispatcher2.rs:360-533)
    be, n, m, nw = cx.backend, cx.n, cx.m, cx.nw
    # rounds 3-5 never read the witness/permutation tables; a backend may
    # reclaim that device memory for round 3's quotient-domain working set
    if cx.release is not None:
        cx.release(mb.ckt)
    mb.alpha = mb.transcript.get_and_append_challenge(b"alpha")
    alpha_sq_div_n = mb.alpha * mb.alpha % R_MOD * fr_inv(n % R_MOD) % R_MOD
    pi_coeffs = be.ifft_h(
        cx.domain, be.lift(mb.pub + [0] * (n - len(mb.pub))))
    quot_evals = None
    n_coset_polys = len(cx.sel_h) + 2 * nw + 2
    if cx.stream_poly is not None:
        with mb.tr.span("quotient_stream_fused", m=m, polys=n_coset_polys,
                        flops=ntt_flops(m, n_coset_polys + 1),
                        data_bytes=n_coset_polys * m * 32):
            quotient_poly = cx.stream_poly(
                n, m, cx.quot_domain, cx.pk.vk.k, mb.beta, mb.gamma,
                mb.alpha, alpha_sq_div_n, cx.sel_h, cx.sigma_h,
                mb.wire_polys, mb.permutation_poly, pi_coeffs)
    elif cx.stream is not None:
        with mb.tr.span("quotient_stream", m=m, polys=n_coset_polys,
                        flops=ntt_flops(m, n_coset_polys),
                        data_bytes=n_coset_polys * m * 32):
            quot_evals = cx.stream(
                n, m, cx.quot_domain, cx.pk.vk.k, mb.beta, mb.gamma,
                mb.alpha, alpha_sq_div_n, cx.sel_h, cx.sigma_h,
                mb.wire_polys, mb.permutation_poly, pi_coeffs)
    else:
        with mb.tr.span("coset_ffts", polys=n_coset_polys,
                        flops=ntt_flops(m, n_coset_polys),
                        data_bytes=n_coset_polys * m * 32):
            # the 24 coset-FFTs go out as one batch (concurrent across
            # the fleet / one device launch; dispatcher2.rs:382-423)
            batch = be.coset_fft_many(
                cx.quot_domain,
                list(cx.sel_h) + list(cx.sigma_h) + mb.wire_polys
                + [mb.permutation_poly, pi_coeffs])
            ns = len(cx.sel_h)
            selectors_coset = batch[:ns]
            sigmas_coset = batch[ns:ns + nw]
            wires_coset = batch[ns + nw:ns + 2 * nw]
            z_coset = batch[ns + 2 * nw]
            pi_coset = batch[ns + 2 * nw + 1]
        with mb.tr.span("quotient_evals", m=m):
            quot_evals = be.quotient(
                n, m, cx.quot_domain, cx.pk.vk.k, mb.beta, mb.gamma,
                mb.alpha, alpha_sq_div_n, selectors_coset, sigmas_coset,
                wires_coset, z_coset, pi_coset,
            )
            del batch, selectors_coset, sigmas_coset, wires_coset
            del z_coset, pi_coset
    if quot_evals is not None:
        with mb.tr.span("coset_ifft_quot", flops=ntt_flops(m),
                        data_bytes=m * 32):
            quotient_poly = be.coset_ifft_h(cx.quot_domain, quot_evals)

    expected_degree = nw * (n + 1) + 2
    assert be.degree_is(quotient_poly, expected_degree), expected_degree
    # split into num_wire_types chunks of n+2 coefficients
    # (reference src/dispatcher2.rs:511-525)
    mb.split_quot_polys = be.split(quotient_poly, n + 2, nw,
                                   expected_degree + 1)
    return _dispatch_commit(
        cx, mb, mb.split_quot_polys, "commit_quot",
        {"polys": nw, "flops": msm_flops(n + 2, nw),
         "data_bytes": nw * (n + 2) * 32})


def _finalize_r3(cx, mb, comms):
    mb.split_quot_poly_comms = list(comms)
    mb.transcript.append_commitments(b"quot_poly_comms",
                                     mb.split_quot_poly_comms)
    if mb.checkpoint is not None:
        mb.ck_arrays.update({"split_quot_poly_%d" % i: h
                             for i, h in enumerate(mb.split_quot_polys)})
        mb.ck_meta["alpha"] = hex(mb.alpha)
        mb.ck_meta["split_quot_poly_comms"] = [
            _point_enc(p) for p in mb.split_quot_poly_comms]
    _save_member(cx, mb, 3)


def _restore_r3(cx, mb, ck_state):
    # the round-3 snapshot was taken AFTER the quot-comms transcript
    # absorb, so restoring it must not absorb them again
    if cx.release is not None:
        cx.release(mb.ckt)
    mb.alpha = int(mb.ck_meta["alpha"], 16)
    mb.split_quot_polys = [_loadh(cx, ck_state, "split_quot_poly_%d" % i)
                           for i in range(cx.nw)]
    mb.split_quot_poly_comms = _points(mb.ck_meta["split_quot_poly_comms"])
    mb.ck_arrays.update({"split_quot_poly_%d" % i: h
                         for i, h in enumerate(mb.split_quot_polys)})


def _launch_r4(cx, mb):
    # --- Round 4: evaluations (reference src/dispatcher2.rs:542-561)
    mb.zeta = mb.transcript.get_and_append_challenge(b"zeta")
    # all 10 evaluations in one backend call (one device round-trip)
    pairs = ([(w, mb.zeta) for w in mb.wire_polys]
             + [(s, mb.zeta) for s in cx.sigma_h[:cx.nw - 1]]
             + [(mb.permutation_poly,
                 mb.zeta * cx.domain.group_gen % R_MOD)])
    return _dispatch_evals(cx, mb, pairs)


def _finalize_r4(cx, mb, evals):
    nw = cx.nw
    mb.wires_evals = evals[:nw]
    mb.wire_sigma_evals = evals[nw:2 * nw - 1]
    mb.perm_next_eval = evals[-1]
    mb.transcript.append_proof_evaluations(
        mb.wires_evals, mb.wire_sigma_evals, mb.perm_next_eval)
    if mb.checkpoint is not None:
        mb.ck_meta["zeta"] = hex(mb.zeta)
        mb.ck_meta["wires_evals"] = [hex(v) for v in mb.wires_evals]
        mb.ck_meta["wire_sigma_evals"] = [hex(v)
                                          for v in mb.wire_sigma_evals]
        mb.ck_meta["perm_next_eval"] = hex(mb.perm_next_eval)
    _save_member(cx, mb, 4)


def _restore_r4(cx, mb, ck_state):
    mb.zeta = int(mb.ck_meta["zeta"], 16)
    mb.wires_evals = [int(v, 16) for v in mb.ck_meta["wires_evals"]]
    mb.wire_sigma_evals = [int(v, 16)
                           for v in mb.ck_meta["wire_sigma_evals"]]
    mb.perm_next_eval = int(mb.ck_meta["perm_next_eval"], 16)


def _launch_r5(cx, mb):
    # --- Round 5: linearization + openings (reference
    # src/dispatcher2.rs:563-692)
    be, n, nw = cx.backend, cx.n, cx.nw
    vanish_eval = (pow(mb.zeta, n, R_MOD) - 1) % R_MOD
    with mb.tr.span("lin_poly"):
        lin_poly = _linearization_poly(
            be, cx.pk, cx.sel_h, cx.sigma_h, n, mb.beta, mb.gamma,
            mb.alpha, mb.zeta, vanish_eval, mb.wires_evals,
            mb.wire_sigma_evals, mb.perm_next_eval, mb.permutation_poly,
            mb.split_quot_polys,
        )
    v = mb.transcript.get_and_append_challenge(b"v")
    # batched opening at zeta: lin + wires + first 4 sigmas, powers of v
    with mb.tr.span("batch_open"):
        polys = [lin_poly] + mb.wire_polys + cx.sigma_h[:nw - 1]
        coeffs = []
        c = 1
        for _ in polys:
            coeffs.append(c)
            c = c * v % R_MOD
        batch_poly = be.lin_comb_h(polys, coeffs)
        mb.witness_poly = be.synth_div_h(batch_poly, mb.zeta)
        mb.shifted_witness_poly = be.synth_div_h(
            mb.permutation_poly, mb.zeta * cx.domain.group_gen % R_MOD)
    return _dispatch_commit(
        cx, mb, [mb.witness_poly, mb.shifted_witness_poly], "commit_open",
        {"flops": msm_flops(n + 2, 2), "data_bytes": 2 * (n + 2) * 32})


def _finalize_r5(cx, mb, comms):
    mb.opening_proof, mb.shifted_opening_proof = comms
    # a finished prove must not leave a snapshot behind: a later prove()
    # pointed at the same path would silently resume at round 5 and emit a
    # byte-identical proof with REUSED blinds instead of a fresh one
    if mb.checkpoint is not None:
        mb.checkpoint.clear()
    mb.proof = Proof(
        mb.wires_poly_comms, mb.prod_perm_poly_comm,
        mb.split_quot_poly_comms, mb.opening_proof,
        mb.shifted_opening_proof, mb.wires_evals, mb.wire_sigma_evals,
        mb.perm_next_eval,
    )


class _Stage:
    """One prover round as a pipeline stage: a device-launch half (returns
    an unforced pending), a host-finalize half (forces it, absorbs into
    the member's transcript, persists the round checkpoint — the stage
    LATCH), and a restore half reproducing the resume path from a
    round-`no` snapshot (round 5 never snapshots, so it has none)."""

    __slots__ = ("no", "name", "launch", "finalize", "restore")

    def __init__(self, no, launch, finalize, restore=None):
        self.no = no
        self.name = "round%d" % no
        self.launch = launch
        self.finalize = finalize
        self.restore = restore


_STAGES = (
    _Stage(1, _launch_r1, _finalize_r1, _restore_r1),
    _Stage(2, _launch_r2, _finalize_r2, _restore_r2),
    _Stage(3, _launch_r3, _finalize_r3, _restore_r3),
    _Stage(4, _launch_r4, _finalize_r4, _restore_r4),
    _Stage(5, _launch_r5, _finalize_r5),
)


def prove(rng, circuit, pk, backend, tracer=None, checkpoint=None):
    """Produce a TurboPlonk proof for a finalized, satisfied circuit.

    tracer: optional trace.Tracer; records per-round and per-kernel-batch
    wall-clock spans (the reference prints these ad hoc,
    /root/reference/src/dispatcher.rs:625-942).
    checkpoint: optional checkpoint.ProverCheckpoint; after each of rounds
    1-4 the inter-round state is persisted, and a prove interrupted at any
    point resumes from the last completed round, producing byte-identical
    output (the reference has no checkpointing — SURVEY.md §5).

    This is the sequential stage driver: each round's launch half runs
    under its round span and is forced immediately, so the trace contract
    (roundN top-level spans, nested kernel spans with flops attribution)
    is the historical one."""
    cx = _ProveCtx(pk, backend)
    mb = _Member(0, rng, circuit, tracer, checkpoint)
    mb.transcript.append_vk_and_pub_input(pk.vk, mb.pub)

    # checkpoint/resume bookkeeping: `start` is the first UNFINISHED round;
    # completed rounds restore their outputs from the snapshot instead of
    # recomputing, and the transcript sponge + blinder RNG rewind to the
    # snapshot point so the challenge schedule continues bit-for-bit
    start = 0
    ck_state = None
    if checkpoint is not None:
        mb.fp = workload_fingerprint(pk.vk, mb.pub)
        ck_state = checkpoint.load(mb.fp)
        if ck_state is not None:
            start = ck_state["round"]
            checkpoint.restore_into(ck_state, mb.rng, mb.transcript)

    for st in _STAGES:
        if st.no <= start:
            st.restore(cx, mb, ck_state)
        else:
            with mb.tr.span(st.name):
                values = st.launch(cx, mb).force()
            st.finalize(cx, mb, values)
    return mb.proof


def prove_many(rngs, circuits, pk, backend, tracers=None, checkpoints=None,
               abort_on=()):
    """N same-shape TurboPlonk proofs in LOCKSTEP, with the cross-job
    kernel launches batched: the round-1 wire iFFTs/commit MSMs, the
    round-2 permutation commits, the round-3 split-quotient commits, the
    round-4 evaluations, and the round-5 opening commits of ALL members
    each run as one batched backend call (`commit_batch` when the backend
    has it, else `commit_many_h`; `ifft_many`; `eval_many_h`) instead of
    N separate call sequences. This is the data-parallel small-job path
    of the placement scheduler (service/placement.py) — throughput scales
    in jobs per launch while each job's proof bytes stay IDENTICAL to a
    sequential `prove`, because per-job state (transcript sponge,
    blinding rng, challenges) never crosses members and every batched
    kernel computes each member's slice independently (MSM results are
    exact group elements; batch width only moves launch boundaries).

    rngs/circuits/tracers/checkpoints: parallel per-member lists (tracers
    and checkpoints optional). All circuits must share `pk`'s shape.

    Failure isolation: a member whose round-boundary control point raises
    (worker kill, timeout — anything the checkpoint guard fires) is
    dropped from the batch with its exception recorded, and the
    SURVIVORS finish unaffected; the dead member's snapshot is durable,
    so its retry resumes alone through the sequential path. Exception
    types in `abort_on` (e.g. a drain) propagate instead, aborting the
    whole batch. Members that already HAVE a snapshot are routed to the
    sequential prover up front — resume semantics stay the single-job
    contract pinned by tests/test_checkpoint.py.

    Returns (proofs, errors): per-member Proof-or-None and
    exception-or-None lists."""
    N = len(circuits)
    rngs = list(rngs)
    tracers = list(tracers) if tracers is not None else [None] * N
    checkpoints = (list(checkpoints) if checkpoints is not None
                   else [None] * N)
    cx = _ProveCtx(pk, backend)
    n, domain, num_wire_types = cx.n, cx.domain, cx.nw
    quot_domain, m, ck = cx.quot_domain, cx.m, cx.ck
    sel_h, sigma_h = cx.sel_h, cx.sigma_h
    commit_many = (getattr(backend, "commit_batch", None)
                   or backend.commit_many_h)

    proofs = [None] * N
    errors = [None] * N
    live = []
    for i in range(N):
        mb = _Member(i, rngs[i], circuits[i], tracers[i], checkpoints[i])
        if mb.checkpoint is not None and \
                getattr(mb.checkpoint, "has_snapshot", lambda: False)():
            # mid-prove state exists: resume through the sequential
            # prover, whose restore path is the pinned contract
            try:
                proofs[i] = prove(mb.rng, mb.ckt, pk, backend,
                                  tracer=mb.tr, checkpoint=mb.checkpoint)
            except abort_on:
                raise
            except Exception as e:
                errors[i] = e
            continue
        mb.transcript.append_vk_and_pub_input(pk.vk, mb.pub)
        if mb.checkpoint is not None:
            mb.fp = workload_fingerprint(pk.vk, mb.pub)
            # round-0 control point, parity with prove(): loading the
            # (absent) snapshot runs the guard's pre-round check — a
            # kill/drain armed at round 0 fires for batch members too
            try:
                mb.checkpoint.load(mb.fp)
            except abort_on:
                raise
            except Exception as e:
                errors[i] = e
                continue
        live.append(mb)

    def each_live(fn):
        """fn(member) for every live member; a raising member is failed
        and dropped (abort_on propagates — the whole batch stops)."""
        nonlocal live
        kept = []
        for mb in live:
            try:
                fn(mb)
            except abort_on:
                raise
            except Exception as e:  # member-local failure, batch survives
                errors[mb.i] = e
                continue
            kept.append(mb)
        live = kept

    def mark_round(name, wall0, dur):
        # every member's timeline shows the batch round it rode in (the
        # launches are shared, so the span IS each job's wall time)
        for mb in live:
            mb.tr.add_event(name, ts=wall0, dur_s=dur,
                            batched_jobs=len(live))

    # --- Round 1: wire polynomials (one iFFT + one commit launch set) -------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        all_wires = []
        for mb in live:
            all_wires.extend(backend.wire_values(mb.ckt))
        coeffs = backend.ifft_many(domain, all_wires)
        polys = []
        for j, mb in enumerate(live):
            cs = coeffs[num_wire_types * j:num_wire_types * (j + 1)]
            mb.wire_polys = [backend.blind(c, _rand(mb.rng, 2), n)
                             for c in cs]
            polys.extend(mb.wire_polys)
        comms = commit_many(ck, polys)
        for j, mb in enumerate(live):
            mb.wires_poly_comms = \
                comms[num_wire_types * j:num_wire_types * (j + 1)]
        each_live(lambda mb: _finalize_r1(cx, mb, mb.wires_poly_comms))
        mark_round("round1", w0, time.perf_counter() - p0)

    # --- Round 2: permutation product ---------------------------------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r2a(mb):
            mb.beta = mb.transcript.get_and_append_challenge(b"beta")
            mb.gamma = mb.transcript.get_and_append_challenge(b"gamma")
            mb.product_h = backend.perm_product(mb.ckt, mb.beta, mb.gamma, n)
        each_live(r2a)
    if live:
        prods = backend.ifft_many(domain, [mb.product_h for mb in live])
        for mb, pc in zip(live, prods):
            mb.perm_coeffs = pc

        def r2b(mb):
            mb.permutation_poly = backend.blind(mb.perm_coeffs,
                                                _rand(mb.rng, 3), n)
        each_live(r2b)
    if live:
        comms = commit_many(ck, [mb.permutation_poly for mb in live])
        for mb, c in zip(live, comms):
            mb.prod_perm_poly_comm = c
        each_live(lambda mb: _finalize_r2(cx, mb, [mb.prod_perm_poly_comm]))
        mark_round("round2", w0, time.perf_counter() - p0)

    if cx.release is not None:
        for mb in live:
            cx.release(mb.ckt)

    # --- Round 3: quotient polynomial (per-member pipeline, one commit) -----
    w0, p0 = time.time(), time.perf_counter()
    if live:
        pis = backend.ifft_many(
            domain, [backend.lift(mb.pub + [0] * (n - len(mb.pub)))
                     for mb in live])
        for mb, pi in zip(live, pis):
            mb.pi_coeffs = pi

        def r3(mb):
            mb.alpha = mb.transcript.get_and_append_challenge(b"alpha")
            asdn = (mb.alpha * mb.alpha % R_MOD
                    * fr_inv(n % R_MOD) % R_MOD)
            if cx.stream_poly is not None:
                quotient_poly = cx.stream_poly(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, sel_h, sigma_h, mb.wire_polys,
                    mb.permutation_poly, mb.pi_coeffs)
            elif cx.stream is not None:
                quot_evals = cx.stream(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, sel_h, sigma_h, mb.wire_polys,
                    mb.permutation_poly, mb.pi_coeffs)
                quotient_poly = backend.coset_ifft_h(quot_domain,
                                                     quot_evals)
            else:
                batch = backend.coset_fft_many(
                    quot_domain,
                    list(sel_h) + list(sigma_h) + mb.wire_polys
                    + [mb.permutation_poly, mb.pi_coeffs])
                ns, nw = len(sel_h), num_wire_types
                quot_evals = backend.quotient(
                    n, m, quot_domain, pk.vk.k, mb.beta, mb.gamma,
                    mb.alpha, asdn, batch[:ns], batch[ns:ns + nw],
                    batch[ns + nw:ns + 2 * nw], batch[ns + 2 * nw],
                    batch[ns + 2 * nw + 1])
                quotient_poly = backend.coset_ifft_h(quot_domain,
                                                     quot_evals)
            expected_degree = num_wire_types * (n + 1) + 2
            assert backend.degree_is(quotient_poly, expected_degree), \
                expected_degree
            mb.split_quot_polys = backend.split(
                quotient_poly, n + 2, num_wire_types, expected_degree + 1)
        each_live(r3)
    if live:
        comms = commit_many(ck, [h for mb in live
                                 for h in mb.split_quot_polys])
        for j, mb in enumerate(live):
            mb.split_quot_poly_comms = \
                comms[num_wire_types * j:num_wire_types * (j + 1)]
        each_live(lambda mb: _finalize_r3(cx, mb, mb.split_quot_poly_comms))
        mark_round("round3", w0, time.perf_counter() - p0)

    # --- Round 4: evaluations (one launch across all members) ---------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r4a(mb):
            mb.zeta = mb.transcript.get_and_append_challenge(b"zeta")
        each_live(r4a)
    if live:
        pairs = []
        for mb in live:
            pairs.extend(
                [(w, mb.zeta) for w in mb.wire_polys]
                + [(s, mb.zeta) for s in sigma_h[:num_wire_types - 1]]
                + [(mb.permutation_poly,
                    mb.zeta * domain.group_gen % R_MOD)])
        evals = backend.eval_many_h(pairs)
        per = 2 * num_wire_types  # 5 wires + 4 sigmas + z_next
        for j, mb in enumerate(live):
            mb._evs = evals[per * j:per * (j + 1)]
        each_live(lambda mb: _finalize_r4(cx, mb, mb._evs))
        mark_round("round4", w0, time.perf_counter() - p0)

    # --- Round 5: linearization + openings (one commit launch) --------------
    w0, p0 = time.time(), time.perf_counter()
    if live:
        def r5a(mb):
            vanish_eval = (pow(mb.zeta, n, R_MOD) - 1) % R_MOD
            lin_poly = _linearization_poly(
                backend, pk, sel_h, sigma_h, n, mb.beta, mb.gamma,
                mb.alpha, mb.zeta, vanish_eval, mb.wires_evals,
                mb.wire_sigma_evals, mb.perm_next_eval,
                mb.permutation_poly, mb.split_quot_polys)
            v = mb.transcript.get_and_append_challenge(b"v")
            polys = ([lin_poly] + mb.wire_polys
                     + sigma_h[:num_wire_types - 1])
            coeffs = []
            c = 1
            for _ in polys:
                coeffs.append(c)
                c = c * v % R_MOD
            batch_poly = backend.lin_comb_h(polys, coeffs)
            mb.witness_poly = backend.synth_div_h(batch_poly, mb.zeta)
            mb.shifted_witness_poly = backend.synth_div_h(
                mb.permutation_poly, mb.zeta * domain.group_gen % R_MOD)
        each_live(r5a)
    if live:
        comms = commit_many(ck, [h for mb in live
                                 for h in (mb.witness_poly,
                                           mb.shifted_witness_poly)])
        for j, mb in enumerate(live):
            mb._open_comms = (comms[2 * j], comms[2 * j + 1])

        def r5b(mb):
            _finalize_r5(cx, mb, mb._open_comms)
            proofs[mb.i] = mb.proof
        each_live(r5b)
        mark_round("round5", w0, time.perf_counter() - p0)

    return proofs, errors


class PipelinedProver:
    """Round-pipelined driver: up to `depth` members in flight, each at
    its own stage. Launch halves run on a single-worker executor — THE
    device queue, which preserves per-member launch order and mirrors how
    an accelerator serializes dispatched work — while the driver thread
    runs host-finalize halves (transcript hashing, challenge derivation,
    checkpoint encode + fsync). A member's device results are forced only
    at its OWN finalize, so a younger member's launches keep the device
    queue full while an older member's host work runs: the round barrier
    of the lockstep path becomes a per-member stage latch.

    Byte-identity argument: each member's mutation happens either in its
    launch half (executor thread) or its finalize half (driver thread),
    and the driver never submits stage k+1 before finalize k returned —
    per-member op order is EXACTLY the sequential prover's, and no state
    crosses members. Pipelining changes only the interleaving between
    members, which no per-member state observes.

    observer: optional callable; called once per completed stage with
    {round, depth, stage_wait_s, force_wait_s, finalize_s, device_idle_s}
    — the pool turns these into the pipeline_* metrics."""

    def __init__(self, backend, depth=None, abort_on=(), observer=None):
        self.backend = backend
        self.depth = max(1, int(depth if depth is not None
                                else PIPELINE_DEPTH))
        self.abort_on = tuple(abort_on)
        self.observer = observer
        self._ctxs = {}

    def _ctx(self, pk):
        # per-pk stage context, cached so coalesced mixed-shape members
        # of the same key reuse domains + device-side pk handles
        cx = self._ctxs.get(id(pk))
        if cx is None:
            cx = self._ctxs[id(pk)] = _ProveCtx(pk, self.backend)
        return cx

    def run(self, rngs, circuits, pks, tracers, checkpoints,
            proofs, errors):
        queue = deque()
        for i, ckt in enumerate(circuits):
            mb = _Member(i, rngs[i], ckt, tracers[i], checkpoints[i])
            mb.cx = self._ctx(pks[i])
            if mb.checkpoint is not None and \
                    getattr(mb.checkpoint, "has_snapshot",
                            lambda: False)():
                # mid-prove state exists: resume through the sequential
                # prover up front, whose restore path is the pinned
                # contract — a resumed member never re-enters the pipeline
                try:
                    proofs[i] = prove(mb.rng, mb.ckt, mb.cx.pk,
                                      self.backend, tracer=mb.tr,
                                      checkpoint=mb.checkpoint)
                except self.abort_on:
                    raise
                except Exception as e:
                    errors[i] = e
                continue
            mb.transcript.append_vk_and_pub_input(mb.cx.pk.vk, mb.pub)
            if mb.checkpoint is not None:
                mb.fp = workload_fingerprint(mb.cx.pk.vk, mb.pub)
                # round-0 control point, parity with prove()
                try:
                    mb.checkpoint.load(mb.fp)
                except self.abort_on:
                    raise
                except Exception as e:
                    errors[i] = e
                    continue
            mb.stage = 0
            queue.append(mb)

        inflight = []  # admission order; [0] is the oldest member

        ex = ThreadPoolExecutor(max_workers=1)

        def submit(mb):
            st = _STAGES[mb.stage]

            def _launch():
                # the round span covers this member's launch half only;
                # its finalize half gets its own roundN_finalize span, and
                # forced device time lands on the kernels/* events — so a
                # pipelined trace never double-books overlapped wall time
                with mb.tr.span(st.name):
                    return st.launch(mb.cx, mb)
            mb._fut = ex.submit(_launch)

        try:
            while queue or inflight:
                while queue and len(inflight) < self.depth:
                    nxt = queue.popleft()
                    submit(nxt)
                    inflight.append(nxt)
                # finalize the oldest READY member (admission order breaks
                # ties): forcing only at a member's own finalize is the
                # pipeline — while this member's host work runs, the
                # executor keeps draining younger members' launches
                mb = next((m for m in inflight if m._fut.done()),
                          inflight[0])
                st = _STAGES[mb.stage]
                t0 = time.perf_counter()
                t1 = force_s = None
                try:
                    pending = mb._fut.result()
                    wait_s = time.perf_counter() - t0
                    t1 = time.perf_counter()
                    with mb.tr.span(st.name + "_finalize"):
                        values = pending.force()
                        force_s = time.perf_counter() - t1
                        st.finalize(mb.cx, mb, values)
                except self.abort_on:
                    raise
                except Exception as e:
                    # member-local failure (kill/timeout at ITS latch):
                    # record, drop, and let the rest of the pipeline run
                    errors[mb.i] = e
                    inflight.remove(mb)
                    continue
                fin_s = time.perf_counter() - t1
                if self.observer is not None:
                    self.observer({
                        "round": st.no,
                        "depth": len(inflight),
                        "stage_wait_s": wait_s,
                        "force_wait_s": force_s,
                        "finalize_s": fin_s,
                        "device_idle_s": max(0.0, fin_s - force_s),
                    })
                mb.stage += 1
                if mb.stage >= len(_STAGES):
                    proofs[mb.i] = mb.proof
                    inflight.remove(mb)
                else:
                    submit(mb)
        finally:
            # abort (drain) or crash: cancel queued launches, wait out the
            # one in flight — members park at their own last-saved latch
            ex.shutdown(wait=True, cancel_futures=True)
        return proofs, errors


def prove_pipelined(rngs, circuits, pk, backend, tracers=None,
                    checkpoints=None, abort_on=(), depth=None,
                    observer=None):
    """N TurboPlonk proofs through the round PIPELINE (PipelinedProver):
    members need not share a shape — `pk` may be one key or a per-member
    list, which is how the pool coalesces mixed small/mid traffic from
    the dispatch queue into one pipelined attempt.

    Same failure contract as prove_many: member-local exceptions are
    recorded in `errors` and the survivors finish; `abort_on` types
    propagate and every in-flight member parks at its own next stage
    latch (its last saved round checkpoint). Members that already have a
    snapshot resume through sequential `prove` up front.

    With DPT_PIPELINE=0 this degrades to a plain sequential prove loop —
    the bit-parity escape hatch (the pipeline is byte-identical anyway;
    the knob exists so an operator can excise the machinery entirely).

    Returns (proofs, errors) per-member lists."""
    N = len(circuits)
    rngs = list(rngs)
    tracers = list(tracers) if tracers is not None else [None] * N
    checkpoints = (list(checkpoints) if checkpoints is not None
                   else [None] * N)
    pks = list(pk) if isinstance(pk, (list, tuple)) else [pk] * N
    proofs = [None] * N
    errors = [None] * N
    if not PIPELINE:
        for i in range(N):
            try:
                proofs[i] = prove(rngs[i], circuits[i], pks[i], backend,
                                  tracer=tracers[i],
                                  checkpoint=checkpoints[i])
            except abort_on:
                raise
            except Exception as e:
                errors[i] = e
        return proofs, errors
    drv = PipelinedProver(backend, depth=depth, abort_on=abort_on,
                          observer=observer)
    return drv.run(rngs, circuits, pks, tracers, checkpoints,
                   proofs, errors)


def _linearization_poly(backend, pk, sel_h, sigma_h, n, beta, gamma, alpha,
                        zeta, vanish_eval, wires_evals, wire_sigma_evals,
                        perm_next_eval, permutation_poly, split_quot_polys):
    """lin_poly assembly (reference src/dispatcher2.rs:565-633): all scalar
    coefficients computed on host, one backend linear combination."""
    a, b, c, d, e = wires_evals
    ab = a * b % R_MOD
    cd = c * d % R_MOD

    polys = []
    coeffs = []

    def term(h, cf):
        polys.append(h)
        coeffs.append(cf % R_MOD)

    term(sel_h[Q_LC], a)
    term(sel_h[Q_LC + 1], b)
    term(sel_h[Q_LC + 2], c)
    term(sel_h[Q_LC + 3], d)
    term(sel_h[Q_MUL], ab)
    term(sel_h[Q_MUL + 1], cd)
    term(sel_h[Q_HASH], pow(a, 5, R_MOD))
    term(sel_h[Q_HASH + 1], pow(b, 5, R_MOD))
    term(sel_h[Q_HASH + 2], pow(c, 5, R_MOD))
    term(sel_h[Q_HASH + 3], pow(d, 5, R_MOD))
    term(sel_h[Q_ECC], ab * cd % R_MOD * e % R_MOD)
    term(sel_h[Q_O], -e)
    term(sel_h[Q_C], 1)

    lagrange_1_eval = vanish_eval * fr_inv(
        n % R_MOD * ((zeta - 1) % R_MOD) % R_MOD) % R_MOD
    coeff_z = alpha
    for w_eval, ki in zip(wires_evals, pk.vk.k):
        coeff_z = coeff_z * ((w_eval + beta * ki % R_MOD * zeta + gamma) % R_MOD) % R_MOD
    coeff_z = (coeff_z + alpha * alpha % R_MOD * lagrange_1_eval) % R_MOD
    term(permutation_poly, coeff_z)

    coeff_sigma = alpha * beta % R_MOD * perm_next_eval % R_MOD
    for w_eval, s_eval in zip(wires_evals[:NUM_WIRE_TYPES - 1], wire_sigma_evals):
        coeff_sigma = coeff_sigma * ((w_eval + beta * s_eval + gamma) % R_MOD) % R_MOD
    term(sigma_h[NUM_WIRE_TYPES - 1], -coeff_sigma)

    zeta_np2 = (vanish_eval + 1) * zeta % R_MOD * zeta % R_MOD
    cf = (-vanish_eval) % R_MOD
    for poly in split_quot_polys:
        term(poly, cf)
        cf = cf * zeta_np2 % R_MOD

    return backend.lin_comb_h(polys, coeffs)
