"""The 5-round TurboPlonk prover.

Round structure and math mirror the reference's fully-distributed v2 prover
(`Prover::prove`, /root/reference/src/dispatcher2.rs:192-713); the heavy ops
(NTT, MSM) are delegated to a pluggable backend (host oracle, single-TPU, or
sharded mesh), which plays the role of the reference's worker fleet.

Fiat-Shamir challenge schedule (beta, gamma, alpha, zeta, v) and transcript
bytes match FakeStandardTranscript exactly.
"""

import random

from .constants import R_MOD, FR_GENERATOR
from .fields import fr_inv, batch_inverse
from . import poly as P
from .poly import Domain
from .circuit import (
    GATE_WIDTH,
    NUM_WIRE_TYPES,
    Q_LC,
    Q_MUL,
    Q_HASH,
    Q_O,
    Q_C,
    Q_ECC,
)
from .transcript import StandardTranscript


class Proof:
    def __init__(self, wires_poly_comms, prod_perm_poly_comm, split_quot_poly_comms,
                 opening_proof, shifted_opening_proof, wires_evals,
                 wire_sigma_evals, perm_next_eval):
        self.wires_poly_comms = wires_poly_comms
        self.prod_perm_poly_comm = prod_perm_poly_comm
        self.split_quot_poly_comms = split_quot_poly_comms
        self.opening_proof = opening_proof
        self.shifted_opening_proof = shifted_opening_proof
        self.wires_evals = wires_evals
        self.wire_sigma_evals = wire_sigma_evals
        self.perm_next_eval = perm_next_eval


def _rand_poly(rng, degree):
    return [rng.randrange(R_MOD) for _ in range(degree + 1)]


def prove(rng, circuit, pk, backend):
    """Produce a TurboPlonk proof for a finalized, satisfied circuit."""
    n = pk.domain_size
    domain = pk.domain
    num_wire_types = NUM_WIRE_TYPES
    quot_domain = Domain((num_wire_types + 1) * (n + 1) + 1)
    m = quot_domain.size
    ck = pk.ck
    rng = rng or random.Random()

    transcript = StandardTranscript()
    pub_input = circuit.public_input()
    transcript.append_vk_and_pub_input(pk.vk, pub_input)

    # --- Round 1: wire polynomials -------------------------------------------
    # (reference src/dispatcher2.rs:293-323)
    wire_polys = []
    for i in range(num_wire_types):
        coeffs = backend.ifft(domain, circuit.wire_values(i))
        blind = P.poly_mul_vanishing(_rand_poly(rng, 1), n)
        wire_polys.append(P.poly_add(blind, coeffs))
    wires_poly_comms = [
        backend.commit(ck, _pad(poly, len(ck))) for poly in wire_polys
    ]
    transcript.append_commitments(b"witness_poly_comms", wires_poly_comms)

    # --- Round 2: permutation product ----------------------------------------
    # (reference src/dispatcher2.rs:325-357)
    beta = transcript.get_and_append_challenge(b"beta")
    gamma = transcript.get_and_append_challenge(b"gamma")

    product_vec = _permutation_product(circuit, beta, gamma, n, num_wire_types)
    perm_coeffs = backend.ifft(domain, product_vec)
    permutation_poly = P.poly_add(
        P.poly_mul_vanishing(_rand_poly(rng, 2), n), perm_coeffs
    )
    prod_perm_poly_comm = backend.commit(ck, _pad(permutation_poly, len(ck)))
    transcript.append_commitment(b"perm_poly_comms", prod_perm_poly_comm)

    # --- Round 3: quotient polynomial ----------------------------------------
    # (reference src/dispatcher2.rs:360-533)
    alpha = transcript.get_and_append_challenge(b"alpha")
    alpha_sq_div_n = alpha * alpha % R_MOD * fr_inv(n % R_MOD) % R_MOD

    selectors_coset = [backend.coset_fft(quot_domain, s) for s in pk.selectors]
    sigmas_coset = [backend.coset_fft(quot_domain, s) for s in pk.sigmas]
    wires_coset = [backend.coset_fft(quot_domain, w) for w in wire_polys]
    z_coset = backend.coset_fft(quot_domain, permutation_poly)
    pi_coeffs = backend.ifft(domain, pub_input + [0] * (n - len(pub_input)))
    pi_coset = backend.coset_fft(quot_domain, pi_coeffs)

    quot_evals = _quotient_evals(
        n, m, quot_domain, pk.vk.k, beta, gamma, alpha, alpha_sq_div_n,
        selectors_coset, sigmas_coset, wires_coset, z_coset, pi_coset,
    )
    quotient_poly = backend.coset_ifft(quot_domain, quot_evals)

    expected_degree = num_wire_types * (n + 1) + 2
    assert P.poly_degree(quotient_poly) == expected_degree, (
        P.poly_degree(quotient_poly), expected_degree)
    # split into num_wire_types chunks of n+2 coefficients
    # (reference src/dispatcher2.rs:511-525)
    split_quot_polys = [
        quotient_poly[i:i + n + 2] for i in range(0, expected_degree + 1, n + 2)
    ]
    split_quot_poly_comms = [
        backend.commit(ck, _pad(t, len(ck))) for t in split_quot_polys
    ]
    transcript.append_commitments(b"quot_poly_comms", split_quot_poly_comms)

    # --- Round 4: evaluations ------------------------------------------------
    # (reference src/dispatcher2.rs:542-561)
    zeta = transcript.get_and_append_challenge(b"zeta")
    wires_evals = [P.poly_eval(w, zeta) for w in wire_polys]
    wire_sigma_evals = [P.poly_eval(s, zeta) for s in pk.sigmas[:num_wire_types - 1]]
    perm_next_eval = P.poly_eval(permutation_poly, zeta * domain.group_gen % R_MOD)
    transcript.append_proof_evaluations(wires_evals, wire_sigma_evals, perm_next_eval)

    # --- Round 5: linearization + openings -----------------------------------
    # (reference src/dispatcher2.rs:563-692)
    vanish_eval = (pow(zeta, n, R_MOD) - 1) % R_MOD
    lin_poly = _linearization_poly(
        pk, n, beta, gamma, alpha, zeta, vanish_eval,
        wires_evals, wire_sigma_evals, perm_next_eval,
        permutation_poly, split_quot_polys,
    )
    v = transcript.get_and_append_challenge(b"v")

    # batched opening at zeta: lin + wires + first 4 sigmas, powers of v
    polys = [lin_poly] + wire_polys + pk.sigmas[:num_wire_types - 1]
    batch_poly = []
    coeff = 1
    for poly in polys:
        batch_poly = P.poly_add(batch_poly, P.poly_scale(poly, coeff))
        coeff = coeff * v % R_MOD
    witness_poly = P.synthetic_divide(batch_poly, zeta)
    opening_proof = backend.commit(ck, _pad(witness_poly, len(ck)))

    shifted_witness_poly = P.synthetic_divide(
        permutation_poly, zeta * domain.group_gen % R_MOD)
    shifted_opening_proof = backend.commit(ck, _pad(shifted_witness_poly, len(ck)))

    return Proof(
        wires_poly_comms, prod_perm_poly_comm, split_quot_poly_comms,
        opening_proof, shifted_opening_proof,
        wires_evals, wire_sigma_evals, perm_next_eval,
    )


def _pad(coeffs, size):
    assert len(coeffs) <= size
    return list(coeffs) + [0] * (size - len(coeffs))


def _permutation_product(circuit, beta, gamma, n, num_wire_types):
    """z(w^j) running product (reference src/dispatcher2.rs:330-345)."""
    product_vec = [1]
    nums = []
    dens = []
    for j in range(n - 1):
        a = 1
        b = 1
        for i in range(num_wire_types):
            wire_value = circuit.witness[circuit.wire_variables[i][j]]
            t = (wire_value + gamma) % R_MOD
            a = a * ((t + beta * circuit.extended_id_permutation[i][j]) % R_MOD) % R_MOD
            pi, pj = circuit.wire_permutation[i][j]
            b = b * ((t + beta * circuit.extended_id_permutation[pi][pj]) % R_MOD) % R_MOD
        nums.append(a)
        dens.append(b)
    den_invs = batch_inverse(dens, R_MOD)
    for j in range(n - 1):
        product_vec.append(product_vec[j] * nums[j] % R_MOD * den_invs[j] % R_MOD)
    return product_vec


def _quotient_evals(n, m, quot_domain, k, beta, gamma, alpha, alpha_sq_div_n,
                    selectors_coset, sigmas_coset, wires_coset, z_coset, pi_coset):
    """Coset evaluations of the quotient polynomial
    (reference src/dispatcher2.rs:434-504)."""
    g = FR_GENERATOR
    wq = quot_domain.group_gen
    eval_points = []
    cur = g
    for _ in range(m):
        eval_points.append(cur)
        cur = cur * wq % R_MOD
    ratio = m // n
    z_h_vals = [(pow(eval_points[i], n, R_MOD) - 1) % R_MOD for i in range(ratio)]
    z_h_inv = batch_inverse(z_h_vals, R_MOD)
    # 1/(eval_point - 1) for the L1 term
    shifted = [(e - 1) % R_MOD for e in eval_points]
    shifted_inv = batch_inverse(shifted, R_MOD)

    q_lc = selectors_coset[Q_LC:Q_LC + GATE_WIDTH]
    q_mul = selectors_coset[Q_MUL:Q_MUL + 2]
    q_hash = selectors_coset[Q_HASH:Q_HASH + GATE_WIDTH]
    q_o = selectors_coset[Q_O]
    q_c = selectors_coset[Q_C]
    q_ecc = selectors_coset[Q_ECC]

    out = []
    for i in range(m):
        a, b, c, d, e = (w[i] for w in wires_coset)
        ab = a * b % R_MOD
        cd = c * d % R_MOD
        gate = (
            q_c[i] + pi_coset[i]
            + q_lc[0][i] * a + q_lc[1][i] * b + q_lc[2][i] * c + q_lc[3][i] * d
            + q_mul[0][i] * ab + q_mul[1][i] * cd
            + q_ecc[i] * ab % R_MOD * cd % R_MOD * e
            + q_hash[0][i] * pow(a, 5, R_MOD)
            + q_hash[1][i] * pow(b, 5, R_MOD)
            + q_hash[2][i] * pow(c, 5, R_MOD)
            + q_hash[3][i] * pow(d, 5, R_MOD)
            - q_o[i] * e
        ) % R_MOD
        acc1 = z_coset[i]
        acc2 = z_coset[(i + ratio) % m]
        ep = eval_points[i]
        for j in range(NUM_WIRE_TYPES):
            t = (wires_coset[j][i] + gamma) % R_MOD
            acc1 = acc1 * ((t + k[j] * ep % R_MOD * beta) % R_MOD) % R_MOD
            acc2 = acc2 * ((t + sigmas_coset[j][i] * beta) % R_MOD) % R_MOD
        perm = alpha * (acc1 - acc2) % R_MOD
        l1_term = alpha_sq_div_n * ((z_coset[i] - 1) % R_MOD) % R_MOD * shifted_inv[i] % R_MOD
        out.append((z_h_inv[i % ratio] * ((gate + perm) % R_MOD) + l1_term) % R_MOD)
    return out


def _linearization_poly(pk, n, beta, gamma, alpha, zeta, vanish_eval,
                        wires_evals, wire_sigma_evals, perm_next_eval,
                        permutation_poly, split_quot_polys):
    """lin_poly assembly (reference src/dispatcher2.rs:565-633)."""
    a, b, c, d, e = wires_evals
    ab = a * b % R_MOD
    cd = c * d % R_MOD
    sel = pk.selectors
    gate_part = []
    terms = [
        (sel[Q_LC], a), (sel[Q_LC + 1], b), (sel[Q_LC + 2], c), (sel[Q_LC + 3], d),
        (sel[Q_MUL], ab), (sel[Q_MUL + 1], cd),
        (sel[Q_HASH], pow(a, 5, R_MOD)), (sel[Q_HASH + 1], pow(b, 5, R_MOD)),
        (sel[Q_HASH + 2], pow(c, 5, R_MOD)), (sel[Q_HASH + 3], pow(d, 5, R_MOD)),
        (sel[Q_ECC], ab * cd % R_MOD * e % R_MOD),
        (sel[Q_O], (-e) % R_MOD),
    ]
    for poly, coeff in terms:
        gate_part = P.poly_add(gate_part, P.poly_scale(poly, coeff))
    gate_part = P.poly_add(gate_part, sel[Q_C])

    lagrange_1_eval = vanish_eval * fr_inv(n % R_MOD * ((zeta - 1) % R_MOD) % R_MOD) % R_MOD
    coeff_z = alpha
    for w_eval, ki in zip(wires_evals, pk.vk.k):
        coeff_z = coeff_z * ((w_eval + beta * ki % R_MOD * zeta + gamma) % R_MOD) % R_MOD
    coeff_z = (coeff_z + alpha * alpha % R_MOD * lagrange_1_eval) % R_MOD
    z_part = P.poly_scale(permutation_poly, coeff_z)

    coeff_sigma = alpha * beta % R_MOD * perm_next_eval % R_MOD
    for w_eval, s_eval in zip(wires_evals[:NUM_WIRE_TYPES - 1], wire_sigma_evals):
        coeff_sigma = coeff_sigma * ((w_eval + beta * s_eval + gamma) % R_MOD) % R_MOD
    sigma_part = P.poly_scale(pk.sigmas[NUM_WIRE_TYPES - 1], (-coeff_sigma) % R_MOD)

    zeta_np2 = (vanish_eval + 1) * zeta % R_MOD * zeta % R_MOD
    r_quot = list(split_quot_polys[0])
    coeff = 1
    for poly in split_quot_polys[1:]:
        coeff = coeff * zeta_np2 % R_MOD
        r_quot = P.poly_add(r_quot, P.poly_scale(poly, coeff))
    quot_part = P.poly_scale(r_quot, (-vanish_eval) % R_MOD)

    lin = P.poly_add(P.poly_add(gate_part, z_part), P.poly_add(sigma_part, quot_part))
    return lin
