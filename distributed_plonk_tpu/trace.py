"""Structured per-round / per-collective tracing.

The structured upgrade of the reference's ad-hoc timing printouts
(`println!("Elapsed: {:.2?}")` around each prover round,
/root/reference/src/dispatcher.rs:625,645,678,806,827,942 — commented out
in v2, dispatcher2.rs:293-693): spans are recorded as events with
wall-clock durations and emitted as JSON, so the driver/bench can consume
per-round numbers instead of scraping stdout.

Usage:
    tracer = Tracer()
    with tracer.span("round1"):
        with tracer.span("round1/ifft", polys=5):
            ...
    print(tracer.to_json())
"""

import json
import os
import time
from contextlib import contextmanager, nullcontext

# DPT_JAX_TRACE=1: every Tracer span additionally opens a
# jax.profiler.TraceAnnotation, so spans show up on the device timeline of
# a jax.profiler capture (the SURVEY §5 device-trace replacement for the
# reference's wall-clock printouts). Off by default: annotation setup is
# not free on the hot path and tooling to view traces may be absent.
_JAX_TRACE = bool(os.environ.get("DPT_JAX_TRACE"))


def _jax_annotation(path):
    if not _JAX_TRACE:
        return nullcontext()
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(path)
    except Exception:  # pragma: no cover - profiler backend absent
        return nullcontext()


@contextmanager
def profile_to(log_dir):
    """Capture a jax.profiler device trace for the enclosed block into
    `log_dir` (viewable with tensorboard / xprof). Pairs with
    DPT_JAX_TRACE=1 so Tracer spans appear as annotations on the device
    timeline. No-ops (with a note on stderr) when tracing is unsupported
    on the platform."""
    import sys
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - tunneled platform quirks
        print(f"[trace] jax profiler unavailable: {e!r}", file=sys.stderr)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                print(f"[trace] stop_trace failed: {e!r}", file=sys.stderr)


class Tracer:
    def __init__(self):
        self.events = []
        self._stack = []

    @contextmanager
    def span(self, name, **attrs):
        path = "/".join(s for s in self._stack + [name])
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            with _jax_annotation(path):
                yield
        finally:
            dur = time.perf_counter() - t0
            self._stack.pop()
            ev = {"span": path, "dur_s": round(dur, 6)}
            if attrs:
                ev.update(attrs)
            self.events.append(ev)

    def totals(self, depth=1):
        """{span: total seconds} for spans at most `depth` levels deep."""
        out = {}
        for ev in self.events:
            if ev["span"].count("/") < depth:
                out[ev["span"]] = out.get(ev["span"], 0.0) + ev["dur_s"]
        return out

    def to_json(self):
        return json.dumps({"events": self.events}, separators=(",", ":"))


class _NullTracer:
    """No-op tracer: `span` costs one contextmanager enter/exit."""

    events = ()

    @contextmanager
    def span(self, name, **attrs):
        yield

    def totals(self, depth=1):
        return {}

    def to_json(self):
        return "{}"


NULL_TRACER = _NullTracer()
