"""Structured per-round / per-collective tracing.

The structured upgrade of the reference's ad-hoc timing printouts
(`println!("Elapsed: {:.2?}")` around each prover round,
/root/reference/src/dispatcher.rs:625,645,678,806,827,942 — commented out
in v2, dispatcher2.rs:293-693): spans are recorded as events with
wall-clock durations and emitted as JSON, so the driver/bench can consume
per-round numbers instead of scraping stdout.

Usage:
    tracer = Tracer()
    with tracer.span("round1"):
        with tracer.span("round1/ifft", polys=5):
            ...
    print(tracer.to_json())
"""

import json
import time
from contextlib import contextmanager


class Tracer:
    def __init__(self):
        self.events = []
        self._stack = []

    @contextmanager
    def span(self, name, **attrs):
        path = "/".join(s for s in self._stack + [name])
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self._stack.pop()
            ev = {"span": path, "dur_s": round(dur, 6)}
            if attrs:
                ev.update(attrs)
            self.events.append(ev)

    def totals(self, depth=1):
        """{span: total seconds} for spans at most `depth` levels deep."""
        out = {}
        for ev in self.events:
            if ev["span"].count("/") < depth:
                out[ev["span"]] = out.get(ev["span"], 0.0) + ev["dur_s"]
        return out

    def to_json(self):
        return json.dumps({"events": self.events}, separators=(",", ":"))


class _NullTracer:
    """No-op tracer: `span` costs one contextmanager enter/exit."""

    events = ()

    @contextmanager
    def span(self, name, **attrs):
        yield

    def totals(self, depth=1):
        return {}

    def to_json(self):
        return "{}"


NULL_TRACER = _NullTracer()
