"""Distributed tracing: one timeline per proof, across processes.

The structured upgrade of the reference's ad-hoc timing printouts
(`println!("Elapsed: {:.2?}")` around each prover round,
/root/reference/src/dispatcher.rs:625,645,678,806,827,942 — commented out
in v2, dispatcher2.rs:293-693), grown into a propagated trace plane:

- every span carries a wall-anchored START timestamp (`ts`) and duration,
  so overlapping spans (pool concurrency, the fleet's concurrent phases)
  reconstruct into a real timeline instead of a bag of durations;
- every tracer owns a 128-bit `trace_id`, every span a 64-bit `sid` with
  a `parent` link, so spans recorded in DIFFERENT PROCESSES (service
  frontend, pool worker, fleet workers) correlate under one id;
- `context()` / `Tracer.from_context()` inject/extract a trace context
  dict across any boundary (job spec field, wire frame prefix — see
  runtime/protocol.py's TRACED flag);
- `merge_traces()` stitches per-process dumps into one timeline,
  applying per-process clock offsets (the dispatcher estimates them from
  the HEALTH ping round trip, NTP-style);
- `to_chrome_trace()` exports the Chrome trace-event JSON that
  chrome://tracing / Perfetto render directly — the xprof-style timeline
  view over the whole request path.

Timestamps: each Tracer latches (time.time(), perf_counter()) once at
construction and derives every span's `ts` from the perf_counter delta —
monotonic WITHIN a process, wall-anchored for cross-process merge. Within
one process, later spans therefore never time-travel even if the system
clock steps.

Usage:
    tracer = Tracer(proc="pool/w0g1")
    with tracer.span("round1"):
        with tracer.span("round1/ifft", polys=5):
            ...
    print(tracer.to_json())

Cross-process:
    ctx = tracer.context()               # {"trace_id": ..., "parent_id": ...}
    ...ship ctx...
    remote = Tracer.from_context(ctx, proc="worker/2")
    merged = merge_traces([tracer.dump(), remote_dump], offsets=[0.0, off])
    open("trace.json", "w").write(json.dumps(to_chrome_trace(merged)))
"""

import json
import os
import secrets
import socket
import threading
import time
from contextlib import contextmanager, nullcontext

# DPT_JAX_TRACE=1: every Tracer span additionally opens a
# jax.profiler.TraceAnnotation, so spans show up on the device timeline of
# a jax.profiler capture (the SURVEY §5 device-trace replacement for the
# reference's wall-clock printouts). Off by default: annotation setup is
# not free on the hot path and tooling to view traces may be absent.
_JAX_TRACE = bool(os.environ.get("DPT_JAX_TRACE"))


def _jax_annotation(path):
    if not _JAX_TRACE:
        return nullcontext()
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(path)
    except Exception:  # pragma: no cover - profiler backend absent
        return nullcontext()


@contextmanager
def profile_to(log_dir):
    """Capture a jax.profiler device trace for the enclosed block into
    `log_dir` (viewable with tensorboard / xprof). Pairs with
    DPT_JAX_TRACE=1 so Tracer spans appear as annotations on the device
    timeline. No-ops (with a note on stderr) when tracing is unsupported
    on the platform."""
    import sys
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - tunneled platform quirks
        print(f"[trace] jax profiler unavailable: {e!r}", file=sys.stderr)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                print(f"[trace] stop_trace failed: {e!r}", file=sys.stderr)


def new_trace_id():
    """128-bit trace id, 32 hex chars."""
    return secrets.token_hex(16)


def new_span_id():
    """64-bit span id, 16 hex chars."""
    return secrets.token_hex(8)


# --- workload flops/bytes models ---------------------------------------------
# The bench.py attribution model, exported so prover/worker kernel spans
# can carry `flops`/`data_bytes` attrs and the metrics layer can expose
# live per-stage MFU instead of bench-only numbers. "Useful flops" = the
# band FMAs of the field muls each kernel performs (limb-matrix SOS
# multiplication: 3 byte-product bands of (2L)^2 MACs, 2 flops each).

FR_BAND_FLOPS = 3 * 32 * 32 * 2      # one Fr mul (L=16 u16 limbs)
FQ_BAND_FLOPS = 3 * 48 * 48 * 2      # one Fq mul (L=24)
FR_BYTES = 32
MSM_MULS_PER_POINT = 32 * 11         # signed radix-256: 32 windows, ~11
                                     # Fq muls per mixed add


def ntt_flops(n, count=1):
    """Model flops for `count` n-point NTTs."""
    if n < 2:
        return 0
    return count * (n // 2) * (n.bit_length() - 1) * FR_BAND_FLOPS


def msm_flops(n_points, count=1):
    """Model flops for `count` n-point G1 MSMs."""
    return count * n_points * MSM_MULS_PER_POINT * FQ_BAND_FLOPS


class Tracer:
    """Span recorder for one process's slice of one trace.

    Thread-safe: the span stack is thread-local (concurrent pool/fleet
    threads nest independently) and the event list is lock-guarded, so
    one tracer can serve a whole multi-threaded prove."""

    def __init__(self, trace_id=None, parent_id=None, proc=None, host=None):
        self.trace_id = trace_id or new_trace_id()
        self.parent_id = parent_id    # remote parent span (extracted ctx)
        self.proc = proc or "main"
        self.host = host or socket.gethostname()
        self.pid = os.getpid()
        self.events = []
        self._tls = threading.local()
        self._lock = threading.Lock()
        # wall anchor: spans derive ts from the perf_counter delta, so
        # within this process timestamps are monotonic AND wall-anchored
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    @classmethod
    def from_context(cls, ctx, proc=None, host=None):
        """Extract: continue a propagated trace in this process. `ctx` is
        the dict `context()` produced (tolerates None/garbage — a fresh
        root trace is started instead, never an error)."""
        if not isinstance(ctx, dict):
            return cls(proc=proc, host=host)
        tid = ctx.get("trace_id")
        if not (isinstance(tid, str) and tid):
            tid = None
        pid = ctx.get("parent_id")
        if not isinstance(pid, str):
            pid = None
        return cls(trace_id=tid, parent_id=pid, proc=proc, host=host)

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def context(self):
        """Inject: the propagation dict for the CURRENT point in the
        trace — innermost active span on this thread as parent, falling
        back to the extracted remote parent."""
        stack = self._stack()
        parent = stack[-1][1] if stack else self.parent_id
        ctx = {"trace_id": self.trace_id}
        if parent is not None:
            ctx["parent_id"] = parent
        return ctx

    @contextmanager
    def span(self, name, parent=None, **attrs):
        """Record one span; yields its span id (the value to use as a
        remote child's parent). `parent` overrides the inferred parent
        (innermost active span on this thread, else the extracted remote
        parent) — receivers link each incoming frame's span to the
        caller-supplied parent this way without racing on tracer state."""
        stack = self._stack()
        path = "/".join([s[0] for s in stack] + [name])
        sid = new_span_id()
        if parent is None:
            parent = stack[-1][1] if stack else self.parent_id
        stack.append((name, sid))
        t0 = time.perf_counter()
        try:
            with _jax_annotation(path):
                yield sid
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            ev = {"span": path, "dur_s": round(dur, 6),
                  "ts": round(self._wall0 + (t0 - self._perf0), 6),
                  "sid": sid,
                  # thread lane: overlapping spans from concurrent fleet/
                  # pool threads render side by side, not stacked
                  "tid": threading.get_ident() % 1_000_000}
            if parent is not None:
                ev["parent"] = parent
            if attrs:
                ev.update(attrs)
            with self._lock:
                self.events.append(ev)

    def add_event(self, name, ts, dur_s, parent=None, **attrs):
        """Record a synthetic span from explicit wall-clock bounds (e.g.
        the queue-wait interval measured outside any `with` block).
        Like span(), an omitted parent falls back to the extracted
        remote parent so synthetic spans stay in the caller's tree."""
        if parent is None:
            parent = self.parent_id
        ev = {"span": name, "dur_s": round(float(dur_s), 6),
              "ts": round(float(ts), 6), "sid": new_span_id()}
        if parent is not None:
            ev["parent"] = parent
        if attrs:
            ev.update(attrs)
        with self._lock:
            self.events.append(ev)
        return ev["sid"]

    def totals(self, depth=1):
        """{span: total seconds} for spans at most `depth` levels deep."""
        out = {}
        with self._lock:
            events = list(self.events)
        for ev in events:
            if ev["span"].count("/") < depth:
                out[ev["span"]] = out.get(ev["span"], 0.0) + ev["dur_s"]
        return out

    def dump(self):
        """This process's slice of the trace: one JSON-able dict
        (merge_traces input; TRACE_DUMP ships exactly this)."""
        with self._lock:
            events = list(self.events)
        return {"trace_id": self.trace_id, "proc": self.proc,
                "host": self.host, "pid": self.pid, "events": events}

    def to_json(self):
        return json.dumps(self.dump(), separators=(",", ":"))

    def to_chrome_trace(self):
        """Chrome trace-event export of this process's spans alone (the
        merged multi-process export goes through merge_traces first)."""
        return to_chrome_trace(self.dump())


class _NullTracer:
    """No-op tracer: `span` costs one contextmanager enter/exit."""

    events = ()
    trace_id = None

    @contextmanager
    def span(self, name, **attrs):
        yield None

    def add_event(self, name, ts, dur_s, parent=None, **attrs):
        return None

    def context(self):
        return None

    def totals(self, depth=1):
        return {}

    def dump(self):
        return {}

    def to_json(self):
        return "{}"


NULL_TRACER = _NullTracer()


# --- cross-process merge + export --------------------------------------------

def merge_traces(dumps, offsets=None):
    """Stitch per-process tracer dumps into ONE timeline.

    dumps: list of Tracer.dump() dicts (or TRACE_DUMP replies). offsets:
    optional list, aligned with dumps, of estimated seconds each dump's
    clock runs AHEAD of the reference clock (dump 0's, usually the
    dispatcher's) — subtracted from that dump's timestamps, so a worker
    whose wall clock is skewed still lands in the right place on the
    merged timeline. The offset estimate comes from the HEALTH ping
    round trip: offset = worker_now - (t_send + t_recv)/2.

    Returns {"trace_id", "processes": [{proc, host, pid, offset_s,
    spans}], "events": [...]} with per-event proc/host/pid labels
    attached and events sorted by corrected start time.
    """
    if offsets is None:
        offsets = [0.0] * len(dumps)
    trace_id = next((d.get("trace_id") for d in dumps
                     if d.get("trace_id")), None)
    processes = []
    events = []
    for d, off in zip(dumps, offsets):
        if not d or not d.get("events"):
            continue
        if "processes" in d:
            # already-merged timeline (e.g. fetched from /trace/<job_id>):
            # splice it in — events carry their proc/pid labels already —
            # so a client can stitch its own spans onto a server timeline
            processes.extend(dict(p) for p in d.get("processes") or [])
            for ev in d["events"]:
                ev = dict(ev)
                ev["ts"] = round(float(ev.get("ts", 0.0)) - off, 6)
                events.append(ev)
            continue
        proc = d.get("proc") or "?"
        host = d.get("host") or "?"
        pid = d.get("pid") or 0
        processes.append({"proc": proc, "host": host, "pid": pid,
                          "offset_s": round(float(off), 6),
                          "spans": len(d["events"])})
        for ev in d["events"]:
            ev = dict(ev)
            ev["ts"] = round(float(ev.get("ts", 0.0)) - off, 6)
            ev["proc"] = proc
            ev["host"] = host
            ev["pid"] = pid
            events.append(ev)
    events.sort(key=lambda ev: ev["ts"])
    return {"trace_id": trace_id, "processes": processes, "events": events}


_EVENT_KEYS = ("span", "ts", "dur_s", "sid", "parent", "proc", "host",
               "pid", "tid")


def to_chrome_trace(merged):
    """Merged timeline (merge_traces output, or a single Tracer.dump())
    -> Chrome trace-event JSON dict: load the result in chrome://tracing
    or https://ui.perfetto.dev. Complete events ("ph": "X") with
    microsecond timestamps rebased to the earliest span; per-process
    metadata rows name each pid as proc@host."""
    if "processes" not in merged:
        merged = merge_traces([merged])
    events = merged.get("events") or []
    base = min((ev["ts"] for ev in events), default=0.0)
    out = []
    for p in merged.get("processes", []):
        out.append({"ph": "M", "name": "process_name", "pid": p["pid"],
                    "args": {"name": f"{p['proc']}@{p['host']}"}})
    for ev in events:
        args = {k: v for k, v in ev.items() if k not in _EVENT_KEYS}
        args["sid"] = ev.get("sid")
        if ev.get("parent") is not None:
            args["parent"] = ev["parent"]
        out.append({
            "ph": "X",
            "name": ev["span"],
            "cat": "span",
            "ts": round((ev["ts"] - base) * 1e6, 1),
            "dur": round(ev["dur_s"] * 1e6, 1),
            "pid": ev.get("pid", 0),
            "tid": ev.get("tid", 0),
            "args": args,
        })
    # structured log events (obs/log.py, merged in by collect_trace /
    # the service pool) render as instant events on the same timeline:
    # quarantines/replans/respawns line up visually under the spans
    for ev in merged.get("logs") or []:
        out.append({
            "ph": "i",
            "name": f"{ev.get('subsystem', '?')}/{ev.get('event', '?')}",
            "cat": "log",
            "s": "g",  # global-scope instant marker
            "ts": round((float(ev.get("ts", base)) - base) * 1e6, 1),
            "pid": ev.get("pid", 0),
            "tid": 0,
            "args": {k: v for k, v in ev.items()
                     if k not in ("ts", "pid")},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"trace_id": merged.get("trace_id"),
                          "base_ts_s": round(base, 6)}}
