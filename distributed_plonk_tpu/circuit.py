"""TurboPlonk constraint system (5 wire types, 13 selectors).

Re-provides the jf-plonk circuit surface the reference consumes through
`Arithmetization` (/root/reference/src/dispatcher2.rs:171-186 exposes the
fields: wire_variables, witness, wire_permutation, extended_id_permutation,
pub_input_gate_ids, eval_domain). Gate semantics follow the reference's
quotient formula (/root/reference/src/dispatcher2.rs:434-504):

    q_c + PI
      + q_lc0*a + q_lc1*b + q_lc2*c + q_lc3*d
      + q_mul0*(a*b) + q_mul1*(c*d)
      + q_ecc*(a*b*c*d*e)
      + q_hash0*a^5 + q_hash1*b^5 + q_hash2*c^5 + q_hash3*d^5
      - q_o*e  == 0        on every row of the evaluation domain.

Selector order (matches prove_key.selectors indexing in the reference):
    [q_lc0..3, q_mul0, q_mul1, q_hash0..3, q_o, q_c, q_ecc]   (13 total)
"""

from .constants import R_MOD, FR_GENERATOR
from .poly import Domain

GATE_WIDTH = 4  # fan-in; wire types = GATE_WIDTH + 1 (4 inputs + 1 output)
NUM_WIRE_TYPES = 5
NUM_SELECTORS = 2 * GATE_WIDTH + 5  # 13

# selector indices
Q_LC = 0          # ..3
Q_MUL = 4         # ..5
Q_HASH = 6        # ..9
Q_O = 10
Q_C = 11
Q_ECC = 12

_INV_5 = pow(5, -1, R_MOD - 1)  # x -> x^(1/5) exponent (gcd(5, r-1) = 1)


def coset_representatives(num):
    """Wire-subset separators k_0=1, k_i = g^i (g = 7, a primitive root).

    k_i/k_j = g^(i-j) lies in the order-2^s FFT subgroup only if its order
    divides 2^s; ord(g^d) = (r-1)/gcd(d, r-1) keeps the odd part of r-1 for
    0 < d < 5, so the five cosets k_i * H are pairwise disjoint.
    """
    ks = [1]
    cur = 1
    for _ in range(1, num):
        cur = cur * FR_GENERATOR % R_MOD
        ks.append(cur)
    return ks


class PlonkCircuit:
    """Mutable TurboPlonk circuit builder + finalized arithmetization."""

    def __init__(self):
        self.witness = []           # variable values
        self.wire_variables = [[] for _ in range(NUM_WIRE_TYPES)]
        self.selectors = [[] for _ in range(NUM_SELECTORS)]
        self.pub_input_gate_ids = []
        self.pub_inputs = []
        self._finalized = False
        # constant variables 0 and 1, constrained by gates
        self.zero_var = self.create_variable(0)
        self._constant_gate(self.zero_var, 0)
        self.one_var = self.create_variable(1)
        self._constant_gate(self.one_var, 1)

    # --- variables -----------------------------------------------------------

    def create_variable(self, value):
        assert not self._finalized
        self.witness.append(value % R_MOD)
        return len(self.witness) - 1

    def create_public_variable(self, value):
        v = self.create_variable(value)
        self.set_public(v)
        return v

    def set_public(self, var):
        """Add an IO gate exposing `var` as a public input (q_o = 1, PI row)."""
        gid = self._add_gate(
            [self.zero_var] * GATE_WIDTH + [var],
            {Q_O: 1},
        )
        self.pub_input_gate_ids.append(gid)
        self.pub_inputs.append(self.witness[var])

    # --- gates ---------------------------------------------------------------

    def _add_gate(self, wires, sel):
        assert len(wires) == NUM_WIRE_TYPES
        for i in range(NUM_WIRE_TYPES):
            self.wire_variables[i].append(wires[i])
        for i in range(NUM_SELECTORS):
            self.selectors[i].append(sel.get(i, 0) % R_MOD)
        return len(self.wire_variables[0]) - 1

    def _constant_gate(self, var, value):
        # q_c + PI - q_o*e = 0 with q_o=1, q_c=value -> e == value
        self._add_gate([self.zero_var] * GATE_WIDTH + [var], {Q_O: 1, Q_C: value})

    def add_constant_gate(self, var, value):
        self._constant_gate(var, value)

    def add(self, a, b):
        out = self.create_variable(self.witness[a] + self.witness[b])
        self._add_gate([a, b, self.zero_var, self.zero_var, out], {Q_LC: 1, Q_LC + 1: 1, Q_O: 1})
        return out

    def sub(self, a, b):
        out = self.create_variable(self.witness[a] - self.witness[b])
        self._add_gate([a, b, self.zero_var, self.zero_var, out],
                       {Q_LC: 1, Q_LC + 1: R_MOD - 1, Q_O: 1})
        return out

    def mul(self, a, b):
        out = self.create_variable(self.witness[a] * self.witness[b])
        self._add_gate([a, b, self.zero_var, self.zero_var, out], {Q_MUL: 1, Q_O: 1})
        return out

    def lc(self, vars4, coeffs4):
        """out = sum coeffs4[i] * vars4[i]."""
        val = sum(c * self.witness[v] for v, c in zip(vars4, coeffs4))
        out = self.create_variable(val)
        sel = {Q_LC + i: coeffs4[i] % R_MOD for i in range(4)}
        sel[Q_O] = 1
        self._add_gate(list(vars4) + [out], sel)
        return out

    def add_constant(self, a, const):
        out = self.create_variable(self.witness[a] + const)
        self._add_gate([a, self.zero_var, self.zero_var, self.zero_var, out],
                       {Q_LC: 1, Q_C: const % R_MOD, Q_O: 1})
        return out

    def mul_constant(self, a, const):
        out = self.create_variable(self.witness[a] * const)
        self._add_gate([a, self.zero_var, self.zero_var, self.zero_var, out],
                       {Q_LC: const % R_MOD, Q_O: 1})
        return out

    def power5(self, a):
        """out = a^5 via the dedicated hash selector (one gate)."""
        out = self.create_variable(pow(self.witness[a], 5, R_MOD))
        self._add_gate([a, self.zero_var, self.zero_var, self.zero_var, out],
                       {Q_HASH: 1, Q_O: 1})
        return out

    def root5(self, a):
        """out with out^5 == a (one gate, S-box run backwards: the witness
        carries the 5th root, the q_hash selector enforces the power)."""
        out = self.create_variable(pow(self.witness[a], _INV_5, R_MOD))
        self._add_gate([out, self.zero_var, self.zero_var, self.zero_var, a],
                       {Q_HASH: 1, Q_O: 1})
        return out

    def lc_with_const(self, vars4, coeffs4, const):
        """out = sum coeffs4[i]*vars4[i] + const (one gate)."""
        val = sum(c * self.witness[v] for v, c in zip(vars4, coeffs4)) + const
        out = self.create_variable(val)
        sel = {Q_LC + i: coeffs4[i] % R_MOD for i in range(4)}
        sel[Q_C] = const % R_MOD
        sel[Q_O] = 1
        self._add_gate(list(vars4) + [out], sel)
        return out

    def pow5_lc_with_const(self, vars4, coeffs4, const):
        """out = sum coeffs4[i]*vars4[i]^5 + const (one gate).

        The TurboPlonk hash selectors q_hash0..3 weight the 5th powers of all
        four input wires, so a Rescue forward half-round's S-box + one MDS row
        + round constant fuse into a single gate (the gate shape jf-plonk's
        RescueGadget was built around; cf. the q_hash terms of the quotient
        formula at /root/reference/src/dispatcher2.rs:469-473)."""
        val = sum(c * pow(self.witness[v], 5, R_MOD)
                  for v, c in zip(vars4, coeffs4)) + const
        out = self.create_variable(val)
        sel = {Q_HASH + i: coeffs4[i] % R_MOD for i in range(4)}
        sel[Q_C] = const % R_MOD
        sel[Q_O] = 1
        self._add_gate(list(vars4) + [out], sel)
        return out

    def mul_add(self, a, b, c, d):
        """out = a*b + c*d (one gate via the two q_mul selectors)."""
        out = self.create_variable(
            self.witness[a] * self.witness[b] + self.witness[c] * self.witness[d])
        self._add_gate([a, b, c, d, out], {Q_MUL: 1, Q_MUL + 1: 1, Q_O: 1})
        return out

    def enforce_bool(self, a):
        """Constrain a in {0,1}: a*a - a == 0 (one gate)."""
        self._add_gate([a, a, self.zero_var, self.zero_var, self.zero_var],
                       {Q_MUL: 1, Q_LC: R_MOD - 1})

    def enforce_equal(self, a, b):
        self._add_gate([a, b, self.zero_var, self.zero_var, self.zero_var],
                       {Q_LC: 1, Q_LC + 1: R_MOD - 1})

    def enforce_ecc_product(self, a, b, c, d, e, k):
        """Native q_ecc gate: constrain a*b*c*d*e == k (single row).

        The 5th factor rides the output wire; the q_ecc selector contributes
        the full 5-way product additively, balanced by the constant.
        """
        self._add_gate([a, b, c, d, e], {Q_ECC: 1, Q_C: (-k) % R_MOD})

    def check_satisfiability(self):
        """Debug oracle: every gate constraint holds on the raw witness."""
        n = len(self.wire_variables[0])
        pi_by_gate = dict(zip(self.pub_input_gate_ids, self.pub_inputs))
        for j in range(n):
            w = [self.witness[self.wire_variables[i][j]] for i in range(NUM_WIRE_TYPES)]
            a, b, c, d, e = w
            s = lambda k: self.selectors[k][j]  # noqa: E731
            pi = pi_by_gate.get(j, 0)
            val = (
                s(Q_C) + pi
                + s(Q_LC) * a + s(Q_LC + 1) * b + s(Q_LC + 2) * c + s(Q_LC + 3) * d
                + s(Q_MUL) * (a * b) + s(Q_MUL + 1) * (c * d)
                + s(Q_ECC) * (a * b % R_MOD * c % R_MOD * d % R_MOD * e)
                + s(Q_HASH) * pow(a, 5, R_MOD) + s(Q_HASH + 1) * pow(b, 5, R_MOD)
                + s(Q_HASH + 2) * pow(c, 5, R_MOD) + s(Q_HASH + 3) * pow(d, 5, R_MOD)
                - s(Q_O) * e
            ) % R_MOD
            if val != 0:
                return False, j
        return True, -1

    # --- finalization --------------------------------------------------------

    @property
    def num_gates(self):
        return len(self.wire_variables[0])

    @property
    def num_vars(self):
        return len(self.witness)

    @property
    def num_inputs(self):
        return len(self.pub_input_gate_ids)

    def finalize(self):
        """Rearrange IO gates to the first rows, pad to a power of two,
        and compute the permutation tables. Mirrors jf-plonk's
        finalize_for_arithmetization (consumed by the reference at
        /root/reference/src/dispatcher2.rs:248)."""
        assert not self._finalized
        # 1. move IO gates to rows 0..num_inputs-1 (stable order)
        order = list(self.pub_input_gate_ids)
        io_set = set(order)
        order += [j for j in range(self.num_gates) if j not in io_set]
        for i in range(NUM_WIRE_TYPES):
            self.wire_variables[i] = [self.wire_variables[i][j] for j in order]
        for k in range(NUM_SELECTORS):
            self.selectors[k] = [self.selectors[k][j] for j in order]
        self.pub_input_gate_ids = list(range(len(self.pub_input_gate_ids)))

        # 2. pad to power of two (strictly greater so z-poly row n-1 is free)
        n = 1
        while n < self.num_gates + 1:
            n <<= 1
        pad = n - self.num_gates
        for i in range(NUM_WIRE_TYPES):
            self.wire_variables[i] += [self.zero_var] * pad
        for k in range(NUM_SELECTORS):
            self.selectors[k] += [0] * pad

        self.eval_domain = Domain(n)
        self.n = n
        self._finalized = True

        # 3. permutation tables
        self.k = coset_representatives(NUM_WIRE_TYPES)
        # extended id: id[i][j] = k_i * w^j
        powers = list(self.eval_domain.elements())
        self.extended_id_permutation = [
            [self.k[i] * powers[j] % R_MOD for j in range(n)]
            for i in range(NUM_WIRE_TYPES)
        ]
        # wire_permutation: cyclic right-shift within each variable's slots
        positions = {}
        for i in range(NUM_WIRE_TYPES):
            for j in range(n):
                positions.setdefault(self.wire_variables[i][j], []).append((i, j))
        self.wire_permutation = [[None] * n for _ in range(NUM_WIRE_TYPES)]
        for var, slots in positions.items():
            m = len(slots)
            for t, (i, j) in enumerate(slots):
                self.wire_permutation[i][j] = slots[(t + 1) % m]
        return self

    def sigma_values(self):
        """sigma_i(w^j) = extended_id[perm(i, j)] for the 5 sigma polys."""
        assert self._finalized
        out = []
        for i in range(NUM_WIRE_TYPES):
            row = []
            for j in range(self.n):
                pi, pj = self.wire_permutation[i][j]
                row.append(self.extended_id_permutation[pi][pj])
            out.append(row)
        return out

    def public_input(self):
        assert self._finalized
        return list(self.pub_inputs)

    def wire_values(self, i):
        """Evaluations of wire polynomial i over the domain."""
        assert self._finalized
        return [self.witness[v] for v in self.wire_variables[i]]
