"""Warm-start layer: store-owned compile cache + AOT shape warmup.

Two cold-start costs dominate serving a new circuit shape (PAPER.md's
prover pays both once per shape): trusted-setup/key construction and the
XLA compilation of the prover's NTT/MSM stages. The artifact store
(artifacts.py + keycache.py) removes the first across restarts; this
module removes the second by (a) parking JAX's persistent compilation
cache under the store root, so compiled stages live and die with the
artifacts they serve, and (b) an AOT warmup entry point that pre-builds
keys AND pre-lowers/compiles the prover stages for a shape before any
job arrives (WARMUP wire tag, scripts/warmup.py).

None of this imports jax at module scope: the proof service's default
backend is the pure-host oracle and must stay importable (and testable)
with no XLA present. jax only loads when a jax-capable backend is
actually handed in, or `configure_jax_cache` is called.
"""

import os
import time

from . import keycache
from .artifacts import JAX_CACHE_SUBDIR  # one name for the GC'd subdir


def set_jax_cache_env(store_root):
    """Point the (not-yet-imported) jax backend's persistent compile cache
    under `store_root`, via the DPT_JAX_CACHE_DIR knob field_jax reads at
    import. Env-only — safe to call from processes that never load jax.
    An explicit user setting (either knob) wins."""
    if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
        os.environ.setdefault(
            "DPT_JAX_CACHE_DIR",
            os.path.join(os.path.abspath(store_root), JAX_CACHE_SUBDIR))


def configure_jax_cache(store_root, min_compile_secs=0.5):
    """Repoint an already-imported jax at the store-owned compile cache
    (machine-fingerprint partitioned). Imports jax; returns the cache dir
    or None when this jax can't be wired.

    Same precedence rule as set_jax_cache_env: an operator's explicit
    JAX_COMPILATION_CACHE_DIR wins — otherwise an offline `warmup --aot`
    would bake executables into a directory the (env-respecting) server
    never reads, silently wasting the whole warmup pass."""
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        return None
    from ..backend import field_jax
    return field_jax.configure_compile_cache(
        os.path.join(os.path.abspath(store_root), JAX_CACHE_SUBDIR),
        min_compile_secs=min_compile_secs)


def aot_warmup(backend, domain_size, ck=None):
    """Pre-lower/compile the prover stages for one shape's domain on a
    backend that supports it (JaxBackend.warm_stages); the host oracle
    has no compile step, so it reports `unsupported` and costs nothing."""
    if backend is None or not hasattr(backend, "warm_stages"):
        return {"aot": "unsupported",
                "backend": getattr(backend, "name", None)}
    t0 = time.monotonic()
    report = backend.warm_stages(domain_size, ck=ck)
    report["aot"] = "ok"
    report["aot_s"] = round(time.monotonic() - t0, 3)
    return report


def warm_spec(store, spec_obj, backend=None, aot_backend=None):
    """Offline store provisioning (scripts/warmup.py --store-dir): make
    sure `store` holds the bucket keys for one wire spec, building them
    only on a disk miss; `aot_backend` additionally precompiles the
    shape's prover stages. Returns a summary dict ({source: disk|built})."""
    from ..service import jobs as J

    spec = J.JobSpec.from_wire(spec_obj)
    key = J.shape_key(spec)
    t0 = time.monotonic()
    hit = keycache.load_bucket(store, key)
    if hit is not None:
        _srs, pk, vk, meta = hit
        out = {"shape_key": [str(p) for p in key], "source": "disk",
               "domain_size": vk.domain_size,
               "load_s": round(time.monotonic() - t0, 6),
               "build_s": meta.get("build_s")}
    else:
        srs, pk, vk = J.build_bucket_keys(spec, backend=backend)
        build_s = time.monotonic() - t0
        keycache.store_bucket(store, key, srs, pk, vk, build_s=build_s)
        out = {"shape_key": [str(p) for p in key], "source": "built",
               "domain_size": vk.domain_size, "build_s": round(build_s, 6)}
    if aot_backend is not None:
        out["aot"] = aot_warmup(aot_backend, vk.domain_size, ck=pk.ck)
        # the AOT pass is what grows the store-owned compile cache:
        # re-bound it against the byte budget right after (the periodic
        # put()-side sweep only runs while artifacts are being written)
        out["jax_cache_swept"] = store.sweep_jax_cache()
    return out
