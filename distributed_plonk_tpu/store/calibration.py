"""Calibration-plan artifacts: persist/load kernel autotune plans.

The store side of backend/autotune.py: a `KernelPlan` (the measured
winning kernel configuration for one machine) lives in the content-
addressed artifact store under `autotune:<machine_fingerprint>`, so it

  - survives restarts like bucket keys (a second service start against
    a calibrated store reaches first proof with ZERO measurement runs),
  - warm-syncs to joining fleet workers over the STORE_LIST plane like
    any other artifact (store/remote.WARM_SYNC_PREFIXES includes
    `autotune:`), and
  - stays per-machine: a store shared across heterogeneous hosts holds
    one plan per fingerprint, and a fingerprint miss means "calibrate
    (or default)", never "crash" or "apply another chip's winners".

`load_or_run` is the one startup entry point (ProofService.start,
runtime/worker.py, scripts/autotune.py), driven by DPT_AUTOTUNE:

    off    touch nothing — no store reads, no counters, no plan: every
           kernel path is exactly the pre-autotune tree
    load   (default) adopt the store's plan for this fingerprint if one
           exists; otherwise run with built-in defaults (also exactly
           the pre-autotune tree — the existence probe uses store.meta,
           which counts nothing)
    run    load, and on a miss CALIBRATE (budgeted by
           DPT_AUTOTUNE_BUDGET_S), persist the plan + the winners' AOT
           executables, then adopt it

Calibration runs under a store-level fcntl lock (`calibration.lock`,
same discipline as the manifest lock) so concurrent starters against
one store measure once: losers block, then load the winner's plan.
"""

import os
import time

from ..backend import autotune
from .artifacts import _FileLock

PLAN_PREFIX = "autotune:"


def plan_store_key(fingerprint):
    return PLAN_PREFIX + fingerprint


def calibration_lock(store):
    """Cross-process advisory lock for calibration runs on `store` (the
    manifest _FileLock mechanism on a sidecar file)."""
    return _FileLock(os.path.join(store.root, "calibration.lock"))


def store_plan(store, plan, metrics=None):
    """Persist `plan` as the content-addressed artifact for its
    fingerprint; returns the digest. Canonical JSON, so an unchanged
    plan re-stores to the identical blob/digest."""
    digest = store.put(
        plan_store_key(plan.fingerprint), plan.to_json_bytes(),
        meta={"kind": "autotune_plan", "fingerprint": plan.fingerprint,
              "cells": len(plan.cells)})
    if metrics is not None:
        metrics.inc("autotune_plan_stores")
    return digest


def load_plan(store, fingerprint=None):
    """The store's plan for `fingerprint` (default: this machine), or
    None — on a plain miss, an unparseable blob, or a plan whose
    EMBEDDED fingerprint disagrees with the requested one (a foreign or
    hand-copied artifact must trigger a rebuild, not dispatch another
    chip's winners). The existence probe is store.meta (counter-free),
    so a plan-less start changes no metrics."""
    fp = fingerprint or autotune.machine_fingerprint()
    key = plan_store_key(fp)
    if store.meta(key) is None:
        return None
    blob = store.get(key)
    if blob is None:
        return None
    plan = autotune.KernelPlan.from_json_bytes(blob)
    if plan is None or plan.fingerprint != fp:
        return None
    return plan


def parse_shapes(spec):
    """'2^10,2^14,16384' -> sorted domain sizes."""
    out = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "^" in part:
            base, _, exp = part.partition("^")
            out.add(int(base) ** int(exp))
        else:
            out.add(int(part))
    return sorted(out)


def _default_shapes(store):
    """Shapes to calibrate at when the caller has none: the explicit
    DPT_AUTOTUNE_SHAPES knob, else the domain sizes of the store's
    provisioned shape buckets (a warmed store describes its own
    workload), else one small default."""
    env = os.environ.get("DPT_AUTOTUNE_SHAPES")
    if env:
        return parse_shapes(env)
    sizes = set()
    for key in store.keys():
        if not key.startswith("bucket:"):
            continue
        meta = store.meta(key)
        if meta and isinstance(meta.get("domain_size"), int):
            sizes.add(meta["domain_size"])
    return sorted(sizes) or [1 << 10]


def load_or_run(store, mode=None, shapes=None, budget_s=None, metrics=None,
                aot=True):
    """Startup plan pickup (see module docstring). Returns a report:
    {source: off|none|store|fresh, fingerprint, cells, measure_runs,
    run_s?}; on store/fresh the plan is installed as the process-wide
    KernelConfig (backend/autotune.set_active_plan)."""
    mode = (mode or os.environ.get("DPT_AUTOTUNE", "load")).strip().lower()
    if mode not in ("off", "load", "run"):
        raise ValueError(f"DPT_AUTOTUNE must be off|load|run, got {mode!r}")
    if mode == "off":
        return {"source": "off"}
    fp = autotune.machine_fingerprint()
    plan = load_plan(store, fp)
    if plan is not None:
        autotune.set_active_plan(plan)
        if metrics is not None:
            metrics.inc("autotune_plan_loads")
            _publish(metrics, "store", plan)
        return {"source": "store", "fingerprint": fp,
                "cells": len(plan.cells), "measure_runs": 0}
    if mode != "run":
        return {"source": "none", "fingerprint": fp, "measure_runs": 0}
    t0 = time.monotonic()
    with calibration_lock(store):
        # a concurrent starter may have calibrated while we waited on
        # the lock: measure once per store, everyone else loads
        plan = load_plan(store, fp)
        source = "store"
        measure_runs = 0
        if plan is None:
            from ..backend.autotune import Autotuner

            tuner = Autotuner(shapes or _default_shapes(store),
                              budget_s=budget_s, metrics=metrics)
            plan = tuner.run(aot=aot)
            store_plan(store, plan, metrics=metrics)
            source = "fresh"
            measure_runs = sum(
                c.get("candidates", 0) + c.get("parity_rejects", 0)
                + c.get("errors", 0) for c in plan.cells.values())
    autotune.set_active_plan(plan)
    if metrics is not None:
        if source == "store":
            metrics.inc("autotune_plan_loads")
        _publish(metrics, source, plan)
    return {"source": source, "fingerprint": fp, "cells": len(plan.cells),
            "measure_runs": measure_runs,
            "run_s": round(time.monotonic() - t0, 3)}


def _publish(metrics, source, plan):
    metrics.gauge("autotune_plan_source", source)
    metrics.gauge("autotune_plan_cells", len(plan.cells))
    metrics.gauge("autotune_plan_revision", autotune.plan_revision())
