"""Content-addressed on-disk artifact store with integrity + LRU eviction.

The persistence layer under the proof service's warm-start path
(store/keycache.py serializes bucket keys into it; scheduler.BucketCache
is its main consumer). Inference-stack shape: a model-weights /
compiled-program cache, specialized to proving artifacts.

Layout under `root`:

    manifest.json            versioned index: key -> {digest, bytes, seq, meta}
    objects/ab/abcdef...bin  blobs, named by their SHA-256 (content-addressed)
    jax_cache/<machine_fp>/  store-owned JAX persistent compile cache
                             (managed by store/warmstart.py, not this module)

Contracts:
- Every write is atomic (tmp file + os.replace), manifest included, so a
  crash mid-write can never leave a referenced-but-truncated entry: either
  the old manifest (no reference) or the new one (fully written blob).
- `get` re-verifies SHA-256 over the full blob on every read. An integrity
  failure (truncation, bit rot, a partial copy) logs, DELETES the entry,
  and returns None — callers fall through to a fresh build instead of
  crashing (service satellite contract, tests/test_store.py).
- LRU byte-budget eviction: each hit bumps a sequence number (in memory;
  persisted with the next put/delete); a put that pushes the store past
  `byte_budget` evicts lowest-seq entries first (never the entry just
  written). Object files are refcounted by digest, so two keys sharing
  identical bytes share one blob; blobs orphaned by a manifest reset or
  writer race are swept at the next open.
- Cross-process: readers reload the manifest from disk on a miss, so a
  store populated by another process (warmup job, previous server run) is
  visible without restart, and a plain hit never writes the manifest, so
  readers cannot clobber a writer. Concurrent WRITERS are safe too:
  every manifest read-modify-write (put/delete) runs under an fcntl
  lockfile (`manifest.lock`) and starts by MERGING the on-disk manifest
  into memory — disk is the source of truth for the entry set (a key we
  hold that disk lacks was deleted by another writer), while in-memory
  LRU recency survives as max(seq). Two warmup/serve writers on one
  store can no longer drop each other's entries (the PR 2 ROADMAP gap);
  on platforms without fcntl the lock degrades to the old
  atomic-replace-only behavior.

Metrics (duck-typed `inc`/`gauge`, e.g. service.metrics.Metrics or its
`scoped("store")` view): hits, misses, corrupt, evictions, put_bytes,
and gauges bytes / entries.
"""

import hashlib
import json
import logging
import os
import threading
import time

from ..runtime.health import NullMetrics

try:
    import fcntl
except ImportError:  # pragma: no cover - non-posix fallback
    fcntl = None

log = logging.getLogger("dpt.store")

MANIFEST_VERSION = 1

# store-owned JAX persistent compile cache subdir (warmstart.py parks
# the cache here; this module garbage-collects it against the budget)
JAX_CACHE_SUBDIR = "jax_cache"


class _FileLock:
    """Advisory exclusive lock on a sidecar file (blocking). Serializes
    manifest read-modify-write across PROCESSES; the in-process
    threading lock still serializes threads within one store object.
    No-ops when fcntl is unavailable."""

    def __init__(self, path):
        self.path = path
        self._f = None

    def __enter__(self):
        if fcntl is not None:
            self._f = open(self.path, "a+")
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        if self._f is not None:
            fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
            self._f.close()
            self._f = None
        return False




class ArtifactStore:
    def __init__(self, root, byte_budget=None, metrics=None):
        self.root = root
        self.byte_budget = byte_budget
        self.metrics = metrics or NullMetrics()
        self._lock = threading.Lock()
        os.makedirs(os.path.join(root, "objects"), exist_ok=True)
        self._manifest_path = os.path.join(root, "manifest.json")
        self._file_lock = _FileLock(os.path.join(root, "manifest.lock"))
        # load + orphan sweep under the file lock: a lock-free sweep
        # could delete an old blob a concurrent put() just revived via
        # its exists()-skip path (entry published, backing blob gone)
        with self._file_lock:
            self._manifest = self._load_manifest()
            self._sweep_orphans()
        # the store-owned JAX compile cache counts against the SAME byte
        # budget (ROADMAP: it used to grow unbounded); swept at open and
        # then periodically from put()
        self._jax_sweep_interval = float(
            os.environ.get("DPT_STORE_JAX_SWEEP_S", "300"))
        self._jax_cache_bytes = 0
        # unconditional at open (NOT the throttled wrapper: on a freshly
        # booted machine monotonic() < interval and a 0.0 sentinel would
        # suppress the open-time bound entirely)
        self._last_jax_sweep = time.monotonic()
        with self._lock:
            self._sweep_jax_cache_locked()
        self._publish_gauges()

    # -- manifest -------------------------------------------------------------

    def _load_manifest(self):
        try:
            with open(self._manifest_path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"version": MANIFEST_VERSION, "seq": 0, "entries": {}}
        if m.get("version") != MANIFEST_VERSION:
            # future/foreign manifest: start fresh rather than misparse.
            # Blobs are content-addressed so orphans are harmless; the
            # next open's _sweep_orphans reclaims the disk.
            log.warning("store %s: manifest version %r != %d, resetting",
                        self.root, m.get("version"), MANIFEST_VERSION)
            return {"version": MANIFEST_VERSION, "seq": 0, "entries": {}}
        return m

    def _sweep_orphans(self):
        """Delete object files no manifest entry references (left by a
        manifest reset or a lost writer race) — they are invisible to the
        byte budget, so without this they would grow the disk unbounded."""
        live = {e["digest"] for e in self._manifest["entries"].values()}
        objroot = os.path.join(self.root, "objects")
        for sub in os.listdir(objroot):
            subdir = os.path.join(objroot, sub)
            if not os.path.isdir(subdir):
                continue
            for fname in os.listdir(subdir):
                digest = fname[:-4] if fname.endswith(".bin") else None
                if digest in live:
                    continue
                path = os.path.join(subdir, fname)
                try:  # stray tmp files from a crashed writer also land
                    # here; an age floor keeps the sweep from racing a
                    # concurrent put whose manifest write is in flight
                    if time.time() - os.path.getmtime(path) > 300:
                        os.remove(path)
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass

    def _save_manifest(self):
        tmp = self._manifest_path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(self._manifest, f)
        os.replace(tmp, self._manifest_path)

    def _merge_from_disk(self):
        """Merge the on-disk manifest into memory (writers call this
        with the file lock held; get()'s miss path calls it lock-free,
        which is safe because _save_manifest publishes atomically).

        Disk is authoritative for the ENTRY SET: every write by any
        process saves before releasing the file lock, so an entry we
        hold that disk lacks was deleted by another writer (eviction),
        and a disk entry we lack was added by one. What memory
        contributes is recency — LRU touches are in-memory-only until
        the next write — so per-key seq merges as max(), and the global
        counter as max() too, keeping seq monotonic across writers."""
        disk = self._load_manifest()
        mem = self._manifest["entries"]
        for key, e in disk["entries"].items():
            m = mem.get(key)
            if m is not None and m["digest"] == e["digest"]:
                e["seq"] = max(e["seq"], m["seq"])
        disk["seq"] = max(disk["seq"], self._manifest["seq"])
        self._manifest = disk

    def _publish_gauges(self):
        ents = self._manifest["entries"]
        self.metrics.gauge("bytes",
                           sum(e["bytes"] for e in ents.values()))
        self.metrics.gauge("entries", len(ents))

    def _obj_path(self, digest):
        return os.path.join(self.root, "objects", digest[:2], digest + ".bin")

    def _next_seq(self):
        self._manifest["seq"] += 1
        return self._manifest["seq"]

    # -- public API -----------------------------------------------------------

    def keys(self):
        with self._lock:
            return sorted(self._manifest["entries"])

    def stats(self):
        with self._lock:
            ents = self._manifest["entries"]
            # jax_cache_bytes is the total gauged by the last sweep/walk,
            # not a fresh walk: stats() sits on the METRICS poll path and
            # a per-poll os.walk of a few thousand compile-cache files
            # under self._lock would stall concurrent put()/get()
            return {"entries": len(ents),
                    "bytes": sum(e["bytes"] for e in ents.values()),
                    "jax_cache_bytes": self._jax_cache_bytes,
                    "byte_budget": self.byte_budget}

    def meta(self, key):
        with self._lock:
            e = self._manifest["entries"].get(key)
            return dict(e["meta"]) if e else None

    def put(self, key, blob, meta=None):
        """Store `blob` under `key` (replacing any prior entry), atomically.
        Returns the content digest."""
        digest = hashlib.sha256(blob).hexdigest()
        path = self._obj_path(digest)
        def _write_blob():
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)

        with self._lock:
            # bulk blob I/O OUTSIDE the cross-process flock (multi-MB
            # key blobs must not serialize concurrent warmup writers);
            # content-addressed atomic rename makes it idempotent. The
            # existence is RE-CHECKED under the flock: a concurrent
            # writer's eviction between our write and our manifest
            # insert would otherwise publish an entry with no backing
            # blob
            if not os.path.exists(path):
                _write_blob()
            with self._file_lock:
                if not os.path.exists(path):  # evicted in the window
                    _write_blob()
                self._merge_from_disk()
                old = self._manifest["entries"].get(key)
                self._manifest["entries"][key] = {
                    "digest": digest, "bytes": len(blob),
                    "seq": self._next_seq(), "created": time.time(),
                    "meta": dict(meta or {}),
                }
                if old is not None and old["digest"] != digest:
                    self._drop_blob_if_unreferenced(old["digest"])
                self.metrics.inc("put_bytes", len(blob))
                self._evict_over_budget(protect=key)
                self._save_manifest()
            self._maybe_sweep_jax_cache()
            self._publish_gauges()
        return digest

    def get(self, key):
        """Blob for `key`, or None (miss, or integrity failure — in which
        case the corrupt entry is deleted so the caller's rebuild can
        repopulate it)."""
        hit = self.get_entry(key)
        return hit[0] if hit is not None else None

    def get_entry(self, key):
        """-> (blob, digest, meta) for a verified hit, or None. The digest
        is the one the read was just verified against, so STORE_FETCH
        servers (store/remote.serve_fetch) can advertise it without
        hashing the blob a second time."""
        with self._lock:
            e = self._manifest["entries"].get(key)
            if e is None:
                # another process may have populated the store since we
                # loaded the manifest (warmup job, previous server run);
                # merge rather than overwrite so in-memory LRU touches
                # (persisted only on the next write) keep their recency
                self._merge_from_disk()
                e = self._manifest["entries"].get(key)
            if e is None:
                self.metrics.inc("misses")
                return None
            blob = self._read_verified(key, e)
            if blob is None:
                # before declaring corruption, resync: another writer
                # may have re-put the key (old blob legitimately gone)
                # or deleted it — neither is an integrity failure
                with self._file_lock:
                    self._merge_from_disk()
                    cur = self._manifest["entries"].get(key)
                    if cur is None:
                        self.metrics.inc("misses")
                        return None
                    # re-read unconditionally: even a SAME-digest entry
                    # may have been evicted and re-put by another writer
                    # (deterministic key blobs), making the blob valid
                    # again on disk
                    blob = self._read_verified(key, cur)
                    e = cur
                    if blob is None:
                        self.metrics.inc("corrupt")
                        self._delete_locked(key)
                        self._save_manifest()
                if blob is None:
                    self._publish_gauges()
                    return None
            self.metrics.inc("hits")
            # LRU touch, in memory only: a hit must NOT rewrite the
            # manifest — a reader that writes would clobber entries a
            # concurrent warmup/serve writer just added (last-write-wins
            # manifest). Recency is persisted by the next real write
            # (put/delete), which is also when eviction reads it.
            e["seq"] = self._next_seq()
            return blob, e["digest"], dict(e["meta"])

    def delete(self, key):
        with self._lock:
            with self._file_lock:
                self._merge_from_disk()
                found = key in self._manifest["entries"]
                if found:
                    self._delete_locked(key)
                    self._save_manifest()
            self._publish_gauges()
            return found

    # -- internals (lock held) ------------------------------------------------

    def _read_verified(self, key, e):
        try:
            with open(self._obj_path(e["digest"]), "rb") as f:
                blob = f.read()
        except OSError as err:
            log.warning("store %s: %s unreadable (%s); dropping entry",
                        self.root, key, err)
            return None
        if len(blob) != e["bytes"]:
            log.warning("store %s: %s failed integrity check "
                        "(%d bytes on disk, %d expected); dropping entry",
                        self.root, key, len(blob), e["bytes"])
            return None
        digest = hashlib.sha256(blob).hexdigest()
        if digest != e["digest"]:
            log.warning("store %s: %s failed integrity check "
                        "(digest %s.. != %s..); dropping entry",
                        self.root, key, digest[:12], e["digest"][:12])
            return None
        return blob

    def _delete_locked(self, key):
        e = self._manifest["entries"].pop(key)
        self._drop_blob_if_unreferenced(e["digest"])

    def _drop_blob_if_unreferenced(self, digest):
        if any(e["digest"] == digest
               for e in self._manifest["entries"].values()):
            return
        try:
            os.remove(self._obj_path(digest))
        except OSError:
            pass

    # -- jax compile-cache GC (ROADMAP: count jax_cache against the
    #    budget) ---------------------------------------------------------------

    def _jax_cache_files(self):
        """[(path, mtime, size)] of every file under the store-owned JAX
        persistent compile cache (all machine-fingerprint partitions)."""
        root = os.path.join(self.root, JAX_CACHE_SUBDIR)
        out = []
        for dirpath, _dirs, files in os.walk(root):
            for fname in files:
                path = os.path.join(dirpath, fname)
                try:
                    st = os.stat(path)
                except OSError:  # pragma: no cover - concurrent cleanup
                    continue
                out.append((path, st.st_mtime, st.st_size))
        return out

    def _jax_cache_path(self, rel):
        """Absolute path for one cache-relative name, REFUSING anything
        that escapes the cache root (peer-supplied names ride the wire —
        a traversal like `../manifest.json` must be a loud error)."""
        root = os.path.realpath(os.path.join(self.root, JAX_CACHE_SUBDIR))
        rel = rel.replace("/", os.sep)
        path = os.path.realpath(os.path.join(root, rel))
        if os.path.isabs(rel) or path == root \
                or not path.startswith(root + os.sep):
            raise ValueError(f"jax-cache name escapes the cache: {rel!r}")
        return path

    def jax_cache_list(self):
        """Cache-relative names (posix separators — the wire form used
        by STORE_LIST's jaxcache:<rel> pseudo-keys) of every compile-
        cache file, all machine-fingerprint partitions."""
        root = os.path.join(self.root, JAX_CACHE_SUBDIR)
        return sorted(
            os.path.relpath(path, root).replace(os.sep, "/")
            for path, _m, _s in self._jax_cache_files())

    def jax_cache_has(self, rel):
        try:
            return os.path.exists(self._jax_cache_path(rel))
        except ValueError:
            return False

    def jax_cache_read(self, rel):
        """Bytes of one cache file, or None (missing / escaping name)."""
        try:
            with open(self._jax_cache_path(rel), "rb") as f:
                return f.read()
        except (ValueError, OSError):
            return None

    def jax_cache_write(self, rel, blob):
        """Install one synced compile-cache file (warm rejoin): atomic
        tmp+rename like artifact blobs — jax must never see a torn
        entry. Budget enforcement stays with the normal sweeps."""
        path = self._jax_cache_path(rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def jax_cache_bytes(self):
        """Fresh walk of the compile-cache tree (also refreshes the total
        that stats() reports without walking)."""
        with self._lock:
            total = sum(s for _, _, s in self._jax_cache_files())
            self._jax_cache_bytes = total
            self.metrics.gauge("jax_cache_bytes", total)
            return total

    def sweep_jax_cache(self):
        """Bound the store-owned JAX compile cache: artifact entries plus
        compiled executables share ONE `byte_budget`, with the compile
        cache yielding first (its blobs are deterministic recompiles,
        cheaper to lose than a trusted-setup key). Eviction is
        oldest-mtime first — the cache is content-keyed and written
        once, so mtime order IS insertion order. Returns files removed.
        Lock-free across processes by design: a concurrent sweeper
        deleting the same file is a tolerated ENOENT, and jax treats a
        missing cache entry as a plain miss."""
        with self._lock:
            return self._sweep_jax_cache_locked()

    def _sweep_jax_cache_locked(self):
        files = sorted(self._jax_cache_files(), key=lambda f: f[1])
        total = sum(s for _, _, s in files)
        removed = 0
        if self.byte_budget is not None:
            ents = self._manifest["entries"]
            allowed = self.byte_budget - sum(
                e["bytes"] for e in ents.values())
            for path, _mtime, size in files:
                if total <= max(allowed, 0):
                    break
                try:
                    os.remove(path)
                except OSError:  # pragma: no cover - concurrent sweep/use
                    continue
                total -= size
                removed += 1
                self.metrics.inc("jax_cache_evictions")
        self._jax_cache_bytes = total
        self.metrics.gauge("jax_cache_bytes", total)
        return removed

    def _maybe_sweep_jax_cache(self):
        """Throttled sweep (DPT_STORE_JAX_SWEEP_S, default 300 s):
        put() calls this so a serving process periodically re-bounds the
        compile cache without a dedicated timer thread. Callers hold
        self._lock."""
        now = time.monotonic()
        if now - self._last_jax_sweep < self._jax_sweep_interval:
            return
        self._last_jax_sweep = now
        self._sweep_jax_cache_locked()

    def _evict_over_budget(self, protect=None):
        if self.byte_budget is None:
            return
        ents = self._manifest["entries"]
        total = sum(e["bytes"] for e in ents.values())
        # oldest-use first; the just-written entry survives even when it is
        # alone over budget (an empty store that can't hold its one artifact
        # would defeat the cache entirely)
        for key in sorted(ents, key=lambda k: ents[k]["seq"]):
            if total <= self.byte_budget:
                break
            if key == protect:
                continue
            total -= ents[key]["bytes"]
            self._delete_locked(key)
            self.metrics.inc("evictions")
