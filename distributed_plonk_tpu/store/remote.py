"""Cross-host artifact fetch: pull store blobs from a serving peer.

The client side of the STORE_FETCH wire tag (runtime/protocol.py): a fresh
or replacement host asks a peer that already holds an artifact — bucket
keys, an SRS, a mid-prove checkpoint — for its bytes instead of rebuilding
them. Cold start and cross-host resume become one network copy (ROADMAP
direction 2: store-backed distributed serving).

Trust model: the peer is inside the deployment but the network is not
infallible — every fetched blob is re-hashed locally and compared to the
digest the peer advertised BEFORE it is written into the local store, so
a truncated/garbled transfer is a loud error, never a poisoned cache
(the local store then re-verifies on every read, as always).

Servers: the proof service answers STORE_FETCH when started with a store
(service/server.py); runtime workers answer it when launched with
--store (runtime/worker.py) so the fleet can serve each other without
routing through the dispatcher.
"""

import hashlib
import os
import time

from ..runtime import native, protocol
from ..runtime.health import NullMetrics

# STORE_FETCH/STORE_LIST pseudo-key prefix for jax persistent-compile-
# cache FILES (they live under the store root but outside the artifact
# manifest): `jaxcache:<cache-relative posix path>`. Syncing these is
# the compiled-exec half of warm rejoin — a replacement worker reaches
# first-kernel-launch on compile-cache HITS instead of minutes of
# recompiles (ROADMAP direction-2 remainder).
JAX_CACHE_PREFIX = "jaxcache:"


class FetchError(RuntimeError):
    pass


def serve_fetch(store, payload, conn, metrics=None,
                no_store_reason="no store on this server"):
    """Answer one STORE_FETCH request on `conn` — the server side of
    `fetch_blob`, shared by the proof service frontend
    (service/server.py) and runtime workers launched with --store
    (runtime/worker.py) so the two servers cannot skew. Advertises the
    digest the store just verified the blob against (`get_entry`)
    instead of re-hashing a possibly multi-MB blob per fetch.
    `jaxcache:<rel>` pseudo-keys serve compile-cache FILES (hashed here
    — they carry no manifest digest; escaping names are a miss)."""
    metrics = metrics or NullMetrics()
    if store is None:
        conn.send(protocol.ERR, protocol.encode_json(
            {"reason": no_store_reason}))
        return
    key = protocol.decode_json(payload).get("key")
    if key and key.startswith(JAX_CACHE_PREFIX):
        blob = store.jax_cache_read(key[len(JAX_CACHE_PREFIX):])
        if blob is None:
            metrics.inc("store_fetch_misses")
            conn.send(protocol.ERR, protocol.encode_json(
                {"reason": f"unknown key {key!r}"}))
            return
        metrics.inc("store_fetch_served")
        metrics.inc("store_fetch_bytes", len(blob))
        header = {"key": key, "digest": hashlib.sha256(blob).hexdigest(),
                  "meta": {"kind": "jax_cache"}}
        conn.send(protocol.OK, protocol.encode_result(header, blob))
        return
    hit = store.get_entry(key) if key else None
    if hit is None:
        metrics.inc("store_fetch_misses")
        conn.send(protocol.ERR, protocol.encode_json(
            {"reason": f"unknown key {key!r}"}))
        return
    blob, digest, meta = hit
    metrics.inc("store_fetch_served")
    metrics.inc("store_fetch_bytes", len(blob))
    header = {"key": key, "digest": digest, "meta": meta}
    conn.send(protocol.OK, protocol.encode_result(header, blob))


def serve_list(store, payload, conn, metrics=None,
               no_store_reason="no store on this server"):
    """Answer one STORE_LIST request: manifest keys plus jaxcache:<rel>
    pseudo-keys, filtered by the requested prefix — how a joining worker
    learns what a roster peer can serve it for warm rejoin."""
    metrics = metrics or NullMetrics()
    if store is None:
        conn.send(protocol.ERR, protocol.encode_json(
            {"reason": no_store_reason}))
        return
    prefix = protocol.decode_json(payload).get("prefix", "") or ""
    keys = [k for k in store.keys() if k.startswith(prefix)]
    keys += [k for k in (JAX_CACHE_PREFIX + rel
                         for rel in store.jax_cache_list())
             if k.startswith(prefix)]
    metrics.inc("store_list_served")
    conn.send(protocol.OK, protocol.encode_json({"keys": sorted(keys)}))


def fetch_blob(host, port, key, timeout_ms=30000):
    """-> (meta dict, blob bytes) from the peer, digest-verified.

    Raises FetchError when the peer lacks the key or the transfer fails
    integrity (callers treat either as a miss and fall back to a build).
    """
    # bound the dial too: peer fetch may run under the scheduler's bucket
    # lock, and a partitioned (SYN-dropped) peer must cost a bounded wait
    # there, not the OS connect default of minutes
    conn = native.connect(host, port, timeout_ms=timeout_ms)
    try:
        if timeout_ms:
            conn.set_timeout(timeout_ms)
        conn.send(protocol.STORE_FETCH, protocol.encode_json({"key": key}))
        rtag, rpayload = conn.recv()
    finally:
        conn.close()
    if rtag != protocol.OK:
        raise FetchError(
            f"peer {host}:{port} has no {key!r}: "
            f"{protocol.decode_json(rpayload).get('reason')}")
    header, blob = protocol.decode_result(rpayload)
    digest = hashlib.sha256(blob).hexdigest()
    if digest != header.get("digest"):
        raise FetchError(
            f"digest mismatch fetching {key!r} from {host}:{port} "
            f"({digest[:12]} != {str(header.get('digest'))[:12]})")
    return header.get("meta") or {}, blob


def fetch_into(store, host, port, key, timeout_ms=30000):
    """Fetch `key` from the peer into the local store. Returns the blob,
    or None when the peer lacks it / the transfer failed verification
    (logged by the caller's metrics, not raised: peer fetch is an
    optimization tier, the build tier still exists below it)."""
    try:
        meta, blob = fetch_blob(host, port, key, timeout_ms=timeout_ms)
    except (FetchError, ConnectionError, OSError):
        return None
    store.put(key, blob, meta=meta)
    return blob


def list_keys(host, port, prefix="", timeout_ms=10000):
    """Peer's STORE_LIST for one prefix -> [key]. Raises FetchError when
    the peer serves no store (callers treat it as an empty peer)."""
    conn = native.connect(host, port, timeout_ms=timeout_ms)
    try:
        if timeout_ms:
            conn.set_timeout(timeout_ms)
        conn.send(protocol.STORE_LIST,
                  protocol.encode_json({"prefix": prefix}))
        rtag, rpayload = conn.recv()
    finally:
        conn.close()
    if rtag != protocol.OK:
        raise FetchError(
            f"peer {host}:{port} cannot list: "
            f"{protocol.decode_json(rpayload).get('reason')}")
    return protocol.decode_json(rpayload).get("keys", [])


def sync_jax_cache(store, host, port, timeout_ms=30000, keys=None):
    """Copy the peer's jax persistent-compile-cache entries this store
    lacks (digest-verified per file, atomic installs). Returns the count
    copied. Cache entries are keyed by content inside jax, so an entry
    already present locally is never re-fetched, and a half-synced cache
    is still strictly warmer than an empty one. `keys`: a key list the
    caller already fetched from this peer (warm_sync passes its
    unprefixed listing, saving a second STORE_LIST round trip)."""
    copied = 0
    if keys is None:
        keys = list_keys(host, port, prefix=JAX_CACHE_PREFIX,
                         timeout_ms=timeout_ms)
    for key in keys:
        if not key.startswith(JAX_CACHE_PREFIX):
            continue
        rel = key[len(JAX_CACHE_PREFIX):]
        if store.jax_cache_has(rel):
            continue
        try:
            _meta, blob = fetch_blob(host, port, key, timeout_ms=timeout_ms)
            store.jax_cache_write(rel, blob)
        except (FetchError, ConnectionError, OSError, ValueError):
            continue  # one bad file must not abort the sync
        copied += 1
    return copied


# artifact-key prefixes a joining worker pulls from roster peers: bucket
# keys carry the SRS + proving/verifying keys (keycache.py layout) and
# autotune: keys the per-fingerprint kernel calibration plans
# (store/calibration.py) — the expensive-to-rebuild/-remeasure state.
# Checkpoints/proofs stay fetch-on-demand (they are job-scoped, not
# shape-scoped). A synced plan only activates on a host whose
# fingerprint matches (load_plan rejects foreign plans), so pulling
# every fingerprint's plan is cheap insurance, never a wrong config.
WARM_SYNC_PREFIXES = tuple(
    p for p in os.environ.get(
        "DPT_WARM_SYNC_PREFIXES", "bucket:,autotune:").split(",") if p)


def warm_sync(store, peers, prefixes=None, timeout_ms=10000):
    """Warm-rejoin sync: pull every missing `prefixes` artifact AND the
    jax compile-cache entries from each peer in order. Per-peer/per-key
    failures are skipped — the sync is an accelerator, never a gate.
    Returns a stats dict ({warm_rejoin_s, artifacts, jax_cache_files,
    peers, errors}) for the JOIN phase=ready report."""
    t0 = time.monotonic()
    prefixes = WARM_SYNC_PREFIXES if prefixes is None else tuple(prefixes)
    stats = {"artifacts": 0, "jax_cache_files": 0, "peers": 0, "errors": 0}
    have = set(store.keys())
    for host, port in peers:
        try:
            keys = list_keys(host, port, timeout_ms=timeout_ms)
        except (FetchError, ConnectionError, OSError):
            stats["errors"] += 1
            continue
        stats["peers"] += 1
        for key in keys:
            if key in have or not key.startswith(prefixes):
                continue
            if fetch_into(store, host, port, key,
                          timeout_ms=timeout_ms) is not None:
                have.add(key)
                stats["artifacts"] += 1
        try:
            stats["jax_cache_files"] += sync_jax_cache(
                store, host, port, timeout_ms=timeout_ms, keys=keys)
        except (FetchError, ConnectionError, OSError):
            stats["errors"] += 1
    stats["warm_rejoin_s"] = round(time.monotonic() - t0, 6)
    return stats
