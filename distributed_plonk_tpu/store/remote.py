"""Cross-host artifact fetch: pull store blobs from a serving peer.

The client side of the STORE_FETCH wire tag (runtime/protocol.py): a fresh
or replacement host asks a peer that already holds an artifact — bucket
keys, an SRS, a mid-prove checkpoint — for its bytes instead of rebuilding
them. Cold start and cross-host resume become one network copy (ROADMAP
direction 2: store-backed distributed serving).

Trust model: the peer is inside the deployment but the network is not
infallible — every fetched blob is re-hashed locally and compared to the
digest the peer advertised BEFORE it is written into the local store, so
a truncated/garbled transfer is a loud error, never a poisoned cache
(the local store then re-verifies on every read, as always).

Servers: the proof service answers STORE_FETCH when started with a store
(service/server.py); runtime workers answer it when launched with
--store (runtime/worker.py) so the fleet can serve each other without
routing through the dispatcher.
"""

import hashlib

from ..runtime import native, protocol
from ..runtime.health import NullMetrics


class FetchError(RuntimeError):
    pass


def serve_fetch(store, payload, conn, metrics=None,
                no_store_reason="no store on this server"):
    """Answer one STORE_FETCH request on `conn` — the server side of
    `fetch_blob`, shared by the proof service frontend
    (service/server.py) and runtime workers launched with --store
    (runtime/worker.py) so the two servers cannot skew. Advertises the
    digest the store just verified the blob against (`get_entry`)
    instead of re-hashing a possibly multi-MB blob per fetch."""
    metrics = metrics or NullMetrics()
    if store is None:
        conn.send(protocol.ERR, protocol.encode_json(
            {"reason": no_store_reason}))
        return
    key = protocol.decode_json(payload).get("key")
    hit = store.get_entry(key) if key else None
    if hit is None:
        metrics.inc("store_fetch_misses")
        conn.send(protocol.ERR, protocol.encode_json(
            {"reason": f"unknown key {key!r}"}))
        return
    blob, digest, meta = hit
    metrics.inc("store_fetch_served")
    metrics.inc("store_fetch_bytes", len(blob))
    header = {"key": key, "digest": digest, "meta": meta}
    conn.send(protocol.OK, protocol.encode_result(header, blob))


def fetch_blob(host, port, key, timeout_ms=30000):
    """-> (meta dict, blob bytes) from the peer, digest-verified.

    Raises FetchError when the peer lacks the key or the transfer fails
    integrity (callers treat either as a miss and fall back to a build).
    """
    # bound the dial too: peer fetch may run under the scheduler's bucket
    # lock, and a partitioned (SYN-dropped) peer must cost a bounded wait
    # there, not the OS connect default of minutes
    conn = native.connect(host, port, timeout_ms=timeout_ms)
    try:
        if timeout_ms:
            conn.set_timeout(timeout_ms)
        conn.send(protocol.STORE_FETCH, protocol.encode_json({"key": key}))
        rtag, rpayload = conn.recv()
    finally:
        conn.close()
    if rtag != protocol.OK:
        raise FetchError(
            f"peer {host}:{port} has no {key!r}: "
            f"{protocol.decode_json(rpayload).get('reason')}")
    header, blob = protocol.decode_result(rpayload)
    digest = hashlib.sha256(blob).hexdigest()
    if digest != header.get("digest"):
        raise FetchError(
            f"digest mismatch fetching {key!r} from {host}:{port} "
            f"({digest[:12]} != {str(header.get('digest'))[:12]})")
    return header.get("meta") or {}, blob


def fetch_into(store, host, port, key, timeout_ms=30000):
    """Fetch `key` from the peer into the local store. Returns the blob,
    or None when the peer lacks it / the transfer failed verification
    (logged by the caller's metrics, not raised: peer fetch is an
    optimization tier, the build tier still exists below it)."""
    try:
        meta, blob = fetch_blob(host, port, key, timeout_ms=timeout_ms)
    except (FetchError, ConnectionError, OSError):
        return None
    store.put(key, blob, meta=meta)
    return blob
