"""Bucket key (SRS + proving/verifying key) <-> bytes, for the ArtifactStore.

The serialization layer between `service.jobs.build_bucket_keys` output and
`store.artifacts.ArtifactStore` blobs: everything a restarted server needs
to serve a previously seen circuit shape without re-running trusted setup
or preprocess. Proofs made with a deserialized proving key are
byte-identical to ones made with the freshly built key (pinned by
tests/test_store.py), so checkpoint fingerprints and golden fixtures keep
working across a restart.

Layout (versioned; all offsets fixed once the JSON header is read):

    magic "DPTK" | u16 version | u32 header_len | header JSON | body

header: domain_size, num_inputs, k (hex), n_powers, n_selectors, n_sigmas
body, in order:
    n_powers x 96B   SRS G1 powers, zcash uncompressed (encoding.py)
    18       x 96B   selector (13) + sigma (5) commitments, same format
    2        x 96B   g2, tau_g2, zcash compressed (full validation)
    13 x n   x 32B   selector polynomial coefficients, canonical LE Fr
    5  x n   x 32B   sigma polynomial coefficients, canonical LE Fr

Point loading uses a fast path: parse the uncompressed encoding and check
curve membership, but SKIP the per-point r-order subgroup check that
`encoding.g1_from_zcash` performs (~255 host Jacobian steps per point —
minutes for a 2^13-power SRS). The store is a local trust boundary whose
blobs we wrote ourselves and whose integrity SHA-256 already covers;
wire-facing paths (proof_io, encoding) keep the full zcash validation.
"""

import json
import struct

from ..constants import R_MOD, Q_MOD
from .. import curve as C
from .. import encoding as E
from .. import kzg
from ..poly import Domain
from ..circuit import NUM_WIRE_TYPES, NUM_SELECTORS

MAGIC = b"DPTK"
VERSION = 1

_PT = 96   # uncompressed G1
_FR = 32


def bucket_store_key(shape_key):
    """jobs.shape_key tuple -> stable manifest key string."""
    return "bucket:" + json.dumps(shape_key, separators=(",", ":"))


# -- finished-proof artifacts -------------------------------------------------
# Completed proofs join the same content-addressed surface as keys and
# checkpoints (ROADMAP direction 2): the service journal's DONE record
# carries the digest returned by store_proof, a restarted service serves
# the result without re-proving, and any peer can STORE_FETCH it
# cross-host. The blob is the raw proof_io layout (already a canonical
# fixed-size wire format — no extra framing needed).

def proof_store_key(job_id):
    """Service job id -> finished-proof manifest key."""
    return f"proof:{job_id}"


def store_proof(store, job_id, proof_bytes, public_input, spec_wire=None,
                retries=0):
    """Persist one finished proof; returns its content digest (journaled
    in the DONE record)."""
    meta = {"kind": "proof",
            "public_input": [hex(x) for x in public_input],
            "retries": retries}
    if spec_wire is not None:
        meta["spec"] = spec_wire
    return store.put(proof_store_key(job_id), proof_bytes, meta=meta)


def load_proof(store, job_id):
    """-> (proof_bytes, public_input ints, meta) or None (evicted /
    integrity failure — recovery degrades to a re-prove, never crashes)."""
    hit = store.get_entry(proof_store_key(job_id))
    if hit is None:
        return None
    blob, _digest, meta = hit
    pub = [int(x, 16) for x in meta.get("public_input", [])]
    return blob, pub, meta


# -- merged-trace artifacts ---------------------------------------------------
# One per-job distributed timeline (trace.merge_traces output) joins the
# content-addressed surface next to the proof it explains: the service
# stores it at job completion, /trace/<job_id> (serve.py --obs-port) and
# STORE_FETCH serve it, and bench/loadgen pin its digest. The blob is the
# merged dump as canonical compact JSON — to_chrome_trace() re-derives
# the viewer format on demand, so the stored artifact stays the richer,
# lossless representation.

def trace_store_key(job_id):
    """Service job id -> merged-trace manifest key."""
    return f"trace:{job_id}"


def store_trace(store, job_id, merged):
    """Persist one merged timeline; returns its content digest."""
    blob = json.dumps(merged, separators=(",", ":"),
                      sort_keys=True).encode()
    meta = {"kind": "trace", "trace_id": merged.get("trace_id"),
            "spans": len(merged.get("events") or []),
            "processes": len(merged.get("processes") or [])}
    return store.put(trace_store_key(job_id), blob, meta=meta)


def load_trace(store, job_id):
    """-> merged timeline dict, or None (evicted / integrity failure /
    undecodable — observability never crashes the serving path)."""
    hit = store.get_entry(trace_store_key(job_id))
    if hit is None:
        return None
    blob, _digest, _meta = hit
    try:
        return json.loads(blob.decode())
    except (ValueError, UnicodeDecodeError):
        return None


# -- batch-aggregate artifacts (aggregate.py, ISSUE 17) ------------------------
# One built aggregate (the canonical JSON blob aggregate.to_bytes emits)
# joins the content-addressed surface next to the proofs it folds:
# aggregate:<agg_id>, where <agg_id> is already the content address of
# the member list. The journal's AGG record carries the digest returned
# here, so a restarted service re-serves the artifact without refolding.

def aggregate_store_key(agg_id):
    return f"aggregate:{agg_id}"


def store_aggregate(store, agg_id, blob, members, kinds=None):
    """Persist one aggregate artifact; returns its content digest
    (journaled in the AGG record)."""
    meta = {"kind": "aggregate", "agg_id": agg_id,
            "members": list(members)}
    if kinds:
        meta["circuit_kinds"] = sorted(set(kinds))
    return store.put(aggregate_store_key(agg_id), blob, meta=meta)


def load_aggregate(store, agg_id):
    """-> (blob, meta) or None (evicted / integrity failure — clients
    can always refold from the member proofs, never crash)."""
    hit = store.get_entry(aggregate_store_key(agg_id))
    if hit is None:
        return None
    blob, _digest, meta = hit
    return blob, meta


# -- on-demand profile artifacts (obs/profiling.py) ---------------------------
# One PROFILE-tag capture (jax.profiler xplane tar.gz, or the pystacks
# JSON fallback) joins the content-addressed surface: profile:<id> where
# <id> is the blob's own digest prefix, served at /profile/<id> and
# linked from the trace timeline's obs/profile span.

def profile_store_key(profile_id):
    return f"profile:{profile_id}"


def store_profile(store, profile_id, blob, meta=None):
    """Persist one capture blob; returns its content digest."""
    m = {"kind": "profile", "profile_id": profile_id}
    m.update({k: v for k, v in (meta or {}).items()
              if isinstance(v, (int, float, str, bool))})
    return store.put(profile_store_key(profile_id), blob, meta=m)


def load_profile(store, profile_id):
    """-> (meta, blob), or None (evicted / integrity failure)."""
    hit = store.get_entry(profile_store_key(profile_id))
    if hit is None:
        return None
    blob, _digest, meta = hit
    return meta, blob


def _fr_bytes(x):
    assert 0 <= x < R_MOD
    return int(x).to_bytes(_FR, "little")


def _fr_load(b, off):
    x = int.from_bytes(b[off:off + _FR], "little")
    if x >= R_MOD:
        raise ValueError("scalar out of canonical range")
    return x


def _g1_load_fast(b, off):
    """Uncompressed zcash G1 -> affine point/None; on-curve check only
    (subgroup check skipped — see module docstring)."""
    raw = b[off:off + _PT]
    if len(raw) != _PT:
        raise ValueError("truncated point")
    if raw[0] & 0x40:  # infinity
        if any(raw[1:]) or (raw[0] & 0xBF):
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([raw[0] & 0x1F]) + raw[1:48], "big")
    y = int.from_bytes(raw[48:], "big")
    if x >= Q_MOD or y >= Q_MOD:
        raise ValueError("coordinate out of range")
    if (y * y - (pow(x, 3, Q_MOD) + 4)) % Q_MOD != 0:
        raise ValueError("point not on curve")
    return (x, y)


def _srs_powers(srs):
    """Host affine power list for either SRS flavor."""
    if isinstance(srs, kzg.DeviceSrs):
        return srs.powers_affine()
    return srs.powers_of_g1


def serialize_bucket(srs, pk, vk):
    """(srs, pk, vk) as built by jobs.build_bucket_keys -> one blob."""
    powers = _srs_powers(srs)
    selectors = pk.selectors   # materializes lazy device keys if needed
    sigmas = pk.sigmas
    n = vk.domain_size
    assert len(selectors) == NUM_SELECTORS and len(sigmas) == NUM_WIRE_TYPES
    header = {
        "domain_size": n,
        "num_inputs": vk.num_inputs,
        "k": [hex(x) for x in vk.k],
        "n_powers": len(powers),
    }
    h = json.dumps(header, separators=(",", ":")).encode()
    out = bytearray()
    out += MAGIC + struct.pack("<HI", VERSION, len(h)) + h
    for p in powers:
        out += E.g1_to_zcash(p, compressed=False)
    for p in list(vk.selector_comms) + list(vk.sigma_comms):
        out += E.g1_to_zcash(p, compressed=False)
    out += E.g2_to_zcash(vk.g2) + E.g2_to_zcash(vk.tau_g2)
    for poly in list(selectors) + list(sigmas):
        assert len(poly) == n, "coefficient vector length != domain size"
        for x in poly:
            out += _fr_bytes(x)
    return bytes(out)


def deserialize_bucket(blob):
    """Blob -> (srs, pk, vk) equal (element-for-element) to the build that
    produced it. Raises ValueError on any structural problem — callers
    treat that as a cache miss and rebuild."""
    if blob[:4] != MAGIC:
        raise ValueError("not a bucket-key blob")
    version, hlen = struct.unpack_from("<HI", blob, 4)
    if version != VERSION:
        raise ValueError(f"bucket blob version {version} != {VERSION}")
    off = 10
    header = json.loads(blob[off:off + hlen].decode())
    off += hlen
    n = header["domain_size"]
    n_powers = header["n_powers"]
    k = [int(x, 16) for x in header["k"]]

    want = (n_powers + NUM_SELECTORS + NUM_WIRE_TYPES) * _PT + 2 * 96 \
        + (NUM_SELECTORS + NUM_WIRE_TYPES) * n * _FR
    if len(blob) - off != want:
        raise ValueError(f"bucket blob body {len(blob) - off}B != {want}B")

    powers = []
    for _ in range(n_powers):
        powers.append(_g1_load_fast(blob, off))
        off += _PT
    comms = []
    for _ in range(NUM_SELECTORS + NUM_WIRE_TYPES):
        comms.append(_g1_load_fast(blob, off))
        off += _PT
    g2 = E.g2_from_zcash(blob[off:off + 96])
    tau_g2 = E.g2_from_zcash(blob[off + 96:off + 192])
    off += 192

    def frs(count):
        nonlocal off
        out = []
        for _ in range(count):
            out.append(_fr_load(blob, off))
            off += _FR
        return out

    selectors = [frs(n) for _ in range(NUM_SELECTORS)]
    sigmas = [frs(n) for _ in range(NUM_WIRE_TYPES)]

    srs = kzg.UniversalSrs(powers, g2, tau_g2)
    vk = kzg.VerifyingKey(
        domain_size=n, num_inputs=header["num_inputs"],
        selector_comms=comms[:NUM_SELECTORS],
        sigma_comms=comms[NUM_SELECTORS:],
        k=k, g1=C.G1_GEN, g2=g2, tau_g2=tau_g2)
    ck = kzg.pad_commit_key(powers, n + 3)
    pk = kzg.ProvingKey(ck, selectors, sigmas, vk, Domain(n))
    return srs, pk, vk


# -- ArtifactStore bridge -----------------------------------------------------

def store_bucket(store, shape_key, srs, pk, vk, build_s=None):
    """Persist one bucket's keys; returns the content digest."""
    blob = serialize_bucket(srs, pk, vk)
    meta = {"domain_size": vk.domain_size, "kind": "bucket_keys",
            "format_version": VERSION}
    if build_s is not None:
        meta["build_s"] = round(build_s, 6)
    return store.put(bucket_store_key(shape_key), blob, meta=meta)


def load_bucket(store, shape_key):
    """-> (srs, pk, vk, meta) or None. A blob that fails to parse (stale
    format version, structural damage below the SHA-256's radar) is
    deleted so the rebuild repopulates the entry."""
    key = bucket_store_key(shape_key)
    blob = store.get(key)
    if blob is None:
        return None
    meta = store.meta(key) or {}
    try:
        srs, pk, vk = deserialize_bucket(blob)
    except Exception as e:
        # ANY parse failure is a miss-and-rebuild, per the module
        # contract: the blob shapes several exception families
        # (struct.error on a short header, ValueError on bad
        # points/scalars, AssertionError from pad_commit_key on an
        # undersized SRS, TypeError from malformed header JSON) and a
        # damaged artifact must never crash the scheduler
        import logging
        logging.getLogger("dpt.store").warning(
            "bucket blob for %r undeserializable (%s); rebuilding", key, e)
        store.delete(key)
        return None
    return srs, pk, vk, meta
