"""Artifact store + warm start: the persistence layer under the service.

The layer between key setup and the serving path that turns every server
restart and repeat circuit shape into a warm hit (ROADMAP: cold-start is
the dominant serving cost at scale):

    artifacts.py    content-addressed on-disk store — SHA-256 integrity,
                    atomic writes, versioned manifest, LRU byte budget
    keycache.py     SRS/proving-key/verifying-key <-> blob serialization
                    (encoding/proof_io wire idioms; load == fresh build,
                    element for element)
    warmstart.py    store-owned JAX persistent-compile-cache dir + AOT
                    stage precompilation per shape bucket
    calibration.py  kernel-autotune plan artifacts (backend/autotune.py
                    winners keyed by machine fingerprint): load_or_run
                    is the service/worker startup entry point

Consumers: service.scheduler.BucketCache (memory -> disk -> build tiers),
the WARMUP wire tag (service/server.py), scripts/warmup.py +
scripts/autotune.py, bench.py's cold-vs-warm service round trip,
tests/test_store.py + tests/test_autotune.py.
"""

from .artifacts import ArtifactStore
from .keycache import (bucket_store_key, serialize_bucket,
                       deserialize_bucket, store_bucket, load_bucket,
                       proof_store_key, store_proof, load_proof,
                       trace_store_key, store_trace, load_trace,
                       profile_store_key, store_profile, load_profile)
from .warmstart import (set_jax_cache_env, configure_jax_cache,
                        aot_warmup, warm_spec)
from .remote import FetchError, fetch_blob, fetch_into
from .calibration import (plan_store_key, store_plan, load_plan,
                          load_or_run, parse_shapes)

__all__ = [
    "ArtifactStore", "bucket_store_key", "serialize_bucket",
    "deserialize_bucket", "store_bucket", "load_bucket",
    "proof_store_key", "store_proof", "load_proof",
    "trace_store_key", "store_trace", "load_trace",
    "profile_store_key", "store_profile", "load_profile",
    "set_jax_cache_env", "configure_jax_cache", "aot_warmup", "warm_spec",
    "FetchError", "fetch_blob", "fetch_into",
    "plan_store_key", "store_plan", "load_plan", "load_or_run",
    "parse_shapes",
]
