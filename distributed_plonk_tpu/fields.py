"""Pure-Python reference field arithmetic (the CPU oracle).

This plays the role the `ark-ff` crates play for the reference
(/root/reference/Cargo.toml:31-37): a trusted, simple implementation that the
TPU limb kernels are asserted bit-identical against, and that hosts the cheap
sequential protocol math (challenges, small inversions).

Representation: Fr/Fq elements are plain Python ints in [0, mod).
Extension tower (for the pairing-based verifier):
    Fq2  = Fq[u]/(u^2 + 1)            -> tuple (c0, c1)
    Fq6  = Fq2[v]/(v^3 - (u + 1))     -> tuple of 3 Fq2
    Fq12 = Fq6[w]/(w^2 - v)           -> tuple of 2 Fq6
"""

from .constants import R_MOD, Q_MOD, FR_GENERATOR, FR_ROOT_OF_UNITY, FR_TWO_ADICITY


# --- prime fields ------------------------------------------------------------

def fr_add(a, b):
    return (a + b) % R_MOD


def fr_sub(a, b):
    return (a - b) % R_MOD


def fr_mul(a, b):
    return (a * b) % R_MOD


def fr_neg(a):
    return (-a) % R_MOD


def fr_inv(a):
    if a == 0:
        raise ZeroDivisionError("Fr inverse of zero")
    return pow(a, R_MOD - 2, R_MOD)


def fr_pow(a, e):
    return pow(a, e, R_MOD)


def fq_add(a, b):
    return (a + b) % Q_MOD


def fq_sub(a, b):
    return (a - b) % Q_MOD


def fq_mul(a, b):
    return (a * b) % Q_MOD


def fq_neg(a):
    return (-a) % Q_MOD


def fq_inv(a):
    if a == 0:
        raise ZeroDivisionError("Fq inverse of zero")
    return pow(a, Q_MOD - 2, Q_MOD)


def batch_inverse(vals, mod):
    """Montgomery batch inversion: one modular inverse + 3(n-1) mults."""
    n = len(vals)
    if n == 0:
        return []
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        if v == 0:
            raise ZeroDivisionError("batch_inverse of zero")
        prefix[i + 1] = prefix[i] * v % mod
    inv_all = pow(prefix[n], mod - 2, mod)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % mod
        inv_all = inv_all * vals[i] % mod
    return out


def fr_root_of_unity(n):
    """Primitive n-th root of unity in Fr (n a power of two <= 2^32).

    Matches ark-poly's Radix2EvaluationDomain group_gen construction
    (used at /root/reference/src/worker.rs:49-54).
    """
    assert n & (n - 1) == 0 and n >= 1
    log_n = n.bit_length() - 1
    assert log_n <= FR_TWO_ADICITY
    return pow(FR_ROOT_OF_UNITY, 1 << (FR_TWO_ADICITY - log_n), R_MOD)


# --- Fq2 ---------------------------------------------------------------------

FQ2_ZERO = (0, 0)
FQ2_ONE = (1, 0)


def fq2_add(a, b):
    return ((a[0] + b[0]) % Q_MOD, (a[1] + b[1]) % Q_MOD)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % Q_MOD, (a[1] - b[1]) % Q_MOD)


def fq2_neg(a):
    return ((-a[0]) % Q_MOD, (-a[1]) % Q_MOD)


def fq2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u), u^2 = -1
    t0 = a[0] * b[0] % Q_MOD
    t1 = a[1] * b[1] % Q_MOD
    c0 = (t0 - t1) % Q_MOD
    c1 = ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % Q_MOD
    return (c0, c1)


def fq2_sq(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    c0 = (a[0] + a[1]) * (a[0] - a[1]) % Q_MOD
    c1 = 2 * a[0] * a[1] % Q_MOD
    return (c0, c1)


def fq2_scalar(a, k):
    return (a[0] * k % Q_MOD, a[1] * k % Q_MOD)


def fq2_conj(a):
    return (a[0], (-a[1]) % Q_MOD)


def fq2_inv(a):
    # 1/(a0 + a1 u) = (a0 - a1 u)/(a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % Q_MOD
    ninv = fq_inv(norm)
    return (a[0] * ninv % Q_MOD, (-a[1]) * ninv % Q_MOD)


# nonresidue xi = u + 1 (Fq6 = Fq2[v]/(v^3 - xi))
FQ2_XI = (1, 1)


def fq2_mul_by_xi(a):
    # (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    return ((a[0] - a[1]) % Q_MOD, (a[0] + a[1]) % Q_MOD)


# --- Fq6 ---------------------------------------------------------------------

FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a, b):
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a, b):
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a):
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    c0 = fq2_add(t0, fq2_mul_by_xi(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), fq2_add(t1, t2))))
    c1 = fq2_add(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), fq2_add(t0, t1)), fq2_mul_by_xi(t2))
    c2 = fq2_add(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), fq2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fq6_sq(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    # v * (a0 + a1 v + a2 v^2) = xi a2 + a0 v + a1 v^2
    return (fq2_mul_by_xi(a[2]), a[0], a[1])


def fq6_inv(a):
    a0, a1, a2 = a
    c0 = fq2_sub(fq2_sq(a0), fq2_mul_by_xi(fq2_mul(a1, a2)))
    c1 = fq2_sub(fq2_mul_by_xi(fq2_sq(a2)), fq2_mul(a0, a1))
    c2 = fq2_sub(fq2_sq(a1), fq2_mul(a0, a2))
    t = fq2_add(fq2_mul_by_xi(fq2_add(fq2_mul(a2, c1), fq2_mul(a1, c2))), fq2_mul(a0, c0))
    tinv = fq2_inv(t)
    return (fq2_mul(c0, tinv), fq2_mul(c1, tinv), fq2_mul(c2, tinv))


# --- Fq12 --------------------------------------------------------------------

FQ12_ONE = (FQ6_ONE, FQ6_ZERO)


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sq(a):
    return fq12_mul(a, a)


def fq12_inv(a):
    a0, a1 = a
    t = fq6_sub(fq6_sq(a0), fq6_mul_by_v(fq6_sq(a1)))
    tinv = fq6_inv(t)
    return (fq6_mul(a0, tinv), fq6_neg(fq6_mul(a1, tinv)))


def fq12_conj(a):
    return (a[0], fq6_neg(a[1]))


def fq12_pow(a, e):
    result = FQ12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_sq(base)
        e >>= 1
    return result
