"""Value-semantics pass: prove what registered kernels COMPUTE, not
just what ranges they stay in.

The bounds pass (bounds.py) walks a kernel's jaxpr with interval +
exactness abstract values and proves the machine arithmetic never
wraps, never rounds, and never leaves its declared limb ranges.  That
makes the machine semantics EQUAL to exact integer semantics — but it
says nothing about WHICH integer function the kernel computes.  A
dropped carry lane in `mont_mul`'s high-half assembly stays comfortably
inside every interval (the lane is < 2^16 either way) while silently
changing the product mod p.  On the u32 path that bug is caught
operationally by parity tests; on the f32/MXU path nothing checks it.

This module closes that gap with a second interpreter over the SAME
traced jaxpr: an exact big-integer/rational evaluator.  Every cell is a
numpy object array of Python ints (or `fractions.Fraction` for the f32
byte-product intermediates — exact binary fractions, so `floor(x *
2**-8)` means exactly what the lazy-carry local rounds claim).  Because
the bounds pass has already proven machine == exact-integer semantics,
evaluating the jaxpr exactly and checking an algebraic contract at
sampled points IS a statement about the machine kernel:

    bounds pass   ⊢  machine semantics == exact semantics
    value pass    ⊢  exact semantics   ⊨  value contract
    ───────────────────────────────────────────────────────
                  ⊢  machine kernel satisfies the contract

Contracts are per-entry (registry.Entry.value_contract) and algebraic:
`value(out) ≡ value(a)·value(b)·R⁻¹ (mod p)` for Montgomery background
multipliers, `value(limbs) + carry·2^(16·K) == value(cols)` EXACTLY for
`_carry_sweep`, `value(out) = DFT·value(in) (mod p)` for the NTT stage
pipelines (Fr-linearity makes the plain-Python poly oracle apply to raw
limb values in both Montgomery and plain boundaries), and so on.
Sample points are seeded-random field elements plus the corner values
0, 1, p-1 — a dropped carry lane / off-by-one limb shift / wrong
modulus constant is not a measure-zero bug, it changes the value at
almost every point, so a handful of samples rejects each class (the
mutant harness in analysis/mutants.py demonstrates this).

Nothing here executes on a device: the interpreter consumes the jaxpr
that `jax.make_jaxpr` produced on abstract inputs and evaluates it in
pure Python (the one exception: `gather` index arithmetic is resolved
by binding the real primitive on concrete int32 POSITION arrays — host
numpy, still no kernel values near a device).
"""

import math
import operator
from fractions import Fraction

import numpy as np
import jax
import jax.numpy as jnp
import jax.tree_util as jtu

from .bounds import Violation, _CALL_PRIMS

__all__ = [
    "Violation", "UnsupportedPrim", "ExactInterpreter", "to_exact",
    "run_exact", "check_value", "limb_value", "limbs_from_int",
    "rand_fe", "mont_r", "elementwise", "mismatch_report",
]

_MAX_WHILE_ITERS = 1 << 20


class UnsupportedPrim(Exception):
    """A primitive (or primitive mode) the exact evaluator cannot model
    faithfully.  Strict mode turns this into a Violation: silently
    skipping an op would let a kernel rewrite smuggle unvetted
    arithmetic past the value pass."""


# -- exact value conversion ----------------------------------------------------

def _exact_scalar(v):
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, Fraction):
        return v
    f = float(v)
    if math.isnan(f) or math.isinf(f):
        raise UnsupportedPrim(f"non-finite constant {f!r}")
    if f.is_integer():
        return int(f)
    return Fraction(f)  # exact: binary float -> dyadic rational


_EXACTIFY = np.frompyfunc(_exact_scalar, 1, 1)


def to_exact(x):
    """numpy/jax array (or scalar) -> object ndarray of exact values:
    Python int / bool / Fraction (floats convert EXACTLY — a binary
    float is a dyadic rational)."""
    a = np.asarray(x)
    if a.dtype == object:
        return a.copy()
    return np.asarray(_EXACTIFY(a), dtype=object)


def _obj(x):
    return np.asarray(x, dtype=object)


def _to_index_array(x):
    """object array of exact ints -> int64 numpy array (for binding
    position/index primitives)."""
    a = _obj(x)
    out = np.empty(a.shape, dtype=np.int64)
    flat, of = a.reshape(-1), out.reshape(-1)
    for i in range(a.size):
        v = flat[i]
        if isinstance(v, Fraction):
            raise UnsupportedPrim("non-integer used as an index")
        of[i] = int(v)
    return out


def _ew(fn, *xs):
    """Elementwise with numpy broadcasting over object arrays."""
    xs = [_obj(x) for x in xs]
    return np.asarray(np.frompyfunc(fn, len(xs), 1)(*xs), dtype=object)


elementwise = _ew  # public alias for contract builders


def _scalar_of(x):
    a = _obj(x)
    if a.size != 1:
        raise UnsupportedPrim(f"expected scalar, got shape {a.shape}")
    return a.reshape(-1)[0]


# -- exact scalar ops matching XLA integer semantics ---------------------------

def _srl(a, s):
    if a < 0:
        # logical shift on a negative value reinterprets the two's
        # complement bits; the exact value would diverge from the
        # machine and the bounds pass cannot have proven otherwise
        raise UnsupportedPrim("shift_right_logical on negative value")
    return a >> s


def _trunc_div(a, b):
    if isinstance(a, Fraction) or isinstance(b, Fraction):
        return a / b  # float path: exactness is the bounds pass's job
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _trunc_rem(a, b):
    return a - _trunc_div(a, b) * b


_ELEMENTWISE = {
    "add": operator.add,
    "sub": operator.sub,
    "mul": operator.mul,
    "neg": operator.neg,
    "max": max,
    "min": min,
    "and": operator.and_,
    "or": operator.or_,
    "xor": operator.xor,
    "not": lambda v: (not v) if isinstance(v, bool) else ~v,
    "eq": operator.eq,
    "ne": operator.ne,
    "lt": operator.lt,
    "le": operator.le,
    "gt": operator.gt,
    "ge": operator.ge,
    "floor": math.floor,
    "ceil": math.ceil,
    "abs": abs,
    "sign": lambda v: (v > 0) - (v < 0),
    "shift_left": lambda a, s: a << s,
    "shift_right_logical": _srl,
    "shift_right_arithmetic": lambda a, s: a >> s,
    "div": _trunc_div,
    "rem": _trunc_rem,
    "clamp": lambda lo, v, hi: min(max(v, lo), hi),
    "square": lambda v: v * v,
}

_IDENTITY = {
    "device_put", "copy", "stop_gradient", "sharding_constraint",
    "optimization_barrier", "reduce_precision", "convert_element_type",
    "real",
}


# -- the interpreter -----------------------------------------------------------

class ExactInterpreter:
    """Evaluate a ClosedJaxpr exactly on object arrays of Python
    ints/Fractions.  Control flow (scan/while/cond/pallas grids) runs
    concretely; VMEM refs are mutable object arrays."""

    def __init__(self, kernel_name):
        self.kernel = kernel_name
        self._grids = []  # (grid_tuple, current_index_tuple) stack

    # -- plumbing --------------------------------------------------------------

    def run(self, closed_jaxpr, in_vals):
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        consts = getattr(closed_jaxpr, "consts", ())
        env = {}
        for var, const in zip(jaxpr.constvars, consts):
            env[var] = to_exact(const)
        if len(jaxpr.invars) != len(in_vals):
            raise UnsupportedPrim(
                f"arity mismatch: {len(jaxpr.invars)} invars, "
                f"{len(in_vals)} values")
        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = _obj(val)
        for eqn in jaxpr.eqns:
            ins = [self._read(env, v) for v in eqn.invars]
            outs = self._eqn(eqn, ins)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for var, val in zip(eqn.outvars, outs):
                env[var] = _obj(val)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _read(self, env, v):
        if isinstance(v, jax.core.Literal):
            return to_exact(v.val)
        return env[v]

    def _sub(self, eqn):
        p = eqn.params
        sub = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if sub is not None and not hasattr(sub, "consts"):
            sub = jax.core.ClosedJaxpr(sub, ())
        return sub

    def _eqn(self, eqn, ins):
        name = eqn.primitive.name
        if name in _CALL_PRIMS:
            sub = self._sub(eqn)
            if sub is None:
                raise UnsupportedPrim(f"call primitive '{name}' "
                                      "without a sub-jaxpr")
            n = len(sub.jaxpr.invars)
            return self.run(sub, ins[len(ins) - n:])
        if name in _ELEMENTWISE:
            return _ew(_ELEMENTWISE[name], *ins)
        if name in _IDENTITY:
            return self._convert(eqn, ins[0])
        handler = getattr(self, "_p_" + name.replace("-", "_"), None)
        if handler is None:
            raise UnsupportedPrim(
                f"unhandled primitive '{name}' in exact evaluation")
        return handler(eqn, ins)

    def _convert(self, eqn, x):
        dt = eqn.params.get("new_dtype")
        if dt is None:
            return x
        kind = np.dtype(dt).kind
        if kind in "iu":
            # truncation toward zero, exactly like XLA float->int;
            # int->narrower-int wrap is the bounds pass's problem (it
            # proves the value fits, so truncation == identity)
            return _ew(lambda v: int(v), x)
        if kind == "b":
            return _ew(lambda v: bool(v != 0), x)
        if kind == "f" or jnp.issubdtype(dt, jnp.floating):
            # int/Fraction value carried exactly (incl. bf16: the
            # bounds pass's float-exactness discipline is what makes
            # identity sound here)
            return x
        raise UnsupportedPrim(f"convert to unsupported dtype {dt}")

    # -- elementwise variants needing params -----------------------------------

    def _p_select_n(self, eqn, ins):
        which, *cases = ins
        return _ew(lambda w, *cs: cs[int(w)], which, *cases)

    def _p_integer_pow(self, eqn, ins):
        y = eqn.params["y"]
        return _ew(lambda v: v ** y, ins[0])

    def _p_is_finite(self, eqn, ins):
        return _ew(lambda v: True, ins[0])

    # -- structural ------------------------------------------------------------

    def _p_broadcast_in_dim(self, eqn, ins):
        shape = tuple(eqn.params["shape"])
        bdims = tuple(eqn.params["broadcast_dimensions"])
        a = ins[0]
        newshape = [1] * len(shape)
        for i, d in enumerate(bdims):
            newshape[d] = a.shape[i]
        return np.broadcast_to(a.reshape(newshape), shape).copy()

    def _p_reshape(self, eqn, ins):
        a = ins[0]
        dims = eqn.params.get("dimensions")
        if dims is not None:
            a = np.transpose(a, dims)
        return a.reshape(tuple(eqn.params["new_sizes"]))

    def _p_squeeze(self, eqn, ins):
        return np.squeeze(ins[0], axis=tuple(eqn.params["dimensions"]))

    def _p_expand_dims(self, eqn, ins):
        a = ins[0]
        for d in sorted(eqn.params["dimensions"]):
            a = np.expand_dims(a, d)
        return a

    def _p_transpose(self, eqn, ins):
        return np.transpose(ins[0], tuple(eqn.params["permutation"]))

    def _p_rev(self, eqn, ins):
        return np.flip(ins[0], axis=tuple(eqn.params["dimensions"]))

    def _p_slice(self, eqn, ins):
        p = eqn.params
        strides = p.get("strides") or (1,) * ins[0].ndim
        idx = tuple(slice(s, l, st) for s, l, st in
                    zip(p["start_indices"], p["limit_indices"], strides))
        return ins[0][idx].copy()

    def _p_dynamic_slice(self, eqn, ins):
        a, starts = ins[0], ins[1:]
        sizes = tuple(eqn.params["slice_sizes"])
        idx = []
        for d, (s, n) in enumerate(zip(starts, sizes)):
            s = int(_scalar_of(s))
            s = min(max(s, 0), a.shape[d] - n)  # XLA clamp semantics
            idx.append(slice(s, s + n))
        return a[tuple(idx)].copy()

    def _p_dynamic_update_slice(self, eqn, ins):
        a, u, starts = ins[0], ins[1], ins[2:]
        out = a.copy()
        idx = []
        for d, s in enumerate(starts):
            s = int(_scalar_of(s))
            s = min(max(s, 0), a.shape[d] - u.shape[d])
            idx.append(slice(s, s + u.shape[d]))
        out[tuple(idx)] = u
        return out

    def _p_concatenate(self, eqn, ins):
        return np.concatenate(ins, axis=eqn.params["dimension"])

    def _p_pad(self, eqn, ins):
        a, padval = ins[0], _scalar_of(ins[1])
        cfg = eqn.params["padding_config"]
        out_shape = tuple(
            lo + hi + n + max(n - 1, 0) * interior
            for n, (lo, hi, interior) in zip(a.shape, cfg))
        out = np.empty(out_shape, dtype=object)
        out[...] = padval
        pos_idx, src_idx = [], []
        for d, (lo, hi, interior) in enumerate(cfg):
            pos = lo + np.arange(a.shape[d]) * (interior + 1)
            keep = (pos >= 0) & (pos < out_shape[d])
            pos_idx.append(pos[keep])
            src_idx.append(np.arange(a.shape[d])[keep])
        if all(len(p) for p in pos_idx) or a.ndim == 0:
            out[np.ix_(*pos_idx)] = a[np.ix_(*src_idx)]
        return out

    def _p_iota(self, eqn, ins):
        shape = tuple(eqn.params["shape"])
        dim = eqn.params["dimension"]
        ar = to_exact(np.arange(shape[dim]))
        view = [1] * len(shape)
        view[dim] = shape[dim]
        return np.broadcast_to(ar.reshape(view), shape).copy()

    # -- reductions / contractions ---------------------------------------------

    def _p_reduce_sum(self, eqn, ins):
        return _obj(np.sum(ins[0], axis=tuple(eqn.params["axes"])))

    def _p_reduce_prod(self, eqn, ins):
        return _obj(np.prod(ins[0], axis=tuple(eqn.params["axes"])))

    def _p_reduce_max(self, eqn, ins):
        return _obj(np.maximum.reduce(
            ins[0], axis=tuple(eqn.params["axes"])[0]
            if len(eqn.params["axes"]) == 1 else None)) \
            if False else self._reduce_cmp(eqn, ins, max)

    def _p_reduce_min(self, eqn, ins):
        return self._reduce_cmp(eqn, ins, min)

    def _reduce_cmp(self, eqn, ins, fn):
        a = ins[0]
        for ax in sorted(eqn.params["axes"], reverse=True):
            a = _obj(np.frompyfunc(fn, 2, 1).reduce(a, axis=ax))
        return a

    def _p_reduce_and(self, eqn, ins):
        return _obj(np.all(ins[0], axis=tuple(eqn.params["axes"])))

    def _p_reduce_or(self, eqn, ins):
        return _obj(np.any(ins[0], axis=tuple(eqn.params["axes"])))

    def _p_argmax(self, eqn, ins):
        raise UnsupportedPrim("argmax has no exact-value story here")

    def _p_cumsum(self, eqn, ins):
        a, ax = ins[0], eqn.params["axis"]
        if eqn.params.get("reverse"):
            a = np.flip(a, axis=ax)
        out = np.cumsum(a, axis=ax)
        if eqn.params.get("reverse"):
            out = np.flip(out, axis=ax)
        return _obj(out)

    def _p_dot_general(self, eqn, ins):
        a, b = ins
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc, lb, rb = map(tuple, (lc, rc, lb, rb))
        lc2 = [d - sum(1 for bd in lb if bd < d) for d in lc]
        rc2 = [d - sum(1 for bd in rb if bd < d) for d in rc]
        if not lb:
            return _obj(np.tensordot(a, b, axes=(lc2, rc2)))
        lfree = [d for d in range(a.ndim) if d not in lc and d not in lb]
        rfree = [d for d in range(b.ndim) if d not in rc and d not in rb]
        out_shape = ([a.shape[d] for d in lb]
                     + [a.shape[d] for d in lfree]
                     + [b.shape[d] for d in rfree])
        out = np.empty(tuple(out_shape), dtype=object)
        for bpos in np.ndindex(*[a.shape[d] for d in lb]):
            ai = [slice(None)] * a.ndim
            bi = [slice(None)] * b.ndim
            for d, i in zip(lb, bpos):
                ai[d] = i
            for d, i in zip(rb, bpos):
                bi[d] = i
            out[bpos] = np.tensordot(a[tuple(ai)], b[tuple(bi)],
                                     axes=(lc2, rc2))
        return out

    # -- gather / scatter ------------------------------------------------------

    def _p_gather(self, eqn, ins):
        op, idx = ins
        # position-bind trick: run the REAL gather on flat positions
        # (host numpy int64, eager) and index the object array with the
        # result — index arithmetic stays primitive-faithful without
        # reimplementing XLA gather semantics
        pos = jnp.arange(op.size, dtype=jnp.int32).reshape(op.shape)
        out_pos = np.asarray(
            eqn.primitive.bind(
                pos, jnp.asarray(_to_index_array(idx).astype(np.int32)),
                **eqn.params))
        if out_pos.size and (out_pos.min() < 0
                             or out_pos.max() >= op.size):
            raise UnsupportedPrim(
                "gather out-of-bounds fill is not modelled")
        return op.reshape(-1)[out_pos]

    def _p_scatter_add(self, eqn, ins):
        return self._scatter(eqn, ins, combine="add")

    def _p_scatter(self, eqn, ins):
        return self._scatter(eqn, ins, combine="set")

    def _scatter(self, eqn, ins, combine):
        op, idx, upd = ins
        dn = eqn.params["dimension_numbers"]
        if (getattr(dn, "operand_batching_dims", ())
                or getattr(dn, "scatter_indices_batching_dims", ())):
            raise UnsupportedPrim("batched scatter dims not modelled")
        uwd = tuple(dn.update_window_dims)
        iwd = tuple(dn.inserted_window_dims)
        sdod = tuple(dn.scatter_dims_to_operand_dims)
        idx_np = _to_index_array(idx)
        if idx_np.ndim == 0:
            idx_np = idx_np.reshape(1)
        batch_shape, k = idx_np.shape[:-1], idx_np.shape[-1]
        usd = [d for d in range(upd.ndim) if d not in uwd]
        owd = [d for d in range(op.ndim) if d not in iwd]
        wsize = [1] * op.ndim
        for ud, od in zip(sorted(uwd), owd):
            wsize[od] = upd.shape[ud]
        out = op.copy()
        for bpos in np.ndindex(*batch_shape):
            start = idx_np[bpos]
            sv = [0] * op.ndim
            for j in range(k):
                sv[sdod[j]] = int(start[j])
            if any(sv[d] < 0 or sv[d] + wsize[d] > op.shape[d]
                   for d in range(op.ndim)):
                continue  # FILL_OR_DROP: out-of-bounds update dropped
            ui = [slice(None)] * upd.ndim
            for d, i in zip(usd, bpos):
                ui[d] = i
            u = _obj(upd[tuple(ui)])
            for wpos in np.ndindex(*u.shape):
                opos = list(sv)
                for od, w in zip(owd, wpos):
                    opos[od] += w
                if combine == "add":
                    out[tuple(opos)] = out[tuple(opos)] + u[wpos]
                else:
                    out[tuple(opos)] = u[wpos]
        return out

    # -- control flow (executed concretely) ------------------------------------

    def _p_scan(self, eqn, ins):
        p = eqn.params
        nc, nk = p["num_consts"], p["num_carry"]
        sub = p["jaxpr"]
        length = p["length"]
        consts, carry = list(ins[:nc]), list(ins[nc:nc + nk])
        xs = ins[nc + nk:]
        n_ys = len(sub.jaxpr.outvars) - nk
        order = range(length - 1, -1, -1) if p.get("reverse") \
            else range(length)
        collected = []
        for i in order:
            sliced = [_obj(x[i]) for x in xs]
            outs = self.run(sub, consts + carry + sliced)
            carry = [_obj(o) for o in outs[:nk]]
            collected.append(outs[nk:])
        if p.get("reverse"):
            collected.reverse()
        ys = []
        for j in range(n_ys):
            if collected:
                ys.append(_obj(np.stack([_obj(c[j]) for c in collected])))
            else:
                shape = tuple(eqn.outvars[nk + j].aval.shape)
                ys.append(np.empty(shape, dtype=object))
        return carry + ys

    def _p_while(self, eqn, ins):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cc, bc = list(ins[:cn]), list(ins[cn:cn + bn])
        carry = [list(ins[cn + bn:])][0]
        for _ in range(_MAX_WHILE_ITERS):
            pred = _scalar_of(self.run(p["cond_jaxpr"], cc + carry)[0])
            if not pred:
                return carry
            carry = [_obj(o) for o in self.run(p["body_jaxpr"],
                                               bc + carry)]
        raise UnsupportedPrim("while loop exceeded the exact-evaluation "
                              "iteration cap")

    def _p_cond(self, eqn, ins):
        branches = eqn.params["branches"]
        i = int(_scalar_of(ins[0]))
        i = min(max(i, 0), len(branches) - 1)
        return self.run(branches[i], list(ins[1:]))

    # -- pallas ----------------------------------------------------------------

    def _p_pallas_call(self, eqn, ins):
        p = eqn.params
        inner = p["jaxpr"]
        if not hasattr(inner, "consts"):
            inner = jax.core.ClosedJaxpr(inner, ())
        gm = p["grid_mapping"]
        if getattr(gm, "num_index_operands", 0):
            raise UnsupportedPrim("pallas index operands not modelled")
        grid = tuple(int(g) for g in gm.grid) or (1,)
        nin, nout = gm.num_inputs, gm.num_outputs
        bms = list(gm.block_mappings)
        outs = []
        for v in eqn.outvars:
            o = np.empty(tuple(v.aval.shape), dtype=object)
            o[...] = 0
            outs.append(o)
        scratch = []
        for v in inner.jaxpr.invars[nin + nout:]:
            s = np.empty(tuple(v.aval.shape), dtype=object)
            s[...] = 0
            scratch.append(s)
        operands = list(ins[:nin]) + outs

        def block_slices(bm, step):
            cj = bm.index_map_jaxpr
            bidx = self.run(cj, [_obj(i) for i in step])
            bshape = tuple(bm.block_shape)
            return tuple(
                slice(int(_scalar_of(b)) * n, int(_scalar_of(b)) * n + n)
                for b, n in zip(bidx, bshape))

        for step in np.ndindex(*grid):
            self._grids.append((grid, step))
            try:
                refs = []
                slcs = []
                for operand, bm in zip(operands, bms):
                    sl = block_slices(bm, step)
                    slcs.append(sl)
                    refs.append(operand[sl].copy())
                refs.extend(scratch)  # scratch persists across steps
                self.run(inner, refs)
                for j in range(nout):  # write out-blocks back
                    operands[nin + j][slcs[nin + j]] = refs[nin + j]
            finally:
                self._grids.pop()
        return outs

    def _ref_index(self, eqn, dyn):
        from jax._src.state.indexing import NDIndexer, Slice
        tree = eqn.params["tree"]
        leaves = [int(_scalar_of(x)) for x in dyn]
        nodes = jtu.tree_unflatten(tree, leaves)
        idx = []
        for nd in nodes:
            if isinstance(nd, NDIndexer):
                for s in nd.indices:
                    if isinstance(s, Slice):
                        idx.append(slice(int(s.start),
                                         int(s.start)
                                         + int(s.size) * int(s.stride),
                                         int(s.stride)))
                    elif isinstance(s, (int, np.integer)):
                        idx.append(int(s))
                    else:
                        raise UnsupportedPrim(
                            f"ref indexer {type(s).__name__} "
                            "not modelled")
            elif isinstance(nd, (int, np.integer)):
                idx.append(int(nd))
            else:
                raise UnsupportedPrim(
                    f"ref index node {type(nd).__name__} not modelled")
        return tuple(idx)

    def _p_get(self, eqn, ins):
        ref = ins[0]
        return _obj(ref[self._ref_index(eqn, ins[1:])]).copy()

    def _p_swap(self, eqn, ins):
        ref, val = ins[0], ins[1]
        idx = self._ref_index(eqn, ins[2:])
        old = _obj(ref[idx]).copy()
        ref[idx] = val
        return old

    def _p_addupdate(self, eqn, ins):
        ref, val = ins[0], ins[1]
        idx = self._ref_index(eqn, ins[2:])
        ref[idx] = ref[idx] + val
        return []

    def _p_program_id(self, eqn, ins):
        if not self._grids:
            raise UnsupportedPrim("program_id outside a pallas grid")
        return _obj(self._grids[-1][1][eqn.params["axis"]])

    def _p_num_programs(self, eqn, ins):
        if not self._grids:
            raise UnsupportedPrim("num_programs outside a pallas grid")
        return _obj(self._grids[-1][0][eqn.params["axis"]])

    def _p_debug_callback(self, eqn, ins):
        return []


# -- entry points --------------------------------------------------------------

def run_exact(name, fn, args):
    """Trace `fn` at the args' shapes/dtypes and evaluate the jaxpr
    exactly on the args' values.  `args` is a tuple (pytrees allowed)
    of concrete numpy arrays; returns the list of exact output object
    arrays."""
    specs = jtu.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                       np.asarray(a).dtype), tuple(args))
    closed = jax.make_jaxpr(fn)(*specs)
    flat = [to_exact(x) for x in jtu.tree_leaves(tuple(args))]
    return ExactInterpreter(name).run(closed, flat)


def check_value(name, fn, sampler, contract, samples=2, seed=0,
                strict=True):
    """Evaluate `fn` exactly at `samples` seeded sample points and run
    `contract(args, outs)` on each; returns a list of Violations.

    sampler(rng) -> concrete args tuple; contract(args, outs) -> list of
    error strings ([] / None when satisfied).  outs are object arrays of
    exact ints (the bounds pass separately proves machine == exact, so a
    contract failure here is a statement about the machine kernel)."""
    violations = []
    for s in range(samples):
        rng = np.random.default_rng((seed << 16) ^ (0x5eed + s))
        args = sampler(rng)
        try:
            outs = run_exact(name, fn, args)
        except UnsupportedPrim as e:
            if strict:
                violations.append(
                    Violation(name, "value", str(e), f"sample {s}"))
            return violations
        for msg in (contract(args, outs) or ()):
            violations.append(
                Violation(name, "value", msg, f"sample {s}"))
    return violations


# -- value algebra helpers -----------------------------------------------------

def limb_value(cols, bits=16, axis=0):
    """value(cols) = Σ cols[i] · 2^(bits·i) along `axis`, exactly.
    Returns an object array of Python ints shaped like cols minus
    `axis`."""
    a = np.moveaxis(_obj(cols), axis, 0)
    out = np.empty(a.shape[1:], dtype=object)
    out[...] = 0
    for i in range(a.shape[0]):
        out = out + _ew(int, a[i]) * (1 << (bits * i))
    return out


def limbs_from_int(v, n_limbs, bits=16, dtype=np.uint32):
    """Split an int into `n_limbs` little-endian `bits`-bit limbs."""
    mask = (1 << bits) - 1
    return np.array([(int(v) >> (bits * i)) & mask
                     for i in range(n_limbs)], dtype=dtype)


def rand_fe(rng, p):
    """Uniform field element below p from a seeded Generator (numpy
    cannot draw 255-bit ints natively; compose from bytes)."""
    nbytes = (p.bit_length() + 7) // 8 + 8
    return int.from_bytes(bytes(rng.integers(0, 256, nbytes,
                                             dtype=np.uint8)),
                          "little") % p


def mont_r(spec):
    """The Montgomery radix R = 2^(16·n_limbs) for a field spec."""
    return 1 << (16 * spec.n_limbs)


def mismatch_report(tag, got, want, mod=None):
    """Compare two object arrays of ints (optionally mod `mod`);
    return [] when equal, else one message naming the first bad lane."""
    g, w = _obj(got), _obj(want)
    if mod is not None:
        g, w = _ew(lambda v: int(v) % mod, g), _ew(
            lambda v: int(v) % mod, w)
    if g.shape != w.shape:
        return [f"{tag}: shape mismatch {g.shape} vs {w.shape}"]
    bad = np.argwhere(_ew(operator.ne, g, w))
    if not len(bad):
        return []
    at = tuple(int(x) for x in bad[0])
    return [f"{tag}: value mismatch at lane {at}: "
            f"got {g[at]}, want {w[at]} "
            f"({len(bad)}/{g.size} lanes differ)"]
