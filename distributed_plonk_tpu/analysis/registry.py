"""Kernel registry: the production entry points the verifier must prove.

One place enumerates every hot kernel with its REAL call shapes and the
documented input/output bounds, so `python -m distributed_plonk_tpu.analysis
--strict` is a single proof obligation covering:

- field mul/add/sub (Fr and Fq, BOTH multiplier paths — the default
  f32/MXU byte-product path and the u32 reference path),
- `_carry_sweep` at full-u32 input (its own contract: limbs < 2^16 out),
- the NTT stage pipeline for all 8 (inverse, coset, boundary) modes at
  odd AND even log2(n) (radix-4 default plus the radix-2 parity core),
- MSM digit extraction at the prover's real n+2/n+3 blinded handle
  widths (signed c=7, signed c=8, unsigned c=4 small-window),
- the bucket-update scan in every plane-update strategy the platform
  split can pick (onehot+packed, onehot unpacked, put),
- the MSM finish tail / plane folds, and the complete projective +
  Jacobian curve adds.

Shapes are representative, not production-sized: interval propagation is
width-generic for every rule except reduction/contraction counts, and
those are taken from the traced shape — the registry picks shapes whose
reduction widths EQUAL or EXCEED production's per-column term counts
(limb counts are fixed; scan lengths only repeat the same body). Entries
that depend on a module-level mode latch (DPT_FIELD_MUL,
DPT_BUCKET_UPDATE, DPT_PLANE_PACK) re-point the latch around the trace
so both sides of every platform split are verified regardless of the
machine running the check.
"""

from . import bounds as B
from . import values as V
from .bounds import Bound, limb_rows

import jax.numpy as jnp
import numpy as np

U16 = (1 << 16) - 1
U32 = (1 << 32) - 1


class ValueObligation:
    """A machine-checked value contract for a registry entry.

    sampler(rng) -> concrete args; contract(args, outs) -> error
    strings.  `fn` overrides the entry fn when the value pass needs a
    cheaper instantiation of the same kernel code (e.g. a smaller Horner
    chunk); `patches` are applied ON TOP of the entry's bounds patches
    (e.g. a narrow Pallas lane tile so the exact grid walk stays cheap —
    the kernel body is tile-width-generic, which the bounds pass proves
    at the real tile)."""

    def __init__(self, sampler, contract, samples=1, patches=(), fn=None):
        self.sampler = sampler
        self.contract = contract
        self.samples = samples
        self.patches = tuple(patches)
        self.fn = fn


class Entry:
    def __init__(self, name, fn, args, out_bounds=None, patches=(),
                 value=None):
        self.name = name
        self.fn = fn
        self.args = args
        self.out_bounds = out_bounds
        self.patches = tuple(patches)  # ((module, attr, value), ...)
        self.value = value             # ValueObligation | None

    def _patched(self, patches, thunk):
        saved = [(m, a, getattr(m, a)) for m, a, _ in patches]
        for m, a, v in patches:
            setattr(m, a, v)
        try:
            return thunk()
        finally:
            for m, a, v in saved:
                setattr(m, a, v)

    def check(self, strict=True):
        return self._patched(
            self.patches,
            lambda: B.check_fn(self.name, self.fn, self.args,
                               out_bounds=self.out_bounds, strict=strict))

    def check_values(self, strict=True, seed=0):
        """Run this entry's value contract (None when the entry declares
        no value obligation — e.g. curve group ops, whose value story is
        the field contracts they are composed from plus parity tests)."""
        if self.value is None:
            return None
        ob = self.value
        return self._patched(
            self.patches + ob.patches,
            lambda: V.check_value(self.name, ob.fn or self.fn,
                                  ob.sampler, ob.contract,
                                  samples=ob.samples, seed=seed,
                                  strict=strict))


# -- value samplers / contracts ------------------------------------------------
#
# Sample points are seeded-random field elements PLUS the corner values
# 0, 1, p-1 in fixed lanes: the injected bug classes (dropped carry
# lane, off-by-one limb shift, wrong modulus constant, swapped twiddle
# row) each change the computed value at almost every point, so a
# handful of samples rejects them — while the corners pin the
# conditional-subtract / carry-out edges random sampling would miss.

def _fe_lane_vals(rng, p, lanes):
    vals = [0, 1, p - 1][:lanes]
    return vals + [V.rand_fe(rng, p) for _ in range(lanes - len(vals))]


def _field_sampler(spec, nargs, lanes=5):
    L = spec.n_limbs

    def sample(rng):
        args = []
        for _ in range(nargs):
            vals = _fe_lane_vals(rng, spec.mod, lanes)
            rng.shuffle(vals)  # corners meet corners across samples
            args.append(np.stack([V.limbs_from_int(v, L) for v in vals],
                                 axis=1))
        return tuple(args)
    return sample


def _mod_contract(spec, op):
    """value(out) as a function of value(in) mod p, plus canonicality
    (out < p) — the algebraic claim each field kernel's docstring
    makes, now machine-checked."""
    p, R = spec.mod, V.mont_r(spec)
    rinv = pow(R, -1, p)
    fns = {
        "mont_mul": lambda a, b: a * b * rinv % p,
        "add": lambda a, b: (a + b) % p,
        "sub": lambda a, b: (a - b) % p,
        "neg": lambda a: -a % p,
        "to_mont": lambda a: a * R % p,
        "from_mont": lambda a: a * rinv % p,
    }
    fn = fns[op]
    nargs = fn.__code__.co_argcount

    def contract(args, outs):
        ins = [V.limb_value(V.to_exact(a)) for a in args[:nargs]]
        want = V.elementwise(lambda *vs: fn(*[int(x) for x in vs]), *ins)
        got = V.limb_value(outs[0])
        errs = V.mismatch_report(f"value(out) == {op}(value(in)) mod p",
                                 got, want)
        over = sum(int(g) >= p for g in got.reshape(-1))
        if over:
            errs.append(f"{op}: output not canonical (>= p) in "
                        f"{over} lane(s)")
        return errs
    return contract


def _field_value(spec, op, nargs, lanes=5, samples=2, patches=(),
                 fn=None):
    return ValueObligation(_field_sampler(spec, nargs, lanes),
                           _mod_contract(spec, op), samples=samples,
                           patches=patches, fn=fn)


def _carry_sweep_value():
    def sampler(rng):
        cols = rng.integers(0, 1 << 32, size=(16, 6), dtype=np.uint32)
        cols[:, 0] = 0          # corner: all-zero columns
        cols[:, 1] = U32        # corner: every column saturated
        return (cols,)

    def contract(args, outs):
        K = args[0].shape[0]
        vc = V.limb_value(V.to_exact(args[0]))
        vl = V.limb_value(outs[0])
        carry = V.elementwise(lambda c: int(c) << (16 * K), outs[1])
        return V.mismatch_report(
            "value(limbs) + carry*2^(16K) == value(cols)",
            vl + carry, vc)
    return ValueObligation(sampler, contract, samples=2)


def _roundtrip_value(shape):
    def sampler(rng):
        v = rng.integers(0, 1 << 16, size=shape, dtype=np.uint32)
        v.reshape(-1)[0] = 0
        v.reshape(-1)[1] = U16
        return (v,)

    def contract(args, outs):
        return V.mismatch_report("pack/unpack roundtrip identity",
                                 outs[0], V.to_exact(args[0]))
    return ValueObligation(sampler, contract, samples=2)


def _cumsum_value(spec, lanes=8):
    p, L = spec.mod, spec.n_limbs

    def sampler(rng):
        vals = _fe_lane_vals(rng, p, lanes)
        return (np.stack([V.limbs_from_int(v, L) for v in vals],
                         axis=1),)

    def contract(args, outs):
        vin = V.limb_value(V.to_exact(args[0]))
        got = V.limb_value(outs[0])
        acc, want = 0, []
        for x in vin.reshape(-1):
            acc = (acc + int(x)) % p
            want.append(acc)
        return V.mismatch_report("inclusive prefix sums mod p", got,
                                 np.array(want, dtype=object))
    return ValueObligation(sampler, contract, samples=2)


def _ntt_value(n, inverse, coset, cnp, batch=False, perm=None):
    """value(out) == DFT(value(in)) against the pure-Python poly
    oracle.  Fr-linearity of the transform makes the oracle apply to
    RAW limb values in both boundaries: Montgomery form is scaling by
    R, and the DFT commutes with scalar multiplication — so no
    boundary-specific expected values are needed.  `perm` (the
    defer_perm consts table) relates bit-reversed outputs back to
    natural order."""
    from .. import poly as P
    from ..constants import R_MOD
    dom = P.Domain(n)
    rows = 3 if batch else 1

    def sampler(rng):
        vals = [V.rand_fe(rng, R_MOD) for _ in range(rows * n)]
        vals[0], vals[1] = 0, 1  # corner lanes ride every sample
        arr = np.stack([V.limbs_from_int(v, 16) for v in vals], axis=1)
        shape = (16, rows, n) if batch else (16, n)
        return arr.reshape(shape), cnp

    def oracle(vs):
        if inverse and coset:
            return P.coset_ifft(dom, vs)
        if inverse:
            return P.ifft(dom, vs)
        if coset:
            return P.coset_fft(dom, vs)
        return P.fft(dom, vs)

    def contract(args, outs):
        vin = V.limb_value(V.to_exact(args[0])).reshape(-1, n)
        got = V.limb_value(outs[0]).reshape(-1, n)
        errs = []
        for b in range(vin.shape[0]):
            want = list(oracle([int(x) % R_MOD for x in vin[b]]))
            row = [int(x) % R_MOD for x in got[b]]
            if perm is not None:
                row = [row[i] for i in perm]
            if row != want:
                k = next(i for i in range(n) if row[i] != want[i])
                nbad = sum(r != w for r, w in zip(row, want))
                errs.append(f"row {b}: mismatch vs poly oracle at lane "
                            f"{k} ({nbad}/{n} lanes differ)")
        return errs
    return ValueObligation(sampler, contract, samples=1)


def _digits_value(Lw, c, bias):
    """Σ (digit_w - bias)·2^(c·w) reconstructs from_mont(handle)
    exactly, per lane, zero on padding — the recombination equation the
    bucket accumulation relies on (bias 0 = unsigned)."""
    from ..constants import R_MOD
    rinv = pow(1 << 256, -1, R_MOD)

    def sampler(rng):
        vals = _fe_lane_vals(rng, R_MOD, Lw)
        return (np.stack([V.limbs_from_int(v, 16) for v in vals],
                         axis=1),)

    def contract(args, outs):
        vin = [int(x) for x in
               V.limb_value(V.to_exact(args[0])).reshape(-1)]
        scal = [v * rinv % R_MOD for v in vin]
        d = outs[0]
        W, padded = d.shape
        errs = []
        for j in range(padded):
            want = scal[j] if j < len(scal) else 0
            rec = sum((int(d[w, j]) - bias) << (c * w) for w in range(W))
            if rec != want:
                errs.append(f"digit recombination wrong at lane {j}: "
                            f"sum((d-{bias})*2^({c}w)) = {rec}, "
                            f"scalar = {want}")
                break
        return errs
    return ValueObligation(sampler, contract, samples=1)


def _eval_value(Lc, batch=None, fn=None):
    """value(out) == Σ c_i·z^i in raw-value terms: coeffs/point arrive
    in Montgomery form (c_i = v_i·R⁻¹, z = vz·R⁻¹); poly_eval returns
    the Montgomery form of p(z), poly_eval_many the canonical value."""
    from ..constants import R_MOD
    R = 1 << 256
    rinv = pow(R, -1, R_MOD)

    def sampler(rng):
        def poly(vals):
            return np.stack([V.limbs_from_int(v, 16) for v in vals],
                            axis=1)
        if batch:
            ps = np.stack([poly(_fe_lane_vals(rng, R_MOD, Lc))
                           for _ in range(batch)])
            zs = np.stack([poly([V.rand_fe(rng, R_MOD)])
                           for _ in range(batch)])
            return ps, zs
        return (poly(_fe_lane_vals(rng, R_MOD, Lc)),
                poly([V.rand_fe(rng, R_MOD)]))

    def contract(args, outs):
        ax = 1 if batch else 0  # batched polys are (B, 16, L)
        vin = V.limb_value(V.to_exact(args[0]), axis=ax).reshape(-1, Lc)
        vz = V.limb_value(V.to_exact(args[1]), axis=ax).reshape(-1)
        got = V.limb_value(outs[0]).reshape(-1)
        errs = []
        for b in range(vin.shape[0]):
            cs = [int(x) * rinv % R_MOD for x in vin[b]]
            z = int(vz[b]) * rinv % R_MOD
            pz = 0
            for c in reversed(cs):
                pz = (pz * z + c) % R_MOD
            want = pz if batch else pz * R % R_MOD  # many() -> canonical
            if int(got[b]) != want:
                errs.append(f"poly {b}: p(z) value mismatch: "
                            f"got {int(got[b])}, want {want}")
        return errs
    return ValueObligation(sampler, contract, samples=1, fn=fn)


def _field_entries():
    from ..backend import field_jax as FJ

    out = []
    for spec in (FJ.FR, FJ.FQ):
        L = spec.n_limbs
        pair = (limb_rows(L, 8), limb_rows(L, 8))
        one = (limb_rows(L, 8),)
        limbs_out = [(0, U16)]
        n = spec.name.lower()
        for tag in ("f32", "u32"):  # f32/MXU default, u32 reference
            out.append(Entry(
                f"field/{n}_mont_mul_{tag}",
                lambda a, b, s=spec: FJ.mont_mul(s, a, b), pair,
                limbs_out, patches=[(FJ, "_MUL_MODE", tag)],
                value=_field_value(spec, "mont_mul", 2)))
        out.append(Entry(f"field/{n}_add",
                         lambda a, b, s=spec: FJ.add(s, a, b), pair,
                         limbs_out, value=_field_value(spec, "add", 2)))
        out.append(Entry(f"field/{n}_sub",
                         lambda a, b, s=spec: FJ.sub(s, a, b), pair,
                         limbs_out, value=_field_value(spec, "sub", 2)))
        out.append(Entry(f"field/{n}_neg",
                         lambda a, s=spec: FJ.neg(s, a), one, limbs_out,
                         value=_field_value(spec, "neg", 1)))
        out.append(Entry(f"field/{n}_to_mont",
                         lambda a, s=spec: FJ.to_mont(s, a), one,
                         limbs_out,
                         value=_field_value(spec, "to_mont", 1)))
        out.append(Entry(f"field/{n}_from_mont",
                         lambda a, s=spec: FJ.from_mont(s, a), one,
                         limbs_out,
                         value=_field_value(spec, "from_mont", 1)))
    # the sweep itself, at its weakest precondition (ANY u32 columns):
    # output limbs < 2^16 and a carry bounded by hi[-1] + 1; the value
    # obligation is the EQUATION its docstring used to state as prose —
    # value(limbs) + carry·2^(16K) == value(cols), exactly
    out.append(Entry("field/carry_sweep", FJ._carry_sweep,
                     (Bound((FJ.FR.n_limbs, 8), jnp.uint32, 0, U32),),
                     [(0, U16), (0, 1 << 16)],
                     value=_carry_sweep_value()))
    out.append(Entry("field/pack_unpack_limb_pairs",
                     lambda v: FJ.unpack_limb_pairs(FJ.pack_limb_pairs(v)),
                     (limb_rows(8, 16),), [(0, U16)],
                     value=_roundtrip_value((8, 16))))
    out.append(Entry("field/cumsum_mont",
                     lambda v: FJ.cumsum_mont(FJ.FR, v),
                     (limb_rows(16, 8),), [(0, U16)],
                     value=_cumsum_value(FJ.FR)))
    return out


def _field_pallas_entries():
    """The standalone fused-multiplier Pallas kernels (DPT_FIELD_MUL=
    pallas): lazy-carry VPU (the round-5 default) and MXU-Toeplitz
    variants, both fields, at the kernel's real lane tile. These were
    parity-tested only (tests/test_field_pallas.py) while the bounds
    pass couldn't see inside pallas_call; now their kernel jaxprs are
    proof obligations like the fused MSM/NTT kernels — closing the
    carried-forward Pallas obligation from PR 5 (strict-mul bodies were
    proved there via the MSM kernel; these are the remaining entry
    points, incl. the lazy local-round / bf16 band paths the MSM kernel
    does not embed)."""
    from ..backend import field_jax as FJ
    from ..backend import field_pallas as FP

    out = []
    for spec in (FJ.FR, FJ.FQ):
        L = spec.n_limbs
        pair = (limb_rows(L, FP.LANE_TILE), limb_rows(L, FP.LANE_TILE))
        n = spec.name.lower()
        for variant in ("lazy", "mxu"):
            # value obligation at a narrow lane tile (8): the kernel
            # body is tile-width-generic (one grid step per tile of the
            # SAME traced program — the bounds entry proves it at the
            # real tile), so the exact grid walk stays cheap while the
            # product contract still covers the lazy local rounds /
            # bf16 band paths
            out.append(Entry(
                f"field/{n}_mont_mul_pallas_{variant}",
                lambda a, b, s=spec: FP.mont_mul(s, a, b), pair,
                [(0, U16)], patches=[(FP, "_VARIANT", variant)],
                value=_field_value(spec, "mont_mul", 2, lanes=8,
                                   patches=[(FP, "LANE_TILE", 8)])))
    return out


def _ntt_entries():
    from ..backend import ntt_jax as NTT

    out = []
    # odd + even log2(n): n=32 exercises the radix-2 fixup stage, n=64
    # the peeled-last-radix-4 path; every (inverse, coset, boundary)
    # combination is a distinct fused program
    for n in (32, 64):
        plan = NTT.get_plan(n)
        for inverse in (False, True):
            for coset in (False, True):
                for boundary in ("mont", "plain"):
                    # kernel pinned to the XLA core: these entries prove
                    # the radix-4 stage pipeline regardless of what
                    # DPT_NTT_KERNEL resolves to in the checking env
                    # (the pallas program has its own entries below)
                    fn, consts = plan.traced_kernel(
                        inverse, coset, boundary=boundary, radix=4,
                        kernel="xla")
                    cnp = {k: np.asarray(v) for k, v in consts.items()}
                    # value obligations ride the n=32 programs: the
                    # stage pipeline is width-generic and n=64 costs
                    # 4x in exact evaluation for the same rule set;
                    # n=64 keeps its interval obligation plus the
                    # batch/defer_perm value entries below
                    val = (_ntt_value(n, inverse, coset, cnp)
                           if n == 32 else None)
                    out.append(Entry(
                        f"ntt/n{n}_radix4_inv{int(inverse)}"
                        f"_coset{int(coset)}_{boundary}",
                        fn, (limb_rows(16, n), cnp), [(0, U16)],
                        value=val))
        # radix-2 parity core (one mode per n keeps the sweep cheap; the
        # stage body is mode-independent modulo pre/post table muls,
        # which the inverse+coset variant includes)
        fn, consts = plan.traced_kernel(True, True, boundary="mont",
                                        radix=2, kernel="xla")
        cnp = {k: np.asarray(v) for k, v in consts.items()}
        out.append(Entry(f"ntt/n{n}_radix2_inv1_coset1_mont", fn,
                         (limb_rows(16, n), cnp), [(0, U16)],
                         value=(_ntt_value(n, True, True, cnp)
                                if n == 32 else None)))
        # batched kernel (the prover's round-1/round-3 launches)
        fn, consts = plan.traced_kernel(False, True, radix=4, batch=True,
                                        kernel="xla")
        cnp = {k: np.asarray(v) for k, v in consts.items()}
        out.append(Entry(f"ntt/n{n}_radix4_batch3_coset", fn,
                         (limb_rows(16, 3, n), cnp), [(0, U16)],
                         value=(_ntt_value(n, False, True, cnp,
                                           batch=True)
                                if n == 32 else None)))
    # fused multi-stage Pallas kernel (DPT_NTT_KERNEL=pallas): the
    # pallas_call kernel jaxprs are interpreted like the fused MSM's
    # (bounds._p_pallas_call). Coverage: forward+coset (pre-scale fused
    # into the first group) and inverse+coset (reordered post-scales in
    # the last group) at odd/even log2(n); a small-rows schedule forces
    # TWO sequential fused groups in one program (narrow VMEM budget);
    # batch width > 1 checks the (B, tiles) grid. Fresh NttPlan
    # instances, NOT get_plan: the forced schedules must not poison the
    # shared plan's consts memo.
    from ..backend import ntt_pallas as NP

    def pallas_ntt(n, inverse, coset, batch, rows_cap):
        saved = NP._ROWS_CAP
        NP._ROWS_CAP = rows_cap
        try:
            plan = NTT.NttPlan(n)
            fn, consts = plan.traced_kernel(inverse, coset, radix=4,
                                            batch=batch, kernel="pallas")
        finally:
            NP._ROWS_CAP = saved
        cnp = {k: np.asarray(v) for k, v in consts.items()}
        shape = (16, 3, n) if batch else (16, n)
        # the pallas programs carry value obligations at their OWN
        # traced shape: the exact interpreter executes the grid with
        # persistent scratch refs, so the fused-group scheduling (incl.
        # the two-group VMEM spill path) is part of what is proven
        return Entry(
            f"ntt/n{n}_pallas_inv{int(inverse)}_coset{int(coset)}"
            + ("_batch3" if batch else "") + f"_rows{rows_cap}",
            fn, (limb_rows(*shape), cnp), [(0, U16)],
            value=_ntt_value(n, inverse, coset, cnp, batch=batch))

    out.append(pallas_ntt(64, False, True, False, 64))   # one group, R=6
    out.append(pallas_ntt(64, True, True, False, 8))     # two groups, R=3
    out.append(pallas_ntt(32, False, False, True, 32))   # odd log2, batch

    # deferred output permutation (DPT_R3_BITREV consumer-side fusion):
    # the forward batch kernel that SKIPS the bit-reversal gather — the
    # round-3 producer launches run this program, with the consuming
    # iNTT's input_perm paying the one remaining gather. Same limb
    # bounds as the permuted variant (a gather moves lanes, not values);
    # proved for both stage cores.
    for kern, tag in (("xla", "radix4"), ("pallas", "pallas")):
        plan = NTT.NttPlan(64)
        fn, consts = plan.traced_kernel(False, True, radix=4, batch=True,
                                        kernel=kern, defer_perm=True)
        cnp = {k: np.asarray(v) for k, v in consts.items()}
        # value obligation includes the output-order relation: the
        # kernel's bit-reversed rows, re-ordered by its OWN consts
        # permutation, must equal the natural-order oracle — a swapped
        # or stale perm table is a value finding, not just a lane move
        out.append(Entry(f"ntt/n64_{tag}_batch3_coset_defer_perm", fn,
                         (limb_rows(16, 3, 64), cnp), [(0, U16)],
                         value=_ntt_value(64, False, True, cnp,
                                          batch=True,
                                          perm=np.asarray(cnp["perm"]))))
    return out


def _msm_entries():
    from ..backend import msm_jax as MSM

    out = []
    # digit extraction at the REAL blinded handle widths the prover
    # commits (domain n -> handles of width n+2 / n+3; jit caches per
    # exact width — the PR 3 bug class this registry pins)
    dom = 64
    for Lw in (dom + 2, dom + 3):
        out.append(Entry(
            f"msm/digits_signed_c7_L{Lw}",
            lambda h: MSM.signed_digits7_from_mont(h, padded_n=2 * dom),
            (limb_rows(16, Lw),), [(0, 127)],
            value=_digits_value(Lw, 7, 64)))
        out.append(Entry(
            f"msm/digits_signed_c8_L{Lw}",
            lambda h: MSM.signed_digits_from_mont(h, padded_n=2 * dom),
            (limb_rows(16, Lw),), [(0, 255)],
            value=_digits_value(Lw, 8, 128)))
        out.append(Entry(
            f"msm/digits_unsigned_c4_L{Lw}",
            lambda h: MSM.digits_from_mont(h, 4, padded_n=2 * dom),
            (limb_rows(16, Lw),), [(0, 15)],
            value=_digits_value(Lw, 4, 0)))

    # bucket-update scan: signed c=7 shape (the default batched
    # pipeline), under every plane-update strategy
    nc, Bt, W = 16, 2, 37
    scan_args = (limb_rows(24, nc), limb_rows(24, nc),
                 Bound((nc,), jnp.bool_, 0, 1),
                 Bound((Bt, W, nc), jnp.uint32, 0, 127))
    plane_out = [(0, U16)] * 3
    for mode, pack in (("onehot", True), ("onehot", False), ("put", False)):
        tag = f"{mode}{'_packed' if pack else ''}"
        out.append(Entry(
            f"msm/bucket_scan_signed_{tag}",
            lambda ax, ay, ainf, d: MSM.bucket_planes_batch_signed(
                ax, ay, ainf, d, group=1),
            scan_args, plane_out,
            patches=[(MSM, "_BUCKET_UPDATE", mode),
                     (MSM, "_PLANE_PACK", pack)]))
    # unsigned small-window scan (tiny keys, c=4: 64 windows x 16
    # buckets, digits < 16)
    uargs = (limb_rows(24, nc), limb_rows(24, nc),
             Bound((nc,), jnp.bool_, 0, 1),
             Bound((Bt, 64, nc), jnp.uint32, 0, 15))
    for mode, pack in (("onehot", True), ("put", False)):
        tag = f"{mode}{'_packed' if pack else ''}"
        out.append(Entry(
            f"msm/bucket_scan_unsigned_{tag}",
            lambda ax, ay, ainf, d: MSM.bucket_planes_batch(
                ax, ay, ainf, d, group=1),
            uargs, plane_out,
            patches=[(MSM, "_BUCKET_UPDATE", mode),
                     (MSM, "_PLANE_PACK", pack)]))

    # fused Pallas bucket kernel (DPT_MSM_KERNEL=pallas): the
    # pallas_call kernel jaxpr is interpreted with the SAME interval
    # rules (bounds._p_pallas_call) — one cell per VMEM plane ref, the
    # grid as a join-until-stable fixpoint. Registering it here also
    # covers the in-VMEM RCB15/mont-mul primitives it shares with
    # curve_pallas/field_pallas (the ROADMAP "Pallas kernels are
    # outside the bounds pass" gap, first bite). c=7 checks both plane
    # packings; c=8/c=4 pin the other digit widths.
    for c, W_, tag, pack in ((7, 37, "c7_packed", True),
                             (7, 37, "c7", False),
                             (8, 32, "c8_packed", True)):
        nb = 1 << (c - 1)
        out.append(Entry(
            f"msm/bucket_pallas_signed_{tag}",
            lambda ax, ay, ainf, d: MSM.bucket_planes_batch_signed(
                ax, ay, ainf, d, group=1),
            (limb_rows(24, nc), limb_rows(24, nc),
             Bound((nc,), jnp.bool_, 0, 1),
             Bound((Bt, W_, nc), jnp.uint32, 0, 2 * nb - 1)),
            plane_out,
            patches=[(MSM, "_MSM_KERNEL", "pallas"),
                     (MSM, "_PLANE_PACK", pack)]))
    out.append(Entry(
        "msm/bucket_pallas_unsigned_c4_packed",
        lambda ax, ay, ainf, d: MSM.bucket_planes_batch(
            ax, ay, ainf, d, group=1),
        uargs, plane_out,
        patches=[(MSM, "_MSM_KERNEL", "pallas"),
                 (MSM, "_PLANE_PACK", True)]))

    # finish tail (both bucket semantics) + cross-chunk fold
    out.append(Entry(
        "msm/finish_signed_c7",
        lambda bx, by, bz: MSM.finish(bx, by, bz, signed=True),
        tuple(limb_rows(24, 37, 64) for _ in range(3)), plane_out))
    out.append(Entry(
        "msm/finish_unsigned_c4",
        lambda bx, by, bz: MSM.finish(bx, by, bz, signed=False),
        tuple(limb_rows(24, 64, 16) for _ in range(3)), plane_out))
    out.append(Entry(
        "msm/fold_planes", MSM.fold_planes,
        tuple(limb_rows(4, 24, 8, 16) for _ in range(3)), plane_out))
    return out


def _curve_entries():
    from ..backend import curve_jax as CJ
    from ..backend import curve_pallas as CP

    pt = lambda: tuple(limb_rows(24, 8) for _ in range(3))
    coords_out = [(0, U16)] * 3
    # the standalone curve_pallas FULL-add kernel at its real lane tile:
    # the mixed-add body is proved through the fused MSM kernel (PR 5),
    # the full add (RCB15 algorithm 7 — cross-chunk folds, finish tail
    # doubling ladder on TPU) was parity-tested only. Closes the last
    # curve piece of the carried-forward Pallas proof obligation.
    ptp = lambda: tuple(limb_rows(24, CP.LANE_TILE) for _ in range(3))
    return [
        Entry("curve/proj_add", CJ.proj_add, (pt(), pt()), coords_out),
        Entry("curve/proj_add_mixed", CJ.proj_add_mixed,
              (pt(), (limb_rows(24, 8), limb_rows(24, 8)),
               Bound((8,), jnp.bool_, 0, 1)), coords_out),
        Entry("curve/proj_add_pallas_full", CP.proj_add, (ptp(), ptp()),
              coords_out),
        Entry("curve/jac_add", CJ.jac_add, (pt(), pt()), coords_out),
        Entry("curve/jac_double", CJ.jac_double, (pt(),), coords_out),
    ]


def _eval_entries():
    """The partial-evaluation (Horner-at-r) kernel: prover round 4's
    device evaluation (prover_jax.poly_eval — block Horner + log-depth
    power combine), which the result-integrity plane (ISSUE 13) now also
    uses as the distributed-EVAL serving kernel on jax workers and as
    the per-chunk shape duplicate-executed across workers. Proved at an
    exact-chunk width and at the prover's real blinded n+2 width (the
    chunked reshape pads internally — both the padded and unpadded
    tails are obligations)."""
    from ..backend import prover_jax as PJ

    out = []
    for L in (256, 66):  # one full chunk; the n=64 blinded n+2 width
        # the value obligation runs the SAME poly_eval at chunk=8 on a
        # 20-coeff poly: 3 Horner blocks + the log-depth power combine
        # + the padded tail are all exercised, without 256 exact scan
        # steps per sample (chunk is a real parameter of the real fn,
        # not a shadow implementation)
        out.append(Entry(
            f"eval/horner_at_r_n{L}",
            lambda p, z: PJ.poly_eval(p, z),
            (limb_rows(16, L), limb_rows(16, 1)), [(0, U16)],
            value=_eval_value(
                20, fn=lambda p, z: PJ.poly_eval(p, z, chunk=8))))
    # the batched round-4 launch shape (B polys, one point each)
    out.append(Entry(
        "eval/horner_at_r_batch4_n66",
        lambda p, z: PJ.poly_eval_many(p, z),
        (limb_rows(4, 16, 66), limb_rows(4, 16, 1)), [(0, U16)],
        value=_eval_value(5, batch=2)))
    return out


def build_registry():
    """All production entries (list of Entry)."""
    return (_field_entries() + _field_pallas_entries() + _ntt_entries()
            + _msm_entries() + _curve_entries() + _eval_entries())


def run_bounds(strict=True, names=None, progress=None, contracts=True):
    """Check every registry entry (+ the carry contracts unless the
    caller runs them separately). Returns (violations, entries_checked)."""
    violations = list(B.check_contracts()) if contracts else []
    entries = build_registry()
    checked = 0
    for e in entries:
        if names is not None and not any(s in e.name for s in names):
            continue
        v = e.check(strict=strict)
        checked += 1
        if progress is not None:
            progress(e.name, v)
        violations.extend(v)
    return violations, checked


def run_values(strict=True, names=None, progress=None):
    """Run every entry's value contract (entries without an obligation
    are skipped — curve group ops and the bucket scans, whose value
    story is the field contracts they compose plus parity tests).
    Returns (violations, entries_checked)."""
    violations = []
    checked = 0
    for e in build_registry():
        if names is not None and not any(s in e.name for s in names):
            continue
        v = e.check_values(strict=strict)
        if v is None:
            continue
        checked += 1
        if progress is not None:
            progress(e.name, v)
        violations.extend(v)
    return violations, checked
