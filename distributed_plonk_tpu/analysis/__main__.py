"""CLI: `python -m distributed_plonk_tpu.analysis [--strict] [...]`.

Exit status 0 iff every selected pass is clean — the one-command proof
obligation `scripts/ci.sh analyze` runs and bench.py records as
`analysis_clean`. Four passes:

  lint       AST hazard lints over the package (JIT cache keys, f32
             promotion, lock discipline incl. the LOCK03 order graph,
             metric/log/knob glossaries, wire-tag conformance)
  contracts  the named carry side-condition inequalities, evaluated
             for both field specs
  bounds     jaxpr interval propagation over every registry entry:
             machine arithmetic == exact integer semantics (no
             overflow, no inexact f32, declared output ranges hold)
  values     exact evaluation of every registry entry's value
             contract: the kernel's integer semantics equal its
             algebraic claim (mont_mul really is a*b*R^-1 mod p, the
             NTT really matches the polynomial oracle, ...)

`--changed-only` keys bounds/values/contracts on the mtimes of the
kernel modules each registry family traces (state in
.analysis_state.json at the repo root, refreshed only after a fully
clean run); lints always run — they cover the whole package and cost
well under a second. Runs on CPU (tracing + exact host evaluation,
nothing executes on a device), so it is safe anywhere the repo
imports.
"""

import argparse
import json
import os
import sys
import time

_REPO = os.path.normpath(os.path.join(os.path.dirname(__file__),
                                      "..", ".."))
_PKG = os.path.join(_REPO, "distributed_plonk_tpu")
_STATE_FILE = os.path.join(_REPO, ".analysis_state.json")

# registry-entry name prefix -> package-relative kernel modules whose
# change invalidates that family (what the entries actually trace
# through). Files in _GLOBAL_DEPS invalidate every family: they define
# the analyzers themselves, the field constants, or the oracles the
# value contracts compare against.
_ENTRY_MODULES = {
    "field/": ("backend/field_jax.py", "backend/field_pallas.py"),
    "ntt/": ("backend/ntt_jax.py", "backend/ntt_pallas.py",
             "backend/field_jax.py", "backend/field_pallas.py",
             "poly.py"),
    "msm/": ("backend/msm_jax.py", "backend/msm_pallas.py",
             "backend/field_jax.py", "backend/field_pallas.py",
             "backend/curve_jax.py", "backend/curve_pallas.py"),
    "curve/": ("backend/curve_jax.py", "backend/curve_pallas.py",
               "backend/field_jax.py"),
    "eval/": ("backend/prover_jax.py", "backend/field_jax.py"),
}
_GLOBAL_DEPS = ("constants.py", "backend/limbs.py",
                "analysis/bounds.py", "analysis/values.py",
                "analysis/registry.py")


def _dep_mtimes():
    files = set(_GLOBAL_DEPS)
    for deps in _ENTRY_MODULES.values():
        files |= set(deps)
    out = {}
    for rel in sorted(files):
        p = os.path.join(_PKG, rel)
        if os.path.exists(p):
            out[rel] = os.stat(p).st_mtime
    return out


def _changed_scope():
    """(names_filter, contracts_needed, mtimes) for --changed-only.

    names_filter: None = every entry; [] = nothing changed, skip the
    registry passes; else the list of changed family prefixes."""
    mtimes = _dep_mtimes()
    try:
        with open(_STATE_FILE) as f:
            old = json.load(f)
    except (OSError, ValueError):
        return None, True, mtimes  # no clean baseline: run everything
    changed = {rel for rel, t in mtimes.items() if old.get(rel) != t}
    changed |= set(old) - set(mtimes)  # deleted module: distrust all
    if changed & set(_GLOBAL_DEPS) or set(old) - set(mtimes):
        return None, True, mtimes
    names = [pfx for pfx, deps in sorted(_ENTRY_MODULES.items())
             if changed & set(deps)]
    contracts = any("field_jax" in rel for rel in changed)
    return names, contracts, mtimes


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m distributed_plonk_tpu.analysis",
        description="static kernel verifier: jaxpr interval bounds, "
                    "exact value contracts, carry contracts, and AST "
                    "hazard lints")
    ap.add_argument("--strict", action="store_true",
                    help="treat unhandled primitives / warnings as errors")
    ap.add_argument("--only",
                    choices=("bounds", "values", "lint", "contracts"),
                    help="run a single pass (default: all)")
    ap.add_argument("--kernel", action="append",
                    help="substring filter on registry entry names "
                         "(repeatable; bounds and values passes)")
    ap.add_argument("--changed-only", action="store_true",
                    help="skip registry families whose kernel modules "
                         "are unchanged since the last fully clean run "
                         "(mtime state in .analysis_state.json; lints "
                         "always run)")
    ap.add_argument("--list", action="store_true",
                    help="list registry entries and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print failures and the summary line")
    args = ap.parse_args(argv)

    if args.changed_only and args.kernel:
        ap.error("--changed-only and --kernel are mutually exclusive "
                 "(an explicit filter defeats the staleness tracking)")

    # tracing must not wait on (or disturb) an accelerator runtime; the
    # env var only takes effect when jax has not been imported yet, which
    # is the normal `python -m` path
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.list:
        # enumeration only: no passes run, nothing else interleaves
        from .registry import build_registry
        for e in build_registry():
            print(e.name)
        return 0

    names = args.kernel
    contracts_wanted = True
    state = None
    if args.changed_only:
        names, contracts_wanted, state = _changed_scope()
        if names == []:
            if not args.quiet:
                print("changed-only: no kernel module changed since "
                      "the last clean run")
        elif names is not None and not args.quiet:
            print(f"changed-only: {' '.join(names)}")

    failures = 0
    t0 = time.monotonic()

    if args.only in (None, "lint"):
        from .lint import run_lints
        findings = run_lints()
        for f in findings:
            print(f"LINT FAIL {f}")
        if not args.quiet:
            print(f"lint: {len(findings)} finding(s)")
        failures += len(findings)

    if args.only in (None, "contracts") and contracts_wanted:
        from .bounds import check_contracts
        bad = check_contracts()
        for v in bad:
            print(f"CONTRACT FAIL {v}")
        if not args.quiet:
            from ..backend.field_jax import CARRY_CONTRACTS
            print(f"contracts: {len(CARRY_CONTRACTS)} checked for "
                  f"Fr+Fq, {len(bad)} violated")
        failures += len(bad)

    skip_registry = args.changed_only and names == []

    if args.only in (None, "bounds") and not skip_registry:
        from .registry import run_bounds

        checked_box = [0]

        def progress(name, violations):
            checked_box[0] += 1
            if violations:
                print(f"BOUNDS FAIL {name}: "
                      f"{len(violations)} violation(s)")
                for v in violations:
                    print(f"  {v}")
            elif not args.quiet:
                print(f"ok {name}")

        # when the contracts pass already ran above, don't double-run
        # (or double-count) it here; under --only bounds the contracts
        # still run and COUNT — a violated contract must never print
        # CLEAN just because the pass selection filtered it
        contracts_here = args.only == "bounds" and contracts_wanted
        violations, _ = run_bounds(strict=args.strict, names=names,
                                   progress=progress,
                                   contracts=contracts_here)
        for v in violations:
            if v.kernel.startswith("contract/"):
                print(f"CONTRACT FAIL {v}")
        if not args.quiet:
            print(f"bounds: {checked_box[0]} kernel(s) checked, "
                  f"{len(violations)} violation(s)")
        failures += len(violations)

    if args.only in (None, "values") and not skip_registry:
        from .registry import run_values

        vchecked_box = [0]

        def vprogress(name, violations):
            vchecked_box[0] += 1
            if violations:
                print(f"VALUE FAIL {name}: "
                      f"{len(violations)} violation(s)")
                for v in violations:
                    print(f"  {v}")
            elif not args.quiet:
                print(f"ok {name} (value)")

        violations, _ = run_values(strict=args.strict, names=names,
                                   progress=vprogress)
        if not args.quiet:
            print(f"values: {vchecked_box[0]} contract(s) checked, "
                  f"{len(violations)} violation(s)")
        failures += len(violations)

    dt = time.monotonic() - t0
    verdict = "CLEAN" if failures == 0 else f"{failures} FAILURE(S)"
    print(f"analysis: {verdict} in {dt:.1f}s")

    # refresh the staleness baseline only after a FULLY clean full-pass
    # run: a partial pass selection or any failure must leave the old
    # baseline in place so nothing is ever skipped past a failure
    if args.changed_only and failures == 0 and args.only is None \
            and state is not None:
        try:
            with open(_STATE_FILE, "w") as f:
                # the PRE-run snapshot: a module edited mid-run stays
                # stale and re-proves next time
                json.dump(state, f, indent=0, sort_keys=True)
        except OSError:
            pass  # read-only checkout: fast mode just stays cold
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
