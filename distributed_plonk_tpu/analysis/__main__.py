"""CLI: `python -m distributed_plonk_tpu.analysis [--strict] [...]`.

Exit status 0 iff every selected pass is clean — the one-command proof
obligation `scripts/ci.sh analyze` runs and bench.py records as
`analysis_clean`. Runs on CPU (tracing only, nothing executes on a
device), so it is safe anywhere the repo imports.
"""

import argparse
import os
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m distributed_plonk_tpu.analysis",
        description="static kernel verifier: jaxpr interval bounds, "
                    "carry contracts, and AST hazard lints")
    ap.add_argument("--strict", action="store_true",
                    help="treat unhandled primitives / warnings as errors")
    ap.add_argument("--only", choices=("bounds", "lint", "contracts"),
                    help="run a single pass (default: all)")
    ap.add_argument("--kernel", action="append",
                    help="substring filter on registry entry names "
                         "(repeatable; bounds pass only)")
    ap.add_argument("--list", action="store_true",
                    help="list registry entries and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print failures and the summary line")
    args = ap.parse_args(argv)

    # tracing must not wait on (or disturb) an accelerator runtime; the
    # env var only takes effect when jax has not been imported yet, which
    # is the normal `python -m` path
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.list:
        # enumeration only: no passes run, nothing else interleaves
        from .registry import build_registry
        for e in build_registry():
            print(e.name)
        return 0

    failures = 0
    t0 = time.monotonic()

    if args.only in (None, "lint"):
        from .lint import run_lints
        findings = run_lints()
        for f in findings:
            print(f"LINT FAIL {f}")
        if not args.quiet:
            print(f"lint: {len(findings)} finding(s)")
        failures += len(findings)

    if args.only in (None, "contracts"):
        from .bounds import check_contracts
        bad = check_contracts()
        for v in bad:
            print(f"CONTRACT FAIL {v}")
        if not args.quiet:
            from ..backend.field_jax import CARRY_CONTRACTS
            print(f"contracts: {len(CARRY_CONTRACTS)} checked for "
                  f"Fr+Fq, {len(bad)} violated")
        failures += len(bad)

    if args.only in (None, "bounds"):
        from .registry import run_bounds

        checked_box = [0]

        def progress(name, violations):
            checked_box[0] += 1
            if violations:
                print(f"BOUNDS FAIL {name}: "
                      f"{len(violations)} violation(s)")
                for v in violations:
                    print(f"  {v}")
            elif not args.quiet:
                print(f"ok {name}")

        # when the contracts pass already ran above, don't double-run
        # (or double-count) it here; under --only bounds the contracts
        # still run and COUNT — a violated contract must never print
        # CLEAN just because the pass selection filtered it
        contracts_here = args.only == "bounds"
        violations, _ = run_bounds(strict=args.strict, names=args.kernel,
                                   progress=progress,
                                   contracts=contracts_here)
        for v in violations:
            if v.kernel.startswith("contract/"):
                print(f"CONTRACT FAIL {v}")
        if not args.quiet:
            print(f"bounds: {checked_box[0]} kernel(s) checked, "
                  f"{len(violations)} violation(s)")
        failures += len(violations)

    dt = time.monotonic() - t0
    verdict = "CLEAN" if failures == 0 else f"{failures} FAILURE(S)"
    print(f"analysis: {verdict} in {dt:.1f}s")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
