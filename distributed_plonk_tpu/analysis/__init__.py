"""Static verifier for the kernel + serving stack.

Three passes (see README "Static analysis"):

- `bounds`: jaxpr abstract interpretation — per-value integer magnitude
  intervals over every registered production kernel, proving no-u32-
  overflow, float exactness, and dtype discipline; plus the
  machine-checked zero-carry contracts (field_jax.CARRY_CONTRACTS).
- `values`: exact jaxpr evaluation (arbitrary-precision host ints) of
  each registry entry's VALUE contract — mont_mul == a*b*R^-1 mod p,
  the NTT == the polynomial oracle, digit recombination, Horner — at
  seeded + corner sample points. Bounds prove machine == exact integer
  semantics; values prove exact semantics == the algebraic claim. The
  two passes are complementary: a dropped carry lane that keeps every
  limb in range is invisible to intervals and caught here.
- `lint`: AST-level repo hazard lints — jit-cache keys, Python-scalar /
  float promotion into traced code, lock discipline (incl. the LOCK03
  lock-order deadlock graph) across the concurrent subsystems, the
  metric/log/env-knob glossaries, and wire-tag conformance (TAG01).

`python -m distributed_plonk_tpu.analysis --strict` runs everything and
exits nonzero on any violation; `scripts/ci.sh analyze` wraps it (add
`--changed-only` to skip registry families whose kernel modules are
unchanged since the last clean run). analysis/mutants.py keeps the
verifier honest: a corpus of seeded known-bad kernel variants tier-1
asserts are still rejected by the right pass. Suppress a deliberate
finding with `# analysis: ok(<reason>)` on (or directly above) the
flagged line.
"""

from . import bounds, lint, registry, values  # noqa: F401
from .bounds import Bound, check_fn, check_contracts, limb_rows  # noqa: F401
from .lint import run_lints, lint_source  # noqa: F401
from .registry import build_registry, run_bounds, run_values  # noqa: F401
from .values import check_value, run_exact  # noqa: F401
