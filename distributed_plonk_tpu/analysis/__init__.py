"""Static verifier for the kernel + serving stack.

Two passes (see ISSUE/README "Static analysis"):

- `bounds`: jaxpr abstract interpretation — per-value integer magnitude
  intervals over every registered production kernel, proving no-u32-
  overflow, float exactness, and dtype discipline; plus the
  machine-checked zero-carry contracts (field_jax.CARRY_CONTRACTS).
- `lint`: AST-level repo hazard lints — jit-cache keys, Python-scalar /
  float promotion into traced code, lock discipline in service/+store/.

`python -m distributed_plonk_tpu.analysis --strict` runs everything and
exits nonzero on any violation; `scripts/ci.sh analyze` wraps it.
Suppress a deliberate finding with `# analysis: ok(<reason>)` on (or
directly above) the flagged line.
"""

from . import bounds, lint, registry  # noqa: F401
from .bounds import Bound, check_fn, check_contracts, limb_rows  # noqa: F401
from .lint import run_lints, lint_source  # noqa: F401
from .registry import build_registry, run_bounds  # noqa: F401
